"""QoS admission control: priority classes, per-tenant fair queueing,
and overload shedding (docs/qos.md).

The serve plane treats every request identically by default; production
TPU serving needs explicit SLO tiers — who waits, who sheds, and who
runs first — decided BEFORE work reaches the device. This module is the
dependency-free core, wired through four layers:

  * the infer server parses ``X-Priority`` / ``X-Tenant`` (OpenAI
    routes additionally map ``service_tier``) and gates admission
    through :class:`ServerQoS` — per-tenant token buckets and the
    overload ladder (degrade, then shed with ``429 + Retry-After``);
  * the engine replaces FIFO admission with
    :class:`ClassedRequestQueue` — class-ordered with aging credit and
    deficit-round-robin tenant fairness within a class;
  * the LB propagates both headers and avoids replicas whose
    advertised QoS pressure would shed the request's class;
  * the autoscaler's QoS-aware mode scales on per-class demand and
    observed shed rate (serve/autoscalers.QoSAwareAutoscaler).

Everything is OFF by default: ``SKYT_QOS=0`` keeps the plain FIFO path
byte-for-byte (same discipline as SKYT_TRACE / SKYT_FAULTS). Every
shed/throttle/degrade decision lands in metrics
(``skyt_qos_*``) and as an event on the current trace span, and
``qos.shed`` / ``qos.throttle`` are injectable fault points so chaos
tests can force the paths deterministically.

Priority classes (strict order, aging prevents starvation):

    interactive > standard > batch

Overload ladder (lowest class suffers first; interactive is never shed
by the overload controller):

    level 0  admit everything
    level 1  degrade batch   (clamp max_tokens)
    level 2  shed batch, degrade standard
    level 3  shed batch AND standard
"""
import collections
import dataclasses
import math
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu.utils import faults
from skypilot_tpu.utils import log_utils
from skypilot_tpu.utils import metrics as metrics_lib
from skypilot_tpu.utils import tracing
from skypilot_tpu.utils import env

logger = log_utils.init_logger(__name__)

PRIORITIES = ('interactive', 'standard', 'batch')
CLASS_RANK = {'interactive': 0, 'standard': 1, 'batch': 2}
DEFAULT_CLASS = 'standard'
DEFAULT_TENANT = 'default'

# OpenAI `service_tier` values mapped onto our classes (the OpenAI
# routes' body-level alternative to the X-Priority header).
_SERVICE_TIER_MAP = {
    'priority': 'interactive',
    'auto': 'standard',
    'default': 'standard',
    'flex': 'batch',
    'batch': 'batch',
}

_TENANT_CHARS = frozenset(
    'abcdefghijklmnopqrstuvwxyz'
    'ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-')
_TENANT_MAX_LEN = 64


def enabled() -> bool:
    """Master switch. '0' / unset => the whole subsystem is a no-op
    (the engine keeps its plain FIFO queue, the server never consults
    the admission controller). Read at engine/server CONSTRUCTION —
    the waiting-queue type cannot change under a live engine."""
    return env.get('SKYT_QOS', '0') not in ('', '0', 'false')


# ------------------------------------------------------- header parsing
def parse_priority(value: Optional[str]) -> str:
    """X-Priority header -> class name. Absent/empty => the default
    class; anything not in PRIORITIES raises ValueError (HTTP layers
    turn it into a 400 naming the offender)."""
    if value is None or value == '':
        return DEFAULT_CLASS
    v = value.strip().lower()
    if v not in CLASS_RANK:
        raise ValueError(
            f'X-Priority must be one of {"/".join(PRIORITIES)}, '
            f'got {value!r}')
    return v


def parse_tenant(value: Optional[str]) -> str:
    """X-Tenant header -> tenant id. Absent/empty => the shared
    default tenant. The charset/length bound keeps tenant ids safe as
    metric label values and queue keys (attacker-controlled headers
    must not mint unbounded label cardinality one byte at a time —
    callers should still bound DISTINCT tenants; see
    TenantRateLimiter's eviction)."""
    if value is None or value == '':
        return DEFAULT_TENANT
    v = value.strip()
    if not v or len(v) > _TENANT_MAX_LEN or \
            not all(c in _TENANT_CHARS for c in v):
        raise ValueError(
            f'X-Tenant must be 1-{_TENANT_MAX_LEN} chars of '
            f'[A-Za-z0-9._-], got {value!r}')
    return v


def map_service_tier(tier: Any) -> Optional[str]:
    """OpenAI `service_tier` -> class, or None when the field is
    absent. Unknown tiers raise ValueError (400)."""
    if tier is None:
        return None
    if isinstance(tier, str) and tier.lower() in _SERVICE_TIER_MAP:
        return _SERVICE_TIER_MAP[tier.lower()]
    raise ValueError(
        f'service_tier must be one of '
        f'{sorted(set(_SERVICE_TIER_MAP))}, got {tier!r}')


# --------------------------------------------------- token-bucket limits
class TokenBucket:
    """Deterministic token bucket (injectable clock, float tokens).

    refill rate `rate` tokens/s up to `burst`; try_take returns
    (granted, retry_after_s) where retry_after is the exact time until
    the requested amount would be available — the Retry-After header's
    source of truth."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self.tokens = float(burst)
        self._last = clock()

    def _refill(self, now: float) -> None:
        if now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
        self._last = now

    def try_take(self, n: float = 1.0) -> 'Tuple[bool, float]':
        now = self._clock()
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return True, 0.0
        if self.rate <= 0:
            return False, 60.0
        return False, (n - self.tokens) / self.rate


class TenantRateLimiter:
    """Per-tenant token buckets, lazily created and bounded: beyond
    `max_tenants` the least-recently-used bucket is evicted (a fresh
    bucket starts full, so eviction can only ever be LENIENT — it
    never locks a tenant out). rate <= 0 disables limiting entirely."""

    def __init__(self, rate: float, burst: float,
                 max_tenants: int = 4096,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        self.max_tenants = max(1, int(max_tenants))
        self._clock = clock
        self._buckets: 'collections.OrderedDict[str, TokenBucket]' = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return self.rate > 0

    def try_take(self, tenant: str, n: float = 1.0) -> 'Tuple[bool, float]':
        if not self.active:
            return True, 0.0
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = TokenBucket(self.rate, self.burst,
                                     clock=self._clock)
                self._buckets[tenant] = bucket
                while len(self._buckets) > self.max_tenants:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(tenant)
            return bucket.try_take(n)


# -------------------------------------------------- DRR weighted fair queue
def _class_weights() -> Dict[str, float]:
    """SKYT_QOS_WEIGHTS='interactive:8,standard:4,batch:1' — the DRR
    quantum multiplier per class (matters only when aging lands two
    classes in the same band). Malformed entries fall back."""
    out = {'interactive': 8.0, 'standard': 4.0, 'batch': 1.0}
    raw = env.get('SKYT_QOS_WEIGHTS', '')
    for part in (p for p in raw.split(',') if p.strip()):
        k, sep, v = part.partition(':')
        try:
            if not sep or k.strip() not in out:
                raise ValueError
            out[k.strip()] = max(float(v), 0.001)
        except ValueError:
            logger.warning('ignoring malformed SKYT_QOS_WEIGHTS '
                           'entry %r', part)
    return out


def _model_weights() -> Dict[str, float]:
    """SKYT_QOS_MODEL_WEIGHTS='summarize:4,translate:1' — the DRR
    quantum multiplier per served model/adapter name (docs/serving.md
    "Adapter fleet"), multiplied with the class weight. Unlisted
    models weigh 1.0; malformed entries are dropped (model names are
    operator-chosen, so unlike class weights any key is legal)."""
    out: Dict[str, float] = {}
    raw = env.get('SKYT_QOS_MODEL_WEIGHTS', '')
    for part in (p for p in raw.split(',') if p.strip()):
        k, sep, v = part.partition(':')
        try:
            if not sep or not k.strip():
                raise ValueError
            out[k.strip()] = max(float(v), 0.001)
        except ValueError:
            logger.warning('ignoring malformed SKYT_QOS_MODEL_WEIGHTS '
                           'entry %r', part)
    return out


class FairQueue:
    """Deficit-round-robin weighted fair queue with strict class
    priority and aging (the scheduling core; ClassedRequestQueue
    adapts it to the engine's queue.Queue contract).

    Items are grouped into FLOWS keyed (class, tenant, model) — the
    model key (docs/serving.md "Adapter fleet") isolates adapters
    within a tenant, so one adapter's burst queues behind its own
    flow instead of starving the tenant's other models. A flow's BAND
    is its class rank minus the aging credit of its oldest item
    (``wait // aging_s``) — unbounded below, so a starved batch flow
    eventually outranks fresh interactive traffic (no starvation).
    pop() serves the lowest band; within a band, classic DRR over the
    flows in first-arrival order: each visit grants
    ``quantum * class_weight * model_weight`` deficit, a flow emits
    while its deficit covers its head's cost, and an emptied flow
    forfeits its deficit. FIFO within a flow, always."""

    def __init__(self, quantum: Optional[float] = None,
                 aging_s: Optional[float] = None,
                 weights: Optional[Dict[str, float]] = None,
                 model_weights: Optional[Dict[str, float]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.quantum = (quantum if quantum is not None
                        else env.get_float('SKYT_QOS_QUANTUM', 256.0))
        self.quantum = max(self.quantum, 0.001)
        self.aging_s = (aging_s if aging_s is not None
                        else env.get_float('SKYT_QOS_AGING_S', 30.0))
        self.aging_s = max(self.aging_s, 0.001)
        self.weights = dict(weights or _class_weights())
        self.model_weights = dict(model_weights
                                  if model_weights is not None
                                  else _model_weights())
        self._clock = clock
        # flow key -> deque[(item, cost, seq, enq_t)]
        self._flows: 'collections.OrderedDict[tuple, collections.deque]' \
            = collections.OrderedDict()
        self._deficit: Dict[tuple, float] = {}
        self._n = 0
        self._seq = 0

    def __len__(self) -> int:
        return self._n

    def push(self, item: Any, cls: str = DEFAULT_CLASS,
             tenant: str = DEFAULT_TENANT, cost: float = 1.0,
             seq: Optional[int] = None,
             t: Optional[float] = None,
             model: str = '') -> None:
        if cls not in CLASS_RANK:
            cls = DEFAULT_CLASS
        if seq is None:
            seq = self._seq
            self._seq += 1
        flow = (cls, tenant, model)
        dq = self._flows.get(flow)
        if dq is None:
            dq = collections.deque()
            self._flows[flow] = dq
            self._deficit.setdefault(flow, 0.0)
        dq.append((item, max(float(cost), 0.001), seq,
                   self._clock() if t is None else t))
        self._n += 1

    def seed_debt(self, debt: Dict[tuple, float],
                  cap: Optional[float] = None) -> None:
        """Start flows with NEGATIVE deficit equal to their recent
        service (ClassedRequestQueue's cross-tick fairness memory).
        Capped so an old debt can only delay a flow by a few rounds."""
        if cap is None:
            cap = 4.0 * self.quantum
        for flow, d in debt.items():
            if flow in self._deficit and d > 0:
                self._deficit[flow] -= min(float(d), cap)

    def _band(self, flow: tuple, now: float) -> int:
        dq = self._flows[flow]
        oldest = min(entry[3] for entry in dq)
        credit = int(max(0.0, now - oldest) / self.aging_s)
        return CLASS_RANK[flow[0]] - credit

    def depths(self) -> Dict[str, int]:
        out = {c: 0 for c in PRIORITIES}
        for flow, dq in self._flows.items():
            out[flow[0]] += len(dq)
        return out

    def pop(self, now: Optional[float] = None) -> Optional[Any]:
        if self._n == 0:
            return None
        if now is None:
            now = self._clock()
        bands = {flow: self._band(flow, now) for flow in self._flows}
        target = min(bands.values())
        cand = [flow for flow in self._flows if bands[flow] == target]
        # DRR: serve the first candidate (arrival order) whose deficit
        # covers its head; while nobody can afford, everyone in the
        # band earns a quantum. Bounded: each refill adds
        # quantum*min_weight > 0 and the head cost is finite.
        while True:
            for flow in cand:
                dq = self._flows[flow]
                item, cost, _seq, _t = dq[0]
                if self._deficit[flow] >= cost:
                    dq.popleft()
                    self._n -= 1
                    self._deficit[flow] -= cost
                    if not dq:
                        # An emptied flow forfeits its deficit (DRR).
                        del self._flows[flow]
                        del self._deficit[flow]
                    return item
            for flow in cand:
                self._deficit[flow] += (
                    self.quantum * self.weights.get(flow[0], 1.0) *
                    self.model_weights.get(flow[2], 1.0))

    def drain(self, now: Optional[float] = None) -> List[Any]:
        """Full scheduling order (consumes the queue)."""
        if now is None:
            now = self._clock()
        out = []
        while self._n:
            out.append(self.pop(now))
        return out


@dataclasses.dataclass(frozen=True)
class RequestMeta:
    """What the scheduler needs to know about a queued request. The
    engine supplies a `meta` callable mapping its _Request to this."""
    cls: str
    tenant: str
    cost: float
    seq: int
    enq_t: float
    # Served model/adapter name (docs/serving.md "Adapter fleet") —
    # the third flow key; '' (the default, and pre-adapter callers)
    # collapses to per-(class, tenant) flows as before.
    model: str = ''


class ClassedRequestQueue(queue.Queue):
    """The engine's priority-aware waiting structure: a queue.Queue
    whose backing deque is kept in SCHEDULED order, so every existing
    access pattern — get_nowait() pops, head snapshots under .mutex
    for batched admission, extendleft requeues — keeps working while
    admission order becomes class-ordered with aging + DRR tenant
    fairness.

    put() appends; the engine loop calls reorder() once per tick,
    which recomputes the schedule via FairQueue (seeded with the
    persistent per-flow service debt) and rewrites the deque in place.
    Pops charge the popped flow's debt (decayed exponentially) so a
    tenant that just got a burst served queues behind its peers next
    tick. Multi-host lockstep: only the PRIMARY reorders; the computed
    order rides the tick broadcast and followers apply_order() it, so
    hosts admit identical sequences without trusting follower clocks.

    Batched-admission buckets are preserved within a class: the
    schedule is band-major and stable by arrival within a flow, so a
    same-bucket FIFO prefix never straddles a class boundary."""

    def __init__(self, meta: Callable[[Any], 'RequestMeta'],
                 quantum: Optional[float] = None,
                 aging_s: Optional[float] = None,
                 weights: Optional[Dict[str, float]] = None,
                 debt_halflife_s: Optional[float] = None,
                 clock: Callable[[], float] = time.time) -> None:
        super().__init__()
        self._meta = meta
        self._quantum = (quantum if quantum is not None
                         else env.get_float('SKYT_QOS_QUANTUM', 256.0))
        self._aging_s = (aging_s if aging_s is not None
                         else env.get_float('SKYT_QOS_AGING_S', 30.0))
        self._weights = dict(weights or _class_weights())
        self._model_weights = _model_weights()
        self._halflife = (debt_halflife_s if debt_halflife_s is not None
                          else env.get_float('SKYT_QOS_DEBT_HALFLIFE_S',
                                          30.0))
        self._clock = clock
        self._debt: Dict[tuple, float] = {}
        self._debt_t = clock()

    # --------------------------------------------- queue.Queue plumbing
    def _get(self):
        item = self.queue.popleft()
        try:
            m = self._meta(item)
            flow = (m.cls, m.tenant, m.model)
            self._debt[flow] = self._debt.get(flow, 0.0) + m.cost
        except Exception:  # pylint: disable=broad-except
            logger.exception('qos meta extraction failed on pop')
        return item

    # ---------------------------------------------------- scheduling
    def _decay_debt(self, now: float) -> None:
        dt = max(0.0, now - self._debt_t)
        self._debt_t = now
        if not self._debt or dt <= 0:
            return
        factor = 0.5 ** (dt / max(self._halflife, 0.001))
        self._debt = {k: v * factor for k, v in self._debt.items()
                      if v * factor > 1e-3}

    def _schedule(self, items: List[Any], now: float) -> List[Any]:
        fq = FairQueue(quantum=self._quantum, aging_s=self._aging_s,
                       weights=self._weights,
                       model_weights=self._model_weights,
                       clock=lambda: now)
        for item in items:
            m = self._meta(item)
            fq.push(item, m.cls, m.tenant, m.cost, seq=m.seq,
                    t=m.enq_t, model=m.model)
        fq.seed_debt(self._debt)
        return fq.drain(now)

    def reorder(self, now: Optional[float] = None
                ) -> 'Tuple[List[int], bool]':
        """Recompute the schedule and rewrite the deque in place.
        Returns (seq order, changed) — `changed` is False when the
        deque was already in scheduled order (the lockstep primary
        skips the broadcast then)."""
        if now is None:
            now = self._clock()
        with self.mutex:
            self._decay_debt(now)
            items = list(self.queue)
            if len(items) <= 1:
                return [self._meta(i).seq for i in items], False
            ordered = self._schedule(items, now)
            changed = any(a is not b for a, b in zip(items, ordered))
            if changed:
                self.queue.clear()
                self.queue.extend(ordered)
            return [self._meta(i).seq for i in ordered], changed

    def apply_order(self, seqs: List[int]) -> None:
        """Reorder the deque to match a seq permutation computed
        elsewhere (the lockstep primary's broadcast). Items missing
        from `seqs` keep their relative order at the tail — defensive;
        by construction follower queues hold the identical set."""
        pos = {s: i for i, s in enumerate(seqs)}
        sentinel = len(pos)
        with self.mutex:
            items = sorted(
                self.queue,
                key=lambda it: pos.get(self._meta(it).seq, sentinel))
            self.queue.clear()
            self.queue.extend(items)

    def depths(self) -> Dict[str, int]:
        out = {c: 0 for c in PRIORITIES}
        with self.mutex:
            for item in self.queue:
                cls = self._meta(item).cls
                out[cls if cls in out else DEFAULT_CLASS] += 1
        return out


# ------------------------------------------------------ overload control
class OverloadController:
    """Watches live engine signals and maps them to an overload level
    with hysteresis (raise immediately, lower only after the computed
    level has stayed below the current one for SKYT_QOS_HOLD_S).

    `signals` is a zero-arg callable returning a dict with any of
    queue_depth, num_slots, kv_util (0-1), ttft_p95_s; it is sampled
    at most every SKYT_QOS_REFRESH_S so per-request admission stays
    O(1) dict reads."""

    def __init__(self, signals: Callable[[], Dict[str, float]],
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._signals = signals
        self._clock = clock
        self.queue_degrade = env.get_float('SKYT_QOS_QUEUE_DEGRADE', 4.0)
        self.queue_shed = env.get_float('SKYT_QOS_QUEUE_SHED', 8.0)
        self.kv_degrade = env.get_float('SKYT_QOS_KV_DEGRADE', 0.90)
        self.kv_shed = env.get_float('SKYT_QOS_KV_SHED', 0.97)
        self.ttft_slo_s = env.get_float('SKYT_QOS_TTFT_SLO_MS', 500.0) / 1e3
        self.hold_s = env.get_float('SKYT_QOS_HOLD_S', 2.0)
        self.refresh_s = env.get_float('SKYT_QOS_REFRESH_S', 0.25)
        self.retry_base_s = env.get_float('SKYT_QOS_RETRY_AFTER_S', 1.0)
        self._lock = threading.Lock()
        self._level = 0
        self._below_since: Optional[float] = None
        self._next_refresh = 0.0
        self._pressure = 0.0

    def _raw_level(self, sig: Dict[str, float]) -> int:  # guarded-by: _lock
        level = 0
        q = float(sig.get('queue_depth', 0) or 0)
        slots = max(1.0, float(sig.get('num_slots', 1) or 1))
        ratio = q / slots
        if ratio >= 2 * self.queue_shed:
            level = 3
        elif ratio >= self.queue_shed:
            level = max(level, 2)
        elif ratio >= self.queue_degrade:
            level = max(level, 1)
        kv = sig.get('kv_util')
        if kv is not None:
            if kv >= self.kv_shed:
                level = max(level, 2)
            elif kv >= self.kv_degrade:
                level = max(level, 1)
        ttft = sig.get('ttft_p95_s')
        if ttft is not None and self.ttft_slo_s > 0:
            if ttft >= 2 * self.ttft_slo_s:
                level = max(level, 2)
            elif ttft >= self.ttft_slo_s:
                level = max(level, 1)
        # Pressure: the dominant signal normalized to its shed point
        # (what the LB consults through the controller sync).
        self._pressure = min(1.0, max(
            ratio / max(self.queue_shed, 0.001),
            (kv or 0.0) / max(self.kv_shed, 0.001),
            (ttft or 0.0) / max(2 * self.ttft_slo_s, 0.001)
            if self.ttft_slo_s > 0 else 0.0))
        return level

    def level(self) -> int:
        now = self._clock()
        with self._lock:
            if now < self._next_refresh:
                return self._level
            self._next_refresh = now + self.refresh_s
            try:
                raw = self._raw_level(self._signals() or {})
            except Exception:  # pylint: disable=broad-except
                logger.exception('qos signal sampling failed')
                return self._level
            if raw > self._level:
                self._level = raw          # escalate immediately
                self._below_since = None
            elif raw < self._level:
                if self._below_since is None:
                    self._below_since = now
                elif now - self._below_since >= self.hold_s:
                    self._level = raw      # de-escalate after the hold
                    self._below_since = None
            else:
                self._below_since = None
            return self._level

    @property
    def pressure(self) -> float:
        # Lock-discipline fix (skyanalyze): _pressure is written by
        # level() under _lock from the engine loop while the HTTP
        # handlers read it here — take the lock for a torn-free read.
        with self._lock:
            return self._pressure

    def retry_after(self, level: Optional[int] = None) -> float:
        if level is None:
            # Lock-discipline fix (skyanalyze): the no-arg fallback
            # read raced level()'s writes from other threads.
            with self._lock:
                level = self._level
        return min(30.0, self.retry_base_s * (2 ** max(0, level - 1)))


@dataclasses.dataclass
class Decision:
    """One admission decision (every one also lands on the current
    trace span and in the skyt_qos_* counters)."""
    action: str                      # admit | degrade | shed | throttle
    level: int = 0
    retry_after: float = 0.0
    max_new_tokens: Optional[int] = None   # degrade clamp


class ServerQoS:
    """The infer server's admission controller: per-tenant token
    buckets + the overload ladder, with metrics, span events, and the
    qos.shed / qos.throttle fault points."""

    def __init__(self, signals: Callable[[], Dict[str, float]],
                 registry: Optional['metrics_lib.MetricsRegistry'] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        reg = registry or metrics_lib.REGISTRY
        self.overload = OverloadController(signals, clock=clock)
        rate = env.get_float('SKYT_QOS_TENANT_RPS', 0.0)
        burst = env.get_float('SKYT_QOS_TENANT_BURST',
                           max(10.0, 2 * rate))
        self.limiter = TenantRateLimiter(rate, burst, clock=clock)
        self.degrade_max_tokens = int(
            env.get_float('SKYT_QOS_DEGRADE_MAX_TOKENS', 32))
        self._m_requests = reg.counter(
            'skyt_qos_requests_total',
            'Requests through QoS admission', ('class',))
        # The 'model' label (docs/serving.md "Adapter fleet") is the
        # RESOLVED base-model id or loaded-adapter name — a bounded
        # set (SKYT_ADAPTER_MAX + 1), never the raw request string.
        self._m_shed = reg.counter(
            'skyt_qos_shed_total',
            'Requests shed by the overload controller (429)',
            ('class', 'model'))
        self._m_throttled = reg.counter(
            'skyt_qos_throttled_total',
            'Requests throttled by the per-(tenant, model) token '
            'bucket (429)', ('class', 'model'))
        self._m_degraded = reg.counter(
            'skyt_qos_degraded_total',
            'Requests admitted with degraded limits (max_tokens '
            'clamped)', ('class',))
        self._m_level = reg.gauge(
            'skyt_qos_overload_level',
            'Current overload ladder level (0 ok .. 3 shed standard)')

    def admit(self, cls: str, tenant: str,
              max_new_tokens: Optional[int] = None,
              model: str = '') -> 'Decision':
        """Decide for one request. The caller (HTTP handler) turns
        shed/throttle into 429 + Retry-After and applies the degrade
        clamp before building SamplingParams. `model` MUST be a
        resolved label (base id or loaded-adapter name), never the
        raw request string — it keys a token bucket and two counter
        labels, both cardinality-bounded only if the caller is."""
        self._m_requests.labels(cls).inc()
        level = self.overload.level()
        self._m_level.set(level)
        forced_shed = forced_throttle = False
        # Injectable fault points: an armed 'error' rule FORCES the
        # path (e.g. SKYT_FAULTS='qos.shed=error,where=cls:batch').
        try:
            faults.inject('qos.shed', cls=cls, tenant=tenant)
        except faults.FaultError:
            forced_shed = True
        try:
            faults.inject('qos.throttle', cls=cls, tenant=tenant)
        except faults.FaultError:
            forced_throttle = True
        span = tracing.current_span()
        if span is not None:
            span.set_attribute('qos.class', cls)
            span.set_attribute('qos.tenant', tenant)
            span.set_attribute('qos.level', level)
        if not forced_shed and not forced_throttle:
            # Buckets keyed (class, tenant, model): one adapter's
            # burst exhausts ITS bucket, not the tenant's other
            # models' (docs/serving.md "Adapter fleet").
            ok, wait = self.limiter.try_take(
                f'{cls}|{tenant}|{model}')
            if not ok:
                forced_throttle = True
                retry = wait
            else:
                retry = self.overload.retry_after(level)
        else:
            retry = self.overload.retry_after(max(level, 1))
        if forced_throttle:
            self._m_throttled.labels(cls, model).inc()
            if span is not None:
                span.add_event('qos.throttle', cls=cls, tenant=tenant)
            return Decision('throttle', level, max(retry, 0.1))
        shed = forced_shed or \
            (level >= 3 and cls != 'interactive') or \
            (level >= 2 and cls == 'batch')
        if shed:
            self._m_shed.labels(cls, model).inc()
            if span is not None:
                span.add_event('qos.shed', cls=cls, tenant=tenant,
                               level=level)
            return Decision('shed', level, max(retry, 0.1))
        degrade = (level >= 1 and cls == 'batch') or \
                  (level >= 2 and cls == 'standard')
        if degrade and max_new_tokens is not None and \
                max_new_tokens > self.degrade_max_tokens:
            self._m_degraded.labels(cls).inc()
            if span is not None:
                span.add_event('qos.degrade', cls=cls,
                               max_new_tokens=self.degrade_max_tokens)
            return Decision('degrade', level,
                            max_new_tokens=self.degrade_max_tokens)
        return Decision('admit', level)

    def snapshot(self, depths: Optional[Dict[str, int]] = None
                 ) -> Dict[str, Any]:
        """QoS pressure summary: served in /stats (scraped by the
        controller, forwarded to the LB via the sync response) and
        attached to flight-recorded slow traces."""
        level = self.overload.level()
        out: Dict[str, Any] = {
            'level': level,
            'pressure': round(self.overload.pressure, 4),
            'retry_after_s': round(self.overload.retry_after(level), 3),
        }
        if depths is not None:
            out['classes'] = depths
        return out


def rate_by_class(events, window_s: float,
                  now: Optional[float] = None) -> Dict[str, float]:
    """Per-class event rate (events/second) over the trailing
    ``window_s`` of an iterable of ``(wall_ts, class)`` pairs. The
    N-active LB tier uses this for the demand/shed slices each LB
    advertises to its peers (docs/robustness.md "Front door"), so
    fleet-wide pressure is a sum of per-LB rates rather than one LB's
    view."""
    if now is None:
        now = time.time()
    window_s = max(float(window_s), 1e-9)
    cut = now - window_s
    counts: Dict[str, int] = {}
    for ts, cls in events:
        try:
            if float(ts) >= cut:
                counts[cls] = counts.get(cls, 0) + 1
        except (TypeError, ValueError):
            continue
    return {c: n / window_s for c, n in counts.items()}


def shed_avoid_classes(level: int) -> 'Tuple[str, ...]':
    """Classes a replica at `level` would shed — the LB avoids
    routing those classes there while an unpressured replica exists."""
    if level >= 3:
        return ('standard', 'batch')
    if level >= 2:
        return ('batch',)
    return ()


def autoscale_class_weights() -> Dict[str, float]:
    """Per-class demand weights for the QoS-aware autoscaler
    (SKYT_QOS_AUTOSCALE_WEIGHTS='interactive:1,standard:1,batch:0.25').
    Batch demand is deliberately discounted: it tolerates queueing, so
    it should not force scale-ups the way interactive demand does."""
    out = {'interactive': 1.0, 'standard': 1.0, 'batch': 0.25}
    raw = env.get('SKYT_QOS_AUTOSCALE_WEIGHTS', '')
    for part in (p for p in raw.split(',') if p.strip()):
        k, sep, v = part.partition(':')
        try:
            if not sep or k.strip() not in out:
                raise ValueError
            out[k.strip()] = max(float(v), 0.0)
        except ValueError:
            logger.warning('ignoring malformed '
                           'SKYT_QOS_AUTOSCALE_WEIGHTS entry %r', part)
    return out


def retry_after_header(seconds: float) -> str:
    """Retry-After header value: integral seconds, >= 1 (the header
    is delta-seconds; sub-second advice rounds up)."""
    return str(max(1, int(math.ceil(seconds))))
