"""Service entrypoint: runs controller + load balancer for one service.

Reference: sky/serve/service.py (:131 _start — starts controller and LB
as separate processes, :38 signal-file termination, :64 storage cleanup).
Here both aiohttp apps share one asyncio loop in one process (they are
I/O-bound; the blocking cluster work lives on the controller's threads),
so a service is exactly one daemon process.

Run:  python -m skypilot_tpu.serve.service --service-name NAME
"""
import argparse
import asyncio
import os

from aiohttp import web

from skypilot_tpu.serve import controller as controller_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.utils import log_utils

logger = log_utils.init_logger(__name__)


async def _serve(service_name: str) -> None:
    svc = serve_state.get_service(service_name)
    assert svc is not None, f'service {service_name} not in state DB'
    spec = svc['spec']
    controller = controller_lib.SkyServeController(
        service_name, spec, svc['task_yaml'], svc['controller_port'])
    auth_token = svc.get('auth_token')
    lb = lb_lib.SkyServeLoadBalancer(
        controller_url=f'http://127.0.0.1:{svc["controller_port"]}',
        port=svc['lb_port'],
        policy=getattr(spec, 'load_balancing_policy', None)
        or 'round_robin',
        controller_auth=auth_token)

    # Controller admin API (terminate/update_service): loopback bind
    # AND a per-service bearer token (minted at serve up) — reaching
    # the port is not enough to terminate or roll the service. Only the
    # load balancer is the externally reachable endpoint.
    controller_runner = web.AppRunner(controller.make_app(auth_token))
    await controller_runner.setup()
    await web.TCPSite(controller_runner, '127.0.0.1',
                      svc['controller_port']).start()
    lb_runner = web.AppRunner(lb.make_app())
    await lb_runner.setup()
    await web.TCPSite(lb_runner, '0.0.0.0', svc['lb_port']).start()

    controller.start_control_loop()
    serve_state.set_service_status(service_name,
                                   serve_state.ServiceStatus.REPLICA_INIT)
    logger.info('service %s: controller :%d, load balancer :%d',
                service_name, svc['controller_port'], svc['lb_port'])

    # Run until terminated via /controller/terminate (which tears down
    # replicas) — then clean up the service row and exit.
    while True:
        await asyncio.sleep(1)
        svc = serve_state.get_service(service_name)
        if svc is None:
            break
        if svc['status'] is serve_state.ServiceStatus.SHUTTING_DOWN and \
                controller.replica_manager.num_alive() == 0:
            _cleanup_ephemeral_storages(service_name, svc['task_yaml'])
            serve_state.remove_service(service_name)
            break
    await lb_runner.cleanup()
    await controller_runner.cleanup()
    logger.info('service %s shut down.', service_name)


def _cleanup_ephemeral_storages(service_name: str,
                                task_yaml: str) -> None:
    """Delete translated (persistent: False) buckets when the service
    terminates — every version's, not just the current one (rolling
    updates leave each version's buckets behind; reference:
    sky/serve/service.py:64 cleanup_storage). The jobs analog lives in
    jobs/controller.py `_cleanup`."""
    import glob

    import yaml

    from skypilot_tpu.utils import controller_utils
    pattern = os.path.join(os.path.dirname(task_yaml),
                           f'{service_name}.task*.yaml')
    for path in sorted(glob.glob(pattern)):
        try:
            with open(path, encoding='utf-8') as f:
                cfg = yaml.safe_load(f) or {}
            controller_utils.cleanup_ephemeral_storages(cfg)
        except (OSError, yaml.YAMLError) as e:
            # A corrupt/unreadable yaml must not wedge shutdown: the
            # service row still has to be removed so `serve down`
            # completes (the bucket leak is logged instead).
            logger.warning('storage cleanup skipped for %s: %s', path, e)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--service-name', required=True)
    args = parser.parse_args(argv)
    serve_state.set_service_controller_pid(args.service_name, os.getpid())
    asyncio.run(_serve(args.service_name))


if __name__ == '__main__':
    main()
