"""Service entrypoint: controller + load balancer for one service.

Reference: sky/serve/service.py (:131 _start — starts controller and LB
as separate processes, :38 signal-file termination, :64 storage cleanup).
Here both aiohttp apps share one asyncio loop in one process by default
(they are I/O-bound; the blocking cluster work lives on the controller's
threads), so a service is exactly one daemon process.

Crash-tolerant deployments split the roles (docs/robustness.md
"Control plane"):

    python -m skypilot_tpu.serve.service --service-name NAME   # both
    ... --service-name NAME --role controller   # control plane only
    ... --service-name NAME --role lb           # front door only

Any number of `--role lb` processes may run: the first to win the
LeaderLease (a kernel-released file lock) serves the LB port; the rest
mirror LBState via the controller sync as hot standbys and take over
within one lease interval of leader death. A `--role controller`
restart ADOPTS the replicas recorded in serve.db instead of
relaunching them (serve/replica_managers.py).

N-active front door (docs/serving.md "N-active front door"): give
each `--role lb` process its own port and the peer list, and ALL of
them serve concurrently — no lease, shared state via controller sync
plus LB<->LB gossip, consistent-hash prefix-affinity routing if the
spec asks for it:

    ... --role lb --lb-port 8081 --lb-peers http://h:8082,http://h:8083
    ... --role lb --lb-port 8082 --lb-peers http://h:8081,http://h:8083
    ... --role lb --lb-port 8083 --lb-peers http://h:8081,http://h:8082
"""
import argparse
import asyncio
import os
from typing import Optional

from aiohttp import web

from skypilot_tpu.serve import controller as controller_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.utils import log_utils

logger = log_utils.init_logger(__name__)


# Canonical definition lives beside the rest of the on-disk state
# contract; re-exported here for the LB-runner callers.
lb_lease_path = serve_state.lb_lease_path


async def _start_controller(
        service_name: str, svc: dict
) -> 'tuple[controller_lib.SkyServeController, web.AppRunner]':
    controller = controller_lib.SkyServeController(
        service_name, svc['spec'], svc['task_yaml'],
        svc['controller_port'])
    # Controller admin API (terminate/update_service): loopback bind
    # AND a per-service bearer token (minted at serve up) — reaching
    # the port is not enough to terminate or roll the service. Only the
    # load balancer is the externally reachable endpoint.
    runner = web.AppRunner(controller.make_app(svc.get('auth_token')))
    await runner.setup()
    await web.TCPSite(runner, '127.0.0.1',
                      svc['controller_port']).start()
    controller.start_control_loop()
    return controller, runner


async def _start_lb(service_name: str, svc: dict,
                    lb_port: Optional[int] = None,
                    lb_id: Optional[str] = None,
                    lb_peers: Optional[str] = None,
                    lb_advertise_url: Optional[str] = None
                    ) -> Optional[web.AppRunner]:
    """Build the LB and serve it. Default (no peers, no port
    override): behind the leader lease — blocks until this process IS
    the leader (instant when no other LB runs); a standby gives up the
    wait when the service row disappears (serve down while standing
    by) and returns None. With a peer list (flag or
    SKYT_LB_PEER_URLS): one member of the N-active tier — own port,
    no lease, serves immediately."""
    spec = svc['spec']
    port = lb_port if lb_port is not None else svc['lb_port']
    lb = lb_lib.SkyServeLoadBalancer(
        controller_url=f'http://127.0.0.1:{svc["controller_port"]}',
        port=port,
        policy=getattr(spec, 'load_balancing_policy', None)
        or 'round_robin',
        controller_auth=svc.get('auth_token'),
        # Stale-state mode probes with the service's OWN readiness
        # contract — same path/post-data/timeout the controller's
        # prober uses, so LB-side pruning can never be stricter than
        # the readiness definition the replicas signed up for.
        stale_probe_path=spec.readiness_path,
        stale_probe_post=spec.post_data,
        stale_probe_timeout_s=spec.probe_timeout_seconds,
        lb_id=lb_id,
        # peers=None falls back to SKYT_LB_PEER_URLS inside the
        # constructor — ONE parser (strip, drop empties, drop own
        # advertise URL), not a drifting copy here.
        peers=([p for p in lb_peers.split(',')]
               if lb_peers is not None else None),
        advertise_url=lb_advertise_url)
    if lb.peers or lb.peer_discovery:
        return await lb_lib.serve_active(lb)
    lease = lb_lib.LeaderLease(lb_lease_path(service_name))
    runner, _hb = await lb_lib.serve_as_leader(
        lb, lease,
        abort=lambda: serve_state.get_service(service_name) is None)
    return runner


async def _serve(service_name: str, role: str = 'both',
                 lb_port: Optional[int] = None,
                 lb_id: Optional[str] = None,
                 lb_peers: Optional[str] = None,
                 lb_advertise_url: Optional[str] = None) -> None:
    svc = serve_state.get_service(service_name)
    assert svc is not None, f'service {service_name} not in state DB'

    controller: Optional[controller_lib.SkyServeController] = None
    controller_runner: Optional[web.AppRunner] = None
    lb_runner: Optional[web.AppRunner] = None
    if role in ('both', 'controller'):
        controller, controller_runner = await _start_controller(
            service_name, svc)
    if role in ('both', 'lb'):
        lb_runner = await _start_lb(service_name, svc, lb_port=lb_port,
                                    lb_id=lb_id, lb_peers=lb_peers,
                                    lb_advertise_url=lb_advertise_url)

    if controller is not None:
        serve_state.set_service_status(
            service_name, serve_state.ServiceStatus.REPLICA_INIT)
    logger.info('service %s (%s): controller :%d, load balancer :%d',
                service_name, role, svc['controller_port'],
                svc['lb_port'])

    # Run until terminated via /controller/terminate (which tears down
    # replicas) — the controller role then cleans up the service row
    # and exits; an LB-only process exits when the row disappears.
    while True:
        await asyncio.sleep(1)
        svc = serve_state.get_service(service_name)
        if svc is None:
            break
        if controller is not None and \
                svc['status'] is serve_state.ServiceStatus.SHUTTING_DOWN \
                and controller.replica_manager.num_alive() == 0:
            _cleanup_ephemeral_storages(service_name, svc['task_yaml'])
            serve_state.remove_service(service_name)
            break
    if lb_runner is not None:
        await lb_runner.cleanup()
    if controller_runner is not None:
        await controller_runner.cleanup()
    logger.info('service %s (%s) shut down.', service_name, role)


def _cleanup_ephemeral_storages(service_name: str,
                                task_yaml: str) -> None:
    """Delete translated (persistent: False) buckets when the service
    terminates — every version's, not just the current one (rolling
    updates leave each version's buckets behind; reference:
    sky/serve/service.py:64 cleanup_storage). The jobs analog lives in
    jobs/controller.py `_cleanup`."""
    import glob

    import yaml

    from skypilot_tpu.utils import controller_utils
    pattern = os.path.join(os.path.dirname(task_yaml),
                           f'{service_name}.task*.yaml')
    for path in sorted(glob.glob(pattern)):
        try:
            with open(path, encoding='utf-8') as f:
                cfg = yaml.safe_load(f) or {}
            controller_utils.cleanup_ephemeral_storages(cfg)
        except (OSError, yaml.YAMLError) as e:
            # A corrupt/unreadable yaml must not wedge shutdown: the
            # service row still has to be removed so `serve down`
            # completes (the bucket leak is logged instead).
            logger.warning('storage cleanup skipped for %s: %s', path, e)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--service-name', required=True)
    parser.add_argument('--role', choices=('both', 'controller', 'lb'),
                        default='both',
                        help='which halves of the control plane this '
                             'process runs (lb processes beyond the '
                             'first become hot standbys, or N-active '
                             'peers with --lb-peers)')
    parser.add_argument('--lb-port', type=int, default=None,
                        help='serve this port instead of the service '
                             'row\'s lb_port (one port per member of '
                             'an N-active tier)')
    parser.add_argument('--lb-id', default=None,
                        help='LB instance id (default lb-<port>)')
    parser.add_argument('--lb-peers', default=None,
                        help='comma-separated peer LB base URLs; '
                             'presence switches this LB from the '
                             'lease/standby model to N-active. The '
                             "literal 'auto' discovers the tier from "
                             "the controller's registered-LB list on "
                             'every sync instead (manual lists win)')
    parser.add_argument('--lb-advertise-url', default=None,
                        help='URL peers and the controller reach this '
                             'LB at (default http://127.0.0.1:<port> — '
                             'override on multi-host tiers)')
    args = parser.parse_args(argv)
    if args.role in ('both', 'controller'):
        serve_state.set_service_controller_pid(args.service_name,
                                               os.getpid())
    asyncio.run(_serve(args.service_name, role=args.role,
                       lb_port=args.lb_port, lb_id=args.lb_id,
                       lb_peers=args.lb_peers,
                       lb_advertise_url=args.lb_advertise_url))


if __name__ == '__main__':
    main()
