"""SLO objectives, goodput accounting, and multi-window burn-rate
alerting (docs/observability.md "Fleet plane").

The QoS plane (serve/qos.py) decides who runs first; this module
answers whether the promises held: per-class objectives (TTFT p95, ITL
p95, availability), request/token GOODPUT — work delivered *within*
its SLO, the only throughput number worth paying chips for (the
SLO-per-dollar framing the Gemma-on-TPU paper uses) — and error-budget
burn-rate alerting over the classic paired windows (fast 5m/1h, slow
6h/3d) with asymmetric fire/clear hysteresis.

Three pieces, one per place in the stack:

  * :func:`objectives` — declarative per-class targets, env-tunable
    via ``SKYT_SLO_*``;
  * :class:`GoodputTracker` — lives in the infer server: classifies
    each finished request against its class objective and publishes
    ``skyt_slo_{good_,}{requests,tokens}_total{class,tenant}`` plus a
    per-class TTFT histogram. These counters are what the fleet
    scraper aggregates;
  * :class:`BurnRateEvaluator` — lives fleet-side (serve/fleet.py):
    reads windowed deltas of those counters from a time-series source
    and drives ``skyt_slo_burn_rate{class,window}`` /
    ``skyt_slo_alert{class}`` gauges, with a span event per state
    transition.

Clock discipline: like utils/timeseries.py, this file never calls
``time.time()`` / ``time.monotonic()`` directly (tools/lint.py
enforces it) — every clock is injected, so the burn-rate truth table
in tests/test_slo.py replays deterministically.
"""
import dataclasses
import threading
import time
from typing import Any, Callable, Dict, Optional

from skypilot_tpu.serve import qos as qos_lib
from skypilot_tpu.utils import log_utils
from skypilot_tpu.utils import metrics as metrics_lib
from skypilot_tpu.utils import tracing
from skypilot_tpu.utils import env

logger = log_utils.init_logger(__name__)

# Default per-class latency objectives (ms). Interactive mirrors the
# BASELINE.md serve row (p50 TTFT < 500ms -> p95 objective 500ms on
# the 1B proxy); batch tolerates queueing by design.
_DEFAULT_TTFT_MS = {'interactive': 500.0, 'standard': 2000.0,
                    'batch': 10000.0}
_DEFAULT_ITL_MS = {'interactive': 100.0, 'standard': 250.0,
                   'batch': 1000.0}
_DEFAULT_TARGET = 0.99


@dataclasses.dataclass(frozen=True)
class ClassObjective:
    """One QoS class's promise: p95 TTFT/ITL bounds and the target
    fraction of requests that must meet them (the SLO target whose
    complement is the error budget)."""
    cls: str
    ttft_ms: float
    itl_ms: float
    target: float

    @property
    def budget(self) -> float:
        """Error budget = allowed bad fraction."""
        return max(1e-6, 1.0 - self.target)


def objectives() -> Dict[str, ClassObjective]:
    """Per-class objectives from the environment:

    ``SKYT_SLO_TTFT_MS_<CLASS>`` / ``SKYT_SLO_ITL_MS_<CLASS>`` bound
    the latency halves; ``SKYT_SLO_TARGET`` (global) or
    ``SKYT_SLO_TARGET_<CLASS>`` sets the attainment target. Read at
    call time so tests (and mid-incident operators) can retune without
    a restart."""
    target_all = env.get_float('SKYT_SLO_TARGET', _DEFAULT_TARGET)
    out = {}
    for cls in qos_lib.PRIORITIES:
        up = cls.upper()
        out[cls] = ClassObjective(
            cls=cls,
            ttft_ms=env.get_float(f'SKYT_SLO_TTFT_MS_{up}',
                               _DEFAULT_TTFT_MS[cls]),
            itl_ms=env.get_float(f'SKYT_SLO_ITL_MS_{up}',
                              _DEFAULT_ITL_MS[cls]),
            target=min(0.999999, max(
                0.0, env.get_float(f'SKYT_SLO_TARGET_{up}', target_all))))
    return out


# --------------------------------------------------- goodput accounting
class GoodputTracker:
    """Request-completion classifier for one replica.

    The infer server calls :meth:`record` once per finished engine
    request with what actually happened (status, server-side TTFT,
    mean ITL, generated tokens); the tracker publishes per
    (class, tenant) goodput counters and a per-class TTFT histogram.
    Tenant label cardinality is bounded twice over: qos.parse_tenant's
    charset/length bound upstream, and utils/metrics' per-family
    series cap underneath.

    Objectives are re-read from the environment at most once per
    second — the documented no-restart SKYT_SLO_* retuning must reach
    the replica-side classifier too (counters classified against
    stale objectives would disagree with the fleet report) — without
    paying ~9 env parses on every request."""

    def __init__(self, registry: Optional[
            'metrics_lib.MetricsRegistry'] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        reg = registry or metrics_lib.REGISTRY
        self._clock = clock
        self.objectives = objectives()
        self._objectives_at = clock()
        labels = ('cls', 'tenant')
        self._m_requests = reg.counter(
            'skyt_slo_requests_total',
            'Finished requests by QoS class and tenant', labels)
        self._m_good_requests = reg.counter(
            'skyt_slo_good_requests_total',
            'Requests that finished successfully WITHIN their class '
            'SLO (TTFT/ITL objectives)', labels)
        self._m_tokens = reg.counter(
            'skyt_slo_tokens_total',
            'Generated tokens by QoS class and tenant', labels)
        self._m_good_tokens = reg.counter(
            'skyt_slo_good_tokens_total',
            'Generated tokens belonging to within-SLO requests '
            '(goodput)', labels)
        self._m_ttft = reg.histogram(
            'skyt_slo_ttft_seconds',
            'Server-side TTFT (request arrival to first token) by '
            'QoS class', ('cls',))

    def record(self, cls: str, tenant: str, ok: bool,
               ttft_s: Optional[float] = None,
               itl_s: Optional[float] = None,
               tokens: int = 0) -> bool:
        """Classify one finished request; returns whether it was good
        (successful AND within every measured latency objective)."""
        now = self._clock()
        if now - self._objectives_at >= 1.0:
            self.objectives = objectives()
            self._objectives_at = now
        obj = self.objectives.get(cls)
        if obj is None:
            cls = qos_lib.DEFAULT_CLASS
            obj = self.objectives[cls]
        good = bool(ok)
        if good and ttft_s is not None and \
                ttft_s * 1e3 > obj.ttft_ms:
            good = False
        if good and itl_s is not None and itl_s * 1e3 > obj.itl_ms:
            good = False
        self._m_requests.labels(cls, tenant).inc()
        self._m_tokens.labels(cls, tenant).inc(max(0, int(tokens)))
        if ttft_s is not None:
            self._m_ttft.labels(cls).observe(ttft_s)
        # The good counters are touched (inc 0) even on a bad request:
        # all four series must appear in the SAME scrape as their
        # flow's first request, or a downstream windowed delta would
        # read the missing good series as "no data" and score the
        # window 100% bad (counter windows need both edges).
        self._m_good_requests.labels(cls, tenant).inc(
            1 if good else 0)
        self._m_good_tokens.labels(cls, tenant).inc(
            max(0, int(tokens)) if good else 0)
        return good


# ----------------------------------------------- burn-rate alerting
def _fmt_window(seconds: float) -> str:
    for unit, div in (('d', 86400.0), ('h', 3600.0), ('m', 60.0)):
        if seconds >= div and seconds % div == 0:
            return f'{int(seconds // div)}{unit}'
    return f'{int(seconds)}s'


@dataclasses.dataclass(frozen=True)
class BurnWindows:
    """The two classic paired alert windows (Google SRE workbook
    multi-window multi-burn-rate): the FAST pair catches a budget
    burning in hours (page), the SLOW pair a budget leaking over days
    (ticket). A pair fires only when BOTH its windows burn above its
    threshold — the long window proves it is real, the short window
    both makes detection fast and clears the alert fast once the
    bleeding stops."""
    fast_short_s: float = 300.0
    fast_long_s: float = 3600.0
    fast_threshold: float = 14.4       # 2% of budget in 1h
    slow_short_s: float = 21600.0
    slow_long_s: float = 259200.0
    slow_threshold: float = 6.0        # 10% of budget in 3d (6h pair)

    @classmethod
    def from_env(cls) -> 'BurnWindows':
        return cls(
            fast_short_s=env.get_float('SKYT_SLO_FAST_SHORT_S', 300.0),
            fast_long_s=env.get_float('SKYT_SLO_FAST_LONG_S', 3600.0),
            fast_threshold=env.get_float('SKYT_SLO_FAST_BURN', 14.4),
            slow_short_s=env.get_float('SKYT_SLO_SLOW_SHORT_S', 21600.0),
            slow_long_s=env.get_float('SKYT_SLO_SLOW_LONG_S', 259200.0),
            slow_threshold=env.get_float('SKYT_SLO_SLOW_BURN', 6.0))

    def all(self) -> 'Dict[str, float]':
        """window label -> seconds, dedup'd, short-to-long."""
        out: Dict[str, float] = {}
        for s in sorted({self.fast_short_s, self.fast_long_s,
                         self.slow_short_s, self.slow_long_s}):
            out[_fmt_window(s)] = s
        return out


class BurnRateEvaluator:
    """Error-budget burn rates per class from a windowed time-series
    source, with the paired-window alert state machine.

    `source` is anything with the TimeSeriesStore read protocol —
    ``sum_delta(name, match, window_s, now)`` and
    ``quantile(family, match, q, window_s, now)`` — i.e. a single
    store in tests or serve/fleet.py's cross-replica merger in
    production.

    burn(window) = bad_fraction(window) / error_budget. 1.0 means the
    budget is burning exactly at the rate that exhausts it in one SLO
    period; the fast pair's 14.4 means "2% of a 30-day budget gone in
    one hour".

    Hysteresis is asymmetric by construction: FIRE needs both windows
    of a pair above its threshold; CLEAR needs every pair's SHORT
    window back below. The long windows stay elevated for hours after
    an incident — requiring them to clear would pin the alert long
    after recovery, while clearing on the short window alone is the
    standard fast-clear semantics."""

    def __init__(self, source: Any,
                 objectives_fn: Callable[
                     [], Dict[str, ClassObjective]] = objectives,
                 windows: Optional[BurnWindows] = None,
                 registry: Optional[
                     'metrics_lib.MetricsRegistry'] = None,
                 clock: Callable[[], float] = time.time,
                 tracer: Optional['tracing.Tracer'] = None) -> None:
        self.source = source
        self._objectives_fn = objectives_fn
        self.windows = windows or BurnWindows.from_env()
        self._clock = clock
        self._tracer = tracer
        reg = registry or metrics_lib.REGISTRY
        self._m_burn = reg.gauge(
            'skyt_slo_burn_rate',
            'Error-budget burn rate (bad fraction / budget) per QoS '
            'class and trailing window', ('cls', 'window'))
        self._m_alert = reg.gauge(
            'skyt_slo_alert',
            'Multi-window burn-rate alert state per QoS class '
            '(1 firing, 0 ok)', ('cls',))
        self._m_attainment = reg.gauge(
            'skyt_slo_attainment',
            'Fraction of requests within SLO over the fast-long '
            'window, per QoS class', ('cls',))
        self._lock = threading.Lock()
        self._firing: Dict[str, bool] = {}

    # ------------------------------------------------------- internals
    def _bad_fraction(self, cls: str, window_s: float, now: float
                      ) -> 'tuple[Optional[float], Optional[float]]':
        """-> (bad_fraction, total_requests) over the window; None/None
        with no data (no data must read as 'no burn', never as 100%)."""
        total = self.source.sum_delta('skyt_slo_requests_total',
                                      {'cls': cls}, window_s, now=now)
        if not total:
            return None, total
        good = self.source.sum_delta('skyt_slo_good_requests_total',
                                     {'cls': cls}, window_s,
                                     now=now) or 0.0
        return max(0.0, min(1.0, 1.0 - good / total)), total

    def _transition(self, cls: str, firing: bool, now: float,
                    burns: Dict[str, float]) -> None:
        """Record an alert state change: gauge, log, and a span event
        on the tracing plane (a zero-length forced-sample span — same
        pattern as train.steps: transitions are rare and are exactly
        the moments worth keeping)."""
        logger.warning('SLO alert %s for class %r (burn rates: %s)',
                       'FIRING' if firing else 'resolved', cls,
                       {k: round(v, 2) for k, v in burns.items()})
        if tracing.enabled():
            (self._tracer or tracing.TRACER).record_span(
                'slo.alert', now, now, sampled=True,
                attributes={'class': cls,
                            'state': 'firing' if firing else 'resolved',
                            **{f'burn_{k}': round(v, 3)
                               for k, v in burns.items()}},
                events=[{'name': 'slo.alert.firing' if firing
                         else 'slo.alert.resolved', 'ts': now,
                         'class': cls}])

    # ------------------------------------------------------ evaluation
    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation pass: refresh every gauge, run the alert
        state machine, and return the JSON-ready report (the body of
        ``GET /fleet/slo``'s ``slo`` section)."""
        if now is None:
            now = self._clock()
        objs = self._objectives_fn()
        w = self.windows
        report: Dict[str, Any] = {}
        for cls, obj in objs.items():
            burns: Dict[str, float] = {}
            per_window: Dict[str, Any] = {}
            for label, seconds in w.all().items():
                bad, total = self._bad_fraction(cls, seconds, now)
                burn = 0.0 if bad is None else bad / obj.budget
                burns[label] = burn
                self._m_burn.labels(cls, label).set(round(burn, 4))
                per_window[label] = {
                    'burn_rate': round(burn, 4),
                    'attainment': (None if bad is None
                                   else round(1.0 - bad, 6)),
                    'requests': total or 0,
                }
            fast_s, fast_l = (_fmt_window(w.fast_short_s),
                              _fmt_window(w.fast_long_s))
            slow_s, slow_l = (_fmt_window(w.slow_short_s),
                              _fmt_window(w.slow_long_s))
            fast_active = (burns[fast_s] >= w.fast_threshold and
                           burns[fast_l] >= w.fast_threshold)
            slow_active = (burns[slow_s] >= w.slow_threshold and
                           burns[slow_l] >= w.slow_threshold)
            with self._lock:
                was = self._firing.get(cls, False)
                if not was:
                    firing = fast_active or slow_active
                else:
                    # Asymmetric clear: every pair's SHORT window must
                    # drop below its threshold.
                    firing = not (
                        burns[fast_s] < w.fast_threshold and
                        burns[slow_s] < w.slow_threshold)
                self._firing[cls] = firing
                changed = firing != was
            self._m_alert.labels(cls).set(1 if firing else 0)
            att = per_window[fast_l]['attainment']
            if att is not None:
                self._m_attainment.labels(cls).set(att)
            if changed:
                self._transition(cls, firing, now, burns)
            ttft_p95 = self.source.quantile(
                'skyt_slo_ttft_seconds', {'cls': cls}, 0.95,
                w.fast_long_s, now=now)
            report[cls] = {
                'objective': {'ttft_p95_ms': obj.ttft_ms,
                              'itl_p95_ms': obj.itl_ms,
                              'target': obj.target},
                'windows': per_window,
                'alert': firing,
                'ttft_p95_ms': (None if ttft_p95 is None
                                else round(ttft_p95 * 1e3, 2)),
            }
        return report

    def firing(self, cls: str) -> bool:
        with self._lock:
            return self._firing.get(cls, False)


# ------------------------------------------------------- cost reporting
def _chips_per_replica() -> float:
    return max(0.0, env.get_float('SKYT_FLEET_CHIPS_PER_REPLICA', 1.0))


def goodput_report(source: Any, window_s: float, now: float,
                   replicas: int) -> Dict[str, Any]:
    """Tokens/requests served WITHIN SLO per (class, tenant) over the
    window, plus the chip-time cost report: good tokens per chip-second
    and its inverse — the number the Gemma-on-TPU paper argues TPU
    serving on (what did each good token cost in chip-time?).

    chip-seconds = replicas x chips-per-replica
    (``SKYT_FLEET_CHIPS_PER_REPLICA``, from the accelerator spec; 1 for
    single-chip replicas) x window. Replica count is the number of
    replicas CONTRIBUTING scrapes — a replica whose series aged out
    stops being billed."""
    chips = replicas * _chips_per_replica()
    chip_seconds = chips * window_s
    classes: Dict[str, Any] = {}
    total_good_tokens = 0.0
    total_tokens = 0.0
    for cls in qos_lib.PRIORITIES:
        match = {'cls': cls}
        tenants: Dict[str, Any] = {}
        good_by_tenant = source.grouped_delta(
            'skyt_slo_good_tokens_total', 'tenant', window_s,
            now=now, match=match)
        tok_by_tenant = source.grouped_delta(
            'skyt_slo_tokens_total', 'tenant', window_s, now=now,
            match=match)
        greq_by_tenant = source.grouped_delta(
            'skyt_slo_good_requests_total', 'tenant', window_s,
            now=now, match=match)
        req_by_tenant = source.grouped_delta(
            'skyt_slo_requests_total', 'tenant', window_s, now=now,
            match=match)
        for tenant in sorted(set(tok_by_tenant) | set(req_by_tenant)):
            tenants[tenant] = {
                'good_tokens': good_by_tenant.get(tenant, 0.0),
                'tokens': tok_by_tenant.get(tenant, 0.0),
                'good_requests': greq_by_tenant.get(tenant, 0.0),
                'requests': req_by_tenant.get(tenant, 0.0),
            }
        cls_good = sum(t['good_tokens'] for t in tenants.values())
        cls_tok = sum(t['tokens'] for t in tenants.values())
        total_good_tokens += cls_good
        total_tokens += cls_tok
        classes[cls] = {'tenants': tenants,
                        'good_tokens': cls_good, 'tokens': cls_tok}
    gtps = (total_good_tokens / chip_seconds
            if chip_seconds > 0 else None)
    return {
        'window_s': window_s,
        'replicas': replicas,
        'chips': chips,
        'accelerator': env.get('SKYT_FLEET_ACCELERATOR', ''),
        'classes': classes,
        'good_tokens': total_good_tokens,
        'tokens': total_tokens,
        'good_tokens_per_chip_second': (None if gtps is None
                                        else round(gtps, 4)),
        'chip_seconds_per_good_token': (
            None if not gtps else round(1.0 / gtps, 6)),
    }
