"""User config: ~/.skypilot_tpu/config.yaml with nested-key access.

Mirrors the reference's sky/skypilot_config.py (get_nested :102, set_nested
:155, _try_load_config :178): a small YAML file of overrides — controller
resources, GCP project/service-account, proxies, per-cloud defaults —
loaded once per process, snapshotted & shipped to controller VMs so the
controller sees the same config the client did.
"""
import copy
import os
import threading
from typing import Any, Dict, Iterable, Optional, Tuple

import yaml
from skypilot_tpu.utils import env

CONFIG_PATH = '~/.skypilot_tpu/config.yaml'
ENV_VAR_CONFIG_PATH = 'SKYT_CONFIG'

_config: Optional[Dict[str, Any]] = None
_config_path_loaded: Optional[str] = None
_lock = threading.Lock()


def _config_path() -> str:
    return os.path.expanduser(
        env.get(ENV_VAR_CONFIG_PATH, CONFIG_PATH))


def _try_load_config() -> Dict[str, Any]:
    global _config, _config_path_loaded
    path = _config_path()
    with _lock:
        if _config is not None and _config_path_loaded == path:
            return _config
        _config = {}
        _config_path_loaded = path
        if os.path.exists(path):
            with open(path, 'r', encoding='utf-8') as f:
                loaded = yaml.safe_load(f)
            if isinstance(loaded, dict):
                _config = loaded
        return _config


def reload_for_testing() -> None:
    global _config
    with _lock:
        _config = None


def loaded() -> bool:
    return bool(_try_load_config())


def get_nested(keys: Iterable[str], default_value: Any = None) -> Any:
    """config.get_nested(('gcp', 'project_id')) → value or default.

    Containers are deep-copied: callers must not be able to mutate the
    process-wide cached config through the return value.
    """
    cur: Any = _try_load_config()
    for key in keys:
        if not isinstance(cur, dict) or key not in cur:
            return default_value
        cur = cur[key]
    return copy.deepcopy(cur) if isinstance(cur, (dict, list)) else cur


def set_nested(keys: Tuple[str, ...], value: Any) -> Dict[str, Any]:
    """Return a copy of the config dict with keys set (does NOT persist —
    reference semantics: used to prepare controller config snapshots)."""
    cfg = copy.deepcopy(_try_load_config())
    cur = cfg
    for key in keys[:-1]:
        cur = cur.setdefault(key, {})
    cur[keys[-1]] = value
    return cfg


def to_dict() -> Dict[str, Any]:
    return copy.deepcopy(_try_load_config())
