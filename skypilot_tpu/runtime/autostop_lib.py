"""Autostop: stop/tear down an idle cluster from the inside.

Reference: sky/skylet/autostop_lib.py + AutostopEvent
(sky/skylet/events.py:90-260). The head agent checks every EVENT_INTERVAL:
if an autostop is configured and no job has been active for `idle_minutes`,
it invokes the cluster's own provision module to stop (or `down`) itself.
TPU-specific: multi-host pod slices cannot be stopped, only deleted — the
provisioner raises NotSupportedError and the event falls back to down if
the user asked for `down`, else logs and leaves the cluster up (the same
guard the reference applies at sky/clouds/gcp.py:184-190).
"""
import time

from skypilot_tpu.runtime import job_lib
from skypilot_tpu.utils import log_utils

logger = log_utils.init_logger(__name__)


def get_autostop_config() -> tuple:
    """(idle_minutes, down). idle_minutes < 0 means disabled."""
    idle = int(job_lib.get_kv('autostop_idle_minutes') or -1)
    down = (job_lib.get_kv('autostop_down') or '0') == '1'
    return idle, down


def set_autostop_config(idle_minutes: int, down: bool) -> None:
    job_lib.set_kv('autostop_idle_minutes', str(int(idle_minutes)))
    job_lib.set_kv('autostop_down', '1' if down else '0')


def autostop_event(config) -> None:
    """One tick of the autostop check (head agent only)."""
    idle_minutes, down = get_autostop_config()
    if idle_minutes < 0:
        return
    if not job_lib.is_cluster_idle():
        return
    idle_s = time.time() - job_lib.last_activity_time()
    if idle_s < idle_minutes * 60:
        return
    logger.info('cluster idle for %.0fs (>= %d min): autostop (down=%s)',
                idle_s, idle_minutes, down)
    # Mark so a concurrent status refresh can tell "stopping" from crashed.
    job_lib.set_kv('autostopping', '1')
    try:
        from skypilot_tpu import provision
        if down:
            provision.terminate_instances(config.cloud, config.cluster_name,
                                          config.provider_config,
                                          from_inside=True)
        else:
            provision.stop_instances(config.cloud, config.cluster_name,
                                     config.provider_config,
                                     from_inside=True)
    except Exception:  # pylint: disable=broad-except
        logger.exception('autostop failed')
        job_lib.set_kv('autostopping', '0')
