"""Per-cluster job queue: sqlite job table + FIFO scheduler + gang state.

Mirrors the reference's sky/skylet/job_lib.py (JobStatus :86, FIFOScheduler
:199, add_job :273, update_job_status :512, is_cluster_idle :641) with one
structural change: the reference tracks only per-job status because Ray owns
the per-node fan-out; here the head agent owns the gang, so the job table
carries a companion `gang` table with one row per (job, rank) that workers
update as they start/finish.

Lives on the HEAD host under $SKYT_AGENT_HOME/.skyt/jobs.db. All writes go
through this module; worker hosts never touch the DB (they talk HTTP to the
head agent — runtime/server.py).
"""
import enum
import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import sqlite_utils
from skypilot_tpu.utils import env


def agent_home() -> str:
    return os.path.expanduser(env.get('SKYT_AGENT_HOME', '~'))


def skyt_dir() -> str:
    d = os.path.join(agent_home(), '.skyt')
    os.makedirs(d, exist_ok=True)
    return d


def log_dir_for_job(job_id: int) -> str:
    return os.path.join(skyt_dir(), 'logs', str(job_id))


# Cooperative-preemption exit code (EX_TEMPFAIL): a workload that
# caught SIGTERM, checkpointed at a step boundary, and wants to be
# RESCHEDULED exits with this (train/checkpoint.PreemptionGuard). The
# head agent maps it to JobStatus.PREEMPTED instead of FAILED, and the
# managed-jobs controller recovers (resume from the checkpoint) rather
# than declaring user failure.
EXIT_CODE_PREEMPTED = 75


class JobStatus(enum.Enum):
    """Reference: sky/skylet/job_lib.py:86 (same lifecycle, plus
    PREEMPTED for cooperative-preemption exits — see
    EXIT_CODE_PREEMPTED — and HUNG for gang-watchdog hang verdicts,
    which the managed-jobs controller recovers like a preemption)."""
    INIT = 'INIT'
    PENDING = 'PENDING'
    SETTING_UP = 'SETTING_UP'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    CANCELLED = 'CANCELLED'
    PREEMPTED = 'PREEMPTED'
    # Gang watchdog verdict (train/watchdog.py): a rank stopped making
    # step progress while the process stayed alive — the failure mode
    # exit codes can never surface. Terminal: the gang is killed and
    # the managed-jobs controller resumes from the last checkpoint.
    HUNG = 'HUNG'

    def is_terminal(self) -> bool:
        return self in _TERMINAL

    @classmethod
    def nonterminal_statuses(cls) -> List['JobStatus']:
        return [s for s in cls if not s.is_terminal()]


_TERMINAL = {JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.FAILED_SETUP,
             JobStatus.CANCELLED, JobStatus.PREEMPTED, JobStatus.HUNG}

_DB_LOCK = threading.RLock()
_DB: Optional[sqlite3.Connection] = None
_DB_HOME: Optional[str] = None


def _get_db() -> sqlite3.Connection:
    global _DB, _DB_HOME
    with _DB_LOCK:
        home = skyt_dir()
        if _DB is None or _DB_HOME != home:
            if _DB is not None:
                _DB.close()
            _DB = sqlite_utils.connect(os.path.join(home, 'jobs.db'))
            _DB.executescript("""
            CREATE TABLE IF NOT EXISTS jobs (
                job_id INTEGER PRIMARY KEY AUTOINCREMENT,
                name TEXT,
                username TEXT,
                submitted_at REAL,
                start_at REAL,
                end_at REAL,
                status TEXT,
                spec TEXT,            -- JSON JobSpec
                pid INTEGER DEFAULT -1);
            CREATE TABLE IF NOT EXISTS gang (
                job_id INTEGER,
                rank INTEGER,
                status TEXT,          -- PENDING/RUNNING/DONE
                returncode INTEGER,
                updated_at REAL,
                PRIMARY KEY (job_id, rank));
            CREATE TABLE IF NOT EXISTS kv (
                key TEXT PRIMARY KEY, value TEXT);
            """)
            _DB.commit()
            _DB_HOME = home
        return _DB


def reset_db_for_testing() -> None:
    global _DB, _DB_HOME
    with _DB_LOCK:
        if _DB is not None:
            _DB.close()
        _DB = None
        _DB_HOME = None


# ------------------------------------------------------------------ job CRUD
def add_job(name: Optional[str], spec: Dict[str, Any],
            username: str = '') -> int:
    """Insert a job in INIT and return its id (reference: job_lib.py:273)."""
    db = _get_db()
    with _DB_LOCK:
        cur = db.execute(
            'INSERT INTO jobs (name, username, submitted_at, status, spec) '
            'VALUES (?, ?, ?, ?, ?)',
            (name, username, time.time(), JobStatus.INIT.value,
             json.dumps(spec)))
        db.commit()
        job_id = cur.lastrowid
    num_nodes = int(spec.get('num_nodes', 1))
    with _DB_LOCK:
        for rank in range(num_nodes):
            db.execute(
                'INSERT OR REPLACE INTO gang '
                '(job_id, rank, status, returncode, updated_at) '
                'VALUES (?, ?, ?, NULL, ?)',
                (job_id, rank, 'PENDING', time.time()))
        db.execute('UPDATE jobs SET status=? WHERE job_id=?',
                   (JobStatus.PENDING.value, job_id))
        db.commit()
    return job_id


def set_status(job_id: int, status: JobStatus) -> None:
    db = _get_db()
    now = time.time()
    with _DB_LOCK:
        if status == JobStatus.RUNNING:
            db.execute(
                'UPDATE jobs SET status=?, start_at=COALESCE(start_at, ?) '
                'WHERE job_id=?', (status.value, now, job_id))
        elif status.is_terminal():
            db.execute(
                'UPDATE jobs SET status=?, end_at=COALESCE(end_at, ?) '
                'WHERE job_id=?', (status.value, now, job_id))
        else:
            db.execute('UPDATE jobs SET status=? WHERE job_id=?',
                       (status.value, job_id))
        db.commit()


def get_job(job_id: int) -> Optional[Dict[str, Any]]:
    db = _get_db()
    row = db.execute('SELECT * FROM jobs WHERE job_id=?',
                     (job_id,)).fetchone()
    return _row_to_job(row) if row else None


def get_latest_job_id() -> Optional[int]:
    db = _get_db()
    row = db.execute(
        'SELECT job_id FROM jobs ORDER BY job_id DESC LIMIT 1').fetchone()
    return row['job_id'] if row else None


def get_jobs(statuses: Optional[List[JobStatus]] = None) -> List[Dict[str,
                                                                      Any]]:
    db = _get_db()
    if statuses:
        marks = ','.join('?' * len(statuses))
        rows = db.execute(
            f'SELECT * FROM jobs WHERE status IN ({marks}) '
            'ORDER BY job_id DESC', [s.value for s in statuses]).fetchall()
    else:
        rows = db.execute(
            'SELECT * FROM jobs ORDER BY job_id DESC').fetchall()
    return [_row_to_job(r) for r in rows]


def _row_to_job(row: sqlite3.Row) -> Dict[str, Any]:
    return {
        'job_id': row['job_id'],
        'name': row['name'],
        'username': row['username'],
        'submitted_at': row['submitted_at'],
        'start_at': row['start_at'],
        'end_at': row['end_at'],
        'status': JobStatus(row['status']),
        'spec': json.loads(row['spec']) if row['spec'] else {},
        'pid': row['pid'],
    }


def set_job_started(job_id: int) -> None:
    set_status(job_id, JobStatus.RUNNING)


def is_cluster_idle(threshold_statuses=(JobStatus.INIT, JobStatus.PENDING,
                                        JobStatus.SETTING_UP,
                                        JobStatus.RUNNING)) -> bool:
    """No nonterminal jobs (reference: job_lib.py:641)."""
    db = _get_db()
    marks = ','.join('?' * len(threshold_statuses))
    row = db.execute(
        f'SELECT COUNT(*) AS n FROM jobs WHERE status IN ({marks})',
        [s.value for s in threshold_statuses]).fetchone()
    return row['n'] == 0


def last_activity_time() -> float:
    """Most recent job end/submit time; agent start if no jobs ever."""
    db = _get_db()
    row = db.execute('SELECT MAX(COALESCE(end_at, submitted_at)) AS t '
                     'FROM jobs').fetchone()
    if row['t'] is not None:
        return row['t']
    return float(get_kv('agent_start_time') or time.time())


# ----------------------------------------------------------------- gang state
def gang_records(job_id: int) -> List[Dict[str, Any]]:
    db = _get_db()
    rows = db.execute(
        'SELECT * FROM gang WHERE job_id=? ORDER BY rank',
        (job_id,)).fetchall()
    return [dict(r) for r in rows]


def gang_mark(job_id: int, rank: int, status: str,
              returncode: Optional[int] = None) -> None:
    db = _get_db()
    with _DB_LOCK:
        db.execute(
            'UPDATE gang SET status=?, returncode=?, updated_at=? '
            'WHERE job_id=? AND rank=?',
            (status, returncode, time.time(), job_id, rank))
        db.commit()


def gang_all_done(job_id: int) -> bool:
    return all(r['status'] == 'DONE' for r in gang_records(job_id))


def gang_any_failed(job_id: int) -> bool:
    """True if any rank exited with a REAL failure code — cooperative
    preemption exits (EXIT_CODE_PREEMPTED) are not failures."""
    return any(r['status'] == 'DONE' and
               (r['returncode'] or 0) not in (0, EXIT_CODE_PREEMPTED)
               for r in gang_records(job_id))


def gang_any_preempted(job_id: int) -> bool:
    return any(r['status'] == 'DONE' and
               (r['returncode'] or 0) == EXIT_CODE_PREEMPTED
               for r in gang_records(job_id))


def postmortem_trailer_lines(job_wire: Dict[str, Any]) -> List[str]:
    """Log-surface trailer for a finished job: the gang watchdog
    verdict (HUNG only) plus every rank's postmortem bundle paths
    (docs/observability.md "Training plane"). ONE formatter shared by
    both tail surfaces — the on-host rpc `tail` and the client
    backend's HTTP tail — so the two can't drift."""
    lines: List[str] = []
    if job_wire.get('status') == JobStatus.HUNG.value and \
            job_wire.get('watchdog'):
        lines.append(f'### gang watchdog verdict: '
                     f'{json.dumps(job_wire["watchdog"])} ###')
    bundles = job_wire.get('postmortems') or {}

    def _rank_key(r):
        try:
            return (0, int(r), '')
        except (TypeError, ValueError):
            return (1, 0, str(r))

    if any(bundles.values()):
        lines.append('### postmortem bundles:')
        for rank in sorted(bundles, key=_rank_key):
            for path in bundles[rank]:
                lines.append(f'###   rank {rank}: {path}')
    return lines


# ------------------------------------------------------------------ scheduler
class FIFOScheduler:
    """Pick the next runnable job (reference: job_lib.py:199 FIFOScheduler).

    TPU slices are exclusive: one accelerator job runs at a time. Jobs that
    request no accelerators may run concurrently (bounded).
    """

    MAX_CONCURRENT_CPU_JOBS = 8

    def schedule_step(self) -> Optional[int]:
        """Return a PENDING job_id to start now, or None."""
        active = get_jobs([JobStatus.SETTING_UP, JobStatus.RUNNING])
        acc_busy = any(j['spec'].get('accelerators') for j in active)
        pending = get_jobs([JobStatus.PENDING])
        if not pending:
            return None
        for job in reversed(pending):  # oldest first
            wants_acc = bool(job['spec'].get('accelerators'))
            if wants_acc:
                if not active:  # gang jobs also wait for CPU jobs to drain
                    return job['job_id']
            else:
                if not acc_busy and len(active) < \
                        self.MAX_CONCURRENT_CPU_JOBS:
                    return job['job_id']
        return None


# ------------------------------------------------------------------------ kv
def set_kv(key: str, value: str) -> None:
    db = _get_db()
    with _DB_LOCK:
        db.execute('INSERT INTO kv (key, value) VALUES (?, ?) '
                   'ON CONFLICT(key) DO UPDATE SET value=excluded.value',
                   (key, value))
        db.commit()


def get_kv(key: str) -> Optional[str]:
    db = _get_db()
    row = db.execute('SELECT value FROM kv WHERE key=?', (key,)).fetchone()
    return row['value'] if row else None
