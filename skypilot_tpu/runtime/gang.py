"""Gang execution env contract: what every rank's job process sees.

Replaces the reference's RayCodeGen env export (SKYPILOT_NODE_IPS/
NUM_NODES/NODE_RANK/NUM_GPUS_PER_NODE, sky/backends/cloud_vm_ray_backend.py
:569-630 and sky/skylet/constants.py:263-266) with a TPU-first contract:
the JAX coordinator triplet (JAX_COORDINATOR_ADDRESS/NUM_PROCESSES/
PROCESS_ID) is exported on every rank, and `initialize_jax_distributed()`
below turns it into a jax.distributed runtime on any cluster this
framework launches, CPU or TPU. (jax's own argless initialize only
auto-detects Slurm/OpenMPI/TPU-metadata environments — it does NOT read
a generic env triplet, so gang jobs go through the helper.) SKYPILOT_*
aliases are kept so reference recipes run unmodified.
"""
import os
import time
from typing import Any, Dict, List, Optional
from skypilot_tpu.utils import env

DEFAULT_COORDINATOR_PORT = 8476


def make_task_id(job_id: int, cluster_name: str, task_name: str) -> str:
    """Reference: SKYPILOT_TASK_ID (sky/skylet/constants.py:63) format:
    sky-<timestamp>-<cluster>-<job>."""
    ts = time.strftime('%Y-%m-%d-%H-%M-%S')
    return f'skyt-{ts}_{cluster_name}_{task_name or "task"}-{job_id}'


def job_env_vars(
    *,
    job_id: int,
    rank: int,
    ips: List[str],
    cluster_name: str,
    task_name: Optional[str] = None,
    accelerators_per_node: int = 0,
    coordinator_port: int = DEFAULT_COORDINATOR_PORT,
    user_envs: Optional[Dict[str, str]] = None,
    export_jax_coordinator: Optional[bool] = None,
    num_slices: int = 1,
) -> Dict[str, str]:
    """Build the full env for one rank of a gang job.

    num_slices > 1: hosts are split into contiguous per-slice groups
    (rank order) and each rank additionally gets the MEGASCALE_* DCN
    contract (multislice_env_vars)."""
    num_nodes = len(ips)
    head_ip = ips[0]
    coord = f'{head_ip}:{coordinator_port}'
    env: Dict[str, str] = {}
    # User envs first: the runtime contract below must win conflicts.
    env.update({k: str(v) for k, v in (user_envs or {}).items()})
    env.update({
        'SKYT_NUM_NODES': str(num_nodes),
        'SKYT_NODE_RANK': str(rank),
        'SKYT_NODE_IPS': '\n'.join(ips),
        'SKYT_NUM_ACCELERATORS_PER_NODE': str(accelerators_per_node),
        'SKYT_COORDINATOR_ADDRESS': coord,
        'SKYT_TASK_ID': make_task_id(job_id, cluster_name, task_name),
        'SKYT_CLUSTER_NAME': cluster_name,
        'SKYT_JOB_ID': str(job_id),
        # Reference-compatible aliases (sky/skylet/constants.py:263-266):
        # lets the reference's distributed recipes (torch DDP, DeepSpeed
        # hostfiles) run unmodified on this framework.
        'SKYPILOT_NUM_NODES': str(num_nodes),
        'SKYPILOT_NODE_RANK': str(rank),
        'SKYPILOT_NODE_IPS': '\n'.join(ips),
        'SKYPILOT_NUM_GPUS_PER_NODE': str(accelerators_per_node),
        'SKYPILOT_TASK_ID': make_task_id(job_id, cluster_name, task_name),
    })
    if export_jax_coordinator is None:
        export_jax_coordinator = num_nodes > 1
    if export_jax_coordinator:
        # Consumed by initialize_jax_distributed() below. On single-host
        # jobs they are omitted so plain single-process JAX works
        # untouched.
        env.update({
            'JAX_COORDINATOR_ADDRESS': coord,
            'JAX_NUM_PROCESSES': str(num_nodes),
            'JAX_PROCESS_ID': str(rank),
        })
    if num_slices > 1:
        if num_nodes % num_slices != 0:
            raise ValueError(
                f'num_slices={num_slices} must divide '
                f'num_nodes={num_nodes}')
        hosts_per_slice = num_nodes // num_slices
        env.update(multislice_env_vars(
            slice_id=rank // hosts_per_slice,
            num_slices=num_slices,
            coordinator_ip=head_ip))
    return env


def initialize_jax_distributed() -> None:
    """Join the jax.distributed runtime from the gang env contract.

    Prefers the explicit JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID triplet this framework exports on every multi-node
    gang rank (works on the local provider, CPU clusters, and TPU VMs
    alike); falls back to jax's own auto-detection (TPU metadata,
    Slurm, OpenMPI) when the triplet is absent. No-op on single-node
    jobs (the triplet is only exported for num_nodes > 1 and there is
    nothing to join).
    """
    import jax
    coord = os.environ.get('JAX_COORDINATOR_ADDRESS')
    n = os.environ.get('JAX_NUM_PROCESSES')
    pid = os.environ.get('JAX_PROCESS_ID')
    if coord and n is not None and pid is not None:
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=int(n),
                                   process_id=int(pid))
    elif env.get_int('SKYT_NUM_NODES', 1) > 1:
        jax.distributed.initialize()   # TPU-metadata/Slurm detection


DEFAULT_MEGASCALE_PORT = 8080


def multislice_env_vars(*, slice_id: int, num_slices: int,
                        coordinator_ip: str,
                        port: int = DEFAULT_MEGASCALE_PORT
                        ) -> Dict[str, str]:
    """Megascale env for one host of a multi-slice deployment.

    These are the inter-slice (DCN) analog of the JAX coordinator
    triplet: the TPU runtime reads MEGASCALE_* to bring up the
    inter-slice transport, after which XLA collectives whose mesh axes
    cross slices (parallel/mesh.py build_hybrid_mesh dcn axes) ride DCN
    transparently. Reference's equivalent layer is NCCL-over-Ethernet
    env wiring (examples/nccl_test.yaml); SURVEY.md §5.
    """
    return {
        'MEGASCALE_COORDINATOR_ADDRESS': f'{coordinator_ip}:{port}',
        'MEGASCALE_NUM_SLICES': str(num_slices),
        'MEGASCALE_SLICE_ID': str(slice_id),
        'MEGASCALE_PORT': str(port),
    }


def spec_env_for_rank(spec: Dict[str, Any], rank: int,
                      cluster_name: str) -> Dict[str, str]:
    """Env for one rank from a job spec dict (runtime/server.py wire form)."""
    return job_env_vars(
        job_id=spec['job_id'],
        rank=rank,
        ips=spec['ips'],
        cluster_name=cluster_name,
        task_name=spec.get('name'),
        accelerators_per_node=spec.get('accelerators_per_node', 0),
        coordinator_port=spec.get('coordinator_port',
                                  DEFAULT_COORDINATOR_PORT),
        user_envs=spec.get('envs'),
        num_slices=spec.get('num_slices', 1),
    )
