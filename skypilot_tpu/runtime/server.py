"""Head-agent coordination server: gang dispatch without Ray.

The reference gang-schedules via a generated Ray driver (placement group
STRICT_SPREAD + per-node ray tasks, sky/backends/cloud_vm_ray_backend.py:361,
SURVEY.md §3.5). A TPU pod slice is already gang-allocated, so this is a
~10x simpler pull model: the head agent owns the job queue (runtime/job_lib)
and serves directives over HTTP on the slice-internal network; every host's
worker loop (runtime/agent.py) polls `/work?rank=r`, executes, and reports.

Endpoints (JSON):
  GET  /health                  liveness + cluster identity
  POST /jobs/submit             {spec} -> {job_id}
  GET  /jobs                    [?status=...] -> [job]
  GET  /jobs/<id>               job + gang records + watchdog verdict
  POST /jobs/<id>/cancel        cancel (kill directives fan out via /work)
  GET  /work?rank=r             [{action: run|kill, job_id, spec?, env?}]
  POST /report                  {job_id, rank, event, returncode}
  POST /heartbeat               {job_id, rank, record, postmortems?}
                                (agent relay -> gang watchdog)
  POST /autostop                {idle_minutes, down}
  GET  /autostop                current autostop config
"""
import json
import os
import threading
import time
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu.runtime import gang as gang_lib
from skypilot_tpu.runtime import job_lib
from skypilot_tpu.utils import log_utils

logger = log_utils.init_logger(__name__)

DEFAULT_AGENT_PORT = 46580


class ClusterConfig:
    """Static cluster identity, written by the provisioner to
    $SKYT_AGENT_HOME/.skyt/agent.json on every host."""

    def __init__(self, cfg: Dict[str, Any]) -> None:
        self.cluster_name: str = cfg['cluster_name']
        self.num_nodes: int = int(cfg['num_nodes'])
        self.rank: int = int(cfg.get('rank', 0))
        self.ips: List[str] = list(cfg['ips'])
        self.head_ip: str = cfg.get('head_ip', self.ips[0])
        self.head_port: int = int(cfg.get('head_port', DEFAULT_AGENT_PORT))
        self.coordinator_port: int = int(
            cfg.get('coordinator_port', gang_lib.DEFAULT_COORDINATOR_PORT))
        self.accelerators_per_node: int = int(
            cfg.get('accelerators_per_node', 0))
        self.cloud: str = cfg.get('cloud', 'local')
        self.provider_config: Dict[str, Any] = cfg.get('provider_config', {})
        self.raw = dict(cfg)

    @classmethod
    def load(cls, path: str) -> 'ClusterConfig':
        with open(path, 'r', encoding='utf-8') as f:
            return cls(json.load(f))


class HeadState:
    """Gang bookkeeping + scheduling, shared by server handlers and the
    agent's scheduler loop. All mutations funnel through job_lib (sqlite)."""

    def __init__(self, config: ClusterConfig,
                 clock: Callable[[], float] = time.time) -> None:
        self.config = config
        self.scheduler = job_lib.FIFOScheduler()
        self.lock = threading.RLock()
        self._clock = clock
        # Training-plane watchdog state (train/watchdog.py): relayed
        # heartbeats, one GangWatchdog per active gang job, the last
        # verdict per job, and every bundle path ranks reported —
        # all in-memory (a head-agent restart simply re-learns from
        # the next relay round).
        self.watchdogs: Dict[int, Any] = {}
        self.verdicts: Dict[int, Dict[str, Any]] = {}
        self.postmortems: Dict[int, Dict[int, List[str]]] = {}

    # ------------------------------------------------------------- submit
    def submit(self, spec: Dict[str, Any]) -> int:
        spec = dict(spec)
        spec.setdefault('num_nodes', self.config.num_nodes
                        if spec.get('gang', True) else 1)
        # A job can use fewer nodes than the cluster has, never more.
        spec['num_nodes'] = min(int(spec['num_nodes']),
                                self.config.num_nodes)
        job_id = job_lib.add_job(spec.get('name'), spec,
                                 spec.get('username', ''))
        logger.info('submitted job %d (%s)', job_id, spec.get('name'))
        return job_id

    # ---------------------------------------------------------- scheduling
    def schedule_step(self) -> None:
        with self.lock:
            job_id = self.scheduler.schedule_step()
            if job_id is not None:
                job_lib.set_status(job_id, job_lib.JobStatus.SETTING_UP)
                logger.info('dispatching job %d', job_id)

    # ------------------------------------------------------------ directives
    def work_for_rank(self, rank: int) -> List[Dict[str, Any]]:
        directives = []
        active = job_lib.get_jobs([job_lib.JobStatus.SETTING_UP,
                                   job_lib.JobStatus.RUNNING])
        for job in active:
            recs = {r['rank']: r for r in job_lib.gang_records(
                job['job_id'])}
            rec = recs.get(rank)
            if rec is None:
                continue
            if rec['status'] == 'PENDING':
                job_lib.gang_mark(job['job_id'], rank, 'DISPATCHED')
                directives.append(self._run_directive(job, rank))
        # Kill directives: job reached a terminal state but this rank's
        # process may still be running (failure elsewhere / cancellation
        # / a watchdog HUNG verdict — the hung rank by definition never
        # exits on its own).
        terminal = job_lib.get_jobs([job_lib.JobStatus.CANCELLED,
                                     job_lib.JobStatus.FAILED,
                                     job_lib.JobStatus.FAILED_SETUP,
                                     job_lib.JobStatus.HUNG])
        for job in terminal:
            for rec in job_lib.gang_records(job['job_id']):
                if rec['rank'] == rank and rec['status'] in ('DISPATCHED',
                                                             'SETUP',
                                                             'RUNNING'):
                    directives.append({'action': 'kill',
                                       'job_id': job['job_id']})
        return directives

    def _run_directive(self, job: Dict[str, Any],
                       rank: int) -> Dict[str, Any]:
        spec = dict(job['spec'])
        num_nodes = int(spec.get('num_nodes', self.config.num_nodes))
        spec['job_id'] = job['job_id']
        spec['ips'] = self.config.ips[:num_nodes]
        spec['coordinator_port'] = self.config.coordinator_port
        spec.setdefault('accelerators_per_node',
                        self.config.accelerators_per_node)
        env = gang_lib.spec_env_for_rank(spec, rank,
                                         self.config.cluster_name)
        return {'action': 'run', 'job_id': job['job_id'], 'spec': spec,
                'env': env}

    # ------------------------------------------------------------ watchdog
    def record_heartbeat(self, job_id: int, rank: int,
                         record: Dict[str, Any],
                         postmortems: Optional[List[str]] = None) -> None:
        """Ingest one relayed rank heartbeat (+ any bundle paths the
        rank's host has seen). Lazily creates the job's GangWatchdog
        sized to its gang."""
        from skypilot_tpu.train import watchdog as watchdog_lib
        with self.lock:
            wd = self.watchdogs.get(job_id)
            if wd is None:
                n = len(job_lib.gang_records(job_id)) or \
                    self.config.num_nodes
                wd = watchdog_lib.GangWatchdog(n, clock=self._clock,
                                               job=str(job_id))
                self.watchdogs[job_id] = wd
            if postmortems:
                per_job = self.postmortems.setdefault(job_id, {})
                known = per_job.setdefault(int(rank), [])
                for p in postmortems:
                    if p not in known:
                        known.append(p)
        if isinstance(record, dict):
            wd.observe(int(rank), record)

    def watchdog_tick(self) -> None:
        """One watchdog pass over active gang jobs: evaluate each
        job's verdict, escalate a CONFIRMED hang to the terminal HUNG
        status (kill directives then fan out via /work and the
        managed-jobs controller recovers from the checkpoint), and
        drop state for jobs that finished."""
        active = {j['job_id']: j for j in job_lib.get_jobs(
            job_lib.JobStatus.nonterminal_statuses())}
        with self.lock:
            items = list(self.watchdogs.items())
        for job_id, wd in items:
            job = active.get(job_id)
            if job is None:
                # Keep the final verdict (the job wire serves it);
                # retire the evaluator and its gauge series.
                with self.lock:
                    retired = self.watchdogs.pop(job_id, None)
                if retired is not None:
                    retired.retire()
                continue
            verdict = wd.evaluate()
            with self.lock:
                self.verdicts[job_id] = verdict.to_wire()
            if verdict.state == 'hang' and verdict.confirmed and \
                    job['status'] is job_lib.JobStatus.RUNNING:
                logger.error(
                    'gang watchdog: job %d confirmed HUNG (%s); '
                    'killing the gang for checkpoint-resume recovery',
                    job_id, verdict.detail.get('stalled_ranks'))
                job_lib.set_status(job_id, job_lib.JobStatus.HUNG)

    def job_observability(self, job_id: int) -> Dict[str, Any]:
        """Watchdog verdict + heartbeats + postmortem bundle paths for
        the job wire (GET /jobs/<id>) — what `skyt logs` and the
        dashboard surface next to a dead gang."""
        with self.lock:
            wd = self.watchdogs.get(job_id)
            out: Dict[str, Any] = {
                'watchdog': self.verdicts.get(job_id),
                'postmortems': {
                    str(r): list(paths) for r, paths in
                    self.postmortems.get(job_id, {}).items()},
            }
        if wd is not None:
            out['heartbeats'] = {str(r): rec for r, rec in
                                 wd.records().items()}
        return out

    # -------------------------------------------------------------- reports
    def report(self, job_id: int, rank: int, event: str,
               returncode: Optional[int] = None) -> None:
        job = job_lib.get_job(job_id)
        if job is None:
            return
        status = job['status']
        if event == 'setup_started':
            job_lib.gang_mark(job_id, rank, 'SETUP')
        elif event == 'setup_failed':
            job_lib.gang_mark(job_id, rank, 'DONE', returncode)
            if not status.is_terminal():
                job_lib.set_status(job_id, job_lib.JobStatus.FAILED_SETUP)
        elif event == 'run_started':
            job_lib.gang_mark(job_id, rank, 'RUNNING')
            if status == job_lib.JobStatus.SETTING_UP:
                job_lib.set_status(job_id, job_lib.JobStatus.RUNNING)
        elif event == 'done':
            job_lib.gang_mark(job_id, rank, 'DONE', returncode)
            rc = returncode or 0
            if rc == job_lib.EXIT_CODE_PREEMPTED:
                # Cooperative preemption: the workload checkpointed and
                # asked to be rescheduled — not a user failure. It WINS
                # over FAILED regardless of report ordering: when one
                # rank checkpoints and exits 75, its siblings'
                # collectives usually abort with real nonzero codes
                # (often arriving first) — that collateral must not
                # mask the recovery signal. A genuinely failing job
                # relaunches and fails again WITHOUT any 75, so it
                # still lands FAILED on the next attempt. HUNG also
                # stays: the watchdog's kill SIGTERMs the survivors,
                # whose cooperative 75s must not relabel the hang.
                if status not in (job_lib.JobStatus.SUCCEEDED,
                                  job_lib.JobStatus.CANCELLED,
                                  job_lib.JobStatus.HUNG):
                    job_lib.set_status(job_id,
                                       job_lib.JobStatus.PREEMPTED)
            elif rc != 0:
                if not status.is_terminal():
                    job_lib.set_status(job_id, job_lib.JobStatus.FAILED)
            elif job_lib.gang_all_done(job_id):
                if job_lib.gang_any_preempted(job_id):
                    if status not in (job_lib.JobStatus.SUCCEEDED,
                                      job_lib.JobStatus.CANCELLED,
                                      job_lib.JobStatus.HUNG):
                        job_lib.set_status(job_id,
                                           job_lib.JobStatus.PREEMPTED)
                elif job_lib.gang_any_failed(job_id):
                    if not status.is_terminal():
                        job_lib.set_status(job_id, job_lib.JobStatus.FAILED)
                elif not status.is_terminal():
                    job_lib.set_status(job_id, job_lib.JobStatus.SUCCEEDED)

    def cancel(self, job_id: int) -> bool:
        job = job_lib.get_job(job_id)
        if job is None or job['status'].is_terminal():
            return False
        job_lib.set_status(job_id, job_lib.JobStatus.CANCELLED)
        return True


def _json_bytes(obj: Any) -> bytes:
    return json.dumps(obj, default=str).encode()


class _Handler(BaseHTTPRequestHandler):
    state: HeadState = None  # set by make_server

    # Silence default per-request stderr logging.
    def log_message(self, fmt, *args):  # noqa: N802
        pass

    def _reply(self, obj: Any, code: int = 200) -> None:
        body = _json_bytes(obj)
        self.send_response(code)
        self.send_header('Content-Type', 'application/json')
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> Dict[str, Any]:
        length = int(self.headers.get('Content-Length', 0))
        if length == 0:
            return {}
        return json.loads(self.rfile.read(length))

    def do_GET(self):  # noqa: N802
        try:
            parsed = urllib.parse.urlparse(self.path)
            q = urllib.parse.parse_qs(parsed.query)
            parts = [p for p in parsed.path.split('/') if p]
            st = self.state
            if parsed.path == '/health':
                self._reply({'ok': True,
                             'cluster': st.config.cluster_name,
                             'num_nodes': st.config.num_nodes,
                             'time': time.time()})
            elif parsed.path == '/work':
                rank = int(q.get('rank', ['0'])[0])
                st.schedule_step()
                self._reply({'directives': st.work_for_rank(rank)})
            elif parts[:1] == ['jobs'] and len(parts) == 1:
                statuses = None
                if 'status' in q:
                    statuses = [job_lib.JobStatus(s) for s in q['status']]
                self._reply({'jobs': [_job_wire(j) for j in
                                      job_lib.get_jobs(statuses)]})
            elif parts[:1] == ['jobs'] and len(parts) == 2:
                job = job_lib.get_job(int(parts[1]))
                if job is None:
                    self._reply({'error': 'not found'}, 404)
                else:
                    wire = _job_wire(job)
                    wire['gang'] = job_lib.gang_records(job['job_id'])
                    wire.update(st.job_observability(job['job_id']))
                    self._reply(wire)
            elif parts[:1] == ['logs'] and len(parts) == 2:
                # Incremental log read: head host's rank-0 log for the job.
                # Client polls with ?offset=<bytes read so far>; replies
                # {data, offset, done}. Keeps log streaming transport-
                # agnostic (same path for local and SSH-reached clusters).
                job_id = int(parts[1])
                offset = int(q.get('offset', ['0'])[0])
                job = job_lib.get_job(job_id)
                if job is None:
                    self._reply({'error': 'not found'}, 404)
                else:
                    path = os.path.join(job_lib.log_dir_for_job(job_id),
                                        'rank-0.log')
                    data = ''
                    new_offset = offset
                    try:
                        with open(path, 'r', encoding='utf-8',
                                  errors='replace') as f:
                            f.seek(offset)
                            data = f.read()
                            new_offset = f.tell()
                    except OSError:
                        pass
                    self._reply({'data': data, 'offset': new_offset,
                                 'done': job['status'].is_terminal()})
            elif parsed.path == '/autostop':
                self._reply({
                    'idle_minutes': int(job_lib.get_kv('autostop_idle_minutes')
                                        or -1),
                    'down': (job_lib.get_kv('autostop_down') or '0') == '1',
                })
            else:
                self._reply({'error': 'unknown path'}, 404)
        except Exception as e:  # pylint: disable=broad-except
            traceback.print_exc()
            self._reply({'error': str(e)}, 500)

    def do_POST(self):  # noqa: N802
        try:
            parts = [p for p in self.path.split('?')[0].split('/') if p]
            st = self.state
            body = self._body()
            if parts == ['jobs', 'submit']:
                job_id = st.submit(body['spec'])
                st.schedule_step()
                self._reply({'job_id': job_id})
            elif len(parts) == 3 and parts[0] == 'jobs' and \
                    parts[2] == 'cancel':
                ok = st.cancel(int(parts[1]))
                self._reply({'cancelled': ok})
            elif parts == ['report']:
                st.report(body['job_id'], body['rank'], body['event'],
                          body.get('returncode'))
                self._reply({'ok': True})
            elif parts == ['heartbeat']:
                st.record_heartbeat(int(body['job_id']),
                                    int(body['rank']),
                                    body.get('record') or {},
                                    body.get('postmortems'))
                self._reply({'ok': True})
            elif parts == ['autostop']:
                job_lib.set_kv('autostop_idle_minutes',
                               str(int(body['idle_minutes'])))
                job_lib.set_kv('autostop_down',
                               '1' if body.get('down') else '0')
                self._reply({'ok': True})
            else:
                self._reply({'error': 'unknown path'}, 404)
        except Exception as e:  # pylint: disable=broad-except
            traceback.print_exc()
            self._reply({'error': str(e)}, 500)


def _job_wire(job: Dict[str, Any]) -> Dict[str, Any]:
    wire = dict(job)
    wire['status'] = job['status'].value
    return wire


def make_server(state: HeadState, port: int) -> ThreadingHTTPServer:
    handler = type('BoundHandler', (_Handler,), {'state': state})
    server = ThreadingHTTPServer(('0.0.0.0', port), handler)
    server.daemon_threads = True
    return server
