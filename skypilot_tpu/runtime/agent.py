"""Per-host agent daemon: the skylet analog.

Reference: sky/skylet/skylet.py (20s event loop) + the Ray worker processes.
One agent runs on every host of a cluster. The head (rank 0) additionally
runs the coordination HTTP server (runtime/server.py) and the autostop
event. Workers (all ranks, including the head's own worker thread) poll the
head for gang directives and execute jobs through runtime/log_lib.

Start (done by the provisioner over SSH / local runner):
    python -m skypilot_tpu.runtime.agent --config ~/.skyt/agent.json
The process daemonizes; its pid is written to ~/.skyt/agent.pid.
"""
import argparse
import os
import signal
import sys
import threading
import time
from typing import Any, Dict, Optional

import requests

from skypilot_tpu.runtime import autostop_lib
from skypilot_tpu.runtime import job_lib
from skypilot_tpu.runtime import log_lib
from skypilot_tpu.runtime import server as server_lib
from skypilot_tpu.utils import log_utils
from skypilot_tpu.utils import subprocess_utils
from skypilot_tpu.utils import env as env_lib

logger = log_utils.init_logger(__name__)

WORK_POLL_INTERVAL_S = 1.0
EVENT_INTERVAL_S = 20  # reference: sky/skylet/events.py:26


def _watchdog_interval_s() -> float:
    """Head-side gang-watchdog evaluation cadence (must be finer than
    the 20s event loop: a hang verdict's latency floor is this tick)."""
    return env_lib.get_float('SKYT_WATCHDOG_INTERVAL_S', 2.0)


def _heartbeat_path(job_id: int, rank: int) -> str:
    """Local heartbeat file for one (job, rank) — the same default the
    dispatch env exports, so the relay can find it without plumbing."""
    return os.path.join(job_lib.log_dir_for_job(job_id),
                        f'heartbeat-rank-{rank}.json')


def _postmortem_dir(job_id: int) -> str:
    return os.path.join(job_lib.log_dir_for_job(job_id), 'postmortems')


class RunningJob:
    def __init__(self, job_id: int, thread: threading.Thread) -> None:
        self.job_id = job_id
        self.thread = thread
        self.pid: Optional[int] = None
        self.killed = False
        # Resolved observability paths from the dispatch env (a task
        # env override wins over the defaults) — the heartbeat relay
        # reads these.
        self.hb_path: Optional[str] = None
        self.pm_dir: Optional[str] = None


class Worker:
    """Polls the head for directives; executes jobs locally."""

    def __init__(self, config: server_lib.ClusterConfig) -> None:
        self.config = config
        self.head_url = f'http://{config.head_ip}:{config.head_port}'
        self.running: Dict[int, RunningJob] = {}
        self._lock = threading.Lock()
        # job_id -> (last relayed heartbeat ts, bundle names already
        # relayed): the relay only POSTs on change, so an idle or
        # heartbeat-less job costs one stat() per poll, no HTTP.
        self._hb_relayed: Dict[int, list] = {}

    # ------------------------------------------------------------- HTTP
    def _get(self, path: str) -> Dict[str, Any]:
        resp = requests.get(self.head_url + path, timeout=10)
        resp.raise_for_status()
        return resp.json()

    def _post(self, path: str, payload: Dict[str, Any]) -> None:
        requests.post(self.head_url + path, json=payload,
                      timeout=10).raise_for_status()

    def _report(self, job_id: int, event: str,
                returncode: Optional[int] = None) -> None:
        try:
            self._post('/report', {'job_id': job_id,
                                   'rank': self.config.rank,
                                   'event': event,
                                   'returncode': returncode})
        except requests.RequestException as e:
            logger.warning('report %s for job %d failed: %s', event, job_id,
                           e)

    # ------------------------------------------------------------- loop
    def poll_once(self) -> None:
        data = self._get(f'/work?rank={self.config.rank}')
        for directive in data.get('directives', []):
            action = directive['action']
            job_id = directive['job_id']
            with self._lock:
                if action == 'run' and job_id not in self.running:
                    rj = RunningJob(job_id, None)
                    thread = threading.Thread(
                        target=self._execute, args=(directive, rj),
                        daemon=True, name=f'job-{job_id}')
                    rj.thread = thread
                    self.running[job_id] = rj
                    thread.start()
                elif action == 'kill':
                    rj = self.running.get(job_id)
                    if rj is not None and rj.pid and not rj.killed:
                        rj.killed = True
                        logger.info('killing job %d (pid %s)', job_id,
                                    rj.pid)
                        subprocess_utils.kill_process_tree(rj.pid)
        self._relay_heartbeats()

    def _relay_heartbeats(self) -> None:
        """Ship this host's rank heartbeat (and any new postmortem
        bundle paths) to the head's gang watchdog. Change-driven: the
        POST only happens when the heartbeat advanced or a bundle
        appeared, and a relay failure is just logged — the watchdog's
        job is to notice SILENCE, so the relay must never take the
        work loop down."""
        from skypilot_tpu.train import heartbeat as heartbeat_lib
        if not heartbeat_lib.enabled():
            return
        with self._lock:
            jobs = list(self.running.values())
        for rj in jobs:
            if rj.hb_path is None:
                continue
            rec = heartbeat_lib.read(rj.hb_path)
            bundles = []
            if rj.pm_dir is not None:
                try:
                    bundles = sorted(
                        os.path.join(rj.pm_dir, n)
                        for n in os.listdir(rj.pm_dir)
                        if n.startswith('postmortem-'))
                except OSError:
                    pass
            with self._lock:
                last = self._hb_relayed.get(rj.job_id)
            ts = (rec or {}).get('ts')
            if last is not None and last[0] == ts and \
                    set(bundles) <= set(last[1]):
                continue
            if rec is None and not bundles:
                continue
            try:
                self._post('/heartbeat',
                           {'job_id': rj.job_id,
                            'rank': self.config.rank,
                            'record': rec or {},
                            'postmortems': bundles})
                with self._lock:
                    self._hb_relayed[rj.job_id] = [ts, bundles]
            except requests.RequestException as e:
                logger.warning('heartbeat relay for job %d failed: %s',
                               rj.job_id, e)
        # Bounded: drop relay state for jobs no longer running here.
        # This method runs from the poll loop AND from finishing job
        # threads (the final relay), so the cleanup must be
        # lock-guarded and tolerate concurrent removal.
        live = {rj.job_id for rj in jobs}
        with self._lock:
            for jid in list(self._hb_relayed):
                if jid not in live:
                    self._hb_relayed.pop(jid, None)

    def run_forever(self) -> None:
        while True:
            try:
                self.poll_once()
            except requests.RequestException as e:
                logger.warning('head unreachable: %s', e)
            except Exception:  # pylint: disable=broad-except
                logger.exception('worker poll error')
            time.sleep(WORK_POLL_INTERVAL_S)

    # ---------------------------------------------------------- execution
    def _execute(self, directive: Dict[str, Any], rj: RunningJob) -> None:
        job_id = directive['job_id']
        spec = directive['spec']
        env = dict(directive['env'])
        rank = self.config.rank
        log_dir = job_lib.log_dir_for_job(job_id)
        os.makedirs(log_dir, exist_ok=True)
        run_log = os.path.join(log_dir, f'rank-{rank}.log')
        workdir = os.path.join(job_lib.agent_home(), 'skyt_workdir')
        if os.path.isdir(workdir):
            env.setdefault('SKYT_WORKDIR', workdir)
        if env.get('SKYT_PROFILE') not in (None, '', '0'):
            # jax.profiler traces land INSIDE the job's log dir, so the
            # existing sync-down path ships them (`skyt logs --profile`).
            env.setdefault('SKYT_PROFILE_DIR',
                           os.path.join(log_dir, 'profile', f'rank-{rank}'))
        # Training-plane observability contract (docs/observability.md
        # "Training plane"): the workload writes per-step heartbeats
        # here (this worker relays them to the head's gang watchdog)
        # and postmortem bundles next to the job logs. setdefault: a
        # task env override wins (e.g. a durable bundle dir).
        env.setdefault('SKYT_HEARTBEAT_FILE',
                       _heartbeat_path(job_id, rank))
        env.setdefault('SKYT_POSTMORTEM_DIR',
                       os.path.join(log_dir, 'postmortems'))
        rj.hb_path = env['SKYT_HEARTBEAT_FILE']
        rj.pm_dir = env['SKYT_POSTMORTEM_DIR']

        setup = spec.get('setup')
        if setup:
            self._report(job_id, 'setup_started')
            script = log_lib.make_task_bash_script(setup, env)
            rc, pid = self._run_tracked(script, run_log, rj)
            os.unlink(script)
            if rc != 0:
                self._report(job_id, 'setup_failed', rc)
                return

        run_cmd = spec.get('run') or 'true'
        self._report(job_id, 'run_started')
        script = log_lib.make_task_bash_script(run_cmd, env)
        docker = None
        if spec.get('docker_image'):
            from skypilot_tpu.utils import docker_utils
            docker = (spec['docker_image'],
                      docker_utils.container_name(
                          env.get('SKYT_CLUSTER_NAME', 'cluster'),
                          rank))
        rc, _ = self._run_tracked(script, run_log, rj, docker=docker)
        os.unlink(script)
        self._report(job_id, 'done', rc)
        # Final relay while the job is still in `running`: a bundle
        # dumped on the way out (preempt/crash) must reach the head
        # even though no further poll will see this job.
        self._relay_heartbeats()
        with self._lock:
            self.running.pop(job_id, None)

    def _run_tracked(self, script: str, log_path: str,
                     rj: RunningJob, docker=None) -> tuple:
        """run_with_log but exposing the child pid for kill directives.

        docker: optional (image, container_name) — the script then
        executes INSIDE the long-lived task container (brought up
        idempotently first; its stdout lands in the same job log). The
        script file is visible in the container via the /tmp mount and
        carries its own env exports, so the wrap is exactly
        `docker exec <name> bash <script>`."""
        import subprocess
        if docker is not None:
            from skypilot_tpu.utils import docker_utils
            image, name = docker
            argv = ['bash', '-c',
                    docker_utils.ensure_container_cmd(image, name)
                    + '\nexec '
                    + docker_utils.exec_script_cmd(name, script)]
        else:
            argv = ['bash', script]
        log_path = os.path.expanduser(log_path)
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        with open(log_path, 'a', encoding='utf-8') as log_file:
            proc = subprocess.Popen(argv,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT,
                                    start_new_session=True, text=True)
            rj.pid = proc.pid
            # Orphan reaper: if THIS agent dies (crash/SIGKILL) the job
            # session would outlive it holding chips; a stdlib-only
            # sibling watches both pids and kills the job's process
            # group when the agent disappears (reference:
            # sky/skylet/subprocess_daemon.py). Exits on its own when
            # the job finishes.
            reaper = subprocess.Popen(
                [sys.executable, '-m', 'skypilot_tpu.runtime.reaper',
                 '--parent-pid', str(os.getpid()),
                 '--target-pid', str(proc.pid)],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                start_new_session=True)
            # Reap finished reapers so they don't sit as zombies in this
            # long-running agent's process table.
            self._reapers = [r for r in getattr(self, '_reapers', [])
                             if r.poll() is None]
            self._reapers.append(reaper)
            assert proc.stdout is not None
            for line in proc.stdout:
                log_file.write(line)
                log_file.flush()
            proc.wait()
            return proc.returncode, proc.pid


class HeadLoop:
    """Head-only periodic events: scheduling tick + autostop.

    Reference: sky/skylet/events.py (AutostopEvent, JobSchedulerEvent).
    """

    def __init__(self, state: server_lib.HeadState) -> None:
        self.state = state
        self._last_autostop_check = 0.0

    def run_forever(self) -> None:
        while True:
            try:
                self.state.schedule_step()
                now = time.time()
                if now - self._last_autostop_check >= EVENT_INTERVAL_S:
                    self._last_autostop_check = now
                    autostop_lib.autostop_event(self.state.config)
            except Exception:  # pylint: disable=broad-except
                logger.exception('head loop error')
            time.sleep(EVENT_INTERVAL_S)


def write_pid_file() -> None:
    path = os.path.join(job_lib.skyt_dir(), 'agent.pid')
    with open(path, 'w', encoding='utf-8') as f:
        f.write(str(os.getpid()))


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--config', required=True,
                        help='path to agent.json')
    parser.add_argument('--foreground', action='store_true',
                        help='do not daemonize (tests)')
    args = parser.parse_args(argv)

    config = server_lib.ClusterConfig.load(os.path.expanduser(args.config))
    if not args.foreground:
        subprocess_utils.daemonize()
    write_pid_file()
    job_lib.set_kv('agent_start_time', str(time.time()))

    log_path = os.path.join(job_lib.skyt_dir(), 'agent.log')
    log_utils.add_file_handler(log_path)
    logger.info('agent starting: cluster=%s rank=%d',
                config.cluster_name, config.rank)

    is_head = config.rank == 0
    if is_head:
        state = server_lib.HeadState(config)
        httpd = server_lib.make_server(state, config.head_port)
        threading.Thread(target=httpd.serve_forever, daemon=True,
                         name='head-http').start()
        threading.Thread(target=HeadLoop(state).run_forever, daemon=True,
                         name='head-loop').start()
        from skypilot_tpu.train import heartbeat as heartbeat_lib
        if heartbeat_lib.enabled():
            # Gang watchdog on its own (finer) cadence: the 20s event
            # loop would put a 20s floor under hang detection.
            def _watchdog_loop() -> None:
                while True:
                    try:
                        state.watchdog_tick()
                    except Exception:  # pylint: disable=broad-except
                        logger.exception('watchdog tick failed')
                    time.sleep(_watchdog_interval_s())
            threading.Thread(target=_watchdog_loop, daemon=True,
                             name='gang-watchdog').start()

    worker = Worker(config)
    # Graceful shutdown for tests / teardown.
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    worker.run_forever()


if __name__ == '__main__':
    main()
