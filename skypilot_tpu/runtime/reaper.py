"""Orphan reaper: kills a job's process group when its agent dies.

The agent runs every job in its own session (start_new_session=True), so
an agent crash/SIGKILL would orphan the job tree and leave TPU chips
held by a process nobody tracks. The agent therefore spawns one reaper
per tracked job; the reaper watches BOTH pids and:

  * exits quietly when the job finishes (normal case);
  * SIGTERMs, then after a grace period SIGKILLs, the job's process
    group when the agent disappears (orphan case).

Reference analog: sky/skylet/subprocess_daemon.py (psutil-based parent
wait). This one is stdlib-only (os.kill(pid, 0) liveness probes) so it
runs in any environment the agent itself runs in.
"""
import argparse
import os
import signal
import sys
import time
from typing import Optional

POLL_INTERVAL_S = 1.0
TERM_GRACE_S = 5.0


def pid_alive(pid: int) -> bool:
    """Liveness probe shared with the serve control plane: the
    controller's restart adoption (serve/replica_managers.py) uses the
    same check to tell an adoptable replica from a dead-pid orphan."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        pass
    # A SIGKILLed agent whose parent hasn't waited on it yet is a zombie:
    # kill(pid, 0) still succeeds, but the agent is gone and its jobs are
    # orphans — treat Z as dead. (comm can contain spaces/parens, so
    # split at the LAST ')'.)
    try:
        with open(f'/proc/{pid}/stat', 'r', encoding='utf-8') as f:
            return f.read().rsplit(')', 1)[1].split()[0] != 'Z'
    except OSError:
        return True


_alive = pid_alive


def pid_start_token(pid: int) -> Optional[int]:
    """Opaque identity token for a pid: the kernel's starttime field
    (jiffies since boot, /proc/<pid>/stat field 22). A recorded
    (pid, token) pair still matching means it is the SAME process, not
    a reused pid — the guard the serve controller needs before
    adopting a replica row that survived its own crash."""
    try:
        with open(f'/proc/{pid}/stat', 'r', encoding='utf-8') as f:
            return int(f.read().rsplit(')', 1)[1].split()[19])
    except (OSError, ValueError, IndexError):
        return None


def _group_alive(pgid: int) -> bool:
    try:
        os.killpg(pgid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _kill_group(pgid: int, sig: int) -> None:
    try:
        os.killpg(pgid, sig)
    except (ProcessLookupError, PermissionError):
        pass


def reap(parent_pid: int, target_pid: int,
         poll_interval: float = POLL_INTERVAL_S,
         term_grace: float = TERM_GRACE_S) -> int:
    """Watch loop. Returns 0 when the job group is gone."""
    # The job was started with start_new_session=True, so its pid IS its
    # process-group id.
    pgid = target_pid
    while True:
        if not _group_alive(pgid):
            return 0
        if not _alive(parent_pid):
            _kill_group(pgid, signal.SIGTERM)
            deadline = time.time() + term_grace
            while time.time() < deadline and _group_alive(pgid):
                time.sleep(0.2)
            _kill_group(pgid, signal.SIGKILL)
            return 0
        time.sleep(poll_interval)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--parent-pid', type=int, required=True)
    parser.add_argument('--target-pid', type=int, required=True)
    parser.add_argument('--poll-interval', type=float,
                        default=POLL_INTERVAL_S)
    parser.add_argument('--term-grace', type=float, default=TERM_GRACE_S)
    args = parser.parse_args(argv)
    return reap(args.parent_pid, args.target_pid, args.poll_interval,
                args.term_grace)


if __name__ == '__main__':
    sys.exit(main())
