"""On-host RPC CLI: the client's door into the head agent.

The reference generates Python source strings and pipes them through SSH
(`JobLibCodeGen` sky/skylet/job_lib.py:803). Here the shipped package
itself is the protocol: the backend runs
    python -m skypilot_tpu.runtime.rpc <op> [--payload JSON]
on the head host (over SSH or the local runner); this module relays to the
head agent's HTTP server on localhost and prints one JSON document. No
string codegen, and the wire format is versioned with the package.
"""
import argparse
import json
import os
import sys

import requests

from skypilot_tpu.runtime import gang as gang_lib
from skypilot_tpu.runtime import job_lib
from skypilot_tpu.runtime import log_lib
from skypilot_tpu.runtime import server as server_lib


def _agent_config() -> server_lib.ClusterConfig:
    path = os.path.join(job_lib.skyt_dir(), 'agent.json')
    return server_lib.ClusterConfig.load(path)


def _base_url() -> str:
    cfg = _agent_config()
    return f'http://127.0.0.1:{cfg.head_port}'


def op_submit(payload):
    resp = requests.post(_base_url() + '/jobs/submit',
                         json={'spec': payload['spec']}, timeout=30)
    resp.raise_for_status()
    return resp.json()


def op_queue(payload):
    url = _base_url() + '/jobs'
    resp = requests.get(url, timeout=30)
    resp.raise_for_status()
    return resp.json()


def op_status(payload):
    resp = requests.get(_base_url() + f"/jobs/{payload['job_id']}",
                        timeout=30)
    if resp.status_code == 404:
        return {'error': 'not found'}
    resp.raise_for_status()
    return resp.json()


def op_cancel(payload):
    resp = requests.post(_base_url() + f"/jobs/{payload['job_id']}/cancel",
                         json={}, timeout=30)
    resp.raise_for_status()
    return resp.json()


def op_autostop(payload):
    resp = requests.post(_base_url() + '/autostop', json=payload, timeout=30)
    resp.raise_for_status()
    return resp.json()


def op_tail(payload):
    """Stream a job's rank-0 log to stdout; NOT JSON (follows until the job
    is terminal when --follow)."""
    job_id = int(payload['job_id'])
    follow = bool(payload.get('follow', True))
    log_path = os.path.join(job_lib.log_dir_for_job(job_id), 'rank-0.log')

    def job_done() -> bool:
        try:
            resp = requests.get(_base_url() + f'/jobs/{job_id}', timeout=10)
            if resp.status_code != 200:
                return True
            return job_lib.JobStatus(resp.json()['status']).is_terminal()
        except requests.RequestException:
            return True

    for line in log_lib.tail_logs(log_path, follow=follow,
                                  job_done=job_done):
        print(line, end='', flush=True)
    status = None
    wire = {}
    try:
        resp = requests.get(_base_url() + f'/jobs/{job_id}', timeout=10)
        if resp.status_code == 200:
            wire = resp.json()
            status = wire['status']
    except requests.RequestException:
        pass
    print(f'\n### Job {job_id} finished with status: {status} ###'
          if status and job_lib.JobStatus(status).is_terminal() else '',
          file=sys.stderr)
    # Training-plane postmortems ride the log surface: a HUNG/crashed
    # gang's bundles (py-stacks, flight-recorder spans, train state)
    # are the first thing an operator needs next to the logs.
    for line in job_lib.postmortem_trailer_lines(wire):
        print(line, file=sys.stderr)
    return None


def op_task_id(payload):
    """Echo the env contract for a hypothetical rank (debugging aid)."""
    cfg = _agent_config()
    env = gang_lib.job_env_vars(job_id=0, rank=0, ips=cfg.ips,
                                cluster_name=cfg.cluster_name)
    return {'env': env}


OPS = {
    'submit': op_submit,
    'queue': op_queue,
    'status': op_status,
    'cancel': op_cancel,
    'autostop': op_autostop,
    'tail': op_tail,
    'env': op_task_id,
}


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('op', choices=sorted(OPS))
    parser.add_argument('--payload', default='{}',
                        help='JSON arguments for the op')
    args = parser.parse_args(argv)
    payload = json.loads(args.payload)
    out = OPS[args.op](payload)
    if out is not None:
        print(json.dumps(out, default=str))


if __name__ == '__main__':
    main()
