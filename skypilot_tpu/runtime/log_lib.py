"""On-host log runner: run bash with streamed + filed logs; tail/follow.

Mirrors the reference's sky/skylet/log_lib.py (run_with_log :130,
make_task_bash_script :264, run_bash_command_with_log :311,
_follow_job_logs :339, tail_logs :387). This is what the per-host agent
executes a job's setup/run scripts through.
"""
import os
import subprocess
import tempfile
import time
from typing import Dict, Iterator, Optional, Tuple

_BASH_PRELUDE = """\
#!/bin/bash
source ~/.bashrc 2> /dev/null || true
set -a
"""


def make_task_bash_script(codegen: str,
                          env_vars: Optional[Dict[str, str]] = None) -> str:
    """Write the task script to a temp file; returns its path.

    Reference: log_lib.py:264 — login-shell semantics so user dotfile env
    (conda, PATH) is visible, `set -a` so exported vars reach subprocesses.
    """
    import shlex
    script = [_BASH_PRELUDE]
    for k, v in (env_vars or {}).items():
        # shlex.quote: values may contain newlines (SKYT_NODE_IPS is one IP
        # per line, reference-compatible) — POSIX single-quoting keeps them.
        script.append(f'export {k}={shlex.quote(str(v))}')
    script += ['set +a', 'cd "${SKYT_WORKDIR:-$HOME}" 2>/dev/null || true',
               codegen]
    fd, path = tempfile.mkstemp(prefix='skyt_task_', suffix='.sh')
    with os.fdopen(fd, 'w') as f:
        f.write('\n'.join(script) + '\n')
    os.chmod(path, 0o755)
    return path


def run_with_log(cmd, log_path: str,
                 *,
                 env_vars: Optional[Dict[str, str]] = None,
                 stream_logs: bool = False,
                 start_new_session: bool = True,
                 cwd: Optional[str] = None) -> Tuple[int, int]:
    """Run cmd (list or shell str), teeing stdout+stderr to log_path.

    Returns (returncode, pid). start_new_session puts the job in its own
    process group so cancellation can kill the whole tree (reference:
    log_lib.py run_with_log uses the same trick).
    """
    log_path = os.path.expanduser(log_path)
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    shell = isinstance(cmd, str)
    env = dict(os.environ)
    env.update({k: str(v) for k, v in (env_vars or {}).items()})
    with open(log_path, 'a', encoding='utf-8') as log_file:
        proc = subprocess.Popen(cmd, shell=shell, cwd=cwd, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT,
                                start_new_session=start_new_session,
                                text=True)
        assert proc.stdout is not None
        for line in proc.stdout:
            log_file.write(line)
            log_file.flush()
            if stream_logs:
                print(line, end='', flush=True)
        proc.wait()
        return proc.returncode, proc.pid


def tail_logs(log_path: str, *, follow: bool = False,
              job_done: Optional[callable] = None,
              from_start: bool = True,
              poll_interval: float = 0.5) -> Iterator[str]:
    """Yield log lines; in follow mode keep reading until job_done() is
    True AND the file is drained (reference: log_lib.py:339 follow loop).
    """
    log_path = os.path.expanduser(log_path)
    # Wait briefly for the file to appear (job may still be starting).
    deadline = time.time() + (30 if follow else 0)
    while not os.path.exists(log_path):
        if time.time() > deadline:
            return
        time.sleep(poll_interval)
    with open(log_path, 'r', encoding='utf-8', errors='replace') as f:
        if not from_start:
            f.seek(0, os.SEEK_END)
        while True:
            line = f.readline()
            if line:
                yield line
                continue
            if not follow:
                return
            if job_done is not None and job_done():
                # Drain whatever arrived between the check and now.
                rest = f.read()
                if rest:
                    yield rest
                return
            time.sleep(poll_interval)
