"""Client-side persistent state (sqlite).

Mirrors the reference's sky/global_user_state.py: tables `clusters`,
`cluster_history`, `config`, `storage` in a per-user sqlite DB. Default
location ~/.skypilot_tpu/state.db; override with SKYT_STATE_DIR (tests).
"""
import enum
import json
import os
import pickle
import sqlite3
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import sqlite_utils
from skypilot_tpu.utils import env


def state_dir() -> str:
    d = env.get('SKYT_STATE_DIR',
                       os.path.expanduser('~/.skypilot_tpu'))
    os.makedirs(d, exist_ok=True)
    return d


class ClusterStatus(enum.Enum):
    """Reference: sky/global_user_state.py ClusterStatus (INIT/UP/STOPPED)."""
    INIT = 'INIT'
    UP = 'UP'
    STOPPED = 'STOPPED'

    def colored(self) -> str:
        return self.value


class StorageStatus(enum.Enum):
    INIT = 'INIT'
    UPLOAD_FAILED = 'UPLOAD_FAILED'
    READY = 'READY'


# RLock: helpers like _get_hash() call _get_db() while a public function
# already holds the lock (remove_cluster deadlocked with a plain Lock).
_DB_LOCK = threading.RLock()
_DB: Optional[sqlite3.Connection] = None


def _get_db() -> sqlite3.Connection:
    global _DB
    with _DB_LOCK:
        if _DB is None:
            path = os.path.join(state_dir(), 'state.db')
            _DB = sqlite_utils.connect(path)
            _create_tables(_DB)
        return _DB


def reset_db_for_testing() -> None:
    global _DB
    with _DB_LOCK:
        if _DB is not None:
            _DB.close()
        _DB = None


def _create_tables(db: sqlite3.Connection) -> None:
    db.executescript("""
    CREATE TABLE IF NOT EXISTS clusters (
        name TEXT PRIMARY KEY,
        launched_at INTEGER,
        handle BLOB,
        last_use TEXT,
        status TEXT,
        autostop INTEGER DEFAULT -1,
        to_down INTEGER DEFAULT 0,
        cluster_hash TEXT,
        requested_resources BLOB);
    CREATE TABLE IF NOT EXISTS cluster_history (
        cluster_hash TEXT PRIMARY KEY,
        name TEXT,
        num_nodes INTEGER,
        requested_resources BLOB,
        launched_resources BLOB,
        usage_intervals BLOB,
        hourly_cost REAL DEFAULT 0);
    CREATE TABLE IF NOT EXISTS config (
        key TEXT PRIMARY KEY,
        value TEXT);
    CREATE TABLE IF NOT EXISTS storage (
        name TEXT PRIMARY KEY,
        launched_at INTEGER,
        handle BLOB,
        last_use TEXT,
        status TEXT);
    """)
    # Migrations for DBs created before a column existed (CREATE IF NOT
    # EXISTS never alters an existing table).
    try:
        db.execute('ALTER TABLE cluster_history ADD COLUMN '
                   'hourly_cost REAL DEFAULT 0')
    except sqlite3.OperationalError:
        pass  # already present
    db.commit()


# ----------------------------------------------------------------- clusters
def add_or_update_cluster(name: str, handle: Any,
                          requested_resources: Optional[Any] = None,
                          is_launch: bool = True,
                          status: ClusterStatus = ClusterStatus.INIT) -> None:
    """Reference: sky/global_user_state.py:139 add_or_update_cluster."""
    db = _get_db()
    now = int(time.time())
    handle_blob = pickle.dumps(handle)
    req_blob = pickle.dumps(requested_resources)
    cluster_hash = _get_hash(name) or uuid.uuid4().hex
    with _DB_LOCK:
        db.execute(
            """INSERT INTO clusters
               (name, launched_at, handle, last_use, status, cluster_hash,
                requested_resources)
               VALUES (?, ?, ?, ?, ?, ?, ?)
               ON CONFLICT(name) DO UPDATE SET
                 handle=excluded.handle, status=excluded.status,
                 last_use=excluded.last_use,
                 requested_resources=excluded.requested_resources""" +
            (', launched_at=excluded.launched_at' if is_launch else ''),
            (name, now, handle_blob, _history_cmd(), status.value,
             cluster_hash, req_blob))
        db.commit()
        _record_history(db, name, cluster_hash, handle, requested_resources,
                        now if is_launch else None)


def _history_cmd() -> str:
    import sys
    return ' '.join(sys.argv[:4])


def _get_hash(name: str) -> Optional[str]:
    db = _get_db()
    row = db.execute('SELECT cluster_hash FROM clusters WHERE name=?',
                     (name,)).fetchone()
    return row['cluster_hash'] if row else None


def _record_history(db, name, cluster_hash, handle, requested_resources,
                    launched_at) -> None:
    num_nodes = getattr(handle, 'num_hosts', None)
    launched = getattr(handle, 'launched_resources', None)
    row = db.execute(
        'SELECT usage_intervals FROM cluster_history WHERE cluster_hash=?',
        (cluster_hash,)).fetchone()
    intervals = pickle.loads(row['usage_intervals']) if row else []
    # Only open a new interval if the previous one is closed — a relaunch
    # of a live cluster must not leave an un-closable open interval behind.
    if launched_at is not None and not (intervals and
                                        intervals[-1][1] is None):
        intervals.append((launched_at, None))
    hourly_cost = getattr(handle, 'hourly_cost', 0.0) or 0.0
    db.execute(
        """INSERT INTO cluster_history
           (cluster_hash, name, num_nodes, requested_resources,
            launched_resources, usage_intervals, hourly_cost)
           VALUES (?, ?, ?, ?, ?, ?, ?)
           ON CONFLICT(cluster_hash) DO UPDATE SET
             launched_resources=excluded.launched_resources,
             num_nodes=excluded.num_nodes,
             usage_intervals=excluded.usage_intervals,
             hourly_cost=excluded.hourly_cost""",
        (cluster_hash, name, num_nodes, pickle.dumps(requested_resources),
         pickle.dumps(launched), pickle.dumps(intervals), hourly_cost))
    db.commit()


def update_cluster_status(name: str, status: ClusterStatus) -> None:
    db = _get_db()
    with _DB_LOCK:
        db.execute('UPDATE clusters SET status=? WHERE name=?',
                   (status.value, name))
        db.commit()


def set_cluster_autostop(name: str, idle_minutes: int, to_down: bool) -> None:
    db = _get_db()
    with _DB_LOCK:
        db.execute('UPDATE clusters SET autostop=?, to_down=? WHERE name=?',
                   (idle_minutes, int(to_down), name))
        db.commit()


def get_cluster(name: str) -> Optional[Dict[str, Any]]:
    db = _get_db()
    row = db.execute('SELECT * FROM clusters WHERE name=?', (name,)).fetchone()
    return _cluster_row_to_dict(row) if row else None


def get_clusters() -> List[Dict[str, Any]]:
    db = _get_db()
    rows = db.execute(
        'SELECT * FROM clusters ORDER BY launched_at DESC').fetchall()
    return [_cluster_row_to_dict(r) for r in rows]


def _cluster_row_to_dict(row: sqlite3.Row) -> Dict[str, Any]:
    # On a closed interval the end timestamp is recorded at teardown; the
    # cost report integrates these (reference: cost_report sky/core.py:136).
    return {
        'name': row['name'],
        'launched_at': row['launched_at'],
        'handle': pickle.loads(row['handle']),
        'last_use': row['last_use'],
        'status': ClusterStatus(row['status']),
        'autostop': row['autostop'],
        'to_down': bool(row['to_down']),
        'cluster_hash': row['cluster_hash'],
        'requested_resources': pickle.loads(row['requested_resources'])
        if row['requested_resources'] else None,
    }


def remove_cluster(name: str) -> None:
    db = _get_db()
    with _DB_LOCK:
        ch = _get_hash(name)
        if ch is not None:
            row = db.execute(
                'SELECT usage_intervals FROM cluster_history '
                'WHERE cluster_hash=?', (ch,)).fetchone()
            if row:
                intervals = pickle.loads(row['usage_intervals'])
                if intervals and intervals[-1][1] is None:
                    intervals[-1] = (intervals[-1][0], int(time.time()))
                    db.execute(
                        'UPDATE cluster_history SET usage_intervals=? '
                        'WHERE cluster_hash=?',
                        (pickle.dumps(intervals), ch))
        db.execute('DELETE FROM clusters WHERE name=?', (name,))
        db.commit()


def get_cluster_history() -> List[Dict[str, Any]]:
    db = _get_db()
    rows = db.execute('SELECT * FROM cluster_history').fetchall()
    out = []
    for r in rows:
        out.append({
            'name': r['name'],
            'num_nodes': r['num_nodes'],
            'launched_resources': pickle.loads(r['launched_resources'])
            if r['launched_resources'] else None,
            'usage_intervals': pickle.loads(r['usage_intervals'])
            if r['usage_intervals'] else [],
            'hourly_cost': r['hourly_cost'],
        })
    return out


# ------------------------------------------------------------------- config
def set_config(key: str, value: Any) -> None:
    db = _get_db()
    with _DB_LOCK:
        db.execute(
            'INSERT INTO config (key, value) VALUES (?, ?) '
            'ON CONFLICT(key) DO UPDATE SET value=excluded.value',
            (key, json.dumps(value)))
        db.commit()


def get_config(key: str, default: Any = None) -> Any:
    db = _get_db()
    row = db.execute('SELECT value FROM config WHERE key=?', (key,)).fetchone()
    return json.loads(row['value']) if row else default


def set_enabled_clouds(clouds: List[str]) -> None:
    set_config('enabled_clouds', clouds)


def get_enabled_clouds() -> Optional[List[str]]:
    return get_config('enabled_clouds')


# ------------------------------------------------------------------ storage
def add_or_update_storage(name: str, handle: Any,
                          status: StorageStatus) -> None:
    db = _get_db()
    with _DB_LOCK:
        db.execute(
            """INSERT INTO storage (name, launched_at, handle, last_use,
                                    status)
               VALUES (?, ?, ?, ?, ?)
               ON CONFLICT(name) DO UPDATE SET handle=excluded.handle,
                 status=excluded.status, last_use=excluded.last_use""",
            (name, int(time.time()), pickle.dumps(handle), _history_cmd(),
             status.value))
        db.commit()


def get_storage(name: str) -> Optional[Dict[str, Any]]:
    db = _get_db()
    row = db.execute('SELECT * FROM storage WHERE name=?', (name,)).fetchone()
    if row is None:
        return None
    return {'name': row['name'], 'launched_at': row['launched_at'],
            'handle': pickle.loads(row['handle']),
            'status': StorageStatus(row['status'])}


def get_storages() -> List[Dict[str, Any]]:
    db = _get_db()
    rows = db.execute('SELECT name FROM storage').fetchall()
    return [get_storage(r['name']) for r in rows]


def remove_storage(name: str) -> None:
    db = _get_db()
    with _DB_LOCK:
        db.execute('DELETE FROM storage WHERE name=?', (name,))
        db.commit()
