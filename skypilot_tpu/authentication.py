"""SSH key lifecycle: generate + load the framework keypair.

Reference: sky/authentication.py:1-120 generates ~/.ssh/sky-key once and
injects the public half per cloud. Here the key is ~/.ssh/skyt-key
(ed25519 via the system ssh-keygen; RSA via the cryptography package as
a fallback), and injection happens through TPU-VM node metadata
(provision/gcp/tpu_api.py ssh-keys) — no per-cloud registration quirks
needed for the TPU-first cloud set.

First-run UX: everything that needs a key calls get_or_generate_keypair()
— a fresh machine with an empty ~/.ssh works without manual setup.
"""
import functools
import os
import subprocess
from typing import Optional, Tuple

from skypilot_tpu.utils import log_utils

logger = log_utils.init_logger(__name__)

PRIVATE_KEY_PATH = '~/.ssh/skyt-key'
PUBLIC_KEY_PATH = '~/.ssh/skyt-key.pub'
_KEY_COMMENT = 'skypilot-tpu'


def _expand(path: str) -> str:
    return os.path.expanduser(path)


def _generate_ssh_keygen(private_path: str) -> bool:
    try:
        subprocess.run(
            ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q',
             '-f', private_path, '-C', _KEY_COMMENT],
            check=True, capture_output=True)
        return True
    except (OSError, subprocess.CalledProcessError) as e:
        logger.debug('ssh-keygen unavailable/failed: %r', e)
        return False


def _generate_cryptography(private_path: str) -> bool:
    try:
        from cryptography.hazmat.primitives import serialization
        from cryptography.hazmat.primitives.asymmetric import ed25519
    except ImportError:
        return False
    key = ed25519.Ed25519PrivateKey.generate()
    pem = key.private_bytes(
        encoding=serialization.Encoding.PEM,
        format=serialization.PrivateFormat.OpenSSH,
        encryption_algorithm=serialization.NoEncryption())
    pub = key.public_key().public_bytes(
        encoding=serialization.Encoding.OpenSSH,
        format=serialization.PublicFormat.OpenSSH)
    with open(private_path, 'wb', opener=functools.partial(
            os.open, mode=0o600)) as f:
        f.write(pem)
    with open(private_path + '.pub', 'w', encoding='utf-8') as f:
        f.write(pub.decode() + f' {_KEY_COMMENT}\n')
    return True


def get_or_generate_keypair() -> Tuple[str, str]:
    """Returns (private_key_path, public_key_str); generates the pair
    under ~/.ssh on first use (reference: sky/authentication.py
    _generate_rsa_key_pair + get_or_generate_keys)."""
    private = _expand(PRIVATE_KEY_PATH)
    public = _expand(PUBLIC_KEY_PATH)
    if not (os.path.exists(private) and os.path.exists(public)):
        ssh_dir = os.path.dirname(private)
        os.makedirs(ssh_dir, mode=0o700, exist_ok=True)
        # Clear a half-present pair before regenerating.
        for p in (private, public):
            if os.path.exists(p):
                os.remove(p)
        if not _generate_ssh_keygen(private):
            if not _generate_cryptography(private):
                raise RuntimeError(
                    'cannot generate an SSH keypair: neither ssh-keygen '
                    'nor the cryptography package is available; create '
                    f'{PRIVATE_KEY_PATH} manually')
        os.chmod(private, 0o600)
        logger.info('generated SSH keypair at %s', private)
    with open(public, 'r', encoding='utf-8') as f:
        return private, f.read().strip()


def public_key(generate: bool = True) -> Optional[str]:
    """The framework public key; pre-existing user keys are honored
    first so an operator's own identity keeps working."""
    for name in ('skyt-key.pub', 'id_ed25519.pub', 'id_rsa.pub'):
        path = _expand(f'~/.ssh/{name}')
        if os.path.exists(path):
            with open(path, 'r', encoding='utf-8') as f:
                return f.read().strip()
    if not generate:
        return None
    return get_or_generate_keypair()[1]


def private_key_path() -> Optional[str]:
    """Matching private key for whichever public key public_key() used."""
    for name in ('skyt-key', 'id_ed25519', 'id_rsa'):
        path = _expand(f'~/.ssh/{name}')
        if os.path.exists(path) and os.path.exists(path + '.pub'):
            return path
    return None
