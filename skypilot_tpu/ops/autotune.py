"""Kernel block-size autotuning with a persistent on-disk cache.

The flash kernels' default (256, 256) blocks are a one-size guess; the
best block shape depends on (device generation, sequence lengths, head
dim, dtype). This module sweeps the small legal candidate set ONCE per
(device_kind, op, shape-bucket, dtype) key, times each candidate on the
real device, and persists the winner so every later process — train
jobs, serve replicas — starts tuned.

Design constraints (docs/kernels.md):

* Sweeping executes kernels, so it can only run on CONCRETE arrays —
  never inside a jit trace. ``maybe_sweep_flash`` is a no-op on
  tracers; at trace time the dispatcher only READS the cache
  (``lookup_flash``). Sweeps therefore happen at setup/bench time
  (ops.attention called eagerly with ``SKYT_AUTOTUNE=1``).
* A candidate that fails for ANY reason is skipped, never propagated:
  a broken candidate must cost one log line, not the run.
* Cache writes are atomic (tmpfile + os.replace) so a preempted
  process can never leave a half-written file; a corrupt/unreadable
  cache file degrades to a cold start, never a crash.

Cache file format (``SKYT_AUTOTUNE_CACHE``, default
``~/.cache/skypilot_tpu/autotune.json``)::

    {"version": 1,
     "entries": {"<device_kind>|<op>|<bucket>|<dtype>":
                 {"block_q": 256, "block_k": 128, "us": 123.4}}}

Env vars: SKYT_AUTOTUNE=1 enables sweeping (reads are always on),
SKYT_AUTOTUNE_CACHE overrides the path, SKYT_AUTOTUNE_REPEATS the
per-candidate timing repeats (default 3, best-of).
"""
import json
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.ops import dispatch
from skypilot_tpu.utils import log_utils
from skypilot_tpu.utils import metrics as metrics_lib
from skypilot_tpu.utils import env

logger = log_utils.init_logger(__name__)

_ENV_ENABLE = 'SKYT_AUTOTUNE'
_ENV_CACHE = 'SKYT_AUTOTUNE_CACHE'
_ENV_REPEATS = 'SKYT_AUTOTUNE_REPEATS'

_VERSION = 1

# Candidate seq-block extents, pruned per shape by legality.
_FLASH_CANDIDATE_BLOCKS = (128, 256, 512)


def enabled() -> bool:
    return env.get(_ENV_ENABLE, '0') == '1'


def cache_path() -> str:
    return env.get(_ENV_CACHE) or os.path.expanduser(
        '~/.cache/skypilot_tpu/autotune.json')


def _sweeps() -> 'metrics_lib.Counter':
    return metrics_lib.REGISTRY.counter(
        'skyt_ops_autotune_sweeps_total',
        'Autotune block-size sweeps executed', ('op',))


def _hits() -> 'metrics_lib.Counter':
    return metrics_lib.REGISTRY.counter(
        'skyt_ops_autotune_cache_hits_total',
        'Autotune cache hits (sweep skipped)', ('op',))


class AutotuneCache:
    """Thread-safe persistent key -> dict cache. Never raises from
    load (corrupt file == cold start); writes are atomic."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._entries: Optional[Dict[str, Dict[str, Any]]] = None

    def _load_locked(self) -> Dict[str, Dict[str, Any]]:  # guarded-by: _lock
        if self._entries is not None:
            return self._entries
        entries: Dict[str, Dict[str, Any]] = {}
        try:
            with open(self.path, encoding='utf-8') as f:
                data = json.load(f)
            if (isinstance(data, dict) and
                    data.get('version') == _VERSION and
                    isinstance(data.get('entries'), dict)):
                entries = {k: v for k, v in data['entries'].items()
                           if isinstance(v, dict)}
            else:
                logger.warning(
                    'autotune cache %s has unexpected layout '
                    '(version %r); starting cold', self.path,
                    data.get('version') if isinstance(data, dict)
                    else type(data).__name__)
        except FileNotFoundError:
            pass
        except (OSError, ValueError) as e:
            # json.JSONDecodeError is a ValueError: a corrupt cache
            # (killed mid-debug-edit, disk hiccup) costs a re-sweep,
            # never the process.
            logger.warning('autotune cache %s unreadable (%s); '
                           'starting cold', self.path, e)
        self._entries = entries
        return entries

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._load_locked().get(key)

    def put(self, key: str, value: Dict[str, Any]) -> None:
        with self._lock:
            entries = self._load_locked()
            entries[key] = value
            payload = json.dumps(
                {'version': _VERSION, 'entries': entries},
                indent=1, sort_keys=True)
            try:
                d = os.path.dirname(self.path) or '.'
                os.makedirs(d, exist_ok=True)
                fd, tmp = tempfile.mkstemp(dir=d, prefix='.autotune.')
                try:
                    with os.fdopen(fd, 'w', encoding='utf-8') as f:
                        f.write(payload)
                    os.replace(tmp, self.path)   # atomic on POSIX
                except BaseException:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    raise
            except OSError as e:
                # Read-only FS / ENOSPC: the in-memory winner still
                # serves this process; only persistence is lost.
                logger.warning('autotune cache %s not persisted (%s)',
                               self.path, e)

    def forget_loaded(self) -> None:
        """Drop the in-memory copy so the next access re-reads disk
        (tests simulating a fresh process)."""
        with self._lock:
            self._entries = None


_caches: Dict[str, AutotuneCache] = {}
_caches_lock = threading.Lock()


def get_cache(path: Optional[str] = None) -> AutotuneCache:
    path = path or cache_path()
    with _caches_lock:
        c = _caches.get(path)
        if c is None:
            c = _caches[path] = AutotuneCache(path)
        return c


def flash_key(b: int, sq: int, sk: int, hq: int, hkv: int, d: int,
              dtype, causal: bool, has_seg: bool, window: int) -> str:
    bucket = (f'b{dispatch.shape_bucket(b)}'
              f'.sq{dispatch.shape_bucket(sq)}'
              f'.sk{dispatch.shape_bucket(sk)}'
              f'.h{hq}x{hkv}.d{d}'
              f'.c{int(causal)}.seg{int(has_seg)}.w{window}')
    import jax.numpy as jnp
    return (f'{dispatch.device_kind()}|flash_attention|{bucket}'
            f'|{jnp.dtype(dtype).name}')


def lookup_flash(q_shape: Sequence[int], k_shape: Sequence[int], dtype,
                 causal: bool, has_seg: bool,
                 window: int) -> Optional[Tuple[int, int]]:
    """Trace-time cache read: tuned (block_q, block_k) or None. Shapes
    are concrete even on tracers, so this works under jit."""
    b, sq, hq, d = q_shape
    sk, hkv = k_shape[1], k_shape[2]
    entry = get_cache().get(
        flash_key(b, sq, sk, hq, hkv, d, dtype, causal, has_seg, window))
    if not entry:
        return None
    try:
        return int(entry['block_q']), int(entry['block_k'])
    except (KeyError, TypeError, ValueError):
        return None   # stale/hand-edited entry: behave as a miss


def sweep(op: str, key: str, candidates: Sequence[Any],
          run: Callable[[Any], Any],
          describe: Callable[[Any], Dict[str, Any]]) -> Optional[dict]:
    """Generic timed sweep: run(cand) per candidate (must block until
    the device finishes), best wall time wins, failures are skipped.
    Persists describe(winner) + timing under `key`. Returns the stored
    entry, or None when every candidate failed."""
    cache = get_cache()
    hit = cache.get(key)
    if hit is not None:
        _hits().labels(op).inc()
        return hit
    repeats = env.get_int(_ENV_REPEATS, 3, minimum=1)
    _sweeps().labels(op).inc()
    best: Optional[Tuple[float, Any]] = None
    for cand in candidates:
        try:
            run(cand)                       # warmup / compile
            dt = min(_timed(run, cand) for _ in range(repeats))
        except Exception as e:  # pylint: disable=broad-except
            # "Any candidate failure is a skip, never a propagate."
            logger.info('autotune %s: candidate %r failed (%s: %s); '
                        'skipped', op, cand, type(e).__name__, e)
            continue
        if best is None or dt < best[0]:
            best = (dt, cand)
    if best is None:
        logger.warning('autotune %s: every candidate failed for %s; '
                       'falling back to defaults', op, key)
        # Negative-cache the failure: without this, every later eager
        # call for the bucket re-runs the whole failing sweep
        # (minutes on-device). lookup_flash reads it as a miss (no
        # block_q), so dispatch defaults still apply.
        cache.put(key, {'failed': True})
        return None
    entry = dict(describe(best[1]))
    entry['us'] = round(best[0] * 1e6, 2)
    cache.put(key, entry)
    logger.info('autotune %s: %s -> %s', op, key, entry)
    return entry


def _timed(run: Callable[[Any], Any], cand: Any) -> float:
    t0 = time.perf_counter()
    run(cand)
    return time.perf_counter() - t0


def flash_candidates(sq: int, sk: int, dtype,
                     has_seg: bool) -> List[Tuple[int, int]]:
    """Legal (block_q, block_k) candidates: the cross product of the
    candidate extents clamped through the divisibility-safe selector,
    deduplicated, plus the conservative full-array pair."""
    out: List[Tuple[int, int]] = []
    for wq in _FLASH_CANDIDATE_BLOCKS:
        for wk in _FLASH_CANDIDATE_BLOCKS:
            cand = dispatch.flash_blocks(sq, sk, wq, wk, dtype, has_seg)
            if cand not in out:
                out.append(cand)
    if (sq, sk) not in out:
        out.append((sq, sk))
    return out


def maybe_sweep_flash(q, k, v, causal: bool, segment_ids,
                      window: int) -> None:
    """Sweep flash block sizes for this shape if enabled, concrete,
    and not already cached. Called from ops.attention's eager wrapper;
    one env read when disabled."""
    if not enabled() or dispatch.is_tracer(q):
        return
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    has_seg = segment_ids is not None
    key = flash_key(b, sq, sk, hq, hkv, d, q.dtype, causal, has_seg,
                    window)
    from skypilot_tpu.ops import flash_attention as flash_lib

    def run(cand):
        bq, bk = cand
        out = flash_lib.flash_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            block_q=bq, block_k=bk, window=window)
        out.block_until_ready()

    sweep('flash_attention', key,
          flash_candidates(sq, sk, q.dtype, has_seg), run,
          lambda cand: {'block_q': cand[0], 'block_k': cand[1]})


def reset_for_tests() -> None:
    """Drop all in-memory cache instances (tests swap cache paths)."""
    with _caches_lock:
        _caches.clear()
