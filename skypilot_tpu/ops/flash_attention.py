"""Flash attention (forward + backward) as Pallas TPU kernels.

Blockwise online-softmax attention (Flash-Attention-2 schedule):

* forward: grid over (batch, q_heads, q_blocks, k_blocks) with the k axis
  innermost so the VMEM scratch accumulators (running max m, running sum
  l, output acc) persist across k iterations of one q block; also emits
  the per-row logsumexp L for the backward. Causal masking skips
  fully-masked k blocks via pl.when; GQA is folded into the k/v index_map
  (head h reads kv head h // group). Segment ids (packed sequences) are
  masked in-kernel.
* backward: two kernels, both recomputing p = exp(s - L) blockwise from
  the saved residuals (q, k, v, L, delta = rowsum(dO*O)) — no O(S^2)
  materialization:
    - dq kernel: same grid as forward (k innermost), accumulates
      dq += ds @ k in VMEM scratch;
    - dk/dv kernel: grid (batch, q_heads, k_blocks, q_blocks) with q
      innermost, accumulates dk/dv per *query* head; the GQA group sum
      down to kv heads happens outside the kernel (one cheap XLA
      reduce), avoiding non-contiguous output revisits.

Kernel conventions follow /opt/skills/guides/pallas_guide.md (block
specs, scratch via pl.pallas_call scratch_shapes, MXU-aligned tiles).
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from skypilot_tpu.ops import dispatch
from skypilot_tpu.utils import env

# jax renamed TPUCompilerParams -> CompilerParams (~0.5); support both
# so the kernels work on whichever jax the image ships.
_CompilerParams = getattr(pltpu, 'CompilerParams',
                          getattr(pltpu, 'TPUCompilerParams', None))

NEG_INF = -1e30

# Row statistics (lse, delta) are carried as [..., seq, LANES] arrays with
# the value replicated across the 128 lanes: Mosaic requires the last two
# dims of every block to be (8k, 128)-tileable or equal to the array dims,
# so a (1, block_q)-shaped row block does not lower. Same layout as
# jax.experimental.pallas.ops.tpu.flash_attention (its MIN_BLOCK_SIZE).
LANES = 128


def _bwd_impl_choice() -> str:
    """'pallas' (default) or 'xla' — SKYT_FLASH_BWD overrides. The XLA
    path recomputes reference attention under custom_vjp (the round-1
    behavior); the escape hatch exists so a pathological kernel compile
    can never take down a training run."""
    return env.get('SKYT_FLASH_BWD', 'pallas')

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


def _interpret_mode() -> bool:
    """Pallas interpret mode off-TPU (CPU tests exercise kernel logic)."""
    try:
        return jax.devices()[0].platform != 'tpu'
    except Exception:
        return True


def _block_mask(s, qi, ki, block_q, block_k, causal, window,
                q_seg_ref, k_seg_ref):
    """Apply causal / sliding-window / segment masking to a
    [block_q, block_k] score block. window > 0 (Mistral, every other
    Gemma-2 layer, Phi-3): query p also requires p - k_pos < window.
    Returns the masked scores."""
    if causal or window > 0:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        if causal:
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if window > 0:
            s = jnp.where(q_pos - k_pos < window, s, NEG_INF)
    if q_seg_ref is not None:
        q_seg = q_seg_ref[0, 0]           # [block_q]
        k_seg = k_seg_ref[0, 0]           # [block_k]
        s = jnp.where(q_seg[:, None] == k_seg[None, :], s, NEG_INF)
    return s


def _qk_block_overlaps(qi, ki, block_q, block_k, causal, window):
    """Traced bool: does this (q block, k block) pair contain ANY
    unmasked (q, k) entry under causal+window? Used to skip whole
    blocks: above the diagonal (causal) and, with a window, entirely
    below it."""
    cond = True
    if causal:
        cond = jnp.logical_and(cond, ki * block_k < (qi + 1) * block_q)
    if window > 0:
        # Highest k in the block must reach the lowest q's window
        # start: (ki+1)*bk - 1 >= qi*bq - (window - 1).
        cond = jnp.logical_and(
            cond, (ki + 1) * block_k > qi * block_q - window + 1)
    return cond


def _fwd_kernel(*refs, scale: float, causal: bool, window: int,
                block_q: int, block_k: int, num_k_blocks: int,
                has_seg: bool):
    if has_seg:
        (q_ref, k_ref, v_ref, q_seg_ref, k_seg_ref,
         o_ref, lse_ref, m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        q_seg_ref = k_seg_ref = None
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0, 0]                   # [block_q, d]
        k = k_ref[0, 0]                   # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        s = _block_mask(s, qi, ki, block_q, block_k, causal, window,
                        q_seg_ref, k_seg_ref)
        m_prev = m_scr[:]                 # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Exact 0 for masked entries: a row whose FIRST visited block is
        # fully masked has m_new == NEG_INF, and exp(NEG_INF - NEG_INF)
        # would be 1 — with a sliding window that case is routine (rows
        # near the end of a q block whose window starts past this k
        # block), so guard by value rather than rely on underflow.
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        alpha = jnp.exp(m_prev - m_new)   # [bq, 1]
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    if causal or window > 0:
        pl.when(_qk_block_overlaps(qi, ki, block_q, block_k, causal,
                                   window))(_compute)
    else:
        _compute()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> out 0
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # Logsumexp residual; 0 for fully-masked rows so the backward's
        # p = exp(NEG_INF - 0) is exactly 0.
        lse = jnp.where(l > 0.0, m_scr[:] + jnp.log(safe_l), 0.0)
        lse_ref[0, 0] = jnp.broadcast_to(lse, (lse.shape[0], LANES))


def _dq_kernel(*refs, scale: float, causal: bool, window: int,
               block_q: int, block_k: int, num_k_blocks: int,
               has_seg: bool):
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         q_seg_ref, k_seg_ref, dq_ref, dq_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_scr) = refs
        q_seg_ref = k_seg_ref = None
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        q = q_ref[0, 0]                   # [bq, d]
        k = k_ref[0, 0]                   # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _block_mask(s, qi, ki, block_q, block_k, causal, window,
                        q_seg_ref, k_seg_ref)
        lse = lse_ref[0, 0][:, :1]        # [bq, 1] (lane-replicated)
        p = jnp.exp(s - lse)              # [bq, bk]
        do = do_ref[0, 0]                 # [bq, d]
        dp = jax.lax.dot_general(
            do, v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        delta = delta_ref[0, 0][:, :1]    # [bq, 1]
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal or window > 0:
        pl.when(_qk_block_overlaps(qi, ki, block_q, block_k, causal,
                                   window))(_compute)
    else:
        _compute()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale: float, causal: bool, window: int,
                block_q: int, block_k: int, num_q_blocks: int,
                has_seg: bool):
    if has_seg:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         q_seg_ref, k_seg_ref, dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        q_seg_ref = k_seg_ref = None
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q = q_ref[0, 0]                   # [bq, d]
        k = k_ref[0, 0]                   # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _block_mask(s, qi, ki, block_q, block_k, causal, window,
                        q_seg_ref, k_seg_ref)
        lse = lse_ref[0, 0][:, :1]        # [bq, 1] (lane-replicated)
        p = jnp.exp(s - lse)              # [bq, bk]
        do = do_ref[0, 0]                 # [bq, d]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bk, d]
        dp = jax.lax.dot_general(
            do, v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        delta = delta_ref[0, 0][:, :1]    # [bq, 1]
        ds = p * (dp - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bk, d]

    if causal or window > 0:
        # Same overlap predicate, evaluated from this kernel's
        # (ki outer, qi inner) grid order.
        pl.when(_qk_block_overlaps(qi, ki, block_q, block_k, causal,
                                   window))(_compute)
    else:
        _compute()

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def flash_attention_fwd_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                            causal: bool = True):
    """FORWARD-ONLY flash attention that also returns the per-row
    logsumexp: (out [B,Sq,Hq,D], lse [B,Hq,Sq] f32).

    For callers that merge partial attentions themselves (ring
    attention's cross-chunk online-softmax combine). Not differentiable
    — wrap it in your own custom_vjp (parallel/ring_attention.py routes
    its backward through the einsum path).
    """
    out, lse = _flash_fwd_impl(q, k, v, None, causal, DEFAULT_BLOCK_Q,
                               DEFAULT_BLOCK_K, 0)
    return out, lse[..., 0]


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    segment_ids: Optional[jax.Array] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    window: int = 0) -> jax.Array:
    """q: [B, Sq, Hq, D]; k, v: [B, Sk, Hkv, D] -> [B, Sq, Hq, D].

    block_q/block_k are REQUESTS, not contracts: they are clamped
    through the divisibility-safe selector (ops/dispatch.py) to a
    tile-aligned divisor of the seq dims or to the full dims, so any
    legal input shape lowers — decode shapes included. Serving/train
    call sites should go through ops.attention's dispatch ladder,
    which adds the conservative-Pallas and XLA fallback rungs.

    segment_ids: optional [B, S] int32 packed-sequence ids, masked
    in-kernel (forward and backward).
    window: sliding-window attention (> 0: query p sees k in
    (p - window, p]). Out-of-window blocks skip their COMPUTE (the
    same pl.when structure as the causal above-diagonal skip — a FLOP
    saving; the grid still fetches every k/v block, so memory traffic
    is unchanged).
    """
    return _flash(q, k, v, segment_ids, causal, block_q, block_k,
                  window)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, segment_ids, causal, block_q, block_k, window):
    out, _ = _flash_fwd_impl(q, k, v, segment_ids, causal, block_q,
                             block_k, window)
    return out


def _shape_checks(q, k, block_q, block_k, has_seg=False):
    """Shape-robust block selection (docs/kernels.md): requested
    blocks are CLAMPED through the divisibility-safe selector — to a
    tile-aligned divisor of the seq dim, or to the full dim (always
    legal) — so any legal input shape lowers; decode shapes like the
    BENCH_r02 (4, 32, 8, 256) no longer raise. A block pair whose
    VMEM working set cannot fit is refused at TRACE time (a
    ValueError the dispatch ladder catches), because the Mosaic
    compile error it would become is not catchable."""
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(
            f'q heads ({hq}) must be a multiple of kv heads ({hkv})')
    block_q, block_k = dispatch.flash_blocks(sq, sk, block_q, block_k,
                                             q.dtype, has_seg)
    if not _interpret_mode() and not dispatch.flash_vmem_ok(
            block_q, block_k, d, jnp.dtype(q.dtype).itemsize):
        raise ValueError(
            f'flash blocks ({block_q}, {block_k}) x d={d} exceed the '
            f'VMEM budget ({dispatch.VMEM_BUDGET_BYTES}B) — refusing '
            'a certain Mosaic compile failure')
    return b, sq, sk, hq, hkv, d, block_q, block_k


def _flash_fwd_impl(q, k, v, segment_ids, causal, block_q, block_k,
                    window=0):
    has_seg = segment_ids is not None
    b, sq, sk, hq, hkv, d, block_q, block_k = _shape_checks(
        q, k, block_q, block_k, has_seg)
    group = hq // hkv
    nq, nk = sq // block_q, sk // block_k
    scale = d ** -0.5

    # Kernel layout: [B, H, S, D] (head-major so blocks are contiguous).
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, window=window,
        block_q=block_q, block_k=block_k, num_k_blocks=nk,
        has_seg=has_seg)

    in_specs = [
        pl.BlockSpec((1, 1, block_q, d),
                     lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)),
    ]
    operands = [qt, kt, vt]
    if has_seg:
        # [b, 1, s] so the seq extent rides the LANE axis of the block
        # ((1, 1, block) passes the Mosaic last-two-dims rule for any
        # batch; the old [b, s] layout put the batch in the sublane
        # slot, where a 1-extent block is illegal whenever b > 1).
        seg = segment_ids.astype(jnp.int32)[:, None, :]
        in_specs += [
            pl.BlockSpec((1, 1, block_q),
                         lambda bi, hi, qi, ki: (bi, 0, qi)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, qi, ki: (bi, 0, ki)),
        ]
        operands += [seg, seg]

    out, lse = pl.pallas_call(
        kernel,
        grid=(b, hq, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, LANES),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running sum
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=_interpret_mode(),
    )(*operands)
    return out.transpose(0, 2, 1, 3), lse


def _fwd_rule(q, k, v, segment_ids, causal, block_q, block_k, window):
    out, lse = _flash_fwd_impl(q, k, v, segment_ids, causal, block_q,
                               block_k, window)
    return out, (q, k, v, segment_ids, out, lse)


def _bwd_rule(causal, block_q, block_k, window, res, g):
    q, k, v, segment_ids, out, lse = res
    if _bwd_impl_choice() == 'xla':
        from skypilot_tpu.ops import attention as attention_ops
        _, vjp = jax.vjp(
            lambda q_, k_, v_: attention_ops.mha_reference(
                q_, k_, v_, causal=causal, segment_ids=segment_ids,
                window=window),
            q, k, v)
        return (*vjp(g), None)
    has_seg = segment_ids is not None
    b, sq, sk, hq, hkv, d, block_q, block_k = _shape_checks(
        q, k, block_q, block_k, has_seg)
    group = hq // hkv
    nq, nk = sq // block_q, sk // block_k
    scale = d ** -0.5

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = g.transpose(0, 2, 1, 3)         # dO, [b, hq, sq, d]
    ot = out.transpose(0, 2, 1, 3)

    # delta_i = sum_d dO_i * O_i, the softmax-grad row correction,
    # lane-replicated to the Mosaic-friendly [b, hq, sq, LANES] layout.
    delta = (dot.astype(jnp.float32) * ot.astype(jnp.float32)).sum(-1)
    delta = jnp.broadcast_to(delta[..., None], (b, hq, sq, LANES))

    qkv_spec = lambda bi, hi, qi, ki: (bi, hi, qi, 0)  # noqa: E731
    kv_spec = lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)  # noqa: E731
    row_spec = lambda bi, hi, qi, ki: (bi, hi, qi, 0)  # noqa: E731

    common_in_specs = [
        pl.BlockSpec((1, 1, block_q, d), qkv_spec),       # q
        pl.BlockSpec((1, 1, block_k, d), kv_spec),        # k
        pl.BlockSpec((1, 1, block_k, d), kv_spec),        # v
        pl.BlockSpec((1, 1, block_q, d), qkv_spec),       # dO
        pl.BlockSpec((1, 1, block_q, LANES), row_spec),   # lse
        pl.BlockSpec((1, 1, block_q, LANES), row_spec),   # delta
    ]
    operands = [qt, kt, vt, dot, lse, delta]
    if has_seg:
        seg = segment_ids.astype(jnp.int32)[:, None, :]  # lane-axis seq
        common_in_specs += [
            pl.BlockSpec((1, 1, block_q),
                         lambda bi, hi, qi, ki: (bi, 0, qi)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, qi, ki: (bi, 0, ki)),
        ]
        operands += [seg, seg]

    dq_kernel = functools.partial(
        _dq_kernel, scale=scale, causal=causal, window=window,
        block_q=block_q, block_k=block_k, num_k_blocks=nk,
        has_seg=has_seg)
    dqt = pl.pallas_call(
        dq_kernel,
        grid=(b, hq, nq, nk),
        in_specs=list(common_in_specs),
        out_specs=pl.BlockSpec((1, 1, block_q, d), qkv_spec),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=_interpret_mode(),
    )(*operands)

    # dk/dv per *query* head: the kernel walks q blocks innermost for a
    # fixed k block; the kv-head (GQA group) reduction is one XLA sum.
    def dkv_q_spec(bi, hi, ki, qi):
        return (bi, hi, qi, 0)

    def dkv_kv_spec(bi, hi, ki, qi):
        return (bi, hi // group, ki, 0)

    def dkv_row_spec(bi, hi, ki, qi):
        return (bi, hi, qi, 0)

    dkv_in_specs = [
        pl.BlockSpec((1, 1, block_q, d), dkv_q_spec),      # q
        pl.BlockSpec((1, 1, block_k, d), dkv_kv_spec),     # k
        pl.BlockSpec((1, 1, block_k, d), dkv_kv_spec),     # v
        pl.BlockSpec((1, 1, block_q, d), dkv_q_spec),      # dO
        pl.BlockSpec((1, 1, block_q, LANES), dkv_row_spec),  # lse
        pl.BlockSpec((1, 1, block_q, LANES), dkv_row_spec),  # delta
    ]
    if has_seg:
        dkv_in_specs += [
            pl.BlockSpec((1, 1, block_q),
                         lambda bi, hi, ki, qi: (bi, 0, qi)),
            pl.BlockSpec((1, 1, block_k),
                         lambda bi, hi, ki, qi: (bi, 0, ki)),
        ]

    dkv_kernel = functools.partial(
        _dkv_kernel, scale=scale, causal=causal, window=window,
        block_q=block_q, block_k=block_k, num_q_blocks=nq,
        has_seg=has_seg)
    dk_spec = lambda bi, hi, ki, qi: (bi, hi, ki, 0)  # noqa: E731
    dkt, dvt = pl.pallas_call(
        dkv_kernel,
        grid=(b, hq, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), dk_spec),
            pl.BlockSpec((1, 1, block_k, d), dk_spec),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, hq, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=_interpret_mode(),
    )(*operands)

    if group > 1:
        dkt = dkt.reshape(b, hkv, group, sk, d).sum(2)
        dvt = dvt.reshape(b, hkv, group, sk, d).sum(2)

    dq = dqt.transpose(0, 2, 1, 3)
    dk = dkt.transpose(0, 2, 1, 3)
    dv = dvt.transpose(0, 2, 1, 3)
    return dq, dk, dv, None


_flash.defvjp(_fwd_rule, _bwd_rule)
