"""Flash attention (forward) as a Pallas TPU kernel.

Blockwise online-softmax attention (Flash-Attention-2 schedule): grid over
(batch, q_heads, q_blocks, k_blocks) with the k axis innermost so the VMEM
scratch accumulators (running max m, running sum l, output acc) persist
across k iterations of one q block. Causal masking skips fully-masked k
blocks via pl.when; GQA is folded into the k/v index_map (head h reads kv
head h // group). Backward pass uses XLA recompute via custom_vjp — the
flash win in training is the forward (the backward is recomputed under
jax.checkpoint per layer anyway); a Pallas backward kernel is the next
optimization step.

Kernel conventions follow /opt/skills/guides/pallas_guide.md (block specs,
scratch via pl.pallas_call scratch_shapes, MXU-aligned 128 tiles).
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from skypilot_tpu.ops import attention as attention_ops

NEG_INF = -1e30

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


def _interpret_mode() -> bool:
    """Pallas interpret mode off-TPU (CPU tests exercise kernel logic)."""
    try:
        return jax.devices()[0].platform != 'tpu'
    except Exception:
        return True


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                scale: float, causal: bool, block_q: int, block_k: int,
                num_k_blocks: int):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0, 0]                   # [block_q, d]
        k = k_ref[0, 0]                   # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_prev = m_scr[:]                 # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)            # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)   # [bq, 1]
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    if causal:
        # Skip k blocks entirely above the diagonal.
        first_masked = (qi + 1) * block_q  # k positions >= this are masked
        pl.when(ki * block_k < first_masked)(_compute)
    else:
        _compute()

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[:]
        l = jnp.where(l == 0.0, 1.0, l)   # fully-masked rows -> output 0
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    segment_ids: Optional[jax.Array] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """q: [B, Sq, Hq, D]; k, v: [B, Sk, Hkv, D] -> [B, Sq, Hq, D].

    segment_ids is not yet supported by the kernel (falls back to XLA).
    The dispatch happens OUTSIDE the custom_vjp: segment_ids is a traced
    array and must never appear in nondiff_argnums.
    """
    if segment_ids is not None:
        return attention_ops.mha_reference(q, k, v, causal=causal,
                                           segment_ids=segment_ids)
    return _flash(q, k, v, causal, block_q, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
           block_q: int, block_k: int) -> jax.Array:
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k)


def _flash_fwd_impl(q, k, v, causal, block_q, block_k):
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    assert hq % hkv == 0
    group = hq // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (sq, sk, block_q,
                                                     block_k)
    nq, nk = sq // block_q, sk // block_k
    scale = d ** -0.5

    # Kernel layout: [B, H, S, D] (head-major so blocks are contiguous).
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=nk)

    out = pl.pallas_call(
        kernel,
        grid=(b, hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running sum
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=_interpret_mode(),
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


def _fwd_rule(q, k, v, causal, block_q, block_k):
    out = _flash(q, k, v, causal, block_q, block_k)
    return out, (q, k, v)


def _bwd_rule(causal, block_q, block_k, res, g):
    q, k, v = res
    # Backward via XLA recompute of the reference attention. O(S^2) memory
    # per block is bounded by the remat granularity of the caller.
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_ops.mha_reference(
            q_, k_, v_, causal=causal), q, k, v)
    return vjp(g)


_flash.defvjp(_fwd_rule, _bwd_rule)
