"""Attention ops: XLA reference implementation + dispatch.

The XLA path is the correctness baseline and the grad path on CPU; on TPU
the Pallas flash kernel (ops/flash_attention.py) is used for the hot
forward/backward. GQA (grouped KV heads) handled by logical head repeat
folded into the einsum — no materialized K/V repeat.
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # logits are f32 until softmax, so -1e9 never overflows


def _causal_mask(q_len: int, k_len: int, q_offset: int = 0) -> jax.Array:
    """[q_len, k_len] bool, True = attendable. q_offset shifts query
    positions (used for decode and for ring-attention blocks)."""
    q_pos = jnp.arange(q_len)[:, None] + q_offset
    k_pos = jnp.arange(k_len)[None, :]
    return q_pos >= k_pos


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True,
                  segment_ids: Optional[jax.Array] = None,
                  kv_segment_ids: Optional[jax.Array] = None,
                  q_offset: int = 0,
                  q_positions: Optional[jax.Array] = None,
                  softmax_scale: Optional[float] = None) -> jax.Array:
    """q: [B, Sq, Hq, D]; k,v: [B, Sk, Hkv, D]; Hq % Hkv == 0.

    Returns [B, Sq, Hq, D]. Logits and softmax in f32.

    q_positions: optional [B, Sq] global query positions for the causal
    mask (per-batch offsets — the KV-cache decode path); overrides
    q_offset. Keys are assumed at positions 0..Sk-1.
    """
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    assert hq % hkv == 0, (hq, hkv)
    groups = hq // hkv
    scale = softmax_scale if softmax_scale is not None else d ** -0.5

    qg = q.reshape(b, sq, hkv, groups, d)
    logits = jnp.einsum('bqhgd,bkhd->bhgqk', qg, k,
                        preferred_element_type=jnp.float32)
    logits = logits * scale

    mask = None
    if q_positions is not None:
        k_pos = jnp.arange(sk)
        mask = (q_positions[:, None, None, :, None] >=
                k_pos[None, None, None, None, :])
    elif causal:
        mask = _causal_mask(sq, sk, q_offset)[None, None, None]
    if segment_ids is not None:
        kv_seg = kv_segment_ids if kv_segment_ids is not None else segment_ids
        seg_mask = (segment_ids[:, None, None, :, None] ==
                    kv_seg[:, None, None, None, :])
        mask = seg_mask if mask is None else (mask & seg_mask)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum('bhgqk,bkhd->bqhgd', probs.astype(v.dtype), v)
    return out.reshape(b, sq, hq, d)


@functools.partial(jax.jit, static_argnames=('causal', 'impl'))
def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = True,
              segment_ids: Optional[jax.Array] = None,
              impl: str = 'auto') -> jax.Array:
    """Dispatch: 'auto' uses the Pallas flash kernel on TPU when shapes
    allow, else the XLA reference."""
    if impl == 'auto':
        impl = 'flash' if _flash_ok(q, k) else 'xla'
    if impl == 'flash':
        from skypilot_tpu.ops import flash_attention
        return flash_attention.flash_attention(
            q, k, v, causal=causal, segment_ids=segment_ids)
    return mha_reference(q, k, v, causal=causal, segment_ids=segment_ids)


def _flash_ok(q: jax.Array, k: jax.Array) -> bool:
    try:
        on_tpu = jax.devices()[0].platform == 'tpu'
    except Exception:  # pylint: disable=broad-except
        on_tpu = False
    sq, sk, d = q.shape[1], k.shape[1], q.shape[3]
    return (on_tpu and sq % 128 == 0 and sk % 128 == 0 and
            d in (64, 128, 256))
