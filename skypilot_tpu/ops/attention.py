"""Attention ops: XLA reference implementation + dispatch.

The XLA path is the correctness baseline and the grad path on CPU; on TPU
the Pallas flash kernel (ops/flash_attention.py) is used for the hot
forward/backward. GQA (grouped KV heads) handled by logical head repeat
folded into the einsum — no materialized K/V repeat.
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from skypilot_tpu.ops import dispatch
from skypilot_tpu.utils import env

NEG_INF = -1e9  # logits are f32 until softmax, so -1e9 never overflows


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True,
                  segment_ids: Optional[jax.Array] = None,
                  kv_segment_ids: Optional[jax.Array] = None,
                  q_offset: int = 0,
                  q_positions: Optional[jax.Array] = None,
                  softmax_scale: Optional[float] = None,
                  window: int = 0,
                  window_active=None,
                  logit_softcap: float = 0.0) -> jax.Array:
    """q: [B, Sq, Hq, D]; k,v: [B, Sk, Hkv, D]; Hq % Hkv == 0.

    Returns [B, Sq, Hq, D]. Logits and softmax in f32.

    q_positions: optional [B, Sq] global query positions for the causal
    mask (per-batch offsets — the KV-cache decode path); overrides
    q_offset. Keys are assumed at positions 0..Sk-1.

    window: sliding-window attention (Mistral / every other Gemma-2
    layer): query at position p also requires p - k_pos < window.
    window_active: optional traced BOOL — False disables the window
    restriction at runtime. This is how Gemma-2's alternating
    global/sliding layers stay a single homogeneous nn.scan body: the
    per-layer choice is arithmetic on the scanned layer index, not a
    Python branch.

    logit_softcap: Gemma-2 style soft-capping, cap*tanh(logits/cap),
    applied after the scale, before the mask.
    """
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    assert hq % hkv == 0, (hq, hkv)
    groups = hq // hkv
    scale = softmax_scale if softmax_scale is not None else d ** -0.5

    qg = q.reshape(b, sq, hkv, groups, d)
    logits = jnp.einsum('bqhgd,bkhd->bhgqk', qg, k,
                        preferred_element_type=jnp.float32)
    logits = logits * scale
    if logit_softcap > 0.0:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)

    k_pos = jnp.arange(sk)[None, None, None, None, :]
    if q_positions is not None:
        q_pos = q_positions[:, None, None, :, None]
    else:
        q_pos = (jnp.arange(sq) + q_offset)[None, None, None, :, None]
    mask = (q_pos >= k_pos) if (causal or q_positions is not None) \
        else None
    if window > 0:
        wmask = (q_pos - k_pos) < window
        if window_active is not None:
            wmask = wmask | jnp.logical_not(window_active)
        mask = wmask if mask is None else (mask & wmask)
    if segment_ids is not None:
        kv_seg = kv_segment_ids if kv_segment_ids is not None else segment_ids
        seg_mask = (segment_ids[:, None, None, :, None] ==
                    kv_seg[:, None, None, None, :])
        mask = seg_mask if mask is None else (mask & seg_mask)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum('bhgqk,bkhd->bqhgd', probs.astype(v.dtype), v)
    return out.reshape(b, sq, hq, d)


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = True,
              segment_ids: Optional[jax.Array] = None,
              impl: str = 'auto',
              window: int = 0,
              window_active=None,
              logit_softcap: float = 0.0,
              softmax_scale: Optional[float] = None) -> jax.Array:
    """Public entry: eager autotune hook + the jit'd dispatch ladder.

    When SKYT_AUTOTUNE=1 and the inputs are CONCRETE (not tracers —
    i.e. this call is at setup/bench time, not inside a model trace),
    a flash block-size sweep runs first for this shape if the autotune
    cache has no entry; the jit'd ladder below then reads the winner.
    One env check when disabled."""
    flash_unsupported = (logit_softcap > 0.0 or
                         softmax_scale is not None or
                         (window > 0 and window_active is not None))
    if impl in ('auto', 'flash') and not flash_unsupported:
        from skypilot_tpu.ops import autotune
        # Gate the sweep on the SAME impl resolution the ladder uses:
        # sweeping a shape whose dispatch resolves to the XLA path
        # would burn minutes populating a cache entry nothing reads.
        if (autotune.enabled() and not dispatch.is_tracer(q) and
                _resolve_impl(q, k, impl, window, window_active,
                              flash_unsupported,
                              segment_ids is not None) == 'flash'):
            autotune.maybe_sweep_flash(q, k, v, causal=causal,
                                       segment_ids=segment_ids,
                                       window=window)
    return _attention(q, k, v, causal=causal, segment_ids=segment_ids,
                      impl=impl, window=window,
                      window_active=window_active,
                      logit_softcap=logit_softcap,
                      softmax_scale=softmax_scale)


@functools.partial(jax.jit, static_argnames=('causal', 'impl', 'window',
                                             'logit_softcap',
                                             'softmax_scale'))
def _attention(q: jax.Array, k: jax.Array, v: jax.Array,
               causal: bool = True,
               segment_ids: Optional[jax.Array] = None,
               impl: str = 'auto',
               window: int = 0,
               window_active=None,
               logit_softcap: float = 0.0,
               softmax_scale: Optional[float] = None) -> jax.Array:
    """Dispatch: 'auto' prefers the Pallas flash kernel on TPU when
    shapes allow, else the XLA reference — and every Pallas choice now
    runs through the fallback ladder (ops/dispatch.py): tuned-Pallas →
    default-Pallas → conservative full-array-block Pallas → XLA
    reference, with the selected path recorded in
    skyt_ops_kernel_path_total{op,path} and on the current trace span.
    Soft-capped/rescaled attention (Gemma-2) always takes the XLA path
    — the flash kernel does not implement them, and a silent
    wrong-math fast path is worse than a slower correct one. A STATIC
    sliding window (Mistral, Phi-3) has a flash implementation
    (O(S*window) block visits) behind SKYT_WINDOW_FLASH=on — opt-in
    until the on-chip gate proves the lowering (the same discipline
    the paged MQ kernel went through); Gemma-2's per-layer traced
    window gate (window_active) stays XLA either way (the skip
    predicate must be static-per-kernel). Explicit impl='flash' with a
    static window honors the request without the env gate (it IS the
    opt-in). NOTE: like the other SKYT_* kernel gates, env vars are
    read at TRACE time — under an outer jit (the model) the choice is
    baked into the compiled program, so set them before the process
    builds its engines, not mid-run."""
    flash_unsupported = (logit_softcap > 0.0 or
                         softmax_scale is not None or
                         (window > 0 and window_active is not None))
    impl = _resolve_impl(q, k, impl, window, window_active,
                         flash_unsupported, segment_ids is not None)

    def xla():
        return mha_reference(q, k, v, causal=causal,
                             segment_ids=segment_ids, window=window,
                             window_active=window_active,
                             logit_softcap=logit_softcap,
                             softmax_scale=softmax_scale)

    if impl == 'flash':
        if flash_unsupported:
            offender = ('logit_softcap' if logit_softcap > 0.0 else
                        'softmax_scale' if softmax_scale is not None
                        else 'a traced window gate (window_active)')
            raise ValueError(
                f'flash attention does not support {offender}')
        from skypilot_tpu.ops import autotune
        from skypilot_tpu.ops import flash_attention as flash_lib
        sq, sk = q.shape[1], k.shape[1]
        has_seg = segment_ids is not None

        def rung(bq, bk):
            return lambda: flash_lib.flash_attention(
                q, k, v, causal=causal, segment_ids=segment_ids,
                block_q=bq, block_k=bk, window=window)

        rungs = []
        tuned = autotune.lookup_flash(q.shape, k.shape, q.dtype,
                                      causal, has_seg, window)
        if tuned is not None and tuned != (flash_lib.DEFAULT_BLOCK_Q,
                                           flash_lib.DEFAULT_BLOCK_K):
            rungs.append(('pallas_tuned', rung(*tuned)))
        rungs.append(('pallas', rung(flash_lib.DEFAULT_BLOCK_Q,
                                     flash_lib.DEFAULT_BLOCK_K)))
        eff = dispatch.flash_blocks(sq, sk, flash_lib.DEFAULT_BLOCK_Q,
                                    flash_lib.DEFAULT_BLOCK_K,
                                    q.dtype, has_seg)
        if eff != (sq, sk):   # else 'pallas' IS the full-block rung
            rungs.append(('pallas_full', rung(sq, sk)))
        rungs.append(('xla', xla))
        return dispatch.run_ladder('flash_attention', rungs)
    # 'xla_native': XLA is the CORRECT path for this op (softcap /
    # scale / traced window / auto-resolved shape), not ladder
    # degradation — keep it distinguishable from the 'xla' floor so
    # operators (and tpu_validation's scrape) don't learn to ignore
    # the real degradation signal.
    return dispatch.run_ladder('attention', [('xla_native', xla)])


def _resolve_impl(q, k, impl: str, window: int, window_active,
                  flash_unsupported: bool, has_seg: bool) -> str:
    """The 'auto' gate, shared by the eager autotune hook and the
    jit'd ladder so both agree on whether flash is in play."""
    if impl != 'auto':
        return impl
    window_flash = (window > 0 and window_active is None and
                    env.get('SKYT_WINDOW_FLASH', 'off') == 'on')
    auto_xla = flash_unsupported or (window > 0 and not window_flash)
    return ('flash' if not auto_xla and _flash_ok(q, k, has_seg)
            else 'xla')


def _flash_ok(q: jax.Array, k: jax.Array, has_seg: bool = False) -> bool:
    """Auto-dispatch gate: shapes where the flash kernel is expected
    to WIN on TPU (tile-aligned seqs, MXU-friendly head dim, blocks
    that fit VMEM). Any shape outside this set still works — it takes
    the XLA reference rung instead, and an explicit impl='flash' gets
    the shape-robust clamped blocks. has_seg matters: packed-sequence
    blocks must be 128-aligned or full-array, so a seq that clamps to
    a full-array block can blow the VMEM guard that a seg-less probe
    would pass."""
    try:
        on_tpu = jax.devices()[0].platform == 'tpu'
    except Exception:  # pylint: disable=broad-except
        on_tpu = False
    sq, sk, d = q.shape[1], k.shape[1], q.shape[3]
    if not (on_tpu and sq % 8 == 0 and sk % 8 == 0 and
            d % 64 == 0 and d <= 512):
        return False
    from skypilot_tpu.ops import flash_attention as flash_lib
    bq, bk = dispatch.flash_blocks(sq, sk, flash_lib.DEFAULT_BLOCK_Q,
                                   flash_lib.DEFAULT_BLOCK_K,
                                   q.dtype, has_seg)
    return dispatch.flash_vmem_ok(bq, bk, d,
                                  jnp.dtype(q.dtype).itemsize)
