"""Attention ops: XLA reference implementation + dispatch.

The XLA path is the correctness baseline and the grad path on CPU; on TPU
the Pallas flash kernel (ops/flash_attention.py) is used for the hot
forward/backward. GQA (grouped KV heads) handled by logical head repeat
folded into the einsum — no materialized K/V repeat.
"""
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # logits are f32 until softmax, so -1e9 never overflows


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True,
                  segment_ids: Optional[jax.Array] = None,
                  kv_segment_ids: Optional[jax.Array] = None,
                  q_offset: int = 0,
                  q_positions: Optional[jax.Array] = None,
                  softmax_scale: Optional[float] = None,
                  window: int = 0,
                  window_active=None,
                  logit_softcap: float = 0.0) -> jax.Array:
    """q: [B, Sq, Hq, D]; k,v: [B, Sk, Hkv, D]; Hq % Hkv == 0.

    Returns [B, Sq, Hq, D]. Logits and softmax in f32.

    q_positions: optional [B, Sq] global query positions for the causal
    mask (per-batch offsets — the KV-cache decode path); overrides
    q_offset. Keys are assumed at positions 0..Sk-1.

    window: sliding-window attention (Mistral / every other Gemma-2
    layer): query at position p also requires p - k_pos < window.
    window_active: optional traced BOOL — False disables the window
    restriction at runtime. This is how Gemma-2's alternating
    global/sliding layers stay a single homogeneous nn.scan body: the
    per-layer choice is arithmetic on the scanned layer index, not a
    Python branch.

    logit_softcap: Gemma-2 style soft-capping, cap*tanh(logits/cap),
    applied after the scale, before the mask.
    """
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    assert hq % hkv == 0, (hq, hkv)
    groups = hq // hkv
    scale = softmax_scale if softmax_scale is not None else d ** -0.5

    qg = q.reshape(b, sq, hkv, groups, d)
    logits = jnp.einsum('bqhgd,bkhd->bhgqk', qg, k,
                        preferred_element_type=jnp.float32)
    logits = logits * scale
    if logit_softcap > 0.0:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)

    k_pos = jnp.arange(sk)[None, None, None, None, :]
    if q_positions is not None:
        q_pos = q_positions[:, None, None, :, None]
    else:
        q_pos = (jnp.arange(sq) + q_offset)[None, None, None, :, None]
    mask = (q_pos >= k_pos) if (causal or q_positions is not None) \
        else None
    if window > 0:
        wmask = (q_pos - k_pos) < window
        if window_active is not None:
            wmask = wmask | jnp.logical_not(window_active)
        mask = wmask if mask is None else (mask & wmask)
    if segment_ids is not None:
        kv_seg = kv_segment_ids if kv_segment_ids is not None else segment_ids
        seg_mask = (segment_ids[:, None, None, :, None] ==
                    kv_seg[:, None, None, None, :])
        mask = seg_mask if mask is None else (mask & seg_mask)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum('bhgqk,bkhd->bqhgd', probs.astype(v.dtype), v)
    return out.reshape(b, sq, hq, d)


@functools.partial(jax.jit, static_argnames=('causal', 'impl', 'window',
                                             'logit_softcap',
                                             'softmax_scale'))
def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = True,
              segment_ids: Optional[jax.Array] = None,
              impl: str = 'auto',
              window: int = 0,
              window_active=None,
              logit_softcap: float = 0.0,
              softmax_scale: Optional[float] = None) -> jax.Array:
    """Dispatch: 'auto' uses the Pallas flash kernel on TPU when shapes
    allow, else the XLA reference. Soft-capped/rescaled attention
    (Gemma-2) always takes the XLA path — the flash kernel does not
    implement them, and a silent wrong-math fast path is worse than a
    slower correct one. A STATIC sliding window (Mistral, Phi-3) has a
    flash implementation (O(S*window) block visits) behind
    SKYT_WINDOW_FLASH=on — opt-in until the on-chip gate proves the
    lowering (the same discipline the paged MQ kernel went through);
    Gemma-2's per-layer traced window gate (window_active) stays XLA
    either way (the skip predicate must be static-per-kernel).
    Explicit impl='flash' with a static window honors the request
    without the env gate (it IS the opt-in). NOTE: like the other
    SKYT_* kernel gates, the env var is read at TRACE time — under an
    outer jit (the model) the choice is baked into the compiled
    program, so set it before the process builds its engines, not
    mid-run."""
    flash_unsupported = (logit_softcap > 0.0 or
                         softmax_scale is not None or
                         (window > 0 and window_active is not None))
    window_flash = (window > 0 and window_active is None and
                    os.environ.get('SKYT_WINDOW_FLASH', 'off') == 'on')
    if impl == 'auto':
        auto_xla = flash_unsupported or (window > 0 and
                                         not window_flash)
        impl = 'flash' if not auto_xla and _flash_ok(q, k) else 'xla'
    if impl == 'flash':
        if flash_unsupported:
            offender = ('logit_softcap' if logit_softcap > 0.0 else
                        'softmax_scale' if softmax_scale is not None
                        else 'a traced window gate (window_active)')
            raise ValueError(
                f'flash attention does not support {offender}')
        from skypilot_tpu.ops import flash_attention
        return flash_attention.flash_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            window=window)
    return mha_reference(q, k, v, causal=causal, segment_ids=segment_ids,
                         window=window, window_active=window_active,
                         logit_softcap=logit_softcap,
                         softmax_scale=softmax_scale)


def _flash_ok(q: jax.Array, k: jax.Array) -> bool:
    try:
        on_tpu = jax.devices()[0].platform == 'tpu'
    except Exception:  # pylint: disable=broad-except
        on_tpu = False
    sq, sk, d = q.shape[1], k.shape[1], q.shape[3]
    return (on_tpu and sq % 128 == 0 and sk % 128 == 0 and
            d in (64, 128, 256))
