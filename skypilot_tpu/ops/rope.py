"""Rotary position embeddings (RoPE), including the Llama-3.1 frequency
scaling. Pure function of (positions, head_dim); computed in f32 and applied
via the split-half rotation (the HF/Llama convention, not interleaved).
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=('head_dim', 'theta',
                                             'use_llama31_scaling'))
def rope_freqs(positions: jax.Array, head_dim: int,
               theta: float = 500000.0,
               use_llama31_scaling: bool = False):
    """Return (cos, sin) of shape positions.shape + (head_dim//2,)."""
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                        dtype=jnp.float32) / head_dim))
    if use_llama31_scaling:
        # Llama-3.1 long-context NTK-by-parts scaling (factor 8, original
        # context 8192), reference implementation in Meta's llama3 repo.
        factor, low_mult, high_mult, old_ctx = 8.0, 1.0, 4.0, 8192
        low = old_ctx / low_mult
        high = old_ctx / high_mult
        wavelen = 2.0 * jnp.pi / freqs
        smooth = jnp.clip((old_ctx / wavelen - low_mult) /
                          (high_mult - low_mult), 0.0, 1.0)
        scaled = jnp.where(wavelen > low, freqs / factor, freqs)
        mid = (1.0 - smooth) * freqs / factor + smooth * freqs
        in_mid = (wavelen <= low) & (wavelen >= high)
        freqs = jnp.where(in_mid, mid, scaled)
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [..., seq, heads, head_dim]; cos/sin: [..., seq, head_dim//2]."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    # broadcast cos/sin over the heads axis
    c = cos[..., None, :]
    s = sin[..., None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)


def positions_from_segment_ids(
        segment_ids: Optional[jax.Array], batch: int,
        seq: int) -> jax.Array:
    """Default positions 0..seq-1 per example (packing-aware later)."""
    if segment_ids is None:
        return jnp.broadcast_to(jnp.arange(seq), (batch, seq))
    # restart positions at each segment boundary
    def per_example(seg):
        def step(carry, s):
            prev_seg, pos = carry
            pos = jnp.where(s == prev_seg, pos + 1, 0)
            return (s, pos), pos
        (_, _), out = jax.lax.scan(step, (seg[0], -1), seg)
        return out
    return jax.vmap(per_example)(segment_ids)
