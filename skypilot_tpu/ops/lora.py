"""Grouped multi-LoRA delta as a Pallas TPU kernel + dispatch ladder.

The batched multi-LoRA delta (models/llama.py ``_lora_delta``) is two
rank-r contractions per projection, preceded by a per-sequence gather
of each request's A/B out of the stacked ``lora`` collection. The XLA
path materializes the gathered [B, in, r] / [B, r, out] operands in
HBM before contracting; this module fuses the gather INTO the kernel —
the adapter id rides a scalar-prefetched BlockSpec index map (the same
trick the paged attention kernels use for block tables), so each grid
step DMAs only its own sequence's A/B slices straight from the stack.

Two input shapes, one op (``lora_grouped`` in
``skyt_ops_kernel_path_total``):

* per-sequence ids (``lora_ids`` of shape [B] — the decode path and
  uniform prefill rows): grid (B, S-blocks), A/B blocks selected by
  ``ids[b]`` at index-map time; no accumulation, each grid step owns
  its output block.
* per-token ids (``lora_ids`` of shape [B, S] — ragged prefill packs
  mixing adapters in one packed row): tokens flatten to [T, in] and
  the grid becomes (T-blocks, adapters) with adapters innermost; each
  adapter pass masks the token block to its own segments and
  accumulates into the output block (init under ``pl.when(k == 0)``).

The final rung is the pure-XLA floor: for per-sequence ids the exact
gather-einsum the model ran before this op existed; for per-token ids
a ``lax.scan`` over adapters with the same mask-and-accumulate math
(gathering per token would materialize [B, S, in, r]). The per-id
alpha/rank scale is applied OUTSIDE the kernels, as the floor's final
multiply, so every rung shares that op byte-for-byte. Ladder
selection, fault injection (``ops.lowering``), and path accounting
ride ops/dispatch.py; block sizes are swept through the generic
``autotune.sweep`` helper.
"""
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from skypilot_tpu.ops import autotune
from skypilot_tpu.ops import dispatch

_CompilerParams = getattr(pltpu, 'CompilerParams',
                          getattr(pltpu, 'TPUCompilerParams', None))

OP = 'lora_grouped'

# Candidate token/seq block extents, pruned per shape by legality.
_CANDIDATE_BLOCKS = (128, 256, 512)
_DEFAULT_BLOCK = 256


def _interpret_mode() -> bool:
    try:
        return jax.devices()[0].platform != 'tpu'
    except Exception:  # pylint: disable=broad-except
        return True


# ------------------------------------------------------------ kernels
def _gather_kernel(ids_ref, x_ref, a_ref, b_ref, o_ref):
    """Per-sequence ids: one grid step = one (sequence, seq-block);
    the A/B blocks arriving here were already selected by ids[b] in
    the BlockSpec index maps — the gather happened in the DMA."""
    del ids_ref  # consumed by the index maps
    x = x_ref[0]                               # [bs, in]
    t = jnp.dot(x, a_ref[0].astype(x.dtype))   # [bs, r]
    o_ref[0] = jnp.dot(t, b_ref[0].astype(x.dtype))


def _grouped_kernel(x_ref, ids_ref, a_ref, b_ref, o_ref):
    """Per-token ids: grid (T-blocks, adapters), adapters innermost so
    the output block stays resident across the accumulation sweep.
    Adapter 0 is the zeros no-op entry: its pass adds exact zeros, so
    no special-casing is needed for parity with the floor."""
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    x = x_ref[:]                                   # [bt, in]
    mask = (ids_ref[:] == k).astype(x.dtype)       # [bt, 1]
    t = jnp.dot(x * mask, a_ref[0].astype(x.dtype))
    o_ref[:] += jnp.dot(t, b_ref[0].astype(x.dtype))


# ----------------------------------------------------- pallas wrappers
@functools.partial(jax.jit, static_argnames=('block_s', 'interpret'))
def _pallas_gather(x, a, b, lora_ids, lora_scale, block_s: int,
                   interpret: Optional[bool] = None) -> jax.Array:
    bsz, seq, din = x.shape
    r = a.shape[-1]
    dout = b.shape[-1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bsz, seq // block_s),
        in_specs=[
            pl.BlockSpec((1, block_s, din), lambda bi, j, ids: (bi, j, 0)),
            pl.BlockSpec((1, din, r), lambda bi, j, ids: (ids[bi], 0, 0)),
            pl.BlockSpec((1, r, dout), lambda bi, j, ids: (ids[bi], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_s, dout),
                               lambda bi, j, ids: (bi, j, 0)),
    )
    d = pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bsz, seq, dout), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel')),
        interpret=_interpret_mode() if interpret is None else interpret,
    )(lora_ids.astype(jnp.int32), x, a, b)
    return d * lora_scale[:, None, None].astype(x.dtype)


@functools.partial(jax.jit, static_argnames=('block_t', 'interpret'))
def _pallas_grouped(x, a, b, lora_ids, lora_scale, block_t: int,
                    interpret: Optional[bool] = None) -> jax.Array:
    bsz, seq, din = x.shape
    n, _, r = a.shape
    dout = b.shape[-1]
    tok = bsz * seq
    xt = x.reshape(tok, din)
    ids = lora_ids.reshape(tok, 1).astype(jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(tok // block_t, n),
        in_specs=[
            pl.BlockSpec((block_t, din), lambda j, k: (j, 0)),
            pl.BlockSpec((block_t, 1), lambda j, k: (j, 0)),
            pl.BlockSpec((1, din, r), lambda j, k: (k, 0, 0)),
            pl.BlockSpec((1, r, dout), lambda j, k: (k, 0, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, dout), lambda j, k: (j, 0)),
    )
    d = pl.pallas_call(
        _grouped_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((tok, dout), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'arbitrary')),
        interpret=_interpret_mode() if interpret is None else interpret,
    )(xt, ids, a, b)
    return d.reshape(bsz, seq, dout) * \
        lora_scale[..., None].astype(x.dtype)


# --------------------------------------------------------- XLA floors
def _xla_gather(x, a, b, lora_ids, lora_scale) -> jax.Array:
    """The exact einsum path _lora_delta ran before this op existed —
    the correctness floor per-sequence requests must stay byte-
    identical to."""
    dtype = x.dtype
    ga = jnp.take(a, lora_ids, axis=0).astype(dtype)    # [B, in, r]
    gb = jnp.take(b, lora_ids, axis=0).astype(dtype)    # [B, r, out]
    t = jnp.einsum('bsi,bir->bsr', x, ga)
    d = jnp.einsum('bsr,bro->bso', t, gb)
    return d * lora_scale[:, None, None].astype(dtype)


def _xla_grouped(x, a, b, lora_ids, lora_scale) -> jax.Array:
    """Per-token floor: scan over adapters with mask-and-accumulate —
    a per-token gather would materialize [B, S, in, r]. Adapter 0 is
    skipped (zeros by construction; its tokens contribute exactly 0)."""
    dtype = x.dtype
    n = a.shape[0]
    dout = b.shape[-1]
    acc0 = jnp.zeros(x.shape[:2] + (dout,), dtype)
    if n <= 1:
        return acc0

    def body(acc, k):
        mask = (lora_ids == k).astype(dtype)            # [B, S]
        t = jnp.einsum('bsi,ir->bsr', x * mask[..., None],
                       a[k].astype(dtype))
        d = jnp.einsum('bsr,ro->bso', t, b[k].astype(dtype))
        return acc + d, None

    acc, _ = jax.lax.scan(body, acc0, jnp.arange(1, n))
    return acc * lora_scale[..., None].astype(dtype)


# ------------------------------------------------------------ autotune
def _tune_key(mode: str, tokens: int, din: int, r: int, dout: int,
              n: int, dtype) -> str:
    bucket = (f'{mode}.t{dispatch.shape_bucket(tokens)}.i{din}.r{r}'
              f'.o{dout}.n{dispatch.shape_bucket(n)}')
    return (f'{dispatch.device_kind()}|{OP}|{bucket}'
            f'|{jnp.dtype(dtype).name}')


def _block_candidates(dim: int, dtype) -> Tuple[int, ...]:
    mult = dispatch.sublane_multiple(dtype)
    out = []
    for want in _CANDIDATE_BLOCKS:
        cand = dispatch.choose_block(dim, want, mult)
        if cand not in out:
            out.append(cand)
    if dim not in out:
        out.append(dim)
    return tuple(out)


def _tuned_block(mode: str, dim: int, tokens: int, din: int, r: int,
                 dout: int, n: int, dtype) -> int:
    """Trace-time cache read: tuned block extent, else the clamped
    default. Shapes are concrete even on tracers."""
    entry = autotune.get_cache().get(
        _tune_key(mode, tokens, din, r, dout, n, dtype))
    if entry:
        try:
            blk = int(entry['block'])
            if dispatch.block_dim_ok(blk, dim,
                                     dispatch.sublane_multiple(dtype)):
                return blk
        except (KeyError, TypeError, ValueError):
            pass   # stale/hand-edited entry: behave as a miss
    return dispatch.choose_block(dim, _DEFAULT_BLOCK,
                                 dispatch.sublane_multiple(dtype))


def maybe_sweep_lora(x, a, b, lora_ids, lora_scale) -> None:
    """Sweep block extents for this shape if enabled, concrete, and
    not already cached (autotune.sweep semantics: cache-hit skip,
    failures skipped, all-fail negative-cached)."""
    if not autotune.enabled() or dispatch.is_tracer(x):
        return
    bsz, seq, din = x.shape
    n, _, r = a.shape
    dout = b.shape[-1]
    per_token = lora_ids.ndim == 2
    mode = 'tok' if per_token else 'seq'
    dim = bsz * seq if per_token else seq
    tokens = bsz * seq
    key = _tune_key(mode, tokens, din, r, dout, n, x.dtype)

    def run(cand):
        if per_token:
            out = _pallas_grouped(x, a, b, lora_ids, lora_scale, cand)
        else:
            out = _pallas_gather(x, a, b, lora_ids, lora_scale, cand)
        out.block_until_ready()

    autotune.sweep(OP, key, _block_candidates(dim, x.dtype), run,
                   lambda cand: {'block': cand})


# ------------------------------------------------------------ dispatch
def _vmem_bytes(block: int, din: int, r: int, dout: int,
                itemsize: int) -> int:
    """Per-invocation VMEM working set: x/out token blocks + one
    adapter's A/B + the rank-r intermediate."""
    io = (block * din + block * dout + din * r + r * dout) * itemsize
    return io + block * r * itemsize


def grouped_lora_delta(x, a, b, lora_ids, lora_scale) -> jax.Array:
    """Batched multi-LoRA delta through the dispatch ladder.

    x: [B, S, in] activations (model dtype); a: [N, in, r] stacked
    down-projections; b: [N, r, out]; lora_ids: [B] (per-sequence) or
    [B, S] (per-token, ragged mixed packs) int adapter ids;
    lora_scale: alpha/rank per id, same shape as lora_ids. Returns the
    [B, S, out] delta in x's dtype."""
    maybe_sweep_lora(x, a, b, lora_ids, lora_scale)
    bsz, seq, din = x.shape
    n, _, r = a.shape
    dout = b.shape[-1]
    per_token = lora_ids.ndim == 2
    itemsize = jnp.dtype(x.dtype).itemsize
    mult = dispatch.sublane_multiple(x.dtype)
    tokens = bsz * seq

    rungs = []
    if per_token:
        blk = _tuned_block('tok', tokens, tokens, din, r, dout, n,
                           x.dtype)
        if dispatch.block_dim_ok(blk, tokens, mult) and \
                _vmem_bytes(blk, din, r, dout, itemsize) <= \
                dispatch.VMEM_BUDGET_BYTES:
            rungs.append(('pallas', functools.partial(
                _pallas_grouped, x, a, b, lora_ids, lora_scale, blk)))
        rungs.append(('xla', functools.partial(
            _xla_grouped, x, a, b, lora_ids, lora_scale)))
    else:
        blk = _tuned_block('seq', seq, tokens, din, r, dout, n,
                           x.dtype)
        if dispatch.block_dim_ok(blk, seq, mult) and \
                _vmem_bytes(blk, din, r, dout, itemsize) <= \
                dispatch.VMEM_BUDGET_BYTES:
            rungs.append(('pallas', functools.partial(
                _pallas_gather, x, a, b, lora_ids, lora_scale, blk)))
        rungs.append(('xla', functools.partial(
            _xla_gather, x, a, b, lora_ids, lora_scale)))
    return dispatch.run_ladder(OP, rungs)
