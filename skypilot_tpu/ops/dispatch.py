"""Kernel dispatch: shape-robust block selection + a runtime fallback
ladder so the kernel layer NEVER crashes on a legal input shape.

Why this exists: Mosaic (the Pallas TPU backend) requires the last two
dims of every block to be divisible by (8, 128) — or equal to the
array's dims (jax _check_block_mappings; the exact rule this module
mirrors in ``block_dim_ok``). ``BENCH_r02.json`` shows the flash
kernel hard-crashing TPU lowering on a decode-shaped block, which
zeroed the headline MFU metric for three rounds. Device-specific
lowering rules must never be able to take down a train step or a
serve replica — a slower correct path always exists.

Two pieces:

* **Divisibility-safe block selection** (``choose_block``): clamp a
  requested block size to the largest legal divisor of the dim, or
  fall back to the full array dim (always legal by the "equal" arm of
  the Mosaic rule). Kernels built this way are statically legal — the
  class of failure in BENCH_r02 cannot be constructed.

* **A fallback ladder** (``run_ladder``): tuned-Pallas →
  conservative-Pallas (full-array blocks) → pure-XLA reference,
  selected at TRACE time. Each non-final rung carries the
  ``ops.lowering`` fault point, so ``SKYT_FAULTS=ops.lowering=error``
  forces ladder descent — the whole subsystem is chaos-testable on
  CPU while the TPU tunnel is down. The chosen path is recorded in
  ``skyt_ops_kernel_path_total{op,path}`` and as an attribute on the
  current trace span, so silent degradation is VISIBLE in the
  metrics/tracing plane (docs/kernels.md).

Trace-time semantics: the ladder runs while jax traces the enclosing
jit, i.e. once per compiled (shape, dtype) — the counter measures
compilations, not calls, and re-arming faults after a shape has
compiled does not change its baked-in path. Lowering errors raised by
the Mosaic compiler itself (AFTER tracing) cannot be caught here —
that is exactly why rung selection is static-validation-first: a rung
is only offered if its block specs pass the mirrored legality rule.
"""
import math
import threading
from typing import Any, Callable, Dict, List, Tuple

from skypilot_tpu.utils import log_utils
from skypilot_tpu.utils import metrics as metrics_lib
from skypilot_tpu.utils import env

logger = log_utils.init_logger(__name__)

LANES = 128

# Minimum second-minor (sublane) tile per dtype itemsize
# (pallas_guide.md: f32 (8,128), bf16 (16,128), int8/fp8 (32,128)).
# Mosaic's block-mapping check only demands 8, but a block aligned to
# the dtype's real tile never hits packing slow paths.
_SUBLANE_BY_ITEMSIZE = {4: 8, 2: 16, 1: 32}

# A Pallas rung whose VMEM working set exceeds this is not offered:
# a compile-time OOM inside Mosaic is as fatal as an illegal block
# (and as invisible to a trace-time try/except). v5e has 16MB less
# scratch overheads.
VMEM_BUDGET_BYTES = int(
    env.get('SKYT_OPS_VMEM_BUDGET', str(12 * 1024 * 1024)))

_ENV_FORCE = 'SKYT_OPS_FORCE_PATH'

_lock = threading.Lock()
# op -> most recently selected path (trace-time); surfaced in engine
# /stats and flight-recorder snapshots.
_paths: Dict[str, str] = {}


def sublane_multiple(dtype) -> int:
    """Preferred sublane alignment for a dtype (8/16/32)."""
    import jax.numpy as jnp
    return _SUBLANE_BY_ITEMSIZE.get(jnp.dtype(dtype).itemsize, 8)


def block_dim_ok(block: int, dim: int, multiple: int) -> bool:
    """One dim of the Mosaic last-two-dims rule: the block extent must
    be a multiple of the tile (8 sublane / 128 lane) or equal to the
    array dim. Our kernels' index maps additionally assume blocks
    divide the dim exactly."""
    if block == dim:
        return True
    return block % multiple == 0 and dim % block == 0


def choose_block(dim: int, want: int, multiple: int = 8) -> int:
    """Largest legal block <= want for an array dim: a multiple of
    `multiple` that divides `dim`, else the full dim (always legal).

    This is the divisibility-safe selection that makes decode shapes
    (e.g. sq=8 with a 256 default) lower instead of raising."""
    want = min(want, dim)
    if want <= 0 or want == dim:
        return dim
    # Largest multiple of `multiple` <= want that divides dim.
    for cand in range(want - want % multiple, 0, -multiple):
        if dim % cand == 0:
            return cand
    return dim


def flash_blocks(sq: int, sk: int, want_q: int, want_k: int,
                 q_dtype, has_seg: bool) -> Tuple[int, int]:
    """Legal (block_q, block_k) for the flash kernels.

    Segment-id blocks place the seq extent in the LANE position
    ([b, 1, s] layout), so with packed sequences the seq blocks must
    be 128-aligned (or full); without, the q/k blocks only need the
    dtype's sublane alignment."""
    mult = LANES if has_seg else sublane_multiple(q_dtype)
    return (choose_block(sq, want_q, mult), choose_block(sk, want_k, mult))


def flash_vmem_bytes(block_q: int, block_k: int, d: int,
                     itemsize: int) -> int:
    """Rough per-invocation VMEM working set of the flash forward:
    q/k/v/out blocks + f32 scratch (acc, m, l, lse) + the f32 score
    block. The backward's is the same order of magnitude."""
    io = (block_q * d * 2 + block_k * d * 2) * itemsize
    scratch = (block_q * d + block_q * 2 + block_q * LANES) * 4
    scores = block_q * block_k * 4
    return io + scratch + scores


def flash_vmem_ok(block_q: int, block_k: int, d: int, itemsize: int) -> bool:
    return flash_vmem_bytes(block_q, block_k, d,
                            itemsize) <= VMEM_BUDGET_BYTES


def is_tracer(x: Any) -> bool:
    """True when x is a jax tracer (inside jit/grad tracing) — i.e.
    its VALUES are not available, only shape/dtype."""
    import jax
    return isinstance(x, jax.core.Tracer)


def _counter() -> 'metrics_lib.Counter':
    return metrics_lib.REGISTRY.counter(
        'skyt_ops_kernel_path_total',
        'Kernel dispatch path selected at trace time', ('op', 'path'))


def record_path(op: str, path: str) -> None:
    """Count + remember the selected path and stamp it on the current
    trace span so a degraded kernel is visible on flight-recorded
    traces, not just in aggregate."""
    _counter().labels(op, path).inc()
    with _lock:
        _paths[op] = path
    from skypilot_tpu.utils import tracing
    span = tracing.current_span()
    if span is not None:
        span.set_attribute(f'ops.path.{op}', path)


def snapshot() -> Dict[str, str]:
    """op -> last selected path (engine /stats + flight recorder)."""
    with _lock:
        return dict(_paths)


def run_ladder(op: str,
               rungs: List[Tuple[str, Callable[[], Any]]]) -> Any:
    """Run the first rung that works; record which one did.

    Each rung is (path_name, thunk). Non-final rungs carry the
    ``ops.lowering`` fault point (attrs: op, path — target one rung
    with ``where=path:<name>``) and any exception they raise at trace
    time descends the ladder with a warning. The FINAL rung is the
    correctness floor (pure XLA): it is not fault-injected and its
    errors propagate — there is nothing further to fall back to.

    SKYT_OPS_FORCE_PATH=<name> keeps only that rung plus the final
    one (debug escape hatch; an unknown name is ignored loudly).
    """
    if not rungs:
        raise ValueError(f'ops.{op}: empty dispatch ladder')
    forced = env.get(_ENV_FORCE, '')
    if forced and len(rungs) > 1:
        kept = [r for r in rungs if r[0] == forced]
        if kept:
            if rungs[-1][0] != forced:
                kept.append(rungs[-1])
            rungs = kept
        elif forced != rungs[-1][0]:
            logger.warning('%s=%r matches no rung of ops.%s (have %s)',
                           _ENV_FORCE, forced, op, [r[0] for r in rungs])
    from skypilot_tpu.utils import faults
    last = len(rungs) - 1
    for i, (path, thunk) in enumerate(rungs):
        try:
            if i < last:
                faults.inject('ops.lowering', op=op, path=path)
            out = thunk()
        except Exception as e:  # pylint: disable=broad-except
            if i == last:
                record_path(op, 'error')
                raise
            logger.warning(
                'ops.%s: %r path failed at trace time (%s: %s); '
                'falling back to %r', op, path, type(e).__name__, e,
                rungs[i + 1][0])
            continue
        record_path(op, path)
        return out
    raise AssertionError('unreachable')


def shape_bucket(n: int) -> int:
    """Round a dim up to the next power of two (autotune cache keys
    bucket shapes so one sweep covers the whole padded-bucket family)."""
    if n <= 1:
        return 1
    return 1 << math.ceil(math.log2(n))


def device_kind() -> str:
    """Device kind for autotune cache keys ('TPU v5 lite', 'cpu', ...);
    never raises — an unreachable backend reads as 'unknown'."""
    import jax
    try:
        return getattr(jax.devices()[0], 'device_kind',
                       jax.devices()[0].platform)
    except Exception:  # pylint: disable=broad-except
        return 'unknown'


def reset_for_tests() -> None:
    """Clear the path snapshot (unit tests)."""
    with _lock:
        _paths.clear()
