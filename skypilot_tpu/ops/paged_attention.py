"""Paged decode attention as a Pallas TPU kernel.

One decode step attends each slot's single query against that slot's
pages of the shared KV pool. The XLA fallback materializes a contiguous
[slots, max_seq, H, d] view per layer (gather + write + re-read ≈ 3x the
KV bytes); this kernel DMAs exactly the pages each slot owns, selected
by a SCALAR-PREFETCHED block table in the k/v BlockSpec index maps — the
vLLM-paged-attention idea expressed the Pallas way
(pltpu.PrefetchScalarGridSpec; pallas_guide.md §PrefetchScalarGridSpec).

Grid: (slots, pages) — ONE block per page carrying ALL kv heads
([H, P, d], page-major pool layout), pages innermost ('arbitrary') so
the flash-style running-softmax scratch (m, l, acc) persists across a
slot's pages. A first cut used grid (slots, heads, pages) with [P, d]
blocks; at decode sizes the per-invocation + DMA-issue overhead of
slots*heads*pages tiny kernels made it SLOWER than the XLA gather —
folding heads into the block cut invocations 8x and made the DMAs 8x
bigger. Per-page work is skipped when the page is past the slot's
current length or not reserved (unreserved block-table entries are 0,
the dummy page). GQA: q heads of one kv head ride the sublane axis of
the [H, G, d] query block; the in-kernel matmuls batch over H.

Reference counterpart: none (the reference delegates to vLLM's CUDA
paged attention).
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams (~0.5); support both
# so the paged path works on whichever jax the image ships.
_CompilerParams = getattr(pltpu, 'CompilerParams',
                          getattr(pltpu, 'TPUCompilerParams', None))

NEG_INF = -1e30
LANES = 128


def _interpret_mode() -> bool:
    try:
        return jax.devices()[0].platform != 'tpu'
    except Exception:  # pylint: disable=broad-except
        return True


def _kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
            m_scr, l_scr, acc_scr, *, page_size: int, num_pages: int,
            scale: float):
    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = lens_ref[s]            # current token's position (attendable)
    page_id = tables_ref[s, j]

    # Skip pages past the slot's length and unreserved (dummy) entries.
    @pl.when(jnp.logical_and(j * page_size <= pos,
                             jnp.logical_or(page_id != 0, j == 0)))
    def _compute():
        q = q_ref[0]                        # [H, G, d]
        k = k_ref[0]                        # [H, P, d]
        st = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [H, G, P]
        idx = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, st.shape, 2)
        st = jnp.where(idx <= pos, st, NEG_INF)
        m_prev = m_scr[..., :1]             # [H, G, 1] (lane-replicated)
        m_cur = jnp.max(st, axis=2, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(st - m_new)             # [H, G, P]
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[..., :1] + jnp.sum(p, axis=2,
                                                 keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0],
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [H, G, d]
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_pages - 1)
    def _finalize():
        l = l_scr[..., :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def _kernel_mq(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
               m_scr, l_scr, acc_scr, *, page_size: int, num_pages: int,
               scale: float, g: int, t: int):
    """Multi-query (speculative-verify) variant: the query block folds
    T consecutive tokens into the sublane axis as [H, T*G, d]; row r is
    query token r // G at position lens[s] + r // G, masked causally
    per token. Same flash running-softmax scratch scheme as _kernel."""
    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = lens_ref[s]            # FIRST query token's position
    page_id = tables_ref[s, j]

    # A page is useful if any of the T queries can attend into it.
    @pl.when(jnp.logical_and(j * page_size <= pos + (t - 1),
                             jnp.logical_or(page_id != 0, j == 0)))
    def _compute():
        q = q_ref[0]                        # [H, T*G, d]
        st = jax.lax.dot_general(
            q, k_ref[0], (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [H, T*G, P]
        idx = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, st.shape, 2)
        t_idx = jax.lax.broadcasted_iota(jnp.int32, st.shape, 1) // g
        st = jnp.where(idx <= pos + t_idx, st, NEG_INF)
        m_prev = m_scr[..., :1]             # [H, T*G, 1]
        m_cur = jnp.max(st, axis=2, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(st - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[..., :1] + jnp.sum(p, axis=2,
                                                 keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0],
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [H, T*G, d]
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_pages - 1)
    def _finalize():
        l = l_scr[..., :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def _kernel_q(tables_ref, lens_ref, q_ref, k_ref, v_ref, ks_ref,
              vs_ref, o_ref, m_scr, l_scr, acc_scr, *, page_size: int,
              num_pages: int, scale: float):
    """int8-KV variant of _kernel: k/v blocks are int8 pages and
    ks/vs are their per-token per-head f32 scales ([H, P] per page,
    infer/paged_cache.py layout). Dequantization folds into the two
    matmuls — scores multiply by the key scales (constant over d per
    (h, p), so (q . k_q) * s_k is exact), and the value scales fold
    into the probability weights before the PV product. The int8
    operands cast to the QUERY dtype, not f32: every int8 code
    (-127..127) is exactly representable in bf16, so the matmuls run
    at full MXU rate with the same f32-accumulated result."""
    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = lens_ref[s]
    page_id = tables_ref[s, j]

    @pl.when(jnp.logical_and(j * page_size <= pos,
                             jnp.logical_or(page_id != 0, j == 0)))
    def _compute():
        q = q_ref[0]                        # [H, G, d]
        k = k_ref[0].astype(q_ref.dtype)    # [H, P, d] int8: exact
        st = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [H, G, P]
        st = st * ks_ref[0][:, None, :]     # key scales [H, 1, P]
        idx = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, st.shape, 2)
        st = jnp.where(idx <= pos, st, NEG_INF)
        m_prev = m_scr[..., :1]
        m_cur = jnp.max(st, axis=2, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(st - m_new)             # [H, G, P]
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[..., :1] + jnp.sum(p, axis=2,
                                                 keepdims=True)
        # Value scales fold into the weights; the weighted p rounds to
        # the query dtype like the fp kernel's p.astype(v_ref.dtype).
        pd = (p * vs_ref[0][:, None, :]).astype(q_ref.dtype)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pd, v_ref[0].astype(q_ref.dtype),
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [H, G, d]
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_pages - 1)
    def _finalize():
        l = l_scr[..., :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def _kernel_mq_q(tables_ref, lens_ref, q_ref, k_ref, v_ref, ks_ref,
                 vs_ref, o_ref, m_scr, l_scr, acc_scr, *,
                 page_size: int, num_pages: int, scale: float, g: int,
                 t: int):
    """int8-KV variant of _kernel_mq (speculative multi-query verify):
    same scale folding and query-dtype casting as _kernel_q over the
    [H, T*G, d] query block."""
    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = lens_ref[s]
    page_id = tables_ref[s, j]

    @pl.when(jnp.logical_and(j * page_size <= pos + (t - 1),
                             jnp.logical_or(page_id != 0, j == 0)))
    def _compute():
        q = q_ref[0]                        # [H, T*G, d]
        st = jax.lax.dot_general(
            q, k_ref[0].astype(q_ref.dtype),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [H, T*G, P]
        st = st * ks_ref[0][:, None, :]
        idx = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, st.shape, 2)
        t_idx = jax.lax.broadcasted_iota(jnp.int32, st.shape, 1) // g
        st = jnp.where(idx <= pos + t_idx, st, NEG_INF)
        m_prev = m_scr[..., :1]
        m_cur = jnp.max(st, axis=2, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(st - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[..., :1] + jnp.sum(p, axis=2,
                                                 keepdims=True)
        pd = (p * vs_ref[0][:, None, :]).astype(q_ref.dtype)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pd, v_ref[0].astype(q_ref.dtype),
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [H, T*G, d]
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_pages - 1)
    def _finalize():
        l = l_scr[..., :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=('interpret',))
def paged_decode_attention_q(q: jax.Array, k_pool: jax.Array,
                             v_pool: jax.Array, k_scale: jax.Array,
                             v_scale: jax.Array, tables: jax.Array,
                             lengths: jax.Array,
                             interpret: Optional[bool] = None
                             ) -> jax.Array:
    """int8-KV single-query paged decode: same contract as
    paged_decode_attention plus the scale pools [n_pages, Hkv, P]
    (one layer). Scale blocks ride their own scalar-prefetched
    BlockSpec indexed by the same table lookup as the pages."""
    s_slots, hq, d = q.shape
    _, hkv, page_size, _ = k_pool.shape
    assert hq % hkv == 0, (hq, hkv)
    g = hq // hkv
    mp = tables.shape[1]
    scale = d ** -0.5
    qg = q.reshape(s_slots, hkv, g, d)

    kernel = functools.partial(_kernel_q, page_size=page_size,
                               num_pages=mp, scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_slots, mp),
        in_specs=[
            pl.BlockSpec((1, hkv, g, d),
                         lambda s, j, tbl, lns: (s, 0, 0, 0)),
            pl.BlockSpec((1, hkv, page_size, d),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0, 0)),
            pl.BlockSpec((1, hkv, page_size, d),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0, 0)),
            pl.BlockSpec((1, hkv, page_size),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0)),
            pl.BlockSpec((1, hkv, page_size),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hkv, g, d),
                               lambda s, j, tbl, lns: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, g, LANES), jnp.float32),   # running max
            pltpu.VMEM((hkv, g, LANES), jnp.float32),   # running sum
            pltpu.VMEM((hkv, g, d), jnp.float32),       # out accumulator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, hkv, g, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'arbitrary')),
        interpret=_interpret_mode() if interpret is None else interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), qg, k_pool,
      v_pool, k_scale, v_scale)
    return out.reshape(s_slots, hq, d)


@functools.partial(jax.jit, static_argnames=('interpret',))
def paged_decode_attention_mq_q(q: jax.Array, k_pool: jax.Array,
                                v_pool: jax.Array, k_scale: jax.Array,
                                v_scale: jax.Array, tables: jax.Array,
                                lengths: jax.Array,
                                interpret: Optional[bool] = None
                                ) -> jax.Array:
    """int8-KV multi-query paged decode (speculative verify): same
    contract as paged_decode_attention_mq plus the scale pools."""
    s_slots, t, hq, d = q.shape
    _, hkv, page_size, _ = k_pool.shape
    assert hq % hkv == 0, (hq, hkv)
    g = hq // hkv
    mp = tables.shape[1]
    scale = d ** -0.5
    qg = q.reshape(s_slots, t, hkv, g, d).transpose(0, 2, 1, 3, 4) \
         .reshape(s_slots, hkv, t * g, d)

    kernel = functools.partial(_kernel_mq_q, page_size=page_size,
                               num_pages=mp, scale=scale, g=g, t=t)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_slots, mp),
        in_specs=[
            pl.BlockSpec((1, hkv, t * g, d),
                         lambda s, j, tbl, lns: (s, 0, 0, 0)),
            pl.BlockSpec((1, hkv, page_size, d),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0, 0)),
            pl.BlockSpec((1, hkv, page_size, d),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0, 0)),
            pl.BlockSpec((1, hkv, page_size),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0)),
            pl.BlockSpec((1, hkv, page_size),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hkv, t * g, d),
                               lambda s, j, tbl, lns: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, t * g, LANES), jnp.float32),  # running max
            pltpu.VMEM((hkv, t * g, LANES), jnp.float32),  # running sum
            pltpu.VMEM((hkv, t * g, d), jnp.float32),      # accumulator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, hkv, t * g, d),
                                       q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'arbitrary')),
        interpret=_interpret_mode() if interpret is None else interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), qg, k_pool,
      v_pool, k_scale, v_scale)
    return out.reshape(s_slots, hkv, t, g, d).transpose(0, 2, 1, 3, 4) \
              .reshape(s_slots, t, hq, d)


@functools.partial(jax.jit, static_argnames=('interpret',))
def paged_decode_attention_mq(q: jax.Array, k_pool: jax.Array,
                              v_pool: jax.Array, tables: jax.Array,
                              lengths: jax.Array,
                              interpret: Optional[bool] = None
                              ) -> jax.Array:
    """Multi-query paged decode (speculative verify): q [S, T, Hq, d] —
    T consecutive tokens per slot, token t at position lengths[s] + t
    (all T tokens' KV already appended). Returns [S, T, Hq, d].
    """
    s_slots, t, hq, d = q.shape
    _, hkv, page_size, _ = k_pool.shape
    assert hq % hkv == 0, (hq, hkv)
    g = hq // hkv
    mp = tables.shape[1]
    scale = d ** -0.5
    # [S, T, Hkv, G, d] -> [S, Hkv, T, G, d] -> [S, Hkv, T*G, d]:
    # row r of the sublane axis is (token r // G, q-head-in-group r % G).
    qg = q.reshape(s_slots, t, hkv, g, d).transpose(0, 2, 1, 3, 4) \
         .reshape(s_slots, hkv, t * g, d)

    kernel = functools.partial(_kernel_mq, page_size=page_size,
                               num_pages=mp, scale=scale, g=g, t=t)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_slots, mp),
        in_specs=[
            pl.BlockSpec((1, hkv, t * g, d),
                         lambda s, j, tbl, lns: (s, 0, 0, 0)),
            pl.BlockSpec((1, hkv, page_size, d),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0, 0)),
            pl.BlockSpec((1, hkv, page_size, d),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hkv, t * g, d),
                               lambda s, j, tbl, lns: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, t * g, LANES), jnp.float32),  # running max
            pltpu.VMEM((hkv, t * g, LANES), jnp.float32),  # running sum
            pltpu.VMEM((hkv, t * g, d), jnp.float32),      # accumulator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, hkv, t * g, d),
                                       q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'arbitrary')),
        interpret=_interpret_mode() if interpret is None else interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), qg, k_pool,
      v_pool)
    return out.reshape(s_slots, hkv, t, g, d).transpose(0, 2, 1, 3, 4) \
              .reshape(s_slots, t, hq, d)


@functools.partial(jax.jit, static_argnames=('interpret',))
def paged_decode_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, tables: jax.Array,
                           lengths: jax.Array,
                           interpret: Optional[bool] = None) -> jax.Array:
    """q: [S, Hq, d] (one token per slot); k_pool/v_pool:
    [n_pages, Hkv, P, d] (one layer, page-major); tables: [S, mp] int32;
    lengths: [S] int32 — the position each slot's query token sits at
    (it attends positions <= lengths[s], its own KV already written).

    Returns [S, Hq, d].
    """
    s_slots, hq, d = q.shape
    _, hkv, page_size, _ = k_pool.shape
    assert hq % hkv == 0, (hq, hkv)
    g = hq // hkv
    mp = tables.shape[1]
    scale = d ** -0.5
    qg = q.reshape(s_slots, hkv, g, d)

    kernel = functools.partial(_kernel, page_size=page_size,
                               num_pages=mp, scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_slots, mp),
        in_specs=[
            pl.BlockSpec((1, hkv, g, d),
                         lambda s, j, tbl, lns: (s, 0, 0, 0)),
            pl.BlockSpec((1, hkv, page_size, d),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0, 0)),
            pl.BlockSpec((1, hkv, page_size, d),
                         lambda s, j, tbl, lns: (tbl[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hkv, g, d),
                               lambda s, j, tbl, lns: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, g, LANES), jnp.float32),   # running max
            pltpu.VMEM((hkv, g, LANES), jnp.float32),   # running sum
            pltpu.VMEM((hkv, g, d), jnp.float32),       # out accumulator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, hkv, g, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'arbitrary')),
        interpret=_interpret_mode() if interpret is None else interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), qg, k_pool,
      v_pool)
    return out.reshape(s_slots, hq, d)
