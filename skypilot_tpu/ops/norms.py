"""Normalization ops.

RMSNorm as used by the Llama family. Computation in float32 regardless of
input dtype (bf16 accumulation loses too much precision for variance), cast
back on return — XLA fuses the whole thing into neighboring ops, so there is
no reason for a Pallas kernel here (the op is bandwidth-trivial after
fusion).
"""
import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-5,
             zero_centered: bool = False) -> jax.Array:
    """y = x / rms(x) * w   (w stored as (1+w) when zero_centered, the
    Gemma convention, so zero-init works with weight decay)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if zero_centered:
        w = 1.0 + w
    return (y * w).astype(dtype)
