"""Excluded-file handling for uploads.

Reference: sky/data/storage_utils.py (~230 LoC) — honors `.skyignore`
(one glob per line, '#' comments) falling back to `.gitignore` patterns.
We use the same precedence with a `.skytignore` name plus the reference's
`.skyignore` as an alias so existing projects port over unchanged.
"""
import os
from typing import List

IGNORE_FILES = ('.skytignore', '.skyignore', '.gitignore')

DEFAULT_EXCLUDES = ['.git', '__pycache__', '*.pyc']


def get_excluded_files(src_dir: str) -> List[str]:
    """Return glob patterns to exclude when uploading `src_dir`.

    First ignore-file found (in IGNORE_FILES order) wins, matching the
    reference's skyignore-overrides-gitignore behavior
    (sky/data/storage_utils.py).
    """
    excludes = list(DEFAULT_EXCLUDES)
    for fname in IGNORE_FILES:
        path = os.path.join(src_dir, fname)
        if not os.path.isfile(path):
            continue
        with open(path, 'r', encoding='utf-8') as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith('#') or line.startswith('!'):
                    continue
                excludes.append(line.rstrip('/'))
        break
    return excludes
