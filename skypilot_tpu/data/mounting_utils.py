"""Mount-command builders for bucket stores.

Reference: sky/data/mounting_utils.py — version-pinned FUSE binaries and
mount command builders (gcsfuse :42-62 pinned v2.2.0, goofys, blobfuse2),
plus an idempotent mount-script wrapper with an installed-check.

GCS-first: gcsfuse is the one FUSE path that matters on TPU VMs (they are
GCP VMs; buckets are GCS). ``local://`` stores "mount" via symlink — that
is what makes MOUNT mode testable offline on the local provider.
"""
import hashlib
import shlex

GCSFUSE_VERSION = '2.2.0'

_MOUNT_SCRIPT = """\
set -e
MOUNT_PATH={mount_path}
if grep -qs "$MOUNT_PATH" /proc/mounts; then
  echo "already mounted at $MOUNT_PATH"; exit 0
fi
{install_cmd}
sudo mkdir -p $MOUNT_PATH
sudo chown $(whoami) $MOUNT_PATH
{mount_cmd}
"""


def gcsfuse_install_command() -> str:
    """Install the pinned gcsfuse if absent (reference pins v2.2.0,
    sky/data/mounting_utils.py:16)."""
    return (
        f'gcsfuse --version 2>/dev/null | grep -q {GCSFUSE_VERSION} || '
        f'(curl -fsSL -o /tmp/gcsfuse.deb https://github.com/'
        f'GoogleCloudPlatform/gcsfuse/releases/download/v{GCSFUSE_VERSION}/'
        f'gcsfuse_{GCSFUSE_VERSION}_amd64.deb && '
        f'sudo dpkg -i /tmp/gcsfuse.deb || sudo apt-get install -f -y)')


def gcsfuse_mount_command(bucket: str, mount_path: str,
                          sub_path: str = '') -> str:
    """Build the full idempotent gcsfuse mount script.

    --implicit-dirs: GCS has no real directories; without it empty prefixes
    are invisible. Stat/type cache TTLs mirror the reference's tuning for
    read-heavy training workloads.
    """
    only_dir = f'--only-dir {shlex.quote(sub_path)} ' if sub_path else ''
    mount_cmd = (f'gcsfuse --implicit-dirs '
                 f'--stat-cache-capacity 4096 '
                 f'--stat-cache-ttl 5s --type-cache-ttl 5s '
                 f'--rename-dir-limit 10000 '
                 f'{only_dir}'
                 f'{shlex.quote(bucket)} {shlex.quote(mount_path)}')
    return _MOUNT_SCRIPT.format(mount_path=shlex.quote(mount_path),
                                install_cmd=gcsfuse_install_command(),
                                mount_cmd=mount_cmd)


GOOFYS_VERSION = '0.24.0'
BLOBFUSE2_VERSION = '2.2.0'


def goofys_install_command() -> str:
    """Install goofys if absent (reference mounts S3/R2 via goofys,
    sky/data/mounting_utils.py:24-40). A static single binary — fetch
    straight to /usr/local/bin."""
    return (
        'command -v goofys >/dev/null || '
        f'(sudo curl -fsSL -o /usr/local/bin/goofys '
        f'https://github.com/kahing/goofys/releases/download/'
        f'v{GOOFYS_VERSION}/goofys && '
        f'sudo chmod 755 /usr/local/bin/goofys)')


def _fuse_allow_other_command() -> str:
    """Unprivileged FUSE mounts may pass -o allow_other only when
    /etc/fuse.conf enables user_allow_other (commented out on stock
    Debian/Ubuntu); make it so before mounting."""
    return ('grep -q "^user_allow_other" /etc/fuse.conf 2>/dev/null || '
            'echo user_allow_other | sudo tee -a /etc/fuse.conf '
            '>/dev/null')


def goofys_mount_command(bucket: str, mount_path: str,
                         endpoint: str = '') -> str:
    """Idempotent goofys mount for any S3-compatible store.

    One builder covers S3, R2, and IBM COS: the latter two only differ
    by --endpoint (their stores already speak the S3 API — the same
    design choice as their aws-CLI data paths). ``-o allow_other``
    (enabled in /etc/fuse.conf by the install step) keeps the mount
    readable by the job user regardless of which user ran setup.
    """
    ep = f'--endpoint {shlex.quote(endpoint)} ' if endpoint else ''
    install_cmd = (f'{goofys_install_command()}\n'
                   f'{_fuse_allow_other_command()}')
    mount_cmd = (f'goofys -o allow_other --stat-cache-ttl 5s '
                 f'--type-cache-ttl 5s {ep}'
                 f'{shlex.quote(bucket)} {shlex.quote(mount_path)}')
    return _MOUNT_SCRIPT.format(mount_path=shlex.quote(mount_path),
                                install_cmd=install_cmd,
                                mount_cmd=mount_cmd)


def blobfuse2_install_command() -> str:
    """Install blobfuse2 if absent (reference: blobfuse2 for Azure,
    sky/data/mounting_utils.py:65+). Ubuntu/Debian path via Microsoft's
    package repo — TPU-fleet hosts are Debian-family."""
    return (
        'command -v blobfuse2 >/dev/null || '
        '(sudo curl -fsSL -o /tmp/packages-microsoft-prod.deb '
        'https://packages.microsoft.com/config/ubuntu/22.04/'
        'packages-microsoft-prod.deb && '
        'sudo dpkg -i /tmp/packages-microsoft-prod.deb && '
        'sudo apt-get update -qq && '
        'sudo apt-get install -y -qq libfuse3-dev fuse3 blobfuse2)')


def blobfuse2_mount_command(account: str, container: str,
                            mount_path: str) -> str:
    """Idempotent blobfuse2 mount of an Azure Blob container.

    Auth rides the azure CLI login already required by the AZURE data
    path (AZURE_STORAGE_AUTH_TYPE=azcli), so no key material is ever
    written to disk; a tmp-path block cache keeps reads training-speed.
    The cache dir is keyed by (account, container, mount_path): blobfuse2
    refuses to share a tmp-path between active mounts, so mounting the
    same container twice — or same-named containers from two accounts —
    must not collide.
    """
    key = hashlib.sha256(
        f'{account}\0{container}\0{mount_path}'.encode()).hexdigest()[:12]
    cache_dir = f'/tmp/.blobfuse2-cache-{container}-{key}'
    install_cmd = (f'{blobfuse2_install_command()}\n'
                   f'{_fuse_allow_other_command()}')
    mount_cmd = (f'mkdir -p {shlex.quote(cache_dir)} && '
                 f'AZURE_STORAGE_ACCOUNT={shlex.quote(account)} '
                 f'AZURE_STORAGE_AUTH_TYPE=azcli '
                 f'blobfuse2 mount {shlex.quote(mount_path)} '
                 f'--container-name {shlex.quote(container)} '
                 f'--tmp-path {shlex.quote(cache_dir)} '
                 f'--allow-other')
    return _MOUNT_SCRIPT.format(mount_path=shlex.quote(mount_path),
                                install_cmd=install_cmd,
                                mount_cmd=mount_cmd)


def local_mount_command(store_dir: str, mount_path: str) -> str:
    """'Mount' a local:// store by symlinking its backing directory.

    Gives MOUNT-mode semantics (writes propagate to the store) without FUSE
    — the offline analog the test harness uses.
    """
    q_store = shlex.quote(store_dir)
    q_mount = shlex.quote(mount_path)
    # Never delete pre-existing data at the mount point: an old symlink is
    # replaced, an empty dir is removed, anything else is an error (the
    # gcsfuse path likewise refuses to mount over existing content).
    return (f'set -e; mkdir -p {q_store}; '
            f'mkdir -p "$(dirname {q_mount})"; '
            f'if [ -L {q_mount} ]; then rm {q_mount}; '
            f'elif [ -d {q_mount} ]; then rmdir {q_mount} || '
            f'{{ echo "mount path {q_mount} is a non-empty directory" '
            f'>&2; exit 1; }}; '
            f'elif [ -e {q_mount} ]; then '
            f'echo "mount path {q_mount} exists" >&2; exit 1; fi; '
            f'ln -s {q_store} {q_mount}')


def unmount_command(mount_path: str) -> str:
    q = shlex.quote(mount_path)
    return (f'if [ -L {q} ]; then rm {q}; '
            f'elif grep -qs {q} /proc/mounts; then '
            f'fusermount -u {q} || sudo umount -l {q}; fi')
