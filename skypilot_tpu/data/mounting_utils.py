"""Mount-command builders for bucket stores.

Reference: sky/data/mounting_utils.py — version-pinned FUSE binaries and
mount command builders (gcsfuse :42-62 pinned v2.2.0, goofys, blobfuse2),
plus an idempotent mount-script wrapper with an installed-check.

GCS-first: gcsfuse is the one FUSE path that matters on TPU VMs (they are
GCP VMs; buckets are GCS). ``local://`` stores "mount" via symlink — that
is what makes MOUNT mode testable offline on the local provider.
"""
import shlex

GCSFUSE_VERSION = '2.2.0'

_MOUNT_SCRIPT = """\
set -e
MOUNT_PATH={mount_path}
if grep -qs "$MOUNT_PATH" /proc/mounts; then
  echo "already mounted at $MOUNT_PATH"; exit 0
fi
{install_cmd}
sudo mkdir -p $MOUNT_PATH
sudo chown $(whoami) $MOUNT_PATH
{mount_cmd}
"""


def gcsfuse_install_command() -> str:
    """Install the pinned gcsfuse if absent (reference pins v2.2.0,
    sky/data/mounting_utils.py:16)."""
    return (
        f'gcsfuse --version 2>/dev/null | grep -q {GCSFUSE_VERSION} || '
        f'(curl -fsSL -o /tmp/gcsfuse.deb https://github.com/'
        f'GoogleCloudPlatform/gcsfuse/releases/download/v{GCSFUSE_VERSION}/'
        f'gcsfuse_{GCSFUSE_VERSION}_amd64.deb && '
        f'sudo dpkg -i /tmp/gcsfuse.deb || sudo apt-get install -f -y)')


def gcsfuse_mount_command(bucket: str, mount_path: str,
                          sub_path: str = '') -> str:
    """Build the full idempotent gcsfuse mount script.

    --implicit-dirs: GCS has no real directories; without it empty prefixes
    are invisible. Stat/type cache TTLs mirror the reference's tuning for
    read-heavy training workloads.
    """
    only_dir = f'--only-dir {shlex.quote(sub_path)} ' if sub_path else ''
    mount_cmd = (f'gcsfuse --implicit-dirs '
                 f'--stat-cache-capacity 4096 '
                 f'--stat-cache-ttl 5s --type-cache-ttl 5s '
                 f'--rename-dir-limit 10000 '
                 f'{only_dir}'
                 f'{shlex.quote(bucket)} {shlex.quote(mount_path)}')
    return _MOUNT_SCRIPT.format(mount_path=shlex.quote(mount_path),
                                install_cmd=gcsfuse_install_command(),
                                mount_cmd=mount_cmd)


def local_mount_command(store_dir: str, mount_path: str) -> str:
    """'Mount' a local:// store by symlinking its backing directory.

    Gives MOUNT-mode semantics (writes propagate to the store) without FUSE
    — the offline analog the test harness uses.
    """
    q_store = shlex.quote(store_dir)
    q_mount = shlex.quote(mount_path)
    # Never delete pre-existing data at the mount point: an old symlink is
    # replaced, an empty dir is removed, anything else is an error (the
    # gcsfuse path likewise refuses to mount over existing content).
    return (f'set -e; mkdir -p {q_store}; '
            f'mkdir -p "$(dirname {q_mount})"; '
            f'if [ -L {q_mount} ]; then rm {q_mount}; '
            f'elif [ -d {q_mount} ]; then rmdir {q_mount} || '
            f'{{ echo "mount path {q_mount} is a non-empty directory" '
            f'>&2; exit 1; }}; '
            f'elif [ -e {q_mount} ]; then '
            f'echo "mount path {q_mount} exists" >&2; exit 1; fi; '
            f'ln -s {q_store} {q_mount}')


def unmount_command(mount_path: str) -> str:
    q = shlex.quote(mount_path)
    return (f'if [ -L {q} ]; then rm {q}; '
            f'elif grep -qs {q} /proc/mounts; then '
            f'fusermount -u {q} || sudo umount -l {q}; fi')
