"""URI and bucket helpers for the data layer.

Reference: sky/data/data_utils.py (739 LoC) — URI split/verify and
per-cloud bucket helpers. GCS-first here: the TPU-native framework treats
``gs://`` as the primary scheme; ``local://`` is the offline store used by
the local provider and the test harness.
"""
import os
import re
import urllib.parse
from typing import Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.utils import env

CLOUD_SCHEMES = ('gs', 's3', 'az', 'r2', 'cos', 'local')
# Schemes we can *download from* on a remote host but not manage as stores.
DOWNLOAD_ONLY_SCHEMES = ('https', 'http')

# GCS bucket naming rules (subset): 3-63 chars, lowercase letters, digits,
# dashes, underscores, dots; must start/end alphanumeric.
_BUCKET_NAME_RE = re.compile(r'^[a-z0-9][a-z0-9._-]{1,61}[a-z0-9]$')


def split_uri(uri: str) -> Tuple[str, str, str]:
    """'gs://bucket/a/b' -> ('gs', 'bucket', 'a/b')."""
    parsed = urllib.parse.urlsplit(uri)
    if not parsed.scheme:
        raise exceptions.StorageSourceError(f'Not a URI: {uri!r}')
    return parsed.scheme, parsed.netloc, parsed.path.lstrip('/')


def is_cloud_uri(source: str) -> bool:
    return any(source.startswith(f'{s}://')
               for s in CLOUD_SCHEMES + DOWNLOAD_ONLY_SCHEMES)


def verify_bucket_name(name: str) -> None:
    """Reference: sky/data/storage.py validate_name — GCS naming rules."""
    if not _BUCKET_NAME_RE.match(name):
        raise exceptions.StorageNameError(
            f'Invalid bucket name {name!r}: must be 3-63 chars of '
            f'[a-z0-9._-], starting/ending alphanumeric.')
    if '..' in name or name.startswith('goog'):
        raise exceptions.StorageNameError(
            f'Invalid bucket name {name!r} (reserved pattern).')


def local_store_root() -> str:
    """Root directory that backs ``local://`` buckets (offline store)."""
    root = env.get(
        'SKYT_LOCAL_STORAGE_ROOT',
        os.path.join(env.get('SKYT_LOCAL_ROOT',
                             os.path.expanduser('~/.skyt_local')),
                     '_storage'))
    return os.path.abspath(os.path.expanduser(root))
