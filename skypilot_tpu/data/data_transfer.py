"""Cloud-to-cloud bucket transfer.

Reference: sky/data/data_transfer.py — GCP Storage Transfer Service for
s3->gcs. CLI-first here (matching the stores): same-family transfers go
direct (one rsync/sync process, data never touches this machine twice);
cross-family transfers stream through a local spool directory using the
two stores' native CLIs — no Transfer-Service IAM setup, works from any
machine with both CLIs, and the spool is deleted afterwards.

    transfer('gs://weights', 's3://weights-replica')
    transfer('s3://raw', 'gs://raw')
"""
import os
import shutil
import subprocess
import tempfile
from typing import List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.data import data_utils
from skypilot_tpu.utils import log_utils

logger = log_utils.init_logger(__name__)


def _endpoint_flags(scheme: str) -> List[str]:
    """--endpoint-url flags for S3-compatible stores (r2, cos) —
    resolution (env vars + unset error) lives on the store classes."""
    from skypilot_tpu.data import storage as storage_lib
    cls = {'r2': storage_lib.R2Store,
           'cos': storage_lib.IbmCosStore}[scheme]
    return ['--endpoint-url', cls.endpoint()]


def _run(cmd: List[str], failure: str) -> None:
    logger.info('transfer: %s', ' '.join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          check=False)
    if proc.returncode != 0:
        raise exceptions.StorageError(
            f'{failure}: {" ".join(cmd)!r} failed with '
            f'{proc.stderr.strip() or proc.stdout.strip()}')


def _sync_cmd(scheme: str, src: str, dst: str) -> List[List[str]]:
    """Command(s) syncing src -> dst where at least one side is a
    `scheme` URI and the other is a URI of the same family or a local
    path."""
    if scheme == 'gs':
        return [['gsutil', '-m', 'rsync', '-r', src, dst]]
    if scheme == 's3':
        return [['aws', 's3', 'sync', src, dst]]
    if scheme in ('r2', 'cos'):
        def fix(u: str) -> str:
            return 's3://' + u[len(scheme) + 3:] \
                if u.startswith(f'{scheme}://') else u
        return [['aws', 's3', 'sync', fix(src), fix(dst),
                 *_endpoint_flags(scheme)]]
    if scheme == 'local':
        def path(u: str) -> str:
            if u.startswith('local://'):
                _, bucket, sub = data_utils.split_uri(u)
                p = os.path.join(data_utils.local_store_root(), bucket)
                return os.path.join(p, sub) if sub else p
            return u
        return [['mkdir', '-p', path(dst)],
                ['cp', '-a', f'{path(src)}/.', f'{path(dst)}/']]
    raise exceptions.StorageSourceError(
        f'No transfer strategy for scheme {scheme!r}')


def transfer(src_uri: str, dst_uri: str,
             spool_dir: Optional[str] = None) -> None:
    """Copy all objects under src_uri to dst_uri.

    Same-family (gs->gs, s3->s3, r2->r2, cos->cos, local->local):
    direct sync.
    Cross-family: download into a spool dir, upload, delete the spool.
    """
    s_scheme, _, _ = data_utils.split_uri(src_uri)
    d_scheme, _, _ = data_utils.split_uri(dst_uri)
    family = ('gs', 's3', 'r2', 'cos', 'local')
    if s_scheme not in family or d_scheme not in family:
        raise exceptions.StorageSourceError(
            f'transfer() supports gs/s3/r2/cos/local URIs, got '
            f'{s_scheme!r} -> {d_scheme!r}')

    if s_scheme == d_scheme:
        for cmd in _sync_cmd(s_scheme, src_uri, dst_uri):
            _run(cmd, failure=f'transfer {src_uri} -> {dst_uri}')
        return

    own_spool = spool_dir is None
    spool = spool_dir or tempfile.mkdtemp(prefix='skyt-transfer-')
    try:
        for cmd in _sync_cmd(s_scheme, src_uri, spool):
            _run(cmd, failure=f'download {src_uri}')
        for cmd in _sync_cmd(d_scheme, spool, dst_uri):
            _run(cmd, failure=f'upload to {dst_uri}')
    finally:
        if own_spool:
            shutil.rmtree(spool, ignore_errors=True)
