"""Bridge between the backend and the storage layer.

Reference: sky/backends/cloud_vm_ray_backend.py:4549
`_execute_storage_mounts` — ensures each task storage exists + is
uploaded, then runs the per-store MOUNT (FUSE) or COPY (download)
command on every host in parallel.
"""
from typing import Any, Dict, List, Union

from skypilot_tpu import exceptions
from skypilot_tpu.data import storage as storage_lib
from skypilot_tpu.utils import command_runner as command_runner_lib
from skypilot_tpu.utils import log_utils
from skypilot_tpu.utils import subprocess_utils

logger = log_utils.init_logger(__name__)


def to_storage(obj: Union['storage_lib.Storage', Dict[str, Any], str]
               ) -> 'storage_lib.Storage':
    """Coerce a task.storage_mounts value (raw YAML dict, URI string, or
    Storage) into a Storage object."""
    if isinstance(obj, storage_lib.Storage):
        return obj
    if isinstance(obj, str):
        return storage_lib.Storage(source=obj)
    if isinstance(obj, dict):
        return storage_lib.Storage.from_yaml_config(obj)
    raise exceptions.StorageError(
        f'Cannot interpret storage mount spec {obj!r}')


def mount_storages(
        runners: List['command_runner_lib.CommandRunner'],
        storage_mounts: Dict[str, Any]) -> None:
    """Create/upload each storage, then mount or copy it on every host."""
    for mount_path, spec in storage_mounts.items():
        storage = to_storage(spec)
        store = storage.add_store(storage.requested_store)
        if storage.mode is storage_lib.StorageMode.MOUNT:
            cmd = store.mount_command(mount_path)
            what = 'mount'
        else:
            cmd = store.download_command(mount_path)
            what = 'copy'
        logger.info('Storage %s: %s %s -> %s', storage.name, what,
                    store.uri, mount_path)

        def _apply(runner, _cmd=cmd, _uri=store.uri, _path=mount_path,
                   _what=what):
            runner.run_or_raise(
                _cmd,
                failure_message=f'{_what} of {_uri} at {_path} failed')

        subprocess_utils.run_in_parallel(_apply, runners)


def unmount_storages(
        runners: List['command_runner_lib.CommandRunner'],
        storage_mounts: Dict[str, Any]) -> None:
    from skypilot_tpu.data import mounting_utils
    for mount_path in storage_mounts:
        cmd = mounting_utils.unmount_command(mount_path)

        def _apply(runner, _cmd=cmd):
            runner.run(_cmd, stream_logs=False)

        subprocess_utils.run_in_parallel(_apply, runners)
