"""Download commands for bucket-URI file_mounts.

Reference: sky/cloud_stores.py (492 LoC) — `CloudStorage` classes that
build existence-check + download commands (gsutil / aws s3 / azcopy /
rclone) run on the remote host. Here: one function, scheme-dispatched.
GCS is first-class; s3/r2/https work wherever the remote host has the
matching CLI (TPU VMs ship gsutil + curl).
"""
import os
import shlex

from skypilot_tpu import exceptions
from skypilot_tpu.data import data_utils


def download_command(source: str, target: str) -> str:
    """Shell command (run on the remote host) to fetch `source` into
    `target`.

    Directory/prefix sources sync recursively into `target` (a dir);
    single-object sources land AS `target` (file->file, the reference's
    file_mount semantics, sky/cloud_stores.py is_directory() dispatch).
    Which case applies is decided at runtime on the remote host — the
    client can't stat the bucket from here.
    """
    scheme, bucket, path = data_utils.split_uri(source)
    q_target = shlex.quote(target)
    q_parent = shlex.quote(os.path.dirname(target.rstrip('/')) or '.')
    if scheme == 'gs':
        # `gsutil stat` succeeds only for objects, never prefixes.
        return (f'if gsutil -q stat {shlex.quote(source)}; then '
                f'mkdir -p {q_parent} && '
                f'gsutil cp {shlex.quote(source)} {q_target}; else '
                f'mkdir -p {q_target} && '
                f'gsutil -m rsync -r {shlex.quote(source)} {q_target}; fi')
    if scheme == 'local':
        src_dir = f'{data_utils.local_store_root()}/{bucket}'
        if path:
            src_dir = f'{src_dir}/{path}'
        q_src = shlex.quote(src_dir)
        return (f'if [ -d {q_src} ]; then '
                f'mkdir -p {q_target} && cp -a {q_src}/. {q_target}/; '
                f'else mkdir -p {q_parent} && cp -a {q_src} {q_target}; fi')
    if scheme in ('s3', 'r2', 'cos'):
        ep = ''
        if scheme in ('r2', 'cos'):
            # Raises when SKYT_{R2,COS}_ENDPOINT is unset — a silent
            # fallback would sync from a same-named *AWS* bucket instead.
            from skypilot_tpu.data import storage as storage_lib
            store_cls = (storage_lib.R2Store if scheme == 'r2'
                         else storage_lib.IbmCosStore)
            ep = f' --endpoint-url {shlex.quote(store_cls.endpoint())}'
            source = 's3://' + source[len(scheme) + 3:]
        # `head-object` succeeds only for exact objects (the s3 analog
        # of `gsutil stat`) — dispatching on `aws s3 cp` failure would
        # turn auth/network errors into a silently-empty prefix sync.
        return (f'if aws s3api head-object --bucket {shlex.quote(bucket)} '
                f'--key {shlex.quote(path)}{ep} >/dev/null 2>&1; then '
                f'mkdir -p {q_parent} && '
                f'aws s3 cp {shlex.quote(source)} {q_target}{ep}; else '
                f'mkdir -p {q_target} && '
                f'aws s3 sync {shlex.quote(source)} {q_target}{ep}; fi')
    if scheme == 'az':
        from skypilot_tpu.data import storage as storage_lib
        acct = storage_lib.AzureBlobStore.account()
        src = bucket if not path else f'{bucket}/{path}'
        return (f'mkdir -p {q_target} && az storage blob download-batch '
                f'--destination {q_target} --source {shlex.quote(src)} '
                f'--account-name {shlex.quote(acct)} --overwrite '
                f'--output json')
    if scheme in ('http', 'https'):
        return (f'mkdir -p {q_target} && cd {q_target} && '
                f'curl -fsSLO {shlex.quote(source)}')
    raise exceptions.StorageSourceError(
        f'Cannot build a download command for scheme {scheme!r}')
