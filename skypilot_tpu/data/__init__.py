"""Data & storage layer (reference: sky/data/ + sky/cloud_stores.py)."""
from skypilot_tpu.data.storage import StorageMode
from skypilot_tpu.data.storage import StoreType
from skypilot_tpu.data.storage import Storage

__all__ = ['Storage', 'StoreType', 'StorageMode']
