"""Storage: named bucket abstraction (GCS-first).

Reference: sky/data/storage.py (3,526 LoC) — `StoreType` (:109),
`StorageMode` (:192), `AbstractStore` (:197), `Storage` (:384),
`GcsStore` (:1511, gsutil rsync batching). The reference's five object
stores (S3/GCS/Azure/R2/COS) are all implemented; GCS is first-class
(TPU VMs are GCP VMs — one bucket family rides the same network as the
chips), S3/R2/COS ride the aws CLI (R2/COS via S3-compatible endpoints),
Azure rides the az CLI, and a ``local://`` store backs the offline test
harness and the local provider. Download-only access to bucket-URI
file_mounts lives in cloud_stores.py.
"""
import dataclasses
import enum
import fnmatch
import os
import shutil
import subprocess
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import state
from skypilot_tpu.data import data_utils
from skypilot_tpu.data import mounting_utils
from skypilot_tpu.data import storage_utils
from skypilot_tpu.utils import log_utils
from skypilot_tpu.utils import env

logger = log_utils.init_logger(__name__)


class StoreType(enum.Enum):
    """Reference: sky/data/storage.py:109."""
    GCS = 'GCS'
    S3 = 'S3'
    AZURE = 'AZURE'
    R2 = 'R2'
    COS = 'COS'
    LOCAL = 'LOCAL'

    @classmethod
    def from_scheme(cls, scheme: str) -> 'StoreType':
        for st, sch in _SCHEMES.items():
            if sch == scheme:
                return st
        managed = ', '.join(f'{s}://' for s in _SCHEMES.values())
        raise exceptions.StorageSourceError(
            f'No store type for scheme {scheme!r} (managed stores: '
            f'{managed}).')

    @property
    def scheme(self) -> str:
        return _SCHEMES[self]


# The one scheme<->store mapping; data_utils.CLOUD_SCHEMES must list the
# same schemes (asserted below) so URI validation everywhere stays in
# sync with the registered stores.
_SCHEMES = {StoreType.GCS: 'gs', StoreType.S3: 's3',
            StoreType.AZURE: 'az', StoreType.R2: 'r2',
            StoreType.COS: 'cos', StoreType.LOCAL: 'local'}
assert set(_SCHEMES.values()) == set(data_utils.CLOUD_SCHEMES), \
    (_SCHEMES, data_utils.CLOUD_SCHEMES)


class StorageMode(enum.Enum):
    """Reference: sky/data/storage.py:192."""
    MOUNT = 'MOUNT'
    COPY = 'COPY'


def _run(cmd: List[str], failure: str, **kwargs) -> str:
    proc = subprocess.run(cmd, capture_output=True, text=True, check=False,
                          **kwargs)
    if proc.returncode != 0:
        raise exceptions.StorageError(
            f'{failure}: {" ".join(cmd)!r} failed with '
            f'{proc.stderr.strip() or proc.stdout.strip()}')
    return proc.stdout


@dataclasses.dataclass
class StorageHandle:
    """Pickled into the state DB (reference pickles the Storage's
    StorageMetadata, sky/data/storage.py:384)."""
    storage_name: str
    source: Optional[str]
    mode: str
    store_types: List[str]
    sky_managed: bool


class AbstractStore:
    """One bucket in one store. Reference: sky/data/storage.py:197."""

    store_type: StoreType

    def __init__(self, name: str, source: Optional[str],
                 sky_managed: bool = True) -> None:
        data_utils.verify_bucket_name(name)
        self.name = name
        self.source = source
        # sky_managed: we created the bucket, so delete() removes it;
        # external buckets are never deleted (reference is_sky_managed).
        self.sky_managed = sky_managed

    # Lifecycle ----------------------------------------------------------
    def initialize(self) -> None:
        """Create the bucket if needed; set sky_managed accordingly."""
        raise NotImplementedError

    def upload(self, source: str) -> None:
        """Sync a local directory/file into the bucket."""
        raise NotImplementedError

    def delete(self) -> None:
        raise NotImplementedError

    def exists(self) -> bool:
        raise NotImplementedError

    # Remote-side commands ----------------------------------------------
    def mount_command(self, mount_path: str) -> str:
        raise NotImplementedError

    def download_command(self, target: str) -> str:
        raise NotImplementedError

    @property
    def uri(self) -> str:
        return f'{self.store_type.scheme}://{self.name}'


class GcsStore(AbstractStore):
    """GCS bucket via the gsutil/gcloud CLI (the TPU VM has both baked in;
    the client needs gcloud auth). Reference: sky/data/storage.py:1511 —
    same tool choice (gsutil -m rsync), no SDK dependency.
    """

    store_type = StoreType.GCS

    def initialize(self) -> None:
        if self.exists():
            # Pre-existing bucket — never delete it on `storage delete`.
            self.sky_managed = False
            return
        if self.source is not None and data_utils.is_cloud_uri(self.source):
            raise exceptions.StorageBucketGetError(
                f'Source bucket {self.source!r} does not exist.')
        _run(['gsutil', 'mb', f'gs://{self.name}'],
             failure=f'Could not create bucket {self.name!r}')
        self.sky_managed = True

    def exists(self) -> bool:
        proc = subprocess.run(['gsutil', 'ls', '-b', f'gs://{self.name}'],
                              capture_output=True, text=True, check=False)
        return proc.returncode == 0

    def upload(self, source: str) -> None:
        source = os.path.abspath(os.path.expanduser(source))
        if os.path.isdir(source):
            excludes = storage_utils.get_excluded_files(source)
            # gsutil -x takes a single pipe-joined python-regex matched
            # against each file's bucket-relative path. A bare name like
            # '.git' must also exclude everything *inside* it, and match
            # at any path depth — fnmatch.translate alone anchors to the
            # whole path and would miss '.git/config'.
            parts = []
            for p in excludes:
                seg = fnmatch.translate(p)
                # Strip the terminating \Z (or \)\Z wrapper tail) that
                # translate() appends, keeping the (?s:...) group.
                if seg.endswith(r'\Z'):
                    seg = seg[:-2]
                parts.append(f'(^|.*/){seg}($|/.*)')
            regex = '|'.join(parts)
            _run(['gsutil', '-m', 'rsync', '-r', '-x', regex, source,
                  f'gs://{self.name}'],
                 failure=f'Upload to {self.name!r} failed')
        else:
            _run(['gsutil', 'cp', source, f'gs://{self.name}/'],
                 failure=f'Upload to {self.name!r} failed')

    def delete(self) -> None:
        if not self.sky_managed:
            logger.info('Bucket %s is external; not deleting.', self.name)
            return
        _run(['gsutil', '-m', 'rm', '-r', f'gs://{self.name}'],
             failure=f'Could not delete bucket {self.name!r}')

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.gcsfuse_mount_command(self.name, mount_path)

    def download_command(self, target: str) -> str:
        return (f'mkdir -p {target} && '
                f'gsutil -m rsync -r gs://{self.name} {target}')


class S3Store(AbstractStore):
    """S3 bucket via the aws CLI (same tool-over-SDK choice as GcsStore's
    gsutil; the reference's S3Store is boto3, sky/data/storage.py:1080).

    COPY mode batch-syncs via the aws CLI; MOUNT mode self-installs
    goofys and FUSE-mounts the bucket (reference mounts via goofys,
    sky/data/mounting_utils.py:24).
    """

    store_type = StoreType.S3

    def _aws(self, *args: str) -> List[str]:
        return ['aws', *args]

    def _endpoint_flags(self) -> List[str]:
        return []

    def _mount_endpoint(self) -> str:
        """S3-compatible subclasses (R2/COS) return their endpoint."""
        return ''

    def _endpoint_str(self) -> str:
        return ' '.join(self._endpoint_flags())

    def initialize(self) -> None:
        if self.exists():
            self.sky_managed = False
            return
        if self.source is not None and data_utils.is_cloud_uri(self.source):
            raise exceptions.StorageBucketGetError(
                f'Source bucket {self.source!r} does not exist.')
        _run(self._aws('s3', 'mb', f's3://{self.name}',
                       *self._endpoint_flags()),
             failure=f'Could not create bucket {self.name!r}')
        self.sky_managed = True

    def exists(self) -> bool:
        proc = subprocess.run(
            self._aws('s3api', 'head-bucket', '--bucket', self.name,
                      *self._endpoint_flags()),
            capture_output=True, text=True, check=False)
        return proc.returncode == 0

    def upload(self, source: str) -> None:
        source = os.path.abspath(os.path.expanduser(source))
        if os.path.isdir(source):
            # aws sync --exclude takes globs relative to the source dir;
            # a bare name must also exclude its contents.
            flags: List[str] = []
            for p in storage_utils.get_excluded_files(source):
                flags += ['--exclude', p, '--exclude', f'{p}/*']
            _run(self._aws('s3', 'sync', source, f's3://{self.name}',
                           *flags, *self._endpoint_flags()),
                 failure=f'Upload to {self.name!r} failed')
        elif os.path.exists(source):
            _run(self._aws('s3', 'cp', source, f's3://{self.name}/',
                           *self._endpoint_flags()),
                 failure=f'Upload to {self.name!r} failed')
        else:
            raise exceptions.StorageUploadError(
                f'Source {source!r} does not exist')

    def delete(self) -> None:
        if not self.sky_managed:
            logger.info('Bucket %s is external; not deleting.', self.name)
            return
        _run(self._aws('s3', 'rb', f's3://{self.name}', '--force',
                       *self._endpoint_flags()),
             failure=f'Could not delete bucket {self.name!r}')

    def mount_command(self, mount_path: str) -> str:
        # One goofys builder covers S3 and the S3-compatible stores
        # (R2/COS override _mount_endpoint, matching their aws-CLI
        # data paths).
        return mounting_utils.goofys_mount_command(
            self.name, mount_path, endpoint=self._mount_endpoint())

    def download_command(self, target: str) -> str:
        ep = self._endpoint_str()
        ep = f' {ep}' if ep else ''
        return (f'mkdir -p {target} && '
                f'aws s3 sync s3://{self.name} {target}{ep}')


class AzureBlobStore(AbstractStore):
    """Azure Blob container via the az CLI (reference: AzureBlobStore,
    sky/data/storage.py:1956 — SDK-based there; CLI here matching the
    gsutil/aws choice). The storage account comes from
    SKYT_AZURE_STORAGE_ACCOUNT; auth is whatever `az login` set up.

    MOUNT mode uses blobfuse2 with az-CLI auth (no key material on
    disk); COPY mode batch-downloads via the az CLI.
    """

    store_type = StoreType.AZURE

    @staticmethod
    def account() -> str:
        acct = env.get('SKYT_AZURE_STORAGE_ACCOUNT', '')
        if not acct:
            raise exceptions.StorageError(
                'Azure storage needs SKYT_AZURE_STORAGE_ACCOUNT in the '
                'environment.')
        return acct

    def _az(self, *args: str) -> List[str]:
        # --output json: exists() parses JSON, and a user-level
        # ~/.azure/config output=table would otherwise break the parse
        # (misread as "missing" -> create -> sky_managed=True -> delete()
        # could remove an external container).
        return ['az', 'storage', *args, '--account-name', self.account(),
                '--output', 'json']

    def initialize(self) -> None:
        if self.exists():
            self.sky_managed = False
            return
        if self.source is not None and data_utils.is_cloud_uri(self.source):
            raise exceptions.StorageBucketGetError(
                f'Source container {self.source!r} does not exist.')
        _run(self._az('container', 'create', '--name', self.name),
             failure=f'Could not create container {self.name!r}')
        self.sky_managed = True

    def exists(self) -> bool:
        proc = subprocess.run(
            self._az('container', 'exists', '--name', self.name),
            capture_output=True, text=True, check=False)
        return proc.returncode == 0 and '"exists": true' in proc.stdout

    def upload(self, source: str) -> None:
        import tempfile
        source = os.path.abspath(os.path.expanduser(source))
        if os.path.isdir(source):
            # `az storage blob upload-batch` has no exclude flag (only
            # the include-side --pattern), so excludes are applied
            # client-side: upload a filtered staging copy.
            excludes = storage_utils.get_excluded_files(source)

            def ignore(_d: str, names: List[str]) -> List[str]:
                return [n for n in names
                        if any(fnmatch.fnmatch(n, p) for p in excludes)]

            with tempfile.TemporaryDirectory(
                    prefix='skyt-az-upload-') as staging:
                stage_dir = os.path.join(staging, 'data')
                shutil.copytree(source, stage_dir, ignore=ignore,
                                symlinks=True)
                _run(self._az('blob', 'upload-batch', '--destination',
                              self.name, '--source', stage_dir,
                              '--overwrite'),
                     failure=f'Upload to {self.name!r} failed')
        elif os.path.exists(source):
            _run(self._az('blob', 'upload', '--container-name', self.name,
                          '--file', source, '--name',
                          os.path.basename(source), '--overwrite'),
                 failure=f'Upload to {self.name!r} failed')
        else:
            raise exceptions.StorageUploadError(
                f'Source {source!r} does not exist')

    def delete(self) -> None:
        if not self.sky_managed:
            logger.info('Container %s is external; not deleting.',
                        self.name)
            return
        _run(self._az('container', 'delete', '--name', self.name),
             failure=f'Could not delete container {self.name!r}')

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.blobfuse2_mount_command(
            self.account(), self.name, mount_path)

    def download_command(self, target: str) -> str:
        # --overwrite: re-running a COPY mount on an existing cluster
        # must refresh files like the gsutil/aws sync commands do.
        return (f'mkdir -p {target} && az storage blob download-batch '
                f'--destination {target} --source {self.name} '
                f'--account-name {self.account()} --overwrite '
                f'--output json')


class R2Store(S3Store):
    """Cloudflare R2: S3-compatible API behind an account endpoint
    (reference: sky/data/storage.py:2732 — boto3 with profile 'r2').
    The endpoint comes from SKYT_R2_ENDPOINT (or R2_ENDPOINT), e.g.
    https://<account_id>.r2.cloudflarestorage.com."""

    store_type = StoreType.R2

    @staticmethod
    def endpoint() -> str:
        ep = env.get('SKYT_R2_ENDPOINT',
                            os.environ.get('R2_ENDPOINT', ''))
        if not ep:
            raise exceptions.StorageError(
                'R2 needs SKYT_R2_ENDPOINT (https://<account_id>.'
                'r2.cloudflarestorage.com) in the environment.')
        return ep

    def _endpoint_flags(self) -> List[str]:
        return ['--endpoint-url', self.endpoint()]

    def _mount_endpoint(self) -> str:
        return self.endpoint()


class IbmCosStore(S3Store):
    """IBM Cloud Object Storage via its S3-compatible API (reference:
    IBMCosStore, sky/data/storage.py:3116 — rclone + ibm_boto3 there; the
    aws CLI against the regional COS endpoint here, matching the R2
    design). The endpoint comes from SKYT_COS_ENDPOINT (or COS_ENDPOINT),
    e.g. https://s3.us-south.cloud-object-storage.appdomain.cloud."""

    store_type = StoreType.COS

    @staticmethod
    def endpoint() -> str:
        ep = env.get('SKYT_COS_ENDPOINT',
                            os.environ.get('COS_ENDPOINT', ''))
        if not ep:
            raise exceptions.StorageError(
                'IBM COS needs SKYT_COS_ENDPOINT (https://s3.<region>.'
                'cloud-object-storage.appdomain.cloud) in the '
                'environment.')
        return ep

    def _endpoint_flags(self) -> List[str]:
        return ['--endpoint-url', self.endpoint()]

    def _mount_endpoint(self) -> str:
        return self.endpoint()


class LocalStore(AbstractStore):
    """Directory-backed bucket under SKYT_LOCAL_STORAGE_ROOT.

    The offline analog of GcsStore: same lifecycle, upload, MOUNT
    (symlink) and COPY semantics — what makes the storage layer testable
    without a cloud (SURVEY.md §4 implication: fake-cloud tier).
    """

    store_type = StoreType.LOCAL

    @property
    def bucket_dir(self) -> str:
        return os.path.join(data_utils.local_store_root(), self.name)

    def initialize(self) -> None:
        if self.exists():
            self.sky_managed = False
            return
        if self.source is not None and data_utils.is_cloud_uri(self.source):
            raise exceptions.StorageBucketGetError(
                f'Source bucket {self.source!r} does not exist.')
        os.makedirs(self.bucket_dir, exist_ok=True)
        self.sky_managed = True

    def exists(self) -> bool:
        return os.path.isdir(self.bucket_dir)

    def upload(self, source: str) -> None:
        source = os.path.abspath(os.path.expanduser(source))
        os.makedirs(self.bucket_dir, exist_ok=True)
        if os.path.isdir(source):
            excludes = storage_utils.get_excluded_files(source)

            def ignore(_d: str, names: List[str]) -> List[str]:
                return [n for n in names
                        if any(fnmatch.fnmatch(n, p) for p in excludes)]

            shutil.copytree(source, self.bucket_dir, ignore=ignore,
                            dirs_exist_ok=True, symlinks=True)
        elif os.path.exists(source):
            shutil.copy2(source, self.bucket_dir)
        else:
            raise exceptions.StorageUploadError(
                f'Source {source!r} does not exist')

    def delete(self) -> None:
        if not self.sky_managed:
            return
        shutil.rmtree(self.bucket_dir, ignore_errors=True)

    def mount_command(self, mount_path: str) -> str:
        return mounting_utils.local_mount_command(self.bucket_dir,
                                                  mount_path)

    def download_command(self, target: str) -> str:
        return (f'mkdir -p {target} && '
                f'cp -a {self.bucket_dir}/. {target}/')


_STORE_CLASSES = {StoreType.GCS: GcsStore, StoreType.S3: S3Store,
                  StoreType.AZURE: AzureBlobStore, StoreType.R2: R2Store,
                  StoreType.COS: IbmCosStore, StoreType.LOCAL: LocalStore}


def default_store_type() -> StoreType:
    """Store used when a spec names none: SKYT_DEFAULT_STORE env >
    config `storage.default_store` > GCS. The local provider / test
    harness sets `local` so no cloud CLI is ever invoked offline."""
    from skypilot_tpu import skyt_config
    name = env.get(
        'SKYT_DEFAULT_STORE',
        skyt_config.get_nested(('storage', 'default_store'), 'gcs'))
    return StoreType(str(name).upper())


class Storage:
    """Named bucket abstraction. Reference: sky/data/storage.py:384.

    source semantics (same as reference):
      * None          — scratch bucket named `name`, created on demand.
      * local path    — uploaded into the bucket on add_store().
      * gs://bucket   — external bucket; name defaults to the bucket name,
                        nothing is uploaded, never deleted.
    """

    def __init__(self,
                 name: Optional[str] = None,
                 source: Optional[str] = None,
                 mode: StorageMode = StorageMode.MOUNT,
                 persistent: bool = True) -> None:
        if name is None and source is None:
            raise exceptions.StorageError(
                'Storage needs a name or a source.')
        if source is not None and data_utils.is_cloud_uri(source):
            scheme, bucket, _ = data_utils.split_uri(source)
            if scheme not in data_utils.CLOUD_SCHEMES:
                managed = ', '.join(f'{s}://'
                                    for s in data_utils.CLOUD_SCHEMES)
                raise exceptions.StorageSourceError(
                    f'Managed storage supports {managed} sources; for '
                    f'one-shot downloads from {scheme}:// use a plain '
                    f'file_mount (cloud_stores.py).')
            if name is None:
                name = bucket
        elif source is not None:
            expanded = os.path.abspath(os.path.expanduser(source))
            if not os.path.exists(expanded):
                raise exceptions.StorageSourceError(
                    f'Local source {source!r} does not exist.')
            if name is None:
                raise exceptions.StorageNameError(
                    'A storage with a local source needs an explicit name.')
        assert name is not None
        data_utils.verify_bucket_name(name)
        self.name = name
        self.source = source
        self.mode = mode
        self.persistent = persistent
        self.stores: Dict[StoreType, AbstractStore] = {}

    # ----------------------------------------------------------- lifecycle
    def add_store(self, store_type: StoreType = StoreType.GCS) -> \
            AbstractStore:
        """Create/attach the bucket in `store_type` and upload a local
        source if present. Reference: Storage.add_store + sync."""
        if store_type in self.stores:
            return self.stores[store_type]
        source_is_uri = (self.source is not None and
                         data_utils.is_cloud_uri(self.source))
        store = _STORE_CLASSES[store_type](self.name, self.source)
        state.add_or_update_storage(self.name, self._handle(),
                                    state.StorageStatus.INIT)
        store.initialize()
        if self.source is not None and not source_is_uri:
            try:
                store.upload(self.source)
            except exceptions.StorageError:
                state.add_or_update_storage(
                    self.name, self._handle(),
                    state.StorageStatus.UPLOAD_FAILED)
                raise
        self.stores[store_type] = store
        state.add_or_update_storage(self.name, self._handle(),
                                    state.StorageStatus.READY)
        return store

    def delete(self, store_type: Optional[StoreType] = None) -> None:
        """Reference: Storage.delete — removes bucket(s) + state row."""
        targets = ([store_type] if store_type is not None
                   else list(self.stores))
        for st in targets:
            store = self.stores.pop(st, None)
            if store is not None:
                store.delete()
        if not self.stores:
            state.remove_storage(self.name)

    @classmethod
    def delete_by_name(cls, name: str) -> None:
        record = state.get_storage(name)
        if record is None:
            raise exceptions.StorageError(f'Storage {name!r} not found.')
        handle: StorageHandle = record['handle']
        storage = cls.from_handle(handle)
        storage.delete()

    @classmethod
    def from_handle(cls, handle: StorageHandle) -> 'Storage':
        """Rehydrate from the state DB WITHOUT re-validating the local
        source: the handle may be read on a machine (or at a time) where
        the source no longer exists — a controller VM deleting a
        translated bucket, or the post-upload cleanup of a staging dir —
        and deletion must still work."""
        storage = cls.__new__(cls)
        storage.name = handle.storage_name
        storage.source = handle.source
        storage.mode = StorageMode(handle.mode)
        storage.persistent = True
        storage.stores = {}
        for st_name in handle.store_types:
            st = StoreType(st_name)
            store = _STORE_CLASSES[st](handle.storage_name, handle.source,
                                       sky_managed=handle.sky_managed)
            storage.stores[st] = store
        return storage

    def _handle(self) -> StorageHandle:
        sky_managed = all(s.sky_managed for s in self.stores.values()) \
            if self.stores else True
        return StorageHandle(storage_name=self.name, source=self.source,
                             mode=self.mode.value,
                             store_types=[s.value for s in self.stores],
                             sky_managed=sky_managed)

    # ---------------------------------------------------------------- yaml
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Storage':
        """A `file_mounts` dict value: {name, source, store, mode,
        persistent}. Reference: Storage.from_yaml_config."""
        mode = StorageMode(config.get('mode', 'MOUNT').upper())
        storage = cls(name=config.get('name'),
                      source=config.get('source'),
                      mode=mode,
                      persistent=config.get('persistent', True))
        if 'store' in config and config['store'] is not None:
            storage._requested_store = StoreType(  # pylint: disable=attribute-defined-outside-init
                str(config['store']).upper())
        return storage

    def to_yaml_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {'name': self.name}
        if self.source is not None:
            cfg['source'] = self.source
        cfg['mode'] = self.mode.value
        if not self.persistent:
            cfg['persistent'] = False
        if self.stores:
            cfg['store'] = next(iter(self.stores)).value.lower()
        return cfg

    @property
    def requested_store(self) -> StoreType:
        explicit = getattr(self, '_requested_store', None)
        if explicit is not None:
            return explicit
        if self.source is not None and data_utils.is_cloud_uri(self.source):
            scheme, _, _ = data_utils.split_uri(self.source)
            return StoreType.from_scheme(scheme)
        return default_store_type()

    def __repr__(self) -> str:
        return (f'Storage({self.name!r}, source={self.source!r}, '
                f'mode={self.mode.value})')
