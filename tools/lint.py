#!/usr/bin/env python3
"""Dependency-free linter (the image ships no ruff/pylint/mypy; the
reference gates commits on format.sh — this is the offline equivalent).

Checks:
  * syntax (ast.parse)
  * unused imports (module scope and function scope, string-match
    aware for __all__/docstring re-exports)
  * tabs and trailing whitespace
  * lines over the limit (default 88)
  * bare print() in skypilot_tpu/ — framework code must log through
    utils/log_utils loggers so serving/metrics output stays structured
    (exceptions: the console-surface allowlist below, or `# noqa`)
  * host syncs (jax.device_get / block_until_ready) inside loops in
    train/sft.py — the step loop must stay off the device's critical
    path; metrics pulls go through trainer.DeferredMetrics
    (docs/performance.md). Mark deliberate exceptions with `# noqa`.
  * silent broad swallows (`except Exception: pass` and bare
    `except: pass`) in skypilot_tpu/ — a robustness-first codebase
    must at least log what it ignores (docs/robustness.md). The
    audited pre-existing sites live in _EXCEPT_PASS_OK; new deliberate
    ones need `# noqa` plus a comment saying why.
  * direct `._waiting.put(` callsites in skypilot_tpu/infer/ outside
    the QoS admission path (docs/qos.md) — with SKYT_QOS=1 the waiting
    queue is the priority scheduler, and code enqueueing around the
    sanctioned sites would bypass classing silently. The sanctioned
    sites carry a `qos-admission` marker comment.
  * bare `pl.pallas_call(` outside skypilot_tpu/ops/ — every kernel
    must live in ops/ and route through the dispatch ladder
    (ops/dispatch.py, docs/kernels.md) so it inherits shape-robust
    block selection, the XLA fallback rung, and kernel-path metrics.
    A Pallas call elsewhere would reintroduce the BENCH_r02 class of
    hard lowering crash. Mark a deliberate exception with `# noqa`.
  * direct `sqlite3.connect(` in skypilot_tpu/ outside
    utils/sqlite_utils.py (and serve/serve_state.py, which owns the
    serve.db open-with-integrity-check) — every state DB is shared
    across processes (controller, standby LB, client CLI), and a raw
    connect misses the WAL + busy-timeout recipe that makes that safe
    (docs/robustness.md "Control plane"). `# noqa` for deliberate
    exceptions.
  * direct `time.time()` / `time.monotonic()` (and perf_counter)
    calls in serve/slo.py, utils/timeseries.py, train/heartbeat.py and
    train/watchdog.py — those modules take INJECTABLE clocks so SLO
    burn-rate math and the gang watchdog's hang/straggler truth table
    replay deterministically in tests (docs/observability.md); a stray
    wall-clock call would fork the timeline. Referencing `time.time`
    as a default clock argument is fine — only calls flag. `# noqa`
    escape hatch.

Exit 0 = clean. Used by format.sh and tests/test_lint.py.
"""
import ast
import re
import sys
from pathlib import Path

LINE_LIMIT = 88

# Imports that exist for side effects or re-export by convention.
_SIDE_EFFECT_OK = {'skypilot_tpu', 'conftest'}

# Modules whose stdout IS the interface — CLI surfaces, console log
# relays streaming remote job output to the user's terminal, and train
# examples whose printed lines are the job's log contract. Everything
# else under skypilot_tpu/ must use log_utils loggers; mark deliberate
# one-off exceptions with `# noqa`.
_PRINT_OK_PREFIXES = (
    'skypilot_tpu/cli.py',
    'skypilot_tpu/check.py',
    'skypilot_tpu/dashboard.py',            # startup URL banner
    'skypilot_tpu/utils/command_runner.py',  # remote stdout relay
    'skypilot_tpu/runtime/log_lib.py',       # job log tailing
    'skypilot_tpu/runtime/rpc.py',           # log streaming + CLI JSON
    'skypilot_tpu/backends/tpu_backend.py',  # provision log relay
    'skypilot_tpu/jobs/core.py',             # jobs logs CLI surface
    'skypilot_tpu/serve/core.py',            # serve logs CLI surface
    'skypilot_tpu/parallel/collectives.py',  # bench CLI output
    'skypilot_tpu/catalog/data_fetchers/',   # fetcher CLI scripts
    'skypilot_tpu/train/examples/',          # example job stdout
)


# Audited `except Exception: pass` sites that predate the lint rule —
# each swallows on a genuinely-best-effort path (crash-handler
# broadcast, opt-in usage telemetry, profiler teardown). New silent
# swallows must log, narrow the exception, or carry `# noqa`.
_EXCEPT_PASS_OK = (
    'skypilot_tpu/infer/engine.py',
    'skypilot_tpu/usage/usage_lib.py',
    'skypilot_tpu/utils/profiling.py',
)


def _except_pass_issues(path: Path, tree, lines):
    """Flag broad exception handlers whose entire body is `pass`."""
    issues = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        t = node.type
        broad = (t is None or
                 (isinstance(t, ast.Name) and
                  t.id in ('Exception', 'BaseException')) or
                 (isinstance(t, ast.Attribute) and
                  t.attr in ('Exception', 'BaseException')))
        if not broad:
            continue
        if len(node.body) != 1 or not isinstance(node.body[0], ast.Pass):
            continue
        if node.lineno <= len(lines) and 'noqa' in lines[node.lineno - 1]:
            continue
        issues.append(
            f'{path}:{node.lineno}: except Exception: pass — silent '
            f'broad swallow; log it, narrow the exception, or add '
            f'`# noqa` with a justification')
    return issues


# QoS admission discipline (docs/qos.md): the engine's waiting queue
# is the ONE priority-scheduling point — new code in infer/ must route
# requests through engine.submit / the lockstep tick sync, never
# enqueue directly. Sanctioned sites are marked `qos-admission`.
_WAITING_PUT_RE = re.compile(r'\._waiting\.put\(')


def _waiting_put_issues(path: Path, lines):
    issues = []
    for i, line in enumerate(lines, 1):
        if not _WAITING_PUT_RE.search(line):
            continue
        if 'qos-admission' in line or 'noqa' in line:
            continue
        issues.append(
            f'{path}:{i}: direct ._waiting.put( outside the QoS '
            f'admission path — route through engine.submit so '
            f'priority classing cannot be bypassed (or mark a '
            f'sanctioned admission site with `# qos-admission`)')
    return issues


# Kernel discipline (docs/kernels.md): pl.pallas_call may only appear
# under skypilot_tpu/ops/ — call sites elsewhere go through the
# dispatch ladder, which guarantees a legal block spec or an XLA
# fallback. Comments are stripped before matching so prose can't flag;
# a docstring mentioning the literal call form still would — mark
# those (and deliberate exceptions) with `# noqa`.
_PALLAS_CALL_RE = re.compile(r'\bpallas_call\s*\(')


def _pallas_call_issues(path: Path, lines):
    issues = []
    for i, line in enumerate(lines, 1):
        if not _PALLAS_CALL_RE.search(line.split('#', 1)[0]):
            continue
        if 'noqa' in line:
            continue
        issues.append(
            f'{path}:{i}: pallas_call outside skypilot_tpu/ops/ — '
            f'kernels live in ops/ and dispatch through '
            f'ops/dispatch.run_ladder so every shape lowers or falls '
            f'back (or add `# noqa` with a justification)')
    return issues


# State-DB discipline (docs/robustness.md "Control plane"): every
# sqlite connection in framework code goes through
# utils/sqlite_utils.connect — WAL + busy-timeout is what lets the
# controller, a standby LB, and the client CLI share one DB without
# 'database is locked' flakes. serve_state.py additionally wraps the
# open in its corrupt/fail-fast check and may own raw pragmas.
_SQLITE_CONNECT_RE = re.compile(r'\bsqlite3\s*\.\s*connect\s*\(')
_SQLITE_CONNECT_OK = (
    'skypilot_tpu/utils/sqlite_utils.py',
    'skypilot_tpu/serve/serve_state.py',
)


def _sqlite_connect_issues(path: Path, lines):
    issues = []
    for i, line in enumerate(lines, 1):
        if not _SQLITE_CONNECT_RE.search(line.split('#', 1)[0]):
            continue
        if 'noqa' in line:
            continue
        issues.append(
            f'{path}:{i}: direct sqlite3.connect( — state DBs are '
            f'multi-process; open them through '
            f'utils/sqlite_utils.connect so the WAL + busy-timeout '
            f'recipe applies (or add `# noqa` with a justification)')
    return issues


# Clock discipline (docs/observability.md "Fleet plane" + "Training
# plane"): these files implement windowed SLO/burn-rate math and the
# heartbeat/watchdog stall budgets that tests replay under fake clocks
# — every timestamp must come through the injected clock, so a direct
# wall-clock CALL is a determinism bug. Default arguments like
# `clock=time.time` are references, not calls, and pass.
_INJECTABLE_CLOCK_FILES = ('skypilot_tpu/serve/slo.py',
                           'skypilot_tpu/utils/timeseries.py',
                           'skypilot_tpu/train/heartbeat.py',
                           'skypilot_tpu/train/watchdog.py')
_CLOCK_CALL_NAMES = ('time', 'monotonic', 'perf_counter')


def _clock_call_issues(path: Path, tree, lines):
    issues = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and
                f.attr in _CLOCK_CALL_NAMES and
                isinstance(f.value, ast.Name) and f.value.id == 'time'):
            continue
        if node.lineno <= len(lines) and 'noqa' in lines[node.lineno - 1]:
            continue
        issues.append(
            f'{path}:{node.lineno}: direct time.{f.attr}() — this '
            f'module must read time through its injectable clock so '
            f'SLO math replays deterministically '
            f'(docs/observability.md), or add `# noqa`')
    return issues


# Files whose loops may not contain host-sync calls: the sft step loop
# is the train hot path — one bare jax.device_get per step serializes
# host and device (the deferred-metrics helper in train/trainer.py is
# the sanctioned pull point, one step behind the chain's head).
_NO_SYNC_IN_LOOPS = ('skypilot_tpu/train/sft.py',)
_SYNC_CALL_NAMES = ('device_get', 'block_until_ready')


def _loop_sync_issues(path: Path, tree, lines):
    """Flag device_get/block_until_ready calls inside any loop."""
    issues = []
    seen = set()
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
            continue
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                getattr(f, 'id', '')
            if name not in _SYNC_CALL_NAMES or node.lineno in seen:
                continue
            if node.lineno <= len(lines) and \
                    'noqa' in lines[node.lineno - 1]:
                continue
            seen.add(node.lineno)
            issues.append(
                f'{path}:{node.lineno}: {name}() inside the sft step '
                f'loop — host syncs stall the device; pull metrics '
                f'through trainer.DeferredMetrics (or add `# noqa` '
                f'for a deliberate one-off)')
    return issues


def _print_allowed(path: Path) -> bool:
    posix = path.as_posix()
    for p in _PRINT_OK_PREFIXES:
        if p.endswith('/'):
            if p in posix:
                return True
        elif posix.endswith(p):
            return True
    return False


def _imported_names(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split('.')[0]
                yield node.lineno, alias.name, name
        elif isinstance(node, ast.ImportFrom):
            if node.module == '__future__':
                continue
            for alias in node.names:
                if alias.name == '*':
                    continue
                name = alias.asname or alias.name
                yield node.lineno, alias.name, name


def check_file(path: Path):
    issues = []
    src = path.read_text(encoding='utf-8')
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [f'{path}:{e.lineno}: syntax error: {e.msg}']

    is_init = path.name == '__init__.py'
    lines = src.splitlines()
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # base captured via its Name node
    # Names referenced inside strings (docstring examples, __all__).
    text_blob = src
    if not is_init:
        for lineno, _full, name in _imported_names(tree):
            if name in used or name in _SIDE_EFFECT_OK:
                continue
            if lineno <= len(lines) and 'noqa' in lines[lineno - 1]:
                continue
            # String annotations ('spec_lib.ServiceSpec') and __all__.
            if re.search(rf'[\'"]{re.escape(name)}\b', text_blob):
                continue
            issues.append(f'{path}:{lineno}: unused import {name!r}')

    if any(path.as_posix().endswith(p) for p in _NO_SYNC_IN_LOOPS):
        issues += _loop_sync_issues(path, tree, lines)

    if any(path.as_posix().endswith(p)
           for p in _INJECTABLE_CLOCK_FILES):
        issues += _clock_call_issues(path, tree, lines)

    if 'skypilot_tpu/infer/' in path.as_posix():
        issues += _waiting_put_issues(path, lines)

    if 'skypilot_tpu' in path.as_posix() and \
            'skypilot_tpu/ops/' not in path.as_posix():
        issues += _pallas_call_issues(path, lines)

    if 'skypilot_tpu' in path.as_posix() and not any(
            path.as_posix().endswith(p) for p in _SQLITE_CONNECT_OK):
        issues += _sqlite_connect_issues(path, lines)

    if 'skypilot_tpu' in path.as_posix() and not any(
            path.as_posix().endswith(p) for p in _EXCEPT_PASS_OK):
        issues += _except_pass_issues(path, tree, lines)

    if 'skypilot_tpu' in path.as_posix() and not _print_allowed(path):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == 'print':
                if node.lineno <= len(lines) and \
                        'noqa' in lines[node.lineno - 1]:
                    continue
                issues.append(
                    f'{path}:{node.lineno}: bare print() — use a '
                    f'log_utils logger (or add to the lint allowlist '
                    f'if stdout is this module\'s interface)')

    for i, line in enumerate(src.splitlines(), 1):
        if '\t' in line:
            issues.append(f'{path}:{i}: tab character')
        if line != line.rstrip():
            issues.append(f'{path}:{i}: trailing whitespace')
        if len(line) > LINE_LIMIT and 'http' not in line and \
                'noqa' not in line and 'pylint:' not in line:
            issues.append(f'{path}:{i}: line too long '
                          f'({len(line)} > {LINE_LIMIT})')
    return issues


def main(argv):
    roots = argv or ['skypilot_tpu', 'tests', 'tools', 'bench.py',
                     '__graft_entry__.py']
    files = []
    for root in roots:
        p = Path(root)
        if p.is_dir():
            files += sorted(p.rglob('*.py'))
        elif p.exists():
            files.append(p)
    all_issues = []
    for f in files:
        if '__pycache__' in str(f):
            continue
        all_issues += check_file(f)
    for issue in all_issues:
        print(issue)
    print(f'{len(files)} files checked, {len(all_issues)} issue(s)')
    return 1 if all_issues else 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
