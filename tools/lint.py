#!/usr/bin/env python3
"""CLI entry point for skyanalyze (tools/analysis) — the
dependency-free AST static analyzer that replaced the original
regex linter. Same invocation format.sh and tests/test_lint.py have
always used; exit 0 = clean.

    python tools/lint.py                    full tree, human output
    python tools/lint.py path [path ...]    file passes on those paths
    python tools/lint.py --json OUT.json    also write the JSON
                                            artifact (tpu_validation.sh
                                            archives it with probe.json)
    python tools/lint.py --write-env-docs   regenerate docs/env_vars.md
                                            from the env registry

Passes (catalog + noqa grammar: docs/static_analysis.md):
  * the nine rules ported from the regex linter — unused-import,
    whitespace, print-call, loop-host-sync, clock-injection,
    qos-admission, kernel-dispatch, sqlite-discipline, except-pass —
    plus the syntax gate;
  * lock-discipline — attributes written under a class's lock are
    never accessed lock-free (the PR 7/9 review-race class);
  * async-blocking — no time.sleep / sync HTTP / sqlite / file I/O
    on the serve/infer event loops;
  * tracer-safety — functions reachable from jax.jit / pallas_call /
    the dispatch ladder stay tracer-pure;
  * env-registry — every SKYT_* read resolves through
    utils/env.py, and docs/env_vars.md is generated + fresh;
  * registry-consistency — fault points, metric families, and
    JobStatus terminal states match their docs catalogs.

Project-wide passes (the last three) run only in full-tree mode (no
explicit path arguments) — linting one file stays fast and local.
"""
import sys
from pathlib import Path

_HERE = Path(__file__).resolve().parent
_REPO = _HERE.parent
if str(_HERE) not in sys.path:
    sys.path.insert(0, str(_HERE))

from analysis import core as _core          # noqa: E402


def check_file(path):
    """Single-file API kept for tests/test_lint.py: formatted issue
    strings from every file-scoped pass."""
    return _core.check_file(path)


def write_env_docs() -> Path:
    """Regenerate docs/env_vars.md from the env registry."""
    from analysis import env_registry
    mod = env_registry._load_registry(
        _REPO / 'skypilot_tpu' / 'utils' / 'env.py')
    out = _REPO / 'docs' / 'env_vars.md'
    out.write_text(mod.generate_docs(), encoding='utf-8')
    return out


def main(argv):
    json_path = None
    roots = []
    args = list(argv)
    while args:
        a = args.pop(0)
        if a == '--json':
            if not args:
                print('--json needs an output path')
                return 2
            json_path = args.pop(0)
        elif a == '--write-env-docs':
            path = write_env_docs()
            print(f'wrote {path}')
            return 0
        else:
            roots.append(a)

    # Explicit paths = file passes only; full default tree = file +
    # project passes, rooted at the repo (independent of cwd).
    if roots:
        root, project = Path('.'), False
        if any(Path(r).is_absolute() for r in roots):
            root = Path('/')
            roots = [str(Path(r).resolve().relative_to(root))
                     for r in roots]
    else:
        root, project = _REPO, True
        if Path.cwd() == _REPO:
            root = Path('.')
    violations = _core.analyze(root, roots or None,
                               project_passes=project)
    files = _core.count_files(root, roots or None)
    for v in violations:
        print(v.format())
    print(f'{files} files checked, {len(violations)} issue(s)')
    if json_path:
        Path(json_path).write_text(
            _core.render_json(violations, files), encoding='utf-8')
    return 1 if violations else 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1:]))
