"""async-blocking: no synchronous blocking calls inside ``async def``
bodies in the serve plane and the inference server.

One blocking call on the event loop stalls EVERY in-flight request on
that process — the LB proxies all traffic through one loop, and the
replica server multiplexes all HTTP + engine callbacks through one.
Flagged inside async functions (nested *sync* defs are skipped —
they are what you hand to ``asyncio.to_thread`` / executors):

  * ``time.sleep``                    (use ``asyncio.sleep``)
  * ``requests.*`` / ``urllib.request.urlopen`` / bare ``urlopen``
                                      (use the shared aiohttp session)
  * ``sqlite3.*`` / ``sqlite_utils.connect``
                                      (DB work goes to a thread)
  * builtin ``open``                  (file I/O goes to a thread)
  * ``subprocess.run/call/check_*``, ``os.system``, ``*.wait()`` on a
    Popen is not detected — use ``asyncio.create_subprocess_exec``

Deliberate exceptions (startup-only paths, tiny local files) carry
``# noqa: async-blocking`` with a why-comment.
"""
import ast
from typing import List, Optional

from .core import FileContext, Pass, Violation

_BLOCKING_MODULE_CALLS = {
    'time': ('sleep',),
    'requests': ('get', 'post', 'put', 'delete', 'head', 'patch',
                 'request'),
    'sqlite3': ('connect',),
    'subprocess': ('run', 'call', 'check_call', 'check_output'),
    'os': ('system',),
    'sqlite_utils': ('connect',),
}
_BLOCKING_NAMES = ('urlopen',)


def _blocking_reason(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        if f.id in _BLOCKING_NAMES:
            return f'{f.id}()'
        if f.id == 'open':
            return 'open()'
        return None
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr == 'urlopen':
        return 'urllib urlopen()'
    base = f.value
    if isinstance(base, ast.Name):
        mod = base.id
        if f.attr in _BLOCKING_MODULE_CALLS.get(mod, ()):
            return f'{mod}.{f.attr}()'
    # urllib.request.urlopen handled above via attr == 'urlopen'.
    return None


class AsyncBlockingPass(Pass):
    id = 'async-blocking'
    title = 'no blocking calls on the serve/infer event loops'

    def applies(self, ctx: FileContext) -> bool:
        return 'skypilot_tpu/serve/' in ctx.rel or \
            ctx.rel.endswith('skypilot_tpu/infer/server.py')

    def run(self, ctx: FileContext) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                self._check_async_body(ctx, node, out)
        return out

    def _check_async_body(self, ctx: FileContext,
                          fn: ast.AsyncFunctionDef,
                          out: List[Violation]) -> None:
        stack = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.Lambda)):
                # Sync helpers defined inside an async fn are executor
                # / thread targets — not run on the loop here.
                continue
            if isinstance(node, ast.AsyncFunctionDef):
                # Visited by the outer ast.walk on its own.
                continue
            if isinstance(node, ast.Call):
                reason = _blocking_reason(node)
                if reason is not None:
                    out.append(Violation(
                        ctx.rel, node.lineno, self.id,
                        f'blocking {reason} inside async def '
                        f'{fn.name}() — this stalls every request on '
                        f'the event loop; use the async equivalent '
                        f'(asyncio.sleep, the aiohttp session, '
                        f'asyncio.to_thread) or add '
                        f'`# noqa: async-blocking` with a '
                        f'why-comment'))
            stack.extend(ast.iter_child_nodes(node))
