"""skyanalyze: dependency-free AST static analysis for skypilot-tpu.

The framework (core.py) runs two kinds of passes over the tree:

  * file passes — see one parsed file at a time (the nine rules
    ported from the original regex linter, plus lock-discipline and
    async-blocking);
  * project passes — see every parsed file plus docs/ (tracer-safety
    reachability, env-registry drift, registry-consistency).

``tools/lint.py`` is the CLI entry point (unchanged invocation;
``--json`` and ``--write-env-docs`` are additive). Suppression is
per-line: bare ``# noqa`` (or ``# noqa: <free-text reason>``)
suppresses every pass on that line; ``# noqa: <pass-id>[, <pass-id>]``
suppresses only the named passes. docs/static_analysis.md is the pass
catalog and how-to.
"""
from .core import (  # noqa: re-exports
    FileContext,
    Project,
    Violation,
    all_passes,
    analyze,
    check_file,
    render_json,
)
