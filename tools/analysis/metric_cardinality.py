"""metric-cardinality: metric label values must come from bounded
sets.

A Prometheus-style registry keeps one child series per distinct label
tuple forever, so a label fed from an unbounded source — request ids,
trace/span ids, raw session keys, raw header strings — grows the
registry without bound until the per-family series cap starts
dropping REAL series (utils/metrics.py SKYT_METRICS_MAX_SERIES). The
capacity plane's per-(class, tenant, model) families make this easy
to get wrong: class is a parsed enum, tenant is charset/length-
bounded by qos.parse_tenant, model is the loaded-adapter set — and
every new family must keep that discipline.

Two checks (docs/static_analysis.md):

  * **declarations** — a ``registry.counter/gauge/histogram`` family
    whose label NAMES include an id-like name (``request_id``,
    ``trace_id``, ``session_id``, ...) is flagged: the name promises
    per-identifier series, which is a time-series DB's job, not a
    metric registry's;
  * **label call sites** — a ``.labels(...)`` argument that is an
    id-like variable/attribute (``req.req_id``), or a raw read of
    request-controlled strings (``request.headers.get(...)``,
    ``request.query[...]``, ``match_info``), is flagged: route label
    values through a parser that bounds them (qos.parse_priority /
    parse_tenant, a resolved-model lookup) first.

Suppress a justified site with ``# noqa: metric-cardinality``.
"""
import ast
from typing import List, Optional

from .core import FileContext, Pass, Violation

# Label names that promise one series per identifier. 'path' and
# 'code' are NOT here: route templates and status codes are bounded.
_ID_LABEL_NAMES = frozenset({
    'id', 'request_id', 'req_id', 'rid', 'trace_id', 'span_id',
    'session', 'session_id', 'user_id', 'uuid', 'url'})

# Attributes whose reads yield request-controlled strings.
_RAW_REQUEST_ATTRS = frozenset({'headers', 'query', 'match_info'})

_FAMILY_METHODS = ('counter', 'gauge', 'histogram')


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _raw_request_read(node: ast.AST) -> bool:
    """request.headers.get(...), request.query['x'], ...match_info —
    a request-controlled string reaching a label unparsed."""
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == 'get':
            return _raw_request_read(f.value) or (
                isinstance(f.value, ast.Attribute) and
                f.value.attr in _RAW_REQUEST_ATTRS)
        return False
    if isinstance(node, ast.Subscript):
        return isinstance(node.value, ast.Attribute) and \
            node.value.attr in _RAW_REQUEST_ATTRS
    if isinstance(node, ast.Attribute):
        return node.attr in _RAW_REQUEST_ATTRS
    return False


class MetricCardinalityPass(Pass):
    id = 'metric-cardinality'
    title = 'metric label values must come from bounded sets'

    def applies(self, ctx: FileContext) -> bool:
        return 'skypilot_tpu' in ctx.rel

    def run(self, ctx: FileContext) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr in _FAMILY_METHODS:
                out += self._check_declaration(ctx, node)
            elif node.func.attr == 'labels':
                out += self._check_labels_call(ctx, node)
        return out

    def _check_declaration(self, ctx: FileContext,
                           node: ast.Call) -> List[Violation]:
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith('skyt_')):
            return []
        largs = node.args[2] if len(node.args) > 2 else next(
            (kw.value for kw in node.keywords
             if kw.arg == 'labelnames'), None)
        if not isinstance(largs, (ast.Tuple, ast.List)):
            return []
        out = []
        for elt in largs.elts:
            if isinstance(elt, ast.Constant) and \
                    isinstance(elt.value, str) and \
                    elt.value in _ID_LABEL_NAMES:
                out.append(Violation(
                    ctx.rel, elt.lineno, self.id,
                    f'metric family {node.args[0].value!r} declares '
                    f'id-like label {elt.value!r} — one series per '
                    f'identifier is unbounded cardinality; put '
                    f'per-request detail on traces, not metrics'))
        return out

    def _check_labels_call(self, ctx: FileContext,
                           node: ast.Call) -> List[Violation]:
        out = []
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            name = _terminal_name(arg)
            if name in _ID_LABEL_NAMES:
                out.append(Violation(
                    ctx.rel, node.lineno, self.id,
                    f'.labels() argument {name!r} looks like an '
                    f'unbounded identifier — label values must come '
                    f'from a bounded set (parsed class/tenant, '
                    f'resolved model, enum)'))
            elif _raw_request_read(arg):
                out.append(Violation(
                    ctx.rel, node.lineno, self.id,
                    f'.labels() argument on line {node.lineno} reads '
                    f'request-controlled input directly — bound it '
                    f'first (qos.parse_priority/parse_tenant or an '
                    f'allowlist lookup)'))
        return out
