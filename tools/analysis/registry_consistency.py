"""registry-consistency: runtime registries and their docs catalogs
cannot drift.

Four sub-checks, one pass id:

  * fault points — every ``faults.inject('p')`` / ``ainject('p')``
    call site must have a row in docs/robustness.md's fault-point
    table (`| \\`point\\` | ...`), and every table row must have a
    live call site (a stale row documents a drill that no longer
    exists);
  * metric families — every ``registry.counter/gauge/histogram``
    family named ``skyt_*`` must appear in docs (observability.md,
    qos.md, robustness.md, ...); where the docs attach a label set
    (``name{a,b}``) it must equal the code's label names. Docs may
    use brace alternation (``skyt_slo_{good_,}requests_total``);
  * HTTP debug/fleet surface — every ``add_get``/``add_post`` route
    under ``/debug/*`` or ``/fleet/*`` must appear in
    docs/observability.md, and every such route token in the doc
    must have a live registration (the surface grew to ~10 routes
    across five PRs with no machine check);
  * JobStatus terminal states — the ``_TERMINAL`` set in
    runtime/job_lib.py must equal the backticked list on the
    ``Terminal states:`` line of docs/managed-jobs.md.

Sub-checks skip silently when their code-side file is absent (small
fixture trees exercise one check at a time), but doc-side absence
with code-side presence is drift and flags.
"""
import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Pass, Project, Violation

_FAULT_DOC_REL = 'docs/robustness.md'
_JOBS_DOC_REL = 'docs/managed-jobs.md'
_METRIC_DOC_RELS = ('docs/observability.md', 'docs/qos.md',
                    'docs/robustness.md', 'docs/serving.md',
                    'docs/kernels.md', 'docs/performance.md')

# Fault points are usually dotted (`plane.event`) but may be bare
# (`reshard`); requiring two more table cells after the name keeps
# the two-column kinds table (`| error | ... |`) from matching.
_FAULT_ROW_RE = re.compile(
    r'^\|\s*`([a-z0-9_.]+)`\s*\|[^|]*\|[^|]*\|')
# A metric token: name chars, with {a,b} alternation groups that are
# part of the NAME only when followed by more name chars (a trailing
# {...} group is a label set).
_METRIC_TOK_RE = re.compile(
    r'skyt_(?:[a-z0-9_]|\{[a-z0-9_,]*\}(?=[a-z0-9_]))*'
    r'(?:\{(?P<labels>[a-z0-9_,]+)\})?')
_TERMINAL_LINE_RE = re.compile(r'^Terminal states?:\s*(.*)$')
# A /debug/* or /fleet/* route token (code-side: the literal first
# argument of add_get/add_post; doc-side: any occurrence in
# docs/observability.md's prose or route-catalog table).
_ROUTE_DOC_REL = 'docs/observability.md'
_ROUTE_TOK_RE = re.compile(r'/(?:debug|fleet)/[a-z_]+')


def _expand_braces(tok: str) -> List[str]:
    m = re.search(r'\{([^{}]*)\}', tok)
    if not m:
        return [tok]
    out: List[str] = []
    for alt in m.group(1).split(','):
        out.extend(_expand_braces(tok[:m.start()] + alt + tok[m.end():]))
    return out


class RegistryConsistencyPass(Pass):
    id = 'registry-consistency'
    title = 'fault/metric/JobStatus catalogs match the code'
    scope = 'project'

    def run_project(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        out += self._check_faults(project)
        out += self._check_metrics(project)
        out += self._check_http_routes(project)
        out += self._check_terminal_states(project)
        return out

    # ---------------------------------------------------- fault points
    def _check_faults(self, project: Project) -> List[Violation]:
        sites: Dict[str, Tuple[str, int]] = {}
        for ctx in project.files:
            if ctx.tree is None or 'skypilot_tpu' not in ctx.rel or \
                    ctx.rel.endswith('utils/faults.py'):
                continue
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr in ('inject', 'ainject') and
                        isinstance(node.func.value, ast.Name) and
                        node.func.value.id == 'faults' and
                        node.args and
                        isinstance(node.args[0], ast.Constant)):
                    continue
                sites.setdefault(str(node.args[0].value),
                                 (ctx.rel, node.lineno))
        if not sites:
            return []
        doc = project.doc(_FAULT_DOC_REL)
        if doc is None:
            return []
        documented: Dict[str, int] = {}
        for i, line in enumerate(doc.splitlines(), 1):
            m = _FAULT_ROW_RE.match(line.strip())
            if m:
                documented.setdefault(m.group(1), i)
        out: List[Violation] = []
        for point, (rel, lineno) in sorted(sites.items()):
            if point not in documented:
                out.append(Violation(
                    rel, lineno, self.id,
                    f'fault point {point!r} has no row in the '
                    f'docs/robustness.md fault-point table — every '
                    f'injectable point is part of the chaos-drill '
                    f'contract and must be cataloged (point, '
                    f'location, attrs, supported kinds)'))
        doc_rel = (project.root / _FAULT_DOC_REL).as_posix()
        for point, lineno in sorted(documented.items()):
            if point not in sites:
                out.append(Violation(
                    doc_rel, lineno, self.id,
                    f'fault-point table row {point!r} has no '
                    f'faults.inject/ainject call site — the drill it '
                    f'documents no longer exists; delete the row or '
                    f'restore the point'))
        return out

    # -------------------------------------------------------- metrics
    def _metric_families(self, project: Project
                         ) -> Dict[str, Tuple[str, int,
                                              Optional[Tuple[str, ...]]]]:
        fams: Dict[str, Tuple[str, int, Optional[Tuple[str, ...]]]] = {}
        for ctx in project.files:
            if ctx.tree is None or 'skypilot_tpu' not in ctx.rel:
                continue
            consts = {}
            for node in ctx.tree.body:
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        isinstance(node.value, ast.Constant) and \
                        isinstance(node.value.value, str):
                    consts[node.targets[0].id] = node.value.value
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr in ('counter', 'gauge',
                                           'histogram') and node.args):
                    continue
                a = node.args[0]
                name = a.value if isinstance(a, ast.Constant) else \
                    consts.get(getattr(a, 'id', ''))
                if not (isinstance(name, str) and
                        name.startswith('skyt_')):
                    continue
                labels: Optional[Tuple[str, ...]] = None
                largs = node.args[2] if len(node.args) > 2 else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == 'labelnames'), None)
                if isinstance(largs, (ast.Tuple, ast.List)):
                    if all(isinstance(e, ast.Constant)
                           for e in largs.elts):
                        labels = tuple(e.value for e in largs.elts)
                fams.setdefault(name, (ctx.rel, node.lineno, labels))
        return fams

    def _doc_metrics(self, project: Project
                     ) -> Dict[str, Set[Tuple[str, ...]]]:
        """name -> set of label tuples seen in docs (() = bare)."""
        seen: Dict[str, Set[Tuple[str, ...]]] = {}
        for rel in _METRIC_DOC_RELS:
            doc = project.doc(rel)
            if doc is None:
                continue
            for m in _METRIC_TOK_RE.finditer(doc):
                tok = m.group(0)
                labels = m.group('labels')
                name_part = tok[:-(len(labels) + 2)] if labels else tok
                ltuple = tuple(labels.split(',')) if labels else ()
                for name in _expand_braces(name_part):
                    name = name.rstrip('_')
                    if len(name) > len('skyt_'):
                        seen.setdefault(name, set()).add(ltuple)
        return seen

    def _check_metrics(self, project: Project) -> List[Violation]:
        fams = self._metric_families(project)
        if not fams:
            return []
        documented = self._doc_metrics(project)
        out: List[Violation] = []
        for name, (rel, lineno, labels) in sorted(fams.items()):
            if name not in documented:
                out.append(Violation(
                    rel, lineno, self.id,
                    f'metric family {name!r} is not documented in '
                    f'any docs catalog '
                    f'({", ".join(_METRIC_DOC_RELS[:2])}, ...) — '
                    f'operators alert on these; add it where its '
                    f'plane is described'))
                continue
            doc_labelsets = {s for s in documented[name] if s}
            if labels is not None and doc_labelsets and \
                    not any(set(s) == set(labels)
                            for s in doc_labelsets):
                shown = sorted(doc_labelsets)[0]
                out.append(Violation(
                    rel, lineno, self.id,
                    f'metric family {name!r} label set '
                    f'{tuple(labels)!r} does not match the '
                    f'documented label set {shown!r} — fix '
                    f'whichever is stale'))
        return out

    # ---------------------------------------------- HTTP debug surface
    def _check_http_routes(self, project: Project) -> List[Violation]:
        """Route registrations (`add_get('/debug/x', ...)` /
        `add_post('/fleet/y', ...)`) vs the docs/observability.md
        surface catalog, both ways."""
        sites: Dict[str, Tuple[str, int]] = {}
        for ctx in project.files:
            if ctx.tree is None or 'skypilot_tpu' not in ctx.rel:
                continue
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr in ('add_get', 'add_post') and
                        node.args and
                        isinstance(node.args[0], ast.Constant) and
                        isinstance(node.args[0].value, str)):
                    continue
                route = node.args[0].value
                if not route.startswith(('/debug/', '/fleet/')):
                    continue
                sites.setdefault(route, (ctx.rel, node.lineno))
        if not sites:
            return []
        doc = project.doc(_ROUTE_DOC_REL)
        if doc is None:
            return []
        documented: Dict[str, int] = {}
        for i, line in enumerate(doc.splitlines(), 1):
            for m in _ROUTE_TOK_RE.finditer(line):
                documented.setdefault(m.group(0), i)
        out: List[Violation] = []
        for route, (rel, lineno) in sorted(sites.items()):
            if route not in documented:
                out.append(Violation(
                    rel, lineno, self.id,
                    f'HTTP route {route!r} is not documented in '
                    f'{_ROUTE_DOC_REL} — every /debug/* and /fleet/* '
                    f'surface is part of the observability contract '
                    f'and must appear in the route catalog'))
        doc_rel = (project.root / _ROUTE_DOC_REL).as_posix()
        for route, lineno in sorted(documented.items()):
            if route not in sites:
                out.append(Violation(
                    doc_rel, lineno, self.id,
                    f'documented HTTP route {route!r} has no '
                    f'add_get/add_post registration — the surface it '
                    f'describes no longer exists; delete the mention '
                    f'or restore the route'))
        return out

    # ------------------------------------------------ terminal states
    def _check_terminal_states(self, project: Project
                               ) -> List[Violation]:
        job_lib = next((c for c in project.files if c.rel.endswith(
            'skypilot_tpu/runtime/job_lib.py')), None)
        if job_lib is None or job_lib.tree is None:
            return []
        terminal: Set[str] = set()
        lineno = 1
        for node in job_lib.tree.body:
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == '_TERMINAL' and \
                    isinstance(node.value, ast.Set):
                lineno = node.lineno
                for elt in node.value.elts:
                    if isinstance(elt, ast.Attribute):
                        terminal.add(elt.attr)
        if not terminal:
            return []
        doc = project.doc(_JOBS_DOC_REL)
        doc_rel = (project.root / _JOBS_DOC_REL).as_posix()
        documented: Optional[Set[str]] = None
        doc_line = 1
        if doc is not None:
            for i, line in enumerate(doc.splitlines(), 1):
                m = _TERMINAL_LINE_RE.match(line.strip())
                if m:
                    documented = set(re.findall(r'`([A-Z_]+)`',
                                                m.group(1)))
                    doc_line = i
                    break
        if documented is None:
            return [Violation(
                job_lib.rel, lineno, self.id,
                f'JobStatus terminal set '
                f'{sorted(terminal)} has no docs catalog — '
                f'docs/managed-jobs.md needs a `Terminal states:` '
                f'line listing each backticked state')]
        out: List[Violation] = []
        for s in sorted(terminal - documented):
            out.append(Violation(
                job_lib.rel, lineno, self.id,
                f'terminal JobStatus {s} is missing from the '
                f'`Terminal states:` catalog in '
                f'docs/managed-jobs.md'))
        for s in sorted(documented - terminal):
            out.append(Violation(
                doc_rel, doc_line, self.id,
                f'documented terminal state {s} is not in '
                f'JobStatus._TERMINAL — the catalog is stale'))
        return out
