"""env-registry: every ``SKYT_*`` environment read resolves through
the typed registry in ``skypilot_tpu/utils/env.py``.

Two passes share the id ``env-registry``:

  * EnvReadPass (file): framework code must not read ``os.environ``
    / ``os.getenv`` for a ``SKYT_`` name directly — the accessor adds
    registration, type coercion, and malformed-value warnings.
    Writes (``os.environ[k] = v``, ``setdefault``, ``pop``) are
    allowed: exporting env to child jobs is not a read.
  * EnvRegistryDriftPass (project): loads the registry (by file path,
    stdlib-only import) and proves (a) every accessor read names a
    registered variable, (b) every registered non-exported variable
    is read somewhere (dead knobs rot), and (c) the checked-in
    ``docs/env_vars.md`` byte-matches ``env.generate_docs()``
    (regenerate with ``python tools/lint.py --write-env-docs``).
"""
import ast
import importlib.util
import itertools
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from .core import FileContext, Pass, Project, Violation

_GETTERS = ('get', 'get_bool', 'get_int', 'get_float', 'lookup')
_ENV_MODULE_REL = 'skypilot_tpu/utils/env.py'
_DOCS_REL = 'docs/env_vars.md'

_counter = itertools.count()


def _module_consts(tree: ast.AST) -> Dict[str, str]:
    """Top-level NAME = 'SKYT_...' constants (env var name aliases)."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out[node.targets[0].id] = node.value.value
    return out


def _env_name_of(arg: ast.AST, consts: Dict[str, str]
                 ) -> Tuple[Optional[str], bool]:
    """(name-or-prefix, is_prefix) for an env-name argument: literal,
    module-level constant, or f-string (literal prefix)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.Name):
        return consts.get(arg.id), False
    if isinstance(arg, ast.JoinedStr) and arg.values:
        head = arg.values[0]
        if isinstance(head, ast.Constant) and \
                isinstance(head.value, str):
            return head.value, True
    return None, False


def _is_environ(node: ast.AST) -> bool:
    """os.environ (Attribute) or bare environ (from os import)."""
    return (isinstance(node, ast.Attribute) and
            node.attr == 'environ') or \
        (isinstance(node, ast.Name) and node.id == 'environ')


class EnvReadPass(Pass):
    id = 'env-registry'
    title = 'SKYT_* env reads go through utils/env.py'

    def applies(self, ctx: FileContext) -> bool:
        return 'skypilot_tpu' in ctx.rel and \
            not ctx.rel.endswith(_ENV_MODULE_REL)

    def run(self, ctx: FileContext) -> List[Violation]:
        consts = _module_consts(ctx.tree)
        out: List[Violation] = []

        def flag(lineno: int, name: str) -> None:
            out.append(Violation(
                ctx.rel, lineno, self.id,
                f'direct os.environ read of {name} — SKYT_* '
                f'variables resolve through the typed registry '
                f'(skypilot_tpu/utils/env.py: env.get / get_bool / '
                f'get_int / get_float), which is what keeps '
                f'docs/env_vars.md true and malformed values '
                f'non-fatal'))

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                f = node.func
                is_read = False
                if isinstance(f, ast.Attribute) and f.attr == 'get' \
                        and _is_environ(f.value):
                    is_read = True
                elif isinstance(f, ast.Attribute) and \
                        f.attr == 'getenv' and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id in ('os', '_os'):
                    is_read = True
                if is_read and node.args:
                    name, _ = _env_name_of(node.args[0], consts)
                    if name and name.startswith('SKYT_'):
                        flag(node.lineno, name)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load) and \
                    _is_environ(node.value):
                name, _ = _env_name_of(node.slice, consts)
                if name and name.startswith('SKYT_'):
                    flag(node.lineno, name)
            elif isinstance(node, ast.Compare) and node.ops and \
                    isinstance(node.ops[0], ast.In) and \
                    node.comparators and \
                    _is_environ(node.comparators[0]):
                name, _ = _env_name_of(node.left, consts)
                if name and name.startswith('SKYT_'):
                    flag(node.lineno, name)
        return out


def _load_registry(path: Path):
    """Import utils/env.py by path (stdlib-only module) under a
    unique name so fixture trees can carry their own registries."""
    name = f'_skyt_env_registry_{next(_counter)}'
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class EnvRegistryDriftPass(Pass):
    id = 'env-registry'
    title = 'registry <-> reads <-> docs/env_vars.md stay in sync'
    scope = 'project'

    def run_project(self, project: Project) -> List[Violation]:
        env_path = project.root / _ENV_MODULE_REL
        if not env_path.exists():
            return []
        out: List[Violation] = []
        try:
            mod = _load_registry(env_path)
            registry = mod.registry()
        except Exception as e:  # noqa: surfaced as a violation
            return [Violation(_ENV_MODULE_REL, 1, self.id,
                              f'env registry failed to load: {e!r}')]

        exact: Set[str] = {n for n in registry if '<' not in n}
        patterns: Dict[str, str] = {
            n[:n.index('<')]: n for n in registry if '<' in n}

        def registered(name: str, is_prefix: bool) -> bool:
            if not is_prefix and name in exact:
                return True
            for prefix in patterns:
                if name.startswith(prefix) or \
                        (is_prefix and prefix.startswith(name)):
                    return True
            return False

        read: Set[str] = set()
        for ctx in project.files:
            if ctx.tree is None or 'skypilot_tpu' not in ctx.rel:
                continue
            if ctx.rel.endswith('skypilot_tpu/utils/env_options.py'):
                # The Options enum reads via env.get_bool with a
                # dynamic name; its member declarations are the
                # static read sites.
                for node in ast.walk(ctx.tree):
                    if isinstance(node, ast.Constant) and \
                            isinstance(node.value, str) and \
                            node.value.startswith('SKYT_'):
                        read.add(node.value)
            aliases = self._env_aliases(ctx.tree)
            if not aliases:
                continue
            consts = _module_consts(ctx.tree)
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr in _GETTERS and
                        isinstance(node.func.value, ast.Name) and
                        node.func.value.id in aliases and node.args):
                    continue
                name, is_prefix = _env_name_of(node.args[0], consts)
                if name is None or not name.startswith('SKYT_'):
                    continue
                if not registered(name, is_prefix):
                    out.append(Violation(
                        ctx.rel, node.lineno, self.id,
                        f'env read of unregistered variable '
                        f'{name}{"..." if is_prefix else ""} — '
                        f'declare it in skypilot_tpu/utils/env.py '
                        f'(name, type, default, doc) and regenerate '
                        f'docs/env_vars.md'))
                    continue
                if is_prefix:
                    read.update(p for pre, p in patterns.items()
                                if name.startswith(pre) or
                                pre.startswith(name))
                else:
                    read.add(name if name in exact else next(
                        (p for pre, p in patterns.items()
                         if name.startswith(pre)), name))

        env_src = env_path.read_text(encoding='utf-8').splitlines()
        for name, ev in sorted(registry.items()):
            if ev.exported or name in read:
                continue
            lineno = next((i for i, ln in enumerate(env_src, 1)
                           if f"'{name}'" in ln), 1)
            out.append(Violation(
                (project.root / _ENV_MODULE_REL).as_posix(), lineno,
                self.id,
                f'registered env variable {name} is never read '
                f'through the accessors — delete the entry or mark '
                f'it exported=True if the framework only sets it '
                f'for user jobs'))

        want = mod.generate_docs()
        have = project.doc(_DOCS_REL)
        if have != want:
            detail = 'missing' if have is None else \
                self._first_diff(have, want)
            out.append(Violation(
                (project.root / _DOCS_REL).as_posix(), 1, self.id,
                f'docs/env_vars.md is stale ({detail}) — it is '
                f'generated from the registry; run '
                f'`python tools/lint.py --write-env-docs`'))
        return out

    @staticmethod
    def _env_aliases(tree: ast.AST) -> Set[str]:
        aliases: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module == 'skypilot_tpu.utils':
                for a in node.names:
                    if a.name == 'env':
                        aliases.add(a.asname or 'env')
            elif isinstance(node, ast.ImportFrom) and \
                    node.module == 'skypilot_tpu.utils.env':
                pass   # `from ...env import get` unsupported on
                # purpose: keep reads greppable as env.get(...)
        return aliases

    @staticmethod
    def _first_diff(have: str, want: str) -> str:
        h, w = have.splitlines(), want.splitlines()
        for i, (a, b) in enumerate(zip(h, w), 1):
            if a != b:
                return f'first drift at line {i}: {a!r} != {b!r}'
        return f'line count {len(h)} != {len(w)}'
