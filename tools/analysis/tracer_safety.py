"""tracer-safety: functions reachable from ``jax.jit`` /
``pallas_call`` / the dispatch ladder must stay tracer-pure.

Inside a traced function, Python side effects run once at trace time
(prints fire with tracer reprs, wall-clock reads freeze a single
stamp into the compiled program, module-global mutation desyncs with
the cache) and value extraction (``.item()``, ``jax.device_get``,
``block_until_ready``) either raises a ConcretizationError or forces
a silent host sync on the hot path — the exact stall class PR 9's
gang watchdog exists to catch at runtime. This pass moves that to a
CI line number.

Roots: functions decorated with / passed to ``jax.jit``, kernels
passed to ``pallas_call``, and callables inside the rung list of a
``dispatch.run_ladder(...)`` call (the ladder runs rungs at trace
time). Reachability follows statically-resolvable calls: same-module
functions, ``from m import f`` names, and ``mod.f(...)`` where
``mod`` is an imported skypilot_tpu module. Dynamic dispatch
(methods, higher-order callables) is out of scope — mark such
boundaries with ``# noqa: tracer-safety`` where needed.
"""
import ast
from pathlib import PurePosixPath
from typing import Dict, List, Optional, Tuple

from .core import Pass, Project, Violation

_FORBIDDEN_TIME = ('time', 'monotonic', 'perf_counter')
_FORBIDDEN_SYNC = ('device_get', 'block_until_ready')

# Trace-time infrastructure the dispatch ladder deliberately invokes
# while jax traces (fault injection, path counters, logging setup):
# their side effects are the POINT — they fire once per trace, not
# per step — so they are exempt from the purity scan (they stay part
# of the reachability walk).
_EXEMPT_MODULES = (
    'skypilot_tpu.utils.faults',
    'skypilot_tpu.utils.log_utils',
    'skypilot_tpu.utils.metrics',
    'skypilot_tpu.utils.tracing',
    'skypilot_tpu.utils.timeline',
)

FuncKey = Tuple[str, str]          # (module, function name)


def _module_name(rel: str) -> Optional[str]:
    """'skypilot_tpu/ops/attention.py' -> 'skypilot_tpu.ops.attention'
    (None for files outside the package)."""
    p = PurePosixPath(rel)
    parts = list(p.parts)
    if 'skypilot_tpu' not in parts:
        return None
    parts = parts[parts.index('skypilot_tpu'):]
    parts[-1] = parts[-1][:-3]           # strip .py
    if parts[-1] == '__init__':
        parts = parts[:-1]
    return '.'.join(parts)


class _Module:
    def __init__(self, rel: str, name: str, tree: ast.AST) -> None:
        self.rel = rel
        self.name = name
        self.tree = tree
        self.functions: Dict[str, ast.AST] = {}
        self.mod_aliases: Dict[str, str] = {}    # alias -> module name
        self.func_imports: Dict[str, Tuple[str, str]] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self.functions[node.name] = node
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith('skypilot_tpu'):
                        alias = a.asname or a.name.split('.')[0]
                        self.mod_aliases[alias] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module and \
                    node.module.startswith('skypilot_tpu'):
                for a in node.names:
                    alias = a.asname or a.name
                    # `from pkg import mod` vs `from mod import fn`:
                    # record both; resolution tries module first.
                    self.mod_aliases.setdefault(
                        alias, f'{node.module}.{a.name}')
                    self.func_imports[alias] = (node.module, a.name)


def _is_jit_expr(node: ast.AST) -> bool:
    """jit / jax.jit / functools.partial(jax.jit, ...)"""
    if isinstance(node, ast.Name) and node.id == 'jit':
        return True
    if isinstance(node, ast.Attribute) and node.attr == 'jit':
        return True
    if isinstance(node, ast.Call) and \
            isinstance(node.func, (ast.Name, ast.Attribute)):
        fname = node.func.attr if isinstance(node.func, ast.Attribute) \
            else node.func.id
        if fname == 'partial' and node.args:
            return _is_jit_expr(node.args[0])
    return False


class TracerSafetyPass(Pass):
    id = 'tracer-safety'
    title = 'jit/pallas-reachable functions stay tracer-pure'
    scope = 'project'

    def run_project(self, project: Project) -> List[Violation]:
        modules: Dict[str, _Module] = {}
        for ctx in project.files:
            if ctx.tree is None or 'skypilot_tpu' not in ctx.rel:
                continue
            name = _module_name(ctx.rel)
            if name is None:
                continue
            modules[name] = _Module(ctx.rel, name, ctx.tree)

        roots = self._find_roots(modules)
        reached = self._reach(modules, roots)
        out: List[Violation] = []
        for (mod, fname), root in sorted(reached.items()):
            if mod in _EXEMPT_MODULES:
                continue
            m = modules.get(mod)
            fn = m.functions.get(fname) if m else None
            if fn is None:
                continue
            out.extend(self._scan(m, fn, root))
        return out

    # ----------------------------------------------------- call graph
    def _resolve(self, m: _Module, call: ast.Call,
                 modules: Dict[str, _Module]
                 ) -> Optional[FuncKey]:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in m.functions:
                return (m.name, f.id)
            if f.id in m.func_imports:
                src_mod, src_name = m.func_imports[f.id]
                tgt = modules.get(src_mod)
                if tgt and src_name in tgt.functions:
                    return (src_mod, src_name)
            return None
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name):
            mod_name = m.mod_aliases.get(f.value.id)
            if mod_name:
                tgt = modules.get(mod_name)
                if tgt and f.attr in tgt.functions:
                    return (mod_name, f.attr)
        return None

    def _name_target(self, m: _Module, node: ast.AST,
                     modules: Dict[str, _Module]
                     ) -> Optional[FuncKey]:
        """Resolve a bare function REFERENCE (not call)."""
        if isinstance(node, ast.Name):
            if node.id in m.functions:
                return (m.name, node.id)
            if node.id in m.func_imports:
                src_mod, src_name = m.func_imports[node.id]
                tgt = modules.get(src_mod)
                if tgt and src_name in tgt.functions:
                    return (src_mod, src_name)
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name):
            mod_name = m.mod_aliases.get(node.value.id)
            if mod_name:
                tgt = modules.get(mod_name)
                if tgt and node.attr in tgt.functions:
                    return (mod_name, node.attr)
        return None

    def _find_roots(self, modules: Dict[str, _Module]
                    ) -> Dict[FuncKey, str]:
        roots: Dict[FuncKey, str] = {}
        for m in modules.values():
            for node in ast.walk(m.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if _is_jit_expr(dec):
                            roots.setdefault(
                                (m.name, node.name),
                                f'@jit {node.name}')
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                fname = f.attr if isinstance(f, ast.Attribute) else \
                    getattr(f, 'id', '')
                if fname == 'jit' and node.args:
                    tgt = self._name_target(m, node.args[0], modules)
                    if tgt:
                        roots.setdefault(tgt, f'jax.jit({tgt[1]})')
                elif fname == 'pallas_call' and node.args:
                    tgt = self._name_target(m, node.args[0], modules)
                    if tgt:
                        roots.setdefault(
                            tgt, f'pallas_call({tgt[1]})')
                elif fname == 'run_ladder':
                    # Everything callable inside the rung list runs
                    # at trace time.
                    for arg in node.args[1:]:
                        for sub in ast.walk(arg):
                            tgt = None
                            if isinstance(sub, ast.Call):
                                tgt = self._resolve(m, sub, modules)
                            if tgt:
                                roots.setdefault(
                                    tgt, f'run_ladder rung ({tgt[1]})')
        return roots

    def _reach(self, modules: Dict[str, _Module],
               roots: Dict[FuncKey, str]) -> Dict[FuncKey, str]:
        reached: Dict[FuncKey, str] = {}
        stack = list(roots.items())
        while stack:
            key, via = stack.pop()
            if key in reached:
                continue
            reached[key] = via
            m = modules.get(key[0])
            fn = m.functions.get(key[1]) if m else None
            if fn is None:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    tgt = self._resolve(m, node, modules)
                    if tgt and tgt not in reached:
                        stack.append((tgt, via))
        return reached

    # ------------------------------------------------ forbidden scan
    def _scan(self, m: _Module, fn: ast.AST,
              root: str) -> List[Violation]:
        out: List[Violation] = []

        def flag(lineno: int, what: str, why: str) -> None:
            out.append(Violation(
                m.rel, lineno, self.id,
                f'{what} in {fn.name}() (reachable from {root}) — '
                f'{why}; hoist it out of the traced function or add '
                f'`# noqa: tracer-safety` with a why-comment'))

        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                flag(node.lineno, 'global-statement mutation',
                     'module state mutated under trace desyncs with '
                     'the compilation cache')
                continue
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                if f.id == 'print':
                    flag(node.lineno, 'print()',
                         'it fires at trace time with tracer reprs '
                         '(use jax.debug.print for runtime values)')
                elif f.id in _FORBIDDEN_SYNC:
                    flag(node.lineno, f'{f.id}()',
                         'host syncs under trace stall the device '
                         'pipeline')
            elif isinstance(f, ast.Attribute):
                if f.attr == 'item' and not node.args:
                    flag(node.lineno, '.item()',
                         'concretizes a tracer (ConcretizationError '
                         'at trace time, host sync at best)')
                elif f.attr in _FORBIDDEN_SYNC:
                    flag(node.lineno, f'{f.attr}()',
                         'host syncs under trace stall the device '
                         'pipeline')
                elif f.attr in _FORBIDDEN_TIME and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == 'time':
                    flag(node.lineno, f'time.{f.attr}()',
                         'a wall-clock read freezes one trace-time '
                         'stamp into the compiled program')
                elif f.attr == 'now' and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id in ('datetime', 'dt'):
                    flag(node.lineno, 'datetime.now()',
                         'a wall-clock read freezes one trace-time '
                         'stamp into the compiled program')
        return out
