"""skyanalyze framework: pass registry, noqa grammar, runners, output.

Design rules (mirror tools/lint.py's original constraints):
  * stdlib only — the image ships no ruff/pylint/mypy;
  * every file is read + parsed exactly once per run (FileContext),
    shared by all passes;
  * suppression is handled HERE, not in passes: a pass reports every
    violation it sees and the framework drops the suppressed ones, so
    noqa semantics are uniform across all passes.

noqa grammar (docs/static_analysis.md):
  # noqa                      suppress every pass on this line
  # noqa: free text reason    same (no token is a known pass id)
  # noqa: lock-discipline     suppress exactly the named pass(es)
  # noqa: a, b                comma/space separated pass ids
"""
import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding. ``path`` is the path as given (repo-relative when
    run via lint.py), ``line`` is 1-based (0 = whole file)."""
    path: str
    line: int
    pass_id: str
    message: str

    def format(self) -> str:
        return f'{self.path}:{self.line}: {self.message} ' \
               f'[{self.pass_id}]'

    def as_dict(self) -> Dict[str, object]:
        return {'path': self.path, 'line': self.line,
                'pass': self.pass_id, 'message': self.message}


class FileContext:
    """One parsed source file, shared by every file pass."""

    def __init__(self, path: Path, src: Optional[str] = None) -> None:
        self.path = path
        self.rel = path.as_posix()
        self.src = path.read_text(encoding='utf-8') \
            if src is None else src
        self.lines = self.src.splitlines()
        self.tree: Optional[ast.AST] = None
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.src, filename=str(path))
        except SyntaxError as e:
            self.syntax_error = e

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ''


_NOQA_RE = re.compile(r'#\s*noqa\b(?::\s*(?P<args>.*))?', re.I)


def noqa_suppresses(line: str, pass_id: str,
                    known_ids: Set[str]) -> bool:
    """Does a ``# noqa`` comment on ``line`` suppress ``pass_id``?"""
    m = _NOQA_RE.search(line)
    if not m:
        return False
    args = (m.group('args') or '').strip()
    if not args:
        return True                      # bare noqa: everything
    tokens = {t.strip() for t in re.split(r'[,\s]+', args) if t.strip()}
    named = tokens & known_ids
    if not named:
        return True                      # free-text reason: everything
    return pass_id in named


class Pass:
    """Base class. File passes implement run(ctx); project passes set
    scope = 'project' and implement run_project(project)."""

    id = ''
    title = ''
    scope = 'file'

    def applies(self, ctx: FileContext) -> bool:
        return True

    def run(self, ctx: FileContext) -> List[Violation]:
        raise NotImplementedError

    def run_project(self, project: 'Project') -> List[Violation]:
        raise NotImplementedError


class Project:
    """Whole-tree view for project passes: every FileContext plus the
    repo root (for docs/). Tests point ``root`` at fixture trees."""

    def __init__(self, root: Path,
                 files: Sequence[FileContext]) -> None:
        self.root = root
        self.files = list(files)

    def doc(self, rel: str) -> Optional[str]:
        p = self.root / rel
        try:
            return p.read_text(encoding='utf-8')
        except OSError:
            return None


def _registry() -> List[Pass]:
    # Imported lazily so `import analysis.core` never cycles.
    from . import async_blocking, env_registry, lock_discipline, \
        metric_cardinality, ported, registry_consistency, tracer_safety
    return (ported.PASSES +
            [lock_discipline.LockDisciplinePass(),
             async_blocking.AsyncBlockingPass(),
             tracer_safety.TracerSafetyPass(),
             env_registry.EnvReadPass(),
             env_registry.EnvRegistryDriftPass(),
             metric_cardinality.MetricCardinalityPass(),
             registry_consistency.RegistryConsistencyPass()])


_PASSES: Optional[List[Pass]] = None


def all_passes() -> List[Pass]:
    global _PASSES
    if _PASSES is None:
        _PASSES = _registry()
    return _PASSES


def known_ids() -> Set[str]:
    return {p.id for p in all_passes()} | {'syntax'}


def _filter_noqa(violations: List[Violation],
                 ctx_by_rel: Dict[str, FileContext]) -> List[Violation]:
    ids = known_ids()
    out = []
    for v in violations:
        ctx = ctx_by_rel.get(v.path)
        if ctx is not None and v.line > 0 and noqa_suppresses(
                ctx.line_at(v.line), v.pass_id, ids):
            continue
        out.append(v)
    return out


def run_file_passes(ctx: FileContext) -> List[Violation]:
    if ctx.syntax_error is not None:
        e = ctx.syntax_error
        return [Violation(ctx.rel, e.lineno or 0, 'syntax',
                          f'syntax error: {e.msg}')]
    out: List[Violation] = []
    for p in all_passes():
        if p.scope != 'file' or not p.applies(ctx):
            continue
        out.extend(p.run(ctx))
    return _filter_noqa(out, {ctx.rel: ctx})


def check_file(path) -> List[str]:
    """Single-file compatibility API (tests/test_lint.py): run every
    file pass on one file, return formatted issue strings."""
    ctx = FileContext(Path(path))
    return [v.format() for v in run_file_passes(ctx)]


def analyze(root: Path, roots: Optional[Sequence[str]] = None,
            project_passes: bool = True) -> List[Violation]:
    """Full run: file passes over every .py under ``roots`` (given
    relative to ``root``), then project passes over the whole view.
    Returns violations sorted by (path, line, pass)."""
    roots = list(roots) if roots else [
        'skypilot_tpu', 'tests', 'tools', 'bench.py',
        '__graft_entry__.py']
    files: List[FileContext] = []
    for r in roots:
        p = root / r
        if p.is_dir():
            files += [FileContext(f) for f in sorted(p.rglob('*.py'))
                      if '__pycache__' not in str(f)]
        elif p.exists():
            files.append(FileContext(p))
    ctx_by_rel = {c.rel: c for c in files}
    out: List[Violation] = []
    for ctx in files:
        out.extend(run_file_passes(ctx))
    if project_passes:
        project = Project(root, files)
        pv: List[Violation] = []
        for p in all_passes():
            if p.scope == 'project':
                pv.extend(p.run_project(project))
        out.extend(_filter_noqa(pv, ctx_by_rel))
    out.sort(key=lambda v: (v.path, v.line, v.pass_id, v.message))
    return out


def count_files(root: Path,
                roots: Optional[Sequence[str]] = None) -> int:
    roots = list(roots) if roots else [
        'skypilot_tpu', 'tests', 'tools', 'bench.py',
        '__graft_entry__.py']
    n = 0
    for r in roots:
        p = root / r
        if p.is_dir():
            n += sum(1 for f in p.rglob('*.py')
                     if '__pycache__' not in str(f))
        elif p.exists():
            n += 1
    return n


def render_json(violations: List[Violation], files_checked: int) -> str:
    """Stable JSON artifact (tpu_validation.sh archives it alongside
    probe.json; tests/test_analysis.py goldens the schema)."""
    payload = {
        'schema': 1,
        'tool': 'skyanalyze',
        'files_checked': files_checked,
        'passes': sorted(known_ids()),
        'violations': [v.as_dict() for v in violations],
    }
    return json.dumps(payload, indent=2, sort_keys=True) + '\n'
