"""lock-discipline: attributes a class protects with a lock must
never be touched outside it.

A class opts in by owning a lock (``self.X = threading.Lock() /
RLock() / Condition()`` — these classes are exactly the ones shared
across the engine loop, HTTP handlers, the LB, and watchdog threads).
The guarded set is learned, not declared:

  * any ``self.A = ...`` (or augmented assign / del) inside a
    ``with self.X:`` block marks A as guarded by X;
  * ``# guarded-by: X`` on an assignment line declares the same
    explicitly (useful for attributes initialised in __init__ and
    thereafter only read).

Every OTHER access to a guarded attribute — read or write — must
happen while one of its guarding locks is held, with three escape
hatches:

  * ``__init__``/``__del__`` are exempt (construction/teardown
    happen-before/after sharing);
  * a method whose ``def`` line carries ``# guarded-by: X`` asserts
    "callers hold X" and is analysed as if X were held;
  * ``# noqa: lock-discipline`` with a why-comment for deliberate
    lock-free access (e.g. a monotonic flag read).

Nested functions and lambdas reset the held-lock set: a closure
defined under a lock may run after it is released (thread targets,
callbacks), so it must re-acquire or be marked.
"""
import ast
import re
from typing import Dict, List, Optional, Set

from .core import FileContext, Pass, Violation

_LOCK_FACTORIES = ('Lock', 'RLock', 'Condition')
_GUARDED_BY_RE = re.compile(r'#\s*guarded-by:\s*([A-Za-z_][\w]*)')
_EXEMPT_METHODS = ('__init__', '__del__')

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _self_attr(node: ast.AST) -> Optional[str]:
    """'A' when node is ``self.A``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id == 'self':
        return node.attr
    return None


def _lock_factory_call(node: ast.AST) -> bool:
    """True for threading.Lock() / Lock() / threading.RLock() etc."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _LOCK_FACTORIES and \
            isinstance(f.value, ast.Name) and \
            f.value.id == 'threading':
        return True
    return isinstance(f, ast.Name) and f.id in _LOCK_FACTORIES


class _ClassAnalysis:
    """One ClassDef: discover locks, learn the guarded set, then
    re-walk checking every access against the held-lock context."""

    def __init__(self, ctx: FileContext, cls: ast.ClassDef,
                 pass_id: str) -> None:
        self.ctx = ctx
        self.cls = cls
        self.pass_id = pass_id
        self.locks: Set[str] = set()
        self.guarded: Dict[str, Set[str]] = {}   # attr -> lock names
        self.violations: List[Violation] = []
        self._meth = ''
        self._collecting = True

    def _guard_comment(self, lineno: int) -> Optional[str]:
        m = _GUARDED_BY_RE.search(self.ctx.line_at(lineno))
        return m.group(1) if m else None

    def methods(self):
        for node in self.cls.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                yield node

    def find_locks(self) -> None:
        for meth in self.methods():
            for node in ast.walk(meth):
                if isinstance(node, ast.Assign) and \
                        _lock_factory_call(node.value):
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr:
                            self.locks.add(attr)

    def find_guarded_comments(self) -> None:
        """`# guarded-by: X` on assignment lines (any method)."""
        for meth in self.methods():
            for node in ast.walk(meth):
                if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                         ast.AugAssign)):
                    continue
                lock = self._guard_comment(node.lineno)
                if lock is None or lock not in self.locks:
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = _self_attr(t)
                    if attr and attr not in self.locks:
                        self.guarded.setdefault(
                            attr, set()).add(lock)

    # ------------------------------------------------- shared walker
    def _with_locks(self, node: ast.With) -> Set[str]:
        held = set()
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self.locks:
                held.add(attr)
        return held

    def _entry_held(self, meth) -> Set[str]:
        lock = self._guard_comment(meth.lineno)
        return {lock} if lock in self.locks else set()

    def walk_methods(self, collecting: bool) -> None:
        self._collecting = collecting
        for meth in self.methods():
            if not collecting and meth.name in _EXEMPT_METHODS:
                continue
            self._meth = meth.name
            held = self._entry_held(meth)
            for stmt in meth.body:
                self._visit(stmt, held)

    def _visit(self, node: ast.AST, held: Set[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self._visit(item.context_expr, held)
                if item.optional_vars is not None:
                    self._visit(item.optional_vars, held)
            inner = held | self._with_locks(node)
            for stmt in node.body:
                self._visit(stmt, inner)
            return
        if isinstance(node, _FUNC_NODES):
            # Closures may outlive the lock scope: reset (a
            # `# guarded-by:` on the def line re-asserts).
            lock = self._guard_comment(node.lineno)
            inner = {lock} if lock in self.locks else set()
            body = node.body if isinstance(node.body, list) \
                else [node.body]
            for stmt in body:
                self._visit(stmt, inner)
            return
        if isinstance(node, ast.Attribute):
            self._handle_attr(node, held)
            # fall through: the value side still needs walking
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _handle_attr(self, node: ast.Attribute,
                     held: Set[str]) -> None:
        attr = _self_attr(node)
        if attr is None or attr in self.locks:
            return
        if self._collecting:
            if held and isinstance(node.ctx, (ast.Store, ast.Del)):
                self.guarded.setdefault(attr, set()).update(held)
            return
        if attr not in self.guarded:
            return
        if self.guarded[attr] & held:
            return
        kind = 'written' if isinstance(
            node.ctx, (ast.Store, ast.Del)) else 'read'
        locks = ' or '.join(
            f'self.{x}' for x in sorted(self.guarded[attr]))
        self.violations.append(Violation(
            self.ctx.rel, node.lineno, self.pass_id,
            f'self.{attr} {kind} in {self._meth}() without holding '
            f'{locks} — this attribute is written under that lock '
            f'elsewhere in the class, so lock-free access races '
            f'other threads; hold the lock, mark the method '
            f'`# guarded-by: <lock>` if callers hold it, or add '
            f'`# noqa: lock-discipline` with a why-comment'))


class LockDisciplinePass(Pass):
    id = 'lock-discipline'
    title = 'lock-guarded attributes never accessed lock-free'

    def applies(self, ctx: FileContext) -> bool:
        return 'skypilot_tpu' in ctx.rel

    def run(self, ctx: FileContext) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            an = _ClassAnalysis(ctx, node, self.id)
            an.find_locks()
            if not an.locks:
                continue
            an.find_guarded_comments()
            an.walk_methods(collecting=True)
            if not an.guarded:
                continue
            an.walk_methods(collecting=False)
            out.extend(an.violations)
        return out
