"""The nine rules ported from the original regex linter onto the
skyanalyze pass framework, message-compatible with tools/lint.py
(tests/test_lint.py asserts on these strings).

Rules: unused-import, whitespace (tabs/trailing/line length),
print-call, loop-host-sync, clock-injection, qos-admission,
kernel-dispatch, sqlite-discipline, except-pass. Rationale for each
lives in docs/static_analysis.md; the discipline each enforces is
documented where the original rule pointed (docs/kernels.md,
docs/robustness.md, docs/observability.md, docs/qos.md,
docs/performance.md).
"""
import ast
import re
from typing import List

from .core import FileContext, Pass, Violation

LINE_LIMIT = 88

# Imports that exist for side effects or re-export by convention.
_SIDE_EFFECT_OK = {'skypilot_tpu', 'conftest'}

# Modules whose stdout IS the interface — CLI surfaces, console log
# relays streaming remote job output to the user's terminal, and train
# examples whose printed lines are the job's log contract.
_PRINT_OK_PREFIXES = (
    'skypilot_tpu/cli.py',
    'skypilot_tpu/check.py',
    'skypilot_tpu/dashboard.py',            # startup URL banner
    'skypilot_tpu/utils/command_runner.py',  # remote stdout relay
    'skypilot_tpu/runtime/log_lib.py',       # job log tailing
    'skypilot_tpu/runtime/rpc.py',           # log streaming + CLI JSON
    'skypilot_tpu/backends/tpu_backend.py',  # provision log relay
    'skypilot_tpu/jobs/core.py',             # jobs logs CLI surface
    'skypilot_tpu/serve/core.py',            # serve logs CLI surface
    'skypilot_tpu/parallel/collectives.py',  # bench CLI output
    'skypilot_tpu/train/push_weights.py',    # rollout-state CLI JSON
    'skypilot_tpu/catalog/data_fetchers/',   # fetcher CLI scripts
    'skypilot_tpu/train/examples/',          # example job stdout
)

# Audited `except Exception: pass` sites that predate the rule — each
# swallows on a genuinely-best-effort path (crash-handler broadcast,
# opt-in usage telemetry, profiler teardown).
_EXCEPT_PASS_OK = (
    'skypilot_tpu/infer/engine.py',
    'skypilot_tpu/usage/usage_lib.py',
    'skypilot_tpu/utils/profiling.py',
)

_SQLITE_CONNECT_OK = (
    'skypilot_tpu/utils/sqlite_utils.py',
    'skypilot_tpu/serve/serve_state.py',
)

_INJECTABLE_CLOCK_FILES = ('skypilot_tpu/serve/slo.py',
                           'skypilot_tpu/utils/timeseries.py',
                           'skypilot_tpu/train/heartbeat.py',
                           'skypilot_tpu/train/watchdog.py')
_CLOCK_CALL_NAMES = ('time', 'monotonic', 'perf_counter')

_NO_SYNC_IN_LOOPS = ('skypilot_tpu/train/sft.py',)
_SYNC_CALL_NAMES = ('device_get', 'block_until_ready')

_WAITING_PUT_RE = re.compile(r'\._waiting\.put\(')
_PALLAS_CALL_RE = re.compile(r'\bpallas_call\s*\(')
_SQLITE_CONNECT_RE = re.compile(r'\bsqlite3\s*\.\s*connect\s*\(')


def _in_framework(ctx: FileContext) -> bool:
    return 'skypilot_tpu' in ctx.rel


class UnusedImportPass(Pass):
    id = 'unused-import'
    title = 'imports must be used (or re-exported/marked)'

    def applies(self, ctx: FileContext) -> bool:
        return ctx.path.name != '__init__.py'

    def run(self, ctx: FileContext) -> List[Violation]:
        used = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
        out = []
        for lineno, _full, name in self._imported_names(ctx.tree):
            if name in used or name in _SIDE_EFFECT_OK:
                continue
            # String annotations ('spec_lib.ServiceSpec') and __all__.
            if re.search(rf'[\'"]{re.escape(name)}\b', ctx.src):
                continue
            out.append(Violation(ctx.rel, lineno, self.id,
                                 f'unused import {name!r}'))
        return out

    @staticmethod
    def _imported_names(tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split('.')[0]
                    yield node.lineno, alias.name, name
            elif isinstance(node, ast.ImportFrom):
                if node.module == '__future__':
                    continue
                for alias in node.names:
                    if alias.name == '*':
                        continue
                    name = alias.asname or alias.name
                    yield node.lineno, alias.name, name


class WhitespacePass(Pass):
    id = 'whitespace'
    title = 'no tabs, no trailing whitespace, lines <= 88'

    def run(self, ctx: FileContext) -> List[Violation]:
        out = []
        for i, line in enumerate(ctx.lines, 1):
            if '\t' in line:
                out.append(Violation(ctx.rel, i, self.id,
                                     'tab character'))
            if line != line.rstrip():
                out.append(Violation(ctx.rel, i, self.id,
                                     'trailing whitespace'))
            if len(line) > LINE_LIMIT and 'http' not in line and \
                    'pylint:' not in line:
                out.append(Violation(
                    ctx.rel, i, self.id,
                    f'line too long ({len(line)} > {LINE_LIMIT})'))
        return out


class PrintCallPass(Pass):
    id = 'print-call'
    title = 'framework code logs through log_utils, not print()'

    def applies(self, ctx: FileContext) -> bool:
        if not _in_framework(ctx):
            return False
        for p in _PRINT_OK_PREFIXES:
            if p.endswith('/'):
                if p in ctx.rel:
                    return False
            elif ctx.rel.endswith(p):
                return False
        return True

    def run(self, ctx: FileContext) -> List[Violation]:
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == 'print':
                out.append(Violation(
                    ctx.rel, node.lineno, self.id,
                    'bare print() — use a log_utils logger (or add '
                    'to the lint allowlist if stdout is this '
                    'module\'s interface)'))
        return out


class LoopHostSyncPass(Pass):
    id = 'loop-host-sync'
    title = 'no device_get/block_until_ready in the sft step loop'

    def applies(self, ctx: FileContext) -> bool:
        return any(ctx.rel.endswith(p) for p in _NO_SYNC_IN_LOOPS)

    def run(self, ctx: FileContext) -> List[Violation]:
        out, seen = [], set()
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else \
                    getattr(f, 'id', '')
                if name not in _SYNC_CALL_NAMES or node.lineno in seen:
                    continue
                seen.add(node.lineno)
                out.append(Violation(
                    ctx.rel, node.lineno, self.id,
                    f'{name}() inside the sft step loop — host syncs '
                    f'stall the device; pull metrics through '
                    f'trainer.DeferredMetrics (or add `# noqa` for a '
                    f'deliberate one-off)'))
        return out


class ClockInjectionPass(Pass):
    id = 'clock-injection'
    title = 'SLO/watchdog modules read time via injectable clocks'

    def applies(self, ctx: FileContext) -> bool:
        return any(ctx.rel.endswith(p)
                   for p in _INJECTABLE_CLOCK_FILES)

    def run(self, ctx: FileContext) -> List[Violation]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and
                    f.attr in _CLOCK_CALL_NAMES and
                    isinstance(f.value, ast.Name) and
                    f.value.id == 'time'):
                continue
            out.append(Violation(
                ctx.rel, node.lineno, self.id,
                f'direct time.{f.attr}() — this module must read '
                f'time through its injectable clock so SLO math '
                f'replays deterministically (docs/observability.md), '
                f'or add `# noqa`'))
        return out


class QosAdmissionPass(Pass):
    id = 'qos-admission'
    title = 'infer/ enqueues only through the QoS admission path'

    def applies(self, ctx: FileContext) -> bool:
        return 'skypilot_tpu/infer/' in ctx.rel

    def run(self, ctx: FileContext) -> List[Violation]:
        out = []
        for i, line in enumerate(ctx.lines, 1):
            if not _WAITING_PUT_RE.search(line):
                continue
            if 'qos-admission' in line:
                continue
            out.append(Violation(
                ctx.rel, i, self.id,
                'direct ._waiting.put( outside the QoS admission '
                'path — route through engine.submit so priority '
                'classing cannot be bypassed (or mark a sanctioned '
                'admission site with `# qos-admission`)'))
        return out


class KernelDispatchPass(Pass):
    id = 'kernel-dispatch'
    title = 'pallas_call only under ops/, via the dispatch ladder'

    def applies(self, ctx: FileContext) -> bool:
        return _in_framework(ctx) and \
            'skypilot_tpu/ops/' not in ctx.rel

    def run(self, ctx: FileContext) -> List[Violation]:
        out = []
        for i, line in enumerate(ctx.lines, 1):
            if not _PALLAS_CALL_RE.search(line.split('#', 1)[0]):
                continue
            out.append(Violation(
                ctx.rel, i, self.id,
                'pallas_call outside skypilot_tpu/ops/ — kernels '
                'live in ops/ and dispatch through '
                'ops/dispatch.run_ladder so every shape lowers or '
                'falls back (or add `# noqa` with a justification)'))
        return out


class SqliteDisciplinePass(Pass):
    id = 'sqlite-discipline'
    title = 'state DBs open through utils/sqlite_utils.connect'

    def applies(self, ctx: FileContext) -> bool:
        return _in_framework(ctx) and not any(
            ctx.rel.endswith(p) for p in _SQLITE_CONNECT_OK)

    def run(self, ctx: FileContext) -> List[Violation]:
        out = []
        for i, line in enumerate(ctx.lines, 1):
            if not _SQLITE_CONNECT_RE.search(line.split('#', 1)[0]):
                continue
            out.append(Violation(
                ctx.rel, i, self.id,
                'direct sqlite3.connect( — state DBs are '
                'multi-process; open them through '
                'utils/sqlite_utils.connect so the WAL + '
                'busy-timeout recipe applies (or add `# noqa` with a '
                'justification)'))
        return out


class ExceptPassPass(Pass):
    id = 'except-pass'
    title = 'no silent broad exception swallows'

    def applies(self, ctx: FileContext) -> bool:
        return _in_framework(ctx) and not any(
            ctx.rel.endswith(p) for p in _EXCEPT_PASS_OK)

    def run(self, ctx: FileContext) -> List[Violation]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            t = node.type
            broad = (t is None or
                     (isinstance(t, ast.Name) and
                      t.id in ('Exception', 'BaseException')) or
                     (isinstance(t, ast.Attribute) and
                      t.attr in ('Exception', 'BaseException')))
            if not broad:
                continue
            if len(node.body) != 1 or \
                    not isinstance(node.body[0], ast.Pass):
                continue
            out.append(Violation(
                ctx.rel, node.lineno, self.id,
                'except Exception: pass — silent broad swallow; log '
                'it, narrow the exception, or add `# noqa` with a '
                'justification'))
        return out


PASSES = [UnusedImportPass(), WhitespacePass(), PrintCallPass(),
          LoopHostSyncPass(), ClockInjectionPass(), QosAdmissionPass(),
          KernelDispatchPass(), SqliteDisciplinePass(),
          ExceptPassPass()]
