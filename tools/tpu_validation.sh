#!/usr/bin/env bash
# The on-chip checklist: run the moment the TPU tunnel answers. One
# command; artifacts land in /tmp/tpu_validation/.
#
#   bash tools/tpu_validation.sh
#
# ORDERED BY VALUE PER CHIP-MINUTE (round-5 lesson: the tunnel gave a
# ~25-minute window, the old ordering spent all of it on the test gate
# and the round's headline MFU number died with the tunnel):
#   1. probe the chip (45s bound; exit early if wedged)
#   2. full bench.py -> the BENCH artifact (train MFU first inside;
#      partial results survive phase hangs)
#   3. remat comparison (train phase with remat=dots vs =full;
#      floor 0.7691 from round 1, target >= 0.85)
#   4. tests_tpu/ lowering gate on-chip, one pytest PER TEST ID with
#      its own 420s timeout, first hang aborts (covers flash attention,
#      both paged kernels, int8, chunked prefill, spec decode)
#
# SKYT_SPEC_PAGED_ATTN defaulted to 'pallas' after the attempt-2
# on-chip gate proved the MQ kernel (test_spec_mq_kernel_lowers on a
# real v5e). The _kernel -> _kernel_mq(t=1) collapse stays DEFERRED:
# the single-query kernel is the hot path for ALL decode, and
# replacing it wants an on-chip perf A/B (t=1 equivalence alone says
# nothing about speed), not just the correctness gate.
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT=/tmp/tpu_validation
mkdir -p "$OUT"
FAIL=0

# Persistent XLA compile cache: tunnel windows are short and compiles
# through the tunnel are the expensive part — a re-run after a wedge
# (or a second chip window) reuses every compile the first one paid
# for.
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/skyt_jax_cache_tpu}
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-1}

step() {  # step <name> <cmd...>: run, tee, record PASS/FAIL
    local name=$1; shift
    if "$@" 2>&1 | tee "$OUT/$name.txt"; then
        echo "== $name: PASS =="
    else
        echo "== $name: FAIL (see $OUT/$name.txt) =="
        FAIL=1
    fi
}

echo "== 0. skyanalyze (static analysis; costs no chip time) =="
# Archived alongside probe.json: a red analyzer is visible in the
# same bundle as a red probe (docs/static_analysis.md). Not gating —
# the chip window is the scarce resource — but FAIL is recorded.
step skyanalyze python tools/lint.py --json "$OUT/skyanalyze.json"

echo "== 1. probe =="
PROBE_TIMEOUT=${SKYT_TPU_PROBE_TIMEOUT_S:-45}
if ! timeout "$PROBE_TIMEOUT" python -c "import jax; print(jax.devices())"; then
    # Structured fail-fast (same contract as bench.py's backend-init
    # artifact): a wedged tunnel yields a parseable tpu_unreachable
    # record in the artifact dir, not just prose on stdout.
    printf '{"status": "tpu_unreachable", "step": "probe", "timeout_s": %s}\n' \
        "$PROBE_TIMEOUT" | tee "$OUT/probe.json"
    echo "tunnel wedged; aborting (re-run later)"; exit 1
fi

echo "== 2. full bench (the headline artifact) =="
if SKYT_BENCH_INIT_RETRY_S=240 timeout 5400 python bench.py \
        2> "$OUT/bench.err" | tee "$OUT/bench.json"; then
    echo "== bench: PASS =="
else
    echo "== bench: FAIL (see $OUT/bench.err) =="
    FAIL=1
fi

echo "== 3. remat comparison (train phase only, via bench) =="
for pol in dots full; do
    echo "-- remat=$pol --"
    SKYT_BENCH_REMAT=$pol SKYT_BENCH_INIT_RETRY_S=120 \
        timeout 2000 python - <<'PYEOF' 2>&1 | tee "$OUT/remat_$pol.txt"
import bench
dev = bench._acquire_device()
mfu, name = bench.train_mfu(dev, dev.platform == 'tpu')
print(f'REMAT_RESULT {name} mfu={mfu:.4f}')
PYEOF
done

echo "== 4. tests_tpu gate (one pytest per test id, 420s each;"
echo "   first HANG aborts the gate — a wedged tunnel costs one"
echo "   timeout, not the whole window) =="
: > "$OUT/tests_tpu.txt"
GATE_RC=0
GATE_COUNT=0
while read -r tid; do
    [ -z "$tid" ] && continue
    GATE_COUNT=$((GATE_COUNT + 1))
    echo "-- $tid" | tee -a "$OUT/tests_tpu.txt"
    timeout 420 python -m pytest "$tid" -q >> "$OUT/tests_tpu.txt" 2>&1
    rc=$?
    if [ "$rc" -eq 124 ]; then
        echo "   HANG (420s) — tunnel presumed wedged; aborting gate" \
            | tee -a "$OUT/tests_tpu.txt"
        GATE_RC=124; break
    elif [ "$rc" -ne 0 ]; then
        echo "   FAIL rc=$rc" | tee -a "$OUT/tests_tpu.txt"
        GATE_RC=$rc
    else
        echo "   PASS" | tee -a "$OUT/tests_tpu.txt"
    fi
done < <(JAX_PLATFORMS=cpu python -m pytest tests_tpu/ --collect-only -q \
             2>/dev/null | grep '::' > "$OUT/gate_ids.txt";
         PROVEN=tools/onchip_r05/proven_tests.txt
         if [ -f "$PROVEN" ]; then
             # Unproven tests first: a short window should spend its
             # minutes on tests that have never passed on-chip, not on
             # re-proving the ones that already did.
             grep -vxF -f "$PROVEN" "$OUT/gate_ids.txt" || true
             grep -xF -f "$PROVEN" "$OUT/gate_ids.txt" || true
         else
             cat "$OUT/gate_ids.txt"
         fi)
if [ "$GATE_COUNT" -eq 0 ]; then
    # Collection failure/empty suite must not read as a green gate —
    # a vacuous PASS here would green-light flipping kernel defaults.
    echo "== tests_tpu: FAIL (collected 0 test ids) =="
    FAIL=1
elif [ "$GATE_RC" -eq 0 ]; then
    echo "== tests_tpu: PASS ($GATE_COUNT tests) =="
else
    echo "== tests_tpu: FAIL rc=$GATE_RC (see $OUT/tests_tpu.txt) =="
    FAIL=1
fi

echo "== 5. /metrics scrape (debug server on-chip: the observability"
echo "   plane must come up and expose TTFT/KV gauges where the real"
echo "   checkpoint server will) =="
if timeout 600 python - <<'PYEOF' 2>&1 | tee "$OUT/metrics_scrape.txt"
import json
import socket
import subprocess
import sys
import time

import requests

with socket.socket() as s:
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
proc = subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.infer.server',
     '--model', 'debug', '--port', str(port),
     '--num-slots', '2', '--max-seq-len', '128'])
base = f'http://127.0.0.1:{port}'
try:
    deadline = time.time() + 480   # warmup compiles through the tunnel
    while time.time() < deadline:
        try:
            if requests.get(base + '/health',
                            timeout=2).status_code == 200:
                break
        except requests.RequestException:
            pass
        if proc.poll() is not None:
            raise SystemExit(f'server died rc={proc.returncode}')
        time.sleep(1)
    else:
        raise SystemExit('server never became healthy')
    r = requests.post(base + '/generate',
                      json={'tokens': [7, 8, 9], 'max_tokens': 8},
                      timeout=120)
    r.raise_for_status()
    rid = r.headers['X-Request-Id']
    trace = requests.get(base + f'/stats?request_id={rid}',
                         timeout=5).json()
    assert trace['queued'] <= trace['first_token'] <= trace['done'], \
        trace
    text = requests.get(base + '/metrics', timeout=5).text
    for needle in ('# TYPE skyt_infer_ttft_seconds histogram',
                   'skyt_infer_ttft_seconds_bucket',
                   '# TYPE skyt_infer_kv_cache_utilization gauge',
                   'skyt_infer_decode_tokens_total'):
        assert needle in text, f'missing from /metrics: {needle}'
    ttft = trace['first_token'] - trace['queued']
    print(f'METRICS_SCRAPE_OK ttft_s={ttft:.3f} '
          f'lines={len(text.splitlines())}')
    print(json.dumps(trace))
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
PYEOF
then
    echo "== metrics scrape: PASS =="
else
    echo "== metrics scrape: FAIL (see $OUT/metrics_scrape.txt) =="
    FAIL=1
fi

echo "== 6. tracing plane: one trace id spans LB -> server -> engine"
echo "   (in-process LB + debug replica; curls /debug/traces on both"
echo "   hops and asserts the parent chain + flight-recorder snapshot) =="
if SKYT_TRACE=1 SKYT_TRACE_SAMPLE=1 SKYT_TRACE_SLOW_MS=0 \
        SKYT_SERVE_LB_SYNC_INTERVAL=3600 \
        timeout 600 python - <<'PYEOF' 2>&1 | tee "$OUT/trace_chain.txt"
import socket
import threading
import time

import requests
from aiohttp import web

from skypilot_tpu.infer import server as server_lib
from skypilot_tpu.serve import load_balancer as lb_lib

eng = server_lib.build_engine('debug', num_slots=2, max_seq_len=128)
eng.start()
srv = server_lib.InferenceServer(eng)

def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]

srv_port, lb_port = free_port(), free_port()
replica = f'http://127.0.0.1:{srv_port}'
lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:9', lb_port)
lb.policy.set_ready_replicas([replica])
for app, port in ((srv.make_app(), srv_port), (lb.make_app(), lb_port)):
    threading.Thread(target=lambda a=app, p=port: web.run_app(
        a, port=p, print=None, handle_signals=False),
        daemon=True).start()
lb_base = f'http://127.0.0.1:{lb_port}'
deadline = time.time() + 480   # warmup compiles through the tunnel
while time.time() < deadline:
    try:
        if requests.get(lb_base + '/health',
                        timeout=2).status_code == 200:
            break
    except requests.RequestException:
        pass
    time.sleep(1)
else:
    raise SystemExit('replica never became healthy through the LB')
try:
    r = requests.post(lb_base + '/generate',
                      json={'tokens': [7, 8, 9], 'max_tokens': 8},
                      timeout=120)
    r.raise_for_status()
    assert r.headers['X-Replica-Id'] == replica, r.headers
    assert 'X-Request-Id' in r.headers, r.headers
    summ = requests.get(lb_base + '/debug/traces', timeout=5).json()
    gen = [t for t in summ['recent']
           if t['attributes'].get('http.path') == '/generate']
    assert gen, summ
    tid = gen[0]['trace_id']
    lb_rec = requests.get(
        lb_base + f'/debug/traces?trace_id={tid}', timeout=5).json()
    lb_spans = {s['name']: s for s in lb_rec['spans']}
    assert {'lb.request', 'lb.pick_replica', 'lb.proxy'} <= \
        set(lb_spans), lb_spans.keys()
    srv_rec = requests.get(
        replica + f'/debug/traces?trace_id={tid}', timeout=5).json()
    srv_spans = {s['name']: s for s in srv_rec['spans']}
    assert {'server /generate', 'engine.queue_wait', 'engine.prefill',
            'engine.decode'} <= set(srv_spans), srv_spans.keys()
    # The complete chain: engine spans under the server span, the
    # server span under the LB's proxy span (via traceparent).
    assert srv_spans['server /generate']['parent_id'] == \
        lb_spans['lb.proxy']['span_id']
    assert srv_spans['engine.decode']['parent_id'] == \
        srv_spans['server /generate']['span_id']
    assert 'state_snapshot' in srv_rec, 'flight recorder snapshot missing'
    hops = ' '.join(f"{n}={s['duration_ms']}ms"
                    for n, s in sorted(srv_spans.items()))
    print(f'TRACE_CHAIN_OK trace_id={tid} {hops}')
finally:
    eng.stop()
PYEOF
then
    echo "== trace chain: PASS =="
else
    echo "== trace chain: FAIL (see $OUT/trace_chain.txt) =="
    FAIL=1
fi

echo "== 7. chaos drill: SKYT_FAULTS kills one replica mid-burst;"
echo "   every request whose response headers had not been sent must"
echo "   complete on the surviving replica (0 client-visible 5xx)"
echo "   and the LB breaker must open on the dead one =="
if SKYT_SERVE_LB_SYNC_INTERVAL=3600 SKYT_LB_RETRY_BACKOFF_S=0.02 \
        SKYT_LB_BREAKER_THRESHOLD=2 SKYT_LB_BREAKER_COOLDOWN_S=60 \
        timeout 900 python - <<'PYEOF' 2>&1 | tee "$OUT/chaos_drill.txt"
import os
import socket
import subprocess
import sys
import threading
import time

import requests
from aiohttp import web

from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.utils import metrics as metrics_lib

def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]

ports = [free_port(), free_port()]
urls = [f'http://127.0.0.1:{p}' for p in ports]
procs = []
for i, p in enumerate(ports):
    env = dict(os.environ)
    if i == 0:
        # The chaos event, armed through the fault subsystem: replica 0
        # SIGTERMs ITSELF on its 3rd proxied /generate (mid-burst; the
        # where-filter keeps readiness /health probes from counting).
        env['SKYT_FAULTS'] = \
            'server.request=preempt,after=2,where=path:/generate'
    procs.append(subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.infer.server',
         '--model', 'debug', '--port', str(p),
         '--num-slots', '2', '--max-seq-len', '128'],
        env=env))
try:
    for proc, url in zip(procs, urls):
        deadline = time.time() + 480   # warmup compiles via the tunnel
        while time.time() < deadline:
            if proc.poll() is not None:
                raise SystemExit(f'replica died rc={proc.returncode}')
            try:
                if requests.get(url + '/health',
                                timeout=2).status_code == 200:
                    break
            except requests.RequestException:
                pass
            time.sleep(1)
        else:
            raise SystemExit('replica never became healthy')
    lb_port = free_port()
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:9', lb_port,
        metrics_registry=metrics_lib.MetricsRegistry())
    lb.policy.set_ready_replicas(urls)
    threading.Thread(target=lambda: web.run_app(
        lb.make_app(), port=lb_port, print=None,
        handle_signals=False), daemon=True).start()
    base = f'http://127.0.0.1:{lb_port}'
    deadline = time.time() + 30     # poll until the LB app is bound
    while time.time() < deadline:
        try:
            requests.get(base + '/metrics', timeout=2)
            break
        except requests.RequestException:
            time.sleep(0.2)
    results = []
    lock = threading.Lock()
    def one(i):
        r = requests.post(base + '/generate',
                          json={'tokens': [i + 1, i + 2, i + 3],
                                'max_tokens': 8}, timeout=300)
        with lock:
            results.append((r.status_code,
                            r.headers.get('X-Replica-Id')))
    threads = [threading.Thread(target=one, args=(i,))
               for i in range(12)]
    for th in threads:
        th.start()
        time.sleep(0.1)   # spread the burst across the kill
    for th in threads:
        th.join(timeout=300)
    assert len(results) == 12, results
    bad = [r for r in results if r[0] != 200]
    assert not bad, f'client-visible failures: {bad}'
    assert any(rep == urls[1] for _, rep in results), results
    # Replica 0 really died (the fault fired) ...
    deadline = time.time() + 60
    while time.time() < deadline and procs[0].poll() is None:
        time.sleep(1)
    assert procs[0].poll() is not None, 'replica 0 survived the fault'
    # ... and the breaker ejected it ahead of any controller sync.
    text = requests.get(base + '/metrics', timeout=5).text
    assert f'skyt_lb_breaker_state{{replica="{urls[0]}"}} 2' in text, \
        [l for l in text.splitlines() if 'breaker' in l]
    n0 = sum(1 for _, rep in results if rep == urls[0])
    print(f'CHAOS_DRILL_OK 12/12 ok, {n0} served by the doomed '
          f'replica before death, breaker=open')
finally:
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
PYEOF
then
    echo "== chaos drill: PASS =="
else
    echo "== chaos drill: FAIL (see $OUT/chaos_drill.txt) =="
    FAIL=1
fi

echo "== 8. QoS overload drill: a batch-class flood against one"
echo "   replica with SKYT_QOS=1 — every interactive request must"
echo "   succeed (zero 429/5xx) while batch sheds are > 0 =="
if timeout 900 python - <<'PYEOF' 2>&1 | tee "$OUT/qos_drill.txt"
import os
import socket
import subprocess
import sys
import threading
import time

import requests

def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]

port = free_port()
url = f'http://127.0.0.1:{port}'
env = dict(os.environ)
env.update({
    'SKYT_QOS': '1',
    'SKYT_QOS_QUEUE_DEGRADE': '1',
    'SKYT_QOS_QUEUE_SHED': '2',
    'SKYT_QOS_DEGRADE_MAX_TOKENS': '4',
    'SKYT_QOS_REFRESH_S': '0.05',
    'SKYT_QOS_HOLD_S': '5',
    'SKYT_QOS_TTFT_SLO_MS': '0',
    'SKYT_QOS_RESERVE_SLOTS': '1',
})
proc = subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.infer.server',
     '--model', 'debug', '--port', str(port),
     '--num-slots', '2', '--max-seq-len', '128'], env=env)
try:
    deadline = time.time() + 480
    while time.time() < deadline:
        if proc.poll() is not None:
            raise SystemExit(f'replica died rc={proc.returncode}')
        try:
            if requests.get(url + '/health',
                            timeout=2).status_code == 200:
                break
        except requests.RequestException:
            pass
        time.sleep(1)
    else:
        raise SystemExit('replica never became healthy')
    stop = threading.Event()
    def flood():
        s2 = requests.Session()
        while not stop.is_set():
            try:
                r = s2.post(url + '/generate',
                            json={'tokens': [3, 4, 5],
                                  'max_tokens': 48},
                            headers={'X-Priority': 'batch',
                                     'X-Tenant': 'flooder'},
                            timeout=120)
                if r.status_code == 429:
                    time.sleep(min(float(
                        r.headers.get('Retry-After', 1)), 0.25))
            except requests.RequestException:
                pass
    flooders = [threading.Thread(target=flood, daemon=True)
                for _ in range(6)]
    for th in flooders:
        th.start()
    time.sleep(2)
    sess = requests.Session()
    codes = []
    for i in range(12):
        r = sess.post(url + '/generate',
                      json={'tokens': [i + 1, i + 2], 'max_tokens': 4},
                      headers={'X-Priority': 'interactive'},
                      timeout=120)
        codes.append(r.status_code)
    stop.set()
    for th in flooders:
        th.join(timeout=30)
    bad = [c for c in codes if c != 200]
    assert not bad, f'interactive failures under flood: {codes}'
    text = requests.get(url + '/metrics', timeout=5).text
    def shed(cls):
        total = 0.0
        for line in text.splitlines():
            if line.startswith(f'skyt_qos_shed_total{{class="{cls}"'):
                total += float(line.rsplit(' ', 1)[1])
        return total
    assert shed('batch') > 0, 'batch flood never shed'
    assert shed('interactive') == 0, 'interactive was shed'
    print(f'QOS_DRILL_OK 12/12 interactive ok, '
          f'{shed("batch"):.0f} batch sheds, 0 interactive sheds')
finally:
    if proc.poll() is None:
        proc.kill()
PYEOF
then
    echo "== QoS overload drill: PASS =="
else
    echo "== QoS overload drill: FAIL (see $OUT/qos_drill.txt) =="
    FAIL=1
fi

echo "== 9. kernel-path scrape: the dispatch ladder must actually be"
echo "   on the Pallas rung on-chip — a replica silently serving from"
echo "   the XLA fallback would pass every correctness gate while"
echo "   giving away the TPU's perf (docs/kernels.md) =="
# Probe the platform in a SHORT-LIVED process before the server
# exists: once the server subprocess owns the TPU, a jax.devices()
# in the driver would either raise (device busy) or silently read
# 'cpu' — defeating the on-chip degradation warning below.
SKYT_VALIDATION_PLATFORM=$(timeout 60 python -c \
    "import jax; print(jax.devices()[0].platform)" 2>/dev/null || echo unknown)
export SKYT_VALIDATION_PLATFORM
if timeout 600 python - <<'PYEOF' 2>&1 | tee "$OUT/kernel_paths.txt"
import os
import socket
import subprocess
import sys
import time

import requests

with socket.socket() as s:
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
proc = subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.infer.server',
     '--model', 'debug', '--port', str(port),
     '--num-slots', '2', '--max-seq-len', '128'])
base = f'http://127.0.0.1:{port}'
try:
    deadline = time.time() + 480
    while time.time() < deadline:
        try:
            if requests.get(base + '/health',
                            timeout=2).status_code == 200:
                break
        except requests.RequestException:
            pass
        if proc.poll() is not None:
            raise SystemExit(f'server died rc={proc.returncode}')
        time.sleep(1)
    else:
        raise SystemExit('server never became healthy')
    requests.post(base + '/generate',
                  json={'tokens': [7, 8, 9], 'max_tokens': 8},
                  timeout=120).raise_for_status()
    text = requests.get(base + '/metrics', timeout=5).text
    rows = [l for l in text.splitlines()
            if l.startswith('skyt_ops_kernel_path_total')]
    print('\n'.join(rows) or '(no kernel-path samples)')
    pallas = sum(float(l.rsplit(' ', 1)[1]) for l in rows
                 if 'path="pallas' in l)
    xla = sum(float(l.rsplit(' ', 1)[1]) for l in rows
              if 'path="xla"' in l)
    assert pallas > 0, (
        'no Pallas rung selected — the serve path is running entirely '
        'on the XLA fallback; check the ladder warnings in the server '
        'log')
    on_tpu = os.environ.get('SKYT_VALIDATION_PLATFORM') == 'tpu'
    if on_tpu and xla > 0:
        print(f'WARNING: {xla:.0f} op(s) degraded to the XLA rung '
              'on-chip — investigate before trusting perf numbers')
    paths = requests.get(base + '/stats',
                         timeout=5).json().get('kernel_paths', {})
    print(f'KERNEL_PATHS_OK pallas={pallas:.0f} xla={xla:.0f} '
          f'stats={paths}')
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
PYEOF
then
    echo "== kernel-path scrape: PASS =="
else
    echo "== kernel-path scrape: FAIL (see $OUT/kernel_paths.txt) =="
    FAIL=1
fi

echo "== 10. control-plane drill: SIGKILL the serve controller mid-"
echo "   burst — the LB's stale-state mode must keep every request at"
echo "   200 (0 client-visible 5xx), and a restarted controller must"
echo "   ADOPT the replicas (relaunch counter == 0 on /metrics) =="
if SKYT_SERVE_CONTROLLER_INTERVAL=0.3 SKYT_SERVE_LB_SYNC_INTERVAL=0.3 \
        SKYT_STATE_DIR=/tmp/skyt_cp_drill/state \
        SKYT_LOCAL_ROOT=/tmp/skyt_cp_drill/local \
        SKYT_DEFAULT_STORE=local \
        timeout 600 python - <<'PYEOF' 2>&1 | tee "$OUT/control_plane_drill.txt"
import os
import shutil
import socket
import subprocess
import sys
import threading
import time

import requests
import yaml
from aiohttp import web

shutil.rmtree('/tmp/skyt_cp_drill', ignore_errors=True)

import skypilot_tpu as sky
from skypilot_tpu import resources as resources_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.utils import metrics as metrics_lib

REPLICA = (
    "python -c \""
    "import http.server, os;\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    def do_GET(self):\n"
    "        self.send_response(200); self.end_headers();\n"
    "        self.wfile.write(b'ok')\n"
    "    def log_message(self, *a):\n"
    "        pass\n"
    "http.server.HTTPServer(('127.0.0.1', "
    "int(os.environ['SKYT_REPLICA_PORT'])), H).serve_forever()\"")

def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]

task = sky.Task(name='cpd', run=REPLICA)
task.set_resources(resources_lib.Resources(cloud='local'))
spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=2,
                            initial_delay_seconds=120,
                            probe_timeout_seconds=2)
task.service = spec
task_yaml = '/tmp/skyt_cp_drill/cpd.task.yaml'
os.makedirs(os.path.dirname(task_yaml), exist_ok=True)
with open(task_yaml, 'w', encoding='utf-8') as f:
    yaml.safe_dump(task.to_yaml_config(), f)
cport, lport = free_port(), free_port()
assert serve_state.add_service('cpd', spec, task_yaml, cport, lport)
token = serve_state.get_service('cpd')['auth_token']

def spawn_controller():
    return subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.serve.service',
         '--service-name', 'cpd', '--role', 'controller'],
        env=dict(os.environ))

def wait_ready(n, timeout=180):
    deadline = time.time() + timeout
    while time.time() < deadline:
        ready = [r for r in serve_state.get_replicas('cpd')
                 if r.status is serve_state.ReplicaStatus.READY]
        if len(ready) >= n:
            return ready
        time.sleep(0.5)
    raise SystemExit(f'{n} replicas never READY')

ctrl = spawn_controller()
try:
    wait_ready(2)
    reg = metrics_lib.MetricsRegistry()
    lb_port = free_port()
    lb = lb_lib.SkyServeLoadBalancer(
        f'http://127.0.0.1:{cport}', lb_port,
        controller_auth=token, metrics_registry=reg)
    threading.Thread(target=lambda: web.run_app(
        lb.make_app(), port=lb_port, print=None,
        handle_signals=False), daemon=True).start()
    base = f'http://127.0.0.1:{lb_port}'
    deadline = time.time() + 60
    while time.time() < deadline and len(lb.policy.ready_replicas) < 2:
        time.sleep(0.2)
    assert len(lb.policy.ready_replicas) == 2, lb.policy.ready_replicas

    results, lock = [], threading.Lock()
    def one(i):
        r = requests.get(base + f'/drill-{i}', timeout=60)
        with lock:
            results.append(r.status_code)
    threads = [threading.Thread(target=one, args=(i,))
               for i in range(12)]
    for th in threads[:4]:
        th.start()
    ctrl.kill()                      # the chaos event: no grace
    for th in threads[4:]:
        th.start()
    for th in threads:
        th.join(timeout=120)
    bad = [c for c in results if c != 200]
    assert len(results) == 12 and not bad, \
        f'client-visible failures: {results}'
    # Stale-state mode engaged and still serving.
    deadline = time.time() + 30
    while time.time() < deadline and \
            'skyt_lb_stale 1' not in requests.get(
                base + '/metrics', timeout=5).text:
        time.sleep(0.3)
    assert requests.get(base + '/post-kill', timeout=30).status_code \
        == 200

    ctrl = spawn_controller()        # restart: adopt, don't relaunch
    wait_ready(2)
    headers = {'Authorization': f'Bearer {token}'}
    deadline = time.time() + 60
    text = ''
    while time.time() < deadline:
        try:
            text = requests.get(
                f'http://127.0.0.1:{cport}/controller/metrics',
                headers=headers, timeout=5).text
            if 'skyt_serve_replica_adoptions_total{service="cpd"} 2' \
                    in text:
                break
        except requests.RequestException:
            pass
        time.sleep(0.5)
    assert 'skyt_serve_replica_adoptions_total{service="cpd"} 2' \
        in text, [l for l in text.splitlines() if 'replica' in l]
    assert 'skyt_serve_replica_launches_total{service="cpd"}' \
        not in text, 'controller RELAUNCHED instead of adopting'
    assert 'skyt_serve_replica_reaps_total{' not in text
    print('CONTROL_PLANE_DRILL_OK 12/12 through controller death, '
          'adoptions=2 relaunches=0')
finally:
    if ctrl.poll() is None:
        ctrl.kill()
    from skypilot_tpu import core as core_lib
    from skypilot_tpu import state as cluster_state
    for rec in cluster_state.get_clusters():
        try:
            core_lib.down(rec['name'], purge=True)
        except Exception:
            pass
PYEOF
then
    echo "== control-plane drill: PASS =="
else
    echo "== control-plane drill: FAIL (see $OUT/control_plane_drill.txt) =="
    FAIL=1
fi

echo "== 11. fleet telemetry drill: burst through the real LB->server"
echo "   stack; /fleet/slo must report nonzero goodput and"
echo "   /fleet/metrics per-replica series; a telemetry.scrape=error"
echo "   fault against one replica mid-burst must tick the scrape-"
echo "   error counter and age its series out WITHOUT any client-"
echo "   visible 5xx; /fleet/profile proxies a real capture =="
if SKYT_SERVE_LB_SYNC_INTERVAL=3600 SKYT_FLEET_SCRAPE_S=0.2 \
        SKYT_FLEET_STALE_S=3 SKYT_PROFILE_REMOTE=1 \
        timeout 900 python - <<'PYEOF' 2>&1 | tee "$OUT/fleet_drill.txt"
import socket
import subprocess
import sys
import threading
import time

import requests
from aiohttp import web

from skypilot_tpu.serve import fleet as fleet_lib
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.utils import faults
from skypilot_tpu.utils import metrics as metrics_lib

def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]

ports = [free_port(), free_port()]
urls = [f'http://127.0.0.1:{p}' for p in ports]
procs = [subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.infer.server',
     '--model', 'debug', '--port', str(p),
     '--num-slots', '2', '--max-seq-len', '128'])
    for p in ports]
try:
    for proc, url in zip(procs, urls):
        deadline = time.time() + 480   # warmup compiles via the tunnel
        while time.time() < deadline:
            if proc.poll() is not None:
                raise SystemExit(f'replica died rc={proc.returncode}')
            try:
                if requests.get(url + '/health',
                                timeout=2).status_code == 200:
                    break
            except requests.RequestException:
                pass
            time.sleep(1)
        else:
            raise SystemExit('replica never became healthy')
    lb_port = free_port()
    reg = metrics_lib.MetricsRegistry()
    lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:9', lb_port,
                                     metrics_registry=reg)
    lb.policy.set_ready_replicas(urls)
    threading.Thread(target=lambda: web.run_app(
        lb.make_app(), port=lb_port, print=None,
        handle_signals=False), daemon=True).start()
    base = f'http://127.0.0.1:{lb_port}'
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            requests.get(base + '/metrics', timeout=2)
            break
        except requests.RequestException:
            time.sleep(0.2)

    # Fleet plane + its HTTP surface (the same routes the controller
    # mounts), scraping both replicas AND the LB.
    freg = metrics_lib.MetricsRegistry()
    fl = fleet_lib.FleetTelemetry('drill', metrics_registry=freg)
    fleet_port = free_port()
    fapp = web.Application()
    fleet_lib.add_fleet_routes(
        fapp, fl, lambda rid: dict(zip(('1', '2'), urls)).get(rid))
    threading.Thread(target=lambda: web.run_app(
        fapp, port=fleet_port, print=None, handle_signals=False),
        daemon=True).start()
    fbase = f'http://127.0.0.1:{fleet_port}'

    def burst(n, start=0):
        codes = []
        for i in range(n):
            r = requests.post(
                base + '/generate',
                json={'tokens': [start + i + 1, 4, 5],
                      'max_tokens': 8},
                headers={'X-Priority': 'interactive',
                         'X-Tenant': 'drill'}, timeout=120)
            codes.append(r.status_code)
        return codes

    burst(4)                      # prime compiles + SLO series
    for rid, url in zip(('1', '2'), urls):
        assert fl.scrape(rid, url)
    assert fl.scrape('lb', base)
    codes = burst(8, start=10)    # the measured burst

    # Mid-drill chaos: scrapes of replica 1 start failing. The fleet
    # plane must keep serving (errors counted, series aged out) and
    # clients must never notice.
    faults.configure('telemetry.scrape=error,where=replica:1')
    ok1 = fl.scrape('1', urls[0])
    for rid, url in zip(('2', 'lb'), (urls[1], base)):
        assert fl.scrape(rid, url), rid
    codes += burst(4, start=30)
    assert ok1 is False, 'telemetry.scrape fault did not fire'
    errs = freg.get('skyt_fleet_scrape_errors_total').value('1')
    assert errs >= 1, 'scrape-error counter never ticked'
    bad = [c for c in codes if c != 200]
    assert not bad, f'client-visible failures: {codes}'

    slo = requests.get(fbase + '/fleet/slo',
                       params={'window_s': 300}, timeout=10).json()
    good = slo['goodput']
    assert good['good_tokens'] > 0, slo
    assert good['good_tokens_per_chip_second'] > 0, slo
    att = slo['slo']['interactive']['windows']['5m']['attainment']
    text = requests.get(fbase + '/fleet/metrics', timeout=10).text
    for rid in ('1', '2', 'lb'):
        assert f'replica="{rid}"' in text, f'no series for {rid}'
    assert 'skyt_slo_good_tokens_total' in text

    # Stale age-out: replica 1's scrapes keep failing past the TTL.
    deadline = time.time() + 30
    while time.time() < deadline:
        fl.scrape('1', urls[0])
        fl.scrape('2', urls[1])
        if 'replica="1"' not in requests.get(
                fbase + '/fleet/metrics', timeout=10).text:
            break
        time.sleep(0.5)
    else:
        raise SystemExit('faulted replica never aged out')
    faults.reset()

    # On-demand device profile through the fleet proxy.
    prof = requests.post(fbase + '/fleet/profile',
                         params={'replica': '2', 'ms': '100'},
                         timeout=60)
    assert prof.status_code == 200, prof.text
    body = prof.json()
    assert body['trace_dir'] and body['replica'] == '2', body

    print(f'FLEET_DRILL_OK {len(codes)}/{len(codes)} ok through the '
          f'scrape fault, attainment={att}, '
          f'good_tok/chip_s={good["good_tokens_per_chip_second"]}, '
          f'scrape_errors={errs:.0f}, profile n_files='
          f'{body["n_files"]}')
finally:
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
PYEOF
then
    echo "== fleet telemetry drill: PASS =="
else
    echo "== fleet telemetry drill: FAIL (see $OUT/fleet_drill.txt) =="
    FAIL=1
fi

echo "== 12. gang hang drill: one rank of a real 2-rank gang wedges"
echo "   (SKYT_FAULTS=train.step=hang) — the head agent's watchdog"
echo "   must confirm the hang, escalate the cluster job to HUNG,"
echo "   every rank must dump a postmortem bundle (stacks + spans +"
echo "   train state), and the managed-jobs controller must recover"
echo "   to a checkpoint-resumed SUCCEEDED run. Runs on CPU by design:"
echo "   the watchdog plane is host-side and must not need a chip =="
if timeout 900 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_chaos.py::test_chaos_gang_hang_watchdog_recovery \
        -q -p no:cacheprovider 2>&1 | tee "$OUT/gang_hang_drill.txt"
then
    echo "== gang hang drill: PASS =="
else
    echo "== gang hang drill: FAIL (see $OUT/gang_hang_drill.txt) =="
    FAIL=1
fi

echo "== 13. N-active LB drill: 3 concurrently-active LBs"
echo "   (prefix-affinity ring + peer gossip) serve a burst while one"
echo "   SIGKILLs itself mid-burst via SKYT_FAULTS=lb.crash=crash —"
echo "   zero client-visible 5xx, the dead peer leaves the survivors'"
echo "   fresh sets within one exchange interval, and the same"
echo "   affinity key keeps routing to the same replica through every"
echo "   survivor (ring reconvergence via /debug/lb_state). Runs on"
echo "   CPU by design: the front door is host-side =="
if timeout 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_chaos.py::test_chaos_n_active_lb_sigkill_mid_burst \
        tests/test_chaos.py::test_lb_gossip_partition_and_reconverge \
        -q -p no:cacheprovider 2>&1 | tee "$OUT/n_active_lb_drill.txt"
then
    echo "== N-active LB drill: PASS =="
else
    echo "== N-active LB drill: FAIL (see $OUT/n_active_lb_drill.txt) =="
    FAIL=1
fi

echo "== 14. quantized-KV serve drill: one replica with"
echo "   SKYT_KV_DTYPE=int8 against an fp replica — greedy token"
echo "   parity on a fixed prompt set (first tokens exact + >=70%"
echo "   aggregate agreement, the documented quantization bound) and"
echo "   the int8 kernel path visible in skyt_ops_kernel_path_total"
echo "   on /metrics. Runs on CPU too (interpret-mode kernels) =="
if timeout 900 python - <<'PYEOF' 2>&1 | tee "$OUT/kv_int8_drill.txt"
import os
import socket
import subprocess
import sys
import time

import requests

def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]

ports = {'fp': free_port(), 'int8': free_port()}
env_int8 = dict(os.environ, SKYT_KV_DTYPE='int8')
procs = {
    'fp': subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.infer.server',
         '--model', 'debug', '--port', str(ports['fp']),
         '--num-slots', '2', '--max-seq-len', '128',
         '--cache-mode', 'paged']),
    'int8': subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.infer.server',
         '--model', 'debug', '--port', str(ports['int8']),
         '--num-slots', '2', '--max-seq-len', '128',
         '--cache-mode', 'paged'], env=env_int8),
}
urls = {k: f'http://127.0.0.1:{p}' for k, p in ports.items()}
try:
    for name, proc in procs.items():
        deadline = time.time() + 480
        while time.time() < deadline:
            if proc.poll() is not None:
                raise SystemExit(f'{name} replica died '
                                 f'rc={proc.returncode}')
            try:
                if requests.get(urls[name] + '/health',
                                timeout=2).status_code == 200:
                    break
            except requests.RequestException:
                pass
            time.sleep(1)
        else:
            raise SystemExit(f'{name} replica never became healthy')

    prompts = [list(range(1, 20)), list(range(5, 55)),
               list(range(7, 40)), list(range(2, 11))]

    def gen(base, toks):
        r = requests.post(base + '/generate',
                          json={'tokens': toks, 'max_tokens': 8},
                          timeout=300)
        r.raise_for_status()
        return r.json()['tokens']

    total = agree = first_ok = 0
    for p in prompts:
        fp = gen(urls['fp'], p)
        q8 = gen(urls['int8'], p)
        assert len(fp) == len(q8), (fp, q8)
        first_ok += int(fp[0] == q8[0])
        for a, b in zip(fp, q8):
            total += 1
            agree += int(a == b)
    assert first_ok == len(prompts), \
        f'first tokens diverged: {first_ok}/{len(prompts)}'
    frac = agree / total
    assert frac >= 0.7, f'token agreement {frac:.2f} below the bound'

    # The int8 read path must be the one serving: its op label shows
    # in the kernel-path counter, and the fp replica's must NOT.
    text = requests.get(urls['int8'] + '/metrics', timeout=10).text
    line = [l for l in text.splitlines()
            if 'skyt_ops_kernel_path_total' in l
            and 'paged_attention_int8' in l]
    assert line, 'no paged_attention_int8 kernel-path series'
    fp_text = requests.get(urls['fp'] + '/metrics', timeout=10).text
    assert 'paged_attention_int8' not in fp_text
    print(f'KV_INT8_DRILL_OK agreement={frac:.2f} '
          f'first_tokens={first_ok}/{len(prompts)} '
          f'path_series={line[0].strip()}')
finally:
    for proc in procs.values():
        if proc.poll() is None:
            proc.kill()
PYEOF
then
    echo "== quantized-KV drill: PASS =="
else
    echo "== quantized-KV drill: FAIL (see $OUT/kv_int8_drill.txt) =="
    FAIL=1
fi

echo "== 15. rolling-update drill: 2 real engine replicas, a mid-"
echo "   burst in-place weight rollout (canary -> bake -> fleet) to a"
echo "   new checkpoint with zero dropped requests and zero"
echo "   relaunches; then a second rollout with weights.swap=error"
echo "   armed on the canary's checkpoint -> automatic fleet-wide"
echo "   rollback, fleet ending on the old version. CPU-verified =="
if timeout 900 python - <<'PYEOF' 2>&1 | tee "$OUT/rolling_update_drill.txt"
import dataclasses
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

import requests

os.environ['SKYT_STATE_DIR'] = tempfile.mkdtemp(prefix='skyt-ru-state-')
os.environ['SKYT_ROLLOUT_BAKE_S'] = '0.5'

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama
from skypilot_tpu.models import weights as weights_lib
from skypilot_tpu.serve import replica_managers
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib


def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


tmp = tempfile.mkdtemp(prefix='skyt-ru-ckpt-')
cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64,
                          param_dtype='float32', dtype='float32')
model = llama.LlamaModel(cfg)
zeros = jnp.zeros((1, 8), jnp.int32)
ckpts = []
for i, seed in enumerate((0, 7, 11)):
    params = jax.jit(model.init)(jax.random.PRNGKey(seed), zeros)
    path = os.path.join(tmp, f'ckpt_{i}')
    weights_lib.save_hf_checkpoint(cfg, params, path)
    ckpts.append(path)

spec = spec_lib.ServiceSpec(readiness_path='/health', min_replicas=2,
                            weights=ckpts[0])
assert serve_state.add_service('ruv', spec, '/tmp/none.yaml',
                               free_port(), free_port())
token = serve_state.get_service('ruv')['auth_token']
# The canary-kill fault for run 2, keyed on the target checkpoint so
# run 1 is untouched; inherited by the replica processes at spawn.
env = dict(os.environ, SKYT_ADMIN_TOKEN=token,
           SKYT_FAULTS=f'weights.swap=error,where=checkpoint:{ckpts[2]}')
ports = [free_port(), free_port()]
procs = [subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.infer.server',
     '--checkpoint', ckpts[0], '--port', str(p),
     '--num-slots', '2', '--max-seq-len', '64'],
    env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    for p in ports]
urls = [f'http://127.0.0.1:{p}' for p in ports]
try:
    for proc, url in zip(procs, urls):
        deadline = time.time() + 480
        while time.time() < deadline:
            if proc.poll() is not None:
                raise SystemExit(f'replica died rc={proc.returncode}')
            try:
                if requests.get(url + '/health',
                                timeout=2).status_code == 200:
                    break
            except requests.RequestException:
                pass
            time.sleep(0.5)
        else:
            raise SystemExit('replica never became healthy')

    mgr = replica_managers.ReplicaManager('ruv', spec, '/tmp/none.yaml')
    for rid, url in enumerate(urls, start=1):
        info = replica_managers.ReplicaInfo(
            replica_id=rid, cluster_name=f'ruv-{rid}', version=1,
            status=serve_state.ReplicaStatus.READY, endpoint=url)
        mgr.replicas[rid] = info

    results = []
    stop = threading.Event()
    lock = threading.Lock()

    def burst(wid):
        i = 0
        while not stop.is_set():
            i += 1
            try:
                code = requests.post(
                    urls[(wid + i) % 2] + '/generate',
                    json={'tokens': [wid + 1, (i % 5) + 1, 3],
                          'max_tokens': 6}, timeout=120).status_code
            except requests.RequestException as e:
                code = f'EXC:{e!r}'
            with lock:
                results.append(code)

    def drive(target_ckpt, version, want):
        threads = [threading.Thread(target=burst, args=(w,))
                   for w in range(2)]
        stop.clear()
        results.clear()
        for th in threads:
            th.start()
        try:
            mgr.start_rolling_update(
                dataclasses.replace(spec, weights=target_ckpt),
                '/tmp/none.yaml', version)
            deadline = time.time() + 240
            while time.time() < deadline:
                mgr.rollout_tick()
                ro = mgr.rollout_status()
                if ro['phase'] in ('done', 'rolled_back'):
                    break
                time.sleep(0.3)
        finally:
            time.sleep(0.5)
            stop.set()
            for th in threads:
                th.join(timeout=120)
        ro = mgr.rollout_status()
        assert ro['phase'] == want, ro
        with lock:
            bad = [c for c in results if c != 200]
        assert results and not bad, (len(results), bad[:5])
        return ro, len(results)

    # Run 1: clean rollout to ckpt_1 -> fleet on version 2.
    ro, n1 = drive(ckpts[1], 2, 'done')
    wv = {requests.get(u + '/stats', timeout=10).json()['weight_version']
          for u in urls}
    assert wv == {2}, wv
    assert mgr.version == 2

    # Run 2: armed fault kills the canary's swap -> auto-rollback.
    ro2, n2 = drive(ckpts[2], 3, 'rolled_back')
    wv = {requests.get(u + '/stats', timeout=10).json()['weight_version']
          for u in urls}
    assert wv == {2}, wv                 # fleet ended on the OLD version
    assert mgr.version == 2              # spec never committed
    assert 'swap failed' in (ro2['error'] or '')
    # Zero relaunches anywhere: both server processes never restarted.
    assert all(p.poll() is None for p in procs)
    launches = mgr._m_launches.value('ruv')
    assert not launches, launches
    print(f'ROLLING_UPDATE_DRILL_OK run1={n1}/{n1} ok -> v2; '
          f'run2={n2}/{n2} ok, rolled back to v2; relaunches=0')
finally:
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
PYEOF
then
    echo "== rolling-update drill: PASS =="
else
    echo "== rolling-update drill: FAIL (see $OUT/rolling_update_drill.txt) =="
    FAIL=1
fi

echo "== 16. comms plane: link probe + HLO census on the chip; the"
echo "   profile is archived as comms_profile.json alongside"
echo "   probe.json and the collectives CLI writes its structured"
echo "   artifact (docs/observability.md 'Comms plane') =="
if SKYT_COMMS_CACHE="$OUT/comms_profile.json" timeout 600 python - \
        <<'PYEOF' 2>&1 | tee "$OUT/comms_plane.txt"
import json
import os

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import comms_census
from skypilot_tpu.parallel import comms_profile
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import trainer

n = jax.device_count()
axis = 'fsdp'
mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(**{axis: n}))
profile, src = comms_profile.load_or_probe(
    mesh, payloads_mb=[1.0], iters=3, budget_s=240.0, force=True)
summ = comms_profile.summary(profile)
print(f'probe ({src}): {json.dumps(summ, sort_keys=True)}')
if profile['entries']:
    assert os.path.exists(os.environ['SKYT_COMMS_CACHE']), \
        'profile cache not archived'

if n >= 2:
    cfg = llama.CONFIGS['debug']
    model = llama.LlamaModel(cfg)
    tx = trainer.make_optimizer(trainer.TrainerConfig(
        warmup_steps=1, total_steps=4))
    sample = jnp.zeros((4, 64), jnp.int32)
    state, _ = trainer.create_sharded_state(model, tx, mesh, sample,
                                            jax.random.PRNGKey(0))
    step = trainer.make_train_step(model, tx, mesh, donate=False)
    data = {'tokens': sample, 'targets': sample}
    entries, source = comms_census.census_step(
        step, state, data, mesh=mesh, mode='compiled')
    rep = comms_census.report(
        entries, source, profile=profile,
        link_classes=comms_profile.axis_link_classes(mesh))
    print(f'census ({source}): {comms_census.format_report(rep)}')
    assert rep['sites'] > 0, 'census found no collectives'
    assert rep['axes'][axis]['bytes'] > 0
    assert rep['axes'][axis]['seconds'] is not None, \
        'profile did not price the census'
    print(f'COMMS_PLANE_OK sites={rep["sites"]} '
          f'bytes={rep["total_bytes"]} '
          f'predicted_ms={round((rep["total_seconds"] or 0) * 1e3, 3)}')
else:
    print('COMMS_PLANE_OK single-device (probe only)')
PYEOF
then
    echo "== comms plane: PASS =="
else
    echo "== comms plane: FAIL (see $OUT/comms_plane.txt) =="
    FAIL=1
fi
# The structured collectives artifact (PR 6 status discipline).
timeout 300 python -m skypilot_tpu.parallel.collectives \
    --mb 1 --iters 3 --json "$OUT/collectives.json" \
    > "$OUT/collectives.txt" 2>&1 || true
if [ -f "$OUT/collectives.json" ]; then
    echo "collectives artifact: $(head -c 200 "$OUT/collectives.json")"
fi

echo "== 17. capacity plane: seeded open-loop probe against an"
echo "   on-chip replica — short capacity search at the TTFT SLO,"
echo "   busy-ledger sums-to-busy check via /stats, structured"
echo "   capacity_probe.json artifact (docs/observability.md"
echo "   'Capacity plane') =="
if SKYT_VALIDATION_OUT="$OUT" timeout 900 python - \
        <<'PYEOF' 2>&1 | tee "$OUT/capacity_probe.txt"
import json
import os
import socket
import subprocess
import sys
import time

import requests

from skypilot_tpu.benchmark import capacity
from skypilot_tpu.benchmark import workload

OUT = os.environ['SKYT_VALIDATION_OUT']
ART = os.path.join(OUT, 'capacity_probe.json')
TTFT_SLO_S = 2.0    # generous: on-chip debug model, cold HBM


def artifact(status, **kw):
    rec = {'status': status, 'step': 'capacity_probe', **kw}
    with open(ART, 'w') as f:
        json.dump(rec, f, sort_keys=True)
    print(f'capacity artifact: {json.dumps(rec, sort_keys=True)}')


with socket.socket() as s:
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
env = dict(os.environ, SKYT_CAPACITY_LEDGER='1', SKYT_QOS='1')
proc = subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.infer.server',
     '--model', 'debug', '--port', str(port),
     '--num-slots', '2', '--max-seq-len', '64'], env=env)
base = f'http://127.0.0.1:{port}'
try:
    deadline = time.time() + 480
    while time.time() < deadline:
        try:
            if requests.get(base + '/health',
                            timeout=2).status_code == 200:
                break
        except requests.RequestException:
            pass
        if proc.poll() is not None:
            artifact('replica_died', rc=proc.returncode)
            raise SystemExit(f'server died rc={proc.returncode}')
        time.sleep(1)
    else:
        artifact('replica_unhealthy', timeout_s=480)
        raise SystemExit('server never became healthy')

    submit = workload.http_submitter(base, timeout_s=120.0)
    tenants = (workload.TenantProfile(
        tenant='probe', cls='interactive', prompt_mean=12.0,
        prompt_cap=16, output_mean=8.0, output_cap=8),)

    def measure(rate):
        spec = workload.WorkloadSpec(
            seed=workload.default_seed(), duration_s=4.0,
            rate_rps=rate, arrival='poisson', tenants=tenants)
        outs = workload.OpenLoopRunner(
            submit, compression=1.0).run(
                workload.generate_schedule(spec))
        good = sum(1 for o in outs if o.status == 200
                   and o.ttft_s is not None
                   and o.ttft_s <= TTFT_SLO_S)
        return good / max(1, len(outs))

    res = capacity.capacity_search(
        measure, target=0.9, rate_lo=1.0, rate_hi=16.0,
        resolution=0.5, max_trials=5)
    led = requests.get(base + '/stats',
                       timeout=5).json().get('capacity_ledger', {})
    busy = led.get('busy_seconds', 0.0)
    attr = sum(led.get('attributed_seconds', {}).values())
    toks = sum(led.get('tokens', {}).values())
    assert res.max_sustained_qps > 0, \
        f'probe could not sustain the floor rate: {res.as_dict()}'
    assert any(k.startswith('interactive/probe/')
               for k in led.get('tokens', {})), led
    assert attr <= busy + 1e-6, (attr, busy)
    assert toks > 0, led
    artifact('ok',
             max_sustained_qps=res.max_sustained_qps,
             slo_attainment=res.slo_attainment,
             ttft_slo_s=TTFT_SLO_S, trials=len(res.trials),
             busy_seconds=round(busy, 6),
             attributed_seconds=round(attr, 6),
             chip_seconds_per_token=round(attr / toks, 9))
    print(f'CAPACITY_PROBE_OK qps={res.max_sustained_qps} '
          f'attainment={res.slo_attainment:.3f} '
          f's_per_tok={attr / toks:.6f}')
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
PYEOF
then
    echo "== capacity probe: PASS =="
else
    echo "== capacity probe: FAIL (see $OUT/capacity_probe.txt) =="
    FAIL=1
fi

echo "== 18. tiered KV cache: cross-replica page transfer on-chip —"
echo "   two fleet-tier replicas, golden prompt seeded on the donor,"
echo "   refetched via X-KV-Peer on the cold replica; asserts the"
echo "   fetched stream is byte-identical and /kv/prefix is authed"
echo "   (docs/performance.md 'Tiered prefix cache') =="
if SKYT_VALIDATION_OUT="$OUT" timeout 900 python - \
        <<'PYEOF' 2>&1 | tee "$OUT/kv_tier_drill.txt"
import json
import os
import socket
import subprocess
import sys
import time

import requests

OUT = os.environ['SKYT_VALIDATION_OUT']
ART = os.path.join(OUT, 'kv_tier_drill.json')
TOKEN = 'kv-validation'


def artifact(status, **kw):
    rec = {'status': status, 'step': 'kv_tier_drill', **kw}
    with open(ART, 'w') as f:
        json.dump(rec, f, sort_keys=True)
    print(f'kv tier artifact: {json.dumps(rec, sort_keys=True)}')


def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


ports = [free_port(), free_port()]
env = dict(os.environ, SKYT_KV_TIER='fleet', SKYT_ADMIN_TOKEN=TOKEN)
procs = [subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.infer.server',
     '--model', 'debug', '--port', str(p),
     '--num-slots', '2', '--max-seq-len', '128'], env=env)
    for p in ports]
bases = [f'http://127.0.0.1:{p}' for p in ports]
try:
    for proc, base in zip(procs, bases):
        deadline = time.time() + 480
        while time.time() < deadline:
            try:
                if requests.get(base + '/health',
                                timeout=2).status_code == 200:
                    break
            except requests.RequestException:
                pass
            if proc.poll() is not None:
                artifact('replica_died', rc=proc.returncode)
                raise SystemExit(f'server died rc={proc.returncode}')
            time.sleep(1)
        else:
            artifact('replica_unhealthy', timeout_s=480)
            raise SystemExit('server never became healthy')

    donor, fetcher = bases
    # 100 tokens = one full publishable 64-token page on the donor;
    # greedy so the streams must match byte for byte.
    prompt = [(j * 37) % 97 + 3 for j in range(100)]
    body = {'tokens': prompt, 'max_tokens': 8}
    golden = requests.post(donor + '/generate', json=body,
                           timeout=300).json()['tokens']

    # Donor endpoint auth: no bearer -> 403 (the fetch worker sends
    # SKYT_ADMIN_TOKEN; an unauthed scrape must not leak KV bytes).
    rc = requests.get(donor + '/kv/prefix?hashes=' + 'ab' * 8,
                      timeout=10).status_code
    assert rc == 403, f'/kv/prefix without bearer returned {rc}'

    # Cold replica + X-KV-Peer hint: pages are fetched from the
    # donor over HTTP, promoted through the host store, spliced in,
    # and the stream must equal the donor's golden.
    got = requests.post(fetcher + '/generate', json=body,
                        headers={'X-KV-Peer': donor},
                        timeout=300).json()['tokens']
    stats = requests.get(fetcher + '/stats', timeout=10).json()
    tier = stats.get('kv_tier') or {}
    fetched = tier.get('fetched_pages', 0)
    promoted = tier.get('promotions', 0)
    identical = got == golden
    assert fetched > 0, f'no pages fetched from peer: {tier}'
    assert promoted > 0, f'no host->device promotions: {tier}'
    assert identical, f'fetched stream diverged: {got} != {golden}'
    artifact('ok', fleet_fetched_pages=fetched,
             promotions=promoted, byte_identical=identical,
             prefix_cache=stats.get('prefix_cache', {}))
    print(f'KV_TIER_DRILL_OK fetched_pages={fetched} '
          f'promotions={promoted} byte_identical={identical}')
finally:
    for proc in procs:
        proc.terminate()
    for proc in procs:
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
PYEOF
then
    echo "== kv tier drill: PASS =="
else
    echo "== kv tier drill: FAIL (see $OUT/kv_tier_drill.txt) =="
    FAIL=1
fi

echo "== 19. tick plane: interference observatory on-chip — mixed"
echo "   burst through a real server, /debug/ticks populated (+ the"
echo "   chrome export), /fleet/interference shows a nonzero"
echo "   attributed component, and the disaggregation advisor returns"
echo "   a structured verdict (docs/observability.md 'Tick plane') =="
if SKYT_VALIDATION_OUT="$OUT" timeout 900 python - \
        <<'PYEOF' 2>&1 | tee "$OUT/interference_probe.txt"
import json
import os
import socket
import subprocess
import sys
import time

import requests

from skypilot_tpu.benchmark import workload
from skypilot_tpu.serve import fleet as fleet_lib
from skypilot_tpu.utils import metrics as metrics_lib

OUT = os.environ['SKYT_VALIDATION_OUT']
ART = os.path.join(OUT, 'interference_probe.json')


def artifact(status, **kw):
    rec = {'status': status, 'step': 'interference_probe', **kw}
    with open(ART, 'w') as f:
        json.dump(rec, f, sort_keys=True)
    print(f'interference artifact: {json.dumps(rec, sort_keys=True)}')


with socket.socket() as s:
    s.bind(('127.0.0.1', 0))
    port = s.getsockname()[1]
env = dict(os.environ, SKYT_TICKSTATS='1')
proc = subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.infer.server',
     '--model', 'debug', '--port', str(port),
     '--num-slots', '2', '--max-seq-len', '64'], env=env)
base = f'http://127.0.0.1:{port}'
try:
    deadline = time.time() + 480
    while time.time() < deadline:
        try:
            if requests.get(base + '/health',
                            timeout=2).status_code == 200:
                break
        except requests.RequestException:
            pass
        if proc.poll() is not None:
            artifact('replica_died', rc=proc.returncode)
            raise SystemExit(f'server died rc={proc.returncode}')
        time.sleep(1)
    else:
        artifact('replica_unhealthy', timeout_s=480)
        raise SystemExit('server never became healthy')

    # Prime: multi-chunk decodes warm the pure-decode baselines and
    # give every counter/histogram series a first scrape edge (the
    # ITL histogram only observes steady pull-to-pull intervals).
    for _ in range(4):
        requests.post(base + '/generate',
                      json={'tokens': [7, 8, 9, 10],
                            'max_tokens': 24},
                      headers={'X-Priority': 'interactive'},
                      timeout=300).raise_for_status()
    time.sleep(0.5)
    fl = fleet_lib.FleetTelemetry(
        'validation', metrics_registry=metrics_lib.MetricsRegistry())
    assert fl.scrape('1', base), 'baseline scrape failed'

    # Mixed burst: open-loop arrivals force prefill admission while
    # earlier requests are still decoding -> mixed ticks.
    spec = workload.WorkloadSpec(
        seed=workload.default_seed(), duration_s=8.0, rate_rps=5.0,
        arrival='poisson',
        tenants=(workload.TenantProfile(
            tenant='probe', cls='interactive',
            prompt_mean=6.0, prompt_sigma=0.4, prompt_cap=12,
            output_mean=20.0, output_sigma=0.4, output_cap=32,
            session_pool=4, session_reuse=0.3, prefix_len=2),))
    outs = workload.OpenLoopRunner(
        workload.http_submitter(base, timeout_s=300.0),
        compression=2.0).run(workload.generate_schedule(spec))
    ok = sum(1 for o in outs if o.status == 200)
    assert ok > 0, f'no successful requests in the burst ({len(outs)})'
    time.sleep(0.5)
    assert fl.scrape('1', base), 'post-burst scrape failed'

    # /debug/ticks: populated ring, sane summary, chrome export.
    body = requests.get(base + '/debug/ticks?last=64',
                        timeout=10).json()
    summ = body['summary']
    assert summ['ticks'] > 0, summ
    assert summ['by_kind'].get('mixed', 0) > 0, \
        f'burst produced no mixed ticks: {summ["by_kind"]}'
    assert body['ticks'], 'tick ring empty'
    chrome = requests.get(base + '/debug/ticks?format=chrome',
                          timeout=10).json()
    assert chrome.get('traceEvents'), 'chrome export empty'

    # /fleet/interference through the real read path: a nonzero
    # attributed component and a structured advisor verdict.
    rep = fl.interference_report(window_s=600)
    tgt = rep['targets'].get('1')
    assert tgt, f'replica missing from rollup: {rep}'
    attributed = tgt['excess_seconds']
    assert attributed > 0, \
        f'no attributed interference despite mixed ticks: {tgt}'
    adv = tgt['advisor']
    assert adv['recommendation'] in ('disaggregate',
                                     'keep_colocated'), adv
    assert 'benefit_s_per_request' in adv['tradeoff'], adv
    assert 'predicted_transfer_cost_s_per_request' in \
        adv['transfer'], adv

    artifact('ok',
             requests_ok=ok,
             ticks=summ['ticks'],
             by_kind=summ['by_kind'],
             mixed_tick_frac=tgt['mixed_tick_frac'],
             attributed_excess_seconds=round(attributed, 6),
             interference_frac=tgt['interference_frac'],
             advisor_recommendation=adv['recommendation'],
             advisor_reason=adv['reason'],
             dcn_source=rep['dcn_source'])
    print(f'INTERFERENCE_PROBE_OK ticks={summ["ticks"]} '
          f'mixed={summ["by_kind"].get("mixed", 0)} '
          f'excess_s={attributed:.6f} '
          f'advisor={adv["recommendation"]}')
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
PYEOF
then
    echo "== interference probe: PASS =="
else
    echo "== interference probe: FAIL (see $OUT/interference_probe.txt) =="
    FAIL=1
fi

echo "== 20. elastic capacity drill — a scaled-to-zero wake through"
echo "   the LB surge queue (parked class served with zero 5xx,"
echo "   overflow gets honest 503 + Retry-After), then an in-place"
echo "   /admin/reshard layout flip on the live replica: outputs"
echo "   unchanged, an injected reshard fault leaves the old layout"
echo "   intact, and re-asserting the layout is an idempotent no-op"
echo "   (docs/robustness.md 'Elastic capacity') =="
if SKYT_VALIDATION_OUT="$OUT" timeout 900 python - \
        <<'PYEOF' 2>&1 | tee "$OUT/elastic_drill.txt"
import json
import os
import socket
import subprocess
import sys
import threading
import time

import requests
from aiohttp import web

from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.utils import metrics as metrics_lib

OUT = os.environ['SKYT_VALIDATION_OUT']
ART = os.path.join(OUT, 'elastic_drill.json')
TOKEN = 'elastic-validation'


def artifact(status, **kw):
    rec = {'status': status, 'step': 'elastic_drill', **kw}
    with open(ART, 'w') as f:
        json.dump(rec, f, sort_keys=True)
    print(f'elastic artifact: {json.dumps(rec, sort_keys=True)}')


def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


rport = free_port()
# The fault is armed per-target: only virtual_nodes=4 aborts, so the
# same process serves the clean flip, the fault, and the no-op.
env = dict(os.environ, SKYT_ADMIN_TOKEN=TOKEN,
           SKYT_FAULTS='reshard=error,where=virtual_nodes:4')
proc = subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.infer.server',
     '--model', 'debug', '--port', str(rport),
     '--num-slots', '2', '--max-seq-len', '64'], env=env)
rbase = f'http://127.0.0.1:{rport}'
try:
    deadline = time.time() + 480
    while time.time() < deadline:
        try:
            if requests.get(rbase + '/health',
                            timeout=2).status_code == 200:
                break
        except requests.RequestException:
            pass
        if proc.poll() is not None:
            artifact('replica_died', rc=proc.returncode)
            raise SystemExit(f'server died rc={proc.returncode}')
        time.sleep(1)
    else:
        artifact('replica_unhealthy', timeout_s=480)
        raise SystemExit('server never became healthy')

    body = {'tokens': [5, 6, 7], 'max_tokens': 6}
    golden = requests.post(rbase + '/generate', json=body,
                           timeout=300).json()['tokens']

    # -- Scale-to-zero wake: LB with an EMPTY ready set, surge cap 4.
    os.environ.update({'SKYT_SERVE_LB_SYNC_INTERVAL': '3600',
                       'SKYT_LB_SURGE_QUEUE_MAX': '4',
                       'SKYT_LB_NO_REPLICA_POLL_S': '0.05',
                       'SKYT_LB_NO_REPLICA_TIMEOUT_S': '60'})
    reg = metrics_lib.MetricsRegistry()
    lport = free_port()
    lb = lb_lib.SkyServeLoadBalancer('http://127.0.0.1:9', lport,
                                     metrics_registry=reg)
    threading.Thread(target=lambda: web.run_app(
        lb.make_app(), port=lport, print=None,
        handle_signals=False), daemon=True).start()
    base = f'http://127.0.0.1:{lport}'
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            requests.get(base + '/metrics', timeout=2)
            break
        except requests.RequestException:
            time.sleep(0.1)
    outcomes = reg.counter('skyt_lb_surge_requests_total', '',
                           ('lb', 'outcome'))
    depth = reg.gauge('skyt_lb_surge_queue_depth', '', ('lb',))

    results, lock = [], threading.Lock()

    def arrival():
        s2 = requests.Session()
        t0 = time.perf_counter()
        r = s2.post(base + '/generate', json=body, timeout=120)
        with lock:
            results.append((r.status_code, time.perf_counter() - t0,
                            r.headers.get('Retry-After')))

    threads = [threading.Thread(target=arrival) for _ in range(6)]
    for th in threads:
        th.start()
    # 4 park (cap), 2 overflow to an immediate honest 503.
    deadline = time.time() + 20
    while time.time() < deadline:
        if depth.value(lb.lb_id) == 4 \
                and outcomes.value(lb.lb_id, 'overflow') == 2:
            break
        time.sleep(0.05)
    else:
        raise SystemExit(
            f'surge queue never settled: depth={depth.value(lb.lb_id)} '
            f'overflow={outcomes.value(lb.lb_id, "overflow")}')
    time.sleep(1.0)                         # the fleet cold-starts...
    lb.policy.set_ready_replicas([rbase])   # ...and wakes
    for th in threads:
        th.join(timeout=120)
    ok = [r for r in results if r[0] == 200]
    rejected = [r for r in results if r[0] == 503]
    assert len(ok) == 4 and len(rejected) == 2, results
    assert all(r[2] is not None and float(r[2]) >= 1.0
               for r in rejected), rejected
    assert outcomes.value(lb.lb_id, 'served') == 4
    assert outcomes.value(lb.lb_id, 'timeout') == 0
    cold_ttft = sorted(lat for _, lat, _ in ok)[len(ok) // 2]

    # -- In-place reshard on the live replica: layout flips, outputs
    # don't.
    hdr = {'Authorization': f'Bearer {TOKEN}'}
    r = requests.post(rbase + '/admin/reshard',
                      json={'virtual_nodes': 2}, headers=hdr,
                      timeout=120)
    assert r.status_code == 200, (r.status_code, r.text)
    flip = r.json()
    stats = requests.get(rbase + '/stats', timeout=30).json()
    assert stats['virtual_nodes'] == 2, stats
    assert stats['weight_version'] == 1, stats
    got = requests.post(rbase + '/generate', json=body,
                        timeout=300).json()['tokens']
    assert got == golden, f'reshard changed outputs: {got} != {golden}'

    # -- Injected fault (virtual_nodes=4): aborts with the old layout
    # intact, serving unharmed.
    r = requests.post(rbase + '/admin/reshard',
                      json={'virtual_nodes': 4}, headers=hdr,
                      timeout=120)
    assert r.status_code == 400, (r.status_code, r.text)
    assert 'old layout intact' in r.json()['error'], r.json()
    stats = requests.get(rbase + '/stats', timeout=30).json()
    assert stats['virtual_nodes'] == 2, stats
    got = requests.post(rbase + '/generate', json=body,
                        timeout=300).json()['tokens']
    assert got == golden, f'aborted reshard broke serving: {got}'

    # -- Idempotent re-assert (the controller's restart-convergence
    # contract): same layout again is a no-op success.
    r = requests.post(rbase + '/admin/reshard',
                      json={'virtual_nodes': 2}, headers=hdr,
                      timeout=120)
    assert r.status_code == 200 and r.json().get('noop'), r.text
    artifact('ok',
             parked_served=len(ok),
             overflow_503=len(rejected),
             cold_start_ttft_s=round(cold_ttft, 4),
             reshard_duration_s=flip['duration_s'],
             reshard_from_nodes=flip['from_nodes'],
             reshard_virtual_nodes=flip['virtual_nodes'],
             fault_left_layout_intact=True,
             noop_reassert=True,
             outputs_byte_identical=True)
    print(f'ELASTIC_DRILL_OK parked_served={len(ok)} '
          f'overflow_503={len(rejected)} '
          f'cold_ttft_s={cold_ttft:.3f} '
          f'reshard_s={flip["duration_s"]}')
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
PYEOF
then
    echo "== elastic drill: PASS =="
else
    echo "== elastic drill: FAIL (see $OUT/elastic_drill.txt) =="
    FAIL=1
fi

echo "== 21. adapter hot-load drill — a LoRA adapter lands on the"
echo "   live replica mid-burst through POST /admin/adapters (zero"
echo "   client-visible non-200s), generations route by adapter name"
echo "   (unknown name gets an honest 404), an unload is REFUSED with"
echo "   409 while live requests reference the adapter, and the clean"
echo "   unload leaves base serving byte-identical"
echo "   (docs/serving.md 'Adapter fleet') =="
if SKYT_VALIDATION_OUT="$OUT" timeout 900 python - \
        <<'PYEOF' 2>&1 | tee "$OUT/adapter_drill.txt"
import dataclasses as _dc
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

import requests

OUT = os.environ['SKYT_VALIDATION_OUT']
ART = os.path.join(OUT, 'adapter_drill.json')
TOKEN = 'adapter-validation'


def artifact(status, **kw):
    rec = {'status': status, 'step': 'adapter_drill', **kw}
    with open(ART, 'w') as f:
        json.dump(rec, f, sort_keys=True)
    print(f'adapter artifact: {json.dumps(rec, sort_keys=True)}')


def free_port():
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def save_debug_adapter(path, rank=2, alpha=4.0, seed=9):
    # An Orbax adapter dir shaped exactly like an `sft --lora-rank`
    # run writes (TrainStateS), for the debug model the replica
    # serves.
    import jax
    import jax.numpy as jnp
    import numpy as np
    import flax.linen as nn

    from skypilot_tpu.models import llama
    from skypilot_tpu.train import checkpoint as ckpt_lib
    from skypilot_tpu.train import lora as tlora
    from skypilot_tpu.train import trainer

    cfg = _dc.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))['params'])
    lcfg = tlora.LoRAConfig(rank=rank, alpha=alpha)
    tree = tlora.init_lora_params(params, lcfg,
                                  jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)
    tree = jax.tree.map(
        lambda x: jnp.asarray(rng.normal(0, 0.1, x.shape), x.dtype),
        tree)
    tx = trainer.make_optimizer(trainer.TrainerConfig())
    state = trainer.TrainStateS(step=jnp.zeros((), jnp.int32),
                                params=tree, opt_state=tx.init(tree))
    ck = ckpt_lib.Checkpointer(path, async_save=False)
    ck.save(0, state, force=True)
    ck.wait()
    ck.close()
    return path


tmp = tempfile.mkdtemp(prefix='skyt-adapterdrill-')
adapter_dir = save_debug_adapter(os.path.join(tmp, 'adapter_fr'))
rport = free_port()
env = dict(os.environ, SKYT_ADMIN_TOKEN=TOKEN)
proc = subprocess.Popen(
    [sys.executable, '-m', 'skypilot_tpu.infer.server',
     '--model', 'debug', '--port', str(rport),
     '--num-slots', '2', '--max-seq-len', '64'], env=env)
rbase = f'http://127.0.0.1:{rport}'
hdr = {'Authorization': f'Bearer {TOKEN}'}
try:
    deadline = time.time() + 480
    while time.time() < deadline:
        try:
            if requests.get(rbase + '/health',
                            timeout=2).status_code == 200:
                break
        except requests.RequestException:
            pass
        if proc.poll() is not None:
            artifact('replica_died', rc=proc.returncode)
            raise SystemExit(f'server died rc={proc.returncode}')
        time.sleep(1)
    else:
        artifact('replica_unhealthy', timeout_s=480)
        raise SystemExit('server never became healthy')

    body = {'tokens': [5, 6, 7], 'max_tokens': 6}
    golden = requests.post(rbase + '/generate', json=body,
                           timeout=300).json()['tokens']

    # -- Hot load mid-burst: zero client-visible non-200s.
    codes, lock = [], threading.Lock()
    stop = threading.Event()

    def burst(wid):
        s2 = requests.Session()
        i = 0
        while not stop.is_set():
            i += 1
            try:
                r = s2.post(rbase + '/generate',
                            json={'tokens': [wid + 1, (i % 7) + 1, 3],
                                  'max_tokens': 8}, timeout=120)
                with lock:
                    codes.append(r.status_code)
            except requests.RequestException:
                with lock:
                    codes.append(599)
    threads = [threading.Thread(target=burst, args=(w,))
               for w in range(3)]
    for th in threads:
        th.start()
    time.sleep(1.0)
    t0 = time.perf_counter()
    r = requests.post(rbase + '/admin/adapters',
                      json={'op': 'load', 'name': 'fr',
                            'checkpoint': adapter_dir, 'alpha': 4.0},
                      headers=hdr, timeout=240)
    load_s = time.perf_counter() - t0
    assert r.status_code == 200, (r.status_code, r.text)
    time.sleep(1.0)
    stop.set()
    for th in threads:
        th.join(timeout=120)
    bad = [c for c in codes if c != 200]
    assert codes and not bad, f'burst saw non-200s: {bad}'

    # -- Model-aware routing: the adapter serves by name, a ghost
    # gets an honest 404.
    models = requests.get(rbase + '/v1/models', timeout=30).json()
    ids = [m['id'] for m in models['data']]
    assert 'fr' in ids, ids
    r = requests.post(rbase + '/generate',
                      json=dict(body, lora='fr'), timeout=300)
    assert r.status_code == 200, (r.status_code, r.text)
    routed = r.json()['tokens']
    r = requests.post(rbase + '/generate',
                      json=dict(body, lora='ghost'), timeout=120)
    assert r.status_code == 404, (r.status_code, r.text)

    # -- Unload refused while referenced: long lora decodes in
    # flight, the unload 409s, the decodes finish clean.
    ref_codes = []

    def long_lora(wid):
        s2 = requests.Session()
        r2 = s2.post(rbase + '/generate',
                     json={'tokens': [wid + 1, 2, 3],
                           'max_tokens': 60, 'lora': 'fr'},
                     timeout=300)
        with lock:
            ref_codes.append(r2.status_code)
    refs = [threading.Thread(target=long_lora, args=(w,))
            for w in range(4)]
    for th in refs:
        th.start()
    time.sleep(0.05)
    r = requests.post(rbase + '/admin/adapters',
                      json={'op': 'unload', 'name': 'fr'},
                      headers=hdr, timeout=120)
    refused = r.status_code == 409 and 'referenced' in r.json()['error']
    assert refused, (r.status_code, r.text)
    for th in refs:
        th.join(timeout=300)
    assert ref_codes == [200] * 4, ref_codes

    # -- Clean unload: stack drops to base-only, base serving is
    # byte-identical to the pre-load golden.
    deadline = time.time() + 60
    while time.time() < deadline:
        r = requests.post(rbase + '/admin/adapters',
                          json={'op': 'unload', 'name': 'fr'},
                          headers=hdr, timeout=120)
        if r.status_code == 200:
            break
        time.sleep(0.5)
    else:
        raise SystemExit(f'unload never drained: {r.status_code} '
                         f'{r.text[:200]}')
    snap = requests.get(rbase + '/stats', timeout=30).json()['adapters']
    assert snap['count'] == 0, snap
    got = requests.post(rbase + '/generate', json=body,
                        timeout=300).json()['tokens']
    assert got == golden, f'unload broke base serving: {got}'
    artifact('ok',
             burst_requests=len(codes),
             burst_non_200=0,
             adapter_load_s=round(load_s, 4),
             routed_changed_outputs=routed != golden,
             ghost_404=True,
             unload_refused_while_referenced=True,
             referenced_decodes_ok=len(ref_codes),
             base_outputs_byte_identical=True)
    print(f'ADAPTER_DRILL_OK burst={len(codes)} load_s={load_s:.3f} '
          f'refused=409 clean_unload=ok')
finally:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
PYEOF
then
    echo "== adapter drill: PASS =="
else
    echo "== adapter drill: FAIL (see $OUT/adapter_drill.txt) =="
    FAIL=1
fi

echo "artifacts in $OUT"
if [ "$FAIL" = "1" ]; then
    echo "OVERALL: FAIL — if a Pallas kernel failed, serve with the"
    echo "  escape hatches (SKYT_SPEC_PAGED_ATTN=xla and/or"
    echo "  SKYT_PAGED_ATTN=xla) until it is fixed"; exit 1
fi
echo "OVERALL: PASS"
