#!/usr/bin/env bash
# The blocked on-chip checklist (VERDICT r3 items 1-2): run the moment
# the TPU tunnel answers. One command; artifacts land in
# /tmp/tpu_validation/.
#
#   bash tools/tpu_validation.sh
#
# Steps:
#   1. probe the chip (45s bound; exit early if wedged)
#   2. tests_tpu/ lowering gate on-chip (covers flash attention, both
#      paged-attention kernels, int8, chunked prefill, spec decode)
#   3. train MFU with remat=full vs remat=dots (pick the better;
#      floor 0.7691 from round 1, target >= 0.85)
#   4. full bench.py -> the BENCH artifact
#
# After: if step 2 is green, flip SKYT_SPEC_PAGED_ATTN default to
# 'pallas' (models/llama.py) and collapse _kernel into _kernel_mq(t=1)
# in ops/paged_attention.py (equivalence proven by
# test_t1_matches_single_query_kernel).
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT=/tmp/tpu_validation
mkdir -p "$OUT"
FAIL=0

step() {  # step <name> <cmd...>: run, tee, record PASS/FAIL
    local name=$1; shift
    if "$@" 2>&1 | tee "$OUT/$name.txt"; then
        echo "== $name: PASS =="
    else
        echo "== $name: FAIL (see $OUT/$name.txt) =="
        FAIL=1
    fi
}

echo "== 1. probe =="
if ! timeout 45 python -c "import jax; print(jax.devices())"; then
    echo "tunnel wedged; aborting (re-run later)"; exit 1
fi

echo "== 2. tests_tpu gate =="
step tests_tpu timeout 1800 python -m pytest tests_tpu/ -q

echo "== 3. remat comparison (train phase only, via bench) =="
for pol in full dots; do
    echo "-- remat=$pol --"
    SKYT_BENCH_REMAT=$pol SKYT_BENCH_INIT_RETRY_S=120 \
        timeout 2000 python - <<'PYEOF' 2>&1 | tee "$OUT/remat_$pol.txt"
import bench
dev = bench._acquire_device()
mfu, name = bench.train_mfu(dev, dev.platform == 'tpu')
print(f'REMAT_RESULT {name} mfu={mfu:.4f}')
PYEOF
done

echo "== 4. full bench =="
if timeout 5400 python bench.py 2> "$OUT/bench.err" | tee "$OUT/bench.json"
then
    echo "== bench: PASS =="
else
    echo "== bench: FAIL (see $OUT/bench.err) =="
    FAIL=1
fi

echo "artifacts in $OUT"
if [ "$FAIL" = "1" ]; then
    echo "OVERALL: FAIL — do NOT flip kernel defaults"; exit 1
fi
echo "OVERALL: PASS — safe to flip SKYT_SPEC_PAGED_ATTN to 'pallas'"
