#!/usr/bin/env bash
# Chip trap (VERDICT r4 item 1): probe the TPU on a bounded timeout every
# PROBE_INTERVAL seconds for the whole build session. The moment the
# tunnel answers, fire tools/tpu_validation.sh and exit so the caller is
# notified. If the chip never answers, the probe log is the committed
# evidence of continuous unavailability.
#
#   bash tools/tpu_watcher.sh [max_seconds]
#
# Artifacts:
#   /tmp/tpu_watch/probes.log   one line per probe: ISO-time PROBE ok|dead
#   /tmp/tpu_watch/fired        sentinel written when validation launched
#   /tmp/tpu_validation/*       validation artifacts (from the script)
set -u
cd "$(dirname "$0")/.."
OUT=/tmp/tpu_watch
mkdir -p "$OUT"
MAX=${1:-43200}
INTERVAL=${PROBE_INTERVAL:-240}
START=$(date +%s)

log() { echo "$(date -u +%FT%TZ) $*" | tee -a "$OUT/probes.log"; }

log "WATCHER start max=${MAX}s interval=${INTERVAL}s"
while :; do
    now=$(date +%s)
    if (( now - START > MAX )); then
        log "WATCHER timeout after $((now - START))s; chip never answered"
        exit 2
    fi
    if timeout 45 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu', d; print(d)" \
        > "$OUT/last_probe.txt" 2>&1; then
        log "PROBE ok: $(cat "$OUT/last_probe.txt" | head -1)"
        date -u +%FT%TZ > "$OUT/fired"
        log "WATCHER firing tools/tpu_validation.sh"
        bash tools/tpu_validation.sh > "$OUT/validation_run.log" 2>&1
        rc=$?
        log "WATCHER validation rc=$rc (artifacts in /tmp/tpu_validation)"
        exit $rc
    else
        log "PROBE dead: $(tail -1 "$OUT/last_probe.txt" 2>/dev/null | cut -c1-120)"
    fi
    sleep "$INTERVAL"
done
