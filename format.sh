#!/usr/bin/env bash
# Lint + syntax + test gate (reference: format.sh running black/isort/
# mypy/pylint + the unit/smoke test split, SURVEY §4). The image ships
# none of those linters, so this runs the offline equivalents:
# compileall (syntax across the tree) + tools/lint.py, the skyanalyze
# CLI (tools/analysis — AST passes: the nine classic rules plus
# lock-discipline, async-blocking, tracer-safety, env-registry, and
# registry-consistency; docs/static_analysis.md). Exit-code gated.
#
# Test tiers:
#   ./format.sh         fast tier: lint + non-heavy unit tests (<2 min)
#                       + the on-TPU lowering gate (auto-skips off-TPU)
#   ./format.sh --full  everything: adds the compile-heavy JAX suites
#                       and subprocess integration tests (~30 min on the
#                       1-core host) — run before snapshots/releases.
set -e
cd "$(dirname "$0")"

FULL=0
ARGS=()
for a in "$@"; do
  if [ "$a" = "--full" ]; then FULL=1; else ARGS+=("$a"); fi
done

python -m compileall -q skypilot_tpu tests tests_tpu tools bench.py __graft_entry__.py
python tools/lint.py "${ARGS[@]}"
if [ "$FULL" = "1" ]; then
  python -m pytest tests/ -q
else
  python -m pytest tests/ -q -m "not heavy and not integration"
fi
# On-TPU lowering gate (auto-skips on CPU-only machines): Mosaic must
# accept the Pallas kernels — interpret-mode CPU tests cannot catch a
# BlockSpec the real compiler rejects (VERDICT r2, Weak #2).
python -m pytest tests_tpu/ -q
echo "format.sh: clean"
