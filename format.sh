#!/usr/bin/env bash
# Lint + syntax gate (reference: format.sh running black/isort/mypy/
# pylint). The image ships none of those, so this runs the offline
# equivalents: compileall (syntax across the tree) + tools/lint.py
# (unused imports, whitespace, line length).
set -e
cd "$(dirname "$0")"
python -m compileall -q skypilot_tpu tests tools bench.py __graft_entry__.py
python tools/lint.py "$@"
echo "format.sh: clean"
