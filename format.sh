#!/usr/bin/env bash
# Lint + syntax gate (reference: format.sh running black/isort/mypy/
# pylint). The image ships none of those, so this runs the offline
# equivalents: compileall (syntax across the tree) + tools/lint.py
# (unused imports, whitespace, line length).
set -e
cd "$(dirname "$0")"
python -m compileall -q skypilot_tpu tests tests_tpu tools bench.py __graft_entry__.py
python tools/lint.py "$@"
# On-TPU lowering gate (auto-skips on CPU-only machines): Mosaic must
# accept the Pallas kernels — interpret-mode CPU tests cannot catch a
# BlockSpec the real compiler rejects (VERDICT r2, Weak #2).
python -m pytest tests_tpu/ -q
echo "format.sh: clean"
