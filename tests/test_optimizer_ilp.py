"""General-DAG ILP optimizer tests, including random-DAG brute-force
equivalence (reference: tests/test_optimizer_random_dag.py)."""
import itertools
import types

import networkx as nx
import numpy as np
import pytest

from skypilot_tpu import Dag, Task
from skypilot_tpu.optimizer import (LaunchablePlan, OptimizeTarget,
                                    _egress_cost_per_gb,
                                    _optimize_general_ilp)


def _plan(cloud, region, hourly, runtime_s):
    res = types.SimpleNamespace(cloud=cloud, region=region, zone=None)
    return LaunchablePlan(resources=res, hourly_cost=hourly,
                          estimated_runtime_s=runtime_s)


def _cost_objective(dag, tasks, assign):
    total = sum(assign[t].estimated_cost for t in tasks)
    for (u, v) in dag.graph.edges:
        out_gb = getattr(u, 'output_size_gb', 0.0) or 0.0
        total += _egress_cost_per_gb(assign[u].resources,
                                     assign[v].resources) * out_gb
    return total


def _makespan(dag, tasks, assign):
    finish = {}
    for t in nx.topological_sort(dag.graph):
        start = max((finish[u] for u in dag.graph.predecessors(t)),
                    default=0.0)
        finish[t] = start + assign[t].estimated_runtime_s
    return max(finish.values())


def _brute_force(dag, tasks, per_task, objective):
    best, best_assign = None, None
    for combo in itertools.product(*(per_task[t] for t in tasks)):
        assign = dict(zip(tasks, combo))
        val = objective(dag, tasks, assign)
        if best is None or val < best - 1e-12:
            best, best_assign = val, assign
    return best, best_assign


def _diamond():
    """a -> (b, c) -> d: the canonical non-chain DAG."""
    with Dag() as dag:
        a, b, c, d = (Task(n, run='x') for n in 'abcd')
    for t in (a, b, c, d):
        t.output_size_gb = 10.0
    dag.add_edge(a, b)
    dag.add_edge(a, c)
    dag.add_edge(b, d)
    dag.add_edge(c, d)
    return dag, [a, b, c, d]


class TestGeneralDagILP:
    def test_cost_prefers_colocation(self):
        dag, tasks = _diamond()
        # Root task is gcp-only; every other task is individually
        # cheaper on aws, but 10 GB x $0.12/GB cross-cloud egress per
        # cut edge beats the $0.10 per-task saving -> all-gcp wins.
        # (A per-task greedy would pick aws for b/c/d.)
        per_task = {t: [_plan('gcp', 'us-central1', 1.1, 3600),
                        _plan('aws', 'us-east-1', 1.0, 3600)]
                    for t in tasks}
        per_task[tasks[0]] = [_plan('gcp', 'us-central1', 1.1, 3600)]
        choice = _optimize_general_ilp(dag, tasks, per_task,
                                       OptimizeTarget.COST)
        clouds = {choice[t].resources.cloud for t in tasks}
        assert clouds == {'gcp'}

    def test_cost_ignores_egress_when_outputs_tiny(self):
        dag, tasks = _diamond()
        for t in tasks:
            t.output_size_gb = 0.0
        per_task = {t: [_plan('gcp', 'us-central1', 1.1, 3600),
                        _plan('aws', 'us-east-1', 1.0, 3600)]
                    for t in tasks}
        choice = _optimize_general_ilp(dag, tasks, per_task,
                                       OptimizeTarget.COST)
        clouds = {choice[t].resources.cloud for t in tasks}
        assert clouds == {'aws'}

    def test_time_minimizes_makespan(self):
        dag, tasks = _diamond()
        # Critical path runs through b (slow option cheap, fast option
        # exists); TIME target must take the fast one on the critical
        # path but is free to keep c slow.
        per_task = {
            tasks[0]: [_plan('gcp', 'r', 1.0, 100)],
            tasks[1]: [_plan('gcp', 'r', 1.0, 5000),
                       _plan('gcp', 'r', 8.0, 500)],
            tasks[2]: [_plan('gcp', 'r', 1.0, 400)],
            tasks[3]: [_plan('gcp', 'r', 1.0, 100)],
        }
        choice = _optimize_general_ilp(dag, tasks, per_task,
                                       OptimizeTarget.TIME)
        want, _ = _brute_force(dag, tasks, per_task, _makespan)
        got = _makespan(dag, tasks, choice)
        assert got == pytest.approx(want)
        assert choice[tasks[1]].estimated_runtime_s == 500

    @pytest.mark.parametrize('seed', range(6))
    def test_random_dag_cost_matches_bruteforce(self, seed):
        rng = np.random.default_rng(seed)
        n = int(rng.integers(3, 6))
        with Dag() as dag:
            tasks = [Task(f't{i}', run='x') for i in range(n)]
        for i, t in enumerate(tasks):
            t.output_size_gb = float(rng.uniform(0, 50))
            for j in range(i + 1, n):
                if rng.random() < 0.5:
                    dag.add_edge(t, tasks[j])
        assert not dag.is_chain() or n <= 2 or True
        clouds = [('gcp', 'us-central1'), ('gcp', 'europe-west4'),
                  ('aws', 'us-east-1')]
        per_task = {}
        for t in tasks:
            k = int(rng.integers(2, 4))
            per_task[t] = [
                _plan(*clouds[int(rng.integers(0, len(clouds)))],
                      float(rng.uniform(0.5, 5.0)),
                      float(rng.uniform(600, 7200)))
                for _ in range(k)]
        choice = _optimize_general_ilp(dag, tasks, per_task,
                                       OptimizeTarget.COST)
        want, _ = _brute_force(dag, tasks, per_task, _cost_objective)
        got = _cost_objective(dag, tasks, choice)
        assert got == pytest.approx(want, rel=1e-9)

    @pytest.mark.parametrize('seed', range(3))
    def test_random_dag_time_matches_bruteforce(self, seed):
        rng = np.random.default_rng(100 + seed)
        n = int(rng.integers(3, 6))
        with Dag() as dag:
            tasks = [Task(f't{i}', run='x') for i in range(n)]
        for i, t in enumerate(tasks):
            for j in range(i + 1, n):
                if rng.random() < 0.5:
                    dag.add_edge(t, tasks[j])
        per_task = {t: [_plan('gcp', 'r', 1.0,
                              float(rng.uniform(100, 5000)))
                        for _ in range(int(rng.integers(2, 4)))]
                    for t in tasks}
        choice = _optimize_general_ilp(dag, tasks, per_task,
                                       OptimizeTarget.TIME)
        want, _ = _brute_force(dag, tasks, per_task, _makespan)
        got = _makespan(dag, tasks, choice)
        assert got == pytest.approx(want, rel=1e-9)

    def test_end_to_end_nonchain_dag(self, tmp_state_dir):
        """Full Optimizer.optimize on a non-chain DAG over the real
        catalog path."""
        from skypilot_tpu import Resources, state
        from skypilot_tpu.optimizer import Optimizer
        state.set_enabled_clouds(['gcp', 'local'])
        with Dag() as dag:
            a = Task('a', run='x')
            b = Task('b', run='x')
            c = Task('c', run='x')
            d = Task('d', run='x')
            for t in (a, b, c, d):
                t.set_resources(Resources(cpus='2+'))
        dag.add_edge(a, b)
        dag.add_edge(a, c)
        dag.add_edge(b, d)
        dag.add_edge(c, d)
        Optimizer.optimize(dag, quiet=True)
        for t in (a, b, c, d):
            assert t.best_resources is not None
