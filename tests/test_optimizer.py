"""Catalog + optimizer dry-run tests, fully offline (mirrors the reference's
tests/test_optimizer_dryruns.py with its enable_all_clouds monkeypatch trick,
tests/common.py:11)."""
import pytest

from skypilot_tpu import Dag, Resources, Task, catalog, exceptions
from skypilot_tpu.optimizer import OptimizeTarget, Optimizer


@pytest.fixture
def enable_clouds(tmp_state_dir, monkeypatch):
    from skypilot_tpu import state
    state.set_enabled_clouds(['gcp', 'local'])
    yield


class TestCatalog:
    def test_list_accelerators(self):
        accs = catalog.list_accelerators('gcp')
        assert 'tpu-v5e-16' in accs
        assert 'A100' in accs
        assert all(o.price is not None for o in accs['tpu-v5e-16'])

    def test_tpu_slice_price_scales_with_chips(self):
        p4 = catalog.find_offerings('gcp', accelerator='tpu-v5e-4')[0].price
        p16 = catalog.find_offerings('gcp', accelerator='tpu-v5e-16')[0].price
        assert p16 == pytest.approx(4 * p4)

    def test_find_offerings_spot(self):
        offs = catalog.find_offerings('gcp', accelerator='tpu-v5e-16',
                                      use_spot=True)
        assert offs and all(o.spot_price is not None for o in offs)
        assert offs[0].spot_price < offs[0].price

    def test_validate_region_zone(self):
        catalog.validate_region_zone('gcp', 'us-central1', None)
        with pytest.raises(exceptions.InvalidResourcesError):
            catalog.validate_region_zone('gcp', 'mars-north1', None)
        with pytest.raises(exceptions.InvalidResourcesError):
            catalog.validate_region_zone('gcp', None, 'us-central1-zz')

    def test_cpu_filter(self):
        offs = catalog.find_offerings('gcp', min_cpus=16, min_memory=64)
        assert offs
        assert all(o.vcpus >= 16 and o.memory_gib >= 64 for o in offs)


class TestOptimizer:
    def test_single_tpu_task(self, enable_clouds):
        with Dag() as dag:
            t = Task('train', run='python train.py')
            t.set_resources(Resources(accelerators='tpu-v5e-16'))
        Optimizer.optimize(dag, quiet=True)
        assert t.best_resources is not None
        assert t.best_resources.zone is not None
        assert t.best_resources.accelerator_name == 'tpu-v5e-16'

    def test_cost_picks_spot_when_allowed(self, enable_clouds):
        with Dag() as dag:
            t = Task('t', run='x')
            t.set_resources({
                Resources(accelerators='tpu-v5e-16', use_spot=True),
                Resources(accelerators='tpu-v5e-16'),
            })
        Optimizer.optimize(dag, quiet=True)
        assert t.best_resources.use_spot

    def test_zone_pin_respected(self, enable_clouds):
        with Dag() as dag:
            t = Task('t', run='x')
            t.set_resources(Resources(accelerators='tpu-v5e-16',
                                      zone='us-west4-a'))
        Optimizer.optimize(dag, quiet=True)
        assert t.best_resources.zone == 'us-west4-a'

    def test_unavailable_raises(self, enable_clouds):
        with Dag() as dag:
            t = Task('t', run='x')
            t.set_resources(Resources(accelerators='tpu-v5e-16',
                                      zone='europe-west4-a'))  # v5e not there
        with pytest.raises(exceptions.ResourcesUnavailableError):
            Optimizer.optimize(dag, quiet=True)

    def test_blocked_resources_failover(self, enable_clouds):
        with Dag() as dag:
            t = Task('t', run='x')
            t.set_resources(Resources(accelerators='tpu-v5e-16'))
        Optimizer.optimize(dag, quiet=True)
        first_zone = t.best_resources.zone
        blocked = [t.best_resources.copy()]
        Optimizer.optimize(dag, blocked_resources=blocked, quiet=True)
        assert t.best_resources.zone != first_zone

    def test_chain_dp_prefers_colocation(self, enable_clouds):
        with Dag() as dag:
            a = Task('prep', run='x')
            a.set_resources(Resources(cpus='8+', cloud='gcp'))
            b = Task('train', run='y')
            b.set_resources(Resources(accelerators='tpu-v4-8'))  # us-central2
            a >> b
        a.output_size_gb = 500.0
        Optimizer.optimize(dag, quiet=True)
        # Egress pressure should pull the prep task into the TPU's region.
        assert a.best_resources.region == b.best_resources.region

    def test_time_target(self, enable_clouds):
        with Dag() as dag:
            t = Task('t', run='x')
            t.set_resources(Resources(accelerators='tpu-v5e-4'))
        Optimizer.optimize(dag, minimize=OptimizeTarget.TIME, quiet=True)
        assert t.best_resources is not None

    def test_gpu_head_to_head(self, enable_clouds):
        # TPU v5e-4 ($4.8/h) should beat A100:8 ($29/h) on cost.
        with Dag() as dag:
            t = Task('t', run='x')
            t.set_resources({Resources(accelerators='tpu-v5e-4'),
                             Resources(accelerators='A100:8')})
        Optimizer.optimize(dag, quiet=True)
        assert t.best_resources.is_tpu


class TestReviewRegressions:
    def test_cpu_only_never_gets_accelerators(self, enable_clouds):
        from skypilot_tpu.optimizer import Optimizer
        from skypilot_tpu import Dag, Resources, Task
        with Dag() as dag:
            t = Task('cpu', run='x')
            t.set_resources(Resources(cpus='64+', cloud='gcp'))
        import skypilot_tpu.exceptions as ex
        # No CPU VM in the catalog has >=64 vCPUs; must NOT fall back to TPU.
        import pytest as _pytest
        with _pytest.raises(ex.ResourcesUnavailableError):
            Optimizer.optimize(dag, quiet=True)

    def test_cpu_only_picks_cpu_vm(self, enable_clouds):
        from skypilot_tpu.optimizer import Optimizer
        from skypilot_tpu import Dag, Resources, Task
        with Dag() as dag:
            t = Task('cpu', run='x')
            t.set_resources(Resources(cpus='8+', cloud='gcp'))
        Optimizer.optimize(dag, quiet=True)
        assert t.best_resources.accelerators is None
        assert t.best_resources.instance_type.startswith(('n2', 'e2'))

    def test_region_zone_mismatch_rejected(self):
        import pytest as _pytest
        from skypilot_tpu import catalog, exceptions
        with _pytest.raises(exceptions.InvalidResourcesError):
            catalog.validate_region_zone('gcp', 'us-west4', 'us-central1-a')

    def test_multinode_vm_cost_scales(self, enable_clouds):
        from skypilot_tpu.optimizer import Optimizer
        from skypilot_tpu import Dag, Resources, Task
        with Dag() as d1:
            t1 = Task('one', run='x', num_nodes=1)
            t1.set_resources(Resources(instance_type='n2-standard-8',
                                       cloud='gcp'))
        with Dag() as d4:
            t4 = Task('four', run='x', num_nodes=4)
            t4.set_resources(Resources(instance_type='n2-standard-8',
                                       cloud='gcp'))
        p1 = Optimizer.plan_for_task(t1)[0]
        p4 = Optimizer.plan_for_task(t4)[0]
        assert p4.hourly_cost == pytest.approx(4 * p1.hourly_cost)

    def test_disabled_cloud_hint(self, enable_clouds):
        from skypilot_tpu import state
        from skypilot_tpu.optimizer import Optimizer
        from skypilot_tpu import Dag, Resources, Task
        state.set_enabled_clouds(['local'])
        with Dag() as dag:
            t = Task('t', run='x')
            t.set_resources(Resources(accelerators='tpu-v5e-16'))
        with pytest.raises(exceptions.ResourcesUnavailableError,
                           match='not enabled'):
            Optimizer.optimize(dag, quiet=True)


def test_multislice_pays_per_slice():
    """TPU catalog rows price one slice; num_slices=2 doubles the cost."""
    import skypilot_tpu as sky
    from skypilot_tpu import optimizer as opt
    from skypilot_tpu import resources as res_lib

    def plan_for(n):
        t = sky.Task(name='ms-cost', run='x')
        t.set_resources(res_lib.Resources(accelerators='tpu-v5e-16',
                                          num_slices=n))
        return opt.Optimizer.plan_for_task(t)[0]

    one, two = plan_for(1), plan_for(2)
    assert two.hourly_cost == pytest.approx(2 * one.hourly_cost)
