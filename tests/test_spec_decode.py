"""Speculative decoding (n-gram prompt-lookup, greedy): outputs must be
EXACTLY the plain greedy engine's — drafts only ever change speed, the
acceptance gate rejects anything the model wouldn't have emitted itself.

Reference analog: vLLM speculative decoding / prompt-lookup decoding
(the reference serves via vLLM, llm/vllm/serve.yaml); here the engine is
first-class so speculation is too.
"""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.models import llama

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


def _model_and_params():
    cfg = dataclasses.replace(llama.CONFIGS['debug'])
    model = llama.LlamaModel(cfg)
    sample = jnp.zeros((1, 8), jnp.int32)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), sample)
    return model, params


def _run(engine, prompts, max_new=16):
    engine.start()
    try:
        pairs = [engine.submit(p, engine_lib.SamplingParams(
            max_new_tokens=max_new)) for p in prompts]
        outs = []
        for _, q in pairs:
            toks = []
            while True:
                t = q.get(timeout=300)
                if t is None:
                    break
                toks.append(t)
            outs.append(toks)
        return outs
    finally:
        engine.stop()


def _prompts(vocab, lens, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, n).tolist() for n in lens]


@pytest.mark.parametrize('cache_mode', ['dense', 'paged'])
def test_spec_matches_plain_greedy(cache_mode):
    """Random prompts (low acceptance) and a periodic prompt (high
    acceptance): token-for-token equality either way."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    prompts = _prompts(vocab, [7, 19, 33])
    prompts.append([5, 9, 2] * 8)          # periodic: n-gram heaven
    plain = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=128,
                                       cache_mode=cache_mode)
    spec = engine_lib.InferenceEngine(model, params, num_slots=2,
                                      max_seq_len=128,
                                      cache_mode=cache_mode,
                                      spec_decode=3)
    out_p = _run(plain, prompts)
    out_s = _run(spec, prompts)
    assert out_p == out_s
    assert all(len(o) == 16 for o in out_s)
    assert spec.perf['spec_steps'] > 0


def _draft_model_and_params(seed=1, n_layers=1):
    """A smaller, independently initialized llama as the draft."""
    cfg = dataclasses.replace(llama.CONFIGS['debug'], n_layers=n_layers)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(seed),
                                 jnp.zeros((1, 8), jnp.int32))
    return model, params


@pytest.mark.parametrize('cache_mode', ['dense', 'paged'])
def test_draft_model_spec_matches_plain_greedy(cache_mode):
    """A DIFFERENT (smaller, independently initialized) draft model:
    outputs must still be token-for-token the plain greedy engine's —
    the acceptance gate makes draft quality a pure speed knob."""
    model, params = _model_and_params()
    draft_model, draft_params = _draft_model_and_params()
    vocab = model.cfg.vocab_size
    prompts = _prompts(vocab, [7, 19, 33])
    plain = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=128,
                                       cache_mode=cache_mode)
    spec = engine_lib.InferenceEngine(model, params, num_slots=2,
                                      max_seq_len=128,
                                      cache_mode=cache_mode,
                                      spec_decode=3,
                                      draft_model=draft_model,
                                      draft_params=draft_params)
    out_p = _run(plain, prompts)
    out_s = _run(spec, prompts)
    assert out_p == out_s
    assert all(len(o) == 16 for o in out_s)
    assert spec.perf['spec_verify_steps'] > 0


def test_self_draft_accepts_everything():
    """Draft == target (params shared): every greedy draft token IS the
    target's argmax, so acceptance is exactly k on every verify step —
    the mechanism's upper bound, and a strong end-to-end check that
    draft cache positions stay aligned with the target's."""
    model, params = _model_and_params()
    k = 3
    spec = engine_lib.InferenceEngine(model, params, num_slots=2,
                                      max_seq_len=128,
                                      cache_mode='paged',
                                      spec_decode=k,
                                      draft_model=model,
                                      draft_params=params)
    plain = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=128,
                                       cache_mode='paged')
    prompts = _prompts(model.cfg.vocab_size, [9, 21])
    out_s = _run(spec, prompts)
    assert out_s == _run(plain, prompts)
    assert spec.perf['spec_verify_steps'] > 0
    # Full acceptance: k drafts accepted at every verify step.
    assert spec.perf['spec_accepted'] == \
        k * spec.perf['spec_verify_steps'], spec.perf


def test_draft_model_spec_sampled_completes():
    """Sampled requests ride the same rejection-sampling verify with a
    draft-model point mass: requests complete with valid lengths and a
    same-seed rerun is deterministic."""
    model, params = _model_and_params()
    draft_model, draft_params = _draft_model_and_params()

    def run_once():
        eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                         max_seq_len=128,
                                         cache_mode='paged',
                                         spec_decode=3,
                                         draft_model=draft_model,
                                         draft_params=draft_params)
        eng.start()
        try:
            _, q = eng.submit([3, 1, 4, 1, 5], engine_lib.SamplingParams(
                max_new_tokens=12, temperature=0.8, top_k=8, seed=42))
            toks = []
            while True:
                t = q.get(timeout=300)
                if t is None:
                    return toks
                toks.append(t)
        finally:
            eng.stop()

    a = run_once()
    b = run_once()
    assert 1 <= len(a) <= 12
    assert a == b     # keyed rng: reruns are bit-identical


def test_spec_accepts_on_looping_output():
    """Greedy decode from a random-weight model falls into short loops;
    the proposer must convert those into accepted multi-token steps."""
    model, params = _model_and_params()
    prompt = [5, 9, 2] * 8
    spec = engine_lib.InferenceEngine(model, params, num_slots=1,
                                      max_seq_len=256,
                                      cache_mode='paged', page_size=16,
                                      spec_decode=4)
    out = _run(spec, [prompt], max_new=64)
    assert len(out[0]) == 64
    p = spec.perf_stats()
    # Real draft acceptance happened (spec_accepted counts accepted
    # draft tokens exactly, per delivered verify step — immune to the
    # pipelined full-chunk step inflation).
    assert p['spec_accepted'] > 0, p
    # And verify steps beat 1-token-per-step on the looping tail.
    assert p['spec_accept_per_step'] > 0.2, p


def test_spec_xla_gather_fallback_matches(monkeypatch):
    """The SKYT_SPEC_PAGED_ATTN=xla escape hatch (gather verify path)
    produces identical outputs to plain decode. The pallas MQ kernel is
    the default since the on-chip gate, so every other spec test covers
    it — this keeps the documented fallback from rotting."""
    monkeypatch.setenv('SKYT_SPEC_PAGED_ATTN', 'xla')
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    prompts = _prompts(vocab, [7, 19], seed=6) + [[5, 9, 2] * 8]
    plain = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=128,
                                       cache_mode='paged', page_size=16)
    spec = engine_lib.InferenceEngine(model, params, num_slots=2,
                                      max_seq_len=128,
                                      cache_mode='paged', page_size=16,
                                      spec_decode=3)
    assert _run(plain, prompts, max_new=12) == \
        _run(spec, prompts, max_new=12)


def test_spec_with_sampling_mix_rides_spec_path():
    """A batch mixing greedy and temperature-sampled requests rides the
    SPEC path (rejection-sampling verify for the sampled slot, argmax
    verify for the greedy one) and finishes both."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    spec = engine_lib.InferenceEngine(model, params, num_slots=2,
                                      max_seq_len=128,
                                      cache_mode='paged', page_size=16,
                                      spec_decode=3)
    spec.start()
    try:
        _, q_g = spec.submit(_prompts(vocab, [9])[0],
                             engine_lib.SamplingParams(max_new_tokens=8))
        _, q_s = spec.submit(
            _prompts(vocab, [11], seed=1)[0],
            engine_lib.SamplingParams(max_new_tokens=8,
                                      temperature=0.9, top_k=8))
        for q in (q_g, q_s):
            toks = []
            while True:
                t = q.get(timeout=300)
                if t is None:
                    break
                toks.append(t)
            assert len(toks) == 8
        assert spec.perf['spec_verify_steps'] > 0
    finally:
        spec.stop()


def test_spec_survives_plain_interlude():
    """While a sampled request shares the batch, chunks route through
    the plain path — which must keep the device history current so
    speculation resumes with real acceptance (and identical output)
    once the batch is greedy-only again (regression: plain chunks once
    skipped the history write, silently zeroing acceptance forever)."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    prompt = [5, 9, 2] * 8
    plain = engine_lib.InferenceEngine(model, params, num_slots=2,
                                       max_seq_len=256,
                                       cache_mode='paged', page_size=16)
    ref = _run(plain, [prompt], max_new=48)[0]

    spec = engine_lib.InferenceEngine(model, params, num_slots=2,
                                      max_seq_len=256,
                                      cache_mode='paged', page_size=16,
                                      spec_decode=4)
    spec.start()
    try:
        _, q_g = spec.submit(prompt, engine_lib.SamplingParams(
            max_new_tokens=48))
        # Sampled co-tenant forces plain-path chunks early on.
        _, q_s = spec.submit(
            _prompts(vocab, [9], seed=5)[0],
            engine_lib.SamplingParams(max_new_tokens=4,
                                      temperature=0.8))
        for q, want in ((q_s, 4), (q_g, 48)):
            toks = []
            while True:
                t = q.get(timeout=300)
                if t is None:
                    break
                toks.append(t)
            assert len(toks) == want
            if want == 48:
                assert toks == ref
    finally:
        spec.stop()
    assert spec.perf['spec_accepted'] > 0, spec.perf


def test_spec_eos_and_slot_reuse():
    """EOS mid-accepted-run releases the slot after the EOS token and a
    re-admitted request into the same slot stays correct."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    prompts = _prompts(vocab, [9, 21, 13], seed=2)
    plain = engine_lib.InferenceEngine(model, params, num_slots=1,
                                       max_seq_len=128,
                                       cache_mode='paged', page_size=16)
    spec = engine_lib.InferenceEngine(model, params, num_slots=1,
                                      max_seq_len=128,
                                      cache_mode='paged', page_size=16,
                                      spec_decode=3)
    # Learn what token plain greedy emits 4th, then use it as EOS.
    probe = _run(plain, [prompts[0]], max_new=8)[0]
    eos = probe[3]

    def run_eos(engine):
        engine.start()
        try:
            outs = []
            for pr in prompts:
                _, q = engine.submit(pr, engine_lib.SamplingParams(
                    max_new_tokens=8, eos_token=eos))
                toks = []
                while True:
                    t = q.get(timeout=300)
                    if t is None:
                        break
                    toks.append(t)
                outs.append(toks)
            return outs
        finally:
            engine.stop()

    assert run_eos(plain) == run_eos(spec)


def test_spec_max_seq_tail():
    """Requests running into max_seq_len: the spec path must hand the
    tail to the plain path instead of overrunning the cache."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    prompt = _prompts(vocab, [40], seed=3)[0]
    plain = engine_lib.InferenceEngine(model, params, num_slots=1,
                                       max_seq_len=64,
                                       cache_mode='paged', page_size=16)
    spec = engine_lib.InferenceEngine(model, params, num_slots=1,
                                      max_seq_len=64,
                                      cache_mode='paged', page_size=16,
                                      spec_decode=3)
    out_p = _run(plain, [prompt], max_new=64)
    out_s = _run(spec, [prompt], max_new=64)
    assert out_p == out_s
    # Cut off by max_seq_len, not max_new.
    assert len(out_s[0]) < 64


def test_spec_non_pow2_max_seq_hist_width():
    """Regression: with a non-power-of-two max_seq_len, a long prompt's
    pow2 admission bucket can exceed the history buffer's
    max_seq_len + k + 2 width; the insert must clamp, not error out
    (an unclamped dynamic_update_slice kills the engine loop thread and
    every request hangs)."""
    model, params = _model_and_params()
    vocab = model.cfg.vocab_size
    # width = 48 + 2 + 2 = 52; n=40 buckets to 64 > 52 without the clamp
    prompt = _prompts(vocab, [40], seed=7)[0]
    plain = engine_lib.InferenceEngine(model, params, num_slots=1,
                                       max_seq_len=48)
    spec = engine_lib.InferenceEngine(model, params, num_slots=1,
                                      max_seq_len=48, spec_decode=2)
    out_p = _run(plain, [prompt], max_new=8)
    out_s = _run(spec, [prompt], max_new=8)
    assert out_p == out_s
    assert all(len(o) == 8 for o in out_s)


def test_speculative_sample_step_unbiased():
    """The rejection rule's first emitted token must be distributed
    EXACTLY as sequential sampling from the target distribution —
    accept d w.p. p(d), else residual — regardless of which draft the
    proposer picked (the speculative-sampling guarantee)."""
    import jax.numpy as jnp

    vocab, k, trials = 8, 2, 20000
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(1, k + 1, vocab)) * 2.0,
                         jnp.float32)
    temps = jnp.asarray([0.7], jnp.float32)
    # An arbitrary (deliberately mediocre) draft.
    draft = jnp.asarray([[3, 5]], jnp.int32)

    def run(topk, topp=1.0):
        topks = jnp.asarray([topk], jnp.int32)
        topps = jnp.asarray([topp], jnp.float32)
        stepped = jax.jit(jax.vmap(
            lambda key: engine_lib.speculative_sample_step(
                logits, draft, temps, topks, topps, key[None])))
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(trials))
        out, acc = stepped(keys)
        return np.asarray(out[:, 0, 0]), np.asarray(acc)

    # topk off: marginal == softmax(logits_0 / T).
    first, acc = run(0)
    p0 = np.asarray(jax.nn.softmax(logits[0, 0] / temps[0]))
    emp = np.bincount(first, minlength=vocab) / trials
    np.testing.assert_allclose(emp, p0, atol=0.015)
    # Acceptance really happens (draft token 3 has nonzero mass).
    assert 0 < int(np.sum(acc > 0)) < trials

    # topk active: marginal == the top-3-FILTERED softmax — exercising
    # _topk_filter's 3-D broadcast on the spec path.
    first3, _ = run(3)
    l0 = np.asarray(logits[0, 0])
    kth = np.sort(l0)[-3]
    lf = np.where(l0 < kth, -np.inf, l0) / float(temps[0])
    p3 = np.exp(lf - lf.max()); p3 /= p3.sum()
    emp3 = np.bincount(first3, minlength=vocab) / trials
    np.testing.assert_allclose(emp3, p3, atol=0.015)

    # top_p active: marginal == the NUCLEUS-filtered softmax (smallest
    # descending-prob prefix reaching p; exclusive cumsum).
    firstp, _ = run(0, topp=0.6)
    s = np.sort(np.asarray(logits[0, 0]) / float(temps[0]))[::-1]
    order = np.argsort(-np.asarray(logits[0, 0]))
    sp = np.exp(s - s.max()); sp /= sp.sum()
    before = np.cumsum(sp) - sp
    keep = order[before < 0.6]
    lp = np.full(vocab, -np.inf)
    lp[keep] = np.asarray(logits[0, 0])[keep] / float(temps[0])
    pn = np.exp(lp - lp[keep].max()); pn /= pn.sum()
    empp = np.bincount(firstp, minlength=vocab) / trials
    np.testing.assert_allclose(empp, pn, atol=0.015)


def test_speculative_sample_step_greedy_slots_exact():
    """temp == 0 slots are bit-identical to the argmax verify."""
    import jax.numpy as jnp

    vocab, k = 16, 3
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(2, k + 1, vocab)), jnp.float32)
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    # Slot 0: draft = argmax prefix (fully accepted); slot 1: junk.
    draft = jnp.asarray([greedy[0, :k], [0, 0, 0]], jnp.int32)
    temps = jnp.zeros((2,), jnp.float32)
    topks = jnp.zeros((2,), jnp.int32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(2))
    out, acc = engine_lib.speculative_sample_step(
        logits, draft, temps, topks, jnp.ones((2,), jnp.float32), keys)
    np.testing.assert_array_equal(np.asarray(out), greedy)
    assert int(acc[0]) == k
    assert int(acc[1]) == (1 if greedy[1, 0] == 0 else 0)


def test_sampling_filter_matches_host_semantics():
    """Device _sampling_filter and host _sample must induce the same
    support when top_k and top_p are BOTH active (HF/vLLM warper order:
    top-k first, nucleus over the renormalized survivors)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    for trial in range(20):
        vocab = 12
        logits = rng.normal(size=(vocab,)) * 2.0
        temp, top_k, top_p = 0.7, 4, 0.55
        scaled = logits / temp
        # Host reference: top-k mask, renormalize, exclusive-cumsum
        # nucleus (mirrors engine._sample).
        l = scaled.copy()
        kth = np.partition(l, -top_k)[-top_k]
        l = np.where(l < kth, -np.inf, l)
        order = np.argsort(-l)
        s = l[order]
        sp = np.exp(s - s.max()); sp /= sp.sum()
        before = np.cumsum(sp) - sp
        cut = order[before >= top_p]
        l[cut] = -np.inf
        host_support = set(np.where(np.isfinite(l))[0].tolist())

        dev = engine_lib._sampling_filter(
            jnp.asarray(scaled, jnp.float32)[None, :],
            jnp.asarray([top_k], jnp.int32),
            jnp.asarray([top_p], jnp.float32))
        dev_support = set(np.where(np.isfinite(np.asarray(dev[0])))[0]
                          .tolist())
        assert dev_support == host_support, (trial, dev_support,
                                             host_support)
