"""Inference engine tests: KV-cache decode correctness vs full-context
recompute, continuous batching, and the HTTP server.
"""
import threading
import time

import jax
import jax.numpy as jnp
import pytest
import requests

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.models import llama

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


@pytest.fixture(scope='module')
def small_model():
    cfg = llama.CONFIGS['debug']
    import dataclasses
    cfg = dataclasses.replace(cfg, max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    return model, params


def _reference_greedy(model, params, prompt, n_new):
    """Argmax decoding by full-context recompute — the ground truth the
    cached path must reproduce exactly."""
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray([toks], jnp.int32))
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
    return out


def test_moe_cached_decode_matches_full_recompute():
    """Mixtral (MoE) through the same engine: KV-cache decode must equal
    full-context recompute (reference serves Mixtral via vLLM,
    llm/mixtral/serve.yaml; here it is first-class)."""
    import dataclasses

    from skypilot_tpu.models import moe

    cfg, moe_cfg = moe.MIXTRAL_CONFIGS['debug-moe']
    cfg = dataclasses.replace(cfg, max_seq_len=64)
    # Dropless capacity: with a finite capacity factor the GShard router
    # drops tokens as a function of the *batch shape*, so padded prefill
    # vs incremental recompute would legitimately diverge. Serving wants
    # shape-invariant outputs -> capacity >= worst case.
    moe_cfg = dataclasses.replace(moe_cfg, capacity_factor=8.0)
    model = moe.MixtralModel(cfg, moe_cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    prompt = [5, 17, 3, 99, 42]
    want = _reference_greedy(model, params, prompt, 6)

    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16])
    eng.start()
    try:
        got = eng.generate(prompt, engine_lib.SamplingParams(
            max_new_tokens=6))
    finally:
        eng.stop()
    assert got == want


def test_cached_decode_matches_full_recompute(small_model):
    model, params = small_model
    prompt = [5, 17, 3, 99, 42]
    want = _reference_greedy(model, params, prompt, 8)

    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16])
    eng.start()
    try:
        got = eng.generate(prompt, engine_lib.SamplingParams(
            max_new_tokens=8))
    finally:
        eng.stop()
    assert got == want


def test_continuous_batching_concurrent_requests(small_model):
    model, params = small_model
    prompts = [[1, 2, 3], [7, 8], [100, 101, 102, 103]]
    wants = [_reference_greedy(model, params, p, 6) for p in prompts]

    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16])
    eng.start()
    results = [None] * len(prompts)

    def run(i):
        results[i] = eng.generate(prompts[i], engine_lib.SamplingParams(
            max_new_tokens=6))

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(prompts))]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        eng.stop()
    # 3 requests over 2 slots: continuous batching must still produce
    # exactly the isolated-greedy outputs for every request.
    assert results == wants


def test_eos_and_max_tokens(small_model):
    model, params = small_model
    prompt = [5, 17, 3]
    ref = _reference_greedy(model, params, prompt, 8)
    eng = engine_lib.InferenceEngine(model, params, num_slots=1,
                                     max_seq_len=64,
                                     prefill_buckets=[16])
    eng.start()
    try:
        got = eng.generate(prompt, engine_lib.SamplingParams(
            max_new_tokens=8, eos_token=ref[0]))
        full = eng.generate(prompt, engine_lib.SamplingParams(
            max_new_tokens=8, eos_token=-1))
    finally:
        eng.stop()
    # Stops at (and includes) the first eos token.
    assert got == ref[:ref.index(ref[0]) + 1] == [ref[0]]
    assert full == ref  # never-matching eos -> runs to max_new_tokens


def test_temperature_sampling_is_deterministic_per_seed(small_model):
    model, params = small_model
    eng = engine_lib.InferenceEngine(model, params, num_slots=1,
                                     max_seq_len=64,
                                     prefill_buckets=[16])
    eng.start()
    try:
        a = eng.generate([1, 2, 3], engine_lib.SamplingParams(
            max_new_tokens=5, temperature=1.0, seed=7))
        b = eng.generate([1, 2, 3], engine_lib.SamplingParams(
            max_new_tokens=5, temperature=1.0, seed=7))
    finally:
        eng.stop()
    # same seed and same req-id offset parity is not guaranteed; only
    # check shape/validity here (req ids differ -> rng differs).
    assert len(a) == 5 and len(b) == 5


def _boot_http_server(srv) -> str:
    """Run an InferenceServer app on an ephemeral port (daemon thread)
    and block until /health answers; returns the base URL. Shared by
    every HTTP-surface test. Raises if the server never comes up."""
    import socket

    from aiohttp import web

    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    threading.Thread(
        target=lambda: web.run_app(srv.make_app(), port=port,
                                   print=None, handle_signals=False),
        daemon=True).start()
    base = f'http://127.0.0.1:{port}'
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if requests.get(base + '/health', timeout=2).status_code \
                    == 200:
                return base
        except requests.RequestException:
            pass
        time.sleep(0.2)
    raise RuntimeError('server never became healthy')


@pytest.mark.integration
def test_http_server(small_model):
    from aiohttp import web

    from skypilot_tpu.infer import server as server_lib

    model, params = small_model
    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16])
    eng.start()
    srv = server_lib.InferenceServer(eng)
    base = _boot_http_server(srv)

    want = _reference_greedy(model, params, [9, 9, 9], 4)
    resp = requests.post(base + '/generate',
                         json={'tokens': [9, 9, 9], 'max_tokens': 4},
                         timeout=120)
    assert resp.status_code == 200
    assert resp.json()['tokens'] == want

    # 'max_new_tokens' is accepted as an alias for 'max_tokens'.
    resp = requests.post(base + '/generate',
                         json={'tokens': [9, 9, 9],
                               'max_new_tokens': 4},
                         timeout=120)
    assert resp.status_code == 200
    assert resp.json()['tokens'] == want

    # Penalties flow through /generate: a huge presence penalty makes
    # every generated token distinct (debug models loop otherwise).
    resp = requests.post(base + '/generate',
                         json={'tokens': [5, 9, 2], 'max_tokens': 12,
                               'presence_penalty': 1e9},
                         timeout=120).json()
    assert len(set(resp['tokens'])) == 12

    # Streaming: one ndjson line per token.
    resp = requests.post(base + '/generate',
                         json={'tokens': [9, 9, 9], 'max_tokens': 4,
                               'stream': True},
                         timeout=120, stream=True)
    lines = [l for l in resp.iter_lines() if l]
    import json as json_lib
    assert [json_lib.loads(l)['token'] for l in lines] == want

    stats = requests.get(base + '/stats', timeout=5).json()
    assert stats['num_slots'] == 2
    eng.stop()


@pytest.mark.integration
def test_openai_compat_endpoints(small_model):
    """OpenAI-compatible surface (reference: vLLM's OpenAI server behind
    SkyServe; llm/vllm/service.yaml probes /v1/models)."""
    from aiohttp import web

    from skypilot_tpu.infer import server as server_lib

    model, params = small_model
    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16])
    eng.start()
    srv = server_lib.InferenceServer(eng, model_id='debug-model')
    base = _boot_http_server(srv)

    try:
        models = requests.get(base + '/v1/models', timeout=5).json()
        assert models['data'][0]['id'] == 'debug-model'

        r = requests.post(base + '/v1/completions',
                          json={'prompt': 'hi', 'max_tokens': 4},
                          timeout=120).json()
        assert r['object'] == 'text_completion'
        assert r['choices'][0]['finish_reason'] in ('stop', 'length')
        assert r['usage']['completion_tokens'] >= 1
        assert isinstance(r['choices'][0]['text'], str)

        # Batch of prompts -> one choice per prompt, indexed.
        r = requests.post(base + '/v1/completions',
                          json={'prompt': ['a', 'bb'], 'max_tokens': 3},
                          timeout=120).json()
        assert [c['index'] for c in r['choices']] == [0, 1]

        # OpenAI also accepts token-array prompts: [int] is ONE prompt,
        # [[int]] a batch of one — both must decode the greedy reference
        # continuation.
        want = _reference_greedy(model, params, [9, 9, 9], 3)
        want_text = srv.tokenizer.decode(want)
        r1 = requests.post(base + '/v1/completions',
                           json={'prompt': [9, 9, 9], 'max_tokens': 3},
                           timeout=120).json()
        assert len(r1['choices']) == 1
        assert r1['usage']['prompt_tokens'] == 3
        assert r1['choices'][0]['text'] == want_text
        r2 = requests.post(base + '/v1/completions',
                           json={'prompt': [[9, 9, 9]],
                                 'max_tokens': 3}, timeout=120).json()
        assert r2['choices'][0]['text'] == want_text

        # Streaming SSE: data: chunks, final chunk carries the
        # finish_reason, then [DONE].
        resp = requests.post(base + '/v1/completions',
                             json={'prompt': 'hi', 'max_tokens': 3,
                                   'stream': True},
                             timeout=120, stream=True)
        lines = [l.decode() for l in resp.iter_lines() if l]
        assert lines[-1] == 'data: [DONE]'
        import json as json_lib
        chunks = [json_lib.loads(l[len('data: '):]) for l in lines[:-1]]
        assert all(c['object'] == 'text_completion' for c in chunks)
        assert chunks[-1]['choices'][0]['finish_reason'] == 'length'
        assert all(c['choices'][0]['finish_reason'] is None
                   for c in chunks[:-1])

        # stream + multi-prompt rejected BEFORE any engine work.
        assert requests.post(base + '/v1/completions',
                             json={'prompt': ['a', 'b'], 'stream': True},
                             timeout=10).status_code == 400
        assert requests.get(base + '/stats',
                            timeout=5).json()['waiting'] == 0

        # Sampling bounds the device path cannot honor exactly are
        # 400s, not silent clamps: top_k caps at the 64-wide device
        # sort bucket, top_p must be a probability.
        r = requests.post(base + '/v1/completions',
                          json={'prompt': 'hi', 'max_tokens': 2,
                                'temperature': 1.0, 'top_k': 200},
                          timeout=10)
        assert r.status_code == 400 and '64' in r.json()['error']
        assert requests.post(base + '/v1/completions',
                             json={'prompt': 'hi', 'top_p': 1.5},
                             timeout=10).status_code == 400
        assert requests.post(base + '/v1/chat/completions',
                             json={'messages': [{'role': 'user',
                                                 'content': 'x'}],
                                   'top_k': 65},
                             timeout=10).status_code == 400
        # ... and rejected requests never occupied a slot.
        assert requests.get(base + '/stats',
                            timeout=5).json()['waiting'] == 0
        # top_k at exactly the bucket bound is accepted.
        r = requests.post(base + '/v1/completions',
                          json={'prompt': 'hi', 'max_tokens': 2,
                                'temperature': 1.0, 'top_k': 64},
                          timeout=120)
        assert r.status_code == 200

        r = requests.post(
            base + '/v1/chat/completions',
            json={'messages': [{'role': 'user', 'content': 'hello'}],
                  'max_tokens': 4}, timeout=120).json()
        assert r['object'] == 'chat.completion'
        assert r['choices'][0]['message']['role'] == 'assistant'

        # Chat streaming: first delta carries the assistant role.
        resp = requests.post(
            base + '/v1/chat/completions',
            json={'messages': [{'role': 'user', 'content': 'hi'}],
                  'max_tokens': 3, 'stream': True},
            timeout=120, stream=True)
        lines = [l.decode() for l in resp.iter_lines() if l]
        chunks = [json_lib.loads(l[len('data: '):]) for l in lines[:-1]]
        assert chunks[0]['choices'][0]['delta'].get('role') == \
            'assistant'
        assert chunks[-1]['choices'][0]['finish_reason'] == 'length'

        # stop sequences: output truncated BEFORE the stop text, the
        # engine request cancelled (slot freed), finish_reason 'stop'.
        full = requests.post(base + '/v1/completions',
                             json={'prompt': [9, 9, 9],
                                   'max_tokens': 8},
                             timeout=120).json()['choices'][0]['text']
        assert len(full) >= 2
        stop_at = full[1]    # some char early in the output
        r = requests.post(base + '/v1/completions',
                          json={'prompt': [9, 9, 9], 'max_tokens': 8,
                                'stop': stop_at}, timeout=120).json()
        got = r['choices'][0]['text']
        assert stop_at not in got and full.startswith(got)
        assert r['choices'][0]['finish_reason'] == 'stop'
        deadline2 = time.time() + 30
        while time.time() < deadline2:
            st = requests.get(base + '/stats', timeout=5).json()
            if st['active_slots'] == 0:
                break
            time.sleep(0.2)
        assert st['active_slots'] == 0   # cancelled slot really freed

        # Streaming with a stop sequence: stream ends with 'stop' and
        # the stop text never appears.
        resp = requests.post(base + '/v1/completions',
                             json={'prompt': [9, 9, 9], 'max_tokens': 8,
                                   'stop': stop_at, 'stream': True},
                             timeout=120, stream=True)
        lines = [l.decode() for l in resp.iter_lines() if l]
        chunks = [json_lib.loads(l[len('data: '):]) for l in lines[:-1]]
        text = ''.join(c['choices'][0]['text'] for c in chunks[:-1])
        assert stop_at not in text
        assert chunks[-1]['choices'][0]['finish_reason'] == 'stop'

        # Multi-char stop spanning token boundaries (byte tokenizer:
        # one token per char): the stream must never leak the stop's
        # first char.
        if len(full) >= 4:
            stop2 = full[1:3]     # two chars -> spans two tokens
            # OpenAI semantics truncate at the EARLIEST occurrence of
            # the stop string — which can precede index 1 when the
            # debug model emits repeated chars (e.g. full='3333…'
            # makes stop2='33' match at index 0), so derive the
            # expectation from find() instead of assuming index 1.
            want2 = full[:full.find(stop2)]
            r = requests.post(base + '/v1/completions',
                              json={'prompt': [9, 9, 9],
                                    'max_tokens': 8, 'stop': stop2},
                              timeout=120).json()
            assert r['choices'][0]['text'] == want2
            assert r['choices'][0]['finish_reason'] == 'stop'
            resp = requests.post(base + '/v1/completions',
                                 json={'prompt': [9, 9, 9],
                                       'max_tokens': 8, 'stop': stop2,
                                       'stream': True},
                                 timeout=120, stream=True)
            lines = [l.decode() for l in resp.iter_lines() if l]
            chunks = [json_lib.loads(l[len('data: '):])
                      for l in lines[:-1]]
            text = ''.join(c['choices'][0]['text'] for c in chunks[:-1])
            assert text == want2       # holdback: no stop prefix leaked

        # Malformed n / stop -> 400, not 500.
        for bad in ({'n': 0}, {'n': 'abc'}, {'n': 129}, {'stop': 7},
                    {'stop': [1, 2]}):
            code = requests.post(base + '/v1/completions',
                                 json={'prompt': 'hi', **bad},
                                 timeout=10).status_code
            assert code == 400, bad

        # logprobs: chosen-token raw logprobs aligned with the text.
        r = requests.post(base + '/v1/completions',
                          json={'prompt': [9, 9, 9], 'max_tokens': 4,
                                'logprobs': 1}, timeout=120).json()
        lp = r['choices'][0]['logprobs']
        assert len(lp['token_logprobs']) == len(lp['tokens']) == 4
        assert all(isinstance(x, float) and x <= 0.0
                   for x in lp['token_logprobs'])
        assert ''.join(lp['tokens']) == r['choices'][0]['text']
        # logprobs + stop / stream -> 400.
        assert requests.post(base + '/v1/completions',
                             json={'prompt': 'hi', 'logprobs': 1,
                                   'stop': 'x'},
                             timeout=10).status_code == 400
        assert requests.post(base + '/v1/completions',
                             json={'prompt': 'hi', 'logprobs': 1,
                                   'stream': True},
                             timeout=10).status_code == 400

        # n > 1: one choice per completion, prompt-major indexing.
        r = requests.post(base + '/v1/completions',
                          json={'prompt': 'hi', 'max_tokens': 3,
                                'n': 2}, timeout=120).json()
        assert [c['index'] for c in r['choices']] == [0, 1]
        r = requests.post(
            base + '/v1/chat/completions',
            json={'messages': [{'role': 'user', 'content': 'hello'}],
                  'max_tokens': 3, 'n': 2}, timeout=120).json()
        assert len(r['choices']) == 2

        assert requests.post(base + '/v1/completions', json={},
                             timeout=10).status_code == 400
        assert requests.post(base + '/v1/chat/completions', json={},
                             timeout=10).status_code == 400
    finally:
        eng.stop()


def test_engine_cancel_running_and_waiting(small_model):
    """cancel(): a running request's queue terminates early and its slot
    frees; a waiting request never occupies a slot."""
    model, params = small_model
    eng = engine_lib.InferenceEngine(model, params, num_slots=1,
                                     max_seq_len=64,
                                     prefill_buckets=[16],
                                     decode_chunk=1)
    eng.start()
    try:
        rid1, q1 = eng.submit([1, 2, 3], engine_lib.SamplingParams(
            max_new_tokens=40))
        # Occupy the only slot, then queue a second request behind it.
        rid2, q2 = eng.submit([4, 5], engine_lib.SamplingParams(
            max_new_tokens=40))
        first = q1.get(timeout=120)
        assert first is not None
        assert eng.cancel(rid1) and eng.cancel(rid2)
        got1 = [first]
        while True:
            t = q1.get(timeout=120)
            if t is None:
                break
            got1.append(t)
        assert len(got1) < 40          # ended early
        assert q2.get(timeout=120) is None   # never ran
        # Slot is reusable after the cancels.
        out = eng.generate([9, 9, 9], engine_lib.SamplingParams(
            max_new_tokens=4))
        assert len(out) == 4
        assert eng.cancel(12345) is False
    finally:
        eng.stop()


def test_logprobs_match_recompute_reference(small_model):
    """params.logprobs: the queue yields (token, logprob) pairs whose
    logprob equals the raw log-softmax of a full-context recompute —
    for the first token (host path), plain decode (device path), and
    the speculative verify path (greedy parity extends to logprobs)."""
    from skypilot_tpu.infer import server as server_lib

    model, params = small_model
    prompt = [5, 9, 2] * 4

    def ref_lps(n_new):
        toks = list(prompt)
        out = []
        for _ in range(n_new):
            logits = model.apply(params, jnp.asarray([toks], jnp.int32))
            row = jnp.asarray(logits[0, -1], jnp.float32)
            lse = jax.scipy.special.logsumexp(row)
            nxt = int(jnp.argmax(row))
            out.append((nxt, float(row[nxt] - lse)))
            toks.append(nxt)
        return out

    want = ref_lps(6)

    def run(spec):
        eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                         max_seq_len=64,
                                         prefill_buckets=[16],
                                         spec_decode=spec)
        eng.start()
        try:
            _, q = eng.submit(prompt, engine_lib.SamplingParams(
                max_new_tokens=6, logprobs=True))
            got = []
            while True:
                item = q.get(timeout=300)
                if item is None:
                    return got
                got.append(item)
        finally:
            eng.stop()

    for spec in (0, 3):
        got = run(spec)
        assert [t for t, _ in got] == [t for t, _ in want], spec
        for (t, lp), (_, wlp) in zip(got, want):
            assert abs(lp - wlp) < 2e-3, (spec, t, lp, wlp)


def test_presence_penalty_forbids_repeats(small_model):
    """Greedy + a huge presence penalty: every emitted token is
    distinct (each emission zeroes its own future logit mass), while
    the unpenalized run repeats (debug models loop)."""
    model, params = small_model

    def run(pres, spec=0):
        eng = engine_lib.InferenceEngine(model, params, num_slots=1,
                                         max_seq_len=64,
                                         prefill_buckets=[16],
                                         spec_decode=spec)
        eng.start()
        try:
            return eng.generate([5, 9, 2], engine_lib.SamplingParams(
                max_new_tokens=12, presence_penalty=pres))
        finally:
            eng.stop()

    plain = run(0.0)
    assert len(set(plain)) < len(plain)      # loops without penalty
    pen = run(1e9)
    assert len(set(pen)) == len(pen) == 12   # all distinct
    # Same through a spec engine: penalized requests take the plain
    # path (vLLM-style fallback) and still honor the penalty.
    pen_spec = run(1e9, spec=3)
    assert pen_spec == pen


def test_logprobs_tokens_multibyte_alignment(small_model):
    """logprobs token pieces must concatenate exactly to the text even
    when a multi-byte UTF-8 char spans tokens (byte tokenizer: 0xC3
    0xA9 = 'é' across two tokens)."""
    from skypilot_tpu.infer import server as server_lib
    from skypilot_tpu.infer import tokenizer as tokenizer_lib

    model, params = small_model
    eng = engine_lib.InferenceEngine(model, params, num_slots=1,
                                     max_seq_len=64,
                                     prefill_buckets=[16])
    srv = server_lib.InferenceServer(eng)
    tok = srv.tokenizer
    assert isinstance(tok, tokenizer_lib.ByteTokenizer)

    # Drive the piece-builder logic directly (the engine's outputs are
    # arbitrary bytes; craft the interesting token stream by hand).
    visible = [0xC3, 0xA9, ord('a')]
    dec = srv._incremental_decoder()
    pieces = [dec(t) or '' for t in visible]
    tail = dec(None)
    if tail and pieces:
        pieces[-1] += tail
    assert ''.join(pieces) == tok.decode(visible) == 'éa'
    assert pieces == ['', 'é', 'a']


def test_chat_template_rendering(tmp_path):
    """A checkpoint's HF jinja chat template renders for chat
    completions (llama-3-style header tokens), with the generic
    role-tag fallback on render errors."""
    import dataclasses
    import json

    from skypilot_tpu.infer import server
    from skypilot_tpu.infer import tokenizer as tokenizer_lib

    tpl = (
        "{{ bos_token }}{% for m in messages %}"
        "<|start_header_id|>{{ m['role'] }}<|end_header_id|>\n\n"
        "{{ m['content'] }}<|eot_id|>{% endfor %}"
        "{% if add_generation_prompt %}"
        "<|start_header_id|>assistant<|end_header_id|>\n\n{% endif %}")
    (tmp_path / 'tokenizer_config.json').write_text(json.dumps({
        'chat_template': tpl, 'bos_token': '<BOS>',
        'eos_token': '<EOS>'}))

    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    eng = engine_lib.InferenceEngine(model, params, num_slots=1,
                                     max_seq_len=64,
                                     prefill_buckets=[16])
    srv = server.InferenceServer(
        eng,
        chat_template=tokenizer_lib.load_chat_template(str(tmp_path)),
        special_tokens=tokenizer_lib.special_token_strings(
            str(tmp_path)))
    out = srv._apply_chat_template([
        {'role': 'system', 'content': 'be brief'},
        {'role': 'user', 'content': 'hi'}])
    assert out == ('<BOS><|start_header_id|>system<|end_header_id|>'
                   '\n\nbe brief<|eot_id|>'
                   '<|start_header_id|>user<|end_header_id|>\n\nhi'
                   '<|eot_id|>'
                   '<|start_header_id|>assistant<|end_header_id|>\n\n')
    # Broken template -> generic fallback, not a crash.
    srv2 = server.InferenceServer(
        eng, chat_template="{{ raise_exception('nope') }}")
    out2 = srv2._apply_chat_template([{'role': 'user', 'content': 'x'}])
    assert out2 == '<|user|>\nx\n<|assistant|>\n'
    # Multi-template (list) format: 'default' wins.
    (tmp_path / 'tokenizer_config.json').write_text(json.dumps({
        'chat_template': [
            {'name': 'tool_use', 'template': 'T'},
            {'name': 'default', 'template': 'D'}]}))
    assert tokenizer_lib.load_chat_template(str(tmp_path)) == 'D'


@pytest.mark.integration
def test_completions_echo_and_unsupported_params(small_model):
    """echo=true prepends the prompt; suffix/best_of are rejected with
    clear 400s instead of being silently ignored."""
    import socket

    from aiohttp import web

    from skypilot_tpu.infer import server as server_lib

    model, params = small_model
    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16])
    eng.start()
    try:
        srv = server_lib.InferenceServer(eng)
        base = _boot_http_server(srv)
        r = requests.post(f'{base}/v1/completions', json={
            'prompt': 'hi', 'max_tokens': 2, 'echo': True}, timeout=120)
        assert r.status_code == 200
        # Literal-echo semantics: the response STARTS with exactly the
        # string the client sent, not a tokenize/detokenize round-trip.
        assert r.json()['choices'][0]['text'].startswith('hi')
        r = requests.post(f'{base}/v1/completions', json={
            'prompt': 'hi', 'max_tokens': 2, 'suffix': '!'}, timeout=60)
        assert r.status_code == 400 and 'suffix' in r.json()['error']
        r = requests.post(f'{base}/v1/completions', json={
            'prompt': 'hi', 'max_tokens': 2, 'best_of': 5}, timeout=60)
        assert r.status_code == 400 and 'best_of' in r.json()['error']
        r = requests.post(f'{base}/v1/completions', json={
            'prompt': 'hi', 'max_tokens': 2, 'echo': True,
            'logprobs': 0}, timeout=60)
        assert r.status_code == 400 and 'logprobs' in r.json()['error']
    finally:
        eng.stop()
