"""Multi-slice (DCN) mesh and megascale env tests on the 8-device CPU
mesh: 2 emulated slices of 4 devices each."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.runtime import gang

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


class TestHybridMesh:
    def test_axis_sizes_multiply(self):
        mesh = mesh_lib.build_hybrid_mesh(
            mesh_lib.MeshSpec(fsdp=2, tp=2), mesh_lib.MeshSpec(dp=2),
            num_slices=2)
        assert mesh.shape['dp'] == 2
        assert mesh.shape['fsdp'] == 2
        assert mesh.shape['tp'] == 2

    def test_dp_crosses_slices(self):
        """The dcn axis (dp) must span the two emulated slice chunks;
        the ici axes (fsdp, tp) must stay within one chunk."""
        devices = jax.devices()[:8]
        slice_of = {id(d): i // 4 for i, d in enumerate(devices)}
        mesh = mesh_lib.build_hybrid_mesh(
            mesh_lib.MeshSpec(fsdp=2, tp=2), mesh_lib.MeshSpec(dp=2),
            devices=devices, num_slices=2)
        arr = mesh.devices  # [pp, dp, cp, fsdp, ep, tp]
        # Fix all ici coords; walking dp must change slice.
        for f in range(2):
            for t in range(2):
                slices = {slice_of[id(arr[0, dpi, 0, f, 0, t])]
                          for dpi in range(2)}
                assert slices == {0, 1}, 'dp does not cross slices'
        # Fix dp; walking fsdp/tp must stay within one slice.
        for dpi in range(2):
            slices = {slice_of[id(arr[0, dpi, 0, f, 0, t])]
                      for f in range(2) for t in range(2)}
            assert len(slices) == 1, 'ici axes leak across slices'

    def test_pp_dcn_axis(self):
        mesh = mesh_lib.build_hybrid_mesh(
            mesh_lib.MeshSpec(tp=4), mesh_lib.MeshSpec(pp=2),
            num_slices=2)
        assert mesh.shape['pp'] == 2
        assert mesh.shape['tp'] == 4

    def test_wrong_slice_count_raises(self):
        with pytest.raises(ValueError):
            mesh_lib.build_hybrid_mesh(
                mesh_lib.MeshSpec(tp=2), mesh_lib.MeshSpec(dp=4),
                num_slices=2)

    def test_train_step_on_hybrid_mesh(self):
        """A full sharded train step where dp crosses the slice
        boundary — the dry-run proof that multi-slice sharding compiles
        and executes."""
        from skypilot_tpu.models import llama
        from skypilot_tpu.train import trainer

        mesh = mesh_lib.build_hybrid_mesh(
            mesh_lib.MeshSpec(fsdp=2, tp=2), mesh_lib.MeshSpec(dp=2),
            num_slices=2)
        cfg = llama.CONFIGS['debug']
        model = llama.LlamaModel(cfg)
        tcfg = trainer.TrainerConfig(warmup_steps=1, total_steps=4)
        tx = trainer.make_optimizer(tcfg)
        sample = jnp.zeros((4, 64), jnp.int32)
        state, _ = trainer.create_sharded_state(model, tx, mesh, sample,
                                                jax.random.PRNGKey(0))
        step = trainer.make_train_step(model, tx, mesh, donate=False)
        rng = np.random.default_rng(0)
        data = {'tokens': jnp.array(rng.integers(0, cfg.vocab_size,
                                                 (4, 64)), jnp.int32),
                'targets': jnp.array(rng.integers(0, cfg.vocab_size,
                                                  (4, 64)), jnp.int32)}
        state, metrics = step(state, data)
        assert np.isfinite(float(metrics['loss']))


class TestMegascaleEnv:
    def test_multislice_env_vars(self):
        env = gang.multislice_env_vars(slice_id=1, num_slices=2,
                                       coordinator_ip='10.0.0.1')
        assert env['MEGASCALE_COORDINATOR_ADDRESS'] == '10.0.0.1:8080'
        assert env['MEGASCALE_NUM_SLICES'] == '2'
        assert env['MEGASCALE_SLICE_ID'] == '1'

    def test_job_env_with_slices(self):
        ips = [f'10.0.0.{i}' for i in range(4)]
        env = gang.job_env_vars(job_id=1, rank=3, ips=ips,
                                cluster_name='c', num_slices=2)
        assert env['MEGASCALE_SLICE_ID'] == '1'  # rank 3 of 2x2
        assert env['MEGASCALE_NUM_SLICES'] == '2'
        assert env['JAX_PROCESS_ID'] == '3'

    def test_job_env_single_slice_no_megascale(self):
        env = gang.job_env_vars(job_id=1, rank=0,
                                ips=['10.0.0.1', '10.0.0.2'],
                                cluster_name='c')
        assert 'MEGASCALE_NUM_SLICES' not in env

    def test_bad_slice_division_raises(self):
        with pytest.raises(ValueError):
            gang.job_env_vars(job_id=1, rank=0,
                              ips=['a', 'b', 'c'], cluster_name='c',
                              num_slices=2)
