"""Usage telemetry + dashboard tests."""
import json
import threading
import time

import pytest
import requests

from skypilot_tpu import state
from skypilot_tpu.usage import usage_lib


@pytest.fixture()
def usage_env(tmp_state_dir, monkeypatch):
    monkeypatch.setenv('SKYT_USAGE_COLLECTION', '1')
    yield tmp_state_dir


def test_entrypoint_disabled_by_default(tmp_state_dir, monkeypatch):
    monkeypatch.delenv('SKYT_USAGE_COLLECTION', raising=False)

    @usage_lib.entrypoint
    def my_api():
        return 42

    assert my_api() == 42
    import os
    assert not os.path.exists(usage_lib._spool_path())  # pylint: disable=protected-access


def test_entrypoint_records_success_and_failure(usage_env):
    @usage_lib.entrypoint
    def good():
        usage_lib.messages.annotate(foo='bar')
        return 'ok'

    @usage_lib.entrypoint('named_api')
    def bad():
        raise ValueError('boom')

    assert good() == 'ok'
    with pytest.raises(ValueError):
        bad()

    with open(usage_lib._spool_path(), encoding='utf-8') as f:  # pylint: disable=protected-access
        records = [json.loads(l) for l in f]
    assert len(records) == 2
    ok_rec = records[0]
    assert ok_rec['entrypoint'] == 'good'
    assert ok_rec['exception'] is None
    assert ok_rec['duration_s'] >= 0
    assert ok_rec['foo'] == 'bar'
    bad_rec = records[1]
    assert bad_rec['entrypoint'] == 'named_api'
    assert bad_rec['exception'].startswith('ValueError')


def test_nested_entrypoints_report_once(usage_env):
    @usage_lib.entrypoint
    def inner():
        return 1

    @usage_lib.entrypoint
    def outer():
        return inner() + 1

    assert outer() == 2
    with open(usage_lib._spool_path(), encoding='utf-8') as f:  # pylint: disable=protected-access
        records = [json.loads(l) for l in f]
    assert [r['entrypoint'] for r in records] == ['outer']


@pytest.mark.integration
def test_dashboard_serves_state(tmp_state_dir):
    from aiohttp import web

    from skypilot_tpu import dashboard as dashboard_lib

    state.reset_db_for_testing()
    import socket
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]

    th = threading.Thread(
        target=lambda: web.run_app(dashboard_lib.make_app(), port=port,
                                   print=None, handle_signals=False),
        daemon=True)
    th.start()
    base = f'http://127.0.0.1:{port}'
    deadline = time.time() + 20
    resp = None
    while time.time() < deadline:
        try:
            resp = requests.get(base + '/', timeout=2)
            break
        except requests.RequestException:
            time.sleep(0.2)
    assert resp is not None and resp.status_code == 200
    assert 'skypilot-tpu' in resp.text
    api = requests.get(base + '/api/state', timeout=5).json()
    assert set(api) == {'clusters', 'jobs', 'services'}
