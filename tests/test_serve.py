"""Serve layer tests: policies + autoscaler offline; full service
lifecycle (up → ready → proxy → replica recovery → update → down) on the
local provider with real controller/LB/replica processes.

Reference test strategy: sky tests/skyserve/ (tiny HTTP servers per
scenario) + load_balancer/test_round_robin.py (SURVEY.md §4.5).
"""
import os
import time

import pytest
import requests

import skypilot_tpu as sky
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import state
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve import load_balancing_policies as lb_policies
from skypilot_tpu.serve import replica_managers
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve import service_spec as spec_lib

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy

REPLICA_SERVER = (
    "python -c \""
    "import http.server, os, json;\n"
    "me = os.environ.get('SKYT_NODE_RANK', '?');\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    def do_GET(self):\n"
    "        self.send_response(200); self.end_headers();\n"
    "        self.wfile.write(('hello-from-' + "
    "os.environ['SKYT_REPLICA_PORT']).encode())\n"
    "    def do_POST(self):\n"
    "        self.do_GET()\n"
    "    def log_message(self, *a):\n"
    "        pass\n"
    "http.server.HTTPServer(('127.0.0.1', "
    "int(os.environ['SKYT_REPLICA_PORT'])), H).serve_forever()\"")


# ------------------------------------------------------------ unit: policy
def test_round_robin_policy():
    p = lb_policies.RoundRobinPolicy()
    assert p.select_replica() is None
    p.set_ready_replicas(['a', 'b', 'c'])
    picks = [p.select_replica() for _ in range(6)]
    assert sorted(picks[:3]) == ['a', 'b', 'c']
    assert picks[:3] == picks[3:]  # cycles deterministically


def test_least_connections_policy():
    p = lb_policies.LeastConnectionsPolicy()
    p.set_ready_replicas(['a', 'b'])
    r1 = p.select_replica()
    r2 = p.select_replica()
    assert {r1, r2} == {'a', 'b'}  # spreads across both
    p.on_request_done(r1)
    assert p.select_replica() == r1  # freed one is least-loaded


# -------------------------------------------------------- unit: autoscaler
def _spec(**kw):
    base = dict(readiness_path='/', min_replicas=1, max_replicas=4,
                target_qps_per_replica=1.0, upscale_delay_seconds=0.2,
                downscale_delay_seconds=0.2)
    base.update(kw)
    return spec_lib.ServiceSpec(**base)


def test_autoscaler_upscale_after_delay():
    a = autoscalers.RequestRateAutoscaler(_spec())
    now = time.time()
    # 120 requests in the window => qps 2 => target 2 replicas.
    a.collect_request_timestamps([now] * 120)
    d = a.evaluate_scaling(num_ready=1)
    assert d.target_num_replicas == 1  # delay not yet met
    time.sleep(0.25)
    d = a.evaluate_scaling(num_ready=1)
    assert d.target_num_replicas == 2


def test_autoscaler_downscale_after_delay():
    a = autoscalers.RequestRateAutoscaler(_spec())
    a.target_num_replicas = 3
    d = a.evaluate_scaling(num_ready=3)
    assert d.target_num_replicas == 3
    time.sleep(0.25)
    d = a.evaluate_scaling(num_ready=3)
    assert d.target_num_replicas == 1  # no traffic -> min


def test_autoscaler_fixed_when_not_autoscaling():
    spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=2)
    a = autoscalers.RequestRateAutoscaler(spec)
    a.collect_request_timestamps([time.time()] * 1000)
    time.sleep(0.05)
    assert a.evaluate_scaling(2).target_num_replicas == 2


# ------------------------------------------------- integration: lifecycle
@pytest.fixture()
def serve_env(tmp_path, tmp_state_dir, monkeypatch):
    monkeypatch.setenv('SKYT_LOCAL_ROOT', str(tmp_path / 'local'))
    monkeypatch.setenv('SKYT_DEFAULT_STORE', 'local')
    monkeypatch.setenv('SKYT_SERVE_CONTROLLER_INTERVAL', '0.3')
    monkeypatch.setenv('SKYT_SERVE_LB_SYNC_INTERVAL', '0.3')
    state.reset_db_for_testing()
    serve_state.reset_db_for_testing()
    yield
    for svc in serve_state.get_services():
        try:
            serve_core.down(svc['name'], purge=True)
        except Exception:  # pylint: disable=broad-except
            pass
    from skypilot_tpu import core
    for rec in state.get_clusters():
        try:
            core.down(rec['name'], purge=True)
        except Exception:  # pylint: disable=broad-except
            pass
    state.reset_db_for_testing()
    serve_state.reset_db_for_testing()


def _service_task(name='svc', min_replicas=2):
    t = sky.Task(name=name, run=REPLICA_SERVER)
    t.set_resources(resources_lib.Resources(cloud='local'))
    t.service = spec_lib.ServiceSpec(
        readiness_path='/', min_replicas=min_replicas,
        initial_delay_seconds=30, probe_timeout_seconds=2)
    return t


def _wait_ready(name, want_ready, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        svcs = serve_core.status([name])
        if svcs:
            ready = [r for r in svcs[0]['replicas']
                     if r['status'] is serve_state.ReplicaStatus.READY]
            if len(ready) >= want_ready:
                return svcs[0]
        time.sleep(0.5)
    pytest.fail(f'{name}: {want_ready} replicas not READY in {timeout}s: '
                f'{serve_core.status([name])}')


def test_replica_manager_recovers_orphans(serve_env):
    """Controller killed mid-launch: the persisted PROVISIONING row has
    no cluster. A fresh manager (restart) must tear the orphan down so
    reconcile() can relaunch to target
    (reference: sky/serve/replica_managers.py:940-1019 supervision)."""
    from skypilot_tpu.serve import replica_managers

    spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=1)
    serve_state.add_service('osvc', spec, '/tmp/nonexistent.yaml', 1, 2)
    # Simulate the dead controller's persisted launch intent.
    orphan = replica_managers.ReplicaInfo(
        replica_id=1, cluster_name='osvc-1', version=1,
        status=serve_state.ReplicaStatus.PROVISIONING)
    serve_state.upsert_replica('osvc', 1, orphan)

    mgr = replica_managers.ReplicaManager('osvc', spec,
                                          '/tmp/nonexistent.yaml')
    deadline = time.time() + 10
    while time.time() < deadline and 1 in mgr.replicas:
        time.sleep(0.1)
    assert 1 not in mgr.replicas, 'orphan not reconciled'
    assert all(r.replica_id != 1
               for r in serve_state.get_replicas('osvc'))


def test_replica_manager_keeps_live_cluster_on_restart(serve_env):
    """Mid-launch rows whose cluster DID come up are adopted as
    STARTING, not torn down."""
    import skypilot_tpu as sky
    from skypilot_tpu import execution
    from skypilot_tpu.serve import replica_managers

    t = sky.Task(name='osvc2-1', run='true')
    t.set_resources(resources_lib.Resources(cloud='local'))
    execution.launch(t, cluster_name='osvc2-1', detach_run=True,
                     stream_logs=False)

    spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=1)
    serve_state.add_service('osvc2', spec, '/tmp/nonexistent.yaml', 3, 4)
    row = replica_managers.ReplicaInfo(
        replica_id=1, cluster_name='osvc2-1', version=1,
        status=serve_state.ReplicaStatus.PROVISIONING)
    serve_state.upsert_replica('osvc2', 1, row)

    mgr = replica_managers.ReplicaManager('osvc2', spec,
                                          '/tmp/nonexistent.yaml')
    assert 1 in mgr.replicas
    assert mgr.replicas[1].status is serve_state.ReplicaStatus.STARTING
    assert mgr.replicas[1].endpoint is not None


def test_failed_add_service_releases_write_lock(serve_env):
    """A duplicate add_service (failed INSERT) must roll back its
    implicit transaction: leaving it open pins the write lock, and every
    other process's serve.db writes then die with 'database is locked'
    (found live: duplicate `serve up` wedged the controller's
    terminate)."""
    import sqlite3

    from skypilot_tpu import state as state_lib

    spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=1)
    assert serve_state.add_service('locksvc', spec, '/t.yaml', 1, 2)
    assert not serve_state.add_service('locksvc', spec, '/t.yaml', 3, 4)
    # A second connection stands in for the controller process: its
    # write must succeed immediately, not wait on our busy timeout.
    path = os.path.join(state_lib.state_dir(), 'serve.db')
    conn = sqlite3.connect(path, timeout=2)
    conn.execute("UPDATE services SET status='READY' WHERE name='locksvc'")
    conn.commit()
    conn.close()


def test_controller_auth_rejects_unauthenticated(serve_env):
    """Admin endpoints require the per-service bearer token minted at
    add_service: no token / wrong token => 401 before the handler runs;
    the right token passes (VERDICT r4 weak #3 — the reference gets
    this property from SSH-tunneled codegen instead)."""
    import asyncio

    import aiohttp
    from aiohttp import web

    from skypilot_tpu.serve import controller as controller_lib

    spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=1)
    assert serve_state.add_service('asvc', spec, '/tmp/nonexistent.yaml',
                                   1, 2)
    svc = serve_state.get_service('asvc')
    token = svc['auth_token']
    assert token, 'token must be minted at add_service'

    ctrl = controller_lib.SkyServeController(
        'asvc', spec, '/tmp/nonexistent.yaml', svc['controller_port'])

    async def _run():
        runner = web.AppRunner(ctrl.make_app(token))
        await runner.setup()
        site = web.TCPSite(runner, '127.0.0.1', 0)
        await site.start()
        base = f'http://{runner.addresses[0][0]}:{runner.addresses[0][1]}'
        res = {}
        async with aiohttp.ClientSession() as sess:
            for ep in ('/controller/update_service',
                       '/controller/terminate'):
                async with sess.post(base + ep, json={}) as r:
                    res[ep] = r.status
            async with sess.post(
                    base + '/controller/terminate', json={},
                    headers={'Authorization': 'Bearer wrong'}) as r:
                res['bad-token'] = r.status
            async with sess.get(base + '/controller/status') as r:
                res['status-noauth'] = r.status
            async with sess.get(
                    base + '/controller/status',
                    headers={'Authorization': f'Bearer {token}'}) as r:
                res['status-auth'] = r.status
        await runner.cleanup()
        return res

    res = asyncio.run(_run())
    assert res['/controller/update_service'] == 401
    assert res['/controller/terminate'] == 401
    assert res['bad-token'] == 401
    assert res['status-noauth'] == 401
    assert res['status-auth'] == 200


@pytest.mark.integration
def test_serve_cluster_controller(serve_env, tmp_path, monkeypatch):
    """Controller+LB run as a job on the serve controller cluster (the
    reference's sky-serve-controller VM): no client-side controller
    pid; service serves and tears down normally."""
    cfg = tmp_path / 'skyt_config.yaml'
    cfg.write_text(
        'serve:\n  controller:\n    resources:\n      cloud: local\n')
    monkeypatch.setenv('SKYT_CONFIG', str(cfg))
    from skypilot_tpu import skyt_config
    skyt_config.reload_for_testing()
    try:
        name, endpoint = serve_core.up(_service_task(min_replicas=1),
                                       'csvc', controller='cluster')
        svc = serve_state.get_service('csvc')
        assert not svc.get('controller_pid')
        _wait_ready(name, 1)
        resp = requests.get(endpoint, timeout=5)
        assert resp.status_code == 200
        assert resp.text.startswith('hello-from-')
        assert state.get_cluster('skyt-serve-controller') is not None
        serve_core.down(name)
        deadline = time.time() + 60
        while time.time() < deadline and serve_state.get_service(name):
            time.sleep(0.5)
        assert serve_state.get_service(name) is None
    finally:
        skyt_config.reload_for_testing()


@pytest.mark.integration
def test_serve_multihost_replica(serve_env):
    """A replica spanning MULTIPLE hosts (the reference's
    TP-across-a-replica-cluster shape, llm/vllm/serve.yaml): the task
    gang-runs on every host, only rank 0 binds SKYT_REPLICA_PORT (the
    multihost engine's contract), and the replica endpoint routes to
    the head — service goes READY and proxies."""
    run = (
        "if [ \"$SKYT_NODE_RANK\" = 0 ]; then " + REPLICA_SERVER +
        "; else sleep 3600; fi")
    t = sky.Task(name='mh', run=run, num_nodes=2)
    t.set_resources(resources_lib.Resources(cloud='local'))
    t.service = spec_lib.ServiceSpec(
        readiness_path='/', min_replicas=1,
        initial_delay_seconds=60, probe_timeout_seconds=2)
    name, endpoint = serve_core.up(t, 'mhsvc')
    svc = _wait_ready(name, 1)
    replica = svc['replicas'][0]
    handle = state.get_cluster(replica['cluster_name'])['handle']
    assert handle.num_hosts == 2          # really a 2-host replica
    resp = requests.get(endpoint + '/', timeout=10)
    assert resp.status_code == 200
    assert resp.text.startswith('hello-from-')
    serve_core.down(name)


@pytest.mark.integration
def test_serve_lifecycle(serve_env):
    name, endpoint = serve_core.up(_service_task(min_replicas=2), 'svc')
    svc = _wait_ready(name, 2)
    assert svc['status'] is serve_state.ServiceStatus.READY

    # Proxy round-robins across both replicas (reference:
    # tests/skyserve/load_balancer/test_round_robin.py).
    # Poll until both replicas answer: the LB's replica-set sync can lag
    # READY status by one sync interval (a fixed request count flakes on
    # slow machines).
    seen = set()
    deadline = time.time() + 30
    while time.time() < deadline and len(seen) < 2:
        resp = requests.get(endpoint + '/', timeout=10)
        assert resp.status_code == 200
        assert resp.text.startswith('hello-from-')
        seen.add(resp.text)
        time.sleep(0.1)
    assert len(seen) == 2

    # Replica failure -> detected -> replaced (preemption semantics).
    from skypilot_tpu import core
    victim = svc['replicas'][0]['cluster_name']
    core.down(victim, purge=True)
    deadline = time.time() + 60
    while time.time() < deadline:
        svcs = serve_core.status([name])[0]
        clusters = {r['cluster_name'] for r in svcs['replicas']
                    if r['status'] is serve_state.ReplicaStatus.READY}
        if victim not in clusters and len(clusters) >= 2:
            break
        time.sleep(0.5)
    else:
        pytest.fail(f'replica not replaced: {serve_core.status([name])}')

    # Rolling update bumps the version; replicas roll to it.
    v = serve_core.update(_service_task(min_replicas=2), name)
    assert v == 2
    deadline = time.time() + 90
    while time.time() < deadline:
        svcs = serve_core.status([name])[0]
        ready = [r for r in svcs['replicas']
                 if r['status'] is serve_state.ReplicaStatus.READY]
        if ready and all(r['version'] == 2 for r in ready) and \
                len(ready) >= 2:
            break
        time.sleep(0.5)
    else:
        pytest.fail(f'rolling update stuck: {serve_core.status([name])}')

    # Down removes service + all replica clusters.
    serve_core.down(name)
    assert serve_core.status([name]) == []
    assert state.get_clusters() == []


def test_scale_to_zero_and_wake():
    """min_replicas: 0 — sustained idle scales the service to nothing;
    the first request wakes it immediately (no upscale delay: with
    zero replicas the delay would just be guaranteed 503s)."""
    import time as time_lib

    from skypilot_tpu.serve import autoscalers, service_spec

    spec = service_spec.ServiceSpec(
        readiness_path='/health', min_replicas=0, max_replicas=2,
        target_qps_per_replica=1.0, upscale_delay_seconds=60.0,
        downscale_delay_seconds=0.0)
    a = autoscalers.RequestRateAutoscaler(spec)
    assert a.target_num_replicas == 0
    # Idle: stays at zero.
    d = a.evaluate_scaling(num_ready=0)
    assert d.target_num_replicas == 0
    # A request arrives -> wake instantly despite the 60s upscale delay.
    a.collect_request_timestamps([time_lib.time()])
    d = a.evaluate_scaling(num_ready=0)
    assert d.target_num_replicas >= 1
    assert 'wake from zero' in d.reason
    # Traffic stops -> back to zero after the (zero) downscale delay.
    a.request_timestamps.clear()
    d = a.evaluate_scaling(num_ready=1)
    assert d.target_num_replicas == 0


def test_replica_stats_scrape(tmp_state_dir):
    """The prober scrapes /stats off a READY inference replica and
    `serve status` surfaces it; a replica without /stats yields None."""
    import http.server
    import json as json_lib
    import threading

    stats_payload = {'ttft_ms': {'p50': 42.0, 'p90': 50.0, 'p99': 60.0,
                                 'count': 7},
                     'steady_decode_tok_per_sec': 900.0,
                     'active_slots': 2, 'num_slots': 8, 'waiting': 0,
                     'irrelevant': 'dropped'}

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.end_headers()
            if self.path == '/stats':
                self.wfile.write(json_lib.dumps(stats_payload).encode())
            else:
                self.wfile.write(b'ok')

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(('127.0.0.1', 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        spec = spec_lib.ServiceSpec(readiness_path='/health')
        mgr = replica_managers.ReplicaManager('stats-svc', spec,
                                              task_yaml='/dev/null')
        info = replica_managers.ReplicaInfo(
            replica_id=1, cluster_name='nonexistent-c', version=1,
            status=serve_state.ReplicaStatus.READY,
            endpoint=f'http://127.0.0.1:{srv.server_port}')
        got = mgr._fetch_stats(info)
        assert got == {k: v for k, v in stats_payload.items()
                       if k != 'irrelevant'}
    finally:
        srv.shutdown()
    # No server at all -> None, not an exception.
    info.endpoint = 'http://127.0.0.1:1'
    assert mgr._fetch_stats(info) is None


def test_cold_start_attribution_and_prewarm(tmp_state_dir, monkeypatch):
    """First-READY fires cold-start attribution exactly once per
    replica: kind wake_from_zero when no other replica was READY,
    scale_up otherwise, seconds = launch -> first READY. With
    SKYT_SERVE_PREWARM=1 the new replica is asked to pre-warm its KV
    from the already-READY peers (daemon push, injectable transport);
    off by default."""
    import threading

    class _Telemetry:
        def __init__(self):
            self.cold = []

        def note_cold_start(self, kind, seconds):
            self.cold.append((kind, seconds))

    tel = _Telemetry()
    spec = spec_lib.ServiceSpec(readiness_path='/health')
    mgr = replica_managers.ReplicaManager('cold-svc', spec,
                                          task_yaml='/dev/null',
                                          telemetry=tel)
    prewarms = []
    done = threading.Event()

    def fake_prewarm(info, peers):
        prewarms.append((info.replica_id, list(peers)))
        done.set()
        return True, None

    mgr._prewarm_fn = fake_prewarm  # pylint: disable=protected-access
    now = time.time()

    def _ready(rid):
        info = replica_managers.ReplicaInfo(
            replica_id=rid, cluster_name=f'c-{rid}', version=1,
            status=serve_state.ReplicaStatus.READY,
            endpoint=f'http://127.0.0.1:{9100 + rid}',
            launched_at=now - 5.0, first_ready_at=now)
        mgr.replicas[rid] = info
        return info

    # Fleet was scaled to zero: the first arrival is the wake.
    monkeypatch.delenv('SKYT_SERVE_PREWARM', raising=False)
    mgr._note_first_ready(_ready(1))  # pylint: disable=protected-access
    assert tel.cold == [('wake_from_zero', pytest.approx(5.0, abs=1.0))]
    assert not prewarms                # prewarm is opt-in
    # A second replica joins a serving fleet: scale_up.
    mgr._note_first_ready(_ready(2))  # pylint: disable=protected-access
    assert tel.cold[-1][0] == 'scale_up'
    # Opt in: the NEW replica pulls from the already-READY peers.
    monkeypatch.setenv('SKYT_SERVE_PREWARM', '1')
    mgr._note_first_ready(_ready(3))  # pylint: disable=protected-access
    assert done.wait(10)
    assert prewarms == [(3, ['http://127.0.0.1:9101',
                             'http://127.0.0.1:9102'])]
    assert tel.cold[-1][0] == 'scale_up'
    # The fleet capacity report attributes the burned chip-seconds.
    from skypilot_tpu.serve import fleet as fleet_lib
    from skypilot_tpu.utils import metrics as metrics_lib
    monkeypatch.setenv('SKYT_FLEET_CHIPS_PER_REPLICA', '4')
    ft = fleet_lib.FleetTelemetry(
        'cold-svc', metrics_registry=metrics_lib.MetricsRegistry())
    for kind, seconds in tel.cold:
        ft.note_cold_start(kind, seconds)
    rep = ft.capacity_report()
    assert rep['cold_start']['count'] == {'wake_from_zero': 1,
                                          'scale_up': 2}
    assert rep['cold_start']['chip_seconds'] == \
        pytest.approx(3 * 5.0 * 4, rel=0.3)
