"""Prefix-affinity consistent-hash ring + policy, in isolation
(docs/serving.md "N-active front door", docs/robustness.md "Front
door"): deterministic placement, bounded key movement on single-node
join/leave, occupancy weighting, sticky-session semantics, and the
peer demand-rate helper.
"""
import json
import math

import pytest

from skypilot_tpu.serve import load_balancing_policies as lbp

KEYS = [f'key-{i}' for i in range(600)]
NODES3 = {'http://r1': 1.0, 'http://r2': 1.0, 'http://r3': 1.0}


def _owners(ring):
    return {k: ring.owner(k) for k in KEYS}


# ============================================================== ring
def test_ring_deterministic_placement_across_instances():
    """Same (nodes, weights) => same owner for every key, from any
    ring instance — the property that lets N active LBs route a key
    identically with zero coordination."""
    a, b = lbp.ConsistentHashRing(), lbp.ConsistentHashRing()
    a.set_nodes(NODES3)
    b.set_nodes(dict(reversed(list(NODES3.items()))))  # order-free
    assert _owners(a) == _owners(b)
    # And stable across repeated queries.
    assert _owners(a) == _owners(a)
    # All nodes own a non-trivial share under equal weights.
    counts = {}
    for owner in _owners(a).values():
        counts[owner] = counts.get(owner, 0) + 1
    assert set(counts) == set(NODES3)
    assert min(counts.values()) > len(KEYS) / (len(NODES3) * 2)


def test_ring_bounded_movement_on_leave():
    """Single-node leave: ONLY keys the departed node owned move
    (rendezvous scores of every other node are untouched), and the
    moved count is within the ceil(K/N) fair share."""
    ring = lbp.ConsistentHashRing()
    ring.set_nodes(NODES3)
    before = _owners(ring)
    ring.set_nodes({n: w for n, w in NODES3.items()
                    if n != 'http://r3'})
    after = _owners(ring)
    moved = [k for k in KEYS if before[k] != after[k]]
    assert moved, 'departed node owned nothing?'
    assert all(before[k] == 'http://r3' for k in moved), \
        'a key not owned by the departed node changed owner'
    assert len(moved) <= math.ceil(len(KEYS) / len(NODES3))
    # Rejoin restores the EXACT original placement (deterministic).
    ring.set_nodes(NODES3)
    assert _owners(ring) == before


def test_ring_bounded_movement_on_join():
    """Single-node join: only keys the new node wins move."""
    ring = lbp.ConsistentHashRing()
    ring.set_nodes(NODES3)
    before = _owners(ring)
    joined = dict(NODES3, **{'http://r4': 1.0})
    ring.set_nodes(joined)
    after = _owners(ring)
    moved = [k for k in KEYS if before[k] != after[k]]
    assert moved, 'new node won nothing?'
    assert all(after[k] == 'http://r4' for k in moved), \
        'a key the new node did not win changed owner'
    assert len(moved) <= math.ceil(len(KEYS) / len(joined))


def test_ring_weights_shift_share_toward_warm_nodes():
    """Weight = occupancy signal: doubling one node's weight grows its
    key share, and the shift is incremental (keys that moved went TO
    the upweighted node — nobody else's keys reshuffled)."""
    ring = lbp.ConsistentHashRing()
    ring.set_nodes(NODES3)
    before = _owners(ring)
    share_before = sum(1 for o in before.values() if o == 'http://r1')
    ring.set_nodes(dict(NODES3, **{'http://r1': 2.0}))
    after = _owners(ring)
    share_after = sum(1 for o in after.values() if o == 'http://r1')
    assert share_after > share_before
    moved = [k for k in KEYS if before[k] != after[k]]
    assert all(after[k] == 'http://r1' for k in moved)


def test_ring_owner_exclude_walks_failover_order():
    ring = lbp.ConsistentHashRing()
    ring.set_nodes(NODES3)
    key = 'some-conversation'
    first = ring.owner(key)
    second = ring.owner(key, exclude={first})
    assert second is not None and second != first
    assert ring.ranked(key)[0] == first
    assert ring.ranked(key)[1] == second
    assert ring.owner(key, exclude=set(NODES3)) is None


# ============================================================ policy
def _policy(replicas=('http://r1', 'http://r2', 'http://r3')):
    pol = lbp.PrefixAffinityPolicy()
    pol.set_ready_replicas(list(replicas))
    return pol


def test_policy_keyed_requests_follow_the_ring():
    pol = _policy()
    for key in ('a', 'b', 'c', 'd'):
        want = pol.ring.owner(key)
        for _ in range(3):
            assert pol.select_replica(key=key) == want


def test_policy_session_stickiness_overrides_ring_churn():
    """A pinned session never re-hashes while its replica stays ready:
    not on weight updates, not on a JOIN that would re-home its key."""
    pol = _policy(('http://r1', 'http://r2'))
    picked = pol.select_replica(key='conv-1', session='sess-1')
    assert pol.peek_session('sess-1') == picked
    # Weight update (occupancy refresh) — pin holds.
    pol.set_weights({'http://r1': 0.9, 'http://r2': 0.1})
    assert pol.select_replica(key='conv-1', session='sess-1') == picked
    # Join a replica that may now win the key — pin still holds.
    pol.set_ready_replicas(['http://r1', 'http://r2', 'http://r3'])
    for _ in range(4):
        assert pol.select_replica(key='conv-1',
                                  session='sess-1') == picked


def test_policy_session_reroutes_once_when_replica_leaves():
    pol = _policy(('http://r1', 'http://r2'))
    picked = pol.select_replica(key='conv-2', session='sess-2')
    other = 'http://r1' if picked == 'http://r2' else 'http://r2'
    pol.set_ready_replicas([other])          # pinned replica retired
    assert pol.peek_session('sess-2') is None   # pin dropped
    repick = pol.select_replica(key='conv-2', session='sess-2')
    assert repick == other
    # ... and re-pins there.
    assert pol.peek_session('sess-2') == other
    # The old replica coming back does NOT steal the session.
    pol.set_ready_replicas(['http://r1', 'http://r2'])
    assert pol.select_replica(key='conv-2', session='sess-2') == other


def test_policy_exclusion_falls_through_and_repins():
    """The retry/breaker exclude set beats the pin (a dead replica
    must not blackhole its sessions); the session re-pins on the
    fallback target."""
    pol = _policy(('http://r1', 'http://r2'))
    picked = pol.select_replica(key='k', session='s')
    fallback = pol.select_replica(key='k', session='s',
                                  exclude={picked})
    assert fallback is not None and fallback != picked
    assert pol.peek_session('s') == fallback
    assert pol.select_replica(exclude={'http://r1', 'http://r2'},
                              key='k', session='s') is None


def test_policy_session_lru_bounded(monkeypatch):
    monkeypatch.setenv('SKYT_LB_RING_SESSIONS_MAX', '4')
    pol = _policy()
    for i in range(10):
        pol.select_replica(key=f'k{i}', session=f's{i}')
    assert pol.session_count() == 4
    assert pol.peek_session('s0') is None       # oldest evicted
    assert pol.peek_session('s9') is not None


def test_policy_keyless_traffic_spreads():
    pol = _policy()
    picks = {pol.select_replica() for _ in range(9)}
    assert len(picks) == 3                      # round-robins, no hot spot


def test_policy_weights_rebuild_ring_from_occupancy(monkeypatch):
    monkeypatch.setenv('SKYT_LB_RING_WEIGHT_OCCUPANCY', '1.0')
    pol = _policy(('http://r1', 'http://r2'))
    assert pol.ring.weights() == {'http://r1': 1.0, 'http://r2': 1.0}
    pol.set_weights({'http://r1': 0.5, 'http://r2': 2.5})  # clamped to 1
    assert pol.ring.weights() == {'http://r1': 1.5, 'http://r2': 2.0}


def test_base_policies_accept_affinity_kwargs():
    """The LB passes key/session to every policy — the non-affinity
    ones must ignore them, not crash."""
    for name in ('round_robin', 'least_connections'):
        pol = lbp.POLICIES[name]()
        pol.set_ready_replicas(['http://a'])
        assert pol.select_replica(key='k', session='s') == 'http://a'
        assert pol.peek_session('s') is None
        assert pol.uses_affinity is False
    assert lbp.POLICIES['prefix_affinity'].uses_affinity is True


# =================================================== LB-side helpers
def test_affinity_key_stable_across_turns_and_shared_prefix():
    """Chat bodies key on system prompt + FIRST user message: stable
    across later turns of one conversation, shared by conversations
    over the same opener, distinct across different openers."""
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.utils import metrics as metrics_lib
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:9', 0, policy='prefix_affinity',
        metrics_registry=metrics_lib.MetricsRegistry())

    def chat(*msgs):
        return json.dumps({'messages': [
            {'role': r, 'content': c} for r, c in msgs]}).encode()

    turn1 = chat(('system', 'You are helpful.'), ('user', 'hi'))
    turn3 = chat(('system', 'You are helpful.'), ('user', 'hi'),
                 ('assistant', 'hello!'), ('user', 'tell me more'))
    other = chat(('system', 'You are helpful.'), ('user', 'bye'))
    k1, k3, ko = (lb._affinity_key(b)  # pylint: disable=protected-access
                  for b in (turn1, turn3, other))
    assert k1 == k3                      # multi-turn: key never moves
    assert k1 != ko                      # different opener: new key
    # A system message INJECTED mid-conversation (tool/moderation
    # instructions at turn k) must not re-key the conversation: only
    # the leading system run + first user message are the prefix.
    injected = chat(('system', 'You are helpful.'), ('user', 'hi'),
                    ('assistant', 'hello!'),
                    ('system', 'tool result: 42'),
                    ('user', 'tell me more'))
    assert lb._affinity_key(injected) == k1  # pylint: disable=protected-access
    # Normalization: whitespace shape does not split a key.
    wobbly = chat(('system', ' You   are helpful. '), ('user', 'hi'))
    assert lb._affinity_key(wobbly) == k1  # pylint: disable=protected-access
    # Completion + token bodies key on the prompt prefix.
    assert lb._affinity_key(b'{"prompt": "Once upon"}')  # pylint: disable=protected-access
    assert lb._affinity_key(b'{"tokens": [1, 2, 3]}')  # pylint: disable=protected-access
    # Keyless shapes.
    for body in (b'', b'not json', b'[1,2]', b'{"max_tokens": 4}'):
        assert lb._affinity_key(body) is None  # pylint: disable=protected-access


def test_kv_peer_header_is_lb_internal():
    """X-KV-Peer is LB-internal routing state: a client-supplied value
    is stripped with the hop-by-hop set before proxying (under
    SKYT_KV_TIER=fleet the replica fetches from the named URL with its
    admin bearer token, so a forwarded header would be an SSRF +
    credential-leak vector), and the LB's own hint only ever names
    another member of the ready-replica ring."""
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.utils import metrics as metrics_lib
    assert 'x-kv-peer' in lb_lib._HOP_HEADERS  # pylint: disable=protected-access
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:9', 0, policy='prefix_affinity',
        metrics_registry=metrics_lib.MetricsRegistry())
    replicas = ['http://r1', 'http://r2', 'http://r3']
    lb.policy.set_ready_replicas(replicas)
    for chosen in replicas:
        hint = lb._kv_peer_hint('opener-key', chosen)  # pylint: disable=protected-access
        assert hint in replicas and hint != chosen
    # Keyless traffic gets no hint — and with the incoming header
    # stripped, the upstream request then carries no X-KV-Peer at all.
    assert lb._kv_peer_hint(None, 'http://r1') is None  # pylint: disable=protected-access
    # Non-affinity policies never hint.
    lb_rr = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:9', 0, policy='round_robin',
        metrics_registry=metrics_lib.MetricsRegistry())
    lb_rr.policy.set_ready_replicas(replicas)
    assert lb_rr._kv_peer_hint('opener-key', 'http://r1') is None  # pylint: disable=protected-access


def test_rate_by_class_windows_and_garbage():
    from skypilot_tpu.serve import qos as qos_lib
    now = 1000.0
    events = [(now - 1, 'interactive'), (now - 2, 'interactive'),
              (now - 3, 'batch'), (now - 100, 'interactive'),
              ('garbage', 'batch')]
    rates = qos_lib.rate_by_class(events, 10.0, now=now)
    assert rates['interactive'] == pytest.approx(0.2)
    assert rates['batch'] == pytest.approx(0.1)
    assert qos_lib.rate_by_class([], 10.0, now=now) == {}


def test_least_connections_uses_peer_inflight():
    """Cross-LB least-connections (ROADMAP item 2 leftover): the peer
    LBs' gossiped inflight slices add to the local count, so a replica
    saturated THROUGH another LB stops looking idle here."""
    pol = lbp.LeastConnectionsPolicy()
    pol.set_ready_replicas(['http://r1', 'http://r2'])
    # Locally idle everywhere; peers report r1 busy -> pick r2.
    pol.set_peer_inflight({'http://r1': 5.0})
    assert pol.select_replica() == 'http://r2'
    pol.on_request_done('http://r2')
    # Peer view refresh drops the old slice entirely (no accumulation).
    pol.set_peer_inflight({})
    picks = {pol.select_replica() for _ in range(2)}
    assert picks == {'http://r1', 'http://r2'}
    # Garbage-tolerant: negative counts clamp, unknown replicas are
    # inert, and the base policy ignores the hook entirely.
    pol.set_peer_inflight({'http://r9': -3})
    assert pol.select_replica() in ('http://r1', 'http://r2')
    lbp.RoundRobinPolicy().set_peer_inflight({'http://r1': 2})


def test_gossip_payload_carries_inflight():
    """The LB->LB payload includes this LB's per-replica inflight
    slice; _absorb_peer parses a peer's (garbage included) and the
    fresh-peer aggregate feeds the policy."""
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.utils import metrics as metrics_lib
    lb = lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:9', 0, policy='least_connections',
        metrics_registry=metrics_lib.MetricsRegistry())
    lb.peers = ['http://peer-a']
    lb.policy.set_ready_replicas(['http://r1', 'http://r2'])
    lb._m_inflight.labels(lb.lb_id, 'http://r1').inc()  # pylint: disable=protected-access
    lb._m_inflight.labels(lb.lb_id, 'http://r1').inc()  # pylint: disable=protected-access
    payload = lb._gossip_payload()  # pylint: disable=protected-access
    assert payload['inflight'] == {'http://r1': 2}
    # Round-trips through JSON (the wire format).
    assert json.loads(json.dumps(payload))['inflight'] == \
        {'http://r1': 2}
    pid = lb._absorb_peer({  # pylint: disable=protected-access
        'lb_id': 'lb-peer', 'url': 'http://peer-a',
        'state': {}, 'inflight': {'http://r2': 3, 'http://bad': 'x',
                                  'http://neg': -1}})
    assert pid == 'lb-peer'
    view = lb._peer_views[pid]  # pylint: disable=protected-access
    assert view.inflight == {'http://r2': 3.0, 'http://neg': 0.0}
    lb._refresh_peer_gauges()  # pylint: disable=protected-access
    # Peer slice reached the policy: r2 now looks loaded, r1 carries
    # only the LOCAL count (2) vs r2's peer count (3).
    assert lb.policy.select_replica() == 'http://r1'
