"""Multi-host serving engine: REAL multi-process lockstep on the CPU
backend.

Two processes join one jax.distributed runtime (1 CPU device each →
a tp=2 global mesh), run the paged engine in lockstep (primary owns
submissions; follower driven by tick broadcasts), and the primary's
tokens must equal a single-process tp=2 run of the same engine — the
same mesh partitioning, so the computation (and therefore every token)
is identical; only the process topology differs. This is the CPU
stand-in for a serving replica spanning a multi-host TPU slice
(reference: TP across a whole replica cluster, llm/vllm/serve.yaml
--tensor-parallel-size over $SKYPILOT_NUM_GPUS_PER_NODE).
"""
import jax
import pytest

from skypilot_tpu.infer import multihost

pytestmark = pytest.mark.heavy


@pytest.mark.integration
@pytest.mark.skipif(
    jax.__version__.startswith('0.4.'),
    reason='jax 0.4.x CPU backend cannot run cross-process '
           'computations (XlaRuntimeError "Multiprocess computations '
           'aren\'t implemented on the CPU backend"), so the 2-process '
           'half of this selftest can never lower — documented red '
           'since PR 1, now an explicit skip. Re-enable when the image '
           'ships jax>=0.5 or on real multi-host accelerators '
           '(tests_tpu/ covers the on-chip path).')
def test_two_process_lockstep_matches_single_process(tmp_path):
    # Reference: ONE process, 2 local devices, same tp=2 mesh.
    ref = multihost.run_selftest_gang(
        nprocs=1, devices_per_proc=2,
        out_path=str(tmp_path / 'single.json'), log_dir=str(tmp_path))
    # System under test: TWO processes, 1 device each, tp=2 global mesh.
    got = multihost.run_selftest_gang(
        nprocs=2, devices_per_proc=1,
        out_path=str(tmp_path / 'multi.json'), log_dir=str(tmp_path))

    assert got['greedy'] == ref['greedy'], (got, ref)
    assert 1 <= len(got['greedy']) <= 6
    # Sampled path: the device rng is keyed identically and the mesh
    # partitioning is identical, so tokens match too.
    assert got['sampled'] == ref['sampled'], (got, ref)
    assert 1 <= len(got['sampled']) <= 5
    # A cancel happened between the sampled run and this one (see
    # _selftest_worker): identical output proves the hosts stayed in
    # lockstep through the mid-stream slot release.
    assert got['after_cancel'] == ref['after_cancel'] == got['greedy']
