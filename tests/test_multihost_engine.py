"""Multi-host serving engine: REAL multi-process lockstep on the CPU
backend.

Two processes join one jax.distributed runtime (1 CPU device each →
a tp=2 global mesh), run the paged engine in lockstep (primary owns
submissions; follower driven by tick broadcasts), and the primary's
tokens must equal a single-process tp=2 run of the same engine — the
same mesh partitioning, so the computation (and therefore every token)
is identical; only the process topology differs. This is the CPU
stand-in for a serving replica spanning a multi-host TPU slice
(reference: TP across a whole replica cluster, llm/vllm/serve.yaml
--tensor-parallel-size over $SKYPILOT_NUM_GPUS_PER_NODE).
"""
import json
import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.heavy


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _run_selftest(tmp_path, tag, nprocs, devices_per_proc):
    """Launch the selftest gang; return rank 0's output dict."""
    out = tmp_path / f'{tag}.json'
    port = _free_port()
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['XLA_FLAGS'] = ('--xla_force_host_platform_device_count='
                        f'{devices_per_proc}')
    # A leftover gang env (from an outer test harness) must not leak
    # into the workers' argless-initialize path.
    for k in ('JAX_COORDINATOR_ADDRESS', 'JAX_NUM_PROCESSES',
              'JAX_PROCESS_ID'):
        env.pop(k, None)
    procs = []
    logs = []
    for rank in range(nprocs):
        log = open(tmp_path / f'{tag}-r{rank}.log', 'wb')
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.infer.multihost',
             '--selftest-port', str(port),
             '--selftest-nprocs', str(nprocs),
             '--selftest-rank', str(rank),
             '--selftest-out', str(out)],
            stdout=log, stderr=subprocess.STDOUT, env=env))
    try:
        for rank, p in enumerate(procs):
            rc = p.wait(timeout=900)
            assert rc == 0, (
                f'{tag} rank {rank} rc={rc}:\n'
                + (tmp_path / f'{tag}-r{rank}.log').read_text()[-3000:])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()
    with open(out, encoding='utf-8') as f:
        return json.load(f)


@pytest.mark.integration
def test_two_process_lockstep_matches_single_process(tmp_path):
    # Reference: ONE process, 2 local devices, same tp=2 mesh.
    ref = _run_selftest(tmp_path, 'single', nprocs=1, devices_per_proc=2)
    # System under test: TWO processes, 1 device each, tp=2 global mesh.
    got = _run_selftest(tmp_path, 'multi', nprocs=2, devices_per_proc=1)

    assert got['greedy'] == ref['greedy'], (got, ref)
    assert 1 <= len(got['greedy']) <= 6
    # Sampled path: the device rng is keyed identically and the mesh
    # partitioning is identical, so tokens match too.
    assert got['sampled'] == ref['sampled'], (got, ref)
    assert 1 <= len(got['sampled']) <= 5
    # A cancel happened between the sampled run and this one (see
    # _selftest_worker): identical output proves the hosts stayed in
    # lockstep through the mid-stream slot release.
    assert got['after_cancel'] == ref['after_cancel'] == got['greedy']
