"""State DB tests (mirrors reference tests/test_global_user_state.py)."""

from skypilot_tpu import state


class FakeHandle:
    def __init__(self, name):
        self.cluster_name = name
        self.num_hosts = 4
        self.launched_resources = None


class TestClusterState:
    def test_add_get_remove(self, tmp_state_dir):
        state.add_or_update_cluster('c1', FakeHandle('c1'),
                                    status=state.ClusterStatus.UP)
        rec = state.get_cluster('c1')
        assert rec['status'] == state.ClusterStatus.UP
        assert rec['handle'].cluster_name == 'c1'
        state.remove_cluster('c1')   # regression: deadlocked with Lock
        assert state.get_cluster('c1') is None

    def test_relaunch_updates_resources_and_intervals(self, tmp_state_dir):
        state.add_or_update_cluster('c1', FakeHandle('c1'),
                                    requested_resources='r1')
        state.add_or_update_cluster('c1', FakeHandle('c1'),
                                    requested_resources='r2')
        rec = state.get_cluster('c1')
        assert rec['requested_resources'] == 'r2'
        state.remove_cluster('c1')
        hist = state.get_cluster_history()
        (entry,) = [h for h in hist if h['name'] == 'c1']
        # exactly one closed interval despite the double launch
        assert len(entry['usage_intervals']) == 1
        assert entry['usage_intervals'][0][1] is not None

    def test_status_update(self, tmp_state_dir):
        state.add_or_update_cluster('c2', FakeHandle('c2'))
        state.update_cluster_status('c2', state.ClusterStatus.STOPPED)
        assert state.get_cluster('c2')['status'] == \
            state.ClusterStatus.STOPPED

    def test_autostop(self, tmp_state_dir):
        state.add_or_update_cluster('c3', FakeHandle('c3'))
        state.set_cluster_autostop('c3', 30, to_down=True)
        rec = state.get_cluster('c3')
        assert rec['autostop'] == 30 and rec['to_down']

    def test_storage(self, tmp_state_dir):
        state.add_or_update_storage('b1', {'bucket': 'b1'},
                                    state.StorageStatus.READY)
        assert state.get_storage('b1')['status'] == \
            state.StorageStatus.READY
        state.remove_storage('b1')
        assert state.get_storage('b1') is None

    def test_config_kv(self, tmp_state_dir):
        state.set_config('k', {'a': 1})
        assert state.get_config('k') == {'a': 1}
        assert state.get_config('missing', 42) == 42
