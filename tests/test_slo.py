"""serve/slo.py + serve/fleet.py: burn-rate truth table across both
window pairs, alert hysteresis, goodput attribution per class/tenant,
deterministic replay under seeded scrape data, telemetry.scrape fault
descent, and the acceptance chaos drills (scrape-error mid-burst keeps
/fleet/slo serving; an induced server.request latency fault flips
skyt_slo_alert{class="interactive"} within one fast window)."""
import threading
import time

import pytest

from skypilot_tpu.serve import fleet as fleet_lib
from skypilot_tpu.serve import slo as slo_lib
from skypilot_tpu.utils import faults
from skypilot_tpu.utils import metrics as metrics_lib
from skypilot_tpu.utils import timeseries as ts_lib


class FakeClock:
    def __init__(self, t: float = 1_000_000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> float:
        self.t += dt
        return self.t


class FakeSource:
    """Truth-table source: per-window (bad_fraction, total) per class,
    served through the TimeSeriesStore read protocol."""

    def __init__(self, by_window):
        # {cls: {window_s: (bad_frac, total)}}
        self.by_window = by_window

    def sum_delta(self, name, match, window_s, now=None):
        cls = (match or {}).get('cls')
        spec = self.by_window.get(cls, {}).get(window_s)
        if spec is None:
            return None
        bad, total = spec
        if name == 'skyt_slo_requests_total':
            return total
        if name == 'skyt_slo_good_requests_total':
            return total * (1 - bad)
        return None

    def quantile(self, family, match, q, window_s, now=None):
        return None

    def grouped_delta(self, name, group_label, window_s, now=None,
                      match=None):
        return {}


def make_evaluator(source, clock=None, **kw):
    clock = clock or FakeClock()
    kw.setdefault('registry', metrics_lib.MetricsRegistry())
    kw.setdefault('windows', slo_lib.BurnWindows())
    return slo_lib.BurnRateEvaluator(source, clock=clock, **kw), clock


def windows_spec(fast=(0.0, 0.0), slow=(0.0, 0.0), total=100.0):
    """Per-window (bad_frac, total): fast=(5m, 1h), slow=(6h, 3d)."""
    w = slo_lib.BurnWindows()
    return {
        w.fast_short_s: (fast[0], total),
        w.fast_long_s: (fast[1], total),
        w.slow_short_s: (slow[0], total),
        w.slow_long_s: (slow[1], total),
    }


# ------------------------------------------------------------ objectives
def test_objectives_env_tunable(monkeypatch):
    monkeypatch.setenv('SKYT_SLO_TTFT_MS_INTERACTIVE', '123')
    monkeypatch.setenv('SKYT_SLO_TARGET', '0.9')
    monkeypatch.setenv('SKYT_SLO_TARGET_BATCH', '0.5')
    objs = slo_lib.objectives()
    assert objs['interactive'].ttft_ms == 123
    assert objs['interactive'].target == 0.9
    assert objs['batch'].target == 0.5
    assert abs(objs['standard'].budget - 0.1) < 1e-9


# --------------------------------------------------- goodput attribution
def test_goodput_tracker_attribution(monkeypatch):
    monkeypatch.setenv('SKYT_SLO_TTFT_MS_INTERACTIVE', '100')
    monkeypatch.setenv('SKYT_SLO_ITL_MS_INTERACTIVE', '50')
    reg = metrics_lib.MetricsRegistry()
    tr = slo_lib.GoodputTracker(registry=reg)
    # within SLO -> good
    assert tr.record('interactive', 'a', ok=True, ttft_s=0.05,
                     itl_s=0.01, tokens=10)
    # TTFT blown -> bad (tokens still counted as work done)
    assert not tr.record('interactive', 'a', ok=True, ttft_s=0.5,
                         tokens=10)
    # ITL blown -> bad
    assert not tr.record('interactive', 'a', ok=True, ttft_s=0.05,
                         itl_s=0.2, tokens=10)
    # error -> bad regardless of latency
    assert not tr.record('interactive', 'b', ok=False, ttft_s=0.01)
    # other tenant, other class (default objectives are looser)
    assert tr.record('batch', 'b', ok=True, ttft_s=0.5, tokens=3)
    g = reg.get('skyt_slo_good_requests_total')
    assert g.value('interactive', 'a') == 1
    assert g.value('interactive', 'b') == 0
    assert g.value('batch', 'b') == 1
    assert reg.get('skyt_slo_requests_total').value(
        'interactive', 'a') == 3
    assert reg.get('skyt_slo_good_tokens_total').value(
        'interactive', 'a') == 10
    assert reg.get('skyt_slo_tokens_total').value(
        'interactive', 'a') == 30
    # unknown class folds into the default class, never a crash
    assert tr.record('mystery', 't', ok=True, tokens=1)
    assert reg.get('skyt_slo_requests_total').value(
        'standard', 't') == 1


# --------------------------------------------------- burn-rate truth table
def test_burn_no_data_no_alert():
    ev, _ = make_evaluator(FakeSource({}))
    rep = ev.evaluate()
    for cls, rec in rep.items():
        assert rec['alert'] is False
        assert all(w['burn_rate'] == 0 for w in rec['windows'].values())


def test_burn_fast_pair_fires():
    # budget 0.01 (target .99); 20% bad on BOTH 5m and 1h => burn 20
    # >= 14.4 on both fast windows => page.
    src = FakeSource({'interactive': windows_spec(fast=(0.2, 0.2))})
    ev, _ = make_evaluator(src)
    rep = ev.evaluate()
    assert rep['interactive']['alert'] is True
    assert rep['interactive']['windows']['5m']['burn_rate'] == 20.0
    assert rep['standard']['alert'] is False


def test_burn_short_window_alone_does_not_fire():
    # 5m bad but the hour is clean: a blip, not a page.
    src = FakeSource({'interactive': windows_spec(fast=(0.2, 0.0))})
    ev, _ = make_evaluator(src)
    assert ev.evaluate()['interactive']['alert'] is False
    # and the long window alone (old burn, recovered) does not fire
    src2 = FakeSource({'interactive': windows_spec(fast=(0.0, 0.2))})
    ev2, _ = make_evaluator(src2)
    assert ev2.evaluate()['interactive']['alert'] is False


def test_burn_slow_pair_fires():
    # 7% bad over both 6h and 3d: burn 7 >= 6 on the slow pair.
    src = FakeSource({'batch': windows_spec(slow=(0.07, 0.07))})
    ev, _ = make_evaluator(src)
    rep = ev.evaluate()
    assert rep['batch']['alert'] is True
    assert rep['interactive']['alert'] is False


def test_alert_hysteresis_clears_on_short_windows():
    src = FakeSource({'interactive': windows_spec(fast=(0.2, 0.2))})
    reg = metrics_lib.MetricsRegistry()
    ev, _ = make_evaluator(src, registry=reg)
    assert ev.evaluate()['interactive']['alert'] is True
    assert reg.get('skyt_slo_alert').value('interactive') == 1
    # The hour window stays hot (it decays slowly) but the 5m window
    # recovered: the alert clears — fast-clear semantics.
    src.by_window = {'interactive': windows_spec(fast=(0.0, 0.2))}
    assert ev.evaluate()['interactive']['alert'] is False
    assert reg.get('skyt_slo_alert').value('interactive') == 0
    # Re-firing needs BOTH windows hot again, not the lingering hour.
    assert ev.evaluate()['interactive']['alert'] is False
    src.by_window = {'interactive': windows_spec(fast=(0.3, 0.2))}
    assert ev.evaluate()['interactive']['alert'] is True


def test_alert_stays_firing_while_short_window_burns():
    src = FakeSource({'interactive': windows_spec(fast=(0.2, 0.2))})
    ev, _ = make_evaluator(src)
    assert ev.evaluate()['interactive']['alert'] is True
    # long window drops first (shorter memory upstream): still firing
    # because the 5m window is still burning.
    src.by_window = {'interactive': windows_spec(fast=(0.2, 0.0))}
    assert ev.evaluate()['interactive']['alert'] is True


# ----------------------------------------- deterministic replay / store
def _seeded_store_run():
    """Feed a real TimeSeriesStore with deterministic scrape data and
    evaluate burn rates against it — the replay property."""
    clock = FakeClock()
    store = ts_lib.TimeSeriesStore(clock=clock)
    reg = metrics_lib.MetricsRegistry()
    ev = slo_lib.BurnRateEvaluator(store, registry=reg, clock=clock)
    good, total = 0, 0
    for i in range(40):
        clock.tick(10)
        total += 5
        good += 5 if i < 20 else 2   # the last 200s turn 60% bad
        store.observe('skyt_slo_requests_total',
                      {'cls': 'interactive', 'tenant': 'a'}, total)
        store.observe('skyt_slo_good_requests_total',
                      {'cls': 'interactive', 'tenant': 'a'}, good)
    rep = ev.evaluate()
    return rep, slo_lib.goodput_report(store, 300, clock.t, replicas=2)


def test_deterministic_replay_under_seeded_scrape_data():
    a = _seeded_store_run()
    b = _seeded_store_run()
    assert a == b
    rep, goodput = a
    # The 5m window holds 30 intervals: 20 bad-phase (60% bad) + 10
    # clean => 40% bad => burn 40; the 1h window dilutes further but
    # both stay >= 14.4, so the fast pair fires.
    assert rep['interactive']['windows']['5m']['burn_rate'] == \
        pytest.approx(40.0, rel=0.01)
    assert rep['interactive']['alert'] is True
    assert goodput['replicas'] == 2


def test_goodput_report_cost_math(monkeypatch):
    monkeypatch.setenv('SKYT_FLEET_CHIPS_PER_REPLICA', '4')
    clock = FakeClock()
    store = ts_lib.TimeSeriesStore(clock=clock)
    for i in range(2):
        ts = clock.tick(10)
        for tenant, tok in (('a', 100.0), ('b', 50.0)):
            store.observe('skyt_slo_tokens_total',
                          {'cls': 'interactive', 'tenant': tenant},
                          tok * (i + 1), ts=ts)
            store.observe('skyt_slo_good_tokens_total',
                          {'cls': 'interactive', 'tenant': tenant},
                          tok * (i + 1) * 0.9, ts=ts)
            store.observe('skyt_slo_requests_total',
                          {'cls': 'interactive', 'tenant': tenant},
                          float(i + 1), ts=ts)
            store.observe('skyt_slo_good_requests_total',
                          {'cls': 'interactive', 'tenant': tenant},
                          float(i + 1), ts=ts)
    rep = slo_lib.goodput_report(store, window_s=100.0, now=clock.t,
                                 replicas=2)
    assert rep['chips'] == 8
    tenants = rep['classes']['interactive']['tenants']
    assert tenants['a']['tokens'] == 100.0
    assert tenants['a']['good_tokens'] == pytest.approx(90.0)
    assert tenants['b']['good_tokens'] == pytest.approx(45.0)
    # 135 good tokens / (8 chips * 100 s)
    assert rep['good_tokens_per_chip_second'] == \
        pytest.approx(135.0 / 800.0, rel=1e-3)
    assert rep['chip_seconds_per_good_token'] == \
        pytest.approx(800.0 / 135.0, rel=1e-3)


# ------------------------------------------- fleet: scrape fault descent
def _expo(requests_n, good_n, cls='interactive', tenant='a'):
    return (
        '# TYPE skyt_slo_requests_total counter\n'
        f'skyt_slo_requests_total{{cls="{cls}",tenant="{tenant}"}} '
        f'{requests_n}\n'
        '# TYPE skyt_slo_good_requests_total counter\n'
        f'skyt_slo_good_requests_total{{cls="{cls}",'
        f'tenant="{tenant}"}} {good_n}\n')


def test_fleet_scrape_fault_descent_and_stale_ageout():
    """SKYT_FAULTS=telemetry.scrape=error against one replica: the
    scrape fails COUNTED (never raises into the prober), /fleet/slo
    keeps serving from the healthy replica, and the faulted replica's
    series age out after SKYT_FLEET_STALE_S."""
    clock = FakeClock()
    served = {}

    def fake_get(url, timeout):
        return served[url]

    reg = metrics_lib.MetricsRegistry()
    fl = fleet_lib.FleetTelemetry('svc', metrics_registry=reg,
                                  clock=clock, http_get=fake_get)
    served['http://r1/metrics'] = _expo(10, 10)
    served['http://r2/metrics'] = _expo(20, 20)
    assert fl.scrape('1', 'http://r1')
    assert fl.scrape('2', 'http://r2')
    faults.configure('telemetry.scrape=error,where=replica:1')
    try:
        clock.tick(10)
        served['http://r1/metrics'] = _expo(15, 15)
        served['http://r2/metrics'] = _expo(30, 30)
        assert fl.scrape('1', 'http://r1') is False   # fault fired
        assert fl.scrape('2', 'http://r2') is True    # unaffected
        assert reg.get('skyt_fleet_scrape_errors_total').value('1') == 1
        assert reg.get('skyt_fleet_scrapes_total').value('2', 'ok') == 2
        # /fleet/slo keeps serving: replica 2's data flows, replica 1
        # still contributes its PRE-fault series (not yet stale).
        rep = fl.fleet_slo(window_s=100)
        assert set(rep['targets']) == {'1', '2'}
        assert rep['goodput']['replicas'] == 2
        # Age replica 1 past the stale TTL (scrapes keep failing).
        for _ in range(8):
            clock.tick(10)
            served['http://r2/metrics'] = _expo(40, 40)
            fl.scrape('1', 'http://r1')
            fl.scrape('2', 'http://r2')
        rep = fl.fleet_slo(window_s=1000)
        assert set(rep['targets']) == {'2'}, \
            'faulted replica must age out of the aggregates'
        assert rep['goodput']['replicas'] == 1
        assert 'replica="1"' not in fl.fleet_metrics_text()
    finally:
        faults.reset()


def test_fleet_metrics_text_aggregates_with_replica_label():
    clock = FakeClock()
    served = {'http://r1/metrics': _expo(5, 5),
              'http://r2/metrics': _expo(7, 6, tenant='b')}
    fl = fleet_lib.FleetTelemetry(
        'svc', metrics_registry=metrics_lib.MetricsRegistry(),
        clock=clock, http_get=lambda url, t: served[url])
    fl.scrape('1', 'http://r1')
    fl.scrape('2', 'http://r2')
    text = fl.fleet_metrics_text()
    assert '# TYPE skyt_slo_requests_total counter' in text
    assert ('skyt_slo_requests_total{cls="interactive",replica="1",'
            'tenant="a"} 5') in text
    assert ('skyt_slo_requests_total{cls="interactive",replica="2",'
            'tenant="b"} 7') in text


def test_fleet_maybe_scrape_throttles():
    clock = FakeClock()
    calls = []

    def fake_get(url, timeout):
        calls.append(url)
        return _expo(1, 1)

    fl = fleet_lib.FleetTelemetry(
        'svc', metrics_registry=metrics_lib.MetricsRegistry(),
        clock=clock, http_get=fake_get)
    assert fl.maybe_scrape('1', 'http://r1') is True
    assert fl.maybe_scrape('1', 'http://r1') is None   # throttled
    clock.tick(fl.scrape_interval_s + 1)
    assert fl.maybe_scrape('1', 'http://r1') is True
    assert len(calls) == 2


def test_fleet_cross_replica_quantile():
    """TTFT p95 merges bucket increases ACROSS replica stores."""
    clock = FakeClock()
    hist = (
        '# TYPE skyt_slo_ttft_seconds histogram\n'
        'skyt_slo_ttft_seconds_bucket{{cls="interactive",le="0.1"}} {a}\n'
        'skyt_slo_ttft_seconds_bucket{{cls="interactive",le="1"}} {b}\n'
        'skyt_slo_ttft_seconds_bucket{{cls="interactive",le="+Inf"}} {b}\n')
    served = {}

    def fake_get(url, timeout):
        return served[url]

    fl = fleet_lib.FleetTelemetry(
        'svc', metrics_registry=metrics_lib.MetricsRegistry(),
        clock=clock, http_get=fake_get)
    served['http://r1/metrics'] = hist.format(a=0, b=0)
    served['http://r2/metrics'] = hist.format(a=0, b=0)
    fl.scrape('1', 'http://r1')
    fl.scrape('2', 'http://r2')
    clock.tick(10)
    # r1: 10 fast obs; r2: 10 slow obs => fleet p50 at the 0.1 bound.
    served['http://r1/metrics'] = hist.format(a=10, b=10)
    served['http://r2/metrics'] = hist.format(a=0, b=10)
    fl.scrape('1', 'http://r1')
    fl.scrape('2', 'http://r2')
    p50 = fl.quantile('skyt_slo_ttft_seconds', {'cls': 'interactive'},
                      0.5, 100, now=clock.t)
    assert p50 == pytest.approx(0.1, rel=1e-6)


# ------------------------------------------------ end-to-end chaos drills
def _start_server(env=None):
    """Debug engine + InferenceServer on a loopback port (private
    registry); returns (engine, base_url, registry)."""
    import dataclasses
    import socket

    import jax
    import jax.numpy as jnp
    import requests
    from aiohttp import web

    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import server as server_lib
    from skypilot_tpu.models import llama

    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    reg = metrics_lib.MetricsRegistry()
    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16],
                                     metrics_registry=reg)
    eng.start()
    srv = server_lib.InferenceServer(eng)
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    threading.Thread(target=lambda: web.run_app(
        srv.make_app(), port=port, print=None, handle_signals=False),
        daemon=True).start()
    base = f'http://127.0.0.1:{port}'
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            if requests.get(base + '/health',
                            timeout=2).status_code == 200:
                break
        except requests.RequestException:
            pass
        time.sleep(0.2)
    return eng, base, reg


@pytest.mark.integration
def test_latency_fault_flips_interactive_alert(monkeypatch):
    """THE acceptance drill, deterministically: with
    server.request=latency armed, every interactive request blows a
    50ms TTFT SLO, so within one fast window the 5m AND 1h burn rates
    pin high and skyt_slo_alert{class="interactive"} flips to firing —
    with zero client-visible 5xx."""
    import requests

    monkeypatch.setenv('SKYT_SLO_TTFT_MS_INTERACTIVE', '50')
    eng, base, _reg = _start_server()
    fleet_reg = metrics_lib.MetricsRegistry()
    fl = fleet_lib.FleetTelemetry('drill',
                                  metrics_registry=fleet_reg)
    try:
        # Prime the class/tenant series, then take the pre-burst
        # baseline scrape (a counter window needs both edges).
        r = requests.post(base + '/generate',
                          json={'tokens': [7, 8, 9], 'max_tokens': 2},
                          headers={'X-Priority': 'interactive'},
                          timeout=60)
        r.raise_for_status()
        assert fl.scrape('1', base)
        # Arm AFTER priming: 150ms injected ahead of every /generate.
        faults.configure(
            'server.request=latency,arg=0.15,where=path:/generate')
        codes = []
        for i in range(8):
            r = requests.post(
                base + '/generate',
                json={'tokens': [3 + i, 4, 5], 'max_tokens': 2},
                headers={'X-Priority': 'interactive'}, timeout=60)
            codes.append(r.status_code)
        assert all(c == 200 for c in codes), codes
        assert fl.scrape('1', base)
        rep = fl.fleet_slo(window_s=300)
        rec = rep['slo']['interactive']
        assert rec['alert'] is True, rec
        assert rec['windows']['5m']['burn_rate'] >= 14.4
        assert fleet_reg.get('skyt_slo_alert').value(
            'interactive') == 1
        # The injected latency is visible in the fleet TTFT quantile.
        assert rec['ttft_p95_ms'] is not None
        assert rec['ttft_p95_ms'] > 50
    finally:
        faults.reset()
        eng.stop()


@pytest.mark.integration
def test_debug_profile_endpoint(monkeypatch):
    """POST /debug/profile: 403 without SKYT_PROFILE_REMOTE, 400 on a
    malformed ms, 409 while another capture holds the single-flight
    lock, 200 with a real (CPU-degraded) trace dir."""
    import requests

    from skypilot_tpu.utils import profiling as profiling_lib

    eng, base, _reg = _start_server()
    try:
        monkeypatch.delenv('SKYT_PROFILE_REMOTE', raising=False)
        assert requests.post(base + '/debug/profile',
                             timeout=30).status_code == 403
        monkeypatch.setenv('SKYT_PROFILE_REMOTE', '1')
        assert requests.post(base + '/debug/profile',
                             params={'ms': 'nan'},
                             timeout=30).status_code == 400
        assert requests.post(base + '/debug/profile',
                             params={'ms': '999999'},
                             timeout=30).status_code == 400
        assert profiling_lib._CAPTURE_LOCK.acquire(blocking=False)
        try:
            assert requests.post(base + '/debug/profile',
                                 params={'ms': '20'},
                                 timeout=30).status_code == 409
        finally:
            profiling_lib._CAPTURE_LOCK.release()
        resp = requests.post(base + '/debug/profile',
                             params={'ms': '20'}, timeout=60)
        assert resp.status_code == 200, resp.text
        body = resp.json()
        assert body['trace_dir'] and body['duration_ms'] >= 20
    finally:
        eng.stop()


def test_fleet_routes_profile_proxy(monkeypatch):
    """/fleet/* HTTP surface via add_fleet_routes: metrics text, slo
    JSON, and the profile proxy's 400/404 paths (the 200 path is
    covered end-to-end by tpu_validation.sh step 11)."""
    import asyncio

    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    clock = FakeClock()
    fl = fleet_lib.FleetTelemetry(
        'svc', metrics_registry=metrics_lib.MetricsRegistry(),
        clock=clock, http_get=lambda url, t: _expo(3, 3))
    fl.scrape('1', 'http://r1')

    async def run():
        app = web.Application()
        fleet_lib.add_fleet_routes(app, fl, lambda rid: None)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            resp = await client.get('/fleet/metrics')
            assert resp.status == 200
            assert 'replica="1"' in await resp.text()
            resp = await client.get('/fleet/slo')
            assert resp.status == 200
            body = await resp.json()
            assert body['service'] == 'svc'
            assert 'interactive' in body['slo']
            resp = await client.get('/fleet/slo',
                                    params={'window_s': '-1'})
            assert resp.status == 400
            resp = await client.post('/fleet/profile')
            assert resp.status == 400
            resp = await client.post('/fleet/profile',
                                     params={'replica': '9'})
            assert resp.status == 404
        finally:
            await client.close()

    asyncio.run(run())
