"""infer/tickstats.py + infer/disagg_advisor.py: the tick plane's
ring and attribution math under an injectable clock, the structural
disablement path, the per-request ITL split, and the advisor goldens
(docs/observability.md "Tick plane")."""
import threading

import pytest

from skypilot_tpu.infer import disagg_advisor
from skypilot_tpu.infer import tickstats
from skypilot_tpu.utils import metrics as metrics_lib


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float) -> float:
        self.t += dt
        return self.t


def make(**kw):
    kw.setdefault('clock', FakeClock())
    return tickstats.TickStats(**kw)


# ------------------------------------------------------------ buckets
def test_slot_bucket_is_pow2():
    assert tickstats.slot_bucket(0) == 1
    assert tickstats.slot_bucket(1) == 1
    assert tickstats.slot_bucket(2) == 2
    assert tickstats.slot_bucket(3) == 4
    assert tickstats.slot_bucket(5) == 8
    assert tickstats.slot_bucket(8) == 8
    assert tickstats.slot_bucket(9) == 16


# ----------------------------------------------------- classification
def test_tick_kinds():
    ts = make()
    kind, _, _ = ts.on_tick(dur_s=0.01, active_slots=2, decode_reqs=2)
    assert kind == 'decode'
    kind, _, _ = ts.on_tick(dur_s=0.01, active_slots=2, decode_reqs=2,
                            prefill_reqs=1, prefill_tokens=16)
    assert kind == 'mixed'
    kind, _, _ = ts.on_tick(dur_s=0.01, active_slots=0, decode_reqs=0,
                            prefill_reqs=2, prefill_tokens=32)
    assert kind == 'prefill'
    s = ts.summary()
    assert s['by_kind'] == {'decode': 1, 'mixed': 1, 'prefill': 1}
    assert s['ticks'] == 3
    assert s['mixed_frac'] == pytest.approx(1 / 3)


# ------------------------------------------------------ ring eviction
def test_ring_eviction_counts_drops_and_keeps_newest():
    ts = make(ring=8)
    for i in range(20):
        ts.on_tick(dur_s=0.001 * (i + 1), active_slots=1,
                   decode_reqs=1)
    s = ts.summary()
    assert s['ring'] == {'retained': 8, 'dropped': 12}
    recs = ts.last(100)
    assert len(recs) == 8
    assert [r['seq'] for r in recs] == list(range(13, 21))
    assert ts.last(3) == recs[-3:]
    # Aggregates survive eviction: all 20 ticks counted.
    assert s['ticks'] == 20


# -------------------------------------------------------- attribution
def test_baseline_warms_after_min_samples():
    ts = make(min_samples=4, ewma_alpha=0.2)
    for _ in range(3):
        _, baseline, _ = ts.on_tick(dur_s=0.010, active_slots=1,
                                    decode_reqs=1)
        assert baseline is None
    # A mixed tick against a cold baseline attributes nothing.
    kind, baseline, excess = ts.on_tick(
        dur_s=0.050, active_slots=1, decode_reqs=1, prefill_reqs=1)
    assert (kind, baseline, excess) == ('mixed', None, 0.0)
    # Fourth pure-decode sample warms the bucket.
    _, baseline, _ = ts.on_tick(dur_s=0.010, active_slots=1,
                                decode_reqs=1)
    assert baseline == pytest.approx(0.010)
    kind, baseline, excess = ts.on_tick(
        dur_s=0.015, active_slots=1, decode_reqs=1, prefill_reqs=1)
    assert kind == 'mixed'
    assert baseline == pytest.approx(0.010)
    assert excess == pytest.approx(0.005)
    s = ts.summary()
    assert s['excess_seconds'] == pytest.approx(0.005)
    assert s['baselines']['1']['warm'] is True
    assert s['baselines']['1']['samples'] == 4


def test_ewma_update_math():
    ts = make(min_samples=1, ewma_alpha=0.5)
    ts.on_tick(dur_s=0.010, active_slots=1, decode_reqs=1)
    _, baseline, _ = ts.on_tick(dur_s=0.020, active_slots=1,
                                decode_reqs=1)
    # 0.010 + 0.5 * (0.020 - 0.010)
    assert baseline == pytest.approx(0.015)


def test_baselines_are_per_slot_bucket():
    ts = make(min_samples=1)
    ts.on_tick(dur_s=0.010, active_slots=1, decode_reqs=1)
    ts.on_tick(dur_s=0.030, active_slots=2, decode_reqs=2)
    # A mixed tick at width 2 compares against bucket 2, not 1.
    _, baseline, excess = ts.on_tick(
        dur_s=0.032, active_slots=2, decode_reqs=2, prefill_reqs=1)
    assert baseline == pytest.approx(0.030)
    assert excess == pytest.approx(0.002)
    # Bucket 4 has no samples: cold, nothing attributed.
    _, baseline, excess = ts.on_tick(
        dur_s=0.1, active_slots=4, decode_reqs=4, prefill_reqs=1)
    assert (baseline, excess) == (None, 0.0)


def test_mixed_excess_never_negative():
    ts = make(min_samples=1)
    ts.on_tick(dur_s=0.010, active_slots=1, decode_reqs=1)
    _, _, excess = ts.on_tick(dur_s=0.002, active_slots=1,
                              decode_reqs=1, prefill_reqs=1)
    assert excess == 0.0


def test_mixed_ticks_do_not_move_the_baseline():
    ts = make(min_samples=1, ewma_alpha=0.5)
    ts.on_tick(dur_s=0.010, active_slots=1, decode_reqs=1)
    for _ in range(5):
        ts.on_tick(dur_s=0.100, active_slots=1, decode_reqs=1,
                   prefill_reqs=1)
    assert ts.summary()['baselines']['1']['ewma_s'] == \
        pytest.approx(0.010)


# ------------------------------------------------- per-request split
def test_per_request_itl_split_by_class():
    ts = make()
    ts.note_request('interactive', 0.08, 0.02)
    ts.note_request('interactive', 0.04, 0.0)
    ts.note_request('batch', 0.5, 0.0)
    cls = ts.summary()['classes']
    assert cls['interactive']['requests'] == 2
    assert cls['interactive']['decode_floor_s'] == pytest.approx(0.12)
    assert cls['interactive']['interference_s'] == pytest.approx(0.02)
    assert cls['interactive']['interference_frac'] == \
        pytest.approx(0.02 / 0.14)
    assert cls['batch']['interference_frac'] == 0.0


# ------------------------------------------------------------ metrics
def test_metric_families_and_first_tick_edge():
    reg = metrics_lib.MetricsRegistry()
    ts = make(registry=reg, min_samples=1)
    ts.on_tick(dur_s=0.010, active_slots=1, decode_reqs=1)
    ts.note_request('standard', 0.01, 0.0)
    text = reg.expose()
    # The excess counter must exist from the FIRST tick (inc(0)) so
    # fleet-scrape windowed deltas get a baseline edge before the
    # first attributed excess lands.
    assert 'skyt_tick_excess_seconds_total 0' in text
    assert 'skyt_tick_total{kind="decode"} 1' in text
    ts.on_tick(dur_s=0.015, active_slots=1, decode_reqs=1,
               prefill_reqs=1)
    reg2 = reg.expose()
    assert 'skyt_tick_total{kind="mixed"} 1' in reg2
    assert 'skyt_tick_baseline_seconds{slots="1"}' in reg2
    assert 'skyt_interference_decode_floor_seconds' \
        '{cls="standard"}' in reg2
    assert ts._m_excess.value() == pytest.approx(0.005)


# -------------------------------------------------------- note_host
def test_note_host_backfills_last_record():
    ts = make()
    ts.on_tick(dur_s=0.01, active_slots=1, decode_reqs=1)
    ts.note_host(0.003)
    assert ts.last(1)[0]['host_s'] == pytest.approx(0.003)


# ------------------------------------------------------ chrome trace
def test_chrome_trace_slices():
    clock = FakeClock(10.0)
    ts = make(clock=clock, min_samples=1)
    ts.on_tick(dur_s=0.010, active_slots=1, decode_reqs=1)
    clock.tick(0.02)
    ts.on_tick(dur_s=0.015, active_slots=1, decode_reqs=1,
               prefill_reqs=2, prefill_tokens=32, prefill_bucket=16)
    trace = ts.chrome_trace()
    assert trace['displayTimeUnit'] == 'ms'
    meta = [e for e in trace['traceEvents'] if e['ph'] == 'M']
    slices = [e for e in trace['traceEvents'] if e['ph'] == 'X']
    assert len(meta) == 2 and len(slices) == 2
    mixed = slices[1]
    assert mixed['name'] == 'mixed'
    assert mixed['dur'] == pytest.approx(0.015 * 1e6)
    assert mixed['ts'] == pytest.approx((10.02 - 0.015) * 1e6)
    assert mixed['args']['prefill_reqs'] == 2
    assert mixed['args']['prefill_bucket'] == 16
    assert mixed['args']['interference_excess_ms'] == \
        pytest.approx(5.0)


# ------------------------------------------------------- concurrency
def test_concurrency_hammer():
    ts = make(ring=64)
    n_threads, per = 8, 500
    errs = []

    def worker(i):
        try:
            for j in range(per):
                ts.on_tick(dur_s=0.001, active_slots=(i % 4) + 1,
                           decode_reqs=1,
                           prefill_reqs=1 if j % 3 == 0 else 0)
                ts.note_request('standard', 0.001, 0.0)
                if j % 50 == 0:
                    ts.summary()
                    ts.last(8)
        except Exception as e:  # pylint: disable=broad-except
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errs
    s = ts.summary()
    assert s['ticks'] == n_threads * per
    assert s['ring']['retained'] == 64
    assert s['ring']['dropped'] == n_threads * per - 64
    assert s['classes']['standard']['requests'] == n_threads * per
    # seq stayed unique under contention.
    seqs = [r['seq'] for r in ts.last(64)]
    assert len(set(seqs)) == 64


# ------------------------------------------- structural disablement
def test_from_env_disabled_returns_none(monkeypatch):
    monkeypatch.setenv('SKYT_TICKSTATS', '0')
    assert tickstats.from_env() is None


def test_from_env_knobs(monkeypatch):
    monkeypatch.setenv('SKYT_TICKSTATS', '1')
    monkeypatch.setenv('SKYT_TICKSTATS_RING', '16')
    monkeypatch.setenv('SKYT_TICKSTATS_EWMA', '0.5')
    monkeypatch.setenv('SKYT_INTERFERENCE_MIN_SAMPLES', '2')
    ts = tickstats.from_env()
    assert ts is not None
    assert ts._ring.maxlen == 16
    assert ts._alpha == 0.5
    assert ts._min_samples == 2


# --------------------------------------------------- advisor goldens
def test_advisor_insufficient_without_attribution():
    v = disagg_advisor.advise(
        itl_p99_s=None, interference_frac=None,
        kv_bytes_per_token=512.0, prompt_tokens_per_request=100.0,
        output_tokens_per_request=64.0, dcn_gbps=10.0,
        dcn_source='measured')
    assert v['recommendation'] == 'insufficient_data'
    assert v['tradeoff']['benefit_s_per_request'] is None


def test_advisor_insufficient_without_transfer_inputs():
    v = disagg_advisor.advise(
        itl_p99_s=0.02, interference_frac=0.3,
        kv_bytes_per_token=None, prompt_tokens_per_request=100.0,
        output_tokens_per_request=64.0, dcn_gbps=10.0)
    assert v['recommendation'] == 'insufficient_data'
    assert 'transfer-cost inputs missing' in v['reason']


def test_advisor_keep_colocated_below_noise_floor():
    v = disagg_advisor.advise(
        itl_p99_s=0.02, interference_frac=0.05,
        kv_bytes_per_token=512.0, prompt_tokens_per_request=100.0,
        output_tokens_per_request=64.0, dcn_gbps=10.0,
        dcn_source='measured', min_inflation=0.1)
    assert v['recommendation'] == 'keep_colocated'
    assert 'below the 10% floor' in v['reason']


def test_advisor_keep_colocated_when_transfer_dominates():
    # Benefit 1e-6 * 0.5 * 2 = 1e-6 s/request; transfer
    # 512 * 4096 / (0.001 * 1e9) ≈ 2.1 s/request.
    v = disagg_advisor.advise(
        itl_p99_s=1e-6, interference_frac=0.5,
        kv_bytes_per_token=512.0, prompt_tokens_per_request=4096.0,
        output_tokens_per_request=2.0, dcn_gbps=0.001,
        dcn_source='measured', min_inflation=0.1)
    assert v['recommendation'] == 'keep_colocated'
    assert 'does not cover' in v['reason']


def test_advisor_disaggregate_golden():
    v = disagg_advisor.advise(
        itl_p99_s=0.020, interference_frac=0.3,
        mixed_tick_frac=0.4,
        kv_bytes_per_token=512.0, prompt_tokens_per_request=100.0,
        output_tokens_per_request=64.0, dcn_gbps=10.0,
        dcn_source='measured', min_inflation=0.1)
    assert v['recommendation'] == 'disaggregate'
    assert v['measured']['predicted_itl_improvement_s'] == \
        pytest.approx(0.006)
    assert v['transfer']['bytes_per_request'] == pytest.approx(51200.0)
    assert v['transfer']['predicted_transfer_cost_s_per_request'] == \
        pytest.approx(51200.0 / 1e10)
    assert v['tradeoff']['benefit_s_per_request'] == \
        pytest.approx(0.384)
    assert 'measured DCN' in v['reason']
    assert v['inputs']['min_inflation'] == 0.1


def test_advisor_env_fallback_marks_assumed(monkeypatch):
    monkeypatch.setenv('SKYT_INTERFERENCE_DCN_GBPS', '25.0')
    v = disagg_advisor.advise(
        itl_p99_s=0.020, interference_frac=0.3,
        kv_bytes_per_token=512.0, prompt_tokens_per_request=100.0,
        output_tokens_per_request=64.0, dcn_gbps=None,
        dcn_source='measured')   # source is overridden: no profile
    assert v['transfer']['dcn_gbps'] == 25.0
    assert v['transfer']['dcn_source'] == 'assumed'
