"""Multi-LoRA serving: stacked adapters, per-request routing, parity.

Reference analog: llm/lorax (the reference serves many adapters by
deploying the LoRAX container); here adapters are first-class in the
engine (infer/lora.py + models/llama.py _lora_delta). The correctness
bar: a request routed through adapter i must produce EXACTLY the
tokens a single-model engine over merge_lora(base, adapter_i) produces
— batched together with requests on other adapters and on the base.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import lora as slora
from skypilot_tpu.models import llama
from skypilot_tpu.train import lora as tlora

pytestmark = pytest.mark.heavy


def _base(max_seq_len=64):
    cfg = dataclasses.replace(llama.CONFIGS['debug'],
                              max_seq_len=max_seq_len)
    model = llama.LlamaModel(cfg)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))['params'])
    return cfg, model, params


def _rand_adapter(params, rank, alpha, seed):
    """A trained-looking adapter: random A AND B (init's B=0 would make
    the delta vanish and the test vacuous)."""
    lcfg = tlora.LoRAConfig(rank=rank, alpha=alpha)
    tree = tlora.init_lora_params(params, lcfg, jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)
    tree = jax.tree.map(
        lambda x: jnp.asarray(rng.normal(0, 0.1, x.shape), x.dtype),
        tree)
    return tree, lcfg


def test_model_level_parity_and_id0():
    cfg, model, params = _base()
    tree, lcfg = _rand_adapter(params, rank=4, alpha=8.0, seed=1)
    stack = slora.build_stack([(tree, lcfg.alpha)], dtype='float32')
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (2, 12)),
        jnp.int32)
    out = model.apply(
        {'params': params, 'lora': stack,
         'lora_ids': {'ids': jnp.asarray([1, 0], jnp.int32)}}, tokens)
    base_out = model.apply({'params': params}, tokens)
    merged_out = model.apply(
        {'params': tlora.merge_lora(params, tree, lcfg)}, tokens)
    np.testing.assert_allclose(np.asarray(out[0]),
                               np.asarray(merged_out[0]),
                               rtol=2e-4, atol=2e-4)
    # id 0 is bit-exact base: the zeros adapter contributes nothing.
    np.testing.assert_array_equal(np.asarray(out[1]),
                                  np.asarray(base_out[1]))


def _greedy(eng, prompt, n=8, lora_id=0):
    return eng.generate(prompt, engine_lib.SamplingParams(
        max_new_tokens=n, lora_id=lora_id))


def _engine(model, params, stack=None, **kw):
    kw.setdefault('num_slots', 3)
    kw.setdefault('max_seq_len', 64)
    kw.setdefault('prefill_buckets', [16])
    return engine_lib.InferenceEngine(model, {'params': params},
                                      lora_stack=stack, **kw)


def test_mixed_batch_matches_merged_engines():
    """Three concurrent requests — adapter A, adapter B (different
    rank!), and base — decode in the same continuous batch and each
    matches its own merged-model engine token-for-token."""
    cfg, model, params = _base()
    tree_a, cfg_a = _rand_adapter(params, rank=4, alpha=8.0, seed=3)
    tree_b, cfg_b = _rand_adapter(params, rank=2, alpha=4.0, seed=4)
    stack = slora.build_stack([(tree_a, cfg_a.alpha),
                               (tree_b, cfg_b.alpha)], dtype='float32')

    prompts = {1: [5, 17, 3, 99, 42], 2: [7, 7, 23, 11], 0: [9, 1, 4]}

    want = {}
    for lid, tree, lcfg in ((1, tree_a, cfg_a), (2, tree_b, cfg_b)):
        merged = tlora.merge_lora(params, tree, lcfg)
        eng = _engine(model, merged)
        eng.start()
        try:
            want[lid] = _greedy(eng, prompts[lid])
        finally:
            eng.stop()
    eng = _engine(model, params)
    eng.start()
    try:
        want[0] = _greedy(eng, prompts[0])
    finally:
        eng.stop()

    eng = _engine(model, params, stack=stack)
    assert eng.num_adapters == 3  # id 0 + two adapters
    eng.start()
    got = {}
    try:
        # Submit all three before draining so they share decode steps.
        qs = {lid: eng.submit(p, engine_lib.SamplingParams(
            max_new_tokens=8, lora_id=lid))[1]
            for lid, p in prompts.items()}
        for lid, q in qs.items():
            out = []
            while True:
                t = q.get(timeout=120)
                if t is None:
                    break
                out.append(t)
            got[lid] = out
    finally:
        eng.stop()
    assert got == want


def test_paged_prefix_cache_isolated_per_adapter():
    """Same prompt under two adapters with prefix caching ON: the
    second request must NOT reuse the first adapter's KV pages (K/V
    depend on the adapter's wk/wv) — outputs match per-adapter merged
    engines."""
    cfg, model, params = _base()
    tree_a, cfg_a = _rand_adapter(params, rank=4, alpha=8.0, seed=5)
    stack = slora.build_stack([(tree_a, cfg_a.alpha)], dtype='float32')
    prompt = list(range(1, 33))   # two full 16-token pages

    merged = tlora.merge_lora(params, tree_a, cfg_a)
    for ref_params, lid in ((merged, 1), (params, 0)):
        eng = _engine(model, ref_params, cache_mode='paged',
                      page_size=16, prefix_caching=True)
        eng.start()
        try:
            want = _greedy(eng, prompt)
        finally:
            eng.stop()

        eng = _engine(model, params, stack=stack, cache_mode='paged',
                      page_size=16, prefix_caching=True)
        eng.start()
        try:
            # Prime the cache with the OTHER route first, then request
            # with `lid`: a cross-adapter page hit would corrupt this.
            _greedy(eng, prompt, lora_id=1 - lid)
            got = _greedy(eng, prompt, lora_id=lid)
        finally:
            eng.stop()
        assert got == want, f'lora_id={lid}'


def test_spec_decode_with_adapter_stays_exact():
    """n-gram speculative decoding verifies against the ADAPTER model
    (the lora collection rides into the verify step), so outputs equal
    the merged engine's plain decode."""
    cfg, model, params = _base()
    tree_a, cfg_a = _rand_adapter(params, rank=4, alpha=8.0, seed=6)
    stack = slora.build_stack([(tree_a, cfg_a.alpha)], dtype='float32')
    prompt = [5, 6, 5, 6, 5, 6, 5, 6]   # repetitive: n-gram drafts fire

    eng = _engine(model, tlora.merge_lora(params, tree_a, cfg_a),
                  cache_mode='paged', page_size=16)
    eng.start()
    try:
        want = _greedy(eng, prompt, n=10)
    finally:
        eng.stop()

    eng = _engine(model, params, stack=stack, cache_mode='paged',
                  page_size=16, spec_decode=2)
    eng.start()
    try:
        got = _greedy(eng, prompt, n=10, lora_id=1)
    finally:
        eng.stop()
    assert got == want


def test_out_of_range_lora_id_rejected():
    cfg, model, params = _base()
    eng = _engine(model, params)   # no stack
    with pytest.raises(ValueError, match='lora_id 1 out of range'):
        eng.submit([1, 2, 3], engine_lib.SamplingParams(lora_id=1))
    tree_a, cfg_a = _rand_adapter(params, rank=2, alpha=4.0, seed=7)
    stack = slora.build_stack([(tree_a, cfg_a.alpha)], dtype='float32')
    eng = _engine(model, params, stack=stack)
    with pytest.raises(ValueError, match='lora_id 2 out of range'):
        eng.submit([1, 2, 3], engine_lib.SamplingParams(lora_id=2))


def test_adapter_roundtrip_through_orbax(tmp_path):
    """load_adapter_dir reads what an sft LoRA run writes (Orbax
    TrainStateS), and build_stack_from_specs maps names to ids."""
    from skypilot_tpu.train import checkpoint as ckpt_lib
    from skypilot_tpu.train import trainer

    cfg, model, params = _base()
    tree, lcfg = _rand_adapter(params, rank=2, alpha=4.0, seed=8)
    tx = trainer.make_optimizer(trainer.TrainerConfig())
    state = trainer.TrainStateS(step=jnp.zeros((), jnp.int32),
                                params=tree, opt_state=tx.init(tree))
    ck = ckpt_lib.Checkpointer(str(tmp_path / 'adpt'), async_save=False)
    ck.save(0, state, force=True)
    ck.wait()

    stack, names = slora.build_stack_from_specs(
        [slora.AdapterSpec(name='my-ft', path=str(tmp_path / 'adpt'),
                           alpha=lcfg.alpha)], dtype='float32')
    assert names == {'my-ft': 1}
    want = slora.build_stack([(tree, lcfg.alpha)], dtype='float32')
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(want),
            jax.tree_util.tree_leaves_with_path(stack)):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_server_model_routing():
    """OpenAI 'model' field routes: base id -> 0, adapter name -> its
    id, unknown -> model_not_found."""
    from skypilot_tpu.infer import server as server_lib

    cfg, model, params = _base()
    eng = _engine(model, params)
    srv = server_lib.InferenceServer(eng, model_id='base',
                                     lora_names={'ft-a': 1})
    assert srv._resolve_lora({}) == (0, None)
    assert srv._resolve_lora({'model': 'base'}) == (0, None)
    assert srv._resolve_lora({'model': 'ft-a'})[0] == 1
    lid, err = srv._resolve_lora({'model': 'nope'})
    assert lid == 0 and err is not None and err.status == 404


def test_parse_lora_flag():
    specs = slora.parse_lora_flag(
        ['a=/tmp/x', 'b=gs://bkt/path:32', 'c=/tmp/y:8.5'])
    assert specs[0] == slora.AdapterSpec('a', '/tmp/x', 16.0)
    assert specs[1] == slora.AdapterSpec('b', 'gs://bkt/path', 32.0)
    assert specs[2] == slora.AdapterSpec('c', '/tmp/y', 8.5)
    with pytest.raises(ValueError, match='name=path'):
        slora.parse_lora_flag(['justapath'])
    with pytest.raises(ValueError, match='duplicate'):
        slora.parse_lora_flag(['a=/x', 'a=/y'])


def test_multilora_tp_sharded_matches_tp1():
    """tp=2 over the CPU mesh: adapter stack replicates, outputs match
    the tp=1 multi-LoRA engine token-for-token."""
    from skypilot_tpu.models import weights
    from skypilot_tpu.parallel import mesh as mesh_lib

    cfg, model, params = _base()
    tree_a, cfg_a = _rand_adapter(params, rank=4, alpha=8.0, seed=9)
    stack = slora.build_stack([(tree_a, cfg_a.alpha)], dtype='float32')
    prompt = [5, 17, 3, 99, 42]

    def run(mesh):
        p = params
        if mesh is not None:
            p = weights.shard_params({'params': params}, model, cfg,
                                     mesh)['params']
        eng = _engine(model, p, stack=stack, mesh=mesh)
        eng.start()
        try:
            return _greedy(eng, prompt, lora_id=1)
        finally:
            eng.stop()

    want = run(None)
    got = run(mesh_lib.build_mesh(mesh_lib.MeshSpec(tp=2)))
    assert got == want


def test_stack_layout_mismatch_rejected():
    """An adapter trained under a different layer layout must fail
    loudly at engine build, not silently serve base-model outputs."""
    cfg, model, params = _base()
    cfg_ns = dataclasses.replace(cfg, scan_layers=False)
    model_ns = llama.LlamaModel(cfg_ns)
    params_ns = nn.meta.unbox(
        jax.jit(model_ns.init)(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))['params'])
    tree_ns, cfg_a = _rand_adapter(params_ns, rank=2, alpha=4.0,
                                   seed=10)
    stack_ns = slora.build_stack([(tree_ns, cfg_a.alpha)],
                                 dtype='float32')
    with pytest.raises(ValueError, match='does not match the serving'):
        _engine(model, params, stack=stack_ns)


def test_adapter_name_collides_with_model_id():
    from skypilot_tpu.infer import server as server_lib
    cfg, model, params = _base()
    eng = _engine(model, params)
    with pytest.raises(ValueError, match='collides'):
        server_lib.InferenceServer(eng, model_id='sql-ft',
                                   lora_names={'sql-ft': 1})


def test_stats_report_ttft_percentiles():
    """/stats surfaces TTFT p50/p90/p99 from the rolling window (the
    reference reads these off vLLM's metrics endpoint)."""
    cfg, model, params = _base()
    eng = _engine(model, params)
    eng.start()
    try:
        for _ in range(3):
            _greedy(eng, [5, 17, 3], n=2)
        s = eng.stats()
    finally:
        eng.stop()
    t = s['ttft_ms']
    assert t['count'] == 3
    assert 0 < t['p50'] <= t['p90'] <= t['p99']


# ---------------------------------------------------------- logit_bias
# OpenAI logit_bias (vLLM serves it too): device-side scatter-add on
# the decode path, host-side add on the admission (first-token) path.

def test_logit_bias_forces_and_bans_tokens():
    cfg, model, params = _base()
    eng = _engine(model, params)
    eng.start()
    try:
        plain = _greedy(eng, [5, 17, 3], n=4)
        # +100 on one token dominates every logit: all outputs = 9.
        forced = eng.generate([5, 17, 3], engine_lib.SamplingParams(
            max_new_tokens=4, logit_bias={9: 100.0}))
        assert forced == [9, 9, 9, 9]
        # -100 on the greedy first token bans it everywhere.
        banned = eng.generate([5, 17, 3], engine_lib.SamplingParams(
            max_new_tokens=4, logit_bias={plain[0]: -100.0}))
        assert plain[0] not in banned
    finally:
        eng.stop()


def test_logit_bias_sampling_path():
    """temperature > 0 with a dominating bias still lands on the
    biased token (the bias applies before temperature/top-k)."""
    cfg, model, params = _base()
    eng = _engine(model, params)
    eng.start()
    try:
        out = eng.generate([5, 17, 3], engine_lib.SamplingParams(
            max_new_tokens=4, temperature=1.0, seed=7,
            logit_bias={11: 100.0}))
        assert out == [11, 11, 11, 11]
    finally:
        eng.stop()


def test_logit_bias_spec_decode_falls_back_exact():
    """Spec decoding falls back to the plain path for biased requests;
    outputs equal the non-spec engine's."""
    cfg, model, params = _base()
    prompt = [5, 6, 5, 6, 5, 6]

    def run(spec):
        eng = _engine(model, params, cache_mode='paged', page_size=16,
                      spec_decode=spec)
        eng.start()
        try:
            return eng.generate(prompt, engine_lib.SamplingParams(
                max_new_tokens=6, logit_bias={3: 5.0, 8: -5.0}))
        finally:
            eng.stop()
    assert run(2) == run(0)


def test_logit_bias_validation():
    cfg, model, params = _base()
    eng = _engine(model, params)
    with pytest.raises(ValueError, match='at most 64'):
        engine_lib.SamplingParams(
            logit_bias={i: 1.0 for i in range(65)}).validate()
    with pytest.raises(ValueError, match=r'\[-100, 100\]'):
        engine_lib.SamplingParams(logit_bias={1: 200.0}).validate()
    with pytest.raises(ValueError, match='out of vocab'):
        eng.submit([1, 2], engine_lib.SamplingParams(
            logit_bias={cfg.vocab_size + 5: 1.0}))
