"""Catalog fetcher tests: static emit + live-API SKU parsing against a
canned Billing Catalog payload (no network; reference:
sky/clouds/service_catalog/data_fetchers/fetch_gcp.py)."""
import csv

from skypilot_tpu.catalog.data_fetchers import fetch_gcp


class _FakeResp:
    def __init__(self, payload):
        self._payload = payload

    def raise_for_status(self):
        pass

    def json(self):
        return self._payload


class _FakeSession:
    """Two pages of SKUs, exercising pagination."""

    def __init__(self):
        self.pages = [
            {'skus': [
                {'description': 'Tpu v5e hourly',
                 'category': {'usageType': 'OnDemand'},
                 'serviceRegions': ['us-central1'],
                 'pricingInfo': [{'pricingExpression': {'tieredRates': [
                     {'unitPrice': {'units': '1', 'nanos': 500000000}},
                 ]}}]},
                {'description': 'Preemptible Tpu v5e hourly',
                 'category': {'usageType': 'Preemptible'},
                 'serviceRegions': ['us-central1'],
                 'pricingInfo': [{'pricingExpression': {'tieredRates': [
                     {'unitPrice': {'units': '0', 'nanos': 600000000}},
                 ]}}]},
                {'description': 'Commitment v1: Tpu v5e for 1 year',
                 'category': {'usageType': 'Commit1Yr'},
                 'serviceRegions': ['us-central1'],
                 'pricingInfo': [{'pricingExpression': {'tieredRates': [
                     {'unitPrice': {'units': '0', 'nanos': 100000000}},
                 ]}}]},
            ], 'nextPageToken': 'p2'},
            {'skus': [
                {'description': 'N2 Instance Core running in Americas',
                 'category': {'usageType': 'OnDemand'},
                 'serviceRegions': ['us-central1'],
                 'pricingInfo': [{'pricingExpression': {'tieredRates': [
                     {'unitPrice': {'units': '0', 'nanos': 31000000}},
                 ]}}]},
            ]},
        ]
        self.calls = []

    def get(self, url, params=None, timeout=None):
        self.calls.append(params)
        page = 1 if params.get('pageToken') else 0
        return _FakeResp(self.pages[page])


def test_static_emit_covers_expected_families(tmp_path):
    out = tmp_path / 'gcp.csv'
    n = fetch_gcp.emit_static(str(out))
    assert n > 100
    with open(out) as f:
        rows = list(csv.DictReader(f))
    names = {r['AcceleratorName'] for r in rows}
    assert 'tpu-v5e-16' in names
    assert 'A100' in names
    assert any(r['InstanceType'] == 'n2-standard-4' for r in rows)


def test_sku_parse_pagination_and_filtering():
    session = _FakeSession()
    skus = list(fetch_gcp.iter_skus('key', session=session))
    assert len(skus) == 4
    assert len(session.calls) == 2
    assert session.calls[1]['pageToken'] == 'p2'

    prices = fetch_gcp.tpu_chip_prices(skus)
    assert prices[('v5e', 'us-central1', False)] == 1.5
    assert prices[('v5e', 'us-central1', True)] == 0.6
    # Commitment SKU skipped; non-TPU SKU skipped.
    assert len(prices) == 2


def test_emit_from_api_overrides_prices(tmp_path):
    out = tmp_path / 'gcp.csv'
    n = fetch_gcp.emit_from_api(str(out), 'key', session=_FakeSession())
    assert n > 100
    with open(out) as f:
        rows = list(csv.DictReader(f))
    v5e16 = [r for r in rows if r['AcceleratorName'] == 'tpu-v5e-16'
             and r['Region'] == 'us-central1'][0]
    # 16 chips x live $1.50 (static table says $1.20).
    assert float(v5e16['Price']) == 24.0
    assert float(v5e16['SpotPrice']) == 9.6
    # Regions without live SKUs keep static prices.
    other = [r for r in rows if r['AcceleratorName'] == 'tpu-v5e-16'
             and r['Region'] == 'europe-west4'][0]
    assert float(other['Price']) == 19.2


def test_emit_writes_provenance_meta(tmp_path):
    """Every catalog write records generated_at + mode so the CLI can
    warn about stale prices (the static table silently ages)."""
    import json
    out = tmp_path / 'gcp.csv'
    fetch_gcp.emit_static(str(out))
    meta = json.load(open(tmp_path / 'gcp.meta.json'))
    assert meta['mode'] == 'static'
    fetch_gcp.emit_from_api(str(out), 'key', session=_FakeSession())
    meta = json.load(open(tmp_path / 'gcp.meta.json'))
    assert meta['mode'] == 'api'
    import datetime
    age = (datetime.datetime.now(datetime.timezone.utc) -
           datetime.datetime.fromisoformat(meta['generated_at']))
    assert age.total_seconds() < 300


def test_catalog_staleness_warning(monkeypatch, tmp_path):
    """> 90 days -> warning with the refresh command; fresh -> None;
    no meta -> 'no generation record'."""
    import datetime
    import json

    from skypilot_tpu.catalog import common as catalog_common
    monkeypatch.setattr(catalog_common, '_CATALOG_DIR', str(tmp_path))
    assert 'no generation record' in catalog_common.staleness_warning()
    old = (datetime.datetime.now(datetime.timezone.utc) -
           datetime.timedelta(days=200)).isoformat()
    json.dump({'generated_at': old, 'mode': 'static'},
              open(tmp_path / 'gcp.meta.json', 'w'))
    msg = catalog_common.staleness_warning()
    assert '200 days old' in msg and 'fetch_gcp' in msg
    now = datetime.datetime.now(datetime.timezone.utc).isoformat()
    json.dump({'generated_at': now, 'mode': 'api'},
              open(tmp_path / 'gcp.meta.json', 'w'))
    assert catalog_common.staleness_warning() is None
