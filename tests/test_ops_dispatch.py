"""Kernel dispatch layer: shape-robust block selection, the
tuned-Pallas -> conservative-Pallas -> XLA fallback ladder, the
autotune cache, and the ops.lowering chaos path (docs/kernels.md).

Everything here runs on CPU: the Pallas rungs execute in interpreter
mode (kernel logic exercised; the Mosaic legality rules are checked
against the STATIC mirror in ops/dispatch.py, the same predicate jax's
_check_block_mappings enforces on-chip).
"""
import json
import os
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import pytest
import requests

from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.ops import autotune
from skypilot_tpu.ops import dispatch
from skypilot_tpu.ops import flash_attention as flash_lib
from skypilot_tpu.utils import faults
from skypilot_tpu.utils import metrics as metrics_lib


def _qkv(b, sq, sk, hq, hkv, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, sq, hq, d), dtype)
    k = jax.random.normal(ks[1], (b, sk, hkv, d), dtype)
    v = jax.random.normal(ks[2], (b, sk, hkv, d), dtype)
    return q, k, v


# ------------------------------------------ static block-spec selection
class TestBlockSelection:

    def test_choose_block_mirrors_mosaic_rule(self):
        """Every selection must satisfy the exact predicate jax's
        _check_block_mappings enforces: block % tile == 0 or block ==
        dim — plus our kernels' exact-division invariant."""
        for dim in (1, 3, 8, 12, 17, 48, 128, 256, 300, 1000, 4096):
            for want in (1, 8, 100, 128, 256, 512):
                for mult in (8, 16, 32, 128):
                    b = dispatch.choose_block(dim, want, mult)
                    assert dispatch.block_dim_ok(b, dim, mult), \
                        (dim, want, mult, b)
                    assert dim % b == 0, (dim, want, mult, b)
                    assert b <= dim

    def test_choose_block_prefers_tile_aligned_divisor(self):
        assert dispatch.choose_block(512, 256, 128) == 256
        assert dispatch.choose_block(48, 256, 8) == 48   # full dim
        assert dispatch.choose_block(48, 24, 8) == 24
        # 300 has no 8-aligned divisor <= 256 -> full-array block.
        assert dispatch.choose_block(300, 256, 8) == 300
        # Decode-shaped: tiny dim -> full dim (equal arm of the rule).
        assert dispatch.choose_block(8, 256, 8) == 8
        assert dispatch.choose_block(1, 256, 8) == 1

    def test_flash_blocks_seg_uses_lane_alignment(self):
        # Packed sequences put the seq extent on the lane axis of the
        # segment-id blocks -> 128-aligned (or full-dim) blocks only.
        bq, bk = dispatch.flash_blocks(512, 512, 256, 256,
                                       jnp.float32, True)
        assert bq % 128 == 0 and bk % 128 == 0
        bq, _ = dispatch.flash_blocks(48, 48, 32, 32, jnp.float32, True)
        assert bq == 48   # no 128-aligned divisor -> full dim

    def test_vmem_guard_refuses_impossible_blocks(self):
        assert dispatch.flash_vmem_ok(256, 256, 128, 2)
        assert not dispatch.flash_vmem_ok(8192, 8192, 256, 4)


# ------------------------------------- shape grid over the public entry
# Adversarial shapes: (b, sq, sk, hq, hkv, d). Includes the exact
# BENCH_r02 decode shape (4, 32, 8, 256) in BOTH layout readings —
# [B,Sq,Hq,D] and the [B,Hq,Sq,D] kernel layout it was logged in.
SHAPE_GRID = [
    (4, 32, 32, 8, 8, 256),     # BENCH_r02, API layout
    (4, 8, 8, 32, 32, 256),     # BENCH_r02, kernel-layout reading
    (2, 1, 1, 4, 2, 64),        # decode: single query token
    (1, 300, 300, 2, 2, 64),    # non-pow2, non-8-divisible seq
    (1, 48, 48, 4, 4, 64),      # tiny batch, sub-block seq
    (3, 24, 24, 2, 1, 128),     # odd batch + GQA
]


class TestShapeGrid:

    @pytest.mark.parametrize('shape', SHAPE_GRID,
                             ids=['x'.join(map(str, s))
                                  for s in SHAPE_GRID])
    def test_no_shape_raises_and_matches_reference(self, shape):
        """No grid shape may raise from the public ops entry point;
        golden numerics vs the XLA reference in interpreter mode."""
        b, sq, sk, hq, hkv, d = shape
        q, k, v = _qkv(b, sq, sk, hq, hkv, d)
        causal = sq == sk   # cross-length decode shapes: plain attn
        out = attention_ops.attention(q, k, v, causal=causal,
                                      impl='flash')
        ref = attention_ops.mha_reference(q, k, v, causal=causal)
        assert jnp.max(jnp.abs(out - ref)) < 2e-5
        # The grid shape must also be statically LEGAL on the Pallas
        # rung it took (the part interpreter mode cannot prove).
        bq, bk = dispatch.flash_blocks(sq, sk, flash_lib.DEFAULT_BLOCK_Q,
                                       flash_lib.DEFAULT_BLOCK_K,
                                       q.dtype, False)
        assert dispatch.block_dim_ok(bq, sq, 8)
        assert dispatch.block_dim_ok(bk, sk, 8)

    def test_bench_r02_shape_lowers_via_flash_impl(self):
        """The headline regression: (4, 32, 8, 256) decode-shaped
        arrays crashed Pallas lowering in r2. Assert the flash path is
        actually TAKEN (not silently descended past)."""
        dispatch.reset_for_tests()
        jax.clear_caches()   # path records at TRACE time; force one
        q, k, v = _qkv(4, 32, 32, 8, 8, 256, seed=7)
        out = attention_ops.attention(q, k, v, impl='flash')
        assert out.shape == q.shape
        assert dispatch.snapshot().get('flash_attention') == 'pallas'

    def test_grad_through_clamped_blocks(self):
        q, k, v = _qkv(1, 24, 24, 2, 2, 64, seed=3)
        g = jax.grad(lambda q_: flash_lib.flash_attention(
            q_, k, v).sum())(q)
        gr = jax.grad(lambda q_: attention_ops.mha_reference(
            q_, k, v).sum())(q)
        assert jnp.max(jnp.abs(g - gr)) < 2e-4

    def test_segment_ids_batch_gt_one(self):
        """Packed sequences with batch > 1: the [b, 1, s] lane-axis
        segment layout must be legal AND numerically golden."""
        q, k, v = _qkv(2, 64, 64, 4, 4, 64, seed=5)
        seg = jnp.stack([jnp.repeat(jnp.arange(2), 32),
                         jnp.repeat(jnp.arange(4), 16)]).astype(
                             jnp.int32)
        out = flash_lib.flash_attention(q, k, v, segment_ids=seg)
        ref = attention_ops.mha_reference(q, k, v, segment_ids=seg)
        assert jnp.max(jnp.abs(out - ref)) < 2e-5


# --------------------------------------------------- the fallback ladder
class TestLadder:

    def teardown_method(self):
        faults.reset()

    def test_chaos_fault_descends_to_xla(self):
        """SKYT_FAULTS=ops.lowering=error forces every Pallas rung to
        fail at trace time; the XLA floor must serve the exact
        reference output and the descent must be observable."""
        dispatch.reset_for_tests()
        faults.configure('ops.lowering=error')
        c = metrics_lib.REGISTRY.counter(
            'skyt_ops_kernel_path_total',
            'Kernel dispatch path selected at trace time',
            ('op', 'path'))
        before = c.value('flash_attention', 'xla')
        q, k, v = _qkv(1, 40, 40, 2, 2, 64, seed=11)  # fresh shape
        out = attention_ops.attention(q, k, v, impl='flash')
        ref = attention_ops.mha_reference(q, k, v)
        assert jnp.max(jnp.abs(out - ref)) < 1e-6
        assert dispatch.snapshot()['flash_attention'] == 'xla'
        assert c.value('flash_attention', 'xla') == before + 1

    def test_where_filter_targets_one_rung(self):
        """where=path:pallas kills only the default-block rung; the
        conservative full-array rung (present because 512 > the 256
        default block) must pick it up — partial degradation, not a
        collapse to XLA."""
        dispatch.reset_for_tests()
        faults.configure('ops.lowering=error,where=path:pallas')
        q, k, v = _qkv(1, 512, 512, 1, 1, 64, seed=13)
        out = attention_ops.attention(q, k, v, impl='flash')
        ref = attention_ops.mha_reference(q, k, v)
        assert jnp.max(jnp.abs(out - ref)) < 2e-5
        assert dispatch.snapshot()['flash_attention'] == 'pallas_full'

    def test_final_rung_never_fault_injected(self):
        """The XLA floor is the correctness guarantee: an armed
        ops.lowering fault must not be able to kill it."""
        faults.configure('ops.lowering=error')
        out = dispatch.run_ladder('t_final', [('xla', lambda: 42)])
        assert out == 42

    def test_forced_path_env(self, monkeypatch):
        monkeypatch.setenv('SKYT_OPS_FORCE_PATH', 'xla')
        dispatch.reset_for_tests()
        q, k, v = _qkv(1, 56, 56, 2, 2, 64, seed=17)
        out = attention_ops.attention(q, k, v, impl='flash')
        ref = attention_ops.mha_reference(q, k, v)
        assert jnp.max(jnp.abs(out - ref)) < 1e-6
        assert dispatch.snapshot()['flash_attention'] == 'xla'


# -------------------------------------------------------- autotune cache
class TestAutotune:

    def _arm(self, monkeypatch, tmp_path):
        path = str(tmp_path / 'autotune.json')
        monkeypatch.setenv('SKYT_AUTOTUNE', '1')
        monkeypatch.setenv('SKYT_AUTOTUNE_CACHE', path)
        monkeypatch.setenv('SKYT_AUTOTUNE_REPEATS', '1')
        autotune.reset_for_tests()
        return path

    def teardown_method(self):
        autotune.reset_for_tests()

    def test_sweep_once_then_cache_hit(self, monkeypatch, tmp_path):
        """Acceptance: a repeated invocation with the same
        (device_kind, shape-bucket, dtype) key is a cache HIT — no
        re-sweep — and the winner survives a 'process restart'
        (in-memory copy dropped, reloaded from disk)."""
        path = self._arm(monkeypatch, tmp_path)
        sweeps = metrics_lib.REGISTRY.counter(
            'skyt_ops_autotune_sweeps_total',
            'Autotune block-size sweeps executed', ('op',))
        hits = metrics_lib.REGISTRY.counter(
            'skyt_ops_autotune_cache_hits_total',
            'Autotune cache hits (sweep skipped)', ('op',))
        s0 = sweeps.value('flash_attention')
        h0 = hits.value('flash_attention')
        q, k, v = _qkv(1, 16, 16, 2, 2, 32, seed=19)
        attention_ops.attention(q, k, v, impl='flash')
        assert sweeps.value('flash_attention') == s0 + 1
        data = json.load(open(path))
        assert data['version'] == 1 and data['entries']
        (key, entry), = data['entries'].items()
        assert 'flash_attention' in key and 'float32' in key
        assert entry['block_q'] and entry['block_k']

        # Same key again: hit, no re-sweep (different VALUES, same
        # shape bucket).
        q2, k2, v2 = _qkv(1, 16, 16, 2, 2, 32, seed=23)
        attention_ops.attention(q2, k2, v2, impl='flash')
        assert sweeps.value('flash_attention') == s0 + 1
        assert hits.value('flash_attention') == h0 + 1

        # 'New process': drop memory, read back from disk.
        autotune.get_cache().forget_loaded()
        got = autotune.lookup_flash(q.shape, k.shape, q.dtype,
                                    True, False, 0)
        assert got == (entry['block_q'], entry['block_k'])

    def test_corrupt_cache_degrades_to_cold_start(self, monkeypatch,
                                                  tmp_path):
        """Acceptance: a corrupted cache file is a cold start, never a
        raise — and the next sweep REWRITES it atomically."""
        path = self._arm(monkeypatch, tmp_path)
        q, k, v = _qkv(1, 16, 16, 2, 2, 32, seed=29)
        attention_ops.attention(q, k, v, impl='flash')
        with open(path, 'w') as f:
            f.write('{"version": 1, "entries": {trailing garbage')
        autotune.reset_for_tests()
        assert autotune.lookup_flash(q.shape, k.shape, q.dtype,
                                     True, False, 0) is None
        # Re-tunes and leaves a valid file behind.
        attention_ops.attention(q, k, v, impl='flash')
        data = json.load(open(path))
        assert data['entries']

    def test_unexpected_layouts_are_cold_starts(self, monkeypatch,
                                                tmp_path):
        path = self._arm(monkeypatch, tmp_path)
        for payload in ('[]', '{"version": 99, "entries": {}}',
                        '{"entries": 3}', ''):
            with open(path, 'w') as f:
                f.write(payload)
            autotune.reset_for_tests()
            assert autotune.get_cache().get('k') is None

    def test_candidate_failure_is_skipped_not_propagated(
            self, monkeypatch, tmp_path):
        self._arm(monkeypatch, tmp_path)
        calls = []

        def run(cand):
            calls.append(cand)
            if cand != 'good':
                raise RuntimeError('boom')

        entry = autotune.sweep('t_op', 'k1', ['bad1', 'good', 'bad2'],
                               run, lambda c: {'pick': c})
        assert entry['pick'] == 'good'
        assert 'bad2' in calls   # sweep continued past the failure

    def test_all_candidates_failing_returns_none(self, monkeypatch,
                                                 tmp_path):
        self._arm(monkeypatch, tmp_path)
        calls = []

        def run(cand):
            calls.append(cand)
            raise RuntimeError('boom')

        assert autotune.sweep('t_op2', 'k2', [1, 2], run,
                              lambda c: {}) is None
        # The failure is negative-cached: a later sweep for the same
        # key must NOT re-run the (minutes-on-device) failing sweep,
        # and the poisoned entry reads as a miss for block lookups.
        n = len(calls)
        assert autotune.sweep('t_op2', 'k2', [1, 2], run,
                              lambda c: {}) == {'failed': True}
        assert len(calls) == n   # no candidate re-executed
        assert autotune.get_cache().get('k2') == {'failed': True}

    def test_disabled_is_a_noop(self, monkeypatch, tmp_path):
        path = str(tmp_path / 'never.json')
        monkeypatch.delenv('SKYT_AUTOTUNE', raising=False)
        monkeypatch.setenv('SKYT_AUTOTUNE_CACHE', path)
        autotune.reset_for_tests()
        q, k, v = _qkv(1, 16, 16, 2, 2, 32, seed=31)
        attention_ops.attention(q, k, v, impl='flash')
        assert not os.path.exists(path)


# ------------------------------------- chaos: ops.lowering mid-serve
@pytest.mark.integration
def test_mid_serve_lowering_chaos_zero_5xx():
    """Acceptance drill: a serve burst with SKYT_FAULTS=
    ops.lowering=error armed — every Pallas rung refuses to lower, the
    engine compiles onto the XLA floor, and ALL requests complete with
    output identical to an unfaulted replica's. Zero client-visible
    5xx, skyt_ops_kernel_path_total{path="xla"} > 0."""
    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(('127.0.0.1', 0))
            return s.getsockname()[1]

    ports = [free_port(), free_port()]
    envs = [{'SKYT_FAULTS': 'ops.lowering=error'}, {}]
    procs = []
    for port, extra in zip(ports, envs):
        env = dict(os.environ, JAX_PLATFORMS='cpu', **extra)
        procs.append(subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.infer.server',
             '--model', 'debug', '--port', str(port),
             '--num-slots', '2', '--max-seq-len', '64'],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL))
    urls = [f'http://127.0.0.1:{p}' for p in ports]
    try:
        for proc, url in zip(procs, urls):
            deadline = time.time() + 240
            while time.time() < deadline:
                if proc.poll() is not None:
                    raise AssertionError(
                        f'replica died rc={proc.returncode}')
                try:
                    if requests.get(url + '/health',
                                    timeout=2).status_code == 200:
                        break
                except requests.RequestException:
                    pass
                time.sleep(0.5)
            else:
                raise AssertionError('replica never became healthy')

        # Burst at the FAULTED replica (concurrent, mid-stream).
        results = [None] * 8
        def one(i):
            r = requests.post(
                urls[0] + '/generate',
                json={'tokens': [i % 4 + 1, 5, 9], 'max_tokens': 6},
                timeout=120)
            results[i] = (r.status_code, r.json().get('tokens'))
        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert all(r is not None for r in results), results
        bad = [r for r in results if r[0] != 200]
        assert not bad, f'client-visible failures: {bad}'

        # Correctness through the degraded path: the unfaulted replica
        # (same deterministic debug init) must emit identical tokens.
        for i in (0, 1, 2, 3):
            want = requests.post(
                urls[1] + '/generate',
                json={'tokens': [i % 4 + 1, 5, 9], 'max_tokens': 6},
                timeout=120).json()['tokens']
            assert results[i][1] == want, (i, results[i][1], want)

        # The descent is observable: faulted replica compiled onto the
        # XLA rung; the clean one is on Pallas.
        text = requests.get(urls[0] + '/metrics', timeout=5).text
        xla = [l for l in text.splitlines()
               if l.startswith('skyt_ops_kernel_path_total')
               and 'path="xla"' in l]
        assert xla and any(float(l.rsplit(' ', 1)[1]) > 0
                           for l in xla), text[:2000]
        assert 'skyt_faults_fired_total{' in text
        stats = requests.get(urls[0] + '/stats', timeout=5).json()
        assert 'xla' in stats['kernel_paths'].values()
        clean = requests.get(urls[1] + '/metrics', timeout=5).text
        assert any(
            l.startswith('skyt_ops_kernel_path_total')
            and 'path="pallas' in l and float(l.rsplit(' ', 1)[1]) > 0
            for l in clean.splitlines())
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
