"""Pipeline parallelism + collectives benchmark tests (8-device CPU
mesh)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.parallel import collectives
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import pipeline

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


def _mesh(pp):
    spec = mesh_lib.MeshSpec(pp=pp)
    return mesh_lib.build_mesh(spec, jax.devices()[:pp])


def _stage_fn(params, x):
    return jnp.tanh(x @ params['w'] + params['b'])


def _make_params(key, num_stages, dim):
    per_stage = []
    for i in range(num_stages):
        k1, k2, key = jax.random.split(key, 3)
        per_stage.append({
            'w': jax.random.normal(k1, (dim, dim)) * 0.3,
            'b': jax.random.normal(k2, (dim,)) * 0.1,
        })
    return pipeline.stack_stage_params(per_stage), per_stage


def _sequential(per_stage, x):
    for p in per_stage:
        x = _stage_fn(p, x)
    return x


@pytest.mark.parametrize('pp,m', [(2, 4), (4, 8)])
def test_pipeline_matches_sequential(pp, m):
    mesh = _mesh(pp)
    dim, bm = 8, 2
    stacked, per_stage = _make_params(jax.random.PRNGKey(0), pp, dim)
    xs = jax.random.normal(jax.random.PRNGKey(1), (m, bm, dim))
    out = pipeline.pipeline_apply(_stage_fn, stacked, xs, mesh)
    want = jnp.stack([_sequential(per_stage, xs[i]) for i in range(m)])
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grad_matches_sequential():
    pp, m, dim, bm = 2, 4, 6, 2
    mesh = _mesh(pp)
    stacked, per_stage = _make_params(jax.random.PRNGKey(2), pp, dim)
    batch = jax.random.normal(jax.random.PRNGKey(3), (m * bm, dim))
    targets = jax.random.normal(jax.random.PRNGKey(4), (m * bm, dim))

    loss = pipeline.pipeline_loss_fn(
        _stage_fn, lambda y, t: jnp.mean((y - t) ** 2), mesh,
        num_microbatches=m)
    g_pipe = jax.grad(loss)(stacked, batch, targets)

    def seq_loss(stacked_params):
        per = [jax.tree.map(lambda l, i=i: l[i], stacked_params)
               for i in range(pp)]
        y = _sequential(per, batch)
        return jnp.mean((y - targets) ** 2)

    g_seq = jax.grad(seq_loss)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g_pipe, g_seq)


def test_pipeline_rejects_too_few_microbatches():
    mesh = _mesh(4)
    stacked, _ = _make_params(jax.random.PRNGKey(0), 4, 4)
    xs = jnp.zeros((2, 1, 4))
    with pytest.raises(ValueError):
        pipeline.pipeline_apply(_stage_fn, stacked, xs, mesh)


def test_microbatch_roundtrip():
    x = jnp.arange(24).reshape(12, 2)
    mb = pipeline.microbatch(x, 4)
    assert mb.shape == (4, 3, 2)
    np.testing.assert_array_equal(np.asarray(pipeline.unmicrobatch(mb)),
                                  np.asarray(x))
    with pytest.raises(ValueError):
        pipeline.microbatch(x, 5)


def test_collectives_bench_smoke():
    n = min(8, len(jax.devices()))
    spec = mesh_lib.MeshSpec(tp=n)
    mesh = mesh_lib.build_mesh(spec, jax.devices()[:n])
    rows = collectives.bench_all(mesh, 'tp', payload_mb=0.5)
    assert {r['op'] for r in rows} == {'all_reduce', 'all_gather',
                                       'reduce_scatter', 'ppermute'}
    for r in rows:
        assert r['ranks'] == n
        assert r['time_ms'] > 0
        assert r['algbw_gbps'] > 0
