"""Tracing subsystem (utils/tracing.py): span store concurrency, ring
eviction, W3C traceparent round-trips, flight-recorder retention vs
head-sampling, and the zero-overhead disabled path. Pure host-side —
no jax, no HTTP (the serving integration lives in
tests/test_server_metrics.py).
"""
import threading
import time

import pytest

from skypilot_tpu.utils import metrics as metrics_lib
from skypilot_tpu.utils import tracing


def _tracer(service='test', **store_kwargs):
    reg = metrics_lib.MetricsRegistry()
    store = tracing.SpanStore(**store_kwargs) if store_kwargs else None
    return tracing.Tracer(service=service, registry=reg,
                          store=store), reg


@pytest.fixture(autouse=True)
def _trace_env(monkeypatch):
    """Deterministic defaults: tracing on, sample everything, nothing
    is 'slow' unless a test lowers the threshold."""
    monkeypatch.setenv('SKYT_TRACE', '1')
    monkeypatch.setenv('SKYT_TRACE_SAMPLE', '1')
    monkeypatch.setenv('SKYT_TRACE_SLOW_MS', '60000')


# ------------------------------------------------------------ model
def test_span_nesting_and_context_propagation():
    t, _ = _tracer()
    with t.start_span('root') as root:
        assert tracing.current_span() is root
        with t.start_span('child') as child:
            assert tracing.current_span() is child
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
            child.add_event('mark', detail=7)
        assert tracing.current_span() is root
    assert tracing.current_span() is None
    rec = t.store.trace(root.trace_id)
    assert rec is not None and not rec.get('open')
    names = {s['name']: s for s in rec['spans']}
    assert set(names) == {'root', 'child'}
    assert names['child']['events'][0]['name'] == 'mark'
    assert names['child']['events'][0]['detail'] == 7
    assert rec['duration_ms'] >= names['child']['duration_ms']


def test_span_end_idempotent_and_exception_attr():
    t, _ = _tracer()
    with pytest.raises(RuntimeError):
        with t.start_span('boom') as span:
            raise RuntimeError('kaput')
    span.end()   # second end is a no-op, not a double record
    rec = t.store.trace(span.trace_id)
    assert len(rec['spans']) == 1
    assert 'kaput' in rec['spans'][0]['attributes']['error']


def test_record_span_manual_timing_parents_under_current():
    t, _ = _tracer()
    with t.start_span('root') as root:
        t.record_span('engine.phase', root.start, root.start + 0.25,
                      attributes={'rid': 3},
                      events=[{'name': 'chunk', 'ts': root.start + .1}])
    rec = t.store.trace(root.trace_id)
    phase = next(s for s in rec['spans'] if s['name'] == 'engine.phase')
    assert phase['parent_id'] == root.span_id
    assert phase['duration_ms'] == pytest.approx(250, abs=1)
    assert phase['events'][0]['name'] == 'chunk'


def test_event_cap_is_bounded():
    t, _ = _tracer()
    with t.start_span('root') as root:
        for i in range(500):
            root.add_event(f'e{i}')
    rec = t.store.trace(root.trace_id)
    sd = rec['spans'][0]
    assert len(sd['events']) == 64
    assert sd['dropped_events'] == 500 - 64


# ----------------------------------------------------- traceparent
def test_traceparent_inject_extract_roundtrip():
    t, _ = _tracer()
    span = t.start_span('root')
    headers = {}
    t.inject(headers, span)
    span.end()
    tp = headers['traceparent']
    assert tp == f'00-{span.trace_id}-{span.span_id}-01'
    ctx = t.extract(headers)
    assert ctx == tracing.SpanContext(span.trace_id, span.span_id,
                                      True)
    # Unsampled roots propagate flags 00 -> sampled False.
    with pytest.MonkeyPatch.context() as mp:
        mp.setenv('SKYT_TRACE_SAMPLE', '0')
        span2 = t.start_span('r2')
        h2 = t.inject({}, span2)
        span2.end()
        assert h2['traceparent'].endswith('-00')
        assert t.extract(h2).sampled is False


@pytest.mark.parametrize('bad', [
    '',
    'garbage',
    '00-abc-def-01',                                       # wrong widths
    '00-' + '0' * 32 + '-' + 'a' * 16 + '-01',             # zero trace
    '00-' + 'a' * 32 + '-' + '0' * 16 + '-01',             # zero span
    'ff-' + 'a' * 32 + '-' + 'b' * 16 + '-01',             # version ff
    '00-' + 'A' * 32 + '-' + 'b' * 16 + '-01',             # uppercase
    '00-' + 'a' * 32 + '-' + 'b' * 16 + '-zz',             # bad flags
    '00-' + 'a' * 32 + '-' + 'b' * 16,                     # truncated
    '00-' + 'a' * 32 + '-' + 'b' * 16 + '-01-x',   # v00 extra field
])
def test_traceparent_malformed_rejected(bad):
    t, _ = _tracer()
    assert t.extract({'traceparent': bad}) is None


def test_traceparent_future_version_accepted():
    """W3C forward compatibility: a version > 00 header with trailing
    fields parses from its first four fields."""
    t, _ = _tracer()
    ctx = t.extract({'traceparent':
                     '01-' + 'a' * 32 + '-' + 'b' * 16 + '-01-future'})
    assert ctx == tracing.SpanContext('a' * 32, 'b' * 16, True)
    # Without the suffix too.
    ctx = t.extract({'traceparent':
                     'cc-' + 'a' * 32 + '-' + 'b' * 16 + '-00'})
    assert ctx is not None and ctx.sampled is False


def test_local_sample_rate_upgrades_unsampled_remote_parent(
        monkeypatch):
    """Flipping ONE replica to SKYT_TRACE_SAMPLE=1 mid-incident must
    retain its traces even when the LB upstream samples at 0 (the
    traceparent arrives with flags 00)."""
    t, _ = _tracer()
    remote = tracing.SpanContext('c' * 32, 'd' * 16, False)
    monkeypatch.setenv('SKYT_TRACE_SAMPLE', '0')
    s0 = t.start_span('server', parent=remote)
    s0.end()
    assert s0.sampled is False           # nothing local boosts it
    monkeypatch.setenv('SKYT_TRACE_SAMPLE', '1')
    s1 = t.start_span('server', parent=remote)
    s1.end()
    assert s1.sampled is True            # local upgrade
    assert t.store.trace('c' * 32) is not None
    # An upstream sampled=true always propagates regardless of rate.
    monkeypatch.setenv('SKYT_TRACE_SAMPLE', '0')
    s2 = t.start_span('server', parent=remote._replace(sampled=True))
    s2.end()
    assert s2.sampled is True


def test_extract_missing_or_nonstring_header():
    t, _ = _tracer()
    assert t.extract({}) is None
    assert t.extract({'traceparent': None}) is None
    # Remote parent continues the trace and marks a local root.
    ctx = tracing.SpanContext('a' * 32, 'b' * 16, True)
    span = t.start_span('server', parent=ctx)
    assert span.trace_id == 'a' * 32
    assert span.parent_id == 'b' * 16
    assert span.local_root
    span.end()
    assert t.store.trace('a' * 32) is not None


# ------------------------------------- flight recorder vs sampling
def test_head_sampling_off_drops_fast_traces(monkeypatch):
    monkeypatch.setenv('SKYT_TRACE_SAMPLE', '0')
    t, reg = _tracer()
    with t.start_span('fast'):
        pass
    assert t.store.summaries() == {'recent': [], 'slow': []}
    # The drop is observable, not silent.
    assert reg.get('skyt_trace_dropped_total').value('test') == 1
    assert reg.get('skyt_trace_spans_total').value('test') == 1


def test_slow_trace_always_retained_with_snapshot(monkeypatch):
    monkeypatch.setenv('SKYT_TRACE_SAMPLE', '0')    # sampling OFF
    monkeypatch.setenv('SKYT_TRACE_SLOW_MS', '5')
    t, _ = _tracer()
    t.store.slow_snapshot = lambda: {'queue_depth': 3, 'running': 2}
    with t.start_span('slow.request') as span:
        with t.start_span('hop'):
            time.sleep(0.02)
    summ = t.store.summaries()
    assert summ['recent'] and summ['slow']   # slow implies retained
    assert summ['slow'][0]['trace_id'] == span.trace_id
    rec = t.store.trace(span.trace_id)
    assert rec['slow'] is True
    assert rec['state_snapshot'] == {'queue_depth': 3, 'running': 2}
    assert {s['name'] for s in rec['spans']} == {'slow.request', 'hop'}


def test_snapshot_hook_failure_does_not_lose_the_trace(monkeypatch):
    monkeypatch.setenv('SKYT_TRACE_SLOW_MS', '0')
    t, _ = _tracer()

    def bad_hook():
        raise RuntimeError('engine gone')
    t.store.slow_snapshot = bad_hook
    with t.start_span('r'):
        time.sleep(0.001)
    rec = t.store.summaries()['slow'][0]
    full = t.store.trace(rec['trace_id'])
    assert 'engine gone' in full['state_snapshot']['error']


def test_malformed_env_falls_back(monkeypatch):
    monkeypatch.setenv('SKYT_TRACE_SAMPLE', 'lots')
    monkeypatch.setenv('SKYT_TRACE_SLOW_MS', 'soon')
    assert tracing.sample_rate() == 0.0
    assert tracing.slow_threshold_ms() == 500.0


# ------------------------------------------------- disabled no-op
def test_disabled_is_noop(monkeypatch):
    monkeypatch.setenv('SKYT_TRACE', '0')
    t, reg = _tracer()
    span = t.start_span('x', attributes={'a': 1})
    assert span is tracing.NOOP_SPAN          # shared singleton
    with span as s:
        s.add_event('e')
        s.set_attribute('k', 'v')
    assert t.inject({}, span) == {}           # nothing to propagate
    t.record_span('y', 0.0, 1.0)
    assert t.store.summaries() == {'recent': [], 'slow': []}
    assert reg.get('skyt_trace_spans_total').value('test') == 0
    # current-span context is untouched by no-op spans.
    assert tracing.current_span() is None


# ------------------------------------------------ bounds / eviction
def test_recent_ring_eviction_under_load():
    t, reg = _tracer(max_recent=8)
    ids = []
    for i in range(32):
        with t.start_span(f'r{i}') as s:
            ids.append(s.trace_id)
    summ = t.store.summaries()
    assert len(summ['recent']) == 8
    kept = [r['trace_id'] for r in summ['recent']]
    assert kept == list(reversed(ids[-8:]))   # newest first, FIFO evict
    assert reg.get('skyt_trace_dropped_total').value('test') == 24
    for tid in ids[:24]:
        assert t.store.trace(tid) is None


def test_open_trace_table_is_bounded():
    t, reg = _tracer(max_open=4)
    # Children whose local root never ends (crashed handlers) must not
    # leak: the open table evicts FIFO past its bound.
    ctxs = [tracing.SpanContext(f'{i:032x}', 'b' * 16, True)
            for i in range(1, 9)]
    for ctx in ctxs:
        t.record_span('child', 0.0, 0.001, parent=ctx)
    assert reg.get('skyt_trace_dropped_total').value('test') >= 4
    # A surviving trace still finishes normally when its root arrives.
    t.start_span('root', parent=ctxs[-1]).end()
    assert t.store.trace(ctxs[-1].trace_id) is not None


def test_spans_per_trace_cap():
    t, reg = _tracer(max_spans_per_trace=10)
    with t.start_span('root') as root:
        for _ in range(50):
            with t.start_span('c'):
                pass
    rec = t.store.trace(root.trace_id)
    assert len(rec['spans']) == 10
    assert reg.get('skyt_trace_dropped_total').value('test') >= 40


def test_store_concurrency_hammer():
    """8 threads x 50 traces x 3 spans against one small store: no
    exceptions, counters exact, rings bounded."""
    t, reg = _tracer(max_recent=16, max_slow=4)
    errors = []

    def worker(k):
        try:
            for i in range(50):
                with t.start_span(f'w{k}.{i}') as root:
                    with t.start_span('a'):
                        pass
                    t.record_span('b', root.start, root.start + .001)
        except Exception as e:  # pylint: disable=broad-except
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    assert reg.get('skyt_trace_spans_total').value('test') == \
        8 * 50 * 3
    summ = t.store.summaries()
    assert len(summ['recent']) == 16
    assert len(summ['slow']) <= 4
    # recorded + dropped covers every span that went in.
    dropped = reg.get('skyt_trace_dropped_total').value('test')
    retained = sum(r['n_spans'] for r in summ['recent'])
    assert dropped + retained == 8 * 50 * 3


# ------------------------------------------------------ export
def test_chrome_trace_export_shape():
    t, _ = _tracer()
    with t.start_span('root') as root:
        with t.start_span('child') as c:
            c.add_event('mark')
    dump = t.chrome_trace(root.trace_id)
    evs = dump['traceEvents']
    xs = [e for e in evs if e['ph'] == 'X']
    marks = [e for e in evs if e['ph'] == 'i']
    assert {e['name'] for e in xs} == {'root', 'child'}
    assert marks[0]['name'] == 'mark'
    for e in xs:
        assert e['dur'] >= 0 and e['cat'] == 'skyt.trace'
        assert e['args']['trace_id'] == root.trace_id
    # Unknown trace id -> empty dump, not an error.
    assert t.chrome_trace('f' * 32) == {'traceEvents': []}


def test_timeline_bridge(monkeypatch):
    """utils/timeline.py B/E events re-emit as spans when SKYT_DEBUG
    is on — the client-op plane lands in the shared store."""
    from skypilot_tpu.utils import timeline
    monkeypatch.setenv('SKYT_DEBUG', '1')
    timeline.reset()
    before = len(tracing.TRACER.store.records())
    with timeline.Event('op.launch'):
        time.sleep(0.001)
    recs = tracing.TRACER.store.records()
    assert len(recs) > before
    names = [s['name'] for r in recs for s in r['spans']]
    assert 'timeline:op.launch' in names


# ---------------------------------------------- metrics satellite
def test_histogram_time_context_manager():
    reg = metrics_lib.MetricsRegistry()
    h = reg.histogram('t_seconds', 'help')
    with h.time():
        time.sleep(0.01)
    sample = h.sample_dicts()[0]
    assert sample['count'] == 1
    assert 0.005 < sample['sum'] < 5.0
    # Labeled children time independently; the exception path still
    # observes (error latency is latency).
    hl = reg.histogram('t2_seconds', 'help', ('route',))
    with pytest.raises(ValueError):
        with hl.labels('/a').time():
            raise ValueError('x')
    assert hl.sample_dicts()[0]['count'] == 1
    assert hl.sample_dicts()[0]['labels'] == {'route': '/a'}
