"""Capacity-plane unit tests (docs/observability.md "Capacity
plane"): the workload engine's determinism contract (same seed =>
byte-identical schedule), arrival-process shape, session-reuse
mechanics, capacity-search convergence on a closed-form attainment
model, and the busy-ledger's sums-to-busy-time invariant.

The end-to-end half (real replica + real LB tier) lives in bench.py's
capacity phase and tests/test_chaos.py's flash-crowd drill.
"""
import math

import pytest

from skypilot_tpu.benchmark import capacity
from skypilot_tpu.benchmark import workload
from skypilot_tpu.infer import ledger as ledger_lib
from skypilot_tpu.utils import faults
from skypilot_tpu.utils import metrics as metrics_lib

MIX = (
    workload.TenantProfile(tenant='acme', cls='interactive',
                           weight=2.0, session_pool=4,
                           session_reuse=0.6),
    workload.TenantProfile(tenant='burst', cls='batch',
                           model='adapter-a', weight=1.0,
                           prompt_mean=128.0, output_mean=64.0),
)


def _spec(**kw):
    base = dict(seed=7, duration_s=20.0, rate_rps=5.0,
                arrival='poisson', tenants=MIX)
    base.update(kw)
    return workload.WorkloadSpec(**base)


# ------------------------------------------------------- determinism
def test_same_seed_byte_identical_schedule():
    a = workload.generate_schedule(_spec())
    b = workload.generate_schedule(_spec())
    assert workload.schedule_json(a) == workload.schedule_json(b)
    assert workload.schedule_digest(a) == workload.schedule_digest(b)
    assert len(a) > 10


def test_different_seed_different_schedule():
    a = workload.generate_schedule(_spec(seed=7))
    b = workload.generate_schedule(_spec(seed=8))
    assert workload.schedule_digest(a) != workload.schedule_digest(b)


def test_schedule_is_compression_independent():
    # Compression scales when arrivals FIRE, never the schedule: the
    # spec has no compression knob at all, so the digest cannot
    # depend on it. Pin that the digest keys on (seed, process, mix).
    d1 = workload.schedule_digest(workload.generate_schedule(_spec()))
    d2 = workload.schedule_digest(
        workload.generate_schedule(_spec(rate_rps=6.0)))
    assert d1 != d2


# -------------------------------------------------- arrival processes
def test_steady_arrivals_evenly_spaced():
    sched = workload.generate_schedule(
        _spec(arrival='steady', rate_rps=10.0, duration_s=2.0))
    assert len(sched) == 20
    gaps = [b.t - a.t for a, b in zip(sched, sched[1:])]
    assert all(abs(g - 0.1) < 1e-9 for g in gaps)


def test_poisson_count_tracks_rate():
    spec = _spec(duration_s=200.0, rate_rps=10.0)
    n = len(workload.generate_schedule(spec))
    # mean 2000, sd ~45 — +/-5 sd keeps this deterministic-seed test
    # robust to spec tweaks without being vacuous.
    assert 1775 < n < 2225


def test_flash_crowd_multiplies_arrivals_in_window():
    spec = _spec(duration_s=60.0, rate_rps=5.0, flash_at_s=20.0,
                 flash_factor=10.0, flash_duration_s=10.0)
    sched = workload.generate_schedule(spec)
    inside = sum(1 for a in sched if 20.0 <= a.t < 30.0)
    before = sum(1 for a in sched if a.t < 20.0)
    # 10s at 50 rps vs 20s at 5 rps: ~500 vs ~100.
    assert inside > 3 * before
    assert spec.rate_at(25.0) == pytest.approx(50.0)
    assert spec.rate_at(35.0) == pytest.approx(5.0)


def test_diurnal_modulation_shapes_rate():
    spec = _spec(diurnal_amplitude=0.5, diurnal_period_s=100.0)
    assert spec.rate_at(25.0) == pytest.approx(7.5)   # sin peak
    assert spec.rate_at(75.0) == pytest.approx(2.5)   # sin trough
    assert spec.peak_rate() == pytest.approx(7.5)


def test_unknown_arrival_process_rejected():
    with pytest.raises(ValueError, match='unknown arrival'):
        workload.generate_schedule(_spec(arrival='bursty'))


# ------------------------------------------------------ session reuse
def test_session_reuse_shares_prefix_and_bounds_pool():
    spec = _spec(duration_s=100.0, tenants=(
        workload.TenantProfile(tenant='acme', session_pool=3,
                               session_reuse=0.7, prefix_len=8),))
    sched = workload.generate_schedule(spec)
    # Pool is bounded: session NAMES cycle through at most 3 slots.
    assert len({a.session for a in sched}) <= 3
    # A reused session resends its prefix verbatim (that's what LB
    # affinity and the prefix cache key on): a solid fraction of
    # arrivals repeat an already-seen (session, prefix) pair.
    seen, reused = set(), 0
    for a in sched:
        pair = (a.session, a.prompt_tokens[:8])
        if pair in seen:
            reused += 1
        seen.add(pair)
    assert reused > 0.3 * len(sched)


def test_lengths_respect_caps():
    spec = _spec(duration_s=100.0, tenants=(
        workload.TenantProfile(tenant='t', prompt_mean=600.0,
                               prompt_sigma=1.5, prompt_cap=64,
                               output_mean=400.0, output_cap=16),))
    for a in workload.generate_schedule(spec):
        assert 1 <= len(a.prompt_tokens) <= 64
        assert 1 <= a.max_new_tokens <= 16


# ---------------------------------------------------- open-loop runner
def test_open_loop_runner_fires_all_and_respects_faults():
    sched = workload.generate_schedule(
        _spec(duration_s=4.0, rate_rps=10.0))
    seen = []

    def submit(a):
        seen.append(a.index)
        return (200, 0.01, 0.02, a.max_new_tokens)

    faults.configure('traffic.arrival=error,where=tenant:burst')
    try:
        runner = workload.OpenLoopRunner(submit, compression=40.0)
        outcomes = runner.run(sched)
    finally:
        faults.reset()
    assert len(outcomes) == len(sched)
    dropped = [o for o in outcomes if o.error
               and o.error.startswith('fault:')]
    assert dropped and all(
        o.arrival.tenant == 'burst' for o in dropped)
    ok = [o for o in outcomes if o.status == 200]
    assert len(ok) + len(dropped) == len(sched)
    assert sorted(seen) == sorted(o.arrival.index for o in ok)
    summary = workload.summarize(outcomes, compression=40.0)
    assert summary['offered'] == len(sched)
    assert summary['ok'] == len(ok)
    assert summary['classes']['batch']['transport_errors'] == \
        len(dropped)


# -------------------------------------------------- capacity search
def test_capacity_search_converges_on_closed_form():
    # Transient M/M/1-flavored attainment: with service rate mu and
    # window T, P(a request is good) ~ 1 - exp(-(mu - r) * T) for
    # r < mu. Solving attainment(r*) = target gives
    # r* = mu - ln(1/(1-target)) / T — a closed form the search must
    # land on without knowing it.
    mu, t_win, target = 100.0, 1.0, 0.99
    r_star = mu - math.log(1.0 / (1.0 - target)) / t_win

    def measure(rate):
        return max(0.0, 1.0 - math.exp(-(mu - rate) * t_win)) \
            if rate < mu else 0.0

    res = capacity.capacity_search(
        measure, target=target, rate_lo=1.0, rate_hi=4096.0,
        resolution=0.02)
    assert res.max_sustained_qps <= r_star + 1e-9
    assert res.bracket_hi is not None and res.bracket_hi > r_star
    # Bisection stops at 2% relative bracket width.
    assert (r_star - res.max_sustained_qps) <= \
        0.025 * res.max_sustained_qps
    assert res.slo_attainment >= target
    assert len(res.trials) <= 20
    assert res.as_dict()['target'] == target


def test_capacity_search_zero_when_floor_fails():
    res = capacity.capacity_search(
        lambda rate: 0.5, target=0.99, rate_lo=1.0)
    assert res.max_sustained_qps == 0.0
    assert res.bracket_hi == 1.0
    assert res.trials[0].passed is False


def test_capacity_search_validates_inputs():
    with pytest.raises(ValueError, match='target'):
        capacity.capacity_search(lambda r: 1.0, target=1.5)
    with pytest.raises(ValueError, match='rate range'):
        capacity.capacity_search(lambda r: 1.0, rate_lo=8.0,
                                 rate_hi=2.0)


# ------------------------------------------------------- busy ledger
def test_ledger_attribution_sums_to_busy_time():
    led = ledger_lib.BusyLedger(metrics_lib.MetricsRegistry(),
                                enabled=True)
    k1 = ('interactive', 'acme', 'base')
    k2 = ('batch', 'burst', 'adapter-a')
    # Interval 1: 3:1 token split.
    led.note(k1, 30)
    led.note(k2, 10)
    led.settle(0.4)
    # Interval 2: only k2 works.
    led.note(k2, 5)
    led.settle(0.1)
    # Interval 3: busy but nothing attributable (all-cancelled chunk):
    # stays in the busy total, attributes to nobody.
    led.settle(0.25)
    snap = led.snapshot()
    assert snap['busy_seconds'] == pytest.approx(0.75)
    attr = snap['attributed_seconds']
    assert attr['interactive/acme/base'] == pytest.approx(0.3)
    assert attr['batch/burst/adapter-a'] == pytest.approx(0.2)
    # Sums-to-busy-time invariant, minus the honest unattributed gap.
    assert sum(attr.values()) == pytest.approx(0.5, abs=1e-6)
    assert snap['tokens'] == {'batch/burst/adapter-a': 15,
                              'interactive/acme/base': 30}


def test_ledger_disabled_is_inert():
    led = ledger_lib.BusyLedger(metrics_lib.MetricsRegistry(),
                                enabled=False)
    led.note(('a', 'b', 'c'), 10)
    led.settle(1.0)
    assert led.pending() is False
    assert led.snapshot()['busy_seconds'] == 0.0
