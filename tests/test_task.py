"""Task DSL + YAML tests (mirrors reference tests/test_yaml_parser.py and
unit task tests)."""
import textwrap

import pytest

from skypilot_tpu import Dag, Resources, Task, exceptions


def _write(tmp_path, content):
    p = tmp_path / 'task.yaml'
    p.write_text(textwrap.dedent(content))
    return str(p)


class TestTaskYaml:
    def test_minimal(self, tmp_path):
        t = Task.from_yaml(_write(tmp_path, """
            name: hello
            run: echo hi
        """))
        assert t.name == 'hello'
        assert t.run == 'echo hi'
        assert t.num_nodes == 1

    def test_empty_yaml(self, tmp_path):
        t = Task.from_yaml(_write(tmp_path, ""))
        assert t.run is None

    def test_tpu_derives_num_nodes(self, tmp_path):
        t = Task.from_yaml(_write(tmp_path, """
            resources:
              accelerators: tpu-v5e-16
            run: python train.py
        """))
        assert t.num_nodes == 4

    def test_num_nodes_conflict(self, tmp_path):
        with pytest.raises(exceptions.InvalidTaskError):
            Task.from_yaml(_write(tmp_path, """
                num_nodes: 2
                resources:
                  accelerators: tpu-v5e-16
            """)).num_nodes  # noqa: B018

    def test_env_substitution(self, tmp_path):
        t = Task.from_yaml(_write(tmp_path, """
            envs:
              MODEL: llama3-8b
            run: python train.py --model $MODEL --out ${MODEL}.ckpt
        """))
        assert t.run == 'python train.py --model llama3-8b --out llama3-8b.ckpt'

    def test_env_override_required(self, tmp_path):
        path = _write(tmp_path, """
            envs:
              HF_TOKEN:
            run: echo $HF_TOKEN
        """)
        with pytest.raises(exceptions.InvalidTaskError):
            Task.from_yaml(path)
        t = Task.from_yaml(path, env_overrides={'HF_TOKEN': 'abc'})
        assert t.run == 'echo abc'

    def test_unknown_field_rejected(self, tmp_path):
        with pytest.raises(exceptions.InvalidTaskError):
            Task.from_yaml(_write(tmp_path, """
                runn: echo typo
            """))

    def test_any_of_resources(self, tmp_path):
        t = Task.from_yaml(_write(tmp_path, """
            resources:
              use_spot: true
              any_of:
                - accelerators: tpu-v5e-16
                - accelerators: tpu-v6e-16
            run: echo hi
        """))
        assert len(t.resources) == 2
        assert all(r.use_spot for r in t.resources)

    def test_storage_mount_split(self, tmp_path):
        t = Task.from_yaml(_write(tmp_path, """
            file_mounts:
              /data: ./local_dir
              /ckpt:
                name: my-bucket
                mode: MOUNT
            run: ls /ckpt
        """))
        assert '/data' in t.file_mounts
        assert '/ckpt' in t.storage_mounts

    def test_round_trip(self, tmp_path):
        t = Task.from_yaml(_write(tmp_path, """
            name: rt
            resources:
              accelerators: tpu-v5e-8
              use_spot: true
            envs:
              A: b
            run: echo $A
        """))
        t2 = Task.from_yaml_config(t.to_yaml_config())
        assert t2.name == 'rt'
        assert next(iter(t2.resources)).use_spot
        assert t2.run == 'echo b'

    def test_service_spec(self, tmp_path):
        t = Task.from_yaml(_write(tmp_path, """
            service:
              readiness_probe: /health
              replica_policy:
                min_replicas: 2
                max_replicas: 5
                target_qps_per_replica: 2.5
            run: python -m server
        """))
        assert t.service.readiness_path == '/health'
        assert t.service.autoscaling_enabled


class TestDag:
    def test_chain(self):
        with Dag() as dag:
            a = Task('a', run='echo a')
            b = Task('b', run='echo b')
            c = Task('c', run='echo c')
            a >> b >> c
        assert len(dag) == 3
        assert dag.is_chain()
        assert dag.get_sorted_tasks() == [a, b, c]

    def test_non_chain(self):
        with Dag() as dag:
            a = Task('a', run='echo a')
            b = Task('b', run='echo b')
            c = Task('c', run='echo c')
            a >> c
            b >> c
        assert not dag.is_chain()

    def test_tasks_register_with_ambient_dag(self):
        with Dag() as dag:
            Task('solo', run='echo hi')
        assert len(dag.tasks) == 1

    def test_set_resources(self):
        t = Task('t', run='x')
        t.set_resources(Resources(accelerators='tpu-v5e-4'))
        assert t.num_nodes == 1


class TestReviewRegressions:
    """Regressions from the round-1 code review."""

    def test_config_not_mutated(self):
        cfg = {'resources': {'any_of': [{'accelerators': 'tpu-v5e-16'},
                                        {'accelerators': 'tpu-v6e-16'}]},
               'run': 'x'}
        t1 = Task.from_yaml_config(cfg)
        t2 = Task.from_yaml_config(cfg)
        assert len(t1.resources) == 2 and len(t2.resources) == 2

    def test_any_of_differing_hosts_rejected(self):
        with pytest.raises(exceptions.InvalidTaskError):
            Task.from_yaml_config(
                {'resources': {'any_of': [{'accelerators': 'tpu-v5e-16'},
                                          {'accelerators': 'tpu-v5e-8'}]},
                 'run': 'x'}).num_nodes  # noqa: B018

    def test_empty_string_env_is_legal(self):
        t = Task.from_yaml_config({'envs': {'EXTRA': ''}, 'run': 'echo $EXTRA'})
        assert t.envs['EXTRA'] == ''

    def test_scalar_ports(self):
        from skypilot_tpu import Resources
        assert Resources(ports=8080).ports == ['8080']
        assert Resources(ports='8080').ports == ['8080']
        assert Resources(ports=[8080, '9000-9010']).ports == ['8080',
                                                              '9000-9010']

    def test_dict_accelerator_bad_count(self):
        from skypilot_tpu import Resources
        with pytest.raises(exceptions.InvalidResourcesError):
            Resources(accelerators={'A100': 'eight'})

    def test_cycle_is_not_chain(self):
        with Dag() as dag:
            a = Task('a', run='x')
            b = Task('b', run='x')
            Task('c', run='x')
            a >> b
            b >> a
        assert not dag.is_chain()

    def test_service_spec_full_round_trip(self):
        from skypilot_tpu.serve.service_spec import ServiceSpec
        s = ServiceSpec(readiness_path='/h', probe_timeout_seconds=60,
                        min_replicas=1, max_replicas=3,
                        target_qps_per_replica=2.0,
                        upscale_delay_seconds=30,
                        downscale_delay_seconds=100,
                        base_ondemand_fallback_replicas=2)
        s2 = ServiceSpec.from_yaml_config(s.to_yaml_config())
        assert s2 == s


class TestPipelineYaml:
    """Multi-document pipeline YAML -> chain Dag (reference:
    sky/utils/dag_utils.py load_chain_dag_from_yaml)."""

    def test_load_example_pipeline(self):
        import os
        from skypilot_tpu import dag as dag_lib
        path = os.path.join(os.path.dirname(__file__), '..', 'examples',
                            'pipeline.yaml')
        dag = dag_lib.load_chain_dag_from_yaml(path)
        assert dag.name == 'tokenize-then-train'
        assert len(dag) == 2
        assert dag.is_chain()
        names = [t.name for t in dag.get_sorted_tasks()]
        assert names == ['tokenize', 'train']

    def test_yaml_is_pipeline(self, tmp_path):
        from skypilot_tpu import dag as dag_lib
        single = tmp_path / 'single.yaml'
        single.write_text('name: solo\nrun: echo hi\n')
        assert not dag_lib.yaml_is_pipeline(str(single))
        multi = tmp_path / 'multi.yaml'
        multi.write_text('name: pipe\n---\nname: a\nrun: echo a\n'
                         '---\nname: b\nrun: echo b\n')
        assert dag_lib.yaml_is_pipeline(str(multi))

    def test_empty_pipeline_raises(self, tmp_path):
        import pytest as _pytest
        from skypilot_tpu import dag as dag_lib
        p = tmp_path / 'empty.yaml'
        p.write_text('name: nothing\n')
        with _pytest.raises(ValueError, match='no task documents'):
            dag_lib.load_chain_dag_from_yaml(str(p))


def test_all_example_yamls_load():
    """Every recipe in examples/ parses through the real loaders:
    single-doc YAMLs as Tasks, multi-doc as chain Dags."""
    import glob
    import os
    from skypilot_tpu import dag as dag_lib
    ex_dir = os.path.join(os.path.dirname(__file__), '..', 'examples')
    paths = sorted(glob.glob(os.path.join(ex_dir, '*.yaml')))
    assert len(paths) >= 7
    for p in paths:
        if dag_lib.yaml_is_pipeline(p):
            dag = dag_lib.load_chain_dag_from_yaml(p)
            assert len(dag) >= 2 and dag.is_chain()
        else:
            t = Task.from_yaml(p)
            assert t.run
