"""Managed-jobs end-to-end tests on the local provider.

Covers the reference's controller behaviors (sky/jobs/controller.py watch
loop, recovery_strategy, signal cancellation) with real controller
subprocesses and real fault injection (tearing the job cluster down
mid-run to simulate a TPU preemption) — coverage the reference only gets
from cloud smoke tests (SURVEY.md §5 failure detection).
"""
import os
import time

import pytest

import skypilot_tpu as sky
from skypilot_tpu import core
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import state
from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs import recovery_strategy
from skypilot_tpu.jobs import state as jobs_state

pytestmark = pytest.mark.integration


@pytest.fixture()
def jobs_env(tmp_path, tmp_state_dir, monkeypatch):
    monkeypatch.setenv('SKYT_LOCAL_ROOT', str(tmp_path / 'local'))
    monkeypatch.setenv('SKYT_LOCAL_STORAGE_ROOT', str(tmp_path / 'buckets'))
    monkeypatch.setenv('SKYT_DEFAULT_STORE', 'local')
    monkeypatch.setenv('SKYT_JOBS_CHECK_GAP', '0.3')
    monkeypatch.setenv('SKYT_JOBS_PREEMPTION_GRACE', '1')
    state.reset_db_for_testing()
    jobs_state.reset_db_for_testing()
    yield
    for job in jobs_state.get_jobs():
        if not job['status'].is_terminal():
            try:
                jobs_core.cancel([job['job_id']])
            except exceptions.SkyTpuError:
                pass
    deadline = time.time() + 20
    while time.time() < deadline and any(
            not j['status'].is_terminal() for j in jobs_state.get_jobs()):
        time.sleep(0.5)
    for rec in state.get_clusters():
        try:
            core.down(rec['name'], purge=True)
        except Exception:  # pylint: disable=broad-except
            pass
    state.reset_db_for_testing()
    jobs_state.reset_db_for_testing()


def _local_task(name, run):
    t = sky.Task(name=name, run=run)
    t.set_resources(resources_lib.Resources(cloud='local'))
    return t


def test_managed_job_success(jobs_env):
    t = _local_task('mj-ok', 'echo managed-ok')
    jid = jobs_core.launch(t, retry_until_up=False)
    job = jobs_core.wait(jid, timeout=60)
    assert job['status'] == jobs_state.ManagedJobStatus.SUCCEEDED
    assert job['recovery_count'] == 0
    # Cluster cleaned up after success.
    assert state.get_cluster(f'mj-ok-{jid}') is None
    # queue shows it
    rows = jobs_core.queue()
    assert [r['job_id'] for r in rows] == [jid]
    assert jobs_core.queue(skip_finished=True) == []


def test_managed_job_user_failure_no_recovery(jobs_env):
    t = _local_task('mj-fail', 'exit 3')
    jid = jobs_core.launch(t, retry_until_up=False)
    job = jobs_core.wait(jid, timeout=60)
    assert job['status'] == jobs_state.ManagedJobStatus.FAILED
    assert job['recovery_count'] == 0
    assert 'failed' in (job['failure_reason'] or '')


def test_managed_job_preemption_recovery(jobs_env):
    """Kill the job cluster mid-run; the controller must relaunch it."""
    # A wide-enough run window that the simulated preemption always
    # lands while the job is still running, even on a loaded machine
    # (with sleep 4 the job could finish before core.down executed and
    # the test raced cluster teardown).
    t = _local_task('mj-rec', 'sleep 12 && echo recovered-done')
    jid = jobs_core.launch(t, retry_until_up=False)
    cluster = f'mj-rec-{jid}'
    # Wait until RUNNING with a live cluster.
    deadline = time.time() + 60
    while time.time() < deadline:
        job = jobs_state.get_job(jid)
        if job['status'] == jobs_state.ManagedJobStatus.RUNNING and \
                state.get_cluster(cluster) is not None:
            break
        time.sleep(0.2)
    else:
        pytest.fail(f'job never RUNNING: {jobs_state.get_job(jid)}')

    # Simulate preemption: tear the cluster down behind its back.
    core.down(cluster, purge=True)

    # Wide window: detection + relaunch + a full 12s re-run, on a host
    # that may be running compile-heavy suites concurrently (observed
    # flakes at 150s AND 300s under full-suite load — the job sat in
    # RECOVERING, making progress; cold XLA compiles in the relaunched
    # agents dominate).
    job = jobs_core.wait(jid, timeout=600)
    assert job['status'] == jobs_state.ManagedJobStatus.SUCCEEDED
    assert job['recovery_count'] >= 1


@pytest.fixture()
def cluster_controller_env(jobs_env, tmp_path, monkeypatch):
    """Controller-on-cluster mode with local-provider controller
    resources (reference: jobs-controller VM)."""
    cfg = tmp_path / 'skyt_config.yaml'
    cfg.write_text(
        'jobs:\n  controller:\n    resources:\n      cloud: local\n')
    monkeypatch.setenv('SKYT_CONFIG', str(cfg))
    from skypilot_tpu import skyt_config
    skyt_config.reload_for_testing()
    yield
    skyt_config.reload_for_testing()


def test_managed_job_cluster_controller_survives_client(
        cluster_controller_env):
    """Controller runs as a job on the controller cluster: no client pid
    anywhere in the job row, so nothing dies with the client
    (reference: sky/jobs/core.py:30-137 controller-VM launch)."""
    t = _local_task('mj-vm', 'echo via-controller-cluster')
    jid = jobs_core.launch(t, retry_until_up=False,
                           controller='cluster')
    job = jobs_state.get_job(jid)
    assert job['controller_cluster'] == 'skyt-jobs-controller'
    assert not job.get('controller_pid')
    # queue() must not declare a pid-less cluster controller dead.
    assert all(r['status'] != jobs_state.ManagedJobStatus.FAILED_CONTROLLER
               for r in jobs_core.queue())
    job = jobs_core.wait(jid, timeout=150)
    assert job['status'] == jobs_state.ManagedJobStatus.SUCCEEDED
    # The controller cluster itself is alive and reusable.
    assert state.get_cluster('skyt-jobs-controller') is not None


def test_managed_job_cluster_controller_recovers_preemption(
        cluster_controller_env):
    """Full recovery semantics through the cluster-hosted controller:
    kill the job cluster mid-run; the controller (itself a cluster job,
    with the client idle) relaunches it."""
    t = _local_task('mj-vmrec', 'sleep 4 && echo done')
    jid = jobs_core.launch(t, retry_until_up=False,
                           controller='cluster')
    cluster = f'mj-vmrec-{jid}'
    deadline = time.time() + 60
    while time.time() < deadline:
        job = jobs_state.get_job(jid)
        if job['status'] == jobs_state.ManagedJobStatus.RUNNING and \
                state.get_cluster(cluster) is not None:
            break
        time.sleep(0.2)
    else:
        pytest.fail(f'job never RUNNING: {jobs_state.get_job(jid)}')
    core.down(cluster, purge=True)
    job = jobs_core.wait(jid, timeout=150)
    assert job['status'] == jobs_state.ManagedJobStatus.SUCCEEDED
    assert job['recovery_count'] >= 1


def test_managed_job_cancel(jobs_env):
    t = _local_task('mj-cxl', 'sleep 300')
    jid = jobs_core.launch(t, retry_until_up=False)
    deadline = time.time() + 60
    while time.time() < deadline:
        if jobs_state.get_job(jid)['status'] == \
                jobs_state.ManagedJobStatus.RUNNING:
            break
        time.sleep(0.2)
    assert jobs_core.cancel([jid]) == [jid]
    job = jobs_core.wait(jid, timeout=60)
    assert job['status'] == jobs_state.ManagedJobStatus.CANCELLED
    # Job cluster torn down on cancel.
    assert state.get_cluster(f'mj-cxl-{jid}') is None


def test_managed_job_chain_dag(jobs_env):
    with sky.Dag() as dag:
        a = _local_task('step-a', 'echo A')
        b = _local_task('step-b', 'echo B')
        a >> b
    jid = jobs_core.launch(dag, name='chain', retry_until_up=False)
    job = jobs_core.wait(jid, timeout=150)
    assert job['status'] == jobs_state.ManagedJobStatus.SUCCEEDED
    assert job['task_index'] == 1  # reached the second task
    assert job['num_tasks'] == 2


def test_managed_job_pipeline_yaml_e2e(jobs_env, tmp_path):
    """The examples/pipeline.yaml FORMAT run end-to-end: multi-doc YAML
    -> chain Dag -> jobs controller executes both stages in order."""
    out = tmp_path / 'order.txt'
    yml = tmp_path / 'pipe.yaml'
    yml.write_text(f"""\
name: yaml-pipe
---
name: stage-prep
resources:
  cloud: local
run: echo prep >> {out}
---
name: stage-train
resources:
  cloud: local
run: echo train >> {out}
""")
    from skypilot_tpu import dag as dag_lib
    assert dag_lib.yaml_is_pipeline(str(yml))
    dag = dag_lib.load_chain_dag_from_yaml(str(yml))
    jid = jobs_core.launch(dag, name='yaml-pipe', retry_until_up=False)
    job = jobs_core.wait(jid, timeout=150)
    assert job['status'] == jobs_state.ManagedJobStatus.SUCCEEDED
    assert job['num_tasks'] == 2
    # Both stages ran, in chain order.
    assert out.read_text().split() == ['prep', 'train']


def test_queue_reconciles_dead_controller(jobs_env):
    t = _local_task('mj-dead', 'sleep 300')
    jid = jobs_core.launch(t, retry_until_up=False)
    deadline = time.time() + 60
    while time.time() < deadline:
        job = jobs_state.get_job(jid)
        if job['status'] == jobs_state.ManagedJobStatus.RUNNING:
            break
        time.sleep(0.2)
    os.kill(job['controller_pid'], 9)
    time.sleep(0.5)
    rows = {j['job_id']: j for j in jobs_core.queue()}
    assert rows[jid]['status'] == \
        jobs_state.ManagedJobStatus.FAILED_CONTROLLER
    # Leaked cluster is cleaned by the fixture (and visible here).
    core.down(f'mj-dead-{jid}', purge=True)


def test_cancel_validation(jobs_env):
    with pytest.raises(exceptions.ManagedJobError):
        jobs_core.cancel()


def test_strategy_registry():
    make = recovery_strategy.StrategyExecutor.make
    t = _local_task('s', 'true')
    assert make('c', t).NAME == 'EAGER_NEXT_REGION'
    assert make('c', t, 'failover').NAME == 'FAILOVER'
    with pytest.raises(exceptions.ManagedJobError):
        make('c', t, 'nope')


def test_probe_narrows_exceptions(monkeypatch):
    """Only network errors mean 'cluster unreachable'; a programming
    error in the probe must propagate (and fail the controller) instead
    of masquerading as a preemption and triggering spurious recovery."""
    import requests

    from skypilot_tpu import state as cluster_state
    from skypilot_tpu.jobs import controller as controller_mod

    class _Handle:
        def __init__(self, exc):
            self._exc = exc

        def head_client(self):
            raise self._exc

    probe = controller_mod.JobsController._probe_job_status

    def with_exc(exc):
        monkeypatch.setattr(cluster_state, 'get_cluster',
                            lambda name: {'handle': _Handle(exc)})
        return lambda: probe(object.__new__(controller_mod.JobsController),
                             'c', 1)

    # Network-ish errors -> None ("unreachable"), the recovery trigger.
    assert with_exc(requests.ConnectionError('down'))() is None
    assert with_exc(requests.Timeout('slow'))() is None
    assert with_exc(OSError('socket'))() is None
    # Programming errors surface.
    with pytest.raises(TypeError):
        with_exc(TypeError('bug'))()
    # Missing cluster record -> None (cluster gone).
    monkeypatch.setattr(cluster_state, 'get_cluster', lambda name: None)
    assert probe(object.__new__(controller_mod.JobsController),
                 'c', 1) is None


def test_cluster_controller_translates_workdir_and_recovers(
        cluster_controller_env, tmp_path):
    """The headline file-mount-translation scenario (reference:
    sky/utils/controller_utils.py:567 called from sky/jobs/core.py:78):
    a managed job with a client-local workdir is preempted AFTER the
    client's filesystem is gone; recovery must rebuild the workdir from
    the translated bucket, not the client path."""
    import shutil

    import yaml as yaml_lib

    workdir = tmp_path / 'client-workdir'
    workdir.mkdir()
    (workdir / 'marker.txt').write_text('from-client-workdir\n')
    t = sky.Task(name='mj-wd', run='sleep 8 && cat marker.txt',
                 workdir=str(workdir))
    t.set_resources(resources_lib.Resources(cloud='local'))
    jid = jobs_core.launch(t, retry_until_up=False, controller='cluster')

    # Submission already rewrote the persisted DAG: no client paths.
    job = jobs_state.get_job(jid)
    with open(job['dag_yaml'], encoding='utf-8') as f:
        cfgs = list(yaml_lib.safe_load_all(f))
    assert len(cfgs) == 1 and 'workdir' not in cfgs[0]
    assert str(workdir) not in str(cfgs[0])
    mounts = cfgs[0]['file_mounts']
    wd_spec = mounts['skyt_workdir']
    assert wd_spec['source'].startswith('local://skyt-workdir-')

    # The client filesystem leaves the picture entirely.
    shutil.rmtree(workdir)

    cluster = f'mj-wd-{jid}'
    # Generous: the controller + runtime agents are subprocesses that
    # may each pay cold XLA compiles on a cold cache (observed: the
    # whole scenario takes ~6 min cold vs ~30 s warm).
    deadline = time.time() + 240
    while time.time() < deadline:
        job = jobs_state.get_job(jid)
        if job['status'] == jobs_state.ManagedJobStatus.RUNNING and \
                state.get_cluster(cluster) is not None:
            break
        time.sleep(0.2)
    else:
        pytest.fail(f'job never RUNNING: {jobs_state.get_job(jid)}')
    core.down(cluster, purge=True)  # simulated preemption

    job = jobs_core.wait(jid, timeout=600)
    # `cat marker.txt` ran in ~/skyt_workdir rebuilt from the bucket —
    # with the client dir deleted, success is only possible via the
    # translated storage mount.
    assert job['status'] == jobs_state.ManagedJobStatus.SUCCEEDED
    assert job['recovery_count'] >= 1
    # Ephemeral translation bucket cleaned up with the job.
    assert state.get_storage(wd_spec['name']) is None
