"""Tiered prefix cache (infer/kv_tier.py; docs/performance.md "Tiered
prefix cache"): host-store LRU semantics, transfer codec roundtrip,
promote-vs-recompute golden stream equality, weight-version
invalidation across tiers, and kv.fetch fault descent to recompute."""
import dataclasses
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import kv_tier as kv_tier_lib
from skypilot_tpu.infer import paged_cache
from skypilot_tpu.models import llama
from skypilot_tpu.utils import faults
from skypilot_tpu.utils import metrics as metrics_lib

# Engine tests compile the debug model (amortized by the XLA cache).
pytestmark = pytest.mark.heavy


def _h(i: int) -> bytes:
    return bytes([i]) * 16


def _arrays(nbytes: int = 100) -> dict:
    return {'k': np.full(nbytes, 7, np.uint8)}


# ------------------------------------------------------- transfer codec
class TestCodec:
    def test_roundtrip_int8_with_scales(self):
        pages = []
        rng = np.random.default_rng(0)
        for i in range(3):
            pages.append((_h(i), {
                'k': rng.integers(-128, 127, (2, 1, 4, 8)).astype(np.int8),
                'v': rng.integers(-128, 127, (2, 1, 4, 8)).astype(np.int8),
                'k_scale': rng.random((2, 1, 4)).astype(np.float32),
                'v_scale': rng.random((2, 1, 4)).astype(np.float32),
            }))
        blob = kv_tier_lib.encode_pages(pages, weight_version=5)
        version, out = kv_tier_lib.decode_pages(blob)
        assert version == 5
        assert [h for h, _ in out] == [h for h, _ in pages]
        for (_, a), (_, b) in zip(pages, out):
            assert sorted(a) == sorted(b)
            for name in a:
                assert b[name].dtype == a[name].dtype
                assert b[name].shape == a[name].shape
                assert b[name].tobytes() == a[name].tobytes()

    def test_roundtrip_bfloat16(self):
        import ml_dtypes
        a = np.arange(32, dtype=np.float32).astype(ml_dtypes.bfloat16)
        blob = kv_tier_lib.encode_pages(
            [(_h(1), {'k': a.reshape(2, 16)})], weight_version=1)
        _, out = kv_tier_lib.decode_pages(blob)
        got = out[0][1]['k']
        assert got.dtype == np.dtype(ml_dtypes.bfloat16)
        assert got.tobytes() == a.reshape(2, 16).tobytes()

    def test_malformed_raises(self):
        good = kv_tier_lib.encode_pages(
            [(_h(1), _arrays())], weight_version=1)
        for bad in (b'', b'junk', b'XXXX' + good[4:],
                    good[:10], good[:-5]):
            with pytest.raises(ValueError):
                kv_tier_lib.decode_pages(bad)


# ----------------------------------------------------------- host store
class TestHostStore:
    def test_lru_byte_budget(self):
        store = kv_tier_lib.HostKVStore(budget_bytes=250)
        assert store.put(_h(1), 1, _arrays(100))
        assert store.put(_h(2), 1, _arrays(100))
        # Refresh h1's recency, then overflow: h2 (now LRU) evicts.
        assert store.get(_h(1), 1) is not None
        assert store.put(_h(3), 1, _arrays(100))
        assert store.get(_h(2), 1) is None
        assert store.get(_h(1), 1) is not None
        assert store.get(_h(3), 1) is not None
        assert store.stats['evictions'] == 1
        assert store.nbytes() <= 250
        # An entry above the whole budget is dropped, not stored.
        assert not store.put(_h(4), 1, _arrays(1000))
        assert store.stats['put_drops'] == 1
        assert len(store) == 2

    def test_version_gate(self):
        store = kv_tier_lib.HostKVStore(budget_bytes=10_000)
        store.put(_h(1), 1, _arrays())
        store.put(_h(2), 1, _arrays())
        store.put(_h(3), 2, _arrays())
        # Lookup is version-checked even before any set_version.
        assert store.get(_h(1), 2) is None
        assert store.get(_h(1), 1) is not None
        # Swap: prune other versions AND gate in-flight old spills.
        assert store.set_version(2) == 2
        assert store.stats['invalidated'] == 2
        assert len(store) == 1
        assert not store.put(_h(4), 1, _arrays())   # stale spill
        assert store.put(_h(5), 2, _arrays())
        assert store.contains(_h(3), 2)
        assert not store.contains(_h(1), 1)

    def test_leading_run(self):
        store = kv_tier_lib.HostKVStore(budget_bytes=10_000)
        for i in (1, 2, 4):
            store.put(_h(i), 1, _arrays())
        run = store.run([_h(1), _h(2), _h(3), _h(4)], 1)
        assert [h for h, _ in run] == [_h(1), _h(2)]
        assert store.run([_h(9)], 1) == []


# ------------------------------------------------- pool splice + spill
class TestPoolSplice:
    def _pool(self):
        cfg = paged_cache.PagedConfig(page_size=4, n_pages=9,
                                      max_pages_per_slot=4)
        return paged_cache.PagePool(cfg, n_layers=2, kv_heads=2,
                                    head_dim=8, num_slots=3,
                                    dtype=jnp.float32)

    def test_install_prefix_free_list_only(self):
        pool = self._pool()
        h = paged_cache.page_hashes(list(range(1, 9)), 4)
        pages = pool.install_prefix(h)
        assert pages is not None and len(pages) == 2
        for hh, p in zip(h, pages):
            assert pool.registered_page(hh) == p
        # Installed pages are shared by the normal reserve path.
        row, matched = pool.try_reserve_prefix(0, 8, h)
        assert row is not None and matched == 2
        # Re-installing a registered run is refused (caller promotes
        # only genuinely missing hashes).
        assert pool.install_prefix(h) is None
        # A run larger than the free list is refused whole — promotion
        # never evicts published pages.
        big = [bytes([i]) * 16 for i in range(50)]
        assert pool.install_prefix(big) is None
        pool.release(0)

    def test_on_evict_hook_fires_with_hash(self):
        pool = self._pool()
        seen = []
        pool.on_evict = lambda page, h: seen.append((page, h))
        h = paged_cache.page_hashes(list(range(1, 9)), 4)
        pool.try_reserve_prefix(0, 8, ())
        pool.publish(0, h)
        pool.release(0)
        # Exhaust the free list: the warm published pages are
        # reclaimed LRU-first and the hook sees each (page, hash).
        pool.try_reserve_prefix(1, 16, ())
        pool.try_reserve_prefix(2, 16, ())
        assert pool.prefix_stats['evictions'] >= 2
        assert {hh for _, hh in seen} == set(h)


# ---------------------------------------------------- engine fixtures
@pytest.fixture(scope='module')
def kv_setup():
    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=128)
    model = llama.LlamaModel(cfg)
    zeros = jnp.zeros((1, 8), jnp.int32)
    p0 = jax.jit(model.init)(jax.random.PRNGKey(0), zeros)
    p1 = jax.jit(model.init)(jax.random.PRNGKey(7), zeros)
    return cfg, model, p0, p1


def _make_engine(kv_setup, monkeypatch, tier='host', **kw):
    monkeypatch.setenv('SKYT_KV_TIER', tier)
    _, model, p0, _ = kv_setup
    reg = metrics_lib.MetricsRegistry()
    defaults = dict(num_slots=2, max_seq_len=128, decode_chunk=2,
                    cache_mode='paged', prefix_caching=True,
                    pool_tokens=512, metrics_registry=reg)
    defaults.update(kw)
    params = defaults.pop('params', p0)
    return engine_lib.InferenceEngine(model, params, **defaults), reg


def _prompt(i: int):
    # 100 tokens = one full 64-token page (+ remainder) per prompt,
    # all distinct so ten of them overflow the 8-usable-page pool.
    return [(i * 37 + j) % 97 + 3 for j in range(100)]


def _gen(eng, tokens, n=8, kv_peer=None, **sp):
    _, q = eng.submit(list(tokens),
                      engine_lib.SamplingParams(max_new_tokens=n, **sp),
                      kv_peer=kv_peer)
    out = []
    while True:
        t = q.get(timeout=300)
        if t is None:
            return out
        out.append(t)


def _fill_until_evicted(eng, first_prompt, start=1, count=9):
    """Submit distinct prompts until first_prompt's lead page is
    evicted (LRU: oldest released goes first), then drain the spill
    writer."""
    for i in range(start, start + count):
        _gen(eng, _prompt(i))
    h0 = paged_cache.page_hashes(first_prompt, eng.pool.cfg.page_size)[0]
    assert eng.pool.registered_page(h0) is None, \
        'expected the first prompt\'s page to be LRU-evicted'
    assert eng.pool.prefix_stats['evictions'] > 0
    assert eng.kv_tier.drain()
    return h0


# --------------------------------------- golden: promote == recompute
class TestGoldenPromotion:
    @pytest.mark.parametrize('kv_dtype', ['auto', 'int8'])
    def test_promote_matches_recompute(self, kv_setup, monkeypatch,
                                       kv_dtype):
        eng, reg = _make_engine(kv_setup, monkeypatch,
                                kv_dtype=kv_dtype)
        eng.start()
        try:
            prompt = _prompt(0)
            golden_greedy = _gen(eng, prompt)
            # Sampling keys mix in the req_id (seed + req_id), so the
            # rerun compensates its seed to hit the SAME key — stream
            # equality then holds iff the promoted KV bytes match.
            rid1 = eng._next_id
            golden_seeded = _gen(eng, prompt, temperature=0.8,
                                 seed=1000)
            h0 = _fill_until_evicted(eng, prompt)
            assert eng.kv_tier.host.contains(h0, eng.weight_version)
            # Seeded rerun first: its admission promotes host->device.
            rid2 = eng._next_id
            assert _gen(eng, prompt, temperature=0.8,
                        seed=1000 + rid1 - rid2) == golden_seeded
            assert eng.kv_tier.stats['promotions'] >= 1
            assert eng.kv_tier.stats['promoted_pages'] >= 1
            # Greedy rerun now HBM-hits the promoted page. Un-throttle
            # the ~4Hz gauge refresh first so its ticks fold the
            # promotion delta into the per-tier counter even when the
            # warm-cache reruns all fit inside one throttle window.
            eng._last_gauge_t = 0.0
            assert _gen(eng, prompt) == golden_greedy
            # Satellite telemetry: eviction counter, occupancy gauges,
            # and the per-tier hit counter are exported.
            text = reg.expose()
            assert 'skyt_infer_prefix_cache_evictions_total' in text
            assert 'skyt_infer_prefix_cache_pages' in text
            assert 'skyt_infer_prefix_cache_occupancy' in text
            assert 'skyt_infer_kv_tier_hit_pages_total{tier="host"}' \
                in text
        finally:
            eng.stop()


# -------------------------------------------- swap invalidation (L2/L3)
class TestSwapInvalidation:
    def test_swap_empties_host_store_and_gates_spills(self, kv_setup,
                                                      monkeypatch):
        _, _, _, p1 = kv_setup
        eng, _ = _make_engine(kv_setup, monkeypatch)
        eng.start()
        try:
            prompt = _prompt(0)
            _gen(eng, prompt)
            _fill_until_evicted(eng, prompt)
            assert len(eng.kv_tier.host) > 0
            old_version = eng.weight_version
            res = eng.request_weight_swap(p1, drain=True, timeout=60)
            assert res['weight_version'] == old_version + 1
            # Every old-version entry pruned; late spills from the old
            # weights can never land.
            assert len(eng.kv_tier.host) == 0
            assert eng.kv_tier.host.stats['invalidated'] > 0
            assert not eng.kv_tier.host.put(
                _h(1), old_version, _arrays())
        finally:
            eng.stop()

    def test_fetch_rejects_peer_version_mismatch(self, monkeypatch):
        mgr = kv_tier_lib.KVTierManager('fleet', host_bytes=10_000,
                                        fetch_max_pages=8,
                                        fetch_timeout_s=1.0)
        monkeypatch.setattr(
            kv_tier_lib, 'fetch_pages',
            lambda *a, **k: (999, [(_h(1), _arrays())]))
        with pytest.raises(RuntimeError, match='weight_version'):
            mgr.fetch_into_host('http://peer', [_h(1)], 1, 'tok')
        assert len(mgr.host) == 0

    def test_fetch_rejects_pool_layout_mismatch(self, monkeypatch):
        """A well-formed SKV1 payload whose arrays do not match the
        local pool layout (misconfigured or malicious peer — other
        quantization, page size, or bogus keys) must fail the fetch
        (-> recompute) BEFORE anything enters the host store, never
        reach the engine-loop install path."""
        mgr = kv_tier_lib.KVTierManager('fleet', host_bytes=10_000,
                                        fetch_max_pages=8,
                                        fetch_timeout_s=1.0)
        mgr.set_page_layout({'k': (np.dtype(np.int8), (2, 4, 8))})
        for bad in ({'k': np.zeros((2, 4, 8), np.int16)},    # dtype
                    {'k': np.zeros((2, 4, 4), np.int8)},     # shape
                    {'v': np.zeros((2, 4, 8), np.int8)},     # keys
                    {'k': np.zeros((2, 4, 8), np.int8),
                     'extra': np.zeros(1, np.int8)}):        # extra key
            monkeypatch.setattr(
                kv_tier_lib, 'fetch_pages',
                lambda *a, bad=bad, **k: (1, [(_h(1), bad)]))
            with pytest.raises(ValueError, match='page'):
                mgr.fetch_into_host('http://peer', [_h(1)], 1, 'tok')
            assert len(mgr.host) == 0
        # A matching page passes; a later bad page in the same run
        # still fails the whole transfer.
        ok = {'k': np.zeros((2, 4, 8), np.int8)}
        monkeypatch.setattr(kv_tier_lib, 'fetch_pages',
                            lambda *a, **k: (1, [(_h(1), ok)]))
        assert mgr.fetch_into_host('http://peer', [_h(1)], 1,
                                   'tok') == 1
        assert mgr.host.contains(_h(1), 1)
        # Unconfigured layout (standalone use) skips the check.
        mgr2 = kv_tier_lib.KVTierManager('fleet', host_bytes=10_000,
                                         fetch_max_pages=8,
                                         fetch_timeout_s=1.0)
        monkeypatch.setattr(
            kv_tier_lib, 'fetch_pages',
            lambda *a, **k: (1, [(_h(2), _arrays())]))
        assert mgr2.fetch_into_host('http://peer', [_h(2)], 1,
                                    'tok') == 1

    def test_host_store_discard(self):
        store = kv_tier_lib.HostKVStore(budget_bytes=10_000)
        store.put(_h(1), 1, _arrays(100))
        store.put(_h(2), 1, _arrays(100))
        store.discard(_h(1))
        store.discard(_h(9))   # absent: no-op
        assert not store.contains(_h(1), 1)
        assert store.contains(_h(2), 1)
        assert store.nbytes() == 100


# ------------------------------------------- kv.fetch fault -> recompute
class TestFetchFaultDescent:
    def test_fetch_failures_degrade_to_recompute(self, kv_setup,
                                                 monkeypatch):
        monkeypatch.setenv('SKYT_KV_FETCH_TIMEOUT_S', '0.2')
        eng, _ = _make_engine(kv_setup, monkeypatch, tier='fleet')
        eng.start()
        try:
            # Injected error: the fetch worker raises, the parked
            # request re-admits and recomputes — tokens still flow.
            faults.configure('kv.fetch=error')
            out = _gen(eng, _prompt(20), kv_peer='http://127.0.0.1:9')
            assert len(out) == 8
            assert eng.kv_tier.stats['fetch_errors'] >= 1
            faults.reset()
            # Real transport failure (dead peer), same descent.
            errs = eng.kv_tier.stats['fetch_errors']
            out = _gen(eng, _prompt(21), kv_peer='http://127.0.0.1:9')
            assert len(out) == 8
            assert eng.kv_tier.stats['fetch_errors'] > errs
            # Hang: the engine abandons the wait at its deadline and
            # recomputes; the stale worker result is discarded.
            faults.configure('kv.fetch=hang,arg=5')
            t0 = time.monotonic()
            out = _gen(eng, _prompt(22), kv_peer='http://127.0.0.1:9')
            assert len(out) == 8
            assert time.monotonic() - t0 < 30
        finally:
            faults.reset()
            eng.stop()


# ------------------------------------- /kv/prefix endpoint + fleet e2e
def _run_app_bg(app, port):
    import asyncio

    from aiohttp import web

    def runner():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        r = web.AppRunner(app)
        loop.run_until_complete(r.setup())
        loop.run_until_complete(
            web.TCPSite(r, '127.0.0.1', port).start())
        loop.run_forever()
    threading.Thread(target=runner, daemon=True).start()


@pytest.mark.integration
class TestFleetTransfer:
    def test_endpoint_contract_and_fleet_golden(self, kv_setup,
                                                monkeypatch):
        import requests

        from skypilot_tpu.infer import server as server_lib
        from tests.test_chaos import _free_port, _wait_http

        # Donor replica: engine + real HTTP surface.
        donor, _ = _make_engine(kv_setup, monkeypatch, tier='host')
        donor.start()
        fetcher = None
        try:
            prompt = _prompt(0)
            golden = _gen(donor, prompt)
            srv = server_lib.InferenceServer(donor)
            port = _free_port()
            _run_app_bg(srv.make_app(), port)
            base = f'http://127.0.0.1:{port}'
            _wait_http(base + '/health', timeout=120)
            h0 = paged_cache.page_hashes(
                prompt, donor.pool.cfg.page_size)[0]

            # Auth/validation contract.
            monkeypatch.delenv('SKYT_ADMIN_TOKEN', raising=False)
            assert requests.get(base + '/kv/prefix',
                                params={'hashes': h0.hex()},
                                timeout=30).status_code == 403
            monkeypatch.setenv('SKYT_ADMIN_TOKEN', 'sesame')
            hdr = {'Authorization': 'Bearer sesame'}
            assert requests.get(base + '/kv/prefix',
                                params={'hashes': h0.hex()},
                                timeout=30).status_code == 403
            for bad in ('', 'zz', 'abcd'):
                assert requests.get(
                    base + '/kv/prefix', params={'hashes': bad},
                    headers=hdr, timeout=30).status_code == 400
            assert requests.get(
                base + '/kv/prefix',
                params={'hashes': (b'\x99' * 16).hex()},
                headers=hdr, timeout=30).status_code == 404

            # Resident run: 200 + decodable payload, version stamped.
            r = requests.get(base + '/kv/prefix',
                             params={'hashes': h0.hex()},
                             headers=hdr, timeout=30)
            assert r.status_code == 200
            assert int(r.headers['X-Weight-Version']) == \
                donor.weight_version
            version, pages = kv_tier_lib.decode_pages(r.content)
            assert version == donor.weight_version
            assert [h for h, _ in pages] == [h0]

            # fetch_pages helper sees the same bytes.
            version2, pages2 = kv_tier_lib.fetch_pages(
                base, [h0], 'sesame', timeout_s=30, max_pages=4)
            assert version2 == version
            assert pages2[0][1]['k'].tobytes() == \
                pages[0][1]['k'].tobytes()

            # Fleet e2e: a cold peer engine warms from the donor and
            # streams byte-identical tokens.
            fetcher, _ = _make_engine(kv_setup, monkeypatch,
                                      tier='fleet')
            fetcher.start()
            assert _gen(fetcher, prompt, kv_peer=base) == golden
            assert fetcher.kv_tier.stats['fetched_pages'] >= 1
            assert fetcher.kv_tier.stats['promotions'] >= 1
        finally:
            if fetcher is not None:
                fetcher.stop()
            donor.stop()


# --------------------------------------------- replica-side peer check
def test_kv_peer_from_validates_against_known_replicas(monkeypatch):
    """The replica half of the X-KV-Peer defense (the LB strips the
    client-supplied header; this guards direct-to-replica callers):
    only loopback peers or SKYT_KV_PEER_ALLOW-listed scheme://host:port
    are accepted — the engine fetches from the peer with its admin
    bearer token, so an arbitrary URL would exfiltrate it."""
    from skypilot_tpu.infer import server as server_lib

    class _Req:
        def __init__(self, peer):
            self.headers = {} if peer is None else {'X-KV-Peer': peer}

    peer_from = server_lib.InferenceServer._kv_peer_from
    monkeypatch.delenv('SKYT_KV_PEER_ALLOW', raising=False)
    # Loopback (single-host fleets, the chaos drill) always passes.
    assert peer_from(_Req('http://127.0.0.1:8001')) == \
        'http://127.0.0.1:8001'
    assert peer_from(_Req('http://localhost:8001')) is not None
    # Everything else is dropped, never an error.
    for bad in (None, '', 'not-a-url', 'http://', 'ftp://127.0.0.1:1',
                'http://evil.example:8001', 'https://10.0.0.5:8001',
                'http://127.0.0.1:notaport',
                'http://127.0.0.1:' + '9' * 510):
        assert peer_from(_Req(bad)) is None
    # Fleets spanning hosts list replica base URLs explicitly;
    # matching is exact on scheme+host+port.
    monkeypatch.setenv('SKYT_KV_PEER_ALLOW',
                       'http://10.0.0.5:8001, http://10.0.0.6:8001,')
    assert peer_from(_Req('http://10.0.0.5:8001')) is not None
    assert peer_from(_Req('http://10.0.0.6:8001')) is not None
    assert peer_from(_Req('http://127.0.0.1:8001')) is not None
    for bad in ('http://10.0.0.5:9999', 'https://10.0.0.5:8001',
                'http://10.0.0.7:8001'):
        assert peer_from(_Req(bad)) is None


# --------------------------------------------------------- off == inert
def test_tier_off_leaves_engine_untouched(kv_setup, monkeypatch):
    monkeypatch.setenv('SKYT_KV_TIER', 'off')
    eng, _ = _make_engine(kv_setup, monkeypatch, tier='off')
    assert eng.kv_tier is None
    # Bad values degrade to off with a warning, never a crash.
    monkeypatch.setenv('SKYT_KV_TIER', 'warp-drive')
    assert kv_tier_lib.tier_from_env() == 'off'


# -------------------------------------- scale-up prewarm (ROADMAP 5c)
class TestPrewarm:
    """Proactive KV pre-warm on scale-up: a freshly READY replica
    pulls its rendezvous share of the fleet's resident prefix pages
    into the host store (docs/serving.md "Elastic capacity")."""

    def _mgr(self):
        return kv_tier_lib.KVTierManager('fleet', host_bytes=1 << 20,
                                         fetch_max_pages=1,
                                         fetch_timeout_s=1.0)

    def test_prewarm_claims_exactly_the_owned_share(self, monkeypatch):
        """Ownership is the same rendezvous-ring math the LB's
        prefix-affinity routing uses: the replica fetches the batches
        the ring ranks it first for — no more, no less — and they land
        in the host store under the prewarm counter."""
        from skypilot_tpu.serve import load_balancing_policies as \
            lb_policies
        mgr = self._mgr()
        hashes = [_h(i) for i in range(40)]
        monkeypatch.setattr(
            kv_tier_lib, 'fetch_index',
            lambda peer, token, timeout_s: (1, list(hashes)))
        monkeypatch.setattr(
            kv_tier_lib, 'fetch_pages',
            lambda peer, hs, token, timeout_s, max_pages:
            (1, [(h, _arrays()) for h in hs]))
        me, peer = 'http://127.0.0.1:9001', 'http://127.0.0.1:9002'
        res = mgr.prewarm_from_peers(me, [peer], 1, 'tok')
        ring = lb_policies.ConsistentHashRing()
        ring.set_nodes({me: 1.0, peer: 1.0})
        expected = [h for h in hashes if ring.owner(h.hex()) == me]
        # The split is real: both replicas own a nonempty share.
        assert 0 < len(expected) < len(hashes)
        assert res['owned_pages'] == res['stored_pages'] == \
            len(expected)
        assert res['errors'] == 0 and res['peers'] == 1
        assert mgr.stats['prewarm_pages'] == len(expected)
        assert all(mgr.host.contains(h, 1) for h in expected)
        assert not any(mgr.host.contains(h, 1)
                       for h in hashes if h not in expected)
        # A self-entry in the peer list is skipped, not fetched.
        res2 = self._mgr().prewarm_from_peers(me, [me], 1, 'tok')
        assert res2 == {'peers': 1, 'owned_pages': 0,
                        'stored_pages': 0, 'errors': 0}

    def test_prewarm_failures_counted_never_raised(self, monkeypatch):
        """Best-effort contract: version-mismatched peers and kv.fetch
        faults are counted and skipped — a failed prewarm costs
        recomputes, never readiness (and never an exception)."""
        mgr = self._mgr()
        # Peer on another weight version: its KV must never splice in.
        monkeypatch.setattr(
            kv_tier_lib, 'fetch_index',
            lambda peer, token, timeout_s: (2, [_h(1)]))
        res = mgr.prewarm_from_peers('http://a:1', ['http://b:2'],
                                     1, 'tok')
        assert res['errors'] == 1 and res['stored_pages'] == 0
        assert len(mgr.host) == 0
        # The shared kv.fetch fault point breaks prewarm the same way
        # it breaks demand fetches: degrade, count, carry on.
        monkeypatch.undo()
        faults.reset()
        faults.configure('kv.fetch=error')
        try:
            res = mgr.prewarm_from_peers('http://a:1',
                                         ['http://b:2',
                                          'http://c:3'], 1, 'tok')
        finally:
            faults.reset()
        assert res['errors'] == 2 and res['stored_pages'] == 0


@pytest.mark.integration
def test_kv_index_inventory_roundtrip(kv_setup, monkeypatch):
    """engine.kv_index() snapshots the resident inventory at a tick
    boundary: HBM registry pages first, host-store continuations
    deduplicated in, weight version stamped — the /kv/index body peers
    batch their prewarm claims over."""
    eng, _ = _make_engine(kv_setup, monkeypatch, tier='host')
    eng.start()
    try:
        prompt = _prompt(0)
        _gen(eng, prompt)
        idx = eng.kv_index()
        assert idx is not None
        assert idx['weight_version'] == eng.weight_version == 1
        h0 = paged_cache.page_hashes(prompt,
                                     eng.pool.cfg.page_size)[0]
        assert h0.hex() in idx['hashes']
        assert len(set(idx['hashes'])) == len(idx['hashes'])
        # Host-only pages (evicted from HBM) stay in the inventory.
        _fill_until_evicted(eng, prompt)
        idx2 = eng.kv_index()
        assert h0.hex() in idx2['hashes']
        # A host-tier engine refuses the prewarm pull itself (fleet
        # transfers are the fleet tier's contract) — explicitly, not
        # with an error.
        res = eng.kv_prewarm('http://me:1', ['http://peer:2'], 'tok')
        assert res['skipped'] and res['stored_pages'] == 0
    finally:
        eng.stop()
