"""Paged KV cache unit tests: host accounting + device kernels match a
dense reference."""
import numpy as np
import pytest

import jax.numpy as jnp

from skypilot_tpu.infer import paged_cache


def _pool(n_pages=9, p=4, l=2, h=2, d=8, slots=3):
    cfg = paged_cache.PagedConfig(page_size=p, n_pages=n_pages,
                                  max_pages_per_slot=4)
    return paged_cache.PagePool(cfg, n_layers=l, kv_heads=h, head_dim=d,
                                num_slots=slots, dtype=jnp.float32)


class TestAccounting:
    def test_reserve_release_cycle(self):
        pool = _pool()
        assert pool.free_pages() == 8
        row = pool.try_reserve(0, 10)      # 3 pages of 4
        assert row is not None
        assert (row[:3] > 0).all() and (row[3:] == 0).all()
        assert pool.free_pages() == 5
        row2 = pool.try_reserve(1, 16)     # 4 pages
        assert row2 is not None
        assert pool.free_pages() == 1
        assert pool.try_reserve(2, 8) is None   # needs 2, only 1 free
        pool.release(0)
        assert pool.free_pages() == 4
        assert (pool.tables[0] == 0).all()
        assert pool.try_reserve(2, 8) is not None

    def test_reservation_capped_at_max_pages(self):
        pool = _pool()
        assert pool.pages_needed(10_000) == 4   # max_pages_per_slot
        assert pool.try_reserve(0, 10_000) is not None

    def test_double_reserve_asserts(self):
        pool = _pool()
        pool.try_reserve(0, 4)
        with pytest.raises(AssertionError):
            pool.try_reserve(0, 4)


class TestPrefixCache:
    """Host-side prefix registry: sharing, refcounts, LRU eviction.
    vLLM-automatic-prefix-caching analog (llm/vllm/serve.yaml)."""

    def test_page_hashes_chain(self):
        p = 4
        a = paged_cache.page_hashes([1, 2, 3, 4, 5, 6, 7, 8, 9], p)
        b = paged_cache.page_hashes([1, 2, 3, 4, 9, 9, 9, 9], p)
        assert len(a) == 2          # only FULL pages are hashed
        assert len(b) == 2
        assert a[0] == b[0]         # same first page
        assert a[1] != b[1]         # diverging second page
        # Chained: same page content after a different prefix differs.
        c = paged_cache.page_hashes([9, 9, 9, 9, 5, 6, 7, 8], p)
        assert c[1] != a[1]

    def test_share_refcount_release(self):
        pool = _pool()
        h = paged_cache.page_hashes(list(range(1, 9)), 4)   # 2 pages
        row0, m0 = pool.try_reserve_prefix(0, 12, h)        # 3 pages
        assert m0 == 0
        pool.publish(0, h)
        free_before = pool.free_pages()
        row1, m1 = pool.try_reserve_prefix(1, 12, h)
        assert m1 == 2                          # both full pages shared
        assert (row1[:2] == row0[:2]).all()
        assert row1[2] != row0[2]               # private third page
        # Sharing consumed only ONE new page.
        assert pool.free_pages() == free_before - 1
        # Slot 0 releases; shared pages stay live for slot 1.
        pool.release(0)
        row2, m2 = pool.try_reserve_prefix(2, 12, h)
        assert m2 == 2 and (row2[:2] == row1[:2]).all()

    def test_released_pages_stay_warm_then_evict(self):
        pool = _pool()                          # 8 usable pages
        h = paged_cache.page_hashes(list(range(1, 9)), 4)
        pool.try_reserve_prefix(0, 8, h)        # 2 pages
        pool.publish(0, h)
        pool.release(0)
        # Nothing active, but the published pages are still hits.
        row, m = pool.try_reserve_prefix(1, 8, h)
        assert m == 2
        pool.release(1)
        # Demand for all 8 pages evicts the cached ones (LRU) rather
        # than failing.
        row2, m2 = pool.try_reserve_prefix(2, 32, ())
        assert row2 is not None and (row2 > 0).sum() == 4
        pool.try_reserve_prefix(0, 16, ())
        assert pool.free_pages() == 0
        assert pool.prefix_stats['evictions'] > 0
        # The evicted prefix no longer hits.
        pool.release(2)
        _, m3 = pool.try_reserve_prefix(2, 8, h)
        assert m3 == 0

    def test_reserve_rollback_on_exhaustion(self):
        pool = _pool()                          # 8 usable pages, 4/slot
        h = paged_cache.page_hashes(list(range(1, 9)), 4)   # 2 hashes
        pool.try_reserve_prefix(0, 12, ())      # slot0: 3 pages
        pool.publish(0, h)                      # its first 2 published
        pool.try_reserve_prefix(1, 16, ())      # slot1: 4 pages
        assert pool.free_pages() == 1
        refs_before = pool._refs.copy()
        # Slot2 wants 4 pages, shares slot0's 2 published ones, but the
        # 2 private pages it still needs exceed the 1 free page: the
        # reservation must fail AND roll the shared refcounts back.
        assert pool.try_reserve_prefix(2, 16, h) is None
        assert (pool._refs == refs_before).all()
        assert pool.free_pages() == 1
        # The registry survived the failure: once space frees up the
        # same reservation succeeds with both shared pages.
        pool.release(1)
        res = pool.try_reserve_prefix(2, 16, h)
        assert res is not None and res[1] == 2


class TestDeviceKernels:
    def test_insert_gather_roundtrip(self):
        pool = _pool()
        l, h, d, p = 2, 2, 8, 4
        s_bucket = 8                      # 2 pages
        rng = np.random.default_rng(0)
        prompt = jnp.asarray(rng.normal(size=(l, 1, s_bucket, h, d)),
                             jnp.float32)
        row = pool.try_reserve(0, s_bucket)
        page_ids = jnp.asarray(row[:2])
        pk = paged_cache.PagePool.insert_prompt(pool.pools['k'], prompt,
                                                page_ids)
        view = paged_cache.PagePool.gather_view(
            pk, jnp.asarray(pool.tables))
        # Slot 0's first 8 positions reproduce the prompt KV.
        np.testing.assert_allclose(np.asarray(view[:, 0, :s_bucket]),
                                   np.asarray(prompt[:, 0]), rtol=1e-6)

    def test_append_lands_in_right_page_and_offset(self):
        pool = _pool(n_pages=13)   # 3 slots x 4 pages + dummy
        l, h, d = 2, 2, 8
        rows = [pool.try_reserve(s, 16) for s in range(3)]
        assert all(r is not None for r in rows)
        tables = jnp.asarray(pool.tables)
        lengths = jnp.asarray([0, 5, 11])   # page 0/off 0, p1/o1, p2/o3
        rng = np.random.default_rng(1)
        new_kv = jnp.asarray(rng.normal(size=(l, 3, h, d)), jnp.float32)
        pk = paged_cache.PagePool.append_token(pool.pools['k'], new_kv,
                                               tables, lengths)
        view = paged_cache.PagePool.gather_view(pk, tables)
        for s, pos in enumerate([0, 5, 11]):
            np.testing.assert_allclose(np.asarray(view[:, s, pos]),
                                       np.asarray(new_kv[:, s]),
                                       rtol=1e-6)
        # Nothing else was touched (all other positions still zero).
        mask = np.ones((3, 16), bool)
        for s, pos in enumerate([0, 5, 11]):
            mask[s, pos] = False
        rest = np.asarray(view)[:, mask]
        assert np.abs(rest).max() == 0.0

    def test_incremental_appends_match_dense(self):
        """Append tokens one by one; the gathered view must equal a dense
        cache built by direct writes."""
        pool = _pool()
        l, h, d = 2, 2, 8
        pool.try_reserve(0, 16)
        tables = jnp.asarray(pool.tables)
        dense = np.zeros((l, 16, h, d), np.float32)
        pk = pool.pools['k']
        rng = np.random.default_rng(2)
        for pos in range(9):
            kv = rng.normal(size=(l, 1, h, d)).astype(np.float32)
            dense[:, pos] = kv[:, 0]
            pk = paged_cache.PagePool.append_token(
                pk, jnp.asarray(np.repeat(kv, 3, axis=1)), tables,
                jnp.full((3,), pos, jnp.int32))
        view = paged_cache.PagePool.gather_view(pk, tables)
        np.testing.assert_allclose(np.asarray(view[:, 0]), dense,
                                   rtol=1e-6)

    def test_config_for_engine(self):
        cfg = paged_cache.PagedConfig.for_engine(
            max_seq_len=1024, num_slots=8, page_size=64)
        assert cfg.max_pages_per_slot == 16
        assert cfg.n_pages == 8 * 16 + 1
        half = paged_cache.PagedConfig.for_engine(
            max_seq_len=1024, num_slots=8, page_size=64,
            pool_tokens=4096)
        assert half.n_pages == 64 + 1
