"""Paged KV cache unit tests: host accounting + device kernels match a
dense reference."""
import numpy as np
import pytest

import jax.numpy as jnp

from skypilot_tpu.infer import paged_cache


def _pool(n_pages=9, p=4, l=2, h=2, d=8, slots=3):
    cfg = paged_cache.PagedConfig(page_size=p, n_pages=n_pages,
                                  max_pages_per_slot=4)
    return paged_cache.PagePool(cfg, n_layers=l, kv_heads=h, head_dim=d,
                                num_slots=slots, dtype=jnp.float32)


class TestAccounting:
    def test_reserve_release_cycle(self):
        pool = _pool()
        assert pool.free_pages() == 8
        row = pool.try_reserve(0, 10)      # 3 pages of 4
        assert row is not None
        assert (row[:3] > 0).all() and (row[3:] == 0).all()
        assert pool.free_pages() == 5
        row2 = pool.try_reserve(1, 16)     # 4 pages
        assert row2 is not None
        assert pool.free_pages() == 1
        assert pool.try_reserve(2, 8) is None   # needs 2, only 1 free
        pool.release(0)
        assert pool.free_pages() == 4
        assert (pool.tables[0] == 0).all()
        assert pool.try_reserve(2, 8) is not None

    def test_reservation_capped_at_max_pages(self):
        pool = _pool()
        assert pool.pages_needed(10_000) == 4   # max_pages_per_slot
        assert pool.try_reserve(0, 10_000) is not None

    def test_double_reserve_asserts(self):
        pool = _pool()
        pool.try_reserve(0, 4)
        with pytest.raises(AssertionError):
            pool.try_reserve(0, 4)


class TestDeviceKernels:
    def test_insert_gather_roundtrip(self):
        pool = _pool()
        l, h, d, p = 2, 2, 8, 4
        s_bucket = 8                      # 2 pages
        rng = np.random.default_rng(0)
        prompt = jnp.asarray(rng.normal(size=(l, 1, s_bucket, h, d)),
                             jnp.float32)
        row = pool.try_reserve(0, s_bucket)
        page_ids = jnp.asarray(row[:2])
        pk = paged_cache.PagePool.insert_prompt(pool.pools['k'], prompt,
                                                page_ids)
        view = paged_cache.PagePool.gather_view(
            pk, jnp.asarray(pool.tables))
        # Slot 0's first 8 positions reproduce the prompt KV.
        np.testing.assert_allclose(np.asarray(view[:, 0, :s_bucket]),
                                   np.asarray(prompt[:, 0]), rtol=1e-6)

    def test_append_lands_in_right_page_and_offset(self):
        pool = _pool(n_pages=13)   # 3 slots x 4 pages + dummy
        l, h, d = 2, 2, 8
        rows = [pool.try_reserve(s, 16) for s in range(3)]
        assert all(r is not None for r in rows)
        tables = jnp.asarray(pool.tables)
        lengths = jnp.asarray([0, 5, 11])   # page 0/off 0, p1/o1, p2/o3
        rng = np.random.default_rng(1)
        new_kv = jnp.asarray(rng.normal(size=(l, 3, h, d)), jnp.float32)
        pk = paged_cache.PagePool.append_token(pool.pools['k'], new_kv,
                                               tables, lengths)
        view = paged_cache.PagePool.gather_view(pk, tables)
        for s, pos in enumerate([0, 5, 11]):
            np.testing.assert_allclose(np.asarray(view[:, s, pos]),
                                       np.asarray(new_kv[:, s]),
                                       rtol=1e-6)
        # Nothing else was touched (all other positions still zero).
        mask = np.ones((3, 16), bool)
        for s, pos in enumerate([0, 5, 11]):
            mask[s, pos] = False
        rest = np.asarray(view)[:, mask]
        assert np.abs(rest).max() == 0.0

    def test_incremental_appends_match_dense(self):
        """Append tokens one by one; the gathered view must equal a dense
        cache built by direct writes."""
        pool = _pool()
        l, h, d = 2, 2, 8
        pool.try_reserve(0, 16)
        tables = jnp.asarray(pool.tables)
        dense = np.zeros((l, 16, h, d), np.float32)
        pk = pool.pools['k']
        rng = np.random.default_rng(2)
        for pos in range(9):
            kv = rng.normal(size=(l, 1, h, d)).astype(np.float32)
            dense[:, pos] = kv[:, 0]
            pk = paged_cache.PagePool.append_token(
                pk, jnp.asarray(np.repeat(kv, 3, axis=1)), tables,
                jnp.full((3,), pos, jnp.int32))
        view = paged_cache.PagePool.gather_view(pk, tables)
        np.testing.assert_allclose(np.asarray(view[:, 0]), dense,
                                   rtol=1e-6)

    def test_config_for_engine(self):
        cfg = paged_cache.PagedConfig.for_engine(
            max_seq_len=1024, num_slots=8, page_size=64)
        assert cfg.max_pages_per_slot == 16
        assert cfg.n_pages == 8 * 16 + 1
        half = paged_cache.PagedConfig.for_engine(
            max_seq_len=1024, num_slots=8, page_size=64,
            pool_tokens=4096)
        assert half.n_pages == 64 + 1
