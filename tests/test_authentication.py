"""SSH key lifecycle tests (reference: sky/authentication.py)."""
import os
import stat

import pytest

from skypilot_tpu import authentication


@pytest.fixture
def fresh_home(tmp_path, monkeypatch):
    """A HOME with no ~/.ssh at all — the first-run machine."""
    home = tmp_path / 'home'
    home.mkdir()
    monkeypatch.setenv('HOME', str(home))
    yield home


def test_generates_keypair_on_fresh_home(fresh_home):
    priv, pub = authentication.get_or_generate_keypair()
    assert os.path.exists(priv)
    assert os.path.exists(priv + '.pub')
    assert pub.split()[0] in ('ssh-ed25519', 'ssh-rsa')
    mode = stat.S_IMODE(os.stat(priv).st_mode)
    assert mode == 0o600
    ssh_dir = os.path.dirname(priv)
    assert stat.S_IMODE(os.stat(ssh_dir).st_mode) == 0o700


def test_generation_is_idempotent(fresh_home):
    priv1, pub1 = authentication.get_or_generate_keypair()
    with open(priv1, 'rb') as f:
        key_bytes = f.read()
    priv2, pub2 = authentication.get_or_generate_keypair()
    assert (priv1, pub1) == (priv2, pub2)
    with open(priv2, 'rb') as f:
        assert f.read() == key_bytes


def test_public_key_prefers_existing_user_key(fresh_home):
    ssh = fresh_home / '.ssh'
    ssh.mkdir(mode=0o700)
    (ssh / 'id_ed25519.pub').write_text('ssh-ed25519 AAAA user@host\n')
    (ssh / 'id_ed25519').write_text('fake-private\n')
    assert authentication.public_key() == 'ssh-ed25519 AAAA user@host'
    # No skyt-key generated when a user key exists.
    assert not (ssh / 'skyt-key').exists()
    assert authentication.private_key_path() == str(ssh / 'id_ed25519')


def test_private_key_matches_generated(fresh_home):
    priv, _ = authentication.get_or_generate_keypair()
    assert authentication.private_key_path() == priv


def test_half_present_pair_regenerated(fresh_home):
    ssh = fresh_home / '.ssh'
    ssh.mkdir(mode=0o700)
    (ssh / 'skyt-key').write_text('orphaned private half\n')
    priv, pub = authentication.get_or_generate_keypair()
    with open(priv, 'r', encoding='utf-8') as f:
        assert 'orphaned' not in f.read()
    assert pub


def test_backend_public_key_generates(fresh_home, tmp_state_dir):
    from skypilot_tpu.backends import tpu_backend
    pub = tpu_backend._public_key()
    assert pub and pub.split()[0] in ('ssh-ed25519', 'ssh-rsa')
