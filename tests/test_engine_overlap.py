"""Host-device overlap layer of the inference engine: batched prefill
admission and the vectorized chunk-delivery path.

Golden contract: with batch_admission on, token streams (including
logprobs, EOS cutoffs, and seeded sampling) must match the sequential
admission path's exactly — batching may only change HOW MANY device
dispatches admission takes, never what any request receives.
"""
import dataclasses
import queue
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.models import llama

pytestmark = pytest.mark.heavy


@pytest.fixture(scope='module')
def small_model():
    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    return model, params


def _run_burst(model, params, prompts, params_list, *, batch, **kw):
    """Submit all prompts BEFORE starting the loop (a deterministic
    same-tick burst), drain every stream, return (streams, perf)."""
    eng = engine_lib.InferenceEngine(model, params, num_slots=4,
                                     max_seq_len=64,
                                     prefill_buckets=[16],
                                     batch_admission=batch, **kw)
    qs = [eng.submit(p, sp)[1] for p, sp in zip(prompts, params_list)]
    eng.start()
    try:
        outs = []
        for q in qs:
            items = []
            while True:
                it = q.get(timeout=120)
                if it is None:
                    break
                items.append(it)
            outs.append(items)
    finally:
        eng.stop()
    return outs, dict(eng.perf)


def test_burst_uses_one_prefill_dispatch(small_model):
    """A same-bucket burst that fits the free slots must prefill in ONE
    device dispatch (the sequential path takes one per request)."""
    model, params = small_model
    prompts = [[1, 2, 3], [7, 8], [5, 5, 5, 5]]   # all bucket 16
    sps = [engine_lib.SamplingParams(max_new_tokens=4)
           for _ in prompts]
    outs, perf = _run_burst(model, params, prompts, sps, batch=True)
    assert perf['admitted_requests'] == 3
    assert perf['prefill_dispatches'] == 1
    assert perf['admission_batch_size'] == 3
    assert all(len(o) == 4 for o in outs)
    # And the sequential reference really does take one per request.
    _, perf_seq = _run_burst(model, params, prompts, sps, batch=False)
    assert perf_seq['prefill_dispatches'] == 3
    assert perf['prefill_dispatches'] < perf_seq['prefill_dispatches']


def test_batched_streams_match_sequential_greedy(small_model):
    model, params = small_model
    prompts = [[1, 2, 3], [7, 8], [5, 5, 5, 5], [9, 1]]
    sps = [engine_lib.SamplingParams(max_new_tokens=6)
           for _ in prompts]
    got, _ = _run_burst(model, params, prompts, sps, batch=True)
    want, _ = _run_burst(model, params, prompts, sps, batch=False)
    assert got == want


def test_batched_streams_match_sequential_sampled(small_model):
    """Seeded temperature/top-k/top-p sampling: identical req-id order
    means identical rng streams, so outputs must match token for
    token."""
    model, params = small_model
    prompts = [[3, 1, 4], [1, 5, 9, 2], [6, 5]]
    sps = [engine_lib.SamplingParams(max_new_tokens=6, temperature=0.9,
                                     top_k=8, top_p=0.95, seed=s)
           for s in (11, 22, 33)]
    got, _ = _run_burst(model, params, prompts, sps, batch=True)
    want, _ = _run_burst(model, params, prompts, sps, batch=False)
    assert got == want


def test_batched_streams_match_sequential_logprobs(small_model):
    model, params = small_model
    prompts = [[2, 4, 6], [8, 10]]
    sps = [engine_lib.SamplingParams(max_new_tokens=5, logprobs=True)
           for _ in prompts]
    got, _ = _run_burst(model, params, prompts, sps, batch=True)
    want, _ = _run_burst(model, params, prompts, sps, batch=False)
    for g, w in zip(got, want):
        assert [t for t, _ in g] == [t for t, _ in w]
        np.testing.assert_allclose([lp for _, lp in g],
                                   [lp for _, lp in w],
                                   rtol=1e-5, atol=1e-6)


def test_eos_mid_chunk_cutoff_matches(small_model):
    """EOS landing mid-decode-chunk: the vectorized cutoff must deliver
    exactly up to and including the EOS token on both paths."""
    model, params = small_model
    prompt = [5, 17, 3, 99, 42]
    sp = engine_lib.SamplingParams(max_new_tokens=12)
    ref, _ = _run_burst(model, params, [prompt], [sp], batch=False)
    assert len(ref[0]) >= 4
    eos = ref[0][2]   # third generated token -> EOS cuts mid-chunk
    sp_eos = engine_lib.SamplingParams(max_new_tokens=12,
                                       eos_token=eos)
    for batch in (False, True):
        got, _ = _run_burst(model, params, [prompt, [7, 8]],
                            [sp_eos, engine_lib.SamplingParams(
                                max_new_tokens=12)], batch=batch)
        assert got[0] == ref[0][:3]          # ends AT the eos token
        assert got[1] == _run_burst(model, params, [[7, 8]],
                                    [engine_lib.SamplingParams(
                                        max_new_tokens=12)],
                                    batch=False)[0][0]


def test_cancel_mid_stream_terminates_and_frees_slot(small_model):
    """Cancel while decoding: the stream ends (None) without the full
    max_new_tokens, the slot frees, and the engine keeps serving."""
    model, params = small_model
    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16],
                                     decode_chunk=2)
    eng.start()
    try:
        rid, q = eng.submit([5, 17, 3],
                            engine_lib.SamplingParams(
                                max_new_tokens=48))
        got = [q.get(timeout=120)]           # stream is live
        assert eng.cancel(rid)
        deadline = time.time() + 60
        while time.time() < deadline:
            it = q.get(timeout=120)
            got.append(it)
            if it is None:
                break
        assert got[-1] is None
        assert len(got) - 1 < 48             # actually cut short
        # Slot really freed: a fresh request still completes.
        out = eng.generate([7, 8], engine_lib.SamplingParams(
            max_new_tokens=3))
        assert len(out) == 3
    finally:
        eng.stop()


def test_burst_larger_than_slots_batches_in_waves(small_model):
    """More requests than slots: admission proceeds in batched waves as
    slots free; total dispatches stay below one per request."""
    model, params = small_model
    prompts = [[(i * 3 + j) % 50 + 1 for j in range(6)]
               for i in range(8)]
    sps = [engine_lib.SamplingParams(max_new_tokens=5)
           for _ in prompts]
    got, perf = _run_burst(model, params, prompts, sps, batch=True)
    assert perf['admitted_requests'] == 8
    assert perf['prefill_dispatches'] < 8
    want, _ = _run_burst(model, params, prompts, sps, batch=False)
    assert got == want


def test_batched_admission_paged_mode(small_model):
    """Paged cache: the batch path reserves pages per request and
    scatters rows from one batched prefill; streams match the
    sequential paged path."""
    model, params = small_model
    prompts = [[1, 2, 3], [7, 8], [5, 5, 5, 5]]
    sps = [engine_lib.SamplingParams(max_new_tokens=5)
           for _ in prompts]
    got, perf = _run_burst(model, params, prompts, sps, batch=True,
                           cache_mode='paged', page_size=16,
                           prefix_caching=False)
    want, _ = _run_burst(model, params, prompts, sps, batch=False,
                         cache_mode='paged', page_size=16,
                         prefix_caching=False)
    assert got == want
    assert perf['prefill_dispatches'] == 1
    assert perf['admitted_requests'] == 3


def test_perf_stats_concurrent_with_appends(small_model):
    """ADVICE r5: /stats percentile math over the TTFT deque must not
    race the engine thread's appends — hammer perf_stats() while
    requests complete."""
    model, params = small_model
    eng = engine_lib.InferenceEngine(model, params, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16])
    eng.start()
    errs = []

    def hammer():
        deadline = time.time() + 8
        while time.time() < deadline:
            try:
                eng.perf_stats()
                eng.stats()
            except Exception as e:  # pylint: disable=broad-except
                errs.append(e)
                return
    t = threading.Thread(target=hammer)
    t.start()
    try:
        for i in range(6):
            eng.generate([i + 1, i + 2],
                         engine_lib.SamplingParams(max_new_tokens=2))
    finally:
        t.join()
        eng.stop()
    assert not errs


def test_batched_put_preserves_queue_protocol():
    q = queue.Queue()
    engine_lib._put_many(q, [1, 2, 3])
    engine_lib._put_many(q, [])
    q.put(None)
    assert [q.get() for _ in range(4)] == [1, 2, 3, None]
