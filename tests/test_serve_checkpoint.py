"""Serve a real HF-format checkpoint END-TO-END and verify greedy
continuations through the HTTP path match transformers.

The reference's serving story is `--model <hf id>` into vLLM
(llm/vllm/serve.yaml); ours is `--checkpoint <dir>` into the TPU-native
engine. This test drives the full served path — safetensors from disk →
server subprocess → HTTP /generate — not just the loader (VERDICT r2
missing #5). The checkpoint is written by save_hf_checkpoint (HF layout:
config.json + model.safetensors), the same format released Llama weights
ship in; swap the dir for a downloaded snapshot and nothing changes.
"""
import dataclasses
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.integration


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


@pytest.fixture(scope='module')
def ckpt_dir(tmp_path_factory):
    from skypilot_tpu.models import llama, weights
    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(7),
                                 jnp.zeros((1, 8), jnp.int32))
    out = tmp_path_factory.mktemp('served_ckpt')
    weights.save_hf_checkpoint(cfg, params, str(out))
    return str(out)


def _post(url, payload, timeout=300):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={'Content-Type': 'application/json'})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_served_checkpoint_matches_transformers(ckpt_dir):
    transformers = pytest.importorskip('transformers')
    torch = pytest.importorskip('torch')

    port = _free_port()
    env = {**os.environ, 'PYTHONPATH': REPO, 'JAX_PLATFORMS': 'cpu'}
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.infer.server',
         '--checkpoint', ckpt_dir, '--port', str(port),
         '--num-slots', '2', '--max-seq-len', '64',
         # f32 for exact greedy parity with transformers: the debug
         # model's random weights leave logits nearly tied, so bf16
         # rounding flips argmax (real trained weights serve in bf16).
         '--dtype', 'float32'],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    base = f'http://127.0.0.1:{port}'
    try:
        deadline = time.time() + 180
        ready = False
        while time.time() < deadline and proc.poll() is None:
            try:
                with urllib.request.urlopen(f'{base}/health', timeout=2):
                    ready = True
                    break
            except OSError:
                time.sleep(0.5)
        assert ready, ('server never became healthy: '
                       + (proc.stdout.read() if proc.poll() is not None
                          else 'still starting'))

        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, 250, n).tolist() for n in (5, 12, 21)]
        served = []
        for p in prompts:
            r = _post(f'{base}/generate',
                      {'tokens': p, 'max_tokens': 8, 'temperature': 0})
            served.append(r['tokens'])

        hf = transformers.LlamaForCausalLM.from_pretrained(ckpt_dir)
        hf.eval()
        for p, got in zip(prompts, served):
            with torch.no_grad():
                full = hf.generate(
                    torch.tensor([p]), max_new_tokens=8,
                    do_sample=False).numpy()[0].tolist()
            assert full[len(p):] == got, (p, full[len(p):], got)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
