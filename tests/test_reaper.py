"""Orphan-reaper tests: the job process group must die when its agent
dies (reference analog: sky/skylet/subprocess_daemon.py).

Two tiers: the reaper process in isolation (fake parent), and the full
agent path on a local cluster (kill -9 the real agent, assert the job
tree is reaped).
"""
import os
import signal
import subprocess
import sys
import time

import pytest

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _alive(pid: int) -> bool:
    try:
        os.waitpid(pid, os.WNOHANG)
    except (ChildProcessError, OSError):
        pass
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    # Direct children linger as zombies until waited; /proc disambiguates.
    try:
        with open(f'/proc/{pid}/stat', 'r', encoding='utf-8') as f:
            return f.read().split()[2] != 'Z'
    except OSError:
        return False


def _spawn_reaper(parent_pid: int, target_pid: int) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.runtime.reaper',
         '--parent-pid', str(parent_pid),
         '--target-pid', str(target_pid),
         '--poll-interval', '0.2', '--term-grace', '2'],
        cwd=REPO, env={**os.environ, 'PYTHONPATH': REPO},
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def test_reaper_kills_group_on_parent_death():
    fake_parent = subprocess.Popen(['sleep', '300'])
    # Job session: a bash with a child, to prove the whole GROUP dies.
    job = subprocess.Popen(['bash', '-c', 'sleep 300 & wait'],
                           start_new_session=True)
    reaper = _spawn_reaper(fake_parent.pid, job.pid)
    try:
        time.sleep(0.5)
        assert _alive(job.pid)
        fake_parent.kill()
        fake_parent.wait()
        deadline = time.time() + 10
        while time.time() < deadline and _alive(job.pid):
            time.sleep(0.2)
        assert not _alive(job.pid), 'job survived agent death'
        assert reaper.wait(timeout=10) == 0
    finally:
        for p in (fake_parent, job, reaper):
            try:
                p.kill()
            except OSError:
                pass


def test_reaper_exits_when_job_finishes():
    job = subprocess.Popen(['sleep', '0.3'], start_new_session=True)
    reaper = _spawn_reaper(os.getpid(), job.pid)
    try:
        job.wait()
        assert reaper.wait(timeout=10) == 0
    finally:
        try:
            reaper.kill()
        except OSError:
            pass


@pytest.mark.integration
def test_agent_death_reaps_job(tmp_path, tmp_state_dir, monkeypatch):
    """kill -9 the real agent of a local cluster; the running job's
    process tree must be reaped by the spawned reaper."""
    monkeypatch.setenv('SKYT_LOCAL_ROOT', str(tmp_path / 'local'))

    import skypilot_tpu as sky
    from skypilot_tpu import core, execution
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.provision.local import instance as local_instance

    pid_file = tmp_path / 'jobpid'
    t = sky.Task(name='orphan',
                 run=f'echo $$ > {pid_file}; sleep 300')
    t.set_resources(resources_lib.Resources(cloud='local'))
    execution.launch(t, cluster_name='c-orphan', detach_run=True)
    try:
        deadline = time.time() + 30
        while time.time() < deadline and not pid_file.exists():
            time.sleep(0.2)
        assert pid_file.exists(), 'job never started'
        job_pid = int(pid_file.read_text().strip())
        assert _alive(job_pid)

        agent_pid = local_instance._agent_pid('c-orphan', 0)
        assert agent_pid is not None
        os.kill(agent_pid, signal.SIGKILL)

        deadline = time.time() + 15
        while time.time() < deadline and _alive(job_pid):
            time.sleep(0.3)
        assert not _alive(job_pid), 'job survived agent SIGKILL'
    finally:
        try:
            core.down('c-orphan', purge=True)
        except Exception:  # pylint: disable=broad-except
            pass
