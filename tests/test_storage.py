"""Storage layer tests — run fully offline on the local:// store.

Reference test strategy: sky tests/test_storage.py + storage smoke tests
(SURVEY.md §4.6); here the LocalStore gives the same lifecycle coverage
without a cloud.
"""
import os

import pytest

from skypilot_tpu import core
from skypilot_tpu import exceptions
from skypilot_tpu import state
from skypilot_tpu.data import cloud_stores
from skypilot_tpu.data import data_utils
from skypilot_tpu.data import storage as storage_lib
from skypilot_tpu.data import storage_mounting
from skypilot_tpu.data import storage_utils
from skypilot_tpu.utils import command_runner


@pytest.fixture()
def storage_env(tmp_path, tmp_state_dir, monkeypatch):
    monkeypatch.setenv('SKYT_LOCAL_STORAGE_ROOT', str(tmp_path / 'buckets'))
    monkeypatch.setenv('SKYT_DEFAULT_STORE', 'local')
    yield tmp_path


def _make_src(tmp_path, files=('a.txt', 'sub/b.txt')):
    src = tmp_path / 'src'
    for rel in files:
        p = src / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(f'content of {rel}')
    return src


def test_scratch_bucket_lifecycle(storage_env):
    s = storage_lib.Storage(name='scratch-bkt')
    store = s.add_store(storage_lib.StoreType.LOCAL)
    assert store.exists()
    assert state.get_storage('scratch-bkt')['status'] == \
        state.StorageStatus.READY
    s.delete()
    assert not store.exists()
    assert state.get_storage('scratch-bkt') is None


def test_local_source_upload(storage_env):
    src = _make_src(storage_env)
    s = storage_lib.Storage(name='up-bkt', source=str(src))
    store = s.add_store(storage_lib.StoreType.LOCAL)
    assert (storage_env / 'buckets' / 'up-bkt' / 'a.txt').read_text() == \
        'content of a.txt'
    assert (storage_env / 'buckets' / 'up-bkt' / 'sub' / 'b.txt').exists()
    store.delete()


def test_skyignore_excludes_upload(storage_env):
    src = _make_src(storage_env, files=('keep.txt', 'drop.log', 'x.pyc'))
    (src / '.skytignore').write_text('*.log\n# comment\n')
    s = storage_lib.Storage(name='ign-bkt', source=str(src))
    s.add_store(storage_lib.StoreType.LOCAL)
    bucket = storage_env / 'buckets' / 'ign-bkt'
    assert (bucket / 'keep.txt').exists()
    assert not (bucket / 'drop.log').exists()
    assert not (bucket / 'x.pyc').exists()  # default excludes


def test_excluded_files_precedence(tmp_path):
    src = tmp_path / 'd'
    src.mkdir()
    (src / '.gitignore').write_text('git-only\n')
    assert 'git-only' in storage_utils.get_excluded_files(str(src))
    (src / '.skytignore').write_text('skyt-only\n')
    excludes = storage_utils.get_excluded_files(str(src))
    assert 'skyt-only' in excludes
    assert 'git-only' not in excludes


def test_external_bucket_not_deleted(storage_env):
    # Pre-create the bucket out-of-band => treated as external.
    bucket = storage_env / 'buckets' / 'ext-bkt'
    bucket.mkdir(parents=True)
    (bucket / 'data.txt').write_text('external')
    s = storage_lib.Storage(source='local://ext-bkt')
    assert s.name == 'ext-bkt'
    store = s.add_store(storage_lib.StoreType.LOCAL)
    assert not store.sky_managed
    s.delete()
    assert bucket.exists()  # external data survives delete


def test_missing_source_bucket_raises(storage_env):
    s = storage_lib.Storage(source='local://no-such-bkt')
    with pytest.raises(exceptions.StorageBucketGetError):
        s.add_store(storage_lib.StoreType.LOCAL)


def test_storage_validation():
    with pytest.raises(exceptions.StorageError):
        storage_lib.Storage()  # neither name nor source
    with pytest.raises(exceptions.StorageNameError):
        storage_lib.Storage(name='UPPER')  # invalid bucket name
    with pytest.raises(exceptions.StorageSourceError):
        storage_lib.Storage(name='ok-name', source='/no/such/path')
    with pytest.raises(exceptions.StorageSourceError):
        storage_lib.Storage(source='ftp://foreign')  # unmanaged scheme
    # s3://, r2://, and cos:// became managed schemes (S3Store/R2Store/
    # IbmCosStore).
    assert storage_lib.Storage(source='s3://foreign').requested_store \
        == storage_lib.StoreType.S3
    assert storage_lib.Storage(source='cos://foreign').requested_store \
        == storage_lib.StoreType.COS


def test_mount_mode_symlink(storage_env):
    host = storage_env / 'host0'
    host.mkdir()
    runner = command_runner.LocalProcessRunner(str(host))
    mount_path = str(host / 'mnt' / 'data')
    storage_mounting.mount_storages(
        [runner], {mount_path: {'name': 'mnt-bkt', 'mode': 'MOUNT'}})
    # Writes through the mount land in the bucket (MOUNT semantics).
    with open(os.path.join(mount_path, 'out.txt'), 'w',
              encoding='utf-8') as f:
        f.write('written-via-mount')
    assert (storage_env / 'buckets' / 'mnt-bkt' / 'out.txt').read_text() \
        == 'written-via-mount'
    storage_mounting.unmount_storages([runner], {mount_path: None})
    assert not os.path.lexists(mount_path)
    # Bucket data survives unmount.
    assert (storage_env / 'buckets' / 'mnt-bkt' / 'out.txt').exists()


def test_copy_mode(storage_env):
    src = _make_src(storage_env)
    host = storage_env / 'host0'
    host.mkdir()
    runner = command_runner.LocalProcessRunner(str(host))
    target = str(host / 'data')
    storage_mounting.mount_storages(
        [runner],
        {target: {'name': 'cp-bkt', 'source': str(src), 'mode': 'COPY'}})
    assert (host / 'data' / 'a.txt').read_text() == 'content of a.txt'
    # COPY is a snapshot: bucket changes don't propagate.
    (storage_env / 'buckets' / 'cp-bkt' / 'new.txt').write_text('later')
    assert not (host / 'data' / 'new.txt').exists()


def test_core_storage_ls_delete(storage_env):
    s = storage_lib.Storage(name='ls-bkt')
    s.add_store(storage_lib.StoreType.LOCAL)
    names = [r['name'] for r in core.storage_ls()]
    assert 'ls-bkt' in names
    core.storage_delete('ls-bkt')
    assert core.storage_ls() == []
    with pytest.raises(exceptions.StorageError):
        core.storage_delete('ls-bkt')


def test_storage_yaml_roundtrip(storage_env):
    cfg = {'name': 'yml-bkt', 'mode': 'COPY', 'persistent': False}
    s = storage_lib.Storage.from_yaml_config(cfg)
    assert s.mode is storage_lib.StorageMode.COPY
    assert not s.persistent
    out = s.to_yaml_config()
    assert out['name'] == 'yml-bkt'
    assert out['mode'] == 'COPY'
    assert out['persistent'] is False


def test_download_commands():
    cmd = cloud_stores.download_command('gs://bkt/path', '/dst')
    assert 'gsutil' in cmd and '/dst' in cmd
    cmd = cloud_stores.download_command('s3://bkt/path', '/dst')
    assert 'aws s3 sync' in cmd
    cmd = cloud_stores.download_command('https://x.test/f.bin', '/dst')
    assert 'curl' in cmd
    with pytest.raises(exceptions.StorageSourceError):
        cloud_stores.download_command('ftp://x/y', '/dst')


def test_split_uri():
    assert data_utils.split_uri('gs://b/a/c.txt') == ('gs', 'b', 'a/c.txt')
    assert data_utils.split_uri('local://bkt') == ('local', 'bkt', '')
    with pytest.raises(exceptions.StorageSourceError):
        data_utils.split_uri('not-a-uri')
