"""Test configuration.

Force JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPUs (the fake multi-host harness the reference lacks —
SURVEY.md §4 implication). Must run before jax is imported anywhere.
"""
import os
import sys

# Force-set (not setdefault): the base image pins JAX_PLATFORMS=axon (the
# tunneled TPU) and its sitecustomize additionally pins the jax config, so
# both the env var and jax.config must be overridden before first use.
os.environ['JAX_PLATFORMS'] = 'cpu'
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()

# Persistent XLA compilation cache: the heavy tier's cost is almost
# entirely re-compiling the same debug-model programs in every test
# process on the 1-core host (measured: 3.8s -> 0.8s for the llama
# debug init+apply pair on the second process). Subprocess-driven tests
# (agents, multihost selftests, local-provider jobs) inherit the env
# var, so they hit the same cache. The cpu_aot_loader 'machine feature'
# stderr warnings this produces are the loader's pseudo-feature check
# tripping on same-host artifacts — artifacts never leave this machine.
os.environ.setdefault('JAX_COMPILATION_CACHE_DIR',
                      os.path.join(os.path.expanduser('~'),
                                   '.cache', 'skyt_jax_cache'))
os.environ.setdefault('JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS', '1')

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_compilation_cache_dir',
                  os.environ['JAX_COMPILATION_CACHE_DIR'])
jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)

# Make the repo root importable when pytest is run from anywhere.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture()
def tmp_state_dir(tmp_path, monkeypatch):
    """Redirect the framework's state directory (~/.skypilot_tpu) to tmp."""
    monkeypatch.setenv('SKYT_STATE_DIR', str(tmp_path / 'state'))
    # Reset cached module-level state DB handles between tests.
    import skypilot_tpu.state as state
    state.reset_db_for_testing()
    yield tmp_path / 'state'
    state.reset_db_for_testing()


def pytest_configure(config):
    config.addinivalue_line(
        'markers', 'integration: spawns real agent/controller subprocesses')
    config.addinivalue_line(
        'markers', 'heavy: compile-heavy JAX suites / long subprocess '
        'suites excluded from the fast tier (see format.sh)')


@pytest.fixture(scope='session', autouse=True)
def _reap_orphaned_test_agents(tmp_path_factory):
    """Kill pytest-spawned runtime agents left running at session end.
    Some kill -9 scenarios (dead-controller tests) can leave an agent
    polling forever — 0.3% CPU + ~200MB each on the 1-core host.

    Two precise rules (so concurrent pytest sessions never kill each
    other's live agents):
      * any agent whose --config lives under THIS session's basetemp —
        every cluster of ours is down by now, so a survivor is an
        orphan (pytest retains the last 3 basetemps, so "config file
        still exists" does NOT imply live);
      * any agent whose --config file no longer exists (stale leftover
        from an older, rotated-out session).
    """
    yield
    import re
    import signal as sig
    import subprocess
    base = str(tmp_path_factory.getbasetemp().resolve())
    try:
        out = subprocess.run(['ps', '-eo', 'pid,args'], text=True,
                             capture_output=True, timeout=10).stdout
    except Exception:  # pylint: disable=broad-except
        return
    for line in out.splitlines():
        m = re.search(r'^\s*(\d+)\s+.*skypilot_tpu\.runtime\.agent'
                      r'\s+--config\s+(\S+)', line)
        if not m:
            continue
        cfg_path = m.group(2)
        ours = os.path.realpath(cfg_path).startswith(base + os.sep)
        if ours or not os.path.exists(cfg_path):
            try:
                os.kill(int(m.group(1)), sig.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
