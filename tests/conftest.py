"""Test configuration.

Force JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths are
exercised without TPUs (the fake multi-host harness the reference lacks —
SURVEY.md §4 implication). Must run before jax is imported anywhere.
"""
import os
import sys

# Force-set (not setdefault): the base image pins JAX_PLATFORMS=axon (the
# tunneled TPU) and its sitecustomize additionally pins the jax config, so
# both the env var and jax.config must be overridden before first use.
os.environ['JAX_PLATFORMS'] = 'cpu'
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

# Make the repo root importable when pytest is run from anywhere.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture()
def tmp_state_dir(tmp_path, monkeypatch):
    """Redirect the framework's state directory (~/.skypilot_tpu) to tmp."""
    monkeypatch.setenv('SKYT_STATE_DIR', str(tmp_path / 'state'))
    # Reset cached module-level state DB handles between tests.
    import skypilot_tpu.state as state
    state.reset_db_for_testing()
    yield tmp_path / 'state'
    state.reset_db_for_testing()


def pytest_configure(config):
    config.addinivalue_line(
        'markers', 'integration: spawns real agent/controller subprocesses')
    config.addinivalue_line(
        'markers', 'heavy: compile-heavy JAX suites / long subprocess '
        'suites excluded from the fast tier (see format.sh)')
