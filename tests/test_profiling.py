"""jax.profiler collection tests (SURVEY.md §5: `skyt logs --profile`).

Tier 1: StepProfiler writes a TensorBoard-loadable trace
(plugins/profile/<ts>/*.xplane.pb) around the requested steps.
Tier 2: full path — job launched with SKYT_PROFILE=1 on a local cluster,
trace collected by the agent env contract, synced down with the logs.
"""
import glob
import os
import time

import pytest


def _xplanes(root: str):
    return glob.glob(os.path.join(root, '**', '*.xplane.pb'),
                     recursive=True)


def test_step_profiler_writes_trace(tmp_path, monkeypatch):
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.utils import profiling

    monkeypatch.setenv('SKYT_PROFILE_START_STEP', '1')
    monkeypatch.setenv('SKYT_PROFILE_NUM_STEPS', '2')
    prof = profiling.StepProfiler(trace_dir=str(tmp_path / 'trace'))
    assert prof.enabled

    @jax.jit
    def f(x):
        return (x @ x).sum()

    x = jnp.ones((64, 64))
    for step in range(5):
        prof.on_step(step)
        f(x).block_until_ready()
    prof.stop()
    assert _xplanes(str(tmp_path / 'trace')), 'no xplane.pb written'


def test_step_profiler_disabled_is_noop(monkeypatch):
    from skypilot_tpu.utils import profiling

    monkeypatch.delenv('SKYT_PROFILE_DIR', raising=False)
    prof = profiling.StepProfiler()
    assert not prof.enabled
    for step in range(3):
        prof.on_step(step)   # must not start a trace
    prof.stop()


@pytest.mark.integration
def test_profile_synced_down_with_logs(tmp_path, tmp_state_dir,
                                       monkeypatch):
    monkeypatch.setenv('SKYT_LOCAL_ROOT', str(tmp_path / 'local'))

    import skypilot_tpu as sky
    from skypilot_tpu import core, execution
    from skypilot_tpu import resources as resources_lib

    # Pin the platform from the env var (some TPU images pin a platform
    # plugin in sitecustomize that wins over JAX_PLATFORMS alone — the
    # same dance train/sft.py and infer/server.py do).
    prog = ("import os, jax\n"
            "if os.environ.get('JAX_PLATFORMS'):\n"
            "    jax.config.update('jax_platforms',\n"
            "                      os.environ['JAX_PLATFORMS'])\n"
            "import jax.numpy as jnp\n"
            "from skypilot_tpu.utils import profiling\n"
            "prof = profiling.StepProfiler()\n"
            "assert prof.enabled, 'agent did not set SKYT_PROFILE_DIR'\n"
            "f = jax.jit(lambda x: (x @ x).sum())\n"
            "x = jnp.ones((32, 32))\n"
            "for s in range(5):\n"
            "    prof.on_step(s)\n"
            "    f(x).block_until_ready()\n"
            "prof.stop()\n")
    script = tmp_path / 'prof_job.py'
    script.write_text(prog)

    t = sky.Task(name='profjob',
                 run=f'python {script}',
                 envs={'SKYT_PROFILE': '1',
                       'SKYT_PROFILE_START_STEP': '1',
                       'SKYT_PROFILE_NUM_STEPS': '2'})
    t.set_resources(resources_lib.Resources(cloud='local'))
    jid = execution.launch(t, cluster_name='c-prof', detach_run=True)
    try:
        deadline = time.time() + 90
        while time.time() < deadline:
            status = core.job_status('c-prof', [jid])[jid]
            if status in ('SUCCEEDED', 'FAILED', 'FAILED_SETUP'):
                break
            time.sleep(0.5)
        assert status == 'SUCCEEDED', f'job ended {status}'
        local = core.download_logs(
            'c-prof', jid, local_dir=str(tmp_path / 'synced'))
        # Logs are synced per host: host-<rank>/profile/rank-<r>/...
        prof_root = os.path.join(local, 'host-0', 'profile')
        assert os.path.isdir(prof_root), 'profile dir not synced'
        assert _xplanes(prof_root), 'no xplane.pb in synced trace'
    finally:
        core.down('c-prof', purge=True)


def test_step_profiler_malformed_env_falls_back(monkeypatch):
    """A typo'd SKYT_PROFILE_* value degrades to the default with a
    warning instead of crashing the training job with a ValueError."""
    from skypilot_tpu.utils import profiling

    monkeypatch.setenv('SKYT_PROFILE_START_STEP', 'banana')
    monkeypatch.setenv('SKYT_PROFILE_NUM_STEPS', '2.5')
    prof = profiling.StepProfiler(trace_dir='/tmp/unused')
    assert prof.start_step == 2 and prof.num_steps == 3

    # Out-of-range num_steps (must be >= 1) also falls back.
    monkeypatch.setenv('SKYT_PROFILE_START_STEP', '0')
    monkeypatch.setenv('SKYT_PROFILE_NUM_STEPS', '0')
    prof = profiling.StepProfiler(trace_dir='/tmp/unused')
    assert prof.start_step == 0 and prof.num_steps == 3
