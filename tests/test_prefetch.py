"""Train input-pipeline prefetcher (train/prefetch.py): producer/
consumer overlap, bounded-queue backpressure, and clean shutdown on
stop / source error — the contracts the sft loop relies on.

Pure-host tests (no jax compilation): the prefetcher's concurrency
behavior is independent of what the batches contain.
"""
import threading
import time

import numpy as np
import pytest

from skypilot_tpu.train import prefetch as prefetch_lib


class _CountingSource:
    """Iterator that records how far the producer has pulled it and can
    block until allowed (for deterministic concurrency assertions)."""

    def __init__(self, n=None, fail_at=None, delay=0.0):
        self.n = n
        self.fail_at = fail_at
        self.delay = delay
        self.produced = 0
        self.lock = threading.Lock()

    def __iter__(self):
        i = 0
        while self.n is None or i < self.n:
            if self.fail_at is not None and i == self.fail_at:
                raise RuntimeError(f'source failed at item {i}')
            if self.delay:
                time.sleep(self.delay)
            item = {'tokens': np.full((1, 4), i, np.int32)}
            with self.lock:
                self.produced += 1
            yield item
            i += 1


def _wait_until(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_producer_runs_ahead_of_slow_consumer():
    """While the consumer sits on batch 0 (a slow train step), the
    producer must keep assembling the next batches — the overlap that
    removes input work from the step chain."""
    src = _CountingSource(n=100)
    pf = prefetch_lib.Prefetcher(iter(src), depth=3)
    try:
        first = next(pf)
        assert int(first['tokens'][0, 0]) == 0
        # Consumer is now "busy"; the producer should fill the queue
        # (depth 3) plus the one item it is offering — strictly more
        # than the single consumed batch.
        assert _wait_until(lambda: src.produced >= 4)
    finally:
        pf.close()


def test_bounded_queue_backpressure():
    """An infinite source must not be drained unboundedly: the producer
    can be at most depth + 1 items ahead of the consumer (queue depth
    plus the one item in its hand)."""
    src = _CountingSource(n=None)     # infinite
    pf = prefetch_lib.Prefetcher(iter(src), depth=2)
    try:
        _wait_until(lambda: src.produced >= 3)
        time.sleep(0.3)               # give an unbounded bug time to run
        assert src.produced <= 2 + 1  # depth + in-hand
        consumed = [next(pf) for _ in range(5)]
        assert [int(b['tokens'][0, 0]) for b in consumed] == \
            [0, 1, 2, 3, 4]           # order preserved
        _wait_until(lambda: src.produced >= 8)
        time.sleep(0.2)
        assert src.produced <= 5 + 2 + 1
    finally:
        pf.close()


def test_items_delivered_in_order_and_placed():
    """place() runs on the producer thread and its output is what the
    consumer sees (the device_put hook)."""
    placed = []

    def place(batch):
        placed.append(int(batch['tokens'][0, 0]))
        return {k: v + 1000 for k, v in batch.items()}

    pf = prefetch_lib.Prefetcher(iter(_CountingSource(n=5)), depth=2,
                                 place=place)
    try:
        got = [int(b['tokens'][0, 0]) for b in pf]
        assert got == [1000, 1001, 1002, 1003, 1004]
        assert placed == [0, 1, 2, 3, 4]
    finally:
        pf.close()


def test_source_error_propagates_after_good_items():
    """A data bug fails the step loop with the ORIGINAL exception, after
    the items produced before it were delivered."""
    pf = prefetch_lib.Prefetcher(iter(_CountingSource(n=10, fail_at=3)),
                                 depth=2)
    try:
        got = []
        with pytest.raises(RuntimeError, match='failed at item 3'):
            for b in pf:
                got.append(int(b['tokens'][0, 0]))
        assert got == [0, 1, 2]
    finally:
        pf.close()


def test_close_unblocks_full_queue_producer():
    """close() must join a producer parked on the bounded queue's
    backpressure wait (infinite source, consumer gone)."""
    src = _CountingSource(n=None)
    pf = prefetch_lib.Prefetcher(iter(src), depth=1)
    _wait_until(lambda: src.produced >= 1)
    pf.close()
    assert not pf._thread.is_alive()
    # Idempotent.
    pf.close()


def test_finite_source_ends_iteration():
    pf = prefetch_lib.Prefetcher(iter(_CountingSource(n=3)), depth=4)
    try:
        assert len(list(pf)) == 3
        # Exhausted: further next() keeps raising StopIteration.
        with pytest.raises(StopIteration):
            next(pf)
    finally:
        pf.close()


def test_bad_depth_rejected():
    with pytest.raises(ValueError):
        prefetch_lib.Prefetcher(iter(_CountingSource(n=1)), depth=0)


def test_sft_lint_forbids_loop_syncs(tmp_path):
    """The tools/lint.py rule backing the overlap contract: a bare
    jax.device_get inside an sft.py loop is flagged; the real sft.py
    is clean."""
    import sys
    sys.path.insert(0, 'tools')
    try:
        import lint as lint_mod
    finally:
        sys.path.pop(0)
    from pathlib import Path

    bad = tmp_path / 'skypilot_tpu' / 'train'
    bad.mkdir(parents=True)
    f = bad / 'sft.py'
    f.write_text('import jax\n'
                 'for i in range(3):\n'
                 '    x = jax.device_get(i)\n'
                 'y = jax.device_get(1)  # outside a loop: allowed\n')
    issues = lint_mod.check_file(f)
    assert any('device_get() inside the sft step loop' in i
               for i in issues)
    assert len([i for i in issues if 'device_get' in i]) == 1
    # The real sft.py must pass its own rule.
    real = Path('skypilot_tpu/train/sft.py')
    assert not [i for i in lint_mod.check_file(real)
                if 'step loop' in i]
