"""S3/R2 managed stores + data_transfer against a FAKE endpoint.

A stub `aws`/`gsutil` pair on PATH implements the used subcommands
against a local directory tree (FAKE_S3_ROOT / FAKE_GS_ROOT) and records
every invocation — so the store layer's command construction, lifecycle
(create/upload/delete/external-bucket), R2 endpoint plumbing, and the
cross-family transfer spool are all exercised offline.

Reference parity: sky/data/storage.py:1080 (S3Store), :2732 (R2Store),
sky/data/data_transfer.py.
"""
import os
import stat
import subprocess
import textwrap

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.data import data_transfer, storage

# Subprocess-driven (fake cloud CLIs): excluded from the fast tier.
pytestmark = pytest.mark.heavy

FAKE_CLI = textwrap.dedent('''\
    #!/usr/bin/env python3
    """Fake `aws`/`gsutil`: local-dir object stores + invocation log."""
    import os, shutil, sys

    root = os.environ['FAKE_{SCHEME}_ROOT']
    log = os.environ.get('FAKE_CLI_LOG')
    if log:
        with open(log, 'a') as f:
            f.write(' '.join(sys.argv) + '\\n')

    def to_path(uri):
        for scheme in ('s3://', 'gs://'):
            if uri.startswith(scheme):
                return os.path.join(root, uri[len(scheme):].rstrip('/'))
        return uri

    def sync(src, dst):
        src, dst = to_path(src), to_path(dst)
        if not os.path.isdir(src):
            sys.exit(f'sync: no such dir {src}')
        os.makedirs(dst, exist_ok=True)
        shutil.copytree(src, dst, dirs_exist_ok=True)

    args = [a for a in sys.argv[1:]]
    # strip flag-value pairs / flags we only record
    cleaned, skip = [], False
    for i, a in enumerate(args):
        if skip:
            skip = False
            continue
        if a in ('--endpoint-url', '--exclude', '-x'):
            skip = True
            continue
        if a in ('--force', '-m', '-r'):
            continue
        cleaned.append(a)
    cmd = cleaned[0] if cleaned else ''
    if cmd == 's3api' and cleaned[1] == 'head-bucket':
        name = cleaned[cleaned.index('--bucket') + 1]
        sys.exit(0 if os.path.isdir(os.path.join(root, name)) else 1)
    elif cmd == 's3' and cleaned[1] == 'mb':
        os.makedirs(to_path(cleaned[2]), exist_ok=True)
    elif cmd == 's3' and cleaned[1] == 'rb':
        shutil.rmtree(to_path(cleaned[2]), ignore_errors=True)
    elif cmd == 's3' and cleaned[1] == 'sync':
        sync(cleaned[2], cleaned[3])
    elif cmd == 's3' and cleaned[1] == 'cp':
        dst = to_path(cleaned[3])
        os.makedirs(dst if dst.endswith('/') else os.path.dirname(dst),
                    exist_ok=True)
        shutil.copy2(cleaned[2], dst)
    elif cmd == 'rsync':           # gsutil rsync SRC DST
        sync(cleaned[1], cleaned[2])
    elif cmd == 'ls':              # gsutil ls -b gs://name
        uri = cleaned[-1]
        sys.exit(0 if os.path.isdir(to_path(uri)) else 1)
    elif cmd == 'mb':
        os.makedirs(to_path(cleaned[1]), exist_ok=True)
    elif cmd == 'rm':
        shutil.rmtree(to_path(cleaned[1]), ignore_errors=True)
    elif cmd == 'cp':
        sync(cleaned[1], cleaned[2])
    else:
        sys.exit(f'fake cli: unhandled {sys.argv[1:]}')
''')


@pytest.fixture()
def fake_clouds(tmp_path, monkeypatch):
    """Install fake `aws` + `gsutil` on PATH, backed by local roots."""
    bindir = tmp_path / 'bin'
    bindir.mkdir()
    s3_root = tmp_path / 's3root'
    gs_root = tmp_path / 'gsroot'
    s3_root.mkdir()
    gs_root.mkdir()
    log = tmp_path / 'cli.log'
    for name, scheme in (('aws', 'S3'), ('gsutil', 'GS')):
        p = bindir / name
        p.write_text(FAKE_CLI.replace('{SCHEME}', scheme))
        p.chmod(p.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv('PATH', f'{bindir}:{os.environ["PATH"]}')
    monkeypatch.setenv('FAKE_S3_ROOT', str(s3_root))
    monkeypatch.setenv('FAKE_GS_ROOT', str(gs_root))
    monkeypatch.setenv('FAKE_CLI_LOG', str(log))
    return {'s3': s3_root, 'gs': gs_root, 'log': log, 'tmp': tmp_path}


def _mk_source(tmp_path):
    src = tmp_path / 'src'
    (src / 'sub').mkdir(parents=True)
    (src / '.git').mkdir()
    (src / '.git' / 'config').write_text('x')
    (src / 'a.txt').write_text('A')
    (src / 'sub' / 'b.txt').write_text('B')
    return src


class TestS3Store:
    def test_lifecycle(self, fake_clouds, tmp_path, tmp_state_dir):
        src = _mk_source(tmp_path)
        st = storage.Storage(name='unit-bkt', source=str(src),
                             mode=storage.StorageMode.COPY)
        store = st.add_store(storage.StoreType.S3)
        assert store.exists()
        assert (fake_clouds['s3'] / 'unit-bkt' / 'a.txt').read_text() \
            == 'A'
        assert (fake_clouds['s3'] / 'unit-bkt' / 'sub' / 'b.txt'
                ).read_text() == 'B'
        cmd = store.download_command('/data')
        assert 'aws s3 sync s3://unit-bkt /data' in cmd
        mnt = store.mount_command('/mnt')
        assert 'goofys' in mnt and 'unit-bkt /mnt' in mnt
        assert '--endpoint' not in mnt   # plain S3: default endpoint
        st.delete()
        assert not (fake_clouds['s3'] / 'unit-bkt').exists()

    def test_external_bucket_never_deleted(self, fake_clouds,
                                           tmp_state_dir):
        (fake_clouds['s3'] / 'pre-existing').mkdir()
        st = storage.Storage(source='s3://pre-existing')
        store = st.add_store(storage.StoreType.S3)
        assert not store.sky_managed
        st.delete()
        assert (fake_clouds['s3'] / 'pre-existing').exists()

    def test_source_scheme_selects_store(self, fake_clouds):
        st = storage.Storage(source='s3://somewhere')
        assert st.requested_store == storage.StoreType.S3
        st = storage.Storage(source='r2://somewhere')
        assert st.requested_store == storage.StoreType.R2


class TestR2Store:
    def test_requires_endpoint(self, fake_clouds, monkeypatch):
        monkeypatch.delenv('SKYT_R2_ENDPOINT', raising=False)
        monkeypatch.delenv('R2_ENDPOINT', raising=False)
        with pytest.raises(exceptions.StorageError, match='ENDPOINT'):
            storage.R2Store('r2-bkt', None).exists()

    def test_endpoint_on_every_call(self, fake_clouds, tmp_path,
                                    tmp_state_dir, monkeypatch):
        monkeypatch.setenv('SKYT_R2_ENDPOINT',
                           'https://acct.r2.cloudflarestorage.com')
        src = _mk_source(tmp_path)
        st = storage.Storage(name='r2-bkt', source=str(src),
                             mode=storage.StorageMode.COPY)
        store = st.add_store(storage.StoreType.R2)
        assert store.exists()
        assert 'endpoint-url' in store.download_command('/data')
        st.delete()
        calls = fake_clouds['log'].read_text().splitlines()
        aws_calls = [c for c in calls if '/aws' in c.split()[0]]
        assert aws_calls, 'no aws invocations recorded'
        assert all('--endpoint-url '
                   'https://acct.r2.cloudflarestorage.com' in c
                   for c in aws_calls), aws_calls


    def test_r2_mount_command_carries_endpoint(self, fake_clouds,
                                               monkeypatch):
        monkeypatch.setenv('SKYT_R2_ENDPOINT',
                           'https://acct.r2.cloudflarestorage.com')
        store = storage.R2Store('r2-bkt', None)
        mnt = store.mount_command('/mnt')
        assert 'goofys' in mnt and 'r2-bkt /mnt' in mnt
        assert '--endpoint https://acct.r2.cloudflarestorage.com' in mnt


class TestIbmCosStore:
    """IBM COS rides the same S3-compatible endpoint path as R2.
    Reference parity: sky/data/storage.py:3116 (IBMCosStore)."""

    def test_requires_endpoint(self, fake_clouds, monkeypatch):
        monkeypatch.delenv('SKYT_COS_ENDPOINT', raising=False)
        monkeypatch.delenv('COS_ENDPOINT', raising=False)
        with pytest.raises(exceptions.StorageError, match='ENDPOINT'):
            storage.IbmCosStore('cos-bkt', None).exists()

    def test_endpoint_on_every_call(self, fake_clouds, tmp_path,
                                    tmp_state_dir, monkeypatch):
        monkeypatch.setenv(
            'SKYT_COS_ENDPOINT',
            'https://s3.us-south.cloud-object-storage.appdomain.cloud')
        src = _mk_source(tmp_path)
        st = storage.Storage(name='cos-bkt', source=str(src),
                             mode=storage.StorageMode.COPY)
        store = st.add_store(storage.StoreType.COS)
        assert store.exists()
        assert 'endpoint-url' in store.download_command('/data')
        st.delete()
        calls = fake_clouds['log'].read_text().splitlines()
        aws_calls = [c for c in calls if '/aws' in c.split()[0]]
        assert aws_calls, 'no aws invocations recorded'
        assert all('--endpoint-url https://s3.us-south.'
                   'cloud-object-storage.appdomain.cloud' in c
                   for c in aws_calls), aws_calls

    def test_cos_mount_command_carries_endpoint(self, fake_clouds,
                                                monkeypatch):
        monkeypatch.setenv(
            'SKYT_COS_ENDPOINT',
            'https://s3.us-south.cloud-object-storage.appdomain.cloud')
        store = storage.IbmCosStore('cos-bkt', None)
        mnt = store.mount_command('/mnt')
        assert 'goofys' in mnt and 'cos-bkt /mnt' in mnt
        assert ('--endpoint https://s3.us-south.'
                'cloud-object-storage.appdomain.cloud') in mnt

    def test_scheme_selects_store(self, fake_clouds):
        st = storage.Storage(source='cos://somewhere')
        assert st.requested_store == storage.StoreType.COS

    def test_cos_file_mount_download_command(self, fake_clouds,
                                             monkeypatch):
        monkeypatch.setenv('SKYT_COS_ENDPOINT', 'https://cos.example')
        from skypilot_tpu.data import cloud_stores
        cmd = cloud_stores.download_command('cos://bkt/sub', '/data')
        assert 'aws s3 sync s3://bkt/sub /data' in cmd
        assert '--endpoint-url https://cos.example' in cmd

    def test_cos_transfer_cross_family(self, fake_clouds, tmp_path,
                                       monkeypatch):
        monkeypatch.setenv('SKYT_COS_ENDPOINT', 'https://cos.example')
        src = _mk_source(tmp_path)
        subprocess.run(['gsutil', 'mb', 'gs://gsrc'], check=True)
        subprocess.run(['gsutil', 'rsync', str(src), 'gs://gsrc'],
                       check=True)
        data_transfer.transfer('gs://gsrc', 'cos://cdst')
        assert (fake_clouds['s3'] / 'cdst' / 'a.txt').read_text() == 'A'


class TestDataTransfer:
    def test_same_family_direct(self, fake_clouds, tmp_path):
        src = _mk_source(tmp_path)
        subprocess.run(['aws', 's3', 'mb', 's3://bkt-a'], check=True)
        subprocess.run(['aws', 's3', 'sync', str(src), 's3://bkt-a'],
                       check=True)
        data_transfer.transfer('s3://bkt-a', 's3://bkt-b')
        assert (fake_clouds['s3'] / 'bkt-b' / 'sub' / 'b.txt'
                ).read_text() == 'B'
        # Direct: exactly one aws sync bucket->bucket, no spool dirs.
        calls = [c for c in fake_clouds['log'].read_text().splitlines()
                 if 's3 sync s3://bkt-a s3://bkt-b' in c.replace(
                     "' '", ' ')]
        assert not any('skyt-transfer' in c for c in calls)

    def test_cross_family_via_spool(self, fake_clouds, tmp_path):
        src = _mk_source(tmp_path)
        subprocess.run(['gsutil', 'mb', 'gs://gbkt'], check=True)
        subprocess.run(['gsutil', 'rsync', str(src), 'gs://gbkt'],
                       check=True)
        data_transfer.transfer('gs://gbkt', 's3://sbkt')
        assert (fake_clouds['s3'] / 'sbkt' / 'a.txt').read_text() == 'A'

    def test_local_to_s3(self, fake_clouds, tmp_path, monkeypatch):
        monkeypatch.setenv('SKYT_LOCAL_STORAGE_ROOT',
                           str(tmp_path / 'lroot'))
        lsrc = tmp_path / 'lroot' / 'lbkt'
        lsrc.mkdir(parents=True)
        (lsrc / 'x.txt').write_text('X')
        data_transfer.transfer('local://lbkt', 's3://from-local')
        assert (fake_clouds['s3'] / 'from-local' / 'x.txt'
                ).read_text() == 'X'

    def test_rejects_unknown_scheme(self, fake_clouds):
        with pytest.raises(exceptions.StorageSourceError):
            data_transfer.transfer('ftp://x', 'gs://y')


AZ_FAKE = '''#!/usr/bin/env python3
"""Fake `az` CLI: local-dir containers + invocation log. STRICT about
flags (real az rejects unknown arguments; a permissive fake once masked
a nonexistent --exclude-pattern flag)."""
import json, os, shutil, sys

root = os.environ['FAKE_AZ_ROOT']
log = os.environ.get('FAKE_CLI_LOG')
if log:
    with open(log, 'a') as f:
        f.write(' '.join(sys.argv) + '\\n')
args = sys.argv[1:]

KNOWN_FLAGS = {'--account-name': 1, '--output': 1, '--name': 1,
               '--destination': 1, '--source': 1, '--container-name': 1,
               '--file': 1, '--overwrite': 0}
_i = 0
while _i < len(args):
    _a = args[_i]
    if _a.startswith('--'):
        if _a not in KNOWN_FLAGS:
            sys.exit(f'az: unrecognized arguments: {_a}')
        _i += 1 + KNOWN_FLAGS[_a]
    else:
        _i += 1

def val(flag):
    return args[args.index(flag) + 1]

assert args[0] == 'storage', args
assert '--account-name' in args, 'account-name flag required'
if args[1] == 'container' and args[2] == 'create':
    os.makedirs(os.path.join(root, val('--name')), exist_ok=True)
elif args[1] == 'container' and args[2] == 'exists':
    ok = os.path.isdir(os.path.join(root, val('--name')))
    print(json.dumps({'exists': ok}))
elif args[1] == 'container' and args[2] == 'delete':
    shutil.rmtree(os.path.join(root, val('--name')), ignore_errors=True)
elif args[1] == 'blob' and args[2] == 'upload-batch':
    dst = os.path.join(root, val('--destination'))
    shutil.copytree(val('--source'), dst, dirs_exist_ok=True)
elif args[1] == 'blob' and args[2] == 'upload':
    dst = os.path.join(root, val('--container-name'))
    os.makedirs(dst, exist_ok=True)
    shutil.copy2(val('--file'), os.path.join(dst, val('--name')))
elif args[1] == 'blob' and args[2] == 'download-batch':
    shutil.copytree(os.path.join(root, val('--source')),
                    val('--destination'), dirs_exist_ok=True)
else:
    sys.exit(f'fake az: unhandled {args}')
'''


@pytest.fixture()
def fake_azure(tmp_path, monkeypatch, fake_clouds):
    bindir = tmp_path / 'bin'
    az_root = tmp_path / 'azroot'
    az_root.mkdir()
    p = bindir / 'az'
    p.write_text(AZ_FAKE)
    p.chmod(p.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv('FAKE_AZ_ROOT', str(az_root))
    monkeypatch.setenv('SKYT_AZURE_STORAGE_ACCOUNT', 'unitacct')
    return az_root


class TestAzureStore:
    def test_lifecycle(self, fake_azure, tmp_path, tmp_state_dir):
        src = _mk_source(tmp_path)
        st = storage.Storage(name='az-bkt', source=str(src),
                             mode=storage.StorageMode.COPY)
        store = st.add_store(storage.StoreType.AZURE)
        assert store.exists()
        assert (fake_azure / 'az-bkt' / 'a.txt').read_text() == 'A'
        assert (fake_azure / 'az-bkt' / 'sub' / 'b.txt').read_text() \
            == 'B'
        # Client-side excludes: .git never reaches the container.
        assert not (fake_azure / 'az-bkt' / '.git').exists()
        cmd = store.download_command('/data')
        assert 'az storage blob download-batch' in cmd
        assert '--overwrite' in cmd
        mnt = store.mount_command('/mnt')
        assert 'blobfuse2 mount /mnt' in mnt
        assert '--container-name az-bkt' in mnt
        assert 'AZURE_STORAGE_AUTH_TYPE=azcli' in mnt
        st.delete()
        assert not (fake_azure / 'az-bkt').exists()

    def test_requires_account(self, fake_azure, monkeypatch):
        monkeypatch.delenv('SKYT_AZURE_STORAGE_ACCOUNT', raising=False)
        with pytest.raises(exceptions.StorageError, match='ACCOUNT'):
            storage.AzureBlobStore('az-bkt', None).exists()

    def test_scheme_selects_store(self, fake_azure):
        st = storage.Storage(source='az://somewhere')
        assert st.requested_store == storage.StoreType.AZURE

    def test_az_file_mount_download_command(self, fake_azure):
        """Plain az:// file_mount sources route through cloud_stores
        (regression: az was in CLOUD_SCHEMES but two consumers outside
        the data layer didn't know the scheme)."""
        from skypilot_tpu.backends import tpu_backend
        from skypilot_tpu.data import cloud_stores

        assert tpu_backend._is_cloud_uri('az://bkt/path')
        cmd = cloud_stores.download_command('az://bkt/sub', '/data')
        assert 'az storage blob download-batch' in cmd
        assert 'bkt/sub' in cmd and '--overwrite' in cmd
