"""End-to-end slice test (SURVEY.md §7.5): launch → queue → logs → exec →
stop/start → cancel → down, all on the local provider with real agents.

This is the fake-multi-host harness the reference lacks — its equivalent
coverage is cloud smoke tests (tests/test_smoke.py), which need real VMs.
"""
import time

import jax
import pytest
from click.testing import CliRunner

import skypilot_tpu as sky
from skypilot_tpu import core
from skypilot_tpu import execution
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import state
from skypilot_tpu.cli import cli

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


@pytest.fixture()
def local_env(tmp_path, monkeypatch):
    monkeypatch.setenv('SKYT_LOCAL_ROOT', str(tmp_path / 'local'))
    # SKYT_STATE_DIR is isolated by conftest already; reset the cached DB.
    state.reset_db_for_testing()
    yield
    for rec in state.get_clusters():
        try:
            core.down(rec['name'], purge=True)
        except Exception:  # pylint: disable=broad-except
            pass
    state.reset_db_for_testing()


def _local_task(name, run, num_nodes=1):
    t = sky.Task(name=name, run=run, num_nodes=num_nodes)
    t.set_resources(resources_lib.Resources(cloud='local'))
    return t


def _wait_terminal(cluster, jid, timeout=30):
    handle = state.get_cluster(cluster)['handle']
    return handle.head_client().wait_job(jid, timeout=timeout)


def test_launch_exec_queue_logs_down(local_env, capsys):
    t = _local_task('e2e', 'echo out rank=$SKYT_NODE_RANK '
                           'n=$SKYT_NUM_NODES', num_nodes=2)
    jid = execution.launch(t, cluster_name='c-e2e', detach_run=True)
    assert jid == 1
    job = _wait_terminal('c-e2e', jid)
    assert job['status'] == 'SUCCEEDED'
    assert len(job['gang']) == 2

    # num_nodes drove the host count.
    handle = state.get_cluster('c-e2e')['handle']
    assert handle.num_hosts == 2

    # queue
    jobs = core.queue('c-e2e')
    assert [j['job_id'] for j in jobs] == [1]

    # logs (rank-0 stream)
    rc = core.tail_logs('c-e2e', jid, follow=True)
    out = capsys.readouterr().out
    assert 'out rank=0 n=2' in out
    assert rc == 0

    # exec fast-path reuses the UP cluster
    jid2 = execution.exec(_local_task('e2', 'echo second'), 'c-e2e',
                          detach_run=True)
    assert _wait_terminal('c-e2e', jid2)['status'] == 'SUCCEEDED'

    # status
    recs = core.status(refresh=True)
    assert [(r['name'], r['status']) for r in recs] == [
        ('c-e2e', state.ClusterStatus.UP)]

    core.down('c-e2e')
    assert core.status() == []


def test_failed_job_reports_failed(local_env):
    t = _local_task('bad', 'exit 3')
    jid = execution.launch(t, cluster_name='c-bad', detach_run=True)
    job = _wait_terminal('c-bad', jid)
    assert job['status'] == 'FAILED'
    assert any(g['returncode'] == 3 for g in job['gang'])
    assert core.tail_logs('c-bad', jid, follow=True) == 1


def test_setup_runs_before_run(local_env):
    t = _local_task('with-setup', 'cat ~/marker.txt')
    t.setup = 'echo setup-was-here > ~/marker.txt'
    jid = execution.launch(t, cluster_name='c-setup', detach_run=True)
    job = _wait_terminal('c-setup', jid)
    assert job['status'] == 'SUCCEEDED'


def test_stop_start_cycle(local_env):
    t = _local_task('cyc', 'echo alive')
    execution.launch(t, cluster_name='c-cyc', detach_run=True)
    core.stop('c-cyc')
    assert state.get_cluster('c-cyc')['status'] == \
        state.ClusterStatus.STOPPED
    # exec on a stopped cluster fails
    with pytest.raises(exceptions.ClusterNotUpError):
        execution.exec(_local_task('x', 'echo x'), 'c-cyc',
                       detach_run=True)
    core.start('c-cyc')
    assert state.get_cluster('c-cyc')['status'] == state.ClusterStatus.UP
    jid = execution.exec(_local_task('x', 'echo back'), 'c-cyc',
                         detach_run=True)
    assert _wait_terminal('c-cyc', jid)['status'] == 'SUCCEEDED'


def test_cancel_running_job(local_env):
    t = _local_task('sleeper', 'sleep 60')
    jid = execution.launch(t, cluster_name='c-cxl', detach_run=True)
    handle = state.get_cluster('c-cxl')['handle']
    client = handle.head_client()
    deadline = time.time() + 20
    while time.time() < deadline:
        job = client.job(jid)
        if job['status'] == 'RUNNING':
            break
        time.sleep(0.3)
    assert core.cancel('c-cxl', [jid]) == [jid]
    job = client.job(jid)
    assert job['status'] == 'CANCELLED'


def test_autostop_roundtrip(local_env):
    execution.launch(_local_task('a', 'echo x'), cluster_name='c-as',
                     detach_run=True)
    core.autostop('c-as', 15, down=False)
    rec = state.get_cluster('c-as')
    assert rec['autostop'] == 15 and not rec['to_down']


def test_launch_reuses_up_cluster(local_env):
    t = _local_task('r1', 'echo one')
    execution.launch(t, cluster_name='c-reuse', detach_run=True)
    jid = execution.launch(_local_task('r2', 'echo two'),
                           cluster_name='c-reuse', detach_run=True)
    assert jid == 2  # same cluster, second job


def test_exec_missing_cluster_raises(local_env):
    with pytest.raises(exceptions.ClusterDoesNotExist):
        execution.exec(_local_task('x', 'echo'), 'nope', detach_run=True)


# ------------------------------------------------------------------- CLI
def test_cli_full_cycle(local_env):
    runner = CliRunner()
    res = runner.invoke(cli, ['launch', '-y', '-d', '-c', 'c-cli',
                              '--cloud', 'local', 'echo cli-ran'])
    assert res.exit_code == 0, res.output
    _wait_terminal('c-cli', 1)

    res = runner.invoke(cli, ['status'])
    assert 'c-cli' in res.output and 'UP' in res.output

    res = runner.invoke(cli, ['queue', 'c-cli'])
    assert 'SUCCEEDED' in res.output

    res = runner.invoke(cli, ['logs', 'c-cli', '1', '--no-follow'])
    assert 'cli-ran' in res.output

    res = runner.invoke(cli, ['exec', 'c-cli', '-d', 'echo more'])
    assert res.exit_code == 0, res.output

    res = runner.invoke(cli, ['autostop', 'c-cli', '-i', '5'])
    assert res.exit_code == 0, res.output

    res = runner.invoke(cli, ['down', '-y', 'c-cli'])
    assert res.exit_code == 0, res.output
    res = runner.invoke(cli, ['status'])
    assert 'No existing clusters' in res.output


def _examples_dir():
    import os
    return os.path.join(os.path.dirname(__file__), '..', 'examples')


@pytest.mark.integration
@pytest.mark.skipif(
    jax.__version__.startswith('0.4.'),
    reason='jax 0.4.x CPU backend cannot run cross-process '
           'computations: every collective in the 2-node DP step dies '
           'with XlaRuntimeError "Multiprocess computations aren\'t '
           'implemented on the CPU backend" (root-caused from the '
           'rank logs, PR 7; the gang plumbing itself works — both '
           'ranks join the coordinator and print the mesh line). '
           'Re-enable when the image ships jax>=0.5 (CPU cross-host '
           'collectives) or when running with real accelerators.')
def test_cnn_distributed_yaml_two_nodes(local_env, capsys):
    """examples/cnn_distributed.yaml (the resnet_distributed_torch
    analog) runs 2-node data-parallel under skyt launch on the local
    provider: both nodes join one jax.distributed runtime via the gang
    env contract and the loss is finite at the end."""
    import os
    t = sky.Task.from_yaml(
        os.path.join(_examples_dir(), 'cnn_distributed.yaml'),
        env_overrides={'STEPS': '8', 'GLOBAL_BATCH': '8'})
    t.envs['JAX_PLATFORMS'] = 'cpu'
    t.envs['XLA_FLAGS'] = '--xla_force_host_platform_device_count=1'
    t.set_resources(resources_lib.Resources(cloud='local'))
    assert t.num_nodes == 2
    jid = execution.launch(t, cluster_name='c-cnn', detach_run=True)
    job = _wait_terminal('c-cnn', jid, timeout=420)
    assert job['status'] == 'SUCCEEDED', job
    core.tail_logs('c-cnn', jid, follow=False)
    out = capsys.readouterr().out
    assert 'nodes=2' in out, out          # really ran 2-process DP
    assert 'FINAL loss=' in out, out


@pytest.mark.integration
def test_text_classify_yaml(local_env, capsys):
    """examples/text_classify_finetune.yaml (the huggingface GLUE/IMDB
    analog) runs under skyt launch on the local provider and actually
    learns (eval accuracy printed; >0.9 at these settings)."""
    import os
    import re
    t = sky.Task.from_yaml(
        os.path.join(_examples_dir(), 'text_classify_finetune.yaml'),
        env_overrides={'STEPS': '40', 'BATCH': '16'})
    t.envs['JAX_PLATFORMS'] = 'cpu'
    t.set_resources(resources_lib.Resources(cloud='local'))
    jid = execution.launch(t, cluster_name='c-imdb', detach_run=True)
    job = _wait_terminal('c-imdb', jid, timeout=420)
    assert job['status'] == 'SUCCEEDED', job
    core.tail_logs('c-imdb', jid, follow=False)
    out = capsys.readouterr().out
    m = re.search(r'eval_acc=([0-9.]+)', out)
    assert m, out
    assert float(m.group(1)) > 0.9, out


@pytest.mark.integration
def test_docker_wrapped_task(local_env, tmp_path, monkeypatch, capsys):
    """`image_id: docker:<image>` runs setup AND run inside a
    container: the agent/backend bring the container up idempotently
    (pull + run -d) and exec the task scripts in it. A fake `docker`
    on PATH records the calls and executes the inner command on the
    host, so the full wrap is asserted without a docker daemon."""
    import os
    fake = tmp_path / 'bin'
    fake.mkdir()
    call_log = tmp_path / 'docker_calls.log'
    (fake / 'docker').write_text(
        '#!/usr/bin/env bash\n'
        f'echo "DOCKER $@" >> {call_log}\n'
        'cmd=$1; shift\n'
        'case "$cmd" in\n'
        '  image|container) exit 1;;\n'     # not present -> pull/run
        '  pull|run) exit 0;;\n'
        '  exec) shift; exec "$@";;\n'      # drop name; run on host
        'esac\n')
    (fake / 'docker').chmod(0o755)
    monkeypatch.setenv('PATH', f'{fake}:{os.environ["PATH"]}')

    t = sky.Task(name='dock', setup='echo setup-in-container',
                 run='echo run-in-container')
    t.set_resources(resources_lib.Resources(
        cloud='local', image_id='docker:ubuntu:22.04'))
    jid = execution.launch(t, cluster_name='c-dock', detach_run=True)
    job = _wait_terminal('c-dock', jid)
    assert job['status'] == 'SUCCEEDED', job

    calls = call_log.read_text()
    assert 'pull ubuntu:22.04' in calls
    assert 'run -d --name skyt-c-dock-r0 --network host' in calls
    assert 'exec skyt-c-dock-r0 bash' in calls
    core.tail_logs('c-dock', jid, follow=False)
    out = capsys.readouterr().out
    assert 'run-in-container' in out


def test_bare_image_id_still_gated():
    """A non-docker image_id still needs provisioner support: the
    local cloud lacks IMAGE_ID, so the feature gate reports it."""
    from skypilot_tpu import clouds
    local_cloud = clouds.Cloud.from_name('local')
    res = resources_lib.Resources(cloud='local',
                                  image_id='projects/x/images/y')
    assert clouds.CloudFeature.IMAGE_ID in \
        local_cloud.unsupported_features_for(res)
    res_docker = resources_lib.Resources(cloud='local',
                                         image_id='docker:img')
    assert clouds.CloudFeature.IMAGE_ID not in \
        local_cloud.unsupported_features_for(res_docker)


def test_cli_show_tpus():
    runner = CliRunner()
    res = runner.invoke(cli, ['show-tpus'])
    assert res.exit_code == 0, res.output
    assert 'tpu-v5e-16' in res.output.replace('v5litepod', 'tpu-v5e')
