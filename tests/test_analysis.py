"""skyanalyze (tools/analysis) tests: each pass fires on a seeded
violation fixture and stays silent on clean equivalents, the noqa
grammar works per pass id, the JSON artifact is golden, and registry
drift (env vars, fault points, metrics, JobStatus terminals) reds.

The whole-repo cleanliness gate is tests/test_lint.py::test_lint_clean
(lint.py now runs all skyanalyze passes); these tests pin each pass's
behavior in isolation on tmp fixture trees.
"""
import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    try:
        import lint
        from analysis import core
    finally:
        sys.path.pop(0)
    return lint, core


lint, core = _load()


def _write(root, rel, body):
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(body))
    return p


# ------------------------------------------------------ lock-discipline
def test_lock_discipline_fires_on_unguarded_access(tmp_path):
    bad = _write(tmp_path, 'skypilot_tpu/serve/racy.py', '''\
        import threading


        class Shared:
            def __init__(self) -> None:
                self._lock = threading.Lock()
                self._count = 0

            def bump(self) -> None:
                with self._lock:
                    self._count += 1

            def peek(self) -> int:
                return self._count
        ''')
    issues = lint.check_file(bad)
    assert any('lock-discipline' in i and 'self._count read' in i
               for i in issues), issues


def test_lock_discipline_guarded_by_method_marker(tmp_path):
    ok = _write(tmp_path, 'skypilot_tpu/serve/marked.py', '''\
        import threading


        class Shared:
            def __init__(self) -> None:
                self._lock = threading.Lock()
                self._count = 0

            def bump(self) -> None:
                with self._lock:
                    self._count += 1
                    self._flush_locked()

            def _flush_locked(self) -> None:  # guarded-by: _lock
                self._count = 0
        ''')
    assert not any('lock-discipline' in i
                   for i in lint.check_file(ok))


def test_lock_discipline_init_exempt_and_noqa(tmp_path):
    f = _write(tmp_path, 'skypilot_tpu/serve/init_ok.py', '''\
        import threading


        class Shared:
            def __init__(self) -> None:
                self._lock = threading.Lock()
                self._count = 0          # construction precedes sharing

            def bump(self) -> None:
                with self._lock:
                    self._count += 1

            def peek(self) -> int:
                return self._count  # noqa: lock-discipline (stale ok)
        ''')
    assert not any('lock-discipline' in i for i in lint.check_file(f))


def test_lock_discipline_closure_resets_held_locks(tmp_path):
    bad = _write(tmp_path, 'skypilot_tpu/serve/closure.py', '''\
        import threading


        class Shared:
            def __init__(self) -> None:
                self._lock = threading.Lock()
                self._count = 0

            def bump(self) -> None:
                with self._lock:
                    self._count += 1

                    def later() -> int:
                        return self._count
                    self.cb = later
        ''')
    issues = lint.check_file(bad)
    assert any('lock-discipline' in i and 'later' not in i
               for i in issues) or \
        any('self._count read' in i for i in issues), issues


# ------------------------------------------------------- async-blocking
def test_async_blocking_fires_in_serve_async_def(tmp_path):
    bad = _write(tmp_path, 'skypilot_tpu/serve/slowpath.py', '''\
        import time


        async def handler() -> None:
            time.sleep(1.0)
        ''')
    issues = lint.check_file(bad)
    assert any('async-blocking' in i and 'time.sleep' in i
               for i in issues), issues


def test_async_blocking_skips_executor_targets_and_sync_code(tmp_path):
    ok = _write(tmp_path, 'skypilot_tpu/serve/okpath.py', '''\
        import asyncio
        import time


        def warmup() -> None:
            time.sleep(0.1)              # sync code may block


        async def handler() -> object:
            def work() -> str:
                with open('/etc/hostname') as f:   # executor target
                    return f.read()
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None, work)
        ''')
    assert not any('async-blocking' in i for i in lint.check_file(ok))


def test_async_blocking_scope_is_serve_and_infer_server(tmp_path):
    elsewhere = _write(tmp_path, 'skypilot_tpu/train/loop.py', '''\
        import time


        async def trainer_side() -> None:
            time.sleep(1.0)
        ''')
    assert not any('async-blocking' in i
                   for i in lint.check_file(elsewhere))


# -------------------------------------------------------- tracer-safety
def test_tracer_safety_fires_through_call_graph(tmp_path):
    _write(tmp_path, 'skypilot_tpu/ops/kern.py', '''\
        import jax


        def _inner(x):
            print(x)
            return x * 2


        @jax.jit
        def traced(x):
            return _inner(x)
        ''')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    msgs = [v.message for v in violations
            if v.pass_id == 'tracer-safety']
    assert any('print()' in m and '_inner' in m for m in msgs), \
        violations


def test_tracer_safety_silent_without_traced_roots(tmp_path):
    _write(tmp_path, 'skypilot_tpu/ops/plain.py', '''\
        import time


        def eager(x):
            t0 = time.perf_counter()
            return x, time.perf_counter() - t0
        ''')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    assert not [v for v in violations if v.pass_id == 'tracer-safety']


# --------------------------------------------------------- env-registry
def test_env_read_pass_flags_direct_environ_read(tmp_path):
    bad = _write(tmp_path, 'skypilot_tpu/serve/knobs.py', '''\
        import os

        FLAG = os.environ.get('SKYT_SOME_FLAG', '0')
        ''')
    issues = lint.check_file(bad)
    assert any('env-registry' in i and 'SKYT_SOME_FLAG' in i
               for i in issues), issues
    # non-SKYT reads stay allowed
    ok = _write(tmp_path, 'skypilot_tpu/serve/other.py', '''\
        import os

        ADDR = os.environ.get('JAX_COORDINATOR_ADDRESS')
        ''')
    assert not any('env-registry' in i for i in lint.check_file(ok))


_MINI_ENV = '''\
import dataclasses
import os
from typing import Dict


@dataclasses.dataclass(frozen=True)
class EnvVar:
    name: str
    type: str
    default: object
    doc: str
    exported: bool = False


_REGISTRY: Dict[str, EnvVar] = {}


def _var(name, type, default, doc, exported=False):
    _REGISTRY[name] = EnvVar(name, type, default, doc, exported)


_var('SKYT_ALPHA', 'str', None, 'a consumed knob.')
_var('SKYT_OMEGA', 'str', None, 'set for user jobs.', exported=True)


def registry():
    return dict(_REGISTRY)


def get(name, default=None):
    return os.environ.get(name, default)


def generate_docs():
    lines = ['# Environment variables', '']
    for name in sorted(_REGISTRY):
        lines.append(f'| `{name}` |')
    return '\\n'.join(lines) + '\\n'
'''

_MINI_READER = '''\
from skypilot_tpu.utils import env

ALPHA = env.get('SKYT_ALPHA')
'''


def _mini_tree(tmp_path):
    _write(tmp_path, 'skypilot_tpu/utils/env.py', _MINI_ENV)
    _write(tmp_path, 'skypilot_tpu/serve/reader.py', _MINI_READER)
    docs = tmp_path / 'docs' / 'env_vars.md'
    docs.parent.mkdir(parents=True, exist_ok=True)
    docs.write_text('# Environment variables\n\n'
                    '| `SKYT_ALPHA` |\n| `SKYT_OMEGA` |\n')


def test_env_registry_consistent_fixture_is_clean(tmp_path):
    _mini_tree(tmp_path)
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    assert not [v for v in violations
                if v.pass_id == 'env-registry'], violations


def test_env_registry_drift_unregistered_read_reds(tmp_path):
    _mini_tree(tmp_path)
    _write(tmp_path, 'skypilot_tpu/serve/rogue.py', '''\
        from skypilot_tpu.utils import env

        BETA = env.get('SKYT_BETA')
        ''')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    assert any(v.pass_id == 'env-registry' and 'SKYT_BETA' in v.message
               and 'unregistered' in v.message for v in violations), \
        violations


def test_env_registry_drift_unread_var_reds(tmp_path):
    _mini_tree(tmp_path)
    env_py = tmp_path / 'skypilot_tpu' / 'utils' / 'env.py'
    env_py.write_text(env_py.read_text().replace(
        "_var('SKYT_ALPHA', 'str', None, 'a consumed knob.')",
        "_var('SKYT_ALPHA', 'str', None, 'a consumed knob.')\n"
        "_var('SKYT_GHOST', 'str', None, 'nobody reads me.')"))
    (tmp_path / 'docs' / 'env_vars.md').write_text(
        '# Environment variables\n\n| `SKYT_ALPHA` |\n'
        '| `SKYT_GHOST` |\n| `SKYT_OMEGA` |\n')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    assert any(v.pass_id == 'env-registry' and 'SKYT_GHOST' in v.message
               and 'never read' in v.message for v in violations), \
        violations


def test_env_registry_docs_drift_reds(tmp_path):
    """The headline drift drill: registry and docs disagree (an
    undocumented variable) => the analyzer goes red."""
    _mini_tree(tmp_path)
    (tmp_path / 'docs' / 'env_vars.md').write_text(
        '# Environment variables\n\n| `SKYT_ALPHA` |\n')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    assert any(v.pass_id == 'env-registry' and 'stale' in v.message
               for v in violations), violations


def test_real_env_docs_are_fresh():
    """docs/env_vars.md in the repo byte-matches the registry output
    (regenerate with `python tools/lint.py --write-env-docs`)."""
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    try:
        from analysis import env_registry
    finally:
        sys.path.pop(0)
    mod = env_registry._load_registry(
        os.path.join(REPO, 'skypilot_tpu', 'utils', 'env.py'))
    with open(os.path.join(REPO, 'docs', 'env_vars.md'),
              encoding='utf-8') as f:
        assert f.read() == mod.generate_docs()


# ------------------------------------------------- metric-cardinality
def test_metric_cardinality_flags_id_label_declaration(tmp_path):
    bad = _write(tmp_path, 'skypilot_tpu/infer/leaky.py', '''\
        from skypilot_tpu.utils import metrics

        M = metrics.REGISTRY.counter(
            'skyt_leaky_requests_total', 'per-request counter',
            ('request_id', 'path'))
        ''')
    issues = lint.check_file(bad)
    assert any('metric-cardinality' in i and "'request_id'" in i
               for i in issues), issues


def test_metric_cardinality_flags_unbounded_label_values(tmp_path):
    bad = _write(tmp_path, 'skypilot_tpu/infer/leaky2.py', '''\
        from skypilot_tpu.utils import metrics

        M = metrics.REGISTRY.counter(
            'skyt_thing_total', 'ok names', ('who', 'route'))


        def record(req, request):
            M.labels(req.trace_id, 'x').inc()
            M.labels('y', request.headers.get('X-Tenant')).inc()
        ''')
    issues = [i for i in lint.check_file(bad)
              if 'metric-cardinality' in i]
    assert any("'trace_id'" in i for i in issues), issues
    assert any('request-controlled' in i for i in issues), issues


def test_metric_cardinality_clean_on_bounded_values_and_noqa(tmp_path):
    ok = _write(tmp_path, 'skypilot_tpu/infer/clean.py', '''\
        from skypilot_tpu.utils import metrics
        from skypilot_tpu.utils import qos

        M = metrics.REGISTRY.counter(
            'skyt_thing_total', 'bounded', ('class', 'tenant'))
        N = metrics.REGISTRY.counter(
            'skyt_noqa_total', 'justified',
            ('session_id',))  # noqa: metric-cardinality


        def record(request):
            cls = qos.parse_priority(request.headers.get('X-Priority'))
            tenant = qos.parse_tenant(request.headers.get('X-Tenant'))
            M.labels(cls, tenant).inc()
        ''')
    assert not any('metric-cardinality' in i
                   for i in lint.check_file(ok))


# ------------------------------------------------- registry-consistency
def test_fault_point_drift_reds_both_ways(tmp_path):
    _write(tmp_path, 'skypilot_tpu/serve/thing.py', '''\
        from skypilot_tpu.utils import faults


        def tick() -> None:
            faults.inject('thing.tick')
        ''')
    _write(tmp_path, 'docs/robustness.md', '''\
        | point | layer | attrs | kinds |
        |---|---|---|---|
        | `ghost.point` | nowhere | — | error |
        ''')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    msgs = [v.message for v in violations
            if v.pass_id == 'registry-consistency']
    assert any("'thing.tick'" in m and 'no row' in m for m in msgs), \
        violations
    assert any("'ghost.point'" in m and 'no faults.inject' in m
               for m in msgs), violations


def test_metric_family_doc_presence_and_labels(tmp_path):
    _write(tmp_path, 'skypilot_tpu/serve/metered.py', '''\
        from skypilot_tpu.utils import metrics as metrics_lib

        REG = metrics_lib.MetricsRegistry()
        GOOD = REG.counter('skyt_widget_spins_total', 'spins',
                           ('widget',))
        BAD = REG.counter('skyt_widget_drops_total', 'drops')
        ''')
    _write(tmp_path, 'docs/observability.md',
           'Widgets: `skyt_widget_spins_total{widget}`.\n')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    msgs = [v.message for v in violations
            if v.pass_id == 'registry-consistency']
    assert any("'skyt_widget_drops_total'" in m and 'not documented'
               in m for m in msgs), violations
    assert not any("'skyt_widget_spins_total'" in m for m in msgs)

    # label mismatch: doc says {gadget}, code says ('widget',)
    _write(tmp_path, 'docs/observability.md',
           'Widgets: `skyt_widget_spins_total{gadget}` and '
           '`skyt_widget_drops_total`.\n')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    msgs = [v.message for v in violations
            if v.pass_id == 'registry-consistency']
    assert any("'skyt_widget_spins_total'" in m and 'label set' in m
               for m in msgs), violations


def test_terminal_state_catalog_equality(tmp_path):
    _write(tmp_path, 'skypilot_tpu/runtime/job_lib.py', '''\
        import enum


        class JobStatus(enum.Enum):
            RUNNING = 'RUNNING'
            SUCCEEDED = 'SUCCEEDED'
            HUNG = 'HUNG'


        _TERMINAL = {JobStatus.SUCCEEDED, JobStatus.HUNG}
        ''')
    _write(tmp_path, 'docs/managed-jobs.md',
           'Terminal states: `SUCCEEDED`.\n')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    msgs = [v.message for v in violations
            if v.pass_id == 'registry-consistency']
    assert any('HUNG is missing' in m for m in msgs), violations

    _write(tmp_path, 'docs/managed-jobs.md',
           'Terminal states: `SUCCEEDED`, `HUNG`.\n')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    assert not [v for v in violations
                if v.pass_id == 'registry-consistency'], violations


def test_http_route_drift_reds_both_ways(tmp_path):
    _write(tmp_path, 'skypilot_tpu/serve/surfaced.py', '''\
        def wire(app, handler):
            app.router.add_get('/debug/widgets', handler)
            app.router.add_get('/fleet/widgets', handler)
            app.router.add_post('/internal/not_checked', handler)
        ''')
    _write(tmp_path, 'docs/observability.md', '''\
        Routes: `GET /debug/widgets` and `GET /fleet/ghost_route`.
        ''')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    msgs = [v.message for v in violations
            if v.pass_id == 'registry-consistency']
    # Code-side drift: a registered surface missing from the catalog.
    assert any("'/fleet/widgets'" in m and 'not documented' in m
               for m in msgs), violations
    # Doc-side drift: a cataloged route with no registration.
    assert any("'/fleet/ghost_route'" in m and 'no add_get/add_post'
               in m for m in msgs), violations
    # Documented routes and non-debug/fleet prefixes stay quiet.
    assert not any("'/debug/widgets'" in m for m in msgs), violations
    assert not any('not_checked' in m for m in msgs), violations

    # Both sides reconciled -> exit clean.
    _write(tmp_path, 'docs/observability.md',
           'Routes: `GET /debug/widgets`, `GET /fleet/widgets`.\n')
    violations = core.analyze(tmp_path, ['skypilot_tpu'])
    assert not [v for v in violations
                if v.pass_id == 'registry-consistency'], violations


# ------------------------------------------------------- noqa semantics
def test_noqa_grammar_per_pass_id(tmp_path):
    # named suppression of a DIFFERENT pass does not silence
    wrong = _write(tmp_path, 'skypilot_tpu/serve/wrongnoqa.py', '''\
        import os

        F = os.environ.get('SKYT_F', '')  # noqa: kernel-dispatch
        ''')
    assert any('env-registry' in i for i in lint.check_file(wrong))

    # named suppression of the RIGHT pass silences only it
    right = _write(tmp_path, 'skypilot_tpu/serve/rightnoqa.py', '''\
        import os

        F = os.environ.get('SKYT_F', '')  # noqa: env-registry (why)
        ''')
    assert not any('env-registry' in i for i in lint.check_file(right))

    # bare noqa and free-text reasons suppress everything on the line
    bare = _write(tmp_path, 'skypilot_tpu/serve/barenoqa.py', '''\
        import os

        F = os.environ.get('SKYT_F', '')  # noqa: startup stamp
        ''')
    assert not any('env-registry' in i for i in lint.check_file(bare))


# --------------------------------------------------------- JSON output
def test_json_artifact_golden(tmp_path):
    bad = _write(tmp_path, 'skypilot_tpu/serve/dirty.py',
                 "x\t= 1\n")
    out = tmp_path / 'skyanalyze.json'
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'lint.py'),
         str(bad), '--json', str(out)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1, proc.stdout
    payload = json.loads(out.read_text())
    assert payload['schema'] == 1
    assert payload['tool'] == 'skyanalyze'
    assert payload['files_checked'] == 1
    assert 'lock-discipline' in payload['passes']
    [v] = payload['violations']
    assert v['path'].endswith('skypilot_tpu/serve/dirty.py')
    assert (v['line'], v['pass'], v['message']) == \
        (1, 'whitespace', 'tab character')


def test_repo_head_is_clean_with_json():
    """lint.py over the real tree: exit 0, empty violation list in the
    JSON artifact (the acceptance gate)."""
    out = os.path.join(REPO, '.skyanalyze_test.json')
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools', 'lint.py'),
             '--json', out],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, proc.stdout
        payload = json.loads(open(out, encoding='utf-8').read())
        assert payload['violations'] == []
    finally:
        if os.path.exists(out):
            os.remove(out)
