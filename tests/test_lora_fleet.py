"""Adapter fleet (docs/serving.md "Adapter fleet"): the dynamic
AdapterRegistry's full lifecycle against a live engine, the
ops/lora.py grouped-LoRA ladder op's golden parity vs its einsum
floor, mixed-adapter ragged packs, the LB's adapter-aware state and
routing helpers, and per-model QoS fairness.

The correctness bars, in order: a hot-loaded adapter must serve
EXACTLY the tokens a single-model engine over merge_lora(base,
adapter) produces, with the base and every other adapter unperturbed
by the mutation; the grouped op must match its XLA floor
byte-for-byte on CPU; and a ragged pack mixing adapters in one packed
row must equal the same requests run sequentially.
"""
import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import lora as slora
from skypilot_tpu.infer import weight_swap
from skypilot_tpu.models import llama
from skypilot_tpu.ops import dispatch
from skypilot_tpu.ops import lora as lora_ops
from skypilot_tpu.serve import qos
from skypilot_tpu.train import lora as tlora
from skypilot_tpu.utils import faults
from skypilot_tpu.utils import metrics as metrics_lib


# ----------------------------------------------------- grouped ladder op
def _rand_stack(n, din, r, dout, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    a = jnp.asarray(rng.normal(0, 0.1, (n, din, r)), dtype)
    b = jnp.asarray(rng.normal(0, 0.1, (n, r, dout)), dtype)
    # Id 0 is the zeros (base) adapter, like infer/lora.py stacks.
    a = a.at[0].set(0.0)
    b = b.at[0].set(0.0)
    return a, b


class TestGroupedOp:

    def teardown_method(self):
        faults.reset()

    def test_per_sequence_byte_identical_to_floor(self):
        """[B] ids (decode / uniform prefill): the ladder output —
        whatever rung it takes — must be byte-identical to the XLA
        gather-einsum floor on CPU (the per-id scale is applied
        outside every rung, so the final multiply is shared)."""
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(0, 1, (3, 16, 32)), jnp.float32)
        a, b = _rand_stack(3, 32, 4, 24, seed=2)
        ids = jnp.asarray([2, 0, 1], jnp.int32)
        scale = jnp.asarray([2.0, 0.0, 0.5], jnp.float32)
        out = lora_ops.grouped_lora_delta(x, a, b, ids, scale)
        ref = lora_ops._xla_gather(x, a, b, ids, scale)  # pylint: disable=protected-access
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(ref))
        # Id 0 rows are exactly zero: the zeros adapter contributes
        # nothing, bit-for-bit.
        assert not np.any(np.asarray(out)[1])

    def test_per_token_byte_identical_to_floor(self):
        """[B, S] ids (ragged packs mixing adapters in one row): the
        accumulate-over-adapters kernel must match the floor's scan
        byte-for-byte."""
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(0, 1, (2, 24, 32)), jnp.float32)
        a, b = _rand_stack(4, 32, 4, 16, seed=4)
        ids = jnp.asarray(rng.integers(0, 4, (2, 24)), jnp.int32)
        scale = jnp.where(ids == 0, 0.0, 1.5).astype(jnp.float32)
        out = lora_ops.grouped_lora_delta(x, a, b, ids, scale)
        ref = lora_ops._xla_grouped(x, a, b, ids, scale)  # pylint: disable=protected-access
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(ref))

    def test_mixed_rank_padded_stack(self):
        """Mixed-rank adapters live in one stack padded to the max
        rank with zero columns (infer/lora.py build_stack) — padding
        must be numerically inert through the grouped op."""
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(0, 1, (2, 8, 16)), jnp.float32)
        a, b = _rand_stack(3, 16, 4, 12, seed=6)
        # Adapter 2 is rank 2: zero its padding columns/rows.
        a = a.at[2, :, 2:].set(0.0)
        b = b.at[2, 2:, :].set(0.0)
        ids = jnp.asarray([1, 2], jnp.int32)
        scale = jnp.asarray([2.0, 4.0], jnp.float32)
        out = lora_ops.grouped_lora_delta(x, a, b, ids, scale)
        # Golden: dense per-sequence einsum over the TRUE ranks.
        want = np.stack([
            np.asarray(x[0]) @ np.asarray(a[1]) @ np.asarray(b[1]) * 2.0,
            np.asarray(x[1]) @ np.asarray(a[2, :, :2]) @
            np.asarray(b[2, :2, :]) * 4.0])
        np.testing.assert_allclose(np.asarray(out), want,
                                   rtol=1e-5, atol=1e-5)

    def test_lowering_fault_descends_to_xla_floor(self):
        """ops.lowering chaos kills every Pallas rung; the floor must
        serve the exact same output and the descent is observable in
        skyt_ops_kernel_path_total{op="lora_grouped"}."""
        dispatch.reset_for_tests()
        jax.clear_caches()
        faults.configure('ops.lowering=error')
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.normal(0, 1, (2, 40, 16)), jnp.float32)
        a, b = _rand_stack(2, 16, 4, 16, seed=8)
        ids = jnp.asarray([1, 1], jnp.int32)
        scale = jnp.asarray([2.0, 2.0], jnp.float32)
        out = lora_ops.grouped_lora_delta(x, a, b, ids, scale)
        ref = lora_ops._xla_gather(x, a, b, ids, scale)  # pylint: disable=protected-access
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(ref))
        assert dispatch.snapshot().get(lora_ops.OP) == 'xla'


# --------------------------------------------------- registry lifecycle
def _base(max_seq_len=64):
    cfg = dataclasses.replace(llama.CONFIGS['debug'],
                              max_seq_len=max_seq_len)
    model = llama.LlamaModel(cfg)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))['params'])
    return cfg, model, params


def _rand_adapter(params, rank, alpha, seed):
    lcfg = tlora.LoRAConfig(rank=rank, alpha=alpha)
    tree = tlora.init_lora_params(params, lcfg,
                                  jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)
    tree = jax.tree.map(
        lambda x: jnp.asarray(rng.normal(0, 0.1, x.shape), x.dtype),
        tree)
    return tree, lcfg


def _engine(model, params, stack=None, **kw):
    kw.setdefault('num_slots', 3)
    kw.setdefault('max_seq_len', 64)
    kw.setdefault('prefill_buckets', [16])
    return engine_lib.InferenceEngine(model, {'params': params},
                                      lora_stack=stack, **kw)


def _greedy(eng, prompt, n=6, lora_id=0):
    return eng.generate(prompt, engine_lib.SamplingParams(
        max_new_tokens=n, lora_id=lora_id))


@pytest.mark.heavy
def test_adapter_registry_lifecycle():
    """The whole hot-load story against a live engine: load parity vs
    the merged-weights golden, graft append, replace-with-rebuild
    (bigger rank forces the full-rebuild path), validation reject with
    the old stack intact, unload-while-referenced refused, id reuse
    after unload, and single-flight with the weight-swap slot."""
    _cfg, model, params = _base()
    t1, c1 = _rand_adapter(params, rank=4, alpha=8.0, seed=1)
    t2, c2 = _rand_adapter(params, rank=2, alpha=4.0, seed=2)
    t3, c3 = _rand_adapter(params, rank=8, alpha=16.0, seed=3)

    eng = _engine(model, params)
    eng.start()
    mreg = metrics_lib.MetricsRegistry()
    mgr = weight_swap.WeightSwapManager(eng, registry=mreg)
    areg = weight_swap.AdapterRegistry(eng, mgr, dtype='float32',
                                       registry=mreg)
    prompt = [1, 5, 9, 13]

    def merged_golden(tree, lcfg):
        m = _engine(model, tlora.merge_lora(params, tree, lcfg))
        m.start()
        try:
            return _greedy(m, prompt)
        finally:
            m.stop()

    try:
        base_out = _greedy(eng, prompt)
        # Fresh load (no stack yet -> build path), exact parity.
        r = areg.load('fr', params=t1, alpha=c1.alpha)
        assert r['id'] == 1 and r['num_adapters'] == 2
        m1 = merged_golden(t1, c1)
        assert _greedy(eng, prompt, lora_id=1) == m1
        assert _greedy(eng, prompt) == base_out

        # Second load: graft append.
        r = areg.load('de', params=t2, alpha=c2.alpha)
        assert r['id'] == 2 and r['num_adapters'] == 3

        # Replace in place with a BIGGER rank: graft cannot fit the
        # padded stack -> full rebuild; the sibling must survive.
        r = areg.load('fr', params=t3, alpha=c3.alpha)
        assert r['id'] == 1 and r['replaced'] and r['version'] == 2
        m3 = merged_golden(t3, c3)
        m2 = merged_golden(t2, c2)
        assert _greedy(eng, prompt, lora_id=1) == m3
        assert _greedy(eng, prompt, lora_id=2) == m2

        # Validation reject: old stack intact, failure recorded.
        with pytest.raises(weight_swap.WeightSwapError):
            areg.load('bad', params={'nope': {
                'a': jnp.zeros((4, 2)), 'b': jnp.zeros((2, 4))}})
        assert areg.last['ok'] is False and areg.last['name'] == 'bad'
        assert _greedy(eng, prompt, lora_id=1) == m3

        # Unload refused while a queued request references the id.
        class _P:  # pylint: disable=too-few-public-methods
            lora_id = 2

        class _R:  # pylint: disable=too-few-public-methods
            params = _P()

        eng._waiting.put(_R())  # pylint: disable=protected-access
        with pytest.raises(weight_swap.AdapterInUse):
            areg.unload('de')
        with eng._waiting.mutex:  # pylint: disable=protected-access
            eng._waiting.queue.clear()

        # Unload succeeds now; siblings and base unperturbed.
        areg.unload('de')
        assert 'de' not in areg.snapshot()['adapters']
        assert _greedy(eng, prompt, lora_id=1) == m3
        assert _greedy(eng, prompt) == base_out

        # Id reuse: the next load takes the lowest free slot.
        r = areg.load('de2', params=t2, alpha=c2.alpha)
        assert r['id'] == 2
        assert _greedy(eng, prompt, lora_id=2) == m2

        # Single-flight: the registry shares the weight-swap slot.
        mgr._flight.acquire()  # pylint: disable=protected-access
        try:
            with pytest.raises(weight_swap.SwapInFlight):
                areg.load('x', params=t2)
        finally:
            mgr._flight.release()  # pylint: disable=protected-access

        snap = areg.snapshot()
        assert snap['count'] == 2 and snap['stack_slots'] == 3
        fams = mreg.expose()
        assert 'skyt_infer_adapters_loaded' in fams
        assert 'skyt_infer_adapter_loads_total' in fams
        assert 'skyt_infer_adapter_unloads_total' in fams
    finally:
        eng.stop()


def _drain(q):
    items = []
    while True:
        it = q.get(timeout=120)
        if it is None:
            return items
        items.append(it)


@pytest.mark.heavy
def test_mixed_adapter_ragged_pack_matches_sequential():
    """A ragged prefill pack mixing adapters in ONE packed row (the
    per-token lora-id path through the grouped op) must produce
    exactly the tokens the same requests produce run one at a time."""
    _cfg, model, params = _base(max_seq_len=128)
    t1, c1 = _rand_adapter(params, rank=4, alpha=8.0, seed=1)
    t2, c2 = _rand_adapter(params, rank=2, alpha=4.0, seed=2)
    stack = slora.build_stack([(t1, c1.alpha), (t2, c2.alpha)],
                              dtype='float32')
    prompts = [list(range(1, 14)), list(range(5, 40)),
               list(range(7, 30))]
    ids = [1, 2, 0]
    sps = [engine_lib.SamplingParams(max_new_tokens=6, lora_id=i)
           for i in ids]

    def burst(**kw):
        eng = engine_lib.InferenceEngine(
            model, {'params': params}, lora_stack=stack, num_slots=4,
            max_seq_len=128, decode_chunk=4, cache_mode='paged',
            page_size=16, prefill_buckets=[16, 64], **kw)
        qs = [eng.submit(p, sp)[1] for p, sp in zip(prompts, sps)]
        eng.start()
        try:
            outs = [_drain(q) for q in qs]
        finally:
            eng.stop()
        return outs, dict(eng.perf)

    seq, _ = burst(batch_admission=False)
    rag, perf = burst()
    assert rag == seq
    assert perf['ragged_dispatches'] >= 1


# ----------------------------------------------------- LB state/routing
def test_lbstate_adapters_roundtrip_and_garbage():
    from skypilot_tpu.serve import load_balancer as lb_lib
    st = lb_lib.LBState(ready_replicas=['http://r1'],
                        replica_adapters={'http://r1': {'fr': 2}})
    back = lb_lib.LBState.from_json(st.to_json())
    assert back.replica_adapters == {'http://r1': {'fr': 2}}
    # Garbage-tolerant: wrong shapes contribute nothing, never raise.
    assert lb_lib.LBState._parse_adapters(  # pylint: disable=protected-access
        {'r1': [1, 2], 'r2': {'a': 'x', 'b': 3}, 3: None}) == \
        {'r2': {'b': 3}}
    assert lb_lib.LBState._parse_adapters('junk') == {}  # pylint: disable=protected-access
    txt = json.dumps({'ready_replicas': [], 'replica_adapters': 7})
    assert lb_lib.LBState.from_json(txt).replica_adapters == {}


def _make_lb(policy='prefix_affinity'):
    from skypilot_tpu.serve import load_balancer as lb_lib
    return lb_lib.SkyServeLoadBalancer(
        'http://127.0.0.1:9', 0, policy=policy,
        metrics_registry=metrics_lib.MetricsRegistry())


def test_affinity_key_folds_model():
    """Two requests over the same prompt but different adapters must
    land on DIFFERENT affinity keys — prefix pages are salted by
    lora id, so colliding them would plant guaranteed misses."""
    lb = _make_lb()
    plain = json.dumps({'prompt': 'Once upon a time'}).encode()
    fr = json.dumps({'prompt': 'Once upon a time',
                     'model': 'fr'}).encode()
    fr2 = json.dumps({'model': 'fr',
                      'prompt': 'Once upon a time'}).encode()
    de = json.dumps({'prompt': 'Once upon a time',
                     'model': 'de'}).encode()
    kp, kf, kf2, kd = (lb._affinity_key(b)  # pylint: disable=protected-access
                       for b in (plain, fr, fr2, de))
    assert kf == kf2          # key order in the body is irrelevant
    assert kp != kf and kf != kd and kp != kd


def test_adapter_avoid_and_honest_404():
    from skypilot_tpu.serve import load_balancer as lb_lib
    lb = _make_lb(policy='round_robin')
    lb.policy.set_ready_replicas(['http://a', 'http://b'])
    lb.state = lb_lib.LBState(
        ready_replicas=['http://a', 'http://b'],
        replica_adapters={'http://a': {'fr': 1}, 'http://b': {}})
    # Model parsing is gated on a non-empty adapter view.
    assert lb._request_model(  # pylint: disable=protected-access
        json.dumps({'model': 'fr'}).encode()) == 'fr'
    assert lb._request_model(b'not json') is None  # pylint: disable=protected-access
    # Soft-avoid: replicas reporting a set WITHOUT the adapter.
    assert lb._adapter_avoid_for('fr') == {'http://b'}  # pylint: disable=protected-access
    # Hosted nowhere -> no steering (base model / 404 / stale view).
    assert lb._adapter_avoid_for('ghost') == set()  # pylint: disable=protected-access
    assert lb._adapter_avoid_for(None) == set()  # pylint: disable=protected-access
    # Honest 404 needs a learned base id; conservative before then.
    assert lb._model_not_found('ghost') is None  # pylint: disable=protected-access
    lb._base_model_id = 'debug'  # pylint: disable=protected-access
    resp = lb._model_not_found('ghost')  # pylint: disable=protected-access
    assert resp is not None and resp.status == 404
    assert b'model_not_found' in resp.body
    # The base model and hosted adapters never 404.
    assert lb._model_not_found('debug') is None  # pylint: disable=protected-access
    assert lb._model_not_found('fr') is None  # pylint: disable=protected-access
    # Stale view: the replica's own 404 stays the source of truth.
    lb._stale = True  # pylint: disable=protected-access
    assert lb._model_not_found('ghost') is None  # pylint: disable=protected-access


# ------------------------------------------------------- per-model QoS
def test_fairqueue_per_model_isolation():
    """Two fine-tunes of one (class, tenant) are separate DRR flows:
    one model's flood cannot starve its sibling, and per-model weights
    skew service proportionally."""
    fq = qos.FairQueue(quantum=1.0, weights={'batch': 1.0},
                       model_weights={'b': 2.0})
    for i in range(6):
        fq.push(f'a{i}', cls='batch', tenant='t', model='a')
    for i in range(6):
        fq.push(f'b{i}', cls='batch', tenant='t', model='b')
    first6 = [fq.pop() for _ in range(6)]
    # Weight 2 vs 1: model b gets twice the service per DRR round.
    assert sum(1 for it in first6 if it.startswith('b')) == 4
    assert sum(1 for it in first6 if it.startswith('a')) == 2
    # Unweighted flood vs trickle: the sibling is never starved.
    fq2 = qos.FairQueue(quantum=1.0, weights={'batch': 1.0})
    for i in range(50):
        fq2.push(f'x{i}', cls='batch', tenant='t', model='x')
    fq2.push('y0', cls='batch', tenant='t', model='y')
    assert 'y0' in [fq2.pop() for _ in range(3)]


def test_model_weights_env_parse(monkeypatch):
    monkeypatch.setenv('SKYT_QOS_MODEL_WEIGHTS',
                       'fr:4, de:0.5 ,bad, x:y')
    assert qos._model_weights() == {'fr': 4.0, 'de': 0.5}  # pylint: disable=protected-access
    monkeypatch.setenv('SKYT_QOS_MODEL_WEIGHTS', '')
    assert qos._model_weights() == {}  # pylint: disable=protected-access
