"""Checkpoint/resume (train/checkpoint.py) + sft entrypoint helpers."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.train import sft

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


def test_parse_mesh_explicit():
    spec = sft.parse_mesh('fsdp=4,tp=2', 8)
    assert spec.fsdp == 4 and spec.tp == 2 and spec.num_devices == 8


def test_parse_mesh_auto():
    spec = sft.parse_mesh('auto', 8)
    assert spec.num_devices == 8


def test_parse_mesh_unknown_axis():
    with pytest.raises(ValueError, match='unknown mesh axes'):
        sft.parse_mesh('bogus=2', 8)


def test_jsonl_batches_pack(tmp_path):
    path = tmp_path / 'data.jsonl'
    path.write_text('{"text": "hello world"}\n'
                    '{"tokens": [5, 6, 7, 300]}\n')
    it = sft.jsonl_batches(str(path), vocab_size=256, batch=2, seq=8)
    b = next(it)
    assert b['tokens'].shape == (2, 8)
    assert b['targets'].shape == (2, 8)
    # tokens wrap modulo vocab (300 % 256 == 44 appears somewhere).
    flat = np.concatenate([b['tokens'].ravel(), b['targets'].ravel()])
    assert flat.max() < 256


def test_checkpointer_roundtrip_and_resume(tmp_path):
    from skypilot_tpu.models import llama
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.train import checkpoint as ckpt_lib
    from skypilot_tpu.train import trainer

    cfg = llama.CONFIGS['debug']
    model = llama.LlamaModel(cfg)
    mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(fsdp=4, tp=2))
    tx = trainer.make_optimizer(trainer.TrainerConfig(warmup_steps=1,
                                                      total_steps=4))
    sample = jnp.zeros((2, 16), jnp.int32)
    state, _ = trainer.create_sharded_state(model, tx, mesh, sample,
                                            jax.random.PRNGKey(0))
    step_fn = trainer.make_train_step(model, tx, mesh, donate=False)
    data = {'tokens': jnp.ones((2, 16), jnp.int32),
            'targets': jnp.ones((2, 16), jnp.int32)}
    state, _ = step_fn(state, data)

    ckpt = ckpt_lib.Checkpointer(str(tmp_path / 'ck'), save_interval_steps=1)
    assert ckpt.save(1, state)
    ckpt.wait()
    assert ckpt.latest_step() == 1

    restored = ckpt.restore(state)
    assert int(jax.device_get(restored.step)) == 1
    # Restored params keep their sharded layout and values.
    orig = jax.device_get(jax.tree.leaves(state.params)[0])
    back = jax.device_get(jax.tree.leaves(restored.params)[0])
    np.testing.assert_allclose(orig, back)
    ckpt.close()


def test_checkpointer_restore_none_when_empty(tmp_path):
    from skypilot_tpu.train import checkpoint as ckpt_lib
    ckpt = ckpt_lib.Checkpointer(str(tmp_path / 'empty'))
    assert ckpt.latest_step() is None
    assert ckpt.restore({'x': jnp.zeros(3)}) is None
    ckpt.close()


def test_sft_multislice_hybrid_mesh_runs():
    """--dcn-mesh dp=2 + --mesh fsdp=2,tp=2 on the virtual 8-device
    mesh: dp crosses the emulated slice boundary (DCN), fsdp/tp stay
    intra-slice — the multi-slice pretrain entry point end to end."""
    sft.main(['--model', 'debug', '--mesh', 'fsdp=2,tp=2',
              '--dcn-mesh', 'dp=2', '--steps', '2', '--batch', '4',
              '--seq', '32', '--log-every', '1'])


def test_sft_ring_attention_runs():
    """--attn ring + --mesh cp: ring attention over the context axis
    end to end (the long_context.yaml recipe's code path)."""
    sft.main(['--model', 'debug', '--mesh', 'cp=4,tp=2', '--attn',
              'ring', '--steps', '2', '--batch', '2', '--seq', '64',
              '--log-every', '1'])
