"""Weight loading, tokenizer, and tp-sharded inference tests.

Parity target: the reference serves real HF checkpoints via vLLM
(llm/vllm/serve.yaml); these tests prove our safetensors loader produces
the same logits as transformers' LlamaForCausalLM on the same checkpoint,
and that the engine decodes correctly when params + KV cache are
tp-sharded over a mesh.
"""
import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import tokenizer as tokenizer_lib
from skypilot_tpu.models import llama, weights
from skypilot_tpu.utils import jax_compat
from skypilot_tpu.parallel import mesh as mesh_lib

# Compile-heavy (JAX jit on the 1-core CPU host) or subprocess-driven:
pytestmark = pytest.mark.heavy


@pytest.fixture(scope='module')
def debug_ckpt(tmp_path_factory):
    """A debug-size HF-format checkpoint written by save_hf_checkpoint."""
    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(42),
                                 jnp.zeros((1, 8), jnp.int32))
    out = tmp_path_factory.mktemp('ckpt')
    weights.save_hf_checkpoint(cfg, params, str(out))
    return cfg, model, params, str(out)


def test_roundtrip_save_load(debug_ckpt):
    import flax.linen as nn
    cfg, _, params, ckpt_dir = debug_ckpt
    loaded = weights.load_llama_params(cfg, ckpt_dir)
    flat_a = jax.tree_util.tree_leaves_with_path(
        nn.meta.unbox(params['params']))
    flat_b = jax.tree_util.tree_leaves_with_path(loaded['params'])
    assert len(flat_a) == len(flat_b)
    for (pa, a), (pb, b) in zip(sorted(flat_a, key=lambda x: str(x[0])),
                                sorted(flat_b, key=lambda x: str(x[0]))):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=0, err_msg=str(pa))


def test_load_config_roundtrip(debug_ckpt):
    cfg, _, _, ckpt_dir = debug_ckpt
    cfg2 = weights.load_config(ckpt_dir, max_seq_len=cfg.max_seq_len,
                               dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               use_llama31_rope=cfg.use_llama31_rope,
                               remat=cfg.remat)
    assert cfg2.vocab_size == cfg.vocab_size
    assert cfg2.dim == cfg.dim
    assert cfg2.n_layers == cfg.n_layers
    assert cfg2.n_kv_heads == cfg.n_kv_heads
    assert cfg2.mlp_dim == cfg.mlp_dim


def test_logits_match_transformers(debug_ckpt):
    """Our model on loaded weights == HF LlamaForCausalLM on the same
    checkpoint (the strongest correctness proof available offline)."""
    torch = pytest.importorskip('torch')
    transformers = pytest.importorskip('transformers')

    cfg, model, params, ckpt_dir = debug_ckpt
    hf_model = transformers.LlamaForCausalLM.from_pretrained(
        ckpt_dir, torch_dtype=torch.float32)
    hf_model.eval()

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (2, 12))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()

    ours = np.asarray(model.apply(params, jnp.asarray(tokens, jnp.int32)))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_sharded_load_matches_unsharded(debug_ckpt):
    cfg, model, params, ckpt_dir = debug_ckpt
    mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(tp=2, fsdp=2, dp=2))
    loaded = weights.load_llama_params(cfg, ckpt_dir, mesh=mesh)
    # Sharding actually applied: wq kernel [L, D, H*hd] has heads on tp.
    wq = loaded['params']['layers']['attn']['wq']['kernel']
    assert wq.sharding.spec[-1] == 'tp'
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 16)),
        jnp.int32)
    import flax.linen as nn
    from skypilot_tpu.parallel import sharding as sharding_lib
    with mesh, nn.logical_axis_rules(list(sharding_lib.DEFAULT_RULES)):
        sharded_out = np.asarray(jax.jit(model.apply)(loaded, tokens))
    plain_out = np.asarray(model.apply(params, tokens))
    np.testing.assert_allclose(sharded_out, plain_out, rtol=2e-4,
                               atol=2e-4)


def test_nonscan_layout_load(debug_ckpt):
    cfg, _, params, ckpt_dir = debug_ckpt
    cfg_ns = dataclasses.replace(cfg, scan_layers=False)
    model_ns = llama.LlamaModel(cfg_ns)
    loaded = weights.load_llama_params(cfg_ns, ckpt_dir)
    tokens = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], jnp.int32)
    out_ns = np.asarray(model_ns.apply(loaded, tokens))
    model_s = llama.LlamaModel(cfg)
    out_s = np.asarray(model_s.apply(params, tokens))
    np.testing.assert_allclose(out_ns, out_s, rtol=2e-4, atol=2e-4)


def test_tied_checkpoint_into_untied_config(tmp_path):
    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64,
                              tie_embeddings=True)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.int32))
    weights.save_hf_checkpoint(cfg, params, str(tmp_path))
    cfg_untied = dataclasses.replace(cfg, tie_embeddings=False)
    loaded = weights.load_llama_params(cfg_untied, str(tmp_path))
    embed = np.asarray(loaded['params']['tok_embed'])
    head = np.asarray(loaded['params']['lm_head']['kernel'])
    np.testing.assert_array_equal(embed.T, head)


def test_engine_sharded_decode_matches_unsharded(debug_ckpt):
    cfg, model, params, ckpt_dir = debug_ckpt
    prompt = [5, 17, 3, 99, 42]

    eng_plain = engine_lib.InferenceEngine(model, params, num_slots=2,
                                           max_seq_len=64,
                                           prefill_buckets=[16])
    eng_plain.start()
    try:
        want = eng_plain.generate(prompt, engine_lib.SamplingParams(
            max_new_tokens=8))
    finally:
        eng_plain.stop()

    mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(tp=2))
    sharded = weights.load_llama_params(cfg, ckpt_dir, mesh=mesh)
    eng = engine_lib.InferenceEngine(model, sharded, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16], mesh=mesh)
    eng.start()
    try:
        got = eng.generate(prompt, engine_lib.SamplingParams(
            max_new_tokens=8))
    finally:
        eng.stop()
    assert got == want
    # The KV cache stayed sharded over tp through decode.
    assert eng.cache['k'].sharding.spec[3] == 'tp'


def test_engine_sharded_paged_decode_matches_unsharded(debug_ckpt):
    """tp-sharded PAGED engine: the page pool shards kv_heads on axis 2
    ([L, pages, H, P, d]) and decode matches the unsharded engine."""
    cfg, model, params, ckpt_dir = debug_ckpt
    prompt = [5, 17, 3, 99, 42]

    eng_plain = engine_lib.InferenceEngine(model, params, num_slots=2,
                                           max_seq_len=64,
                                           prefill_buckets=[16],
                                           cache_mode='paged',
                                           page_size=16)
    eng_plain.start()
    try:
        want = eng_plain.generate(prompt, engine_lib.SamplingParams(
            max_new_tokens=8))
    finally:
        eng_plain.stop()

    mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(tp=2))
    sharded = weights.load_llama_params(cfg, ckpt_dir, mesh=mesh)
    eng = engine_lib.InferenceEngine(model, sharded, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16], mesh=mesh,
                                     cache_mode='paged', page_size=16)
    eng.start()
    try:
        got = eng.generate(prompt, engine_lib.SamplingParams(
            max_new_tokens=8))
    finally:
        eng.stop()
    assert got == want
    assert eng.cache['k'].sharding.spec[2] == 'tp'


# ---------------------------------------------------------------- tokenizer
def test_byte_tokenizer_roundtrip():
    tok = tokenizer_lib.ByteTokenizer(256)
    text = 'hello tpu'
    assert tok.decode(tok.encode(text)) == text


def _write_wordlevel_tokenizer(path):
    """Build a tiny real tokenizer.json with the tokenizers runtime."""
    import tokenizers
    from tokenizers import models as tok_models
    from tokenizers import pre_tokenizers

    vocab = {'<s>': 0, '</s>': 1, '<unk>': 2, 'hello': 3, 'tpu': 4,
             'world': 5}
    tok = tokenizers.Tokenizer(
        tok_models.WordLevel(vocab, unk_token='<unk>'))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    tok.save(str(path))


def test_hf_tokenizer_loads_and_roundtrips(tmp_path):
    tj = tmp_path / 'tokenizer.json'
    _write_wordlevel_tokenizer(tj)
    with open(tmp_path / 'tokenizer_config.json', 'w') as f:
        json.dump({'bos_token': '<s>', 'eos_token': '</s>'}, f)
    tok = tokenizer_lib.load_tokenizer(str(tmp_path))
    assert tok.bos_id == 0
    assert tok.eos_id == 1
    ids = tok.encode('hello tpu world')
    assert ids[0] == 0  # bos prepended
    assert ids[1:] == [3, 4, 5]
    assert tok.decode(ids) == 'hello tpu world'


def test_hf_tokenizer_config_json_ids(tmp_path):
    tj = tmp_path / 'tokenizer.json'
    _write_wordlevel_tokenizer(tj)
    with open(tmp_path / 'config.json', 'w') as f:
        json.dump({'bos_token_id': 0, 'eos_token_id': [1, 2]}, f)
    tok = tokenizer_lib.load_tokenizer(str(tmp_path))
    assert tok.bos_id == 0
    assert tok.eos_id == 1


def test_load_tokenizer_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        tokenizer_lib.load_tokenizer(str(tmp_path))


def test_checkpoint_int8_stream_load_matches_post_quantize(debug_ckpt):
    """quantize='int8' streams each kernel through host-side
    quantization during load; the tree must match load-then-
    quantize_params (± 1 quantization step from host/device float
    rounding), with no bf16 kernel ever placed on device."""
    from skypilot_tpu.models import quant

    cfg, model, params, ckpt_dir = debug_ckpt
    want = quant.quantize_params(
        weights.load_llama_params(cfg, ckpt_dir))
    got = weights.load_llama_params(cfg, ckpt_dir, quantize='int8')
    la = jax_compat.tree_leaves_with_path(want)
    lb = jax_compat.tree_leaves_with_path(got)
    assert [p for p, _ in la] == [p for p, _ in lb]
    for (path, a), (_, b) in zip(la, lb):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype, path
        if a.dtype == np.int8:
            assert np.abs(a.astype(np.int32) -
                          b.astype(np.int32)).max() <= 1, path
        else:
            np.testing.assert_allclose(a.astype(np.float32),
                                       b.astype(np.float32),
                                       rtol=1e-5, atol=1e-8)


def test_engine_from_checkpoint_int8_serves(debug_ckpt, tmp_path):
    """build_engine(checkpoint=..., quantize='int8'): the stream-
    quantized engine decodes identically to an engine quantized after a
    full-precision load."""
    from skypilot_tpu.infer import server as server_lib
    from skypilot_tpu.models import quant

    cfg, model, params, ckpt_dir = debug_ckpt
    prompt = [5, 17, 3, 99, 42]

    eng_stream = server_lib.build_engine(
        checkpoint=ckpt_dir, num_slots=2, max_seq_len=64,
        quantize='int8')
    eng_stream.start()
    try:
        got = eng_stream.generate(prompt, engine_lib.SamplingParams(
            max_new_tokens=8))
    finally:
        eng_stream.stop()

    import dataclasses as _dc
    qcfg = _dc.replace(eng_stream.cfg)
    qparams = quant.quantize_params(
        weights.load_llama_params(cfg, ckpt_dir))
    qmodel = llama.LlamaModel(qcfg)
    eng_post = engine_lib.InferenceEngine(qmodel, qparams, num_slots=2,
                                          max_seq_len=64)
    eng_post.start()
    try:
        want = eng_post.generate(prompt, engine_lib.SamplingParams(
            max_new_tokens=8))
    finally:
        eng_post.stop()
    assert got == want


# ------------------------------------------------------------- mixtral
@pytest.fixture(scope='module')
def mixtral_ckpt(tmp_path_factory):
    """A debug-size HF-format Mixtral checkpoint."""
    from skypilot_tpu.models import moe

    cfg, moe_cfg = moe.MIXTRAL_CONFIGS['debug-moe']
    cfg = dataclasses.replace(cfg, max_seq_len=64)
    model = moe.MixtralModel(cfg, moe_cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(11),
                                 jnp.zeros((1, 8), jnp.int32))
    out = tmp_path_factory.mktemp('mixtral_ckpt')
    weights.save_hf_mixtral_checkpoint(cfg, moe_cfg, params, str(out))
    return cfg, moe_cfg, model, params, str(out)


def test_mixtral_roundtrip_save_load(mixtral_ckpt):
    import flax.linen as nn
    cfg, moe_cfg, _, params, ckpt_dir = mixtral_ckpt
    assert weights.checkpoint_model_type(ckpt_dir) == 'mixtral'
    cfg2, moe_cfg2 = weights.load_mixtral_config(
        ckpt_dir, max_seq_len=cfg.max_seq_len, dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        use_llama31_rope=cfg.use_llama31_rope, remat=cfg.remat)
    assert moe_cfg2.num_experts == moe_cfg.num_experts
    assert moe_cfg2.experts_per_token == moe_cfg.experts_per_token
    loaded = weights.load_mixtral_params(cfg2, moe_cfg2, ckpt_dir)
    flat_a = jax.tree_util.tree_leaves_with_path(
        nn.meta.unbox(params['params']))
    flat_b = jax.tree_util.tree_leaves_with_path(loaded['params'])
    assert len(flat_a) == len(flat_b)
    for (pa, a), (pb, b) in zip(sorted(flat_a, key=lambda x: str(x[0])),
                                sorted(flat_b, key=lambda x: str(x[0]))):
        assert str(pa) == str(pb)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=0, err_msg=str(pa))


def test_mixtral_logits_match_transformers(mixtral_ckpt):
    """Our MoE model on loaded weights == HF MixtralForCausalLM on the
    same checkpoint. Dropless (high capacity) so no tokens drop."""
    torch = pytest.importorskip('torch')
    transformers = pytest.importorskip('transformers')
    from skypilot_tpu.models import moe

    cfg, moe_cfg, _, _, ckpt_dir = mixtral_ckpt
    hf_model = transformers.MixtralForCausalLM.from_pretrained(
        ckpt_dir, torch_dtype=torch.float32)
    hf_model.eval()

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (2, 12))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()

    dropless = dataclasses.replace(moe_cfg, capacity_factor=8.0)
    model = moe.MixtralModel(cfg, dropless)
    loaded = weights.load_mixtral_params(cfg, dropless, ckpt_dir)
    ours = np.asarray(model.apply(loaded, jnp.asarray(tokens, jnp.int32)))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_engine_from_mixtral_checkpoint_serves(mixtral_ckpt):
    """build_engine auto-detects model_type=mixtral and serves it."""
    from skypilot_tpu.infer import server as server_lib

    cfg, moe_cfg, model, params, ckpt_dir = mixtral_ckpt
    eng = server_lib.build_engine(checkpoint=ckpt_dir, num_slots=2,
                                  max_seq_len=64, dtype='float32')
    eng.start()
    try:
        out = eng.generate([5, 9, 2, 31], engine_lib.SamplingParams(
            max_new_tokens=8))
    finally:
        eng.stop()
    assert len(out) == 8


def test_mixtral_int8_stream_load_matches_post_quantize(mixtral_ckpt):
    """Expert weights stream-quantize on host; router/norms stay float;
    tree matches quantize_params(load(...))."""
    from skypilot_tpu.models import quant

    cfg, moe_cfg, _, _, ckpt_dir = mixtral_ckpt
    want = quant.quantize_params(
        weights.load_mixtral_params(cfg, moe_cfg, ckpt_dir))
    got = weights.load_mixtral_params(cfg, moe_cfg, ckpt_dir,
                                      quantize='int8')
    la = jax_compat.tree_leaves_with_path(want)
    lb = jax_compat.tree_leaves_with_path(got)
    assert [p for p, _ in la] == [p for p, _ in lb]
    n_int8 = 0
    for (path, a), (_, b) in zip(la, lb):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype, path
        if a.dtype == np.int8:
            n_int8 += 1
            assert np.abs(a.astype(np.int32) -
                          b.astype(np.int32)).max() <= 1, path
        else:
            np.testing.assert_allclose(a.astype(np.float32),
                                       b.astype(np.float32),
                                       rtol=1e-5, atol=1e-8)
    # 3 expert tensors + lm_head at minimum went int8; router did not.
    assert n_int8 >= 4
    router = got['params']['layers']['moe_mlp']['router']
    assert router.dtype != np.int8


# ------------------------------------------------- model families
# The reference serves Qwen/Gemma by pointing vLLM at the HF checkpoint
# (llm/vllm/serve.yaml, llm/gemma/serve.yaml); here the same LlamaModel
# covers them via config knobs (models/llama.py: attn_bias, mlp_act,
# norm_zero_centered, embed_scale, head_dim_override) and the loader's
# family dispatch (models/weights.py config_from_hf).

def _family_debug_cfg(family):
    base = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    if family == 'qwen2':
        return dataclasses.replace(base, attn_bias=True, norm_eps=1e-6,
                                   rope_theta=1e6)
    if family == 'qwen3':
        return dataclasses.replace(base, qk_norm=True, norm_eps=1e-6,
                                   rope_theta=1e6, head_dim_override=32,
                                   tie_embeddings=True)
    if family == 'phi3':
        # Fused-tensor HF layout + a window smaller than the 12-token
        # test prompts.
        return dataclasses.replace(base, hf_layout='phi3',
                                   sliding_window=8, rope_theta=10000.0)
    if family == 'gemma':
        return dataclasses.replace(
            base, mlp_act='gelu_tanh', norm_zero_centered=True,
            embed_scale=True, tie_embeddings=True, head_dim_override=32,
            norm_eps=1e-6, rope_theta=10000.0)
    if family == 'gemma2':
        # Window 8 < the 12-token test prompts and pattern 2, so the
        # sliding/global alternation and both soft-caps are exercised;
        # attn_scale deliberately != head_dim**-0.5.
        return dataclasses.replace(
            base, n_layers=4, mlp_act='gelu_tanh',
            norm_zero_centered=True, embed_scale=True,
            tie_embeddings=True, head_dim_override=16,
            norm_eps=1e-6, rope_theta=10000.0, sliding_window=8,
            window_pattern=2, attn_softcap=30.0, final_softcap=20.0,
            attn_scale=32.0 ** -0.5, sandwich_norms=True)
    raise ValueError(family)


def _random_family_params(cfg, seed=7):
    """init() then randomize the zero-init bias leaves so the parity
    test actually exercises the bias load path."""
    import flax.linen as nn
    model = llama.LlamaModel(cfg)
    params = nn.meta.unbox(
        jax.jit(model.init)(jax.random.PRNGKey(seed),
                            jnp.zeros((1, 8), jnp.int32))['params'])
    rng = np.random.default_rng(seed)

    def bump(path, leaf):
        if path[-1].key == 'bias':
            return np.asarray(rng.normal(0.0, 0.5, leaf.shape),
                              np.float32)
        return leaf
    params = jax.tree_util.tree_map_with_path(bump, params)
    return model, {'params': params}


@pytest.mark.parametrize('family',
                         ['qwen2', 'qwen3', 'gemma', 'gemma2', 'phi3'])
def test_family_logits_match_transformers(family, tmp_path):
    """save -> config round-trip -> load -> logits == transformers'
    family implementation on the same checkpoint."""
    torch = pytest.importorskip('torch')
    transformers = pytest.importorskip('transformers')

    cfg = _family_debug_cfg(family)
    model, variables = _random_family_params(cfg)
    ckpt = tmp_path / family
    weights.save_hf_checkpoint(cfg, variables, str(ckpt))

    # config.json carries the family: load_config must reconstruct the
    # same knobs without being told the model type.
    cfg2 = weights.load_config(str(ckpt), max_seq_len=cfg.max_seq_len,
                               dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               remat=cfg.remat)
    for field in ('attn_bias', 'mlp_act', 'norm_zero_centered',
                  'embed_scale', 'head_dim', 'tie_embeddings',
                  'sliding_window', 'window_pattern', 'attn_softcap',
                  'final_softcap', 'sandwich_norms'):
        assert getattr(cfg2, field) == getattr(cfg, field), field
    assert abs(cfg2.attn_scale - cfg.attn_scale) < 1e-9

    loaded = weights.load_llama_params(cfg2, str(ckpt))

    # eager attention: HF's sdpa path skips Gemma-2 soft-capping and
    # (on some versions) sliding windows; eager implements both.
    hf_model = transformers.AutoModelForCausalLM.from_pretrained(
        str(ckpt), torch_dtype=torch.float32,
        attn_implementation='eager')
    assert type(hf_model).__name__ == {
        'qwen2': 'Qwen2ForCausalLM', 'qwen3': 'Qwen3ForCausalLM',
        'gemma': 'GemmaForCausalLM', 'gemma2': 'Gemma2ForCausalLM',
        'phi3': 'Phi3ForCausalLM'}[family]
    hf_model.eval()

    rng = np.random.default_rng(3)
    tokens = rng.integers(0, cfg.vocab_size, (2, 12))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        llama.LlamaModel(cfg2).apply(loaded,
                                     jnp.asarray(tokens, jnp.int32)))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize('family',
                         ['qwen2', 'qwen3', 'gemma', 'gemma2', 'phi3'])
def test_family_engine_decode(family, tmp_path):
    """build_engine(checkpoint=<family ckpt>) decodes end-to-end —
    proves the serve path's model-type dispatch, not just logits."""
    from skypilot_tpu.infer import server as server_lib

    cfg = _family_debug_cfg(family)
    _, variables = _random_family_params(cfg)
    ckpt = tmp_path / family
    weights.save_hf_checkpoint(cfg, variables, str(ckpt))

    eng = server_lib.build_engine(checkpoint=str(ckpt), num_slots=2,
                                  max_seq_len=64, dtype='float32')
    eng.start()
    try:
        out = eng.generate([5, 17, 3, 99, 42],
                           engine_lib.SamplingParams(max_new_tokens=8))
    finally:
        eng.stop()
    assert len(out) == 8


def test_qwen2_int8_stream_load_matches_post_quantize(tmp_path):
    """Biased (attn_bias) projection scopes still quantize: kernel ->
    int8 + scale, bias rides along float — stream-load == post-hoc
    quantize_params (the invariant load_llama_params documents)."""
    from skypilot_tpu.models import quant

    cfg = _family_debug_cfg('qwen2')
    _, variables = _random_family_params(cfg)
    ckpt = tmp_path / 'qwen2'
    weights.save_hf_checkpoint(cfg, variables, str(ckpt))

    want = quant.quantize_params(
        weights.load_llama_params(cfg, str(ckpt)))
    got = weights.load_llama_params(cfg, str(ckpt), quantize='int8')
    la = jax_compat.tree_leaves_with_path(want)
    lb = jax_compat.tree_leaves_with_path(got)
    assert [p for p, _ in la] == [p for p, _ in lb]
    n_int8 = 0
    for (path, a), (_, b) in zip(la, lb):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype, path
        if a.dtype == np.int8:
            n_int8 += 1
            assert np.abs(a.astype(np.int32) -
                          b.astype(np.int32)).max() <= 1, path
        else:
            np.testing.assert_allclose(a.astype(np.float32),
                                       b.astype(np.float32),
                                       rtol=1e-5, atol=1e-8,
                                       err_msg=str(path))
    # All 7 scan-stacked projections (wq/wk/wv/wo + gate/up/down) plus
    # lm_head went int8 despite the q/k/v biases in the same scopes.
    assert n_int8 == 8


def test_mistral_checkpoint_dispatch(tmp_path):
    """model_type=mistral loads through the llama path with
    sliding-window attention: logits match transformers'
    MistralForCausalLM on prompts LONGER than the window (the windowed
    mask is the only difference from llama)."""
    torch = pytest.importorskip('torch')
    transformers = pytest.importorskip('transformers')

    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64,
                              norm_eps=1e-6, rope_theta=10000.0)
    model = llama.LlamaModel(cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(5),
                                 jnp.zeros((1, 8), jnp.int32))
    weights.save_hf_checkpoint(cfg, params, str(tmp_path))
    # Rewrite the config as a Mistral checkpoint with a sliding window
    # SMALLER than the test prompt so the window actually bites.
    cfg_path = tmp_path / 'config.json'
    hf_cfg = json.loads(cfg_path.read_text())
    hf_cfg.update(model_type='mistral',
                  architectures=['MistralForCausalLM'],
                  sliding_window=8)
    cfg_path.write_text(json.dumps(hf_cfg))

    cfg2 = weights.load_config(str(tmp_path), dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               remat=False)
    assert cfg2.sliding_window == 8
    assert cfg2.max_seq_len == 64   # no clamp: the window is real now
    loaded = weights.load_llama_params(cfg2, str(tmp_path))

    hf_model = transformers.AutoModelForCausalLM.from_pretrained(
        str(tmp_path), torch_dtype=torch.float32,
        attn_implementation='eager')
    assert type(hf_model).__name__ == 'MistralForCausalLM'
    hf_model.eval()
    tokens = np.random.default_rng(4).integers(0, cfg.vocab_size,
                                               (2, 12))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        llama.LlamaModel(cfg2).apply(loaded,
                                     jnp.asarray(tokens, jnp.int32)))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)
    # Sanity that the window changed the math vs no-window weights.
    plain = np.asarray(model.apply(params,
                                   jnp.asarray(tokens, jnp.int32)))
    assert np.abs(plain - ours).max() > 1e-3


def test_windowed_engine_decode_matches_full_forward(tmp_path):
    """Gemma-2-style incremental decode (windowed + soft-capped cached
    attention, alternating layers) == greedy rollout by full forward
    recompute — the cache path's window mask is position-exact."""
    cfg = _family_debug_cfg('gemma2')
    _, variables = _random_family_params(cfg)
    ckpt = tmp_path / 'g2'
    weights.save_hf_checkpoint(cfg, variables, str(ckpt))
    cfg2 = weights.load_config(str(ckpt), max_seq_len=64,
                               dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype, remat=False)
    loaded = weights.load_llama_params(cfg2, str(ckpt))
    model = llama.LlamaModel(cfg2)

    prompt = list(np.random.default_rng(6).integers(
        1, cfg.vocab_size, 12))          # longer than the 8-token window
    toks = [int(t) for t in prompt]
    for _ in range(6):
        logits = model.apply(loaded, jnp.asarray([toks], jnp.int32))
        toks.append(int(np.argmax(np.asarray(logits[0, -1]))))
    want = toks[len(prompt):]

    eng = engine_lib.InferenceEngine(model, loaded, num_slots=2,
                                     max_seq_len=64,
                                     prefill_buckets=[16],
                                     cache_mode='paged', page_size=16)
    eng.start()
    try:
        got = eng.generate([int(t) for t in prompt],
                           engine_lib.SamplingParams(max_new_tokens=6))
    finally:
        eng.stop()
    assert got == want


def test_gemma2_tp_sharded_decode_matches_unsharded(tmp_path):
    """The windowed/soft-capped family under tp=2: the traced
    layer-index window gating and the masked XLA decode path hold up
    under GSPMD sharding (token-exact vs the unsharded engine)."""
    cfg = _family_debug_cfg('gemma2')
    _, variables = _random_family_params(cfg)
    ckpt = tmp_path / 'g2'
    weights.save_hf_checkpoint(cfg, variables, str(ckpt))
    cfg2 = weights.load_config(str(ckpt), max_seq_len=64,
                               dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype, remat=False)
    model = llama.LlamaModel(cfg2)
    prompt = list(range(1, 13))   # > the 8-token window

    def run(mesh):
        loaded = weights.load_llama_params(cfg2, str(ckpt), mesh=mesh)
        eng = engine_lib.InferenceEngine(model, loaded, num_slots=2,
                                         max_seq_len=64,
                                         prefill_buckets=[16],
                                         cache_mode='paged',
                                         page_size=16, mesh=mesh)
        eng.start()
        try:
            return eng.generate(prompt, engine_lib.SamplingParams(
                max_new_tokens=6))
        finally:
            eng.stop()

    want = run(None)
    got = run(mesh_lib.build_mesh(mesh_lib.MeshSpec(tp=2)))
    assert got == want


# ------------------------------------------------------ qwen3_moe
def test_qwen3_moe_logits_and_engine(tmp_path):
    """Qwen3-MoE (qk-norm attention + llama-named expert tensors under
    mlp.experts): our MixtralModel on a saved qwen3_moe checkpoint
    matches transformers' Qwen3MoeForCausalLM, and build_engine
    dispatches it."""
    import dataclasses as _dc

    torch = pytest.importorskip('torch')
    transformers = pytest.importorskip('transformers')

    from skypilot_tpu.infer import server as server_lib
    from skypilot_tpu.models import moe

    cfg, moe_cfg = moe.MIXTRAL_CONFIGS['debug-moe']
    cfg = _dc.replace(cfg, max_seq_len=64, qk_norm=True,
                      head_dim_override=32, norm_eps=1e-6,
                      rope_theta=1e6)
    # Dropless so the capacity-based routing equals exact top-k.
    moe_cfg = _dc.replace(moe_cfg, capacity_factor=8.0)
    model = moe.MixtralModel(cfg, moe_cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(13),
                                 jnp.zeros((1, 8), jnp.int32))
    ckpt = tmp_path / 'q3moe'
    weights.save_hf_mixtral_checkpoint(cfg, moe_cfg, params, str(ckpt))
    assert weights.checkpoint_model_type(str(ckpt)) == 'qwen3_moe'

    cfg2, moe_cfg2 = weights.load_mixtral_config(
        str(ckpt), max_seq_len=cfg.max_seq_len, dtype=cfg.dtype,
        param_dtype=cfg.param_dtype, remat=cfg.remat)
    assert cfg2.qk_norm and cfg2.mlp_dim == cfg.mlp_dim
    moe_cfg2 = _dc.replace(moe_cfg2, capacity_factor=8.0)
    loaded = weights.load_mixtral_params(cfg2, moe_cfg2, str(ckpt))

    hf_model = transformers.AutoModelForCausalLM.from_pretrained(
        str(ckpt), torch_dtype=torch.float32,
        attn_implementation='eager')
    assert type(hf_model).__name__ == 'Qwen3MoeForCausalLM'
    hf_model.eval()
    tokens = np.random.default_rng(9).integers(0, cfg.vocab_size,
                                               (2, 12))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(moe.MixtralModel(cfg2, moe_cfg2).apply(
        loaded, jnp.asarray(tokens, jnp.int32)))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)

    eng = server_lib.build_engine(checkpoint=str(ckpt), num_slots=2,
                                  max_seq_len=64, dtype='float32')
    eng.start()
    try:
        out = eng.generate([5, 9, 2, 31],
                           engine_lib.SamplingParams(max_new_tokens=6))
    finally:
        eng.stop()
    assert len(out) == 6


def test_qwen3_moe_with_attention_bias_roundtrips(tmp_path):
    """attention_bias=true on a MoE config loads/saves its bias
    tensors (no released qwen3_moe uses it, but config_from_hf honors
    the field, so the loader must too rather than fail opaquely)."""
    import dataclasses as _dc

    from skypilot_tpu.models import moe

    cfg, moe_cfg = moe.MIXTRAL_CONFIGS['debug-moe']
    cfg = _dc.replace(cfg, max_seq_len=64, qk_norm=True, attn_bias=True,
                      head_dim_override=32, norm_eps=1e-6)
    moe_cfg = _dc.replace(moe_cfg, capacity_factor=8.0)
    model = moe.MixtralModel(cfg, moe_cfg)
    params = jax.jit(model.init)(jax.random.PRNGKey(17),
                                 jnp.zeros((1, 8), jnp.int32))
    # Randomize the zero-init biases: a dropped bias tensor must CHANGE
    # the outputs, or this roundtrip proves nothing.
    import flax.linen as nn
    rng = np.random.default_rng(17)
    params = {'params': jax.tree_util.tree_map_with_path(
        lambda p, a: (jnp.asarray(rng.normal(0, 0.5, a.shape),
                                  a.dtype)
                      if p[-1].key == 'bias' else a),
        nn.meta.unbox(params['params']))}
    ckpt = tmp_path / 'biased'
    weights.save_hf_mixtral_checkpoint(cfg, moe_cfg, params, str(ckpt))
    cfg2, moe_cfg2 = weights.load_mixtral_config(
        str(ckpt), max_seq_len=64, dtype=cfg.dtype,
        param_dtype=cfg.param_dtype, remat=cfg.remat)
    assert cfg2.attn_bias
    moe_cfg2 = _dc.replace(moe_cfg2, capacity_factor=8.0)
    loaded = weights.load_mixtral_params(cfg2, moe_cfg2, str(ckpt))
    toks = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    import flax.linen as nn
    a = np.asarray(model.apply(params, toks))
    b = np.asarray(moe.MixtralModel(cfg2, moe_cfg2).apply(loaded, toks))
    np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)
