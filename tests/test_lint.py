"""Keep the tree lint-clean: tools/lint.py must pass (the reference
gates CI on format.sh; SURVEY.md §4.6)."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_lint_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'lint.py')],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, f'lint issues:\n{proc.stdout}'


def test_lint_forbids_pallas_call_outside_ops(tmp_path):
    """Kernel discipline: a bare pl.pallas_call outside skypilot_tpu/
    ops/ must flag (all kernels route through the dispatch ladder);
    the same call under ops/ must not."""
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    try:
        import lint
    finally:
        sys.path.pop(0)
    bad = tmp_path / 'skypilot_tpu' / 'infer' / 'sneaky.py'
    bad.parent.mkdir(parents=True)
    bad.write_text('from jax.experimental import pallas as pl\n'
                   'out = pl.pallas_call(lambda r: None)\n')
    issues = lint.check_file(bad)
    assert any('pallas_call outside' in i for i in issues), issues

    ok = tmp_path / 'skypilot_tpu' / 'ops' / 'kernel.py'
    ok.parent.mkdir(parents=True)
    ok.write_text('from jax.experimental import pallas as pl\n'
                  'out = pl.pallas_call(lambda r: None)\n')
    assert not any('pallas_call' in i for i in lint.check_file(ok))

    # noqa escape hatch.
    bad.write_text('from jax.experimental import pallas as pl\n'
                   'out = pl.pallas_call(lambda r: None)  # noqa\n')
    assert not any('pallas_call' in i for i in lint.check_file(bad))


def test_lint_forbids_direct_sqlite_connect(tmp_path):
    """State-DB discipline: a raw sqlite3.connect in framework code
    must flag (it misses the WAL + busy-timeout recipe multi-process
    sharing relies on); the sanctioned owners and `# noqa` pass."""
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    try:
        import lint
    finally:
        sys.path.pop(0)
    bad = tmp_path / 'skypilot_tpu' / 'jobs' / 'sneaky_state.py'
    bad.parent.mkdir(parents=True)
    bad.write_text('import sqlite3\n'
                   'conn = sqlite3.connect("/tmp/x.db")\n')
    issues = lint.check_file(bad)
    assert any('sqlite3.connect' in i for i in issues), issues

    for owner in ('utils/sqlite_utils.py', 'serve/serve_state.py'):
        ok = tmp_path / 'skypilot_tpu' / owner
        ok.parent.mkdir(parents=True, exist_ok=True)
        ok.write_text('import sqlite3\n'
                      'conn = sqlite3.connect("/tmp/x.db")\n')
        assert not any('sqlite3.connect' in i
                       for i in lint.check_file(ok)), owner

    bad.write_text('import sqlite3\n'
                   'conn = sqlite3.connect("/tmp/x.db")  # noqa\n')
    assert not any('sqlite3.connect' in i for i in lint.check_file(bad))


def test_lint_forbids_wall_clock_in_slo_and_timeseries(tmp_path):
    """Clock discipline: a direct time.time()/time.monotonic() call in
    serve/slo.py or utils/timeseries.py must flag (those modules take
    injectable clocks so burn-rate math replays deterministically);
    `clock=time.time` as a default REFERENCE and `# noqa` both pass,
    and other files are unaffected."""
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    try:
        import lint
    finally:
        sys.path.pop(0)
    for rel in ('serve/slo.py', 'utils/timeseries.py',
                'train/heartbeat.py', 'train/watchdog.py'):
        bad = tmp_path / 'skypilot_tpu' / rel
        bad.parent.mkdir(parents=True, exist_ok=True)
        bad.write_text('import time\n'
                       'now = time.time()\n'
                       'mono = time.monotonic()\n')
        issues = lint.check_file(bad)
        assert sum('injectable clock' in i for i in issues) == 2, issues

        bad.write_text('import time\n'
                       'def f(clock=time.time):\n'
                       '    return clock()\n')
        assert not any('injectable clock' in i
                       for i in lint.check_file(bad))

        bad.write_text('import time\n'
                       'now = time.time()  # noqa: startup stamp\n')
        assert not any('injectable clock' in i
                       for i in lint.check_file(bad))

    other = tmp_path / 'skypilot_tpu' / 'serve' / 'controller.py'
    other.write_text('import time\nnow = time.time()\n')
    assert not any('injectable clock' in i
                   for i in lint.check_file(other))


def test_ported_rules_carry_pass_ids(tmp_path):
    """The regex rules now run as skyanalyze passes: same message
    text (asserted above), plus a stable [pass-id] suffix that the
    per-pass `# noqa: <id>` grammar keys on
    (docs/static_analysis.md)."""
    sys.path.insert(0, os.path.join(REPO, 'tools'))
    try:
        import lint
    finally:
        sys.path.pop(0)
    bad = tmp_path / 'skypilot_tpu' / 'serve' / 'messy.py'
    bad.parent.mkdir(parents=True)
    bad.write_text('def f():\n'
                   '    try:\n'
                   '        print("hi")\n'
                   '    except Exception:\n'
                   '        pass\n')
    issues = lint.check_file(bad)
    assert any('bare print()' in i and '[print-call]' in i
               for i in issues), issues
    assert any('silent broad swallow' in i and '[except-pass]' in i
               for i in issues), issues

    # per-pass suppression: naming one id leaves the other firing
    bad.write_text('def f():\n'
                   '    try:\n'
                   '        print("hi")  # noqa: print-call\n'
                   '    except Exception:\n'
                   '        pass\n')
    issues = lint.check_file(bad)
    assert not any('[print-call]' in i for i in issues)
    assert any('[except-pass]' in i for i in issues)
