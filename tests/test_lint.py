"""Keep the tree lint-clean: tools/lint.py must pass (the reference
gates CI on format.sh; SURVEY.md §4.6)."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_lint_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'lint.py')],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, f'lint issues:\n{proc.stdout}'
