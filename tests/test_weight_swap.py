"""In-place weight hot-swap + rolling-update orchestration
(docs/robustness.md "Zero-downtime rollouts").

Engine/manager half: tree-validation reject table, tick-boundary
atomicity, drain vs continue semantics, prefix-cache flush, version
metrics, and abort-keeps-old-weights under every `weights.swap` fault
kind. Controller half: the canary -> bake -> fleet state machine with
auto-rollback, restart resume semantics, adoption composition, and
the weights-only spec diff routing — all against an injected swap
transport (the real-HTTP drills live in test_chaos.py).
"""
import dataclasses
import threading
import time

import pytest

from skypilot_tpu.utils import faults
from skypilot_tpu.utils import metrics as metrics_lib


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


# ------------------------------------------------------ engine fixtures
@pytest.fixture(scope='module')
def debug_setup():
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models import llama
    cfg = dataclasses.replace(llama.CONFIGS['debug'], max_seq_len=64)
    model = llama.LlamaModel(cfg)
    zeros = jnp.zeros((1, 8), jnp.int32)
    p0 = jax.jit(model.init)(jax.random.PRNGKey(0), zeros)
    p1 = jax.jit(model.init)(jax.random.PRNGKey(7), zeros)
    return cfg, model, p0, p1


def _make_engine(debug_setup, reg, params=None, **kw):
    from skypilot_tpu.infer import engine as engine_lib
    _, model, p0, _ = debug_setup
    defaults = dict(num_slots=2, max_seq_len=64, decode_chunk=2,
                    prefill_buckets=[16], metrics_registry=reg)
    defaults.update(kw)
    return engine_lib.InferenceEngine(model, params if params is not None
                                      else p0, **defaults)


def _gen(eng, tokens, n=8):
    from skypilot_tpu.infer import engine as engine_lib
    return eng.generate(tokens,
                        engine_lib.SamplingParams(max_new_tokens=n))


# ------------------------------------------------- validation rejects
def _rekey(tree, drop=None, add=None):
    import copy
    t = copy.deepcopy(tree)
    p = t['params']
    if drop:
        del p[drop]
    if add:
        p[add] = {'extra': 0.0}
    return t


@pytest.mark.parametrize('mutate,needle', [
    (lambda t: _rekey(t, drop='final_norm'), 'missing'),
    (lambda t: _rekey(t, add='bogus_layer'), 'unexpected'),
    ('shape', 'shape'),
    ('dtype', 'dtype'),
])
def test_validate_reject_table(debug_setup, mutate, needle):
    """Structure / shape / dtype mismatches are rejected with the
    offending path named — before anything touches the engine."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.infer import weight_swap
    _, _, p0, p1 = debug_setup
    if mutate == 'shape':
        bad = jax.tree_util.tree_map(
            lambda x: x[..., :1] if getattr(x, 'ndim', 0) else x, p1)
    elif mutate == 'dtype':
        bad = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, jnp.float16), p1)
    else:
        bad = mutate(p1)
    with pytest.raises(weight_swap.WeightSwapError) as ei:
        weight_swap.validate_tree(p0, bad)
    assert needle in str(ei.value)


def test_validate_accepts_matching_tree(debug_setup):
    from skypilot_tpu.infer import weight_swap
    _, _, p0, p1 = debug_setup
    weight_swap.validate_tree(p0, p1)   # no raise


# ------------------------------------------------- swap semantics
def test_swap_changes_outputs_version_and_metrics(debug_setup):
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    _, _, _, p1 = debug_setup
    eng.start()
    try:
        golden_old = _gen(eng, [1, 2, 3])
        mgr = weight_swap.WeightSwapManager(eng, registry=reg)
        res = mgr.swap(params=p1)
        assert res['ok'] and res['weight_version'] == 2
        assert eng.weight_version == 2
        assert eng.stats()['weight_version'] == 2
        out_new = _gen(eng, [1, 2, 3])
        assert out_new != golden_old
        # Metrics: version gauge, duration histogram, result counter.
        text = reg.expose()
        assert 'skyt_infer_weight_version 2' in text
        assert 'skyt_infer_weight_swaps_total{result="ok"} 1' in text
        assert 'skyt_infer_weight_swap_seconds_count 1' in text
        # swap_back restores the exact old behavior and version.
        back = mgr.swap_back()
        assert back['weight_version'] == 1
        assert _gen(eng, [1, 2, 3]) == golden_old
    finally:
        eng.stop()


def test_drain_true_finishes_inflight_on_old_weights(debug_setup):
    """drain=True (default): a request in flight when the swap lands
    completes ENTIRELY on the old weights — its stream is
    byte-identical to an unswapped run — and the swap applies right
    after its slot frees."""
    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    _, _, _, p1 = debug_setup
    eng.start()
    try:
        golden_old = _gen(eng, [5, 6, 7], n=24)
        mgr = weight_swap.WeightSwapManager(eng, registry=reg)
        rid, q = eng.submit([5, 6, 7], engine_lib.SamplingParams(
            max_new_tokens=24))
        first = q.get(timeout=60)          # request is mid-decode
        res = mgr.swap(params=p1, drain=True)
        out = [first]
        while True:
            tok = q.get(timeout=60)
            if tok is None:
                break
            out.append(tok)
        assert out == golden_old, 'drained request saw the new weights'
        assert res['weight_version'] == 2
        assert _gen(eng, [5, 6, 7], n=24) != golden_old
    finally:
        eng.stop()


def test_drain_false_swaps_while_inflight(debug_setup):
    """SKYT_SWAP_DRAIN=0 semantics: the swap applies at the next tick
    boundary with requests still running — they continue on the new
    weights (their stream diverges from the old-weights golden)."""
    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    _, _, _, p1 = debug_setup
    eng.start()
    try:
        golden_old = _gen(eng, [5, 6, 7], n=32)
        mgr = weight_swap.WeightSwapManager(eng, registry=reg)
        rid, q = eng.submit([5, 6, 7], engine_lib.SamplingParams(
            max_new_tokens=32))
        out = [q.get(timeout=60)]
        res = mgr.swap(params=p1, drain=False)
        swapped_at = time.monotonic()
        done_at = None
        while True:
            tok = q.get(timeout=60)
            if tok is None:
                done_at = time.monotonic()
                break
            out.append(tok)
        # The swap returned while the request was still streaming...
        assert done_at is not None and done_at >= swapped_at
        assert res['weight_version'] == 2
        # ...and the post-boundary suffix came from the NEW weights.
        assert out != golden_old
    finally:
        eng.stop()


def test_prefix_cache_flushed_on_swap(debug_setup):
    """Published prefix pages are stale KV after a version change:
    the swap flushes the registry, so post-swap admissions recompute
    (and republish) instead of silently mixing versions."""
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg, cache_mode='paged',
                       page_size=8, prefix_caching=True)
    _, _, _, p1 = debug_setup
    eng.start()
    try:
        prompt = list(range(1, 18))      # 2 full pages and change
        _gen(eng, prompt)
        _gen(eng, prompt)                # second run shares pages
        assert eng.pool.prefix_stats['hit_pages'] >= 1
        assert eng.pool.prefix_cached_pages() >= 1
        mgr = weight_swap.WeightSwapManager(eng, registry=reg)
        res = mgr.swap(params=p1)
        assert res['flushed_prefix_pages'] >= 1
        assert eng.pool.prefix_cached_pages() == 0
        misses_before = eng.pool.prefix_stats['miss_pages']
        _gen(eng, prompt)                # recomputes under new weights
        assert eng.pool.prefix_stats['miss_pages'] > misses_before
    finally:
        eng.stop()


# ------------------------------------------------- faults + aborts
def test_fault_error_aborts_with_old_weights(debug_setup):
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    _, _, _, p1 = debug_setup
    eng.start()
    try:
        golden = _gen(eng, [1, 2, 3])
        mgr = weight_swap.WeightSwapManager(eng, registry=reg)
        faults.configure('weights.swap=error')
        with pytest.raises(weight_swap.WeightSwapError):
            mgr.swap(params=p1)
        faults.reset()
        assert eng.weight_version == 1
        assert _gen(eng, [1, 2, 3]) == golden
        assert 'skyt_infer_weight_swaps_total{result="aborted"} 1' \
            in reg.expose()
        assert mgr.last is not None and not mgr.last['ok']
        # The abort retained nothing to roll back to.
        with pytest.raises(weight_swap.WeightSwapError):
            mgr.swap_back()
    finally:
        eng.stop()


def test_fault_latency_delays_but_succeeds(debug_setup):
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    _, _, _, p1 = debug_setup
    mgr = weight_swap.WeightSwapManager(eng, registry=reg)
    faults.configure('weights.swap=latency,arg=0.3')
    t0 = time.monotonic()
    res = mgr.swap(params=p1)         # engine not started: inline apply
    assert res['ok'] and time.monotonic() - t0 >= 0.3


def test_fault_hang_holds_single_flight_409(debug_setup):
    """A hung swap (weights.swap=hang) keeps the single-flight lock:
    a concurrent push gets SwapInFlight (the server's 409), and the
    hung one still completes."""
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    _, _, _, p1 = debug_setup
    mgr = weight_swap.WeightSwapManager(eng, registry=reg)
    faults.configure('weights.swap=hang,arg=1.0,count=1')
    results = {}

    def slow():
        results['slow'] = mgr.swap(params=p1)

    th = threading.Thread(target=slow)
    th.start()
    time.sleep(0.3)                    # inside the hang window
    with pytest.raises(weight_swap.SwapInFlight):
        mgr.swap(params=p1)
    th.join(timeout=30)
    assert results['slow']['ok']


def test_engine_swap_timeout_leaves_old_weights(debug_setup):
    """A draining swap that cannot reach an empty boundary within its
    timeout aborts cleanly: TimeoutError, old weights live, and the
    pending request is CLEARED (it does not fire later)."""
    from skypilot_tpu.infer import engine as engine_lib
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    _, _, _, p1 = debug_setup
    eng.start()
    try:
        golden = _gen(eng, [9, 9, 9], n=4)
        # Slow the loop so the in-flight request outlives the swap
        # timeout (the debug model would otherwise finish in ms).
        faults.configure('engine.loop=latency,arg=0.1')
        rid, q = eng.submit([9, 9, 9], engine_lib.SamplingParams(
            max_new_tokens=48))
        q.get(timeout=60)              # slot occupied
        with pytest.raises(TimeoutError):
            eng.request_weight_swap(p1, drain=True, timeout=0.3)
        faults.reset()
        # Drain the long request; the cancelled swap must NOT land.
        while q.get(timeout=60) is not None:
            pass
        time.sleep(0.2)
        assert eng.weight_version == 1
        assert _gen(eng, [9, 9, 9], n=4) == golden
    finally:
        eng.stop()


# ------------------------------------------------- server admin route
def test_admin_weights_route_contract(debug_setup, monkeypatch):
    """403 unauthed / disabled, 400 malformed, 200 on a real swap,
    409 concurrent, swap_back — and weight_version in /stats."""
    import requests as req_lib

    from skypilot_tpu.infer import server as server_lib
    from tests.test_chaos import _free_port, _run_app_bg, _wait_http
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    _, _, _, p1 = debug_setup
    # A checkpoint loader in miniature: one known path.
    eng.param_loader = lambda path: (
        p1 if path == 'ckpt-v2'
        else (_ for _ in ()).throw(FileNotFoundError(path)))
    eng.start()
    try:
        srv = server_lib.InferenceServer(eng)
        port = _free_port()
        _run_app_bg(srv.make_app(), port)
        base = f'http://127.0.0.1:{port}'
        _wait_http(base + '/health', timeout=120)
        body = {'checkpoint': 'ckpt-v2'}
        # Disabled without SKYT_ADMIN_TOKEN.
        monkeypatch.delenv('SKYT_ADMIN_TOKEN', raising=False)
        assert req_lib.post(base + '/admin/weights', json=body,
                            timeout=30).status_code == 403
        monkeypatch.setenv('SKYT_ADMIN_TOKEN', 'sesame')
        hdr = {'Authorization': 'Bearer sesame'}
        # Unauthed / wrong bearer.
        assert req_lib.post(base + '/admin/weights', json=body,
                            timeout=30).status_code == 403
        assert req_lib.post(
            base + '/admin/weights', json=body, timeout=30,
            headers={'Authorization': 'Bearer wrong'}).status_code == 403
        # Malformed bodies.
        for bad in ([1, 2], {'checkpoint': ''}, {'checkpoint': 7},
                    {'checkpoint': 'x', 'version': 'seven'},
                    {'checkpoint': 'x', 'version': 0},
                    {'checkpoint': 'x', 'drain': 'yes'}, {}):
            r = req_lib.post(base + '/admin/weights', json=bad,
                             headers=hdr, timeout=30)
            assert r.status_code == 400, (bad, r.status_code, r.text)
        # Loader failure: clean 400, old weights intact.
        r = req_lib.post(base + '/admin/weights',
                         json={'checkpoint': 'missing'}, headers=hdr,
                         timeout=60)
        assert r.status_code == 400 and r.json()['weight_version'] == 1
        # The real swap.
        r = req_lib.post(base + '/admin/weights',
                         json={'checkpoint': 'ckpt-v2', 'version': 5},
                         headers=hdr, timeout=120)
        assert r.status_code == 200, r.text
        assert r.json()['weight_version'] == 5
        stats = req_lib.get(base + '/stats', timeout=30).json()
        assert stats['weight_version'] == 5
        # Concurrent swap -> 409 (hold the flight with a hang fault).
        faults.configure('weights.swap=hang,arg=1.5,count=1')
        codes = {}

        def push(name):
            codes[name] = req_lib.post(
                base + '/admin/weights',
                json={'checkpoint': 'ckpt-v2'}, headers=hdr,
                timeout=120).status_code

        t1 = threading.Thread(target=push, args=('a',))
        t1.start()
        time.sleep(0.5)
        push('b')
        t1.join(timeout=60)
        faults.reset()
        assert sorted(codes.values()) == [200, 409], codes
        # swap_back restores the boot version.
        r = req_lib.post(base + '/admin/weights',
                         json={'swap_back': True}, headers=hdr,
                         timeout=120)
        assert r.status_code == 200
        assert r.json()['weight_version'] == 5  # back to pre-'a' state
    finally:
        eng.stop()


# ------------------------------------------------- elastic reshard
def test_reshard_changes_layout_not_weights(debug_setup):
    """In-place reshard (docs/robustness.md "Elastic capacity"): the
    virtual-node layout moves at a tick boundary, the weight VALUES
    and VERSION do not — outputs are identical before/after, and the
    layout lands in the gauge + result metrics. reshard_back restores
    the replaced layout."""
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    eng.start()
    try:
        golden = _gen(eng, [1, 2, 3])
        mgr = weight_swap.WeightSwapManager(eng, registry=reg)
        assert eng.virtual_nodes == 1
        res = mgr.reshard(2)
        assert res['ok'] and res['virtual_nodes'] == 2, res
        assert res['from_nodes'] == 1 and not res['reshard_back']
        assert res['weight_version'] == 1
        assert eng.virtual_nodes == 2
        assert eng.weight_version == 1     # version did NOT move
        assert _gen(eng, [1, 2, 3]) == golden   # same weights
        text = reg.expose()
        assert 'skyt_infer_virtual_nodes 2' in text
        assert 'skyt_infer_reshards_total{result="ok"} 1' in text
        assert 'skyt_infer_reshard_seconds_count 1' in text
        info = mgr.info()
        assert info['virtual_nodes'] == 2
        assert info['reshard_back_available']
        assert info['last_reshard']['ok']
        back = mgr.reshard_back()
        assert back['ok'] and back['virtual_nodes'] == 1
        assert back['reshard_back']
        assert eng.virtual_nodes == 1
        assert _gen(eng, [1, 2, 3]) == golden
    finally:
        eng.stop()


def test_reshard_noop_is_idempotent(debug_setup):
    """Re-asserting the current layout is an ok no-op (the controller
    retries through restarts) and retains no rollback history."""
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    mgr = weight_swap.WeightSwapManager(eng, registry=reg)
    res = mgr.reshard(1)
    assert res['ok'] and res.get('noop')
    assert eng.virtual_nodes == 1
    with pytest.raises(weight_swap.WeightSwapError):
        mgr.reshard_back()      # nothing was replaced


def test_reshard_validation_rejects(debug_setup):
    """Bad layouts are rejected BEFORE anything is staged: non-int,
    < 1, and a target that cannot tile the mesh (neither divides the
    other). Old layout intact in every case."""
    import types

    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    mgr = weight_swap.WeightSwapManager(eng, registry=reg)
    for bad, needle in (('two', 'integer'), (None, 'integer'),
                        (0, '>= 1'), (-3, '>= 1')):
        with pytest.raises(weight_swap.WeightSwapError) as ei:
            mgr.reshard(bad)
        assert needle in str(ei.value), (bad, str(ei.value))
    eng.mesh = types.SimpleNamespace(size=4)
    with pytest.raises(weight_swap.WeightSwapError) as ei:
        mgr.reshard(3)          # 3 vs 4: neither divides the other
    assert 'tile' in str(ei.value)
    assert eng.virtual_nodes == 1
    assert mgr.last_reshard is not None and not mgr.last_reshard['ok']


def test_reshard_fault_error_aborts_with_old_layout(debug_setup):
    """`reshard=error` aborts with the old layout intact and lands in
    skyt_infer_reshards_total{result="aborted"}; a clean retry then
    succeeds."""
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    eng.start()
    try:
        golden = _gen(eng, [4, 5, 6])
        mgr = weight_swap.WeightSwapManager(eng, registry=reg)
        faults.configure('reshard=error,count=1')
        with pytest.raises(weight_swap.WeightSwapError) as ei:
            mgr.reshard(2)
        assert 'old layout intact' in str(ei.value)
        assert eng.virtual_nodes == 1
        assert _gen(eng, [4, 5, 6]) == golden
        assert 'skyt_infer_reshards_total{result="aborted"} 1' \
            in reg.expose()
        assert not mgr.last_reshard['ok']
        res = mgr.reshard(2)    # fault exhausted: clean retry lands
        assert res['ok'] and eng.virtual_nodes == 2
    finally:
        eng.stop()


def test_reshard_shares_swap_single_flight(debug_setup):
    """One flight lock for the whole staging surface: a hung reshard
    409s BOTH a concurrent reshard and a concurrent weight swap (they
    ride the same engine slot and must never race)."""
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    _, _, _, p1 = debug_setup
    mgr = weight_swap.WeightSwapManager(eng, registry=reg)
    faults.configure('reshard=hang,arg=1.0,count=1')
    results = {}

    def slow():
        results['slow'] = mgr.reshard(2)

    th = threading.Thread(target=slow)
    th.start()
    time.sleep(0.3)                    # inside the hang window
    with pytest.raises(weight_swap.SwapInFlight):
        mgr.reshard(4)
    with pytest.raises(weight_swap.SwapInFlight):
        mgr.swap(params=p1)
    th.join(timeout=30)
    assert results['slow']['ok']
    assert eng.weight_version == 1     # the blocked swap never landed


def test_reshard_preserves_swap_back_history(debug_setup):
    """A reshard between a swap and its swap_back must not eat the
    weight-rollback retention: swap to v2, reshard, swap_back still
    restores v1 behavior (on the resharded layout)."""
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    _, _, _, p1 = debug_setup
    eng.start()
    try:
        golden = _gen(eng, [1, 2, 3])
        mgr = weight_swap.WeightSwapManager(eng, registry=reg)
        assert mgr.swap(params=p1)['weight_version'] == 2
        assert mgr.reshard(2)['ok']
        back = mgr.swap_back()
        assert back['weight_version'] == 1
        assert eng.virtual_nodes == 2  # layout survives the swap_back
        assert _gen(eng, [1, 2, 3]) == golden
    finally:
        eng.stop()


def test_reshard_flushes_prefix_cache(debug_setup):
    """Page tiling is layout-derived: a reshard flushes the HBM prefix
    registry conservatively (host/fleet KV tiers stay valid — same
    weight version — and re-promote on demand)."""
    from skypilot_tpu.infer import weight_swap
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg, cache_mode='paged',
                       page_size=8, prefix_caching=True)
    eng.start()
    try:
        prompt = list(range(1, 18))
        _gen(eng, prompt)
        _gen(eng, prompt)
        assert eng.pool.prefix_cached_pages() >= 1
        mgr = weight_swap.WeightSwapManager(eng, registry=reg)
        res = mgr.reshard(2)
        assert res['flushed_prefix_pages'] >= 1
        assert eng.pool.prefix_cached_pages() == 0
    finally:
        eng.stop()


def test_admin_reshard_route_contract(debug_setup, monkeypatch):
    """403 unauthed / disabled, 400 malformed or un-tileable, 200 on a
    real reshard, 409 concurrent, reshard_back — mirrors the
    /admin/weights contract on the same single-flight."""
    import requests as req_lib

    from skypilot_tpu.infer import server as server_lib
    from tests.test_chaos import _free_port, _run_app_bg, _wait_http
    reg = metrics_lib.MetricsRegistry()
    eng = _make_engine(debug_setup, reg)
    eng.start()
    try:
        srv = server_lib.InferenceServer(eng)
        port = _free_port()
        _run_app_bg(srv.make_app(), port)
        base = f'http://127.0.0.1:{port}'
        _wait_http(base + '/health', timeout=120)
        body = {'virtual_nodes': 2}
        monkeypatch.delenv('SKYT_ADMIN_TOKEN', raising=False)
        assert req_lib.post(base + '/admin/reshard', json=body,
                            timeout=30).status_code == 403
        monkeypatch.setenv('SKYT_ADMIN_TOKEN', 'sesame')
        hdr = {'Authorization': 'Bearer sesame'}
        assert req_lib.post(base + '/admin/reshard', json=body,
                            timeout=30).status_code == 403
        for bad in ([1], {}, {'virtual_nodes': 0},
                    {'virtual_nodes': 'two'}, {'virtual_nodes': True},
                    {'virtual_nodes': 2, 'drain': 'yes'}):
            r = req_lib.post(base + '/admin/reshard', json=bad,
                             headers=hdr, timeout=30)
            assert r.status_code == 400, (bad, r.status_code, r.text)
        # reshard_back before any reshard: clean 400, layout named.
        r = req_lib.post(base + '/admin/reshard',
                         json={'reshard_back': True}, headers=hdr,
                         timeout=60)
        assert r.status_code == 400 and r.json()['virtual_nodes'] == 1
        # The real reshard.
        r = req_lib.post(base + '/admin/reshard', json=body,
                         headers=hdr, timeout=120)
        assert r.status_code == 200, r.text
        assert r.json()['virtual_nodes'] == 2
        assert eng.virtual_nodes == 2
        # Concurrent -> 409 (hold the flight with a hang fault).
        faults.configure('reshard=hang,arg=1.5,count=1')
        codes = {}

        def push(name, payload):
            codes[name] = req_lib.post(
                base + '/admin/reshard', json=payload, headers=hdr,
                timeout=120).status_code

        t1 = threading.Thread(target=push,
                              args=('a', {'virtual_nodes': 4}))
        t1.start()
        time.sleep(0.5)
        push('b', {'virtual_nodes': 8})
        t1.join(timeout=60)
        faults.reset()
        assert sorted(codes.values()) == [200, 409], codes
        # reshard_back restores what the LAST reshard replaced.
        r = req_lib.post(base + '/admin/reshard',
                         json={'reshard_back': True}, headers=hdr,
                         timeout=120)
        assert r.status_code == 200 and r.json()['virtual_nodes'] == 2
        stats = req_lib.get(base + '/stats', timeout=30).json()
        assert stats['weight_version'] == 1    # never moved
    finally:
        eng.stop()


# ===================================== rollout orchestrator (no HTTP)
class _FakeTelemetry:
    def __init__(self):
        self.firing = []

    def alerts_firing(self):
        return list(self.firing)

    def maybe_scrape(self, *a, **k):
        return None

    def drop_target(self, *a, **k):
        return None


@pytest.fixture()
def rollout_mgr(tmp_state_dir, monkeypatch):
    """A ReplicaManager with 3 fake READY replicas, an injected swap
    transport, and a fake SLO-alert source."""
    del tmp_state_dir
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib
    serve_state.reset_db_for_testing()
    monkeypatch.setenv('SKYT_ROLLOUT_BAKE_S', '0.2')
    spec = spec_lib.ServiceSpec(readiness_path='/', min_replicas=3,
                                weights='/ckpts/v1')
    serve_state.add_service('wsvc', spec, '/tmp/none.yaml', 1, 2)
    tel = _FakeTelemetry()
    mgr = replica_managers.ReplicaManager('wsvc', spec,
                                          '/tmp/none.yaml',
                                          telemetry=tel)
    for rid in (1, 2, 3):
        info = replica_managers.ReplicaInfo(
            replica_id=rid, cluster_name=f'wsvc-{rid}', version=1,
            status=serve_state.ReplicaStatus.READY,
            endpoint=f'http://127.0.0.1:{9000 + rid}')
        mgr.replicas[rid] = info
        mgr._save(info)  # pylint: disable=protected-access
    calls = []

    def fake_swap(info, payload, _responses={}):
        calls.append((info.replica_id, dict(payload)))
        fail = getattr(fake_swap, 'fail_on', None)
        if fail and info.replica_id in fail and \
                not payload.get('swap_back'):
            return False, 'injected swap failure'
        if getattr(fake_swap, 'fail_back', False) and \
                payload.get('swap_back'):
            return False, 'injected swap-back failure'
        return True, None

    fake_swap.calls = calls
    mgr._swap_fn = fake_swap  # pylint: disable=protected-access
    return mgr, spec, tel, fake_swap


def _bump_spec(spec, weights):
    return dataclasses.replace(spec, weights=weights)


def test_weights_only_diff():
    from skypilot_tpu.serve import service_spec as spec_lib
    a = spec_lib.ServiceSpec(readiness_path='/', min_replicas=2,
                             weights='/ckpts/v1')
    assert a.weights_only_diff(_bump_spec(a, '/ckpts/v2'))
    assert not a.weights_only_diff(a)                     # no change
    b = dataclasses.replace(a, weights='/ckpts/v2', min_replicas=3)
    assert not a.weights_only_diff(b)                     # more changed
    no_w = spec_lib.ServiceSpec(readiness_path='/', min_replicas=2)
    assert no_w.weights_only_diff(_bump_spec(no_w, '/ckpts/v2'))
    assert not a.weights_only_diff(
        dataclasses.replace(a, weights=None))             # weights unset
    # And the field round-trips through yaml config + schema.
    cfg = _bump_spec(a, '/ckpts/v9').to_yaml_config()
    assert spec_lib.ServiceSpec.from_yaml_config(cfg).weights == \
        '/ckpts/v9'


def test_rollout_canary_bake_fleet_commit(rollout_mgr):
    from skypilot_tpu.serve import serve_state
    mgr, spec, _tel, fake = rollout_mgr
    mgr.start_rolling_update(_bump_spec(spec, '/ckpts/v2'),
                             '/tmp/none.yaml', 2)
    assert mgr.rollout_status()['phase'] == 'canary'
    mgr.rollout_tick()                       # canary swaps replica 1
    ro = mgr.rollout_status()
    assert ro['phase'] == 'bake' and ro['canary'] == 1
    assert ro['updated'] == [1]
    assert mgr.replicas[1].weight_version == 2
    assert mgr.replicas[1].version == 1      # spec version NOT committed
    # Mixed-version window is visible to the LB sync.
    wv = mgr.ready_weight_versions()
    assert sorted(wv.values()) == [1, 1, 2]
    mgr.rollout_tick()                       # still baking
    assert mgr.rollout_status()['phase'] == 'bake'
    time.sleep(0.25)
    mgr.rollout_tick()                       # bake over -> rollout
    mgr.rollout_tick()                       # replica 2
    mgr.rollout_tick()                       # replica 3
    mgr.rollout_tick()                       # all updated -> commit
    ro = mgr.rollout_status()
    assert ro['phase'] == 'done', ro
    assert mgr.version == 2 and mgr.spec.weights == '/ckpts/v2'
    assert all(r.version == 2 and r.weight_version == 2
               for r in mgr.replicas.values())
    svc = serve_state.get_service('wsvc')
    assert svc['version'] == 2 and svc['spec'].weights == '/ckpts/v2'
    # One replica per tick, canary first, no swap_back calls.
    assert [c[0] for c in fake.calls] == [1, 2, 3]
    assert all(not c[1].get('swap_back') for c in fake.calls)
    assert mgr._m_rollouts.value('wsvc', 'done') == 1  # pylint: disable=protected-access


def test_rollout_canary_failure_rolls_back(rollout_mgr):
    mgr, spec, _tel, fake = rollout_mgr
    fake.fail_on = {1}
    mgr.start_rolling_update(_bump_spec(spec, '/ckpts/v2'),
                             '/tmp/none.yaml', 2)
    mgr.rollout_tick()                       # canary fails
    assert mgr.rollout_status()['phase'] == 'rollback'
    mgr.rollout_tick()                       # nothing updated -> done
    ro = mgr.rollout_status()
    assert ro['phase'] == 'rolled_back'
    assert 'swap failed' in ro['error']
    # Fleet untouched: baseline spec + weights everywhere.
    assert mgr.version == 1
    assert all(r.weight_version == 1 for r in mgr.replicas.values())
    # Only the canary was ever touched.
    assert [c[0] for c in fake.calls] == [1]


def test_rollout_bake_alert_rolls_back(rollout_mgr):
    mgr, spec, tel, fake = rollout_mgr
    mgr.start_rolling_update(_bump_spec(spec, '/ckpts/v2'),
                             '/tmp/none.yaml', 2)
    mgr.rollout_tick()                       # canary ok -> bake
    tel.firing = ['interactive']             # SLO burn alert fires
    mgr.rollout_tick()
    assert mgr.rollout_status()['phase'] == 'rollback'
    mgr.rollout_tick()                       # swap canary back
    ro = mgr.rollout_status()
    assert ro['phase'] == 'rolled_back'
    assert 'burn-rate alert' in ro['error']
    assert mgr.replicas[1].weight_version == 1
    # The canary got exactly one forward swap and one swap_back.
    assert [(c[0], bool(c[1].get('swap_back')))
            for c in fake.calls] == [(1, False), (1, True)]


def test_rollout_canary_not_ready_rolls_back(rollout_mgr):
    from skypilot_tpu.serve import serve_state
    mgr, spec, _tel, _fake = rollout_mgr
    mgr.start_rolling_update(_bump_spec(spec, '/ckpts/v2'),
                             '/tmp/none.yaml', 2)
    mgr.rollout_tick()
    mgr.replicas[1].status = serve_state.ReplicaStatus.NOT_READY
    mgr.rollout_tick()
    assert mgr.rollout_status()['phase'] == 'rollback'


def test_rollout_swapback_escalates_to_relaunch(rollout_mgr,
                                                monkeypatch):
    """A replica that refuses to swap back after SKYT_ROLLOUT_RETRIES
    is drained+relaunched on the (uncommitted) baseline."""
    mgr, spec, tel, fake = rollout_mgr
    monkeypatch.setenv('SKYT_ROLLOUT_RETRIES', '2')
    drained = []
    monkeypatch.setattr(
        mgr, 'terminate_replica',
        lambda rid, sync=False, drain=False: drained.append((rid,
                                                             drain)))
    mgr.start_rolling_update(_bump_spec(spec, '/ckpts/v2'),
                             '/tmp/none.yaml', 2)
    mgr.rollout_tick()                       # canary ok -> bake
    fake.fail_back = True
    tel.firing = ['batch']
    mgr.rollout_tick()                       # -> rollback
    mgr.rollout_tick()                       # back attempt 1 fails
    mgr.rollout_tick()                       # attempt 2 fails -> drain
    mgr.rollout_tick()                       # nothing left -> terminal
    ro = mgr.rollout_status()
    assert ro['phase'] == 'rolled_back'
    assert drained == [(1, True)]


def test_rollout_resume_semantics(rollout_mgr, monkeypatch):
    """Persisted phases survive a controller restart: canary/bake
    conservatively roll back; 'rollout' resumes and commits."""
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib
    mgr, spec, _tel, fake = rollout_mgr
    mgr.start_rolling_update(_bump_spec(spec, '/ckpts/v2'),
                             '/tmp/none.yaml', 2)
    mgr.rollout_tick()                       # canary -> bake; persisted
    assert serve_state.get_rollout('wsvc')['phase'] == 'bake'
    # "Restarted" managers reload the persisted replicas as-is: the
    # fake replicas have no cluster records, so the real adoption
    # ladder would reap them before the resume logic runs (the
    # adoption x rollout COMPOSITION has its own test below and a
    # real-process drill in test_chaos.py).
    monkeypatch.setattr(replica_managers.ReplicaManager,
                        '_reconcile_restart', lambda self: None)

    def new_mgr():
        m = replica_managers.ReplicaManager('wsvc', spec,
                                            '/tmp/none.yaml',
                                            telemetry=_FakeTelemetry())
        m._swap_fn = fake  # pylint: disable=protected-access
        return m

    # "Restart" #1: mid-bake -> rollback.
    mgr2 = new_mgr()
    ro = mgr2.rollout_status()
    assert ro['phase'] == 'rollback' and 'restarted' in ro['error']
    mgr2.rollout_tick()                      # roll the canary back
    assert mgr2.rollout_status()['phase'] == 'rolled_back'
    assert serve_state.get_rollout('wsvc')['phase'] == 'rolled_back'

    # Fresh rollout driven to phase 'rollout', then "restart" #2:
    # resumes where it stopped and commits.
    mgr2.start_rolling_update(_bump_spec(spec, '/ckpts/v3'),
                              '/tmp/none.yaml', 3)
    mgr2.rollout_tick()                      # canary
    time.sleep(0.25)
    mgr2.rollout_tick()                      # bake over -> rollout
    mgr2.rollout_tick()                      # replica 2 swapped
    assert serve_state.get_rollout('wsvc')['phase'] == 'rollout'
    mgr3 = new_mgr()
    assert mgr3.rollout_status()['phase'] == 'rollout'
    assert mgr3.rollout_status()['updated'] == [1, 2]
    mgr3.rollout_tick()                      # replica 3
    mgr3.rollout_tick()                      # commit
    assert mgr3.rollout_status()['phase'] == 'done'
    assert mgr3.version == 3
    svc = serve_state.get_service('wsvc')
    assert svc['version'] == 3 and svc['spec'].weights == '/ckpts/v3'
    assert isinstance(svc['spec'], spec_lib.ServiceSpec)


def test_adoption_guard_spares_rollout_versions(rollout_mgr):
    """A replica one version AHEAD of the committed spec (mid-commit
    crash window) is NOT reaped as stale when the recorded rollout
    names that version."""
    from skypilot_tpu.serve import replica_managers
    mgr, spec, _tel, _fake = rollout_mgr
    mgr.start_rolling_update(_bump_spec(spec, '/ckpts/v2'),
                             '/tmp/none.yaml', 2)
    info = mgr.replicas[2]
    info.version = 2                         # ahead of mgr.version == 1
    assert mgr._orphan_reason(info) != 'stale_spec_version'  # pylint: disable=protected-access
    # Without a recorded rollout the same skew IS stale.
    mgr._rollout = None  # pylint: disable=protected-access
    assert mgr._orphan_reason(info) == 'stale_spec_version'  # pylint: disable=protected-access
    # And a version NOT named by the rollout stays stale too.
    mgr._rollout = replica_managers.RolloutState(  # pylint: disable=protected-access
        phase='rollout', target_version=4, baseline_version=3,
        checkpoint='/ckpts/v4', baseline_checkpoint=None,
        spec_config={}, task_yaml='', started_at=0.0)
    assert mgr._orphan_reason(info) == 'stale_spec_version'  # pylint: disable=protected-access


def test_rollout_state_persistence_roundtrip(tmp_state_dir):
    del tmp_state_dir
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve import service_spec as spec_lib
    serve_state.reset_db_for_testing()
    spec = spec_lib.ServiceSpec(readiness_path='/')
    serve_state.add_service('psvc', spec, '/tmp/none.yaml', 1, 2)
    assert serve_state.get_rollout('psvc') is None
    state = {'phase': 'bake', 'target_version': 2, 'updated': [1]}
    serve_state.set_rollout('psvc', state)
    assert serve_state.get_rollout('psvc') == state
    serve_state.set_rollout('psvc', None)
    assert serve_state.get_rollout('psvc') is None


def test_concurrent_rollout_rejected(rollout_mgr):
    from skypilot_tpu import exceptions
    mgr, spec, _tel, _fake = rollout_mgr
    mgr.start_rolling_update(_bump_spec(spec, '/ckpts/v2'),
                             '/tmp/none.yaml', 2)
    with pytest.raises(exceptions.SkyTpuError):
        mgr.start_rolling_update(_bump_spec(spec, '/ckpts/v3'),
                                 '/tmp/none.yaml', 3)


def test_publish_checkpoint_atomic(tmp_path, debug_setup):
    """publish_checkpoint stages + renames: the destination is always
    absent or complete, and republish replaces in place."""
    import os

    from skypilot_tpu.models import weights as weights_lib
    from skypilot_tpu.train import push_weights
    cfg, _model, p0, p1 = debug_setup
    out = str(tmp_path / 'ckpt')
    got = push_weights.publish_checkpoint(cfg, p0, out)
    assert got == out
    assert sorted(os.listdir(out)) == ['config.json',
                                       'model.safetensors']
    first = open(os.path.join(out, 'model.safetensors'), 'rb').read()
    push_weights.publish_checkpoint(cfg, p1, out)   # replace in place
    second = open(os.path.join(out, 'model.safetensors'), 'rb').read()
    assert first != second
    assert not [d for d in os.listdir(tmp_path)
                if 'staging' in d or '.old' in d]
    # The published dir round-trips through the swap loader path.
    cfg2 = weights_lib.load_config(out, remat=False,
                                   param_dtype='float32',
                                   dtype='float32')
    assert cfg2.n_layers == cfg.n_layers


# ===================================== reshard orchestrator (no HTTP)
def _wire_reshard(rollout_mgr):
    """Point the rollout fixture's manager at an injectable reshard
    transport (same shape as the swap one)."""
    mgr, spec, tel, _fake = rollout_mgr
    calls = []

    def fake_reshard(info, payload):
        calls.append((info.replica_id, dict(payload)))
        fail = getattr(fake_reshard, 'fail_on', None)
        if fail and info.replica_id in fail and \
                not payload.get('reshard_back'):
            return False, 'injected reshard failure'
        if getattr(fake_reshard, 'fail_back', None) and \
                info.replica_id in fake_reshard.fail_back and \
                payload.get('reshard_back'):
            return False, 'injected reshard-back failure'
        return True, None

    fake_reshard.calls = calls
    mgr._reshard_fn = fake_reshard  # pylint: disable=protected-access
    return mgr, spec, tel, fake_reshard


def test_reshard_orchestrator_happy_path(rollout_mgr):
    """start -> one replica per tick in id order -> done; the fleet
    outcome and per-call results land in the service metrics."""
    mgr, _spec, _tel, fake = _wire_reshard(rollout_mgr)
    st = mgr.start_reshard(4)
    assert st['phase'] == 'reshard' and st['target_nodes'] == 4
    mgr.reshard_tick()
    assert mgr.reshard_status()['updated'] == [1]
    mgr.reshard_tick()
    mgr.reshard_tick()
    assert mgr.reshard_status()['updated'] == [1, 2, 3]
    mgr.reshard_tick()                     # no candidates left -> done
    st = mgr.reshard_status()
    assert st['phase'] == 'done' and st['error'] is None
    assert [c[0] for c in fake.calls] == [1, 2, 3]
    assert all(c[1] == {'virtual_nodes': 4} for c in fake.calls)
    assert mgr._m_reshards.value('wsvc', 'done') == 1  # pylint: disable=protected-access
    assert mgr._m_reshard_calls.value('wsvc', 'ok') == 3  # pylint: disable=protected-access
    # Terminal state: a new reshard may start.
    assert mgr.start_reshard(2)['phase'] == 'reshard'


def test_reshard_orchestrator_rolls_back_newest_first(rollout_mgr,
                                                      monkeypatch):
    """A replica that keeps refusing the new layout burns the retry
    budget; the already-resharded set rolls back NEWEST FIRST and the
    run ends rolled_back with the failure named."""
    monkeypatch.setenv('SKYT_ROLLOUT_RETRIES', '2')
    mgr, _spec, _tel, fake = _wire_reshard(rollout_mgr)
    fake.fail_on = {3}
    mgr.start_reshard(2)
    mgr.reshard_tick()                     # 1 ok
    mgr.reshard_tick()                     # 2 ok
    mgr.reshard_tick()                     # 3 fails (1/2)
    assert mgr.reshard_status()['phase'] == 'reshard'
    mgr.reshard_tick()                     # 3 fails (2/2) -> rollback
    assert mgr.reshard_status()['phase'] == 'rollback'
    mgr.reshard_tick()                     # rolls 2 then 1 back
    st = mgr.reshard_status()
    assert st['phase'] == 'rolled_back'
    assert 'replica 3' in st['error']
    backs = [c[0] for c in fake.calls if c[1].get('reshard_back')]
    assert backs == [2, 1]                 # newest first
    assert mgr._m_reshards.value('wsvc', 'rolled_back') == 1  # pylint: disable=protected-access
    # Nobody was drained or relaunched over a layout problem.
    from skypilot_tpu.serve import serve_state
    assert all(r.status is serve_state.ReplicaStatus.READY
               for r in mgr.replicas.values())


def test_reshard_rollback_skips_stubborn_replica(rollout_mgr,
                                                 monkeypatch):
    """A replica that refuses even the rollback is SKIPPED (layout
    left as-is), never drained: wrong layout is degraded throughput,
    not an outage worth a capacity dip."""
    monkeypatch.setenv('SKYT_ROLLOUT_RETRIES', '1')
    mgr, _spec, _tel, fake = _wire_reshard(rollout_mgr)
    fake.fail_on = {3}
    fake.fail_back = {2}
    mgr.start_reshard(2)
    mgr.reshard_tick()                     # 1 ok
    mgr.reshard_tick()                     # 2 ok
    mgr.reshard_tick()                     # 3 fails -> rollback
    assert mgr.reshard_status()['phase'] == 'rollback'
    mgr.reshard_tick()                     # 2 refuses (1/1) -> skipped
    mgr.reshard_tick()                     # 1 rolls back -> rolled_back
    st = mgr.reshard_status()
    assert st['phase'] == 'rolled_back', st
    from skypilot_tpu.serve import serve_state
    assert all(r.status is serve_state.ReplicaStatus.READY
               for r in mgr.replicas.values())
    assert mgr._m_reshard_calls.value('wsvc', 'rollback_error') >= 1  # pylint: disable=protected-access


def test_reshard_validation_and_concurrency(rollout_mgr):
    from skypilot_tpu import exceptions
    mgr, _spec, _tel, _fake = _wire_reshard(rollout_mgr)
    for bad in ('two', None, 0, -1):
        with pytest.raises(exceptions.SkyTpuError):
            mgr.start_reshard(bad)
    mgr.start_reshard(2)
    with pytest.raises(exceptions.SkyTpuError):
        mgr.start_reshard(4)               # one at a time


def test_reshard_and_rollout_are_mutually_exclusive(rollout_mgr):
    """Both ride the replicas' single-flight swap slot: a reshard
    refuses while a rollout is active, and vice versa."""
    from skypilot_tpu import exceptions
    mgr, spec, _tel, _fake = _wire_reshard(rollout_mgr)
    mgr.start_rolling_update(_bump_spec(spec, '/ckpts/v2'),
                             '/tmp/none.yaml', 2)
    with pytest.raises(exceptions.SkyTpuError) as ei:
        mgr.start_reshard(2)
    assert 'rolling update' in str(ei.value)
    # Finish the rollout, then invert the order.
    mgr.rollout_tick()                     # canary
    time.sleep(0.25)
    for _ in range(4):
        mgr.rollout_tick()
    assert mgr.rollout_status()['phase'] == 'done'
    mgr.start_reshard(2)
    with pytest.raises(exceptions.SkyTpuError) as ei:
        mgr.start_rolling_update(_bump_spec(spec, '/ckpts/v3'),
                                 '/tmp/none.yaml', 3)
    assert 'reshard' in str(ei.value)
